"""Trade hub recounts against dense-grid width: the max_degree axis.

At the stretch shape (10^6 agents, Chung-Lu γ=2.5, lognormal β) the
recalibrated census and the recount telemetry agree: ~144 of 200 steps
are HUB-caused full recounts (a changed agent's out-degree exceeds
incremental_max_degree=64), and on TPU each recount costs ~95 ms against
a ~10 ms clean step — recounts dominate the stretch runtime. Raising
max_degree shrinks the hub set on the power-law tail fast (measured on
CPU telemetry, bit-identical dynamics on any platform):

    d:        64     128    256    512    1024
    hubs:     12098  4284   1493   533    190
    recounts: 144    121    101    74     45     (of 200 steps)

but widens the incremental engine's dense (budget × d) out-edge grid,
whose gather + scatter-add runs every clean step. The net is a TPU cost
curve this script measures end-to-end per d, with the recount counts
alongside so the two effects separate.

Run: python benchmarks/ablate_max_degree.py [n_agents] [n_steps]
  SBR_ABL_PLATFORM=cpu pins CPU; SBR_ABL_JSON=path writes the artifact.
  SBR_ABL_CHUNK bounds single-launch duration (axon tunnel).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("SBR_ABL_PLATFORM", "") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import numpy as np

    from sbr_tpu.social import (
        AgentSimConfig,
        prepare_agent_graph,
        scale_free_edges,
        simulate_agents,
    )

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    platform = jax.devices()[0].platform
    chunk = int(os.environ.get("SBR_ABL_CHUNK", "0")) or None
    print(f"platform={platform} n={n} steps={n_steps} (stretch graph/β laws)")

    src, dst = scale_free_edges(n, avg_degree=10.0, gamma=2.5, seed=0)
    betas = (
        np.random.default_rng(1).lognormal(mean=0.0, sigma=0.5, size=n)
        .astype(np.float32)
    )
    outdeg = np.bincount(src, minlength=n)
    cfg = AgentSimConfig(n_steps=n_steps, dt=0.05, max_steps_per_launch=chunk)

    results = {}
    final = {}
    for d in (64, 256, 512, 1024):
        pg = prepare_agent_graph(
            betas, src, dst, n, config=cfg, engine="incremental",
            incremental_max_degree=d,
        )
        t0 = time.perf_counter()
        res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
        jax.block_until_ready(res.withdrawn_frac)
        first = time.perf_counter() - t0
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
            jax.block_until_ready(res.withdrawn_frac)
            times.append(time.perf_counter() - t0)
        final[d] = (int(np.asarray(res.informed).sum()), float(res.withdrawn_frac[-1]))
        n_rec = int(np.asarray(res.full_recount_steps).sum())
        best = min(times)
        results[str(d)] = {
            "hubs": int((outdeg > d).sum()),
            "recount_steps": n_rec,
            "first_call_s": round(first, 2),
            "steady_s": round(best, 3),
            "agent_steps_per_sec": round(n * n_steps / best, 1),
        }
        print(
            f"  d={d:5d}: {best:7.3f}s steady ({n * n_steps / best / 1e6:5.1f}M "
            f"agent-steps/s; {n_rec}/{n_steps} recounts; first {first:.1f}s)"
        )

    assert len(set(final.values())) == 1, final  # d is perf-only: outputs identical
    best_d = min(results, key=lambda k: results[k]["steady_s"])
    gain = results["64"]["steady_s"] / results[best_d]["steady_s"]
    print(f"  best: d={best_d} ({gain:.2f}x vs the d=64 default)")

    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(
                {
                    "platform": platform,
                    "n_agents": n,
                    "n_steps": n_steps,
                    "per_max_degree": results,
                    "best_max_degree": int(best_d),
                    "gain_vs_default": round(gain, 3),
                },
                fh,
                indent=1,
            )
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
