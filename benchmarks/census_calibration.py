"""Calibrate the auto-engine census against measured recount telemetry.

The `engine="auto"` census predicts the incremental engine's full-recount
steps from the logistic trajectory + a saturating hub term
(`agents._census_fallback_steps`). Its known bias (benchmarks/RESULTS.md,
"Auto-engine census vs measurement"): on Chung-Lu hub tails it
over-predicts — hub changes FRONT-LOAD into an early tight wave (a hub's
high in-degree samples the true small G(t) while degree-10 agents'
quantized neighbor fractions lag), so late bulk steps are hub-clean. With
only two TPU end-to-end data points that bias could not be fit.

`AgentSimResult.full_recount_steps` (round-5 telemetry) changes that: the
fallback PATTERN is a property of the simulation dynamics, bit-identical
on any platform, so the census's prediction can be diffed against ground
truth wholesale on CPU. This script does exactly that across a shape grid
(ER + Chung-Lu tails at several γ and n, constant and lognormal β) and
reports predicted vs measured recount steps per shape.

Run: python benchmarks/census_calibration.py [--quick]
  SBR_ABL_JSON=path writes the artifact. CPU by default (the point is
  platform independence); runs anywhere.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from sbr_tpu.utils.platform import pin_cpu_platform

    if os.environ.get("SBR_ABL_PLATFORM", "cpu") == "cpu":
        pin_cpu_platform()
    import numpy as np

    from sbr_tpu.social import (
        AgentSimConfig,
        erdos_renyi_edges,
        prepare_agent_graph,
        scale_free_edges,
        simulate_agents,
    )
    from sbr_tpu.social.agents import (
        _census_fallback_steps,
        _default_incremental_budget,
    )

    quick = "--quick" in sys.argv
    scale = 0.1 if quick else 1.0

    def logn_betas(n, seed=1):
        return (
            np.random.default_rng(seed)
            .lognormal(mean=0.0, sigma=0.5, size=n)
            .astype(np.float32)
        )

    # (name, n, graph builder, betas, n_steps, dt)
    shapes = [
        ("er_1e6_b1", int(1e6 * scale), lambda n: erdos_renyi_edges(n, 10.0, seed=0),
         1.0, 200, 0.05),
        ("er_3e5_b3", int(3e5 * scale), lambda n: erdos_renyi_edges(n, 10.0, seed=0),
         3.0, 120, 0.05),
        ("cl_g2.5_1e6_logn", int(1e6 * scale),
         lambda n: scale_free_edges(n, avg_degree=10.0, gamma=2.5, seed=0),
         "logn", 200, 0.05),
        ("cl_g2.5_3e5_logn", int(3e5 * scale),
         lambda n: scale_free_edges(n, avg_degree=10.0, gamma=2.5, seed=0),
         "logn", 200, 0.05),
        ("cl_g2.2_3e5_logn", int(3e5 * scale),
         lambda n: scale_free_edges(n, avg_degree=10.0, gamma=2.2, seed=0),
         "logn", 200, 0.05),
        ("cl_g3.0_1e6_logn", int(1e6 * scale),
         lambda n: scale_free_edges(n, avg_degree=10.0, gamma=3.0, seed=0),
         "logn", 200, 0.05),
    ]

    rows = {}
    for name, n, build, beta_spec, n_steps, dt in shapes:
        t0 = time.perf_counter()
        src, dst = build(n)
        betas = logn_betas(n) if beta_spec == "logn" else beta_spec
        beta_mean = float(np.mean(betas)) if beta_spec == "logn" else float(beta_spec)
        cfg = AgentSimConfig(n_steps=n_steps, dt=dt)
        pg = prepare_agent_graph(betas, src, dst, n, config=cfg, engine="incremental")
        outdeg = np.bincount(np.asarray(src), minlength=n)
        budget = _default_incremental_budget(n)
        hubs = int((outdeg > 64).sum())
        # waves=1: these configs use the default window (no reentry), so
        # each agent changes once — the same value prepare_agent_graph
        # derives from the config
        predicted = _census_fallback_steps(
            outdeg, 64, n_steps, n, beta_mean, dt, budget, waves=1.0
        )
        res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
        measured = int(np.asarray(res.full_recount_steps).sum())
        final_g = float(res.informed_frac[-1])
        rows[name] = {
            "n": n,
            "hubs_gt64": hubs,
            "beta_mean": round(beta_mean, 4),
            "n_steps": n_steps,
            "predicted_recounts": round(predicted, 1),
            "measured_recounts": measured,
            "ratio_pred_over_meas": round(predicted / max(measured, 1), 2),
            # a die-out (final_G ≈ x0) voids the row: the census models a
            # realized contagion, not extinction fluctuations
            "final_G": round(final_g, 4),
            "wall_s": round(time.perf_counter() - t0, 1),
        }
        print(
            f"  {name:>20}: predicted {predicted:6.1f} vs measured {measured:4d} "
            f"of {n_steps} (H={hubs}, ratio {rows[name]['ratio_pred_over_meas']}, "
            f"final G={final_g:.3f})"
        )

    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        with open(out_path, "w") as fh:
            json.dump({"scale": scale, "shapes": rows}, fh, indent=1)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
