#!/bin/bash
# Round-long TPU session watcher (VERDICT r4 task 1): probe the tunnel every
# INTERVAL seconds with bench.py's killable probe; the moment a probe shows an
# accelerator, run the FULL capture session (benchmarks/tpu_session.sh — bench,
# Pallas recount, grid-cell roofline, sharded engines, stretch) and exit 0 so
# the caller can commit artifacts. Exits 1 after MAX_PROBES failed probes.
#
# A probe-script FAILURE (import error, bad env) is logged distinctly from a
# clean "no accelerator" probe — a broken snippet must not silently burn the
# whole watch window looking like tunnel downtime.
#
# Usage: bash benchmarks/tpu_watch.sh [MAX_PROBES] [INTERVAL_S]
set -u -o pipefail
cd "$(dirname "$0")/.."
MAX_PROBES=${1:-72}
INTERVAL_S=${2:-570}
export SBR_WATCH_PROBE_TIMEOUT_S=${SBR_WATCH_PROBE_TIMEOUT_S:-150}

for attempt in $(seq 1 "$MAX_PROBES"); do
  export SBR_WATCH_PROBE_ATTEMPT=$attempt
  if PLATFORM=$(python - <<'PYEOF'
import os
import bench
t = float(os.environ["SBR_WATCH_PROBE_TIMEOUT_S"])
attempt = int(os.environ["SBR_WATCH_PROBE_ATTEMPT"])
p, outcome, dur = bench._probe_accelerator(t)
bench._log_capture_attempt({"script": "tpu_watch.sh", "platform": p or None,
                            "outcome": outcome, "probe_attempt": attempt})
print(p or "")
PYEOF
  ); then
    echo "[tpu_watch] probe ${attempt}/${MAX_PROBES}: platform='${PLATFORM}'" >&2
  else
    echo "[tpu_watch] probe ${attempt}/${MAX_PROBES}: PROBE SCRIPT ERROR (rc=$?) — not a tunnel result" >&2
    PLATFORM=""
  fi
  if [ -n "$PLATFORM" ] && [ "$PLATFORM" != "cpu" ]; then
    echo "[tpu_watch] accelerator up — running full session" >&2
    bash benchmarks/tpu_session.sh
    exit 0
  fi
  [ "$attempt" -lt "$MAX_PROBES" ] && sleep "$INTERVAL_S"
done
echo "[tpu_watch] no accelerator in ${MAX_PROBES} probes" >&2
exit 1
