"""Vector-curve parity for the reference's 12 committed line-plot figures.

The reference's deliverable is its committed figure set
(`/root/reference/output/figures/**.pdf`, manifest `MASTER.jl:31-88`).
Round 4 diffed the two heatmaps cell-for-cell against the raster embedded in
the reference's own PDF (`benchmarks/reference_frontier.py`); this module
does the analogue for the other 12 figures, which are VECTOR line plots:

- parse each PDF's content stream (GKS 5 PDF driver — one operator per
  line, no text operators: tick labels are filled glyph outlines, data
  polylines are `m`/`l` paths ended by `S`) and recover every stroked
  polyline with its color / width / alpha / dash state;
- calibrate the device→data affine map per axis from the figure's grid
  lines (evenly spaced, known round tick values — verified, not assumed:
  the calibration asserts uniform spacing and semantic anchors like CDF
  plateaus at 1.0) or, where the reference sets axis limits explicitly
  (`plot_equilibrium`'s xlims/ylims, `plotting.jl:190-198`), from the plot
  box corners;
- identify each data series by its stroke color (the reference uses named
  Julia colors per series — `plotting.jl:31,107-125,171-173`,
  `2_heterogeneity.jl:92`, `3_interest_rates.jl:101-160`);
- recompute the same curves with sbr_tpu at the script calibrations and
  report per-series max/mean |Δy| in data units, plus the fraction of the
  y-axis range.

Output: benchmarks/CURVES_vs_reference.json + a table printed to stdout
(narrative lands in PARITY.md). `tests/test_reference_curves.py` asserts
the per-figure tolerances. Run: python benchmarks/reference_curves.py
(host-side; solver work pinned to CPU f64).

Usage:
    python benchmarks/reference_curves.py --dump   # stroke inventory only
    python benchmarks/reference_curves.py          # full parity run
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import zlib
from pathlib import Path

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_FIGDIR = Path("/root/reference/output/figures")
OUT_JSON = Path(__file__).resolve().parent / "CURVES_vs_reference.json"


# ---------------------------------------------------------------------------
# GKS PDF content-stream parsing
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Stroke:
    color: tuple  # stroke RGB (RG operator)
    width: float
    alpha: str  # ExtGState name, e.g. GS255 (opaque) / GS25 (grid)
    dash: str  # dash array tokens, "" = solid
    pts: np.ndarray  # (n, 2) device coords


def _page_stream(pdf_path: Path) -> str:
    data = pdf_path.read_bytes()
    m = re.search(rb"/ExtGState.*?>>\s*stream\r?\n", data, re.S)
    start = m.end()
    end = data.index(b"endstream", start)
    return zlib.decompress(data[start:end].rstrip(b"\r\n")).decode("latin1")


def parse_strokes(pdf_path: Path) -> list[Stroke]:
    """All stroked paths with their graphics state.

    The GKS driver emits flat output (state set right before each path, no
    nested q/Q state dependence for color/width/dash), so a linear walk
    suffices. Clip-path segments (`W n`) and glyph fills (`f`) are dropped:
    `n`/`f`/`f*` clear the current path without recording a stroke.
    """
    toks = _page_stream(pdf_path).split()
    strokes: list[Stroke] = []
    cur: list[tuple] = []
    color = (0.0, 0.0, 0.0)
    width = 1.0
    alpha = "GS255"
    dash = ""
    i = 0
    while i < len(toks):
        t = toks[i]
        if t in ("m", "l"):
            cur.append((float(toks[i - 2]), float(toks[i - 1])))
        elif t in ("v", "y"):
            # curve ops appear only in glyph outlines; keep endpoint so the
            # path clears correctly, the path dies at `f` anyway
            cur.append((float(toks[i - 2]), float(toks[i - 1])))
        elif t == "c":
            cur.append((float(toks[i - 2]), float(toks[i - 1])))
        elif t == "RG":
            color = (float(toks[i - 3]), float(toks[i - 2]), float(toks[i - 1]))
        elif t == "w":
            width = float(toks[i - 1])
        elif t == "gs":
            alpha = toks[i - 1].lstrip("/")
        elif t == "d":
            # dash array: tokens between '[' and ']' before the phase
            j = i - 2
            arr = []
            while j >= 0 and not toks[j].startswith("["):
                arr.append(toks[j].rstrip("]"))
                j -= 1
            lead = toks[j].lstrip("[").rstrip("]") if j >= 0 else ""
            if lead:
                arr.append(lead)
            dash = " ".join(reversed([a for a in arr if a]))
        elif t == "S":
            if cur:
                strokes.append(Stroke(color, width, alpha, dash, np.asarray(cur)))
            cur = []
        elif t in ("n", "f", "f*", "b", "B"):
            cur = []
        i += 1
    return strokes


# ---------------------------------------------------------------------------
# Figure geometry: plot box, grid-line ticks, calibration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Geometry:
    box: tuple  # (x0, x1, y0, y1) device coords of the axes frame
    xticks: np.ndarray  # device x of vertical grid lines
    yticks: np.ndarray  # device y of horizontal grid lines


def figure_geometry(strokes: list[Stroke]) -> Geometry:
    """Plot box from the grid-line extents; tick positions from the
    grid lines themselves (GS25 alpha strokes spanning the box)."""
    grid = [s for s in strokes if s.alpha == "GS25" and len(s.pts) == 2]
    if not grid:
        raise ValueError("no grid lines found (figure drawn without grid?)")
    xt, yt = [], []
    x0 = min(s.pts[:, 0].min() for s in grid)
    x1 = max(s.pts[:, 0].max() for s in grid)
    y0 = min(s.pts[:, 1].min() for s in grid)
    y1 = max(s.pts[:, 1].max() for s in grid)
    for s in grid:
        (ax, ay), (bx, by) = s.pts
        if abs(ax - bx) < 1e-6:  # vertical grid line -> x tick
            xt.append(ax)
        elif abs(ay - by) < 1e-6:
            yt.append(ay)
    return Geometry((x0, x1, y0, y1), np.sort(np.unique(xt)), np.sort(np.unique(yt)))


@dataclasses.dataclass
class Axis:
    """Affine device->data map for one axis: data = (dev - d0) * scale + v0."""

    d0: float
    scale: float
    v0: float

    def to_data(self, dev):
        return (np.asarray(dev) - self.d0) * self.scale + self.v0


def axis_from_ticks(dev_ticks: np.ndarray, values: list[float]) -> Axis:
    """Calibrate from grid-line device positions + their known data values.
    Verifies the device spacing is uniform and consistent with the values."""
    dev_ticks = np.asarray(dev_ticks, float)
    assert len(dev_ticks) == len(values), (
        f"tick count mismatch: {len(dev_ticks)} device vs {len(values)} values"
    )
    values = np.asarray(values, float)
    # least-squares affine fit; residual must be sub-point (device is 0.01pt)
    A = np.stack([values, np.ones_like(values)], axis=1)
    (slope, intercept), res, *_ = np.linalg.lstsq(A, dev_ticks, rcond=None)
    fit = A @ [slope, intercept]
    max_res = float(np.abs(fit - dev_ticks).max())
    assert max_res < 0.05, f"tick calibration residual {max_res:.3f}pt — wrong tick values?"
    return Axis(d0=intercept, scale=1.0 / slope, v0=0.0)


def axis_from_box(d_lo: float, d_hi: float, v_lo: float, v_hi: float) -> Axis:
    """Calibrate from the plot-box edges when the reference sets explicit
    axis limits (xlims/ylims), which GR maps exactly to the frame."""
    return Axis(d0=d_lo, scale=(v_hi - v_lo) / (d_hi - d_lo), v0=v_lo)


# ---------------------------------------------------------------------------
# Series extraction
# ---------------------------------------------------------------------------

# Named Julia colors used by the reference, as the GKS driver writes them
# (3-decimal RGB). Values confirmed against the PDFs' RG operators.
COLORS = {
    "blue": (0.0, 0.0, 1.0),
    "red": (1.0, 0.0, 0.0),
    "green": (0.0, 0.502, 0.0),
    "darkred": (0.545, 0.0, 0.0),
    "royalblue": (0.255, 0.412, 0.882),
    "mediumvioletred": (0.78, 0.082, 0.522),
    "tomato": (1.0, 0.388, 0.278),
    "darkgoldenrod": (0.722, 0.525, 0.043),
    "darkgreen": (0.0, 0.392, 0.0),
    "darkorange": (1.0, 0.549, 0.0),
    "grey": (0.502, 0.502, 0.502),
    "darkgray": (0.663, 0.663, 0.663),
    "black": (0.0, 0.0, 0.0),
    # Plots.jl default-palette series 2 (the un-colored "Return Time" line,
    # `plotting.jl:283-286`), as GKS writes it
    "palette2": (0.8889, 0.4356, 0.2781),
}


def _color_match(c1, c2, tol=0.02):
    return all(abs(a - b) <= tol for a, b in zip(c1, c2))


def series(strokes, color_name, min_pts=10, width=None):
    """Concatenated device polyline of all data strokes in a color.

    GR may split one logical curve into several strokes (clip re-entry);
    they are emitted in order, so concatenation restores the polyline.
    Short strokes (legend samples, tick marks, annotation lines) are
    excluded by ``min_pts`` — pass ``width`` to disambiguate same-color
    series by line width instead.
    """
    want = COLORS[color_name]
    parts = [
        s.pts
        for s in strokes
        if _color_match(s.color, want)
        and len(s.pts) >= min_pts
        and (width is None or abs(s.width - width) < 0.26)
    ]
    if not parts:
        raise ValueError(f"no stroke found for color {color_name} (width={width})")
    return np.concatenate(parts, axis=0)


def diff_series(ref_xy, our_x, our_y, x_window=None, y_clip=None):
    """max/mean |Δy| between a reference polyline (data coords) and our curve
    sampled on ``our_x``: our y is interpolated at the reference's x knots.

    ``x_window`` restricts to an x interval (drop clipped edges);
    ``y_clip`` drops reference points pinned to the axis limits by GR's
    clipping (their true value is outside the frame — not comparable).
    """
    x, y = ref_xy[:, 0], ref_xy[:, 1]
    keep = np.ones(len(x), bool)
    if x_window is not None:
        keep &= (x >= x_window[0]) & (x <= x_window[1])
    if y_clip is not None:
        eps = 1e-9 + 2e-4 * (y.max() - y.min())
        keep &= (y > y_clip[0] + eps) & (y < y_clip[1] - eps)
    x, y = x[keep], y[keep]
    ours = np.interp(x, our_x, our_y)
    d = np.abs(ours - y)
    return {
        "n_ref_points": int(len(x)),
        "max_abs_dy": float(d.max()),
        "mean_abs_dy": float(d.mean()),
    }


# ---------------------------------------------------------------------------
# Dump mode: stroke inventory per figure (used to pin calibrations)
# ---------------------------------------------------------------------------

ALL_PDFS = [
    "baseline/learning_dynamics.pdf",
    "baseline/hazard_rate.pdf",
    "baseline/equilibrium_dynamics_main.pdf",
    "baseline/equilibrium_dynamics_fast.pdf",
    "baseline/equilibrium_dynamics_low_u.pdf",
    "baseline/comp_stat_u_panel_a.pdf",
    "baseline/comp_stat_u_panel_b.pdf",
    "heterogeneity/aggregate_withdrawals_hetero.pdf",
    "interest_rates/hazard_decomposition.pdf",
    "interest_rates/value_function.pdf",
    "social_learning/baseline_equilibrium.pdf",
    "social_learning/social_learning_equilibrium.pdf",
]


def dump():
    from collections import Counter

    for rel in ALL_PDFS:
        strokes = parse_strokes(REF_FIGDIR / rel)
        geo = figure_geometry(strokes)
        print(f"\n=== {rel}")
        print(f"  box={tuple(round(v, 2) for v in geo.box)}")
        print(f"  xticks={np.round(geo.xticks, 2).tolist()}")
        print(f"  yticks={np.round(geo.yticks, 2).tolist()}")
        cnt = Counter(
            (s.color, s.width, s.alpha, s.dash, len(s.pts))
            for s in strokes
            if s.alpha != "GS25" and len(s.pts) > 2
        )
        for (color, width, alpha, dash, n), k in sorted(cnt.items(), key=lambda kv: -kv[0][4]):
            name = next((nm for nm, c in COLORS.items() if _color_match(color, c)), color)
            print(f"  {k} x color={name} w={width} dash='{dash}' pts={n}")


# ---------------------------------------------------------------------------
# Auto-limit axis inference (Plots.jl pads auto limits by exactly 3% a side —
# verified on learning_dynamics where the data range is known: box span =
# 1.06 x data span to 4 digits)
# ---------------------------------------------------------------------------

_NICE = np.array([1.0, 2.0, 2.5, 5.0, 10.0])


def _snap_nice(x: float) -> float:
    k = np.floor(np.log10(abs(x)))
    frac = abs(x) / 10.0**k
    return float(np.sign(x) * _NICE[np.argmin(np.abs(_NICE - frac))] * 10.0**k)


def axis_auto(dev_ticks, box_lo, box_hi, data_lo, data_hi, padded=True) -> Axis:
    """Calibrate an auto-limit axis: seed the scale from the 3%-padding
    identity (box span = 1.06 x data span) using OUR data extent, then SNAP
    the implied tick step/origin to round values and recalibrate from the
    ticks alone. The snap is a discrete selection (nice steps are >=25%
    apart), so our data extent only disambiguates candidates — the final
    affine comes from the reference's own tick geometry, and the residual
    assert fails loudly if the reference's data range disagrees with ours
    by more than ~1% instead of producing a silently wrong calibration."""
    dev_ticks = np.asarray(dev_ticks, float)
    span = data_hi - data_lo
    pad = 0.03 * span if padded else 0.0
    scale = (span + 2 * pad) / (box_hi - box_lo)  # data units per device pt
    v_lo = data_lo - pad
    est_vals = (dev_ticks - box_lo) * scale + v_lo
    step_est = float(np.mean(np.diff(est_vals)))
    step = _snap_nice(step_est)
    assert abs(step - step_est) <= 0.08 * abs(step), (
        f"tick step {step_est} does not snap to a nice value (nearest {step})"
    )
    origin = np.round(est_vals[0] / step) * step
    values = origin + step * np.arange(len(dev_ticks))
    max_off = float(np.abs(values - est_vals).max())
    assert max_off <= 0.25 * step, (
        f"snapped ticks {values} off the padding-identity estimate {est_vals}"
    )
    return axis_from_ticks(dev_ticks, values.tolist())


# ---------------------------------------------------------------------------
# The parity run: reference polylines vs sbr_tpu curves, in data coords
# ---------------------------------------------------------------------------


def _series_xy(strokes, ax_x, ax_y, color, min_pts=10, width=None):
    dev = series(strokes, color, min_pts=min_pts, width=width)
    return np.stack([ax_x.to_data(dev[:, 0]), ax_y.to_data(dev[:, 1])], axis=1)


def main() -> int:
    from sbr_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform()
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from sbr_tpu import make_model_params, solve_learning, with_overrides
    from sbr_tpu.baseline.learning import logistic_cdf
    from sbr_tpu.baseline.solver import get_aw, hazard_rate, solve_equilibrium_baseline
    from sbr_tpu.models.params import SolverConfig, make_hetero_params, make_interest_params

    cfg = SolverConfig()
    out: dict = {}

    def record(fig, series_name, res, note=""):
        fig = fig[:-4] if fig.endswith(".pdf") else fig  # one key convention
        out.setdefault(fig, {})[series_name] = {**res, "note": note} if note else res
        print(
            f"  {fig:45s} {series_name:12s} n={res['n_ref_points']:5d} "
            f"max|dy|={res['max_abs_dy']:.2e} mean={res['mean_abs_dy']:.2e}"
        )

    # ---- Figure 1: learning_dynamics (`plotting.jl:24-40`, betas 0.5/1/2,
    # t in (0, 20), 1000 plot points — `1_baseline.jl:56-74`) --------------
    strokes = parse_strokes(REF_FIGDIR / "baseline/learning_dynamics.pdf")
    geo = figure_geometry(strokes)
    ax_x = axis_auto(geo.xticks, geo.box[0], geo.box[1], 0.0, 20.0)
    ax_y = axis_auto(geo.yticks, geo.box[2], geo.box[3], 1e-4, 1.0)
    t_dense = np.linspace(0.0, 20.0, 8001)
    for color, beta in (("blue", 0.5), ("red", 1.0), ("green", 2.0)):
        xy = _series_xy(strokes, ax_x, ax_y, color, min_pts=100)
        ours = np.asarray(logistic_cdf(t_dense, beta, 1e-4))
        record("baseline/learning_dynamics", f"beta={beta}", diff_series(xy, t_dense, ours))

    # ---- Figure 2: hazard_rate (main calibration; the plotted curves are
    # y(x) = f(xi - x) for f in {h, pi, h_f} — `plotting.jl:95-104`) -------
    m_base = make_model_params()
    ls_base = solve_learning(m_base.learning, cfg)
    res_base = solve_equilibrium_baseline(ls_base, m_base.economic, cfg)
    xi = float(res_base.xi)
    tau_grid = np.asarray(res_base.tau_grid)
    _, hf = hazard_rate(1.0, m_base.economic.lam, ls_base, m_base.economic.eta, cfg)
    hf = np.asarray(hf)
    h = np.asarray(res_base.hr)
    with np.errstate(invalid="ignore", divide="ignore"):
        pi = np.clip(np.nan_to_num(h / hf, nan=0.0, posinf=0.0), 0.0, 1.0)

    def hazard_figure(fig_key, fig_rel, xi_v, tau, series_list, mid_val):
        """Shared structure of the two hazard-decomposition figures: explicit
        xlims (0, 1.2 xi) / ylims (0, 1.2*mid) seeded from solver outputs
        whose parity is separately pinned to 1e-6, reversed-time curves
        y(x) = f(xi - x), and GR's top-edge clipping dropped via y_clip."""
        strokes_h = parse_strokes(REF_FIGDIR / fig_rel)
        geo_h = figure_geometry(strokes_h)
        ax_xh = axis_auto(geo_h.xticks, geo_h.box[0], geo_h.box[1], 0.0, 1.2 * xi_v, padded=False)
        ax_yh = axis_auto(geo_h.yticks, geo_h.box[2], geo_h.box[3], 0.0, 1.2 * mid_val, padded=False)
        xs_h = np.linspace(0.0, xi_v, 8001)
        top = ax_yh.to_data(geo_h.box[3])
        for color, vals, width in series_list:
            xy_h = _series_xy(strokes_h, ax_xh, ax_yh, color, min_pts=100, width=width)
            ours_h = np.interp(np.clip(xi_v - xs_h, 0.0, min(1.3 * xi_v, tau[-1])), tau, vals)
            record(
                fig_key,
                color,
                diff_series(xy_h, xs_h, ours_h, x_window=(0.0, xi_v), y_clip=(-np.inf, top)),
            )

    # ylims seed h_f(xi/2) (`plotting.jl:102,111`: mid of eval_points)
    hazard_figure(
        "baseline/hazard_rate",
        "baseline/hazard_rate.pdf",
        xi,
        tau_grid,
        (("mediumvioletred", h, 1.5), ("royalblue", pi, 1.0), ("tomato", hf, 1.0)),
        float(np.interp(0.5 * xi, tau_grid, hf)),
    )

    # ---- Figure 3 family + social figures: plot_equilibrium
    # (`plotting.jl:156-210`: t_grid = 0:0.1:min(2 xi, eta), AW curves,
    # explicit ylims (0,1); baseline variants add x_range (0,15)) ----------
    def eq_dynamics(fig_rel, result, ls, econ, x_explicit):
        xi_l = float(result.xi)
        eta_l = float(econ.eta)
        t_grid = np.arange(0.0, min(2.0 * xi_l, eta_l) + 1e-9, 0.1)
        aw_cum, aw_out, aw_in = (
            np.asarray(a)
            for a in get_aw(
                result.xi, result.tau_bar_in_unc, result.tau_bar_out_unc, t_grid, ls
            )
        )
        strokes_l = parse_strokes(REF_FIGDIR / fig_rel)
        geo_l = figure_geometry(strokes_l)
        if x_explicit is not None:
            ax_xl = axis_from_box(geo_l.box[0], geo_l.box[1], *x_explicit)
        else:
            ax_xl = axis_auto(geo_l.xticks, geo_l.box[0], geo_l.box[1], 0.0, t_grid[-1])
        ax_yl = axis_from_box(geo_l.box[2], geo_l.box[3], 0.0, 1.0)
        for name, vals, width, dash_color in (
            ("AW", aw_cum, 2.0, "darkred"),
            ("Informed", aw_out, 1.0, "darkred"),
            ("Reentered", aw_in, 1.0, "royalblue"),
        ):
            xy = _series_xy(strokes_l, ax_xl, ax_yl, dash_color, min_pts=20, width=width)
            record(fig_rel, name, diff_series(xy, t_grid, vals))

    eq_dynamics(
        "baseline/equilibrium_dynamics_main.pdf", res_base, ls_base, m_base.economic, (0.0, 15.0)
    )
    for name, overrides in (("fast", dict(beta=3.0)), ("low_u", dict(u=0.01))):
        m_alt = with_overrides(m_base, **overrides)
        ls_alt = solve_learning(m_alt.learning, cfg)
        res_alt = solve_equilibrium_baseline(ls_alt, m_alt.economic, cfg)
        eq_dynamics(
            f"baseline/equilibrium_dynamics_{name}.pdf",
            res_alt,
            ls_alt,
            m_alt.economic,
            (0.0, 15.0),
        )

    # ---- Figure 4 panels: 5000-point u-sweep on [0.001, 0.2]
    # (`1_baseline.jl:137-200`, `plotting.jl:233-302`) ---------------------
    from sbr_tpu.sweeps.baseline_sweeps import u_sweep

    u_values = np.linspace(0.001, 0.2, 5000)
    sweep = u_sweep(ls_base, u_values, m_base.economic)
    max_w = np.asarray(sweep.max_withdrawals)
    collapse = np.asarray(sweep.collapse_times)
    ret = np.asarray(sweep.return_times)

    strokes = parse_strokes(REF_FIGDIR / "baseline/comp_stat_u_panel_a.pdf")
    geo = figure_geometry(strokes)
    ax_x = axis_auto(geo.xticks, geo.box[0], geo.box[1], 0.001, 0.2)
    ax_y = axis_from_box(geo.box[2], geo.box[3], 0.0, 1.0)
    xy = _series_xy(strokes, ax_x, ax_y, "darkred", min_pts=100)
    valid = ~np.isnan(max_w)
    record(
        "baseline/comp_stat_u_panel_a",
        "peak_AW",
        diff_series(xy, u_values[valid], max_w[valid]),
    )

    strokes = parse_strokes(REF_FIGDIR / "baseline/comp_stat_u_panel_b.pdf")
    geo = figure_geometry(strokes)
    vc, vr = ~np.isnan(collapse), ~np.isnan(ret)
    data_lo = min(collapse[vc].min(), ret[vr].min())
    data_hi = max(collapse[vc].max(), ret[vr].max())
    ax_x = axis_auto(geo.xticks, geo.box[0], geo.box[1], 0.001, 0.2)
    ax_y = axis_auto(geo.yticks, geo.box[2], geo.box[3], data_lo, data_hi)
    xy = _series_xy(strokes, ax_x, ax_y, "darkgoldenrod", min_pts=100)
    record(
        "baseline/comp_stat_u_panel_b",
        "collapse",
        diff_series(xy, u_values[vc], collapse[vc]),
    )
    xy = _series_xy(strokes, ax_x, ax_y, "palette2", min_pts=100)
    record("baseline/comp_stat_u_panel_b", "return", diff_series(xy, u_values[vr], ret[vr]))

    # ---- Heterogeneity figure (`2_heterogeneity.jl:90-126`: t in
    # range(0, 2 xi, 1000), total + per-group AW) --------------------------
    from sbr_tpu.hetero.learning import solve_learning_hetero
    from sbr_tpu.hetero.solver import get_aw_hetero, solve_equilibrium_hetero

    m_het = make_hetero_params(
        betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
    )
    lsh = solve_learning_hetero(m_het.learning, cfg)
    res_het = solve_equilibrium_hetero(lsh, m_het.economic, cfg)
    aw_het = get_aw_hetero(res_het, lsh)
    xi_het = float(res_het.xi)
    t_het = np.asarray(aw_het.t_grid)
    groups = np.asarray(aw_het.aw_groups)
    cum = np.asarray(aw_het.aw_cum)
    y_lo = min(cum.min(), groups.min())
    y_hi = max(cum.max(), groups.max())

    strokes = parse_strokes(REF_FIGDIR / "heterogeneity/aggregate_withdrawals_hetero.pdf")
    geo = figure_geometry(strokes)
    ax_x = axis_auto(geo.xticks, geo.box[0], geo.box[1], 0.0, 2.0 * xi_het)
    ax_y = axis_auto(geo.yticks, geo.box[2], geo.box[3], y_lo, y_hi)
    for name, vals, color, width in (
        ("total_AW", cum, "darkred", 2.0),
        ("group1", groups[0], "royalblue", 1.0),
        ("group2", groups[1], "darkgreen", 1.0),
    ):
        xy = _series_xy(strokes, ax_x, ax_y, color, min_pts=100, width=width)
        record(
            "heterogeneity/aggregate_withdrawals_hetero",
            name,
            diff_series(xy, t_het, vals, x_window=(0.0, 2.0 * xi_het)),
        )

    # ---- Interest-rate figures (`3_interest_rates.jl:80-183`) ------------
    from sbr_tpu.interest.solver import solve_equilibrium_interest

    m_int = make_interest_params(u=0.0, r=0.06, delta=0.1)
    ls_int = solve_learning(m_int.learning, cfg)
    res_int = solve_equilibrium_interest(ls_int, m_int.economic, cfg)
    xi_i = float(res_int.base.xi)
    tau_i = np.asarray(res_int.base.tau_grid)
    v_i = np.asarray(res_int.v)

    # value_function: x = xi - tau (tau in range(0, eta, 500) kept where
    # t >= 0), explicit xlims (0, max t) = (0, xi); y auto with the terminal
    # hline delta/(delta-r) = 2.5 extending the range.
    strokes = parse_strokes(REF_FIGDIR / "interest_rates/value_function.pdf")
    geo = figure_geometry(strokes)
    v_term = m_int.economic.delta / (m_int.economic.delta - m_int.economic.r)
    v_on_t = np.interp(xi_i - np.linspace(0.0, xi_i, 4001), tau_i, v_i)
    ax_x = axis_auto(geo.xticks, geo.box[0], geo.box[1], 0.0, xi_i, padded=False)
    ax_y = axis_auto(geo.yticks, geo.box[2], geo.box[3], float(v_on_t.min()), v_term)
    # external y anchor: the dashed terminal-value hline must map to 2.5.
    # (Select the stroke spanning the plot box — the legend also contains a
    # short darkgray sample line at an unrelated position.)
    hline = max(
        (
            s.pts
            for s in strokes
            if _color_match(s.color, COLORS["darkgray"]) and len(s.pts) == 2
        ),
        key=lambda p: p[:, 0].max() - p[:, 0].min(),
    )
    anchor_err = abs(float(ax_y.to_data(hline[:, 1].mean())) - v_term)
    assert anchor_err < 0.005, f"terminal-value hline maps to {anchor_err} off 2.5"
    xy = _series_xy(strokes, ax_x, ax_y, "royalblue", min_pts=100, width=2.0)
    record(
        "interest_rates/value_function",
        "V(t)",
        diff_series(xy, np.linspace(0.0, xi_i, 4001), v_on_t),
        note=f"terminal hline anchor err {anchor_err:.1e}",
    )

    # hazard_decomposition: same y(x) = f(xi - x) structure as Figure 2,
    # plus the rV threshold curve (u = 0).
    _, hf_i = hazard_rate(1.0, m_int.economic.lam, ls_int, m_int.economic.eta, cfg)
    hf_i = np.asarray(hf_i)
    h_i = np.asarray(res_int.base.hr)
    with np.errstate(invalid="ignore", divide="ignore"):
        pi_i = np.clip(np.nan_to_num(h_i / hf_i, nan=0.0, posinf=0.0), 0.0, 1.0)
    thr_i = m_int.economic.u + m_int.economic.r * v_i

    # The interest figure's ylims seed is h_bar_f_vals[div(1000,2)] with the
    # vals on range(0, min(eta, xi), 1000) (`3_interest_rates.jl:130,148`) —
    # i.e. h_f at tau = (499/999)*min(eta, xi), NOT the middle of our grid.
    tau_mid = (500 - 1) / (1000 - 1) * min(float(m_int.economic.eta), xi_i)
    hazard_figure(
        "interest_rates/hazard_decomposition",
        "interest_rates/hazard_decomposition.pdf",
        xi_i,
        tau_i,
        (
            ("mediumvioletred", h_i, 1.5),
            ("royalblue", pi_i, 1.0),
            ("tomato", hf_i, 1.0),
            ("darkgray", thr_i, 1.0),
        ),
        float(np.interp(tau_mid, tau_i, hf_i)),
    )

    # ---- Social-learning figures (`4_social_learning.jl:101-119`:
    # plot_equilibrium on the fixed point and the WOM baseline) ------------
    from sbr_tpu.social.solver import solve_equilibrium_social

    m_soc = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
    social = solve_equilibrium_social(m_soc, cfg, tol=1e-4, max_iter=500)
    ls_wom = solve_learning(m_soc.learning, cfg)
    res_wom = solve_equilibrium_baseline(ls_wom, m_soc.economic, cfg)
    eq_dynamics(
        "social_learning/baseline_equilibrium.pdf", res_wom, ls_wom, m_soc.economic, None
    )
    eq_dynamics(
        "social_learning/social_learning_equilibrium.pdf",
        social.equilibrium,
        social.learning,
        m_soc.economic,
        None,
    )

    OUT_JSON.write_text(json.dumps(out, indent=1))
    print(f"\nwrote {OUT_JSON}")
    worst = max(
        (res["max_abs_dy"], f"{fig}:{name}")
        for fig, sers in out.items()
        for name, res in sers.items()
    )
    print(f"worst series: {worst[1]} max|dy| = {worst[0]:.3e}")
    return 0


if __name__ == "__main__":
    if "--dump" in sys.argv:
        dump()
    else:
        sys.exit(main())
