"""Roofline the β×u grid cell (VERDICT r3 task 5).

The agent sim got a per-stage ablation (`ablate_agent_step.py`) that found
its gather wall and motivated the event-driven engine; the grid sweep —
the repo's headline metric — never did. This script times the vmap² grid
program (`sweeps/baseline_sweeps.py::_grid_fn`) across config axes that
isolate its stages:

  bisect_iters 30/60/90   Stage-3 cost: each iteration is two closed-form
                          G evaluations (exp + divide) per cell
  quad_order 2/4/8        Stage-2 hazard quadrature: order×(n_grid-1)
                          exp+logistic evaluations per cell
  n_grid 512/1024/2048    everything grid-shaped: quadrature points,
                          crossing scan, AW_max reduction
  grid_warp 0/0.5         the round-4 transition-resolving grid: its
                          jnp.sort(n_grid) per cell is the suspected cost
                          of the high-β parity fix (tests/ref_emulator.py)

plus a HOISTED-HAZARD probe: the hazard (grid construction + quadrature +
HR values) depends only on β, not u, so the vmap² program recomputes it
n_u× redundantly; `hazard_hoist_estimate` measures a β-row's hazard alone
to bound what restructuring the sweep as per-row hazard + per-cell
crossings/bisection would save.

Writes one JSON artifact; conclusions land in benchmarks/RESULTS.md.

Run: python benchmarks/ablate_grid_cell.py [n_beta] [n_u]
  SBR_ABL_PLATFORM=cpu pins CPU; SBR_ABL_JSON=path writes the artifact.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("SBR_ABL_PLATFORM", "") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    n_beta = int(sys.argv[1]) if len(sys.argv) > 1 else 640
    n_u = int(sys.argv[2]) if len(sys.argv) > 2 else 640
    platform = jax.devices()[0].platform
    print(f"platform={platform} grid={n_beta}x{n_u} f32 (bench configuration)")

    base = make_model_params()
    amt = np.linspace(1e-4, 1.0, n_beta)
    betas = 1.0 / amt
    us = np.linspace(0.001, 1.0, n_u)

    def timed(config: SolverConfig) -> float:
        def dispatch(rep):
            grid = beta_u_grid(
                betas, us + rep * 1e-6, base, config=config, dtype=jnp.float32
            )
            return grid, (
                jnp.sum(grid.status) + jnp.nansum(grid.max_aw) + jnp.nansum(grid.xi)
            )

        float(dispatch(0)[1])  # compile + fence
        # sustained timing (bench.pipelined_time): the per-dispatch RPC
        # floor on this rig (~0.1 s) used to flatten every variant to the
        # same fenced number — the 2026-07-31T0102 capture read n_grid
        # 512/1024/2048 within 3% of each other, which measured the tunnel
        pipelined_s, _ = bench.pipelined_time(dispatch, start_rep=1, n_pipe=6)
        return pipelined_s

    baseline_cfg = dict(n_grid=1024, bisect_iters=60, refine_crossings=False)
    variants = {
        "baseline(1024,60,q8,warp.5)": SolverConfig(**baseline_cfg),
        "bisect30": SolverConfig(**{**baseline_cfg, "bisect_iters": 30}),
        "bisect90": SolverConfig(**{**baseline_cfg, "bisect_iters": 90}),
        "quad2": SolverConfig(**{**baseline_cfg, "quad_order": 2}),
        "quad4": SolverConfig(**{**baseline_cfg, "quad_order": 4}),
        "warp0(uniform grid)": SolverConfig(**{**baseline_cfg, "grid_warp": 0.0}),
        "ngrid512": SolverConfig(**{**baseline_cfg, "n_grid": 512}),
        "ngrid2048": SolverConfig(**{**baseline_cfg, "n_grid": 2048}),
    }
    results = {}
    for name, cfg in variants.items():
        best = timed(cfg)
        results[name] = round(best, 4)
        print(f"{name:>28}: {best:.4f}s  ({n_beta * n_u / best / 1e6:.2f}M eq/s)")

    # hoisted-hazard bound: hazard work alone for all β rows (one cell per
    # β in u), vs the full grid — the gap × (1 - 1/n_u) is the redundancy
    t_row = None
    try:
        cfg = SolverConfig(**baseline_cfg)

        def hazard_dispatch(rep):
            grid = beta_u_grid(
                betas, np.array([0.5 + rep * 1e-6]), base, config=cfg, dtype=jnp.float32
            )
            return grid, jnp.nansum(grid.xi) + jnp.sum(grid.status)

        float(hazard_dispatch(0)[1])
        # same sustained protocol as the variants, or the ratio below just
        # reads the RPC floor against a pipelined denominator
        t_row, _ = bench.pipelined_time(hazard_dispatch, start_rep=1, n_pipe=6)
        print(
            f"{'hazard+1cell per beta-row':>28}: {t_row:.4f}s "
            f"(if hoisted, bounds per-row overhead at {t_row / results['baseline(1024,60,q8,warp.5)'] * 100:.0f}% "
            "of full-grid time)"
        )
    except Exception as err:
        print(f"hazard-row probe failed: {err!r}")

    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        payload = {
            "platform": platform,
            "grid": [n_beta, n_u],
            # pipelined mean-of-6 per-dispatch seconds; earlier ABLATE_GRID_*
            # artifacts recorded best-of-3 individually-fenced wall times
            # under "best_wall_s" — different protocol, marked here so
            # cross-artifact diffs don't compare incompatible numbers
            "protocol": "pipelined_mean6",
            "best_wall_s": results,
            "hazard_row_s": round(t_row, 4) if t_row else None,
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
