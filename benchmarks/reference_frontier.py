"""Compare the Figure-5 no-run frontier against the reference's COMMITTED figure.

The reference's deliverable is figure parity (`MASTER.jl:31-88`), and its
paper-resolution heatmap PDF (`/root/reference/output/figures/baseline/
comp_stat_cross_heatmap_AW_large.pdf`) embeds the full 5000×5000 raster:
a DeviceRGB image (viridis-mapped AW_max) plus a DeviceGray soft mask in
which NaN (no-run) cells are fully transparent (value 0) and run cells
carry the plot's alpha=0.8 (value 204) — `scripts/1_baseline.jl:278-284`.
That mask is an EXTERNAL, bit-exact record of the reference's own no-run
region, cell for cell, produced by the reference's own adaptive-grid
numerics on the author's machine.

This script extracts the mask + RGB (pure stdlib zlib; the PDF streams are
FlateDecode), assembles this repo's 5000×5000 status grid from the
checkpointed tiles (`output/checkpoints/heatmap_large/`, written by
`python -m sbr_tpu.figures.master --paper`), aligns orientations
(raster row 0 = u = 1.0; column i = ave_meeting_time index i), and reports:

- run/no-run disagreement count and its spatial distribution (distance to
  the frontier in grid cells);
- the split between genuine frontier disagreement and the reference's
  early-termination fill (after 5 consecutive no-run u's per column the
  reference fills the REST of the column with NaN without solving —
  `1_baseline.jl:236-244` — so cells above that cut were never computed
  there; a run cell of ours in that region is not a numerics difference);
- an approximate AW-value comparison by inverting the viridis colormap of
  the RGB raster against our max_aw (8-bit quantized, so ~1/255 of the
  color range is the floor).

Writes a JSON artifact; the narrative lands in PARITY.md.

Run: python benchmarks/reference_frontier.py  (host-side numpy only)
"""

from __future__ import annotations

import json
import os
import re
import sys
import zlib
from pathlib import Path

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_PDF = Path("/root/reference/output/figures/baseline/comp_stat_cross_heatmap_AW_large.pdf")
TILE_DIR = Path(__file__).resolve().parent.parent / "output/checkpoints/heatmap_large"
N = 5000
TILE = 500


def extract_raster(pdf_path: Path):
    """Pull the (mask, rgb) 5000×5000 arrays out of the PDF's image streams."""
    data = pdf_path.read_bytes()
    streams = []
    for m in re.finditer(
        rb"<<[^<>]*?/Subtype\s*/Image(?:[^<>]|<<[^<>]*>>)*?>>\s*stream\r?\n", data, re.S
    ):
        d = m.group(0)
        w = int(re.search(rb"/Width (\d+)", d).group(1))
        h = int(re.search(rb"/Height (\d+)", d).group(1))
        gray = b"/DeviceGray" in d
        start = m.end()
        end = data.index(b"endstream", start)
        raw = zlib.decompress(data[start:end].rstrip(b"\r\n"))
        arr = np.frombuffer(raw, np.uint8)
        streams.append((gray, arr.reshape(h, w) if gray else arr.reshape(h, w, 3)))
    mask = next(a for g, a in streams if g)
    rgb = next(a for g, a in streams if not g)
    return mask, rgb


def load_tiles():
    """Assemble (status, max_aw) [amt_index, u_index] from the tile store."""
    status = np.full((N, N), -1, np.int32)
    max_aw = np.full((N, N), np.nan, np.float32)
    for bi in range(0, N, TILE):
        for ui in range(0, N, TILE):
            t = np.load(TILE_DIR / f"tile_b{bi:05d}_u{ui:05d}.npz")
            status[bi : bi + TILE, ui : ui + TILE] = t["status"]
            max_aw[bi : bi + TILE, ui : ui + TILE] = t["max_aw"]
    assert (status >= 0).all(), "tile store incomplete"
    return status, max_aw


def main() -> None:
    mask, rgb = extract_raster(REF_PDF)
    status, max_aw = load_tiles()

    # orientation: raster[r, c] ↔ (u index N-1-r, amt index c); ours is
    # [amt, u] → transpose to [u, amt] and flip u to match the raster
    ours_norun = (status.T != 0)[::-1, :]
    ref_norun = mask == 0

    agree = ours_norun == ref_norun
    n_dis = int((~agree).sum())
    print(f"no-run masks: {N*N} cells, disagreements: {n_dis} ({n_dis/(N*N):.3e})")
    print(f"  ref no-run frac:  {ref_norun.mean():.6f}")
    print(f"  ours no-run frac: {ours_norun.mean():.6f}")

    # Split disagreements against the reference's early-termination fill:
    # per column the reference solves UP from u=0.001 and, after 5
    # consecutive no-run cells, fills the REST with NaN WITHOUT solving
    # (`1_baseline.jl:236-244`). A disagreement above that cut is "the
    # reference never computed this cell", not a numerics difference.
    ref_bot = ref_norun[::-1, :]  # row 0 = u smallest, solve order
    win5 = np.lib.stride_tricks.sliding_window_view(ref_bot, 5, axis=0).all(axis=-1)
    has_cut = win5.any(axis=0)
    cut_start = np.where(has_cut, np.argmax(win5, axis=0), N)  # first row of the 5-block
    fill_from = cut_start + 5  # rows >= this were never solved by the reference
    bot_rows = N - 1 - np.nonzero(~agree)[0]  # disagreements in solve order
    dis_cols = np.nonzero(~agree)[1]
    in_fill = bot_rows >= fill_from[dis_cols]
    ours_run_there = ~ours_norun[::-1, :][bot_rows, dis_cols]
    n_fill = int((in_fill & ours_run_there).sum())
    genuine = ~(in_fill & ours_run_there)
    n_genuine = int(genuine.sum())
    print(
        f"  split: {n_genuine} genuine (reference solved the cell), "
        f"{n_fill} in the reference's early-termination fill (never solved there)"
    )

    # frontier distance for GENUINE disagreements only, and only in columns
    # where the reference actually has a boundary
    first_norun = np.where(ref_bot.any(axis=0), np.argmax(ref_bot, axis=0), -1)
    g_rows = bot_rows[genuine]
    g_cols = dis_cols[genuine]
    bounded = first_norun[g_cols] >= 0
    dist = np.abs(g_rows[bounded] - first_norun[g_cols[bounded]])
    n_unbounded = int((~bounded).sum())
    if len(dist):
        print(
            "  genuine-disagreement distance to ref frontier (cells): "
            f"max={int(dist.max())}, p99={int(np.percentile(dist, 99))}, "
            f"median={int(np.median(dist))}"
            + (f"; {n_unbounded} in columns where ref never stops running" if n_unbounded else "")
        )

    # approximate AW value check via viridis inversion (8-bit floor ~1/255)
    from matplotlib import cm

    lut = (np.asarray(cm.get_cmap("viridis")(np.linspace(0, 1, 256)))[:, :3] * 255).astype(
        np.uint8
    )
    ours_aw = max_aw.T[::-1, :]
    finite = ~ours_norun & ~ref_norun
    lo, hi = np.nanmin(ours_aw[finite]), np.nanmax(ours_aw[finite])
    sample = np.random.default_rng(0).choice(np.flatnonzero(finite), 200_000, replace=False)
    px = rgb.reshape(-1, 3)[sample].astype(np.int32)
    idx = np.argmin(
        ((px[:, None, :] - lut[None, :, :].astype(np.int32)) ** 2).sum(-1), axis=1
    )
    ref_val = lo + idx / 255.0 * (hi - lo)
    our_val = ours_aw.reshape(-1)[sample]
    dv = ref_val - our_val
    print(
        f"  AW via viridis inversion (n=200k sample, clim=[{lo:.4f},{hi:.4f}]): "
        f"mean|Δ|={np.abs(dv).mean():.5f}, p99|Δ|={np.percentile(np.abs(dv),99):.5f} "
        f"(8-bit floor ≈ {(hi-lo)/255/2:.5f})"
    )

    payload = {
        "cells": N * N,
        "disagreements": n_dis,
        "genuine_disagreements": n_genuine,
        "early_termination_fill_disagreements": n_fill,
        "ref_norun_frac": float(ref_norun.mean()),
        "ours_norun_frac": float(ours_norun.mean()),
        "dist_to_frontier_max": int(dist.max()) if len(dist) else 0,
        "dist_to_frontier_median": float(np.median(dist)) if len(dist) else 0,
        "aw_viridis_mean_abs_delta": float(np.abs(dv).mean()),
        "aw_viridis_p99_abs_delta": float(np.percentile(np.abs(dv), 99)),
        "aw_8bit_floor": float((hi - lo) / 255 / 2),
    }
    out = Path(__file__).resolve().parent / "FRONTIER_vs_reference.json"
    out.write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
