"""End-to-end agent-sim engine comparison at the headline bench shape.

Re-anchors the r3 hand-assembled `INCREMENTAL_tpu_v5e_2026-07-30.json`
(gather 21.1 s vs incremental 8.1 s over 200 steps at 10^6 agents / 10^7
ER edges on 1x v5e) on the CURRENT tree, and records what `engine="auto"`
would pick at this shape — the input the `_auto_engine` census tuning
needs: at the bench config (budget 15625, beta=1, dt=0.05) the logistic
mass-change band predicts ~57 fallback steps of 200, just over the
n_steps/4 threshold, so auto picks "gather"; the r3 measurement says the
incremental engine wins 2.6x at this exact shape INCLUDING those
fallbacks. If that ratio reproduces, the census threshold models the
wrong quantity (fallback fraction, not expected cost) and gets retuned.
[Resolved 2026-07-31: it reproduced at 3.6x (ENGINE_COMPARE_tpu_*.json),
the census was retuned to expected cost and then to the saturating
per-step model, and the scale-free runs (SBR_ABL_GRAPH=scale_free, at
10^6 and chunked 10^7) measured the remaining conservative bias —
benchmarks/RESULTS.md "Auto-engine census vs measurement".]

Run: python benchmarks/engine_compare.py [n_agents] [avg_degree] [n_steps]
  SBR_ABL_PLATFORM=cpu pins CPU; SBR_ABL_JSON=path writes the artifact.
  SBR_ABL_GRAPH=scale_free switches to the STRETCH shape (Chung-Lu
  γ=2.5 + lognormal(0, 0.5) per-agent β — `stretch.stretch_agents`),
  answering whether the hub-census auto pick of "gather" there is right
  by measurement rather than by the census model.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("SBR_ABL_PLATFORM", "") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import numpy as np

    from sbr_tpu.social import (
        AgentSimConfig,
        erdos_renyi_edges,
        prepare_agent_graph,
        simulate_agents,
    )

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    deg = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0
    n_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 200
    graph = os.environ.get("SBR_ABL_GRAPH", "er")
    platform = jax.devices()[0].platform
    print(f"platform={platform} n={n} deg={deg} steps={n_steps} graph={graph}")

    if graph == "scale_free":
        from sbr_tpu.social import scale_free_edges

        src, dst = scale_free_edges(n, avg_degree=deg, gamma=2.5, seed=0)
        rng = np.random.default_rng(1)  # same β law as stretch.stretch_agents
        betas = rng.lognormal(mean=0.0, sigma=0.5, size=n).astype(np.float32)
    else:
        src, dst = erdos_renyi_edges(n, deg, seed=0)
        betas = 1.0
    # SBR_ABL_CHUNK bounds single-launch duration (mandatory at the
    # 10^7/10^8 shape — the axon tunnel kills executions over ~1-2 min;
    # chunked results are bit-identical, tests/test_social.py)
    chunk = int(os.environ.get("SBR_ABL_CHUNK", "0")) or None
    cfg = AgentSimConfig(n_steps=n_steps, dt=0.05, max_steps_per_launch=chunk)
    pg_auto = prepare_agent_graph(betas, src, dst, n, config=cfg)
    auto_pick = pg_auto.engine
    print(f"engine='auto' picks: {auto_pick}")

    results = {}
    final = {}
    for engine in ("gather", "incremental"):
        # the auto probe already built one of the two graphs — reuse it
        if engine == auto_pick:
            pg = pg_auto
        else:
            pg = prepare_agent_graph(betas, src, dst, n, config=cfg, engine=engine)
        t0 = time.perf_counter()
        res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
        jax.block_until_ready(res.withdrawn_frac)
        first = time.perf_counter() - t0
        times = []
        for rep in range(2):
            t0 = time.perf_counter()
            res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
            # device->host fetch as the honest fence (axon tunnel)
            final[engine] = (
                np.asarray(res.informed).sum(),
                float(res.withdrawn_frac[-1]),
            )
            times.append(time.perf_counter() - t0)
        best = min(times)
        results[engine] = {
            "steady_s": round(best, 3),
            "first_call_s": round(first, 3),
            "agent_steps_per_sec": round(n * n_steps / best, 1),
        }
        print(
            f"{engine:>12}: {best:.3f}s steady ({n * n_steps / best / 1e6:.1f}M "
            f"agent-steps/s; first call {first:.1f}s)"
        )

    assert final["gather"] == final["incremental"], "engines disagree"
    ratio = results["gather"]["steady_s"] / results["incremental"]["steady_s"]
    print(f"incremental speedup vs gather: {ratio:.2f}x (outputs identical)")

    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        payload = {
            "platform": platform,
            "graph": graph,
            "n_agents": n,
            "avg_degree": deg,
            "n_steps": n_steps,
            "dt": 0.05,
            "auto_pick": auto_pick,
            "results": results,
            "outputs_identical": True,
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
