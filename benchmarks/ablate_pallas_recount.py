"""Pallas experiment: can a VMEM-resident bitmask beat the wd[src] gather wall?

The agent-sim ablation (`ablate_agent_step.py`) measured the per-edge
``wd[src]`` random gather as the full-recount wall: ~78 ms of a ~95 ms step
at 10^7 edges on 1x v5e, i.e. ~1.3e8 elements/s through the XLA gather
unit, with the withdrawn mask living in HBM. The untried lever (VERDICT r3
task 2): the BITPACKED mask is only N/8 bytes — 125 KB at the 10^6-agent
north star, a fraction of the ~16 MB/core VMEM — so a Pallas kernel can
pin it on-chip and stream dst-sorted edge src-id blocks through the VPU,
extracting one bit per edge with no HBM round-trip per element.

This script isolates exactly that unit (bit extraction per edge; the
surrounding prefix-sum + row-pointer machinery of `_seg_counts` is ~4 ms
and not in question) and measures five variants at the production shape:

  xla_bool_gather      wd[src] on an unpacked bool mask (the production wall)
  xla_bit_gather       packed[src>>3] gather + shift/mask (8x smaller table)
  pallas_bit_gather    the VMEM-resident Pallas kernel, one grid step per
                       edge block, mask block-spec'd to stay resident
  pallas_bit_gather_2d the same kernel with edge blocks shaped
                       (EDGE_BLOCK/128, 128) — Mosaic's native lane layout,
                       the fallback if the 1-D form fails to lower
  pallas_bool_gather   the kernel on the unpacked (1 byte/agent) mask —
                       1 MB at 10^6 agents, still VMEM-resident; separates
                       "VMEM residency" from "bit-unpacking arithmetic"

Outputs are asserted IDENTICAL to the XLA reference before any timing
(the recount semantics of `social/agents.py::_seg_counts` — an edge is
active iff bit src_e of the mask is set).

The experiment has an acceptable negative result: if Mosaic's per-element
dynamic gather binds at the same rate as the XLA gather unit, the numbers
land in the JSON artifact, RESULTS.md records why the gather engine is
already at the hardware wall, and the question closes.

Run: python benchmarks/ablate_pallas_recount.py [n_agents] [n_edges]
  SBR_ABL_PLATFORM=cpu pins CPU (interpret-mode kernels, correctness only);
  on TPU the kernels compile for real and the timings are the result.
  SBR_ABL_JSON=path writes the artifact.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EDGE_BLOCK = 1 << 17  # 131072 edges per grid step


def _build_pallas_gather(
    n_mask: int, e_pad: int, interpret: bool, packed: bool, two_d: bool = False
):
    """pallas_call computing active[e] = bit src_e of the mask.

    The mask (packed uint8 bits, or unpacked uint8 bools) is block-spec'd
    with a constant index map, so it is DMA'd to VMEM once and stays
    resident across all E/EDGE_BLOCK grid steps; each step streams one
    src-id block in and one activity block out.

    ``two_d`` reshapes the edge blocks to (EDGE_BLOCK/128, 128) — Mosaic's
    native lane layout — as a fallback in case the 1-D form fails to lower
    (the mask stays 1-D either way; `jnp.take` with 2-D indices from a 1-D
    array yields the 2-D result directly). Callers reshape in/out.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(mask_ref, src_ref, out_ref):
        src = src_ref[:]
        if packed:
            byte = jnp.take(mask_ref[:], src >> 3, axis=0)
            out_ref[:] = (
                (byte >> (src & 7).astype(jnp.uint8)) & jnp.uint8(1)
            ).astype(jnp.int32)
        else:
            out_ref[:] = jnp.take(mask_ref[:], src, axis=0).astype(jnp.int32)

    grid = e_pad // EDGE_BLOCK
    if two_d:
        rows = EDGE_BLOCK // 128
        edge_spec = pl.BlockSpec((rows, 128), lambda i: (i, 0))
        out_shape = jax.ShapeDtypeStruct((e_pad // 128, 128), jnp.int32)
    else:
        edge_spec = pl.BlockSpec((EDGE_BLOCK,), lambda i: (i,))
        out_shape = jax.ShapeDtypeStruct((e_pad,), jnp.int32)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((n_mask,), lambda i: (0,)),  # resident mask
            edge_spec,
        ],
        out_specs=edge_spec,
        out_shape=out_shape,
        interpret=interpret,
    )


def main() -> None:
    if os.environ.get("SBR_ABL_PLATFORM", "") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    e = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000_000
    platform = jax.devices()[0].platform
    interpret = platform == "cpu"
    print(f"platform={platform} n_agents={n} n_edges={e} interpret={interpret}")

    rng = np.random.default_rng(0)
    n8 = -(-n // 8) * 8  # byte-aligned agent count
    e_pad = -(-e // EDGE_BLOCK) * EDGE_BLOCK
    wd = rng.random(n8) < 0.3
    wd[n:] = False
    src = rng.integers(0, n, size=e_pad, dtype=np.int32)
    wd_d = jnp.asarray(wd)
    wd_u8 = jnp.asarray(wd.astype(np.uint8))
    packed_d = jnp.asarray(np.packbits(wd, bitorder="little"))
    src_d = jnp.asarray(src)

    @jax.jit
    def xla_bool_gather(w, s):
        return w[s].astype(jnp.int32)

    @jax.jit
    def xla_bit_gather(p, s):
        return ((p[s >> 3] >> (s & 7).astype(jnp.uint8)) & jnp.uint8(1)).astype(
            jnp.int32
        )

    pallas_bit = jax.jit(_build_pallas_gather(n8 // 8, e_pad, interpret, packed=True))
    pallas_bool = jax.jit(_build_pallas_gather(n8, e_pad, interpret, packed=False))
    pallas_bit_2d = jax.jit(
        _build_pallas_gather(n8 // 8, e_pad, interpret, packed=True, two_d=True)
    )
    src_2d = src_d.reshape(-1, 128)

    ref = np.asarray(xla_bool_gather(wd_d, src_d))
    variants = {
        "xla_bool_gather": lambda: xla_bool_gather(wd_d, src_d),
        "xla_bit_gather": lambda: xla_bit_gather(packed_d, src_d),
        # NB: the 2d variant is timed WITHOUT the host-facing reshape (a
        # relayout copy on TPU that no other variant pays); the
        # correctness check reshapes once below
        "pallas_bit_gather": lambda: pallas_bit(packed_d, src_d),
        "pallas_bit_gather_2d": lambda: pallas_bit_2d(packed_d, src_2d),
        "pallas_bool_gather": lambda: pallas_bool(wd_u8, src_d),
    }
    results = {}
    for name, fn in variants.items():
        try:
            out = np.asarray(jax.block_until_ready(fn()))  # compile + check
        except Exception as err:  # Mosaic lowering gaps are a valid outcome
            print(f"{name:>20}: FAILED to compile/run: {err!r}"[:300])
            results[name] = {"error": str(err)[:200]}
            continue
        np.testing.assert_array_equal(out.reshape(-1), ref, err_msg=name)
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        best = min(times)
        results[name] = {"best_s": round(best, 5), "elem_per_sec": round(e_pad / best, 1)}
        print(f"{name:>20}: {best * 1e3:8.2f} ms  ({e_pad / best / 1e6:8.1f}M elem/s)")

    ok = [k for k, v in results.items() if "best_s" in v]
    # headline: the best pallas variant that actually lowered vs the wall
    pallas_ok = [k for k in ok if k.startswith("pallas_bit")]
    if pallas_ok and "xla_bool_gather" in ok:
        best = min(pallas_ok, key=lambda k: results[k]["best_s"])
        sp = results["xla_bool_gather"]["best_s"] / results[best]["best_s"]
        print(f"{best} speedup vs production gather: {sp:.2f}x")
    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        payload = {
            "platform": platform,
            "interpret": interpret,
            "n_agents": n,
            "n_edges": e_pad,
            "results": results,
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
