"""Order-of-magnitude scale demonstration: 10^7 agents / 10^8 edges on one
chip (VERDICT r4 task 7).

The pieces have all been measured separately — the native O(E+N) counting
sort did 10^8 edges in 15.3 s, `_seg_counts` is exact to 2^31 edges, and
`prepare_agent_graph` amortizes the ~GB-scale upload — but never as ONE
workload. Two phases:

A. **Headline**: 10^7 heterogeneous-β agents on a Chung–Lu scale-free
   graph with 10^8 edges (avg degree 10, γ=2.5), 200 steps — the stretch
   config an order of magnitude up. Reports agent-steps/sec with the
   prep/steady split (the prep side IS part of the demonstration: one
   graph build + upload serves every subsequent simulation).
B. **Physics check at scale**: the same 10^7/10^8 shape as an Erdős–Rényi
   graph with uniform β and immediate exit, vs the logistic mean-field
   limit (SURVEY §4(e)). At avg degree 10 the per-agent neighbor fraction
   is quantized to tenths, so the mid-transition band deviates from the
   representative-agent ODE by design; the SATURATION level and the
   self-averaged S-shape are the scale-invariant checks (bands measured at
   n = 2x10^5, same degree, where they are n-independent: the curve is an
   average over 10^7 agents — sampling noise is ~10^-4).

Prints ONE JSON line; reuses bench.py's killable parent/child harness
(the tunnel can hang at any point). `SBR_BENCH_SIZES=tiny` shrinks to
smoke scale for the harness contract test.

Usage: python benchmarks/scale_demo.py  (from the repo root)
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _log(msg: str) -> None:
    print(f"[scale] {msg}", file=sys.stderr, flush=True)


def headline(n: int = 10_000_000, n_steps: int = 200) -> dict:
    """The stretch-config workload an order of magnitude up — same timing
    protocol and result contract, so reuse it rather than fork it.

    Launches are capped at 20 steps (~26 s at the measured ~1.3 s/step
    recount): a single 200-step execution runs >2 min on-device, which the
    axon tunnel kills ("TPU worker process crashed" — reproduced at 100
    steps, fine at 30). The chunked run is bit-identical to the single
    launch (tests/test_social.py::TestLaunchChunking), so the metric is
    unchanged; the chunk boundaries add host round-trips that the steady
    number honestly includes."""
    import stretch  # sibling module; benchmarks/ is on sys.path as script dir

    # engine pinned by measurement, not census: at exactly this shape the
    # incremental engine runs 1.14x faster than gather (202.0 vs 230.5 s,
    # ENGINE_COMPARE_sf1e7_tpu_2026-07-31.json, outputs identical); the
    # auto census stays conservative here (its expected-change model puts
    # hub fallbacks at ~99% of steps where the measured rate is ~66% —
    # Chung-Lu hubs front-load their single change), so the demo pins what
    # the measurement established.
    return stretch.stretch_agents(
        n=n, n_steps=n_steps, avg_degree=10.0, max_steps_per_launch=20,
        engine="incremental",
    )


def physics_check(n: int = 10_000_000, avg_degree: float = 10.0) -> dict:
    """Logistic-limit check at the demo shape (immediate exit ⇒ AW = G ⇒
    dG/dt = β·G(1-G)). Tolerances measured at n = 2x10^5, same degree,
    where the degree-10 quantization bias is already converged in n."""
    import numpy as np

    import bench
    from sbr_tpu.baseline.learning import logistic_cdf
    from sbr_tpu.social import AgentSimConfig, erdos_renyi_edges, simulate_agents

    if bench._tiny():
        n = 20_000

    beta, x0 = 1.0, 1e-3
    src, dst = erdos_renyi_edges(n, avg_degree, seed=3)
    # same launch cap as the headline (see `headline` docstring)
    cfg = AgentSimConfig(n_steps=300, dt=0.05, max_steps_per_launch=20)
    t0 = time.perf_counter()
    res = simulate_agents(beta, src, dst, n, x0=x0, config=cfg, seed=0)
    got = np.asarray(res.informed_frac, dtype=np.float64)
    run_s = time.perf_counter() - t0
    t = np.asarray(res.t_grid)
    x0_eff = float(got[0])  # realized Bernoulli seed fraction
    want = np.asarray(logistic_cdf(t, beta, x0_eff))
    active = want > 0.01
    rel_band = float(np.max(np.abs(got[active] - want[active]) / want[active]))
    sat_err = float(abs(got[-1] - want[-1]))
    monotone = bool((np.diff(got) >= -1e-9).all())
    _log(
        f"physics: ER degree {avg_degree} at n={n:,}: saturation |Δ|={sat_err:.4f}, "
        f"active-band rel max={rel_band:.3f}, monotone={monotone} ({run_s:.1f}s)"
    )
    return {
        "n_agents": n,
        "n_edges": len(src),
        "saturation_abs_err": round(sat_err, 5),
        "active_band_rel_max": round(rel_band, 4),
        "monotone": monotone,
        # bands: saturation matches the ODE tightly (every agent with an
        # informed neighbor eventually crosses); the transition band lags
        # the ODE by O(1/degree) quantization, measured 0.43-0.60 falling
        # in n (0.43 at 2e5; the tiny smoke shape sits at 0.60) — 0.7 is
        # the loose-side bound for any n at degree 10
        "pass": bool(sat_err < 0.02 and rel_band < 0.7 and monotone),
        "run_s": round(run_s, 1),
    }


def measure(platform: str) -> None:
    import bench

    devices = bench._init_child_backend(platform)
    platform = devices[0].platform
    head = headline()
    phys = physics_check()
    print(
        json.dumps(
            {
                "metric": "scale_demo_agent_steps_per_sec",
                "value": round(head["agent_steps_per_sec"], 1),
                "unit": "agent-steps/sec",
                "extra": {"platform": platform, "headline": head, "physics": phys},
            }
        )
    )


def main() -> None:
    import bench

    bench.run_harness(
        script=str(Path(__file__).resolve()),
        fallback={
            "metric": "scale_demo_agent_steps_per_sec",
            "value": 0.0,
            "unit": "agent-steps/sec",
        },
    )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--measure":
        measure(sys.argv[2])
    else:
        main()
