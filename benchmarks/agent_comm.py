"""Measure the sharded agent-sim collective strategies against each other.

Compares, per step of the sharded kernel (`social/agents.py::_sharded_sim`):

- "scatter": bitpacked all_gather (N/8 bytes) + psum_scatter (4N/n_dev B)
- "allgather_psum": bool all_gather (N bytes) + full-N int32 psum (4N B)

Bytes over the mesh per device per step (N agents, D devices):

    scatter:         N/8 · (D-1)/D  +  4N/D          ≈ 0.625·N at D=8
    allgather_psum:  N   · (D-1)/D  +  2·4N·(D-1)/D  ≈ 7.9·N   at D=8

i.e. ~12.6× fewer collective bytes. This script measures wall-clock on
whatever mesh is available (the 8-virtual-device CPU mesh in CI — memcpy
"collectives", so the gap here UNDERSTATES the ICI gap on real multi-chip
hardware, where bandwidth is the constraint).

Run:  python benchmarks/agent_comm.py [n_agents] [avg_degree] [n_steps]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    if os.environ.get("SBR_COMM_BENCH_PLATFORM", "cpu") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()

    from sbr_tpu.social import AgentSimConfig, erdos_renyi_edges, simulate_agents

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    deg = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0
    n_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 50

    devs = jax.devices()
    mesh = jax.make_mesh((len(devs),), ("agents",))
    print(f"platform={devs[0].platform} n_dev={len(devs)} n={n} deg={deg} steps={n_steps}")

    t0 = time.perf_counter()
    src, dst = erdos_renyi_edges(n, deg, seed=0)
    print(f"graph: {len(src)} edges in {time.perf_counter() - t0:.1f}s")
    cfg = AgentSimConfig(n_steps=n_steps, dt=0.05)

    # variants: the two gather-engine collective strategies, plus the
    # event-driven incremental engine (edge-count-sharded out-edge chunks)
    variants = {
        "scatter": dict(comm="scatter", engine="gather"),
        "allgather_psum": dict(comm="allgather_psum", engine="gather"),
        "incremental": dict(engine="incremental"),
    }
    results = {}
    for name, kw in variants.items():
        # warm (compile)
        r = simulate_agents(1.0, src, dst, n, x0=1e-3, config=cfg, seed=0, mesh=mesh, **kw)
        float(r.informed_frac[-1])
        times = []
        for rep in range(3):
            t0 = time.perf_counter()
            r = simulate_agents(
                1.0, src, dst, n, x0=1e-3, config=cfg, seed=rep + 1, mesh=mesh, **kw
            )
            float(r.informed_frac[-1])  # device→host fence
            times.append(time.perf_counter() - t0)
        best = min(times)
        results[name] = best
        print(f"{name:>16}: {best:.3f}s ({n * n_steps / best / 1e6:.1f}M agent-steps/s)")

    speedup = results["allgather_psum"] / results["scatter"]
    print(f"scatter speedup vs allgather_psum: {speedup:.2f}x")
    print(
        f"incremental speedup vs gather/scatter: "
        f"{results['scatter'] / results['incremental']:.2f}x"
    )
    out = os.environ.get("SBR_COMM_BENCH_JSON", "")
    if out:
        import json

        payload = {
            "platform": devs[0].platform,
            "n_devices": len(devs),
            "n_agents": n,
            "avg_degree": deg,
            "n_steps": n_steps,
            "best_wall_s": {k: round(v, 4) for k, v in results.items()},
            "agent_steps_per_sec": {
                k: round(n * n_steps / v, 1) for k, v in results.items()
            },
        }
        with open(out, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"wrote {out}")


if __name__ == "__main__":
    main()
