"""A/B the incremental engines' per-step change compaction on hardware.

`_compact_ids` (ascending True indices, dump-padded) runs every step of
the incremental engines and is their largest clean-step cost: the
cumsum+scatter lowering measured 8.2 ms standalone at N=10⁶ on v5e —
~36% of the 22.7 ms headline step. The scatter writes all N ids (the ~N
invalid ones collide on the dump slot and are sliced away), which is the
suspected wall: TPU scatter serializes on colliding indices. The
"searchsorted" lowering removes the scatter entirely — rank j's id is
the first index where the monotone cumsum reaches j+1, i.e. `budget`
vectorized binary searches (log₂N ≈ 20 gather rounds of `budget`
elements ≈ 3×10⁵ gathers at the measured ~1.3×10⁸ elem/s ≫ the N-write
scatter). Both lowerings are bit-identical (tests/test_social.py).

This script times (a) the parts standalone — both lowerings, the shared
cumsum, and the per-agent RNG for context — and (b) the incremental
engine end-to-end at the headline bench shape under each
`AgentSimConfig.compact_impl`, asserting identical final states. The
winner becomes the config default (benchmarks/RESULTS.md records the
verdict).

Run: python benchmarks/ablate_compaction.py [n_agents] [avg_degree] [n_steps]
  SBR_ABL_PLATFORM=cpu pins CPU; SBR_ABL_JSON=path writes the artifact.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("SBR_ABL_PLATFORM", "") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu.social import (
        AgentSimConfig,
        erdos_renyi_edges,
        prepare_agent_graph,
        simulate_agents,
    )
    from sbr_tpu.social.agents import (
        _agent_uniforms,
        _compact_ids,
        _default_incremental_budget,
    )

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    deg = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0
    n_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 200
    budget = _default_incremental_budget(n)  # the engine's actual default
    platform = jax.devices()[0].platform
    print(f"platform={platform} n={n} budget={budget}")

    # -- parts, standalone, at a realistic clean-step change density -------
    rng = np.random.default_rng(0)
    mask_np = np.zeros(n, bool)
    mask_np[rng.choice(n, size=max(1, n // 330), replace=False)] = True
    mask = jnp.asarray(mask_np)

    def timed(fn, *args, reps=50):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    parts = {}
    for name, fn in [
        ("scatter", jax.jit(lambda m: _compact_ids(m, budget, n, "scatter"))),
        ("searchsorted", jax.jit(lambda m: _compact_ids(m, budget, n, "searchsorted"))),
        (
            "searchsorted_blocked",
            jax.jit(lambda m: _compact_ids(m, budget, n, "searchsorted_blocked")),
        ),
        ("cumsum_only", jax.jit(lambda m: jnp.cumsum(m.astype(jnp.int32)))),
    ]:
        parts[name] = round(timed(fn, mask) * 1e3, 3)
        print(f"  part {name:>14}: {parts[name]:8.3f} ms")
    ids = jnp.arange(n, dtype=jnp.uint32)
    key = jax.random.PRNGKey(0)
    for rng_impl in ("foldin", "counter"):
        name = f"uniforms_{rng_impl}"
        parts[name] = round(
            timed(
                jax.jit(
                    lambda k, imp=rng_impl: _agent_uniforms(
                        k, jnp.int32(3), ids, jnp.float32, imp
                    )
                ),
                key,
                reps=20,
            ) * 1e3, 3,
        )
        print(f"  part {name:>20}: {parts[name]:8.3f} ms (context)")

    # -- end to end at the bench shape: impl x budget ----------------------
    # The budget axis matters because the lowerings scale differently with
    # it: "scatter" is O(N) regardless of budget, so raising the budget
    # (fewer ~95 ms fallback recounts near the logistic peak, where the
    # per-step change mass N·β·dt/4 ≈ 12.5k brushes the default 15625) is
    # free for it; the searchsorted lowerings pay budget·log₂N extra
    # gathers. The optimum is a JOINT (impl, budget) choice.
    src, dst = erdos_renyi_edges(n, deg, seed=0)
    results = {}
    final = {}
    for impl in ("scatter", "searchsorted", "searchsorted_blocked"):
        for bmult in (1, 4):
            name = f"{impl}_b{bmult}x"
            cfg = AgentSimConfig(n_steps=n_steps, dt=0.05, compact_impl=impl)
            pg = prepare_agent_graph(
                1.0, src, dst, n, config=cfg, engine="incremental",
                incremental_budget=min(budget * bmult, 65536),
            )
            t0 = time.perf_counter()
            res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
            jax.block_until_ready(res.withdrawn_frac)
            first = time.perf_counter() - t0
            times = []
            for _ in range(2):
                t0 = time.perf_counter()
                res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=7)
                # device-side sync only inside the timed region; the
                # final-state capture (an N-bool device->host copy) happens
                # after the loop
                jax.block_until_ready(res.withdrawn_frac)
                times.append(time.perf_counter() - t0)
            final[name] = (
                int(np.asarray(res.informed).sum()),
                float(res.withdrawn_frac[-1]),
            )
            n_rec = int(np.asarray(res.full_recount_steps).sum())
            best = min(times)
            results[name] = {
                "first_call_s": round(first, 2),
                "steady_s": round(best, 3),
                "agent_steps_per_sec": round(n * n_steps / best, 1),
                "recount_steps": n_rec,
            }
            print(
                f"  e2e {name:>26}: {best:.3f}s steady "
                f"({n * n_steps / best / 1e6:.1f}M agent-steps/s; "
                f"{n_rec}/{n_steps} recounts; first {first:.1f}s)"
            )

    assert len(set(final.values())) == 1, final
    # the incumbent is whatever the shipped config defaults to (b1x budget),
    # so the verdict always protects the CURRENT default, not a hard-coded one
    incumbent = f"{AgentSimConfig().compact_impl}_b1x"
    best_name = min(results, key=lambda k: results[k]["steady_s"])
    ratio = results[incumbent]["steady_s"] / results[best_name]["steady_s"]
    # >2% over the incumbent config to displace it; otherwise it stays
    verdict = best_name if ratio > 1.02 else incumbent
    print(
        f"  best: {best_name} (incumbent {incumbent}/best steady ratio "
        f"{ratio:.2f}) -> {verdict}"
    )

    # One extra e2e config for the RNG axis: the main grid runs the default
    # "counter" stream; this one measures the pre-0.7 "foldin" stream for
    # contrast. The streams are different (equally valid) realizations, so
    # it is excluded from the bit-identity assert above and compared only
    # loosely on final G.
    cfg_r = AgentSimConfig(n_steps=n_steps, dt=0.05, rng_stream="foldin")
    pg_r = prepare_agent_graph(1.0, src, dst, n, config=cfg_r, engine="incremental")
    res = simulate_agents(prepared=pg_r, x0=1e-4, config=cfg_r, seed=7)
    jax.block_until_ready(res.withdrawn_frac)
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        res = simulate_agents(prepared=pg_r, x0=1e-4, config=cfg_r, seed=7)
        jax.block_until_ready(res.withdrawn_frac)
        times.append(time.perf_counter() - t0)
    best_r = min(times)
    g_r, g_s = float(res.informed_frac[-1]), final["scatter_b1x"][0] / n
    assert abs(g_r - g_s) < 0.1, (g_r, g_s)  # same dynamics, different draws
    results["scatter_b1x_rngfoldin"] = {
        "steady_s": round(best_r, 3),
        "agent_steps_per_sec": round(n * n_steps / best_r, 1),
        "recount_steps": int(np.asarray(res.full_recount_steps).sum()),
    }
    print(
        f"  e2e {'scatter_b1x_rngfoldin':>26}: {best_r:.3f}s steady "
        f"({n * n_steps / best_r / 1e6:.1f}M agent-steps/s; pre-0.7 stream)"
    )

    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        payload = {
            "platform": platform,
            "n_agents": n,
            "budget": budget,
            "n_steps": n_steps,
            "parts_ms": parts,
            "end_to_end": results,
            "ratio_incumbent_over_best": round(ratio, 3),
            "verdict": verdict,
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
