"""Quantify the closure's O(dt) bias by dt-halving (VERDICT r3 weak #6).

`social/closure.py` documents two O(dt) biases (informed times rounded up
to step ends; forcing frozen per step) and the tests assert convergence in
N — but convergence in dt was never measured. This script runs the
equilibrium→agent closure at a fixed population and halving step sizes,
averaging several seeds per dt so Monte-Carlo noise (~1/√(N·reps)) sits
well under the dt trend, and fits err(dt) ≈ a + b·dt.

If the closure errors are dominated by the documented O(dt) rounding, the
fitted slope b is positive and the dt→0 intercept a lands near the O(x0)
offset floor (~1e-4, also documented). A flat curve would instead mean the
tolerances are eating something else — worth knowing either way.

Run: python benchmarks/dt_convergence.py [n_agents] [n_reps]
  SBR_ABL_PLATFORM=cpu pins CPU; SBR_ABL_JSON=path writes the artifact.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("SBR_ABL_PLATFORM", "") == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import numpy as np

    from sbr_tpu.social.closure import close_loop

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    n_reps = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    platform = jax.devices()[0].platform
    dts = [0.2, 0.1, 0.05, 0.025]
    print(f"platform={platform} N={n} reps/dt={n_reps} dts={dts}")

    rows = []
    fp = None
    for dt in dts:
        errs_rms, errs_sup = [], []
        for rep in range(n_reps):
            c = close_loop(
                n_agents=n, dt=dt, n_reps=1, seed=100 + rep, fp=fp
            )
            fp = c.fp  # solve the fixed point once; reuse across dt/seed
            # use the closure's OWN error metrics so this calibration can
            # never drift from what the test suite asserts
            errs_rms.append(float(c.err_aw_rms))
            errs_sup.append(float(c.err_aw_sup))
        row = {
            "dt": dt,
            "rms_mean": float(np.mean(errs_rms)),
            "rms_std": float(np.std(errs_rms)),
            "sup_mean": float(np.mean(errs_sup)),
        }
        rows.append(row)
        print(
            f"  dt={dt:6.3f}: AW rms = {row['rms_mean']:.5f} ± {row['rms_std']:.5f}, "
            f"sup = {row['sup_mean']:.5f}"
        )

    x = np.array([r["dt"] for r in rows])
    y = np.array([r["rms_mean"] for r in rows])
    b, a = np.polyfit(x, y, 1)
    print(f"fit: err(dt) ≈ {a:.5f} + {b:.5f}·dt  (intercept = dt→0 floor)")

    out_path = os.environ.get("SBR_ABL_JSON", "")
    if out_path:
        payload = {
            "platform": platform,
            "n_agents": n,
            "n_reps": n_reps,
            "rows": rows,
            "fit_intercept": float(a),
            "fit_slope": float(b),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
