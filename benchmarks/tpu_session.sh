#!/bin/bash
# One-shot TPU capture session: run the moment a probe shows the tunnel up
# (the chip has historically stayed up ~90 min at a time — grab everything).
# Every device touch goes through killable children (bench harness) or a
# bounded `timeout`, so a mid-session tunnel drop cannot hang the shell.
#
# Ordered by THIS round's open questions first (a short window should
# still answer them): headline bench, then the round-5 A/Bs (compaction
# lowering x budget; max_degree hub tradeoff), then stretch (the workload
# those axes target), then the re-confirmation passes (grid roofline,
# pallas lowering), then the long scale demo.
#
# Usage: bash benchmarks/tpu_session.sh
set -u -o pipefail
cd "$(dirname "$0")/.."
STAMP=$(date +%Y-%m-%dT%H%M%S)
echo "=== TPU session $STAMP ==="

run_bench () {  # $1 = script, $2 = artifact path, $3 = per-phase budget (s)
  local tmp
  tmp=$(mktemp)
  # SBR_BENCH_BUDGET_S caps the harness's own probe+measure+retry envelope
  # BELOW the outer timeout, so the JSON line always lands before the kill
  if SBR_BENCH_PLATFORM=tpu SBR_BENCH_MEASURE_TIMEOUT_S="$3" \
     SBR_BENCH_BUDGET_S="$3" timeout $(( $3 + 300 )) python "$1" \
     2>"benchmarks/tpu_session_${STAMP}_$(basename "$1" .py).log" \
     | tail -1 > "$tmp" && [ -s "$tmp" ]; then
    mv "$tmp" "$2"
    echo "captured: $2"; cat "$2"
  else
    rm -f "$tmp"
    echo "FAILED: $1 (no artifact written)"
  fi
}

echo "--- [1/8] headline bench (probe skipped: caller confirmed the tunnel)"
run_bench bench.py "benchmarks/BENCH_tpu_session_${STAMP}.json" 1800

echo "--- [2/8] compaction lowering A/B (round-5: scatter vs searchsorted, x budget)"
SBR_ABL_JSON=benchmarks/ABLATE_COMPACT_tpu_${STAMP}.json \
  timeout 1200 python benchmarks/ablate_compaction.py 2>&1 | tail -14 \
  || echo "FAILED: compaction ablation"

echo "--- [3/8] max_degree axis at the stretch shape (round-5: hub recounts vs grid width)"
SBR_ABL_JSON=benchmarks/ABLATE_MAXDEG_tpu_${STAMP}.json SBR_ABL_CHUNK=40 \
  timeout 1800 python benchmarks/ablate_max_degree.py 2>&1 | tail -8 \
  || echo "FAILED: max_degree ablation"

echo "--- [4/8] stretch config"
run_bench benchmarks/stretch.py "benchmarks/STRETCH_tpu_session_${STAMP}.json" 1800

echo "--- [5/8] grid-cell roofline at bench shape (VERDICT r3 task 5)"
SBR_ABL_JSON=benchmarks/ABLATE_GRID_tpu_${STAMP}.json \
  timeout 2400 python benchmarks/ablate_grid_cell.py 640 640 2>&1 | tail -12 \
  || echo "FAILED: grid ablation"

echo "--- [6/8] pallas VMEM-resident recount experiment (VERDICT r3 task 2)"
SBR_ABL_JSON=benchmarks/PALLAS_RECOUNT_tpu_${STAMP}.json \
  timeout 1200 python benchmarks/ablate_pallas_recount.py 1000000 10000000 \
  2>&1 | tail -8 || echo "FAILED: pallas ablation"

echo "--- [7/8] sharded engine ablation (needs >1 device; expected to skip on 1 chip)"
if SBR_COMM_BENCH_JSON=benchmarks/SHARDED_ENGINES_tpu_${STAMP}.json \
   timeout 1200 python benchmarks/agent_comm.py 1000000 10 50 \
   > "benchmarks/tpu_session_${STAMP}_comm.log" 2>&1; then
  tail -7 "benchmarks/tpu_session_${STAMP}_comm.log"
else
  echo "(agent_comm failed or needs >1 device; see tpu_session_${STAMP}_comm.log)"
fi

echo "--- [8/8] 10^7-agent / 10^8-edge scale demonstration (VERDICT r4 task 7)"
run_bench benchmarks/scale_demo.py "benchmarks/SCALE_DEMO_tpu_session_${STAMP}.json" 2400

echo "=== session done; check for FAILED lines above; artifacts: benchmarks/*_${STAMP}* ==="
