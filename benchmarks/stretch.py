"""Stretch-config workload (BASELINE.md stretch row, VERDICT r2 task 4).

Assembles the ingredients that existed separately into the advertised
configuration:

A. **Heterogeneous 10^6 agents on a scale-free network**: per-agent
   lognormal learning rates β_i (the agent-level generalization of the
   hetero extension's K groups) on a Chung–Lu power-law graph
   (`social.agents.scale_free_edges`, γ=2.5), 200 steps — reported as
   agent-steps/sec.
B. **10^3-point (β, u, r) policy sweep**: the 10×10×10 grid of
   interest-rate equilibria as one jitted vmap³ program
   (`sweeps.policy_sweep_interest`) — reported as equilibria/sec.

Prints ONE JSON line with both metrics; diagnostics on stderr. Reuses
bench.py's hardened parent/child harness (probe in a killable subprocess,
measurement in a killable `--measure` child, CPU re-run on failure — this
rig's TPU tunnel can hang at any point, see bench.py's docstring), so pin
with `SBR_BENCH_PLATFORM=cpu` to skip the probe. Captured artifacts live
next to this script (`STRETCH_*.json`); see RESULTS.md.

Usage: python benchmarks/stretch.py  (from the repo root)
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

# `python benchmarks/stretch.py` puts benchmarks/ (not the repo root) on
# sys.path; make the sbr_tpu package importable regardless of cwd.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _log(msg: str) -> None:
    print(f"[stretch] {msg}", file=sys.stderr, flush=True)


def stretch_agents(
    n: int = 1_000_000,
    n_steps: int = 200,
    avg_degree: float = 10.0,
    max_steps_per_launch: int | None = None,
    engine: str = "auto",
) -> dict:
    import numpy as np

    from sbr_tpu.social import (
        AgentSimConfig,
        prepare_agent_graph,
        scale_free_edges,
        simulate_agents,
    )

    import bench

    if bench._tiny():  # SBR_BENCH_SIZES=tiny: harness smoke-test scale
        n, n_steps = 2_000, 20

    rng = np.random.default_rng(0)
    # lognormal β_i: median 1, σ=0.5 → heavy right tail of fast learners,
    # the continuous analogue of the reference's two-group βs=[0.125, 12.5]
    betas = rng.lognormal(mean=0.0, sigma=0.5, size=n).astype(np.float32)
    t0 = time.perf_counter()
    src, dst = scale_free_edges(n, avg_degree=avg_degree, gamma=2.5, seed=0)
    gen_s = time.perf_counter() - t0
    _log(f"scale-free graph: {len(src)} edges in {gen_s:.1f}s")
    cfg = AgentSimConfig(
        n_steps=n_steps, dt=0.05, max_steps_per_launch=max_steps_per_launch
    )
    t0 = time.perf_counter()
    pg = prepare_agent_graph(betas, src, dst, n, config=cfg, engine=engine)
    prep_s = time.perf_counter() - t0
    _log(f"graph prepared (engine={pg.engine}) in {prep_s:.1f}s")

    def run(seed: int) -> float:
        res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=seed)
        return float(res.informed_frac[-1])  # device→host fence

    t0 = time.perf_counter()
    g_final = run(0)
    first_s = time.perf_counter() - t0
    times = []
    for seed in (1, 2):
        t0 = time.perf_counter()
        run(seed)
        times.append(time.perf_counter() - t0)
    steady = min(times)
    _log(
        f"agents: {n} hetero-β agents × {n_steps} steps on scale-free graph in "
        f"{steady:.2f}s steady (first {first_s:.1f}s); final G = {g_final:.4f}"
    )
    return {
        "agent_steps_per_sec": n * n_steps / steady,
        "n_agents": n,
        "n_edges": len(src),
        "n_steps": n_steps,
        "graph": f"scale_free(avg_degree={avg_degree}, gamma=2.5)",
        "betas": "lognormal(0, 0.5)",
        "engine": pg.engine,
        "graph_gen_s": round(gen_s, 1),
        "first_call_s": round(first_s, 2),
        "steady_s": round(steady, 3),
        # NB: since the prepare_agent_graph migration, graph prep is OUT of
        # first_call_s/steady_s and recorded here — captures from before
        # that change folded it into every run() timing
        "prep_s": round(prep_s, 2),
        # engine="measure": prep_s includes the candidate A/B simulations;
        # the per-candidate rates it measured land here
        "measured_steps_per_sec": (
            list(map(list, pg.measured_steps_per_sec))
            if pg.measured_steps_per_sec
            else None
        ),
        "max_degree": pg.max_degree,
        "final_informed_frac": round(g_final, 4),
    }


def stretch_policy(n_beta: int = 10, n_u: int = 10, n_r: int = 10) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu.models.params import make_interest_params
    from sbr_tpu.sweeps import policy_sweep_interest

    import bench

    if bench._tiny():
        n_beta, n_u, n_r = 4, 4, 3

    base = make_interest_params(u=0.0, delta=0.1)
    betas = np.linspace(0.5, 3.0, n_beta)
    rs = np.linspace(0.0, 0.09, n_r)

    def dispatch(rep: int):
        us = np.linspace(0.0, 0.45, n_u) + rep * 1e-6
        sweep = policy_sweep_interest(betas, us, rs, base, dtype=jnp.float32)
        return sweep, jnp.sum(sweep.status) + jnp.nansum(sweep.aw_max)

    def run(rep: int):
        sweep, fence = dispatch(rep)
        return sweep, float(fence)

    t0 = time.perf_counter()
    sweep, _ = run(0)
    first_s = time.perf_counter() - t0
    times = []
    for rep in (1, 2):
        t0 = time.perf_counter()
        run(rep)
        times.append(time.perf_counter() - t0)
    dispatch_s = min(times)

    # Sustained rate: same RPC-floor amortization as the grid bench (a
    # fenced 1000-cell dispatch is ~all tunnel round-trip; policy sweeps
    # arrive in batches in practice, e.g. the r-resolution refinement
    # ladder) — shared protocol in bench.pipelined_time.
    pipelined_s, n_pipe = bench.pipelined_time(dispatch, start_rep=3)
    steady = min(dispatch_s, pipelined_s)

    cells = n_beta * n_u * n_r
    n_run = int(np.sum(np.asarray(sweep.status) == 0))
    _log(
        f"policy: {cells} (β,u,r) cells in {steady:.3f}s steady "
        f"({pipelined_s:.3f}s/dispatch pipelined ×{n_pipe}, {dispatch_s:.3f}s "
        f"single fenced; first {first_s:.1f}s); {n_run} run cells"
    )
    return {
        "policy_eq_per_sec": cells / steady,
        "cells": cells,
        "n_run": n_run,
        "first_call_s": round(first_s, 2),
        "steady_s": round(steady, 3),
        "dispatch_s": round(dispatch_s, 3),
        "pipelined_s": round(pipelined_s, 3),
        "n_pipe": n_pipe,
    }


def measure(platform: str) -> None:
    """Child side: all device work lives here (killable by the parent)."""
    import bench

    devices = bench._init_child_backend(platform)
    platform = devices[0].platform
    # engine="measure": the on-hardware ground-truth A/B (its default probe
    # trajectory x0=1e-4/seed=0 IS this benchmark's trajectory). It
    # reproduces the standalone comparison's verdict (incremental 1.42x
    # over gather at this shape, ENGINE_COMPARE_sf_tpu_2026-07-31.json)
    # and since round 5 also tries the widened hub cap (max_degree=512 cut
    # recounts 151 -> 78 of 200 here and won 1.15x even on CPU —
    # ABLATE_MAXDEG_cpu_2026-08-01.json); the stretch number is then the
    # measured-best configuration on whatever platform runs it, with the
    # candidate rates recorded in the artifact.
    agents = stretch_agents(engine="measure")
    policy = stretch_policy()
    print(
        json.dumps(
            {
                "metric": "stretch_hetero_agents_steps_per_sec",
                "value": round(agents["agent_steps_per_sec"], 1),
                "unit": "agent-steps/sec",
                "extra": {"platform": platform, "agents": agents, "policy": policy},
            }
        )
    )


def main() -> None:
    """Parent side: bench.py's shared probe/measure harness, this file as
    the `--measure` child."""
    import bench

    bench.run_harness(
        script=str(Path(__file__).resolve()),
        fallback={
            "metric": "stretch_hetero_agents_steps_per_sec",
            "value": 0.0,
            "unit": "agent-steps/sec",
        },
    )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--measure":
        measure(sys.argv[2])
    else:
        main()
