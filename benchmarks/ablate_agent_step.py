"""Per-op ablation of the agent-sim step (the evidence behind RESULTS.md's
"Agent-sim engines" section and the event-driven engine's design).

Three step variants isolate where the time goes at the north-star shape
(10^6 agents, 10^7 ER edges):

- full:     the real gather-engine step (neighbor gather + counts + RNG)
- norng:    gather + counts, RNG replaced by a frac-dependent constant
- nogather: RNG + elementwise physics, neighbor counts replaced by a
            wd-dependent constant

plus microbenchmarks of the primitive ops (random gather, cumsum,
row-pointer gathers, scatter-add, compaction). Measured 2026-07-30 on
1x v5e: full 94.6 ms/step ≈ norng (RNG is free), nogather 1.5 ms/step —
the wd[src] random gather is the wall (~78 ms, ~1.3e8 elements/s).

Usage: python benchmarks/ablate_agent_step.py  (SBR_BENCH_PLATFORM=cpu to pin)
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    if os.environ.get("SBR_BENCH_PLATFORM", "").strip().lower() == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from sbr_tpu.social import erdos_renyi_edges
    from sbr_tpu.social.agents import _agent_uniforms, _prep_inputs, _seg_counts

    n, nsteps = 1_000_000, 50
    src, dst = erdos_renyi_edges(n, 10.0, seed=0)
    betas, src_s, _, indeg, row_ptr, informed0 = _prep_inputs(
        n, 1.0, 1e-4, src, dst, 0, np.float32
    )
    key = jax.random.PRNGKey(0)
    dt = 0.05
    print(f"platform: {jax.devices()[0].platform}; {len(src_s)} edges", file=sys.stderr)

    def make(variant):
        @jax.jit
        def run(betas, src, row_ptr, indeg, informed0, key):
            t_inf0 = jnp.where(informed0, 0.0, jnp.inf).astype(jnp.float32)
            safe = jnp.maximum(indeg, 1.0)
            ids = jnp.arange(n, dtype=jnp.uint32)

            def step(carry, k):
                informed, t_inf = carry
                t = k.astype(jnp.float32) * dt
                wd = informed & (t >= t_inf)
                if variant in ("full", "norng"):
                    frac = _seg_counts(wd[src], row_ptr).astype(jnp.float32) / safe
                else:
                    frac = jnp.full((n,), 0.3, jnp.float32) * wd.mean()
                p_inf = 1.0 - jnp.exp(-betas * frac * dt)
                if variant in ("full", "nogather"):
                    draws = _agent_uniforms(key, k, ids, jnp.float32)
                else:  # keep a data dependency without the RNG
                    draws = jnp.full((n,), 0.5, jnp.float32) * frac
                newly = (~informed) & (draws < p_inf)
                return (informed | newly, jnp.where(newly, t + dt, t_inf)), wd.mean()

            (_, _), aw = lax.scan(step, (informed0, t_inf0), jnp.arange(nsteps))
            return aw

        return run

    args = (
        jnp.asarray(betas), jnp.asarray(src_s), jnp.asarray(row_ptr),
        jnp.asarray(indeg), jnp.asarray(informed0), key,
    )
    for variant in ("full", "norng", "nogather"):
        f = make(variant)
        float(f(*args)[-1])  # compile
        t0 = time.perf_counter()
        float(f(*args)[-1])
        el = time.perf_counter() - t0
        print(f"{variant:9s}: {el:.3f}s / {nsteps} steps = {el / nsteps * 1e3:6.1f} ms/step")

    # primitive microbenchmarks
    e = len(src_s)
    wd = jnp.asarray(np.random.default_rng(0).random(n) < 0.3)
    src_d = jnp.asarray(src_s)
    rp = jnp.asarray(row_ptr)
    reps = 30

    def bench(name, f, *a):
        g = jax.jit(f)
        float(jnp.sum(g(*a)))
        t0 = time.perf_counter()
        for _ in range(reps):
            r = g(*a)
        float(jnp.sum(r))
        print(f"{name:30s}: {(time.perf_counter() - t0) / reps * 1e3:6.2f} ms")

    bench("gather wd[src] (1e7)", lambda w, s: w[s].astype(jnp.int32), wd, src_d)
    bench("cumsum 1e7 int32", jnp.cumsum, jnp.ones(e, jnp.int32))
    bench("prefix gathers at row_ptr", lambda p, r: p[r[1:]] - p[r[:-1]], jnp.ones(e + 1, jnp.int32), rp)
    idx = jnp.asarray(np.random.default_rng(1).integers(0, n, 100_000, np.int32))
    bench("scatter-add 1e5 into 1e6", lambda c, i: c.at[i].add(1), jnp.zeros(n, jnp.int32), idx)
    mask = jnp.asarray(np.random.default_rng(2).random(n) < 0.01)
    bench("nonzero(size=16384) over 1e6", lambda m: jnp.nonzero(m, size=16384, fill_value=n)[0], mask)


if __name__ == "__main__":
    main()
