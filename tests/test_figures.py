"""Unit-render tests for every figure builder (figures/plotting.py).

The master CLI path is covered by tests/test_master_cli.py; these lock each
builder individually — a signature or field rename fails here in seconds
instead of mid-replication. Each test only asserts the figure builds and has
axes; visual parity with the reference is the replication document's job.
"""

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt
import numpy as np
import pytest

from sbr_tpu import make_model_params, solve_learning, solve_equilibrium_baseline
from sbr_tpu.models.params import SolverConfig, make_hetero_params, make_interest_params

CFG = SolverConfig(n_grid=512, bisect_iters=60)


@pytest.fixture(scope="module")
def baseline_solved():
    m = make_model_params()
    ls = solve_learning(m.learning, CFG)
    res = solve_equilibrium_baseline(ls, m.economic, CFG)
    return m, ls, res


def _check(fig):
    assert fig.axes, "figure has no axes"
    plt.close(fig)


def test_plot_learning_distribution(baseline_solved):
    from sbr_tpu.figures.plotting import plot_learning_distribution

    m, ls, _ = baseline_solved
    _check(plot_learning_distribution([ls], m.learning.tspan, [m.learning.beta]))


def test_plot_hazard_rate_decomposition(baseline_solved):
    from sbr_tpu.figures.plotting import plot_hazard_rate_decomposition

    m, ls, res = baseline_solved
    _check(plot_hazard_rate_decomposition(res, ls, m.economic))


def test_plot_equilibrium(baseline_solved):
    from sbr_tpu.figures.plotting import plot_equilibrium

    m, ls, res = baseline_solved
    assert bool(res.bankrun)
    _check(plot_equilibrium(res, ls, m.economic))


def test_plot_comp_stat_panels(baseline_solved):
    from sbr_tpu.figures.plotting import plot_comp_stat_withdrawals_and_collapse
    from sbr_tpu.sweeps import u_sweep

    m, ls, _ = baseline_solved
    sw = u_sweep(ls, np.linspace(0.01, 1.5, 64), m.economic, CFG)
    fig_a, fig_b = plot_comp_stat_withdrawals_and_collapse(
        np.asarray(sw.u_values),
        np.asarray(sw.max_withdrawals),
        np.asarray(sw.collapse_times),
        m.economic.kappa,
        return_times=np.asarray(sw.return_times),
    )
    _check(fig_a)
    _check(fig_b)


def test_plot_heatmap_aw(baseline_solved):
    from sbr_tpu.figures.plotting import plot_heatmap_aw
    from sbr_tpu.sweeps import beta_u_grid

    m, _, _ = baseline_solved
    amt = np.linspace(0.05, 1.0, 8)
    us = np.linspace(0.01, 1.0, 8)
    grid = beta_u_grid(1.0 / amt, us, m, config=CFG)
    _check(plot_heatmap_aw(amt, us, np.asarray(grid.max_aw).T))


def test_plot_aw_hetero():
    from sbr_tpu.figures.plotting import plot_aw_hetero
    from sbr_tpu.hetero import get_aw_hetero, solve_equilibrium_hetero, solve_learning_hetero

    m = make_hetero_params(
        betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
    )
    lsh = solve_learning_hetero(m.learning, CFG)
    res = solve_equilibrium_hetero(lsh, m.economic, CFG)
    assert bool(res.bankrun)
    aw = get_aw_hetero(res, lsh)
    _check(plot_aw_hetero(res, aw, m.economic, m.learning.betas))


def test_plot_value_function():
    from sbr_tpu.figures.plotting import plot_value_function
    from sbr_tpu.interest import solve_equilibrium_interest

    m = make_interest_params(u=0.0, r=0.06, delta=0.1)
    ls = solve_learning(m.learning, CFG)
    res = solve_equilibrium_interest(ls, m.economic, CFG)
    _check(plot_value_function(res, m.economic))
