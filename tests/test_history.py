"""Tests for the perf history + regression gate (`sbr_tpu.obs.history` and
`report trend`, ISSUE 3 tentpole): append/load round-trip, polarity rules,
rolling-median baselines, platform isolation, and the CLI exit-code
contract — exit 1 on a synthetic ≥15% throughput regression, 0 on flat
history, 3 on missing/short history (the acceptance criteria)."""

import json

import pytest

from sbr_tpu.obs import history, report


def _rec(ts, platform="cpu", **metrics):
    return {
        "schema": 1,
        "ts": ts,
        "label": "bench",
        "platform": platform,
        "metrics": metrics,
    }


def _write(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    return path


# -- append / load -----------------------------------------------------------


def test_append_load_round_trip(tmp_path):
    p = tmp_path / "h.jsonl"
    out = history.append(
        {"eq_per_sec": 10.0, "nan_metric": float("nan"), "text": "no", "flag": True},
        label="x",
        platform="cpu",
        path=p,
        meta={"note": "fixture"},
    )
    assert out == p
    (rec,) = history.load(p)
    # schema 13 (ISSUE 19): the self-healing prefetch workload joined the
    # record (12 added the demand observatory, 11 the numerics audit, 10
    # information models, 9 composable scenarios, 8 differentiable
    # equilibria, 7 the fleet SLO split, 6 mega-agents generation, 5
    # adaptive numerics, 4 elastic sweeps, 3 serving, 2 memory); the key
    # set only grew, and schema-1..12/-less lines still load
    # (tests/test_mem.py, tests/test_serve.py, tests/test_elastic.py,
    # tests/test_numerics.py, tests/test_graphgen.py, tests/test_fleet.py,
    # tests/test_grad.py, tests/test_scenario.py, tests/test_infomodels.py,
    # tests/test_audit.py, tests/test_demand.py, tests/test_prewarm.py,
    # tests/test_flight.py).
    assert rec["schema"] == history.SCHEMA == 14
    assert rec["label"] == "x" and rec["platform"] == "cpu"
    # only finite numerics survive; bools coerce to gateable ints
    assert rec["metrics"] == {"eq_per_sec": 10.0, "flag": 1}
    assert rec["meta"] == {"note": "fixture"}
    # a torn tail write must not poison the log
    with open(p, "a") as fh:
        fh.write('{"trunc')
    assert len(history.load(p)) == 1


def test_load_missing_file_is_empty(tmp_path):
    assert history.load(tmp_path / "nope.jsonl") == []


def test_history_path_env_override(tmp_path, monkeypatch):
    monkeypatch.setenv("SBR_OBS_HISTORY", str(tmp_path / "env.jsonl"))
    assert history.history_path() == tmp_path / "env.jsonl"
    assert history.history_path(tmp_path / "arg.jsonl") == tmp_path / "arg.jsonl"
    monkeypatch.delenv("SBR_OBS_HISTORY")
    assert str(history.history_path()).endswith("benchmarks/bench_history.jsonl")


# -- polarity + check --------------------------------------------------------


def test_polarity_rules():
    assert history.polarity("beta_u_grid_equilibria_per_sec") == 1
    assert history.polarity("agent_steps_per_sec") == 1
    assert history.polarity("grid_dispatch_s") == -1
    assert history.polarity("obs_compile_s") == -1
    assert history.polarity("memory_peak_bytes") == -1
    assert history.polarity("health_divergent") == -1
    assert history.polarity("mystery_metric") == 1


def test_check_flat_history_ok():
    records = [_rec(f"t{i}", eq_per_sec=1000.0, grid_dispatch_s=0.5) for i in range(4)]
    verdicts, status = history.check(records, tolerance=0.15)
    assert status == "ok"
    assert all(v["status"] == "ok" for v in verdicts.values())
    assert verdicts["eq_per_sec"]["baseline"] == 1000.0


def test_check_throughput_regression():
    records = [_rec(f"t{i}", eq_per_sec=1000.0) for i in range(3)]
    records.append(_rec("t3", eq_per_sec=700.0))  # -30%, higher-better
    verdicts, status = history.check(records, tolerance=0.15)
    assert status == "regression"
    v = verdicts["eq_per_sec"]
    assert v["status"] == "regression"
    assert v["change"] == pytest.approx(-0.3)
    assert v["direction"] == "higher_better"


def test_check_duration_regression_lower_better():
    records = [_rec(f"t{i}", obs_compile_s=1.0) for i in range(3)]
    records.append(_rec("t3", obs_compile_s=1.5))  # +50% compile time
    verdicts, status = history.check(records, tolerance=0.15)
    assert status == "regression"
    assert verdicts["obs_compile_s"]["direction"] == "lower_better"


def test_check_improvement_is_not_regression():
    records = [_rec(f"t{i}", eq_per_sec=1000.0, grid_dispatch_s=0.5) for i in range(3)]
    records.append(_rec("t3", eq_per_sec=1500.0, grid_dispatch_s=0.3))
    _, status = history.check(records, tolerance=0.15)
    assert status == "ok"


def test_check_within_tolerance_ok():
    records = [_rec(f"t{i}", eq_per_sec=1000.0) for i in range(3)]
    records.append(_rec("t3", eq_per_sec=900.0))  # -10% < 15% tolerance
    _, status = history.check(records, tolerance=0.15)
    assert status == "ok"


def test_check_short_history():
    records = [_rec("t0", eq_per_sec=1000.0), _rec("t1", eq_per_sec=500.0)]
    verdicts, status = history.check(records, min_points=3)
    assert status == "short"
    assert verdicts["eq_per_sec"]["status"] == "short"


def test_check_platform_isolation():
    """A CPU-fallback latest record must gate against CPU history only —
    never read as a collapse vs the TPU numbers."""
    records = [_rec(f"t{i}", platform="tpu", eq_per_sec=100_000.0) for i in range(3)]
    records += [_rec(f"c{i}", platform="cpu", eq_per_sec=1000.0) for i in range(3)]
    _, status = history.check(records, tolerance=0.15)
    assert status == "ok"
    # and a genuine regression within the cpu series still fires
    records.append(_rec("c3", platform="cpu", eq_per_sec=500.0))
    _, status = history.check(records, tolerance=0.15)
    assert status == "regression"


def test_check_divergent_count_zero_baseline():
    """lower-better count with a clean baseline: ANY increase regresses
    (one divergent cell is a signal, not a percentage)."""
    records = [_rec(f"t{i}", health_divergent=0) for i in range(3)]
    records.append(_rec("t3", health_divergent=2))
    verdicts, status = history.check(records)
    assert status == "regression"
    assert verdicts["health_divergent"]["change"] is None


def test_check_rolling_median_window_ignores_ancient_baseline():
    """The baseline is the rolling median of the WINDOW, not all history —
    an old slow era must not mask a regression vs the recent plateau."""
    records = [_rec(f"old{i}", eq_per_sec=100.0) for i in range(10)]
    records += [_rec(f"new{i}", eq_per_sec=1000.0) for i in range(5)]
    records.append(_rec("now", eq_per_sec=700.0))
    verdicts, status = history.check(records, tolerance=0.15, window=5)
    assert status == "regression"
    assert verdicts["eq_per_sec"]["baseline"] == 1000.0


def test_sparkline():
    assert history.sparkline([1.0, 1.0, 1.0]) == "▄▄▄"
    s = history.sparkline([0.0, 1.0, 2.0, 3.0])
    assert s[0] == "▁" and s[-1] == "█"
    assert history.sparkline(list(range(100)), width=24).__len__() == 24
    assert history.sparkline([]) == ""


# -- CLI (report trend) ------------------------------------------------------


def test_trend_cli_exit_codes(tmp_path, capsys):
    flat = _write(tmp_path / "flat.jsonl", [_rec(f"t{i}", eq_per_sec=1000.0) for i in range(4)])
    reg = _write(
        tmp_path / "reg.jsonl",
        [_rec(f"t{i}", eq_per_sec=1000.0) for i in range(3)] + [_rec("t3", eq_per_sec=700.0)],
    )
    short = _write(tmp_path / "short.jsonl", [_rec("t0", eq_per_sec=1000.0)])

    assert report.main(["trend", str(flat), "--check"]) == 0
    assert report.main(["trend", str(reg), "--check", "--tolerance", "0.15"]) == 1
    assert report.main(["trend", str(tmp_path / "missing.jsonl"), "--check"]) == 3
    assert report.main(["trend", str(short), "--check"]) == 3
    # render-only on a fresh checkout (no history yet) is not an error
    assert report.main(["trend", str(tmp_path / "missing.jsonl")]) == 0
    # a generous tolerance swallows the drop
    assert report.main(["trend", str(reg), "--check", "--tolerance", "0.5"]) == 0
    # without --check the CLI only renders (exit 0 regardless)
    assert report.main(["trend", str(reg)]) == 0
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "eq_per_sec" in out


def test_trend_cli_json(tmp_path, capsys):
    reg = _write(
        tmp_path / "reg.jsonl",
        [_rec(f"t{i}", eq_per_sec=1000.0) for i in range(3)] + [_rec("t3", eq_per_sec=700.0)],
    )
    assert report.main(["trend", str(reg), "--check", "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "regression" and doc["exit"] == 1
    assert doc["verdicts"]["eq_per_sec"]["status"] == "regression"
    assert doc["n_records"] == 4

    assert report.main(["trend", str(tmp_path / "missing.jsonl"), "--check", "--json"]) == 3
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "short" and doc["exit"] == 3


def test_trend_cli_render_table(tmp_path, capsys):
    p = _write(
        tmp_path / "h.jsonl",
        [_rec(f"t{i}", eq_per_sec=1000.0 + i, grid_dispatch_s=0.5) for i in range(5)],
    )
    assert report.main(["trend", str(p)]) == 0
    out = capsys.readouterr().out
    assert "PLATFORM cpu" in out
    assert "eq_per_sec" in out and "grid_dispatch_s" in out
    assert any(ch in out for ch in "▁▂▃▄▅▆▇█")


def test_trend_cli_metric_filter(tmp_path, capsys):
    p = _write(
        tmp_path / "h.jsonl",
        [_rec(f"t{i}", eq_per_sec=1000.0, obs_compile_s=1.0) for i in range(3)]
        + [_rec("t3", eq_per_sec=1000.0, obs_compile_s=9.0)],
    )
    # compile time blew up, but the gate is restricted to the throughput metric
    assert report.main(["trend", str(p), "--check", "--metric", "eq_per_sec"]) == 0
    assert report.main(["trend", str(p), "--check"]) == 1
    capsys.readouterr()


# -- bench integration -------------------------------------------------------


def test_bench_append_history_helper(tmp_path, monkeypatch):
    import bench

    monkeypatch.setenv("SBR_BENCH_SIZES", "tiny")
    monkeypatch.setenv("SBR_OBS_HISTORY", str(tmp_path / "h.jsonl"))
    result = {
        "metric": "beta_u_grid_equilibria_per_sec",
        "value": 100.0,
        "unit": "equilibria/sec",
        "extra": {
            "platform": "cpu",
            "agent_steps_per_sec": 5.0,
            "grid_dispatch_s": 0.1,
            "obs": {"compile_s": 1.0, "execute_s": 0.5},
        },
    }
    bench._append_history(result)
    (rec,) = history.load(tmp_path / "h.jsonl")
    assert rec["platform"] == "cpu"
    assert rec["metrics"]["beta_u_grid_equilibria_per_sec"] == 100.0
    assert rec["metrics"]["agent_steps_per_sec"] == 5.0
    assert rec["metrics"]["grid_dispatch_s"] == 0.1
    assert rec["metrics"]["obs_compile_s"] == 1.0
    # tiny smoke runs without SBR_OBS_HISTORY must NOT touch any history
    monkeypatch.delenv("SBR_OBS_HISTORY")
    bench._append_history(result)
    assert len(history.load(tmp_path / "h.jsonl")) == 1


def test_bench_metrics_extraction():
    out = history.bench_metrics({"metric": "m_per_sec", "value": 2.0, "extra": {}})
    assert out == {"m_per_sec": 2.0}
    assert history.bench_metrics({}) == {}
