"""Smoke test of the benchmark harness the driver invokes at round end.

Runs the REAL pipeline — parent orchestration, `--measure` child subprocess,
JSON contract — at SBR_BENCH_SIZES=tiny scale, pinned to CPU so no probe or
accelerator is involved. If this breaks, `BENCH_r*.json` would be empty at
round end, which history shows is the costliest possible failure."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(script: str) -> dict:
    env = {
        **os.environ,
        "PYTHONPATH": str(REPO),
        "SBR_BENCH_PLATFORM": "cpu",
        "SBR_BENCH_SIZES": "tiny",
        "SBR_BENCH_MEASURE_TIMEOUT_S": "240",
    }
    out = subprocess.run(
        [sys.executable, str(REPO / script)],
        capture_output=True,
        text=True,
        timeout=280,
        env=env,
        cwd=str(REPO),
    )
    assert out.returncode == 0, f"{script} rc={out.returncode}\n{out.stderr[-800:]}"
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"{script} must print exactly ONE line, got {len(lines)}"
    return json.loads(lines[0])


@pytest.mark.slow
def test_bench_emits_contract_json():
    d = _run("bench.py")
    assert d["metric"] == "beta_u_grid_equilibria_per_sec"
    assert d["unit"] == "equilibria/sec"
    assert d["value"] > 0
    assert d["vs_baseline"] > 0
    extra = d["extra"]
    assert extra["platform"] == "cpu"
    assert extra["agent_steps_per_sec"] > 0
    # the self-documenting history: forced platform + one ok measure phase
    phases = [h for h in extra["probe_history"] if h.get("phase") == "measure"]
    assert phases and phases[-1]["outcome"] == "ok"
    # ISSUE 3 satellite: ONE uniform, versioned record shape for every
    # probe/measure history entry (probe entries used to carry keys the
    # measure entry lacked)
    uniform = {"schema", "phase", "attempt", "outcome", "platform",
               "duration_s", "timeout_s", "backoff_s"}
    for h in extra["probe_history"]:
        assert h["schema"] == 1
        assert uniform <= set(h), f"non-uniform history entry: {h}"


def test_stretch_emits_contract_json():
    d = _run("benchmarks/stretch.py")
    assert d["metric"] == "stretch_hetero_agents_steps_per_sec"
    assert d["unit"] == "agent-steps/sec"
    assert d["value"] > 0
    extra = d["extra"]
    assert extra["platform"] == "cpu"
    assert extra["policy"]["policy_eq_per_sec"] > 0
    phases = [h for h in extra["probe_history"] if h.get("phase") == "measure"]
    assert phases and phases[-1]["outcome"] == "ok"


def test_run_killable_survives_pipe_holding_grandchild():
    """The observed tunnel failure mode: the probe child spawns a helper
    that inherits stdout and outlives a SIGKILL to the child alone —
    subprocess.run(capture_output=True) then blocks in communicate()
    forever (the watch daemon froze 100 min this way). `_run_killable`
    must return at ~timeout regardless, because (a) output goes to temp
    files, not pipes, and (b) the kill hits the whole process group."""
    import sys
    import time

    import bench

    child = (
        "import subprocess, sys, time\n"
        # grandchild inherits stdout and sleeps far past every timeout
        "subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(600)'])\n"
        "print('CHILD UP', flush=True)\n"
        "time.sleep(600)\n"  # the child itself also hangs
    )
    t0 = time.perf_counter()
    # 10 s start budget: interpreter + nested Popen must land 'CHILD UP'
    # before the kill even on a loaded CI host (2 s flaked under load)
    rc, out, err, dur = bench._run_killable([sys.executable, "-c", child], 10.0)
    wall = time.perf_counter() - t0
    assert rc is None  # timed out
    assert wall < 40.0, f"parent blocked {wall:.0f}s — the pipe hang is back"
    assert "CHILD UP" in out  # pre-kill output still captured via the file


def test_run_killable_captures_fast_child():
    import sys

    import bench

    rc, out, err, dur = bench._run_killable(
        [sys.executable, "-c", "print('OK'); import sys; print('E', file=sys.stderr)"],
        30.0,
    )
    assert rc == 0 and out.strip() == "OK" and err.strip() == "E"


def test_persist_capture_writes_accelerator_artifact(tmp_path, monkeypatch):
    """The watch-daemon/harness persist path: accelerator results land as
    timestamped driver-format JSON; CPU results and tiny smoke runs do not
    (this machinery is the round's TPU evidence chain — a silent bug here
    loses the capture)."""
    import bench

    monkeypatch.delenv("SBR_BENCH_SIZES", raising=False)
    monkeypatch.setattr(bench, "_benchmarks_dir", lambda: tmp_path)
    res = {"metric": "m", "value": 1.5, "unit": "x", "extra": {"platform": "tpu"}}
    bench._persist_capture(res)
    files = list(tmp_path.glob("BENCH_tpu_auto_*.json"))
    assert len(files) == 1
    import json

    assert json.loads(files[0].read_text())["value"] == 1.5
    bench._persist_capture({"extra": {"platform": "cpu"}})  # not a capture
    monkeypatch.setenv("SBR_BENCH_SIZES", "tiny")
    bench._persist_capture(res)  # tiny smoke runs are not captures either
    assert len(list(tmp_path.glob("*.json"))) == 1
    # and the attempt log appends one line per (non-tiny) logged attempt
    monkeypatch.delenv("SBR_BENCH_SIZES")
    bench._log_capture_attempt({"script": "t", "outcome": "ok"})
    log = tmp_path / "CAPTURE_LOG.jsonl"
    assert log.exists() and len(log.read_text().splitlines()) == 1


def test_budget_clamps_phase_timeouts():
    """ADVICE r3 #3: every phase timeout shrinks to the remaining budget so
    a hung tunnel cannot burn a ~107-minute worst case."""
    import bench

    b = bench._Budget()
    b.total_s = 100.0
    assert b.clamp(50.0) == 50.0
    assert b.clamp(1000.0) <= 100.0
    assert b.clamp(10.0) == 30.0  # the floor keeps healthy children alive
    b.t0 -= 200.0  # simulate 200 s elapsed: budget exhausted
    # ADVICE r4: a spent budget returns 0 → the caller SKIPS the phase
    # (the old floor here let late phases overrun SBR_BENCH_BUDGET_S)
    assert b.clamp(1000.0) == 0.0
    assert bench._run_measurement("cpu", b.clamp(1000.0)) == (
        None,
        "skipped-budget",
        0.0,
    )


def test_watch_persists_fake_accelerator_capture(tmp_path, monkeypatch, capsys):
    """VERDICT r4 task 8: the watch daemon's persist+log path, exercised
    with a faked accelerator probe/measurement so the round's one real
    tunnel window cannot be wasted on a plumbing bug. Asserts the
    timestamped artifact and the CAPTURE_LOG line are both written, with
    the probe history embedded."""
    import bench

    monkeypatch.delenv("SBR_BENCH_SIZES", raising=False)  # tiny gates persist
    monkeypatch.setattr(bench, "_benchmarks_dir", lambda: tmp_path)
    monkeypatch.setattr(bench, "_probe_accelerator", lambda t: ("tpu", "ok", 0.1))
    fake = {
        "metric": "beta_u_grid_equilibria_per_sec",
        "value": 123.0,
        "unit": "equilibria/sec",
        "vs_baseline": 61.5,
        "extra": {"platform": "tpu"},
    }
    monkeypatch.setattr(
        bench, "_run_measurement", lambda p, t: ({**fake, "extra": dict(fake["extra"])}, "ok", 1.0)
    )
    assert bench.watch(1, 0.0) == 0
    # exactly-one-JSON-line stdout contract holds in watch mode too
    lines = [ln for ln in capsys.readouterr().out.strip().splitlines() if ln.strip()]
    assert len(lines) == 1 and json.loads(lines[0])["value"] == 123.0

    arts = list(tmp_path.glob("BENCH_tpu_auto_*.json"))
    assert len(arts) == 1, list(tmp_path.iterdir())
    data = json.loads(arts[0].read_text())
    assert data["value"] == 123.0
    hist = data["extra"]["probe_history"]
    assert hist[0]["watch_attempt"] == 1 and hist[1]["phase"] == "measure"

    entries = [
        json.loads(ln)
        for ln in (tmp_path / "CAPTURE_LOG.jsonl").read_text().strip().splitlines()
    ]
    assert entries[-1]["script"] == "bench.py --watch"
    assert entries[-1]["platform"] == "tpu" and entries[-1]["value"] == 123.0


def test_watch_rejects_cpu_fallback_capture(tmp_path, monkeypatch, capsys):
    """A measure child that silently fell back to CPU (tunnel dropped in the
    probe→attach window) must NOT count as an accelerator capture: nothing
    persisted, logged as cpu-fallback-in-child, watch keeps probing."""
    import bench

    monkeypatch.delenv("SBR_BENCH_SIZES", raising=False)
    monkeypatch.setattr(bench, "_benchmarks_dir", lambda: tmp_path)
    monkeypatch.setattr(bench, "_probe_accelerator", lambda t: ("tpu", "ok", 0.1))
    fake = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
            "extra": {"platform": "cpu"}}
    monkeypatch.setattr(
        bench, "_run_measurement", lambda p, t: ({**fake, "extra": dict(fake["extra"])}, "ok", 1.0)
    )
    assert bench.watch(1, 0.0) == 1
    assert not list(tmp_path.glob("*.json"))
    entries = [
        json.loads(ln)
        for ln in (tmp_path / "CAPTURE_LOG.jsonl").read_text().strip().splitlines()
    ]
    assert entries[-1]["outcome"] == "cpu-fallback-in-child"


def test_scale_demo_emits_contract_json():
    d = _run("benchmarks/scale_demo.py")
    assert d["metric"] == "scale_demo_agent_steps_per_sec"
    assert d["value"] > 0
    extra = d["extra"]
    assert extra["platform"] == "cpu"
    assert extra["headline"]["prep_s"] >= 0
    # the logistic-limit physics check must pass even at smoke scale
    assert extra["physics"]["pass"] is True


def _run_ablation(script: str, args, tmp_path, timeout=560, extra_env=None) -> dict:
    """Round-5 ablation scripts: artifact-JSON contract at tiny shapes (the
    scripts guard the one TPU window — a plumbing bug there wastes it)."""
    art = tmp_path / "abl.json"
    env = {
        **os.environ,
        "PYTHONPATH": str(REPO),
        "SBR_ABL_PLATFORM": "cpu",
        "SBR_ABL_JSON": str(art),
        **(extra_env or {}),
    }
    out = subprocess.run(
        [sys.executable, str(REPO / script), *map(str, args)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=str(REPO),
    )
    assert out.returncode == 0, f"{script} rc={out.returncode}\n{out.stderr[-800:]}"
    assert art.exists(), f"{script} wrote no artifact\n{out.stdout[-500:]}"
    return json.loads(art.read_text())


@pytest.mark.slow
def test_ablate_compaction_contract(tmp_path):
    d = _run_ablation("benchmarks/ablate_compaction.py", [20000, 8, 12], tmp_path)
    assert set(d["parts_ms"]) >= {
        "scatter", "searchsorted", "searchsorted_blocked",
        "uniforms_foldin", "uniforms_counter",
    }
    e2e = d["end_to_end"]
    assert set(e2e) == {
        f"{impl}_b{m}x"
        for impl in ("scatter", "searchsorted", "searchsorted_blocked")
        for m in (1, 4)
    } | {"scatter_b1x_rngfoldin"}
    for row in e2e.values():
        assert row["steady_s"] > 0 and row["recount_steps"] >= 0
    assert d["verdict"] in e2e or d["verdict"] == "scatter_b1x"


@pytest.mark.slow
def test_ablate_max_degree_contract(tmp_path):
    d = _run_ablation("benchmarks/ablate_max_degree.py", [20000, 12], tmp_path)
    per = d["per_max_degree"]
    assert set(per) == {"64", "256", "512", "1024"}
    hubs = [per[k]["hubs"] for k in ("64", "256", "512", "1024")]
    assert hubs == sorted(hubs, reverse=True)  # hub set shrinks with d
    assert d["best_max_degree"] in (64, 256, 512, 1024)


@pytest.mark.slow
def test_census_calibration_contract(tmp_path):
    d = _run_ablation(
        "benchmarks/census_calibration.py", ["--quick"], tmp_path, timeout=560
    )
    shapes = d["shapes"]
    assert len(shapes) == 6
    for row in shapes.values():
        assert row["predicted_recounts"] >= 0
        assert 0 <= row["measured_recounts"] <= row["n_steps"]
