"""Sweep tests: Figure-4 u-sweep and Figure-5 β×u grid, including the
8-virtual-device mesh path (SURVEY §7.2 step 3)."""

import jax
import jax.numpy as jnp
import numpy as np

from sbr_tpu import make_model_params, solve_learning, solve_equilibrium_baseline, with_overrides
from sbr_tpu.models.params import SolverConfig
from sbr_tpu.models.results import Status
from sbr_tpu.sweeps import beta_u_grid, u_sweep


def test_u_sweep_matches_scalar_solves():
    m = make_model_params()
    cfg = SolverConfig()
    ls = solve_learning(m.learning, cfg)
    u_values = np.linspace(0.001, 0.2, 40)
    res = u_sweep(ls, u_values, m.economic, cfg)

    for i in [0, 7, 20, 39]:
        mi = with_overrides(m, u=float(u_values[i]))
        single = solve_equilibrium_baseline(ls, mi.economic, cfg)
        np.testing.assert_allclose(
            float(res.collapse_times[i]), float(single.xi), atol=1e-12, equal_nan=True
        )
        np.testing.assert_allclose(
            float(res.max_withdrawals[i]), float(single.aw_max), atol=1e-12, equal_nan=True
        )
        assert int(res.status[i]) == int(single.status)


def test_u_sweep_no_run_region_is_nan():
    """High-u tail must be NaN with NO_* status — the region the reference
    fills via early termination (`1_baseline.jl:147-163`)."""
    m = make_model_params()
    ls = solve_learning(m.learning)
    res = u_sweep(ls, np.linspace(0.15, 0.5, 16), m.economic)
    assert np.isnan(np.asarray(res.max_withdrawals)[-1])
    assert int(np.asarray(res.status)[-1]) != Status.RUN


def test_beta_u_grid_matches_cellwise():
    m = make_model_params()
    cfg = SolverConfig(n_grid=1024)
    betas = np.array([0.5, 1.0, 2.0, 4.0])
    us = np.linspace(0.01, 0.3, 8)
    grid = beta_u_grid(betas, us, m, cfg)
    assert grid.xi.shape == (4, 8)

    for bi in [0, 2]:
        mb = with_overrides(m, beta=float(betas[bi]))
        assert mb.economic.eta == m.economic.eta  # pinned-η sweep semantics
        ls = solve_learning(mb.learning, cfg)
        for ui in [0, 5]:
            mu = with_overrides(mb, u=float(us[ui]))
            single = solve_equilibrium_baseline(ls, mu.economic, cfg)
            np.testing.assert_allclose(
                float(np.asarray(grid.xi)[bi, ui]), float(single.xi), atol=1e-10, equal_nan=True
            )


def test_f32_grid_reproduces_f64_no_run_region():
    """The f32 sweep path (what bench.py and the README numbers run) must
    reproduce the f64 run/no-run frontier at grid scale — the semantics the
    reference's early-termination accounting depends on
    (`1_baseline.jl:236-244`). Status may legitimately flip only in the
    frontier band (cells adjacent to an f64 status change, where the root
    error |AW(ξ*)-κ| sits within one tolerance step of _root_tol); off the
    frontier the two dtypes must agree exactly, and AW_max must be close
    where both run."""

    def binary_dilation(mask):
        """8-neighborhood dilation by one cell (3×3 max over the padded grid)."""
        p = np.pad(mask, 1)
        h, w = mask.shape
        out = np.zeros_like(mask)
        for di in (0, 1, 2):
            for dj in (0, 1, 2):
                out |= p[di : di + h, dj : dj + w]
        return out

    m = make_model_params()
    cfg = SolverConfig(n_grid=1024, bisect_iters=60, refine_crossings=False)
    # 128×128 subgrid of the Figure-5 domain (β = 1/amt, amt ∈ [1e-4, 1]).
    amt = np.linspace(1e-4, 1.0, 128)
    us = np.linspace(0.001, 1.0, 128)
    g64 = beta_u_grid(1.0 / amt, us, m, cfg, dtype=jnp.float64)
    g32 = beta_u_grid(1.0 / amt, us, m, cfg, dtype=jnp.float32)

    run64 = np.asarray(g64.status) == Status.RUN
    run32 = np.asarray(g32.status) == Status.RUN

    # Frontier band: cells within one step of an f64 run/no-run change
    # (where the dilations of the region and its complement overlap).
    frontier = binary_dilation(run64) & binary_dilation(~run64)

    mismatch = run64 != run32
    # every dtype flip must lie in the frontier band …
    assert (mismatch <= frontier).all(), (
        f"{(mismatch & ~frontier).sum()} f32/f64 status flips OFF the frontier"
    )
    # … and the band itself must be thin (quantified, not hand-waved)
    assert mismatch.mean() < 0.01, f"frontier flip rate {mismatch.mean():.3%}"

    # AW_max agrees where both dtypes run (interpolation-bound ⇒ ~1e-3).
    both = run64 & run32
    assert both.sum() > 1000  # the run region is a substantial patch
    aw64 = np.asarray(g64.max_aw)[both]
    aw32 = np.asarray(g32.max_aw)[both]
    np.testing.assert_allclose(aw32, aw64, atol=5e-3)
    xi64 = np.asarray(g64.xi)[both]
    xi32 = np.asarray(g32.xi)[both]
    np.testing.assert_allclose(xi32, xi64, atol=5e-2)


def test_beta_u_grid_on_mesh_matches_single_device():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = jax.sharding.Mesh(devs, ("b", "u"))
    m = make_model_params()
    cfg = SolverConfig(n_grid=512)
    betas = np.linspace(0.5, 4.0, 8)
    us = np.linspace(0.01, 0.3, 6)
    plain = beta_u_grid(betas, us, m, cfg)
    sharded = beta_u_grid(betas, us, m, cfg, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(plain.xi), np.asarray(sharded.xi), atol=1e-12, equal_nan=True
    )
    np.testing.assert_array_equal(np.asarray(plain.status), np.asarray(sharded.status))


def test_u_sweep_sharded_matches_unsharded():
    """u-axis mesh-sharded Figure-4 sweep equals the single-device program
    exactly (one replicated Stage-1 solution, independent cells)."""
    import jax

    from sbr_tpu import make_model_params, solve_learning
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.sweeps import u_sweep

    cfg = SolverConfig(n_grid=512, bisect_iters=60)
    m = make_model_params()
    ls = solve_learning(m.learning, cfg)
    us = np.linspace(0.001, 0.9, 64)
    mesh = jax.make_mesh((8,), ("u",))
    sharded = u_sweep(ls, us, m.economic, cfg, mesh=mesh)
    single = u_sweep(ls, us, m.economic, cfg)
    np.testing.assert_array_equal(np.asarray(sharded.status), np.asarray(single.status))
    np.testing.assert_allclose(
        np.asarray(sharded.collapse_times), np.asarray(single.collapse_times),
        atol=1e-12, equal_nan=True,
    )
    np.testing.assert_allclose(
        np.asarray(sharded.max_withdrawals), np.asarray(single.max_withdrawals),
        atol=1e-12, equal_nan=True,
    )
