"""Vector-curve figure parity against the reference's COMMITTED PDFs
(VERDICT r4 task 2 / Weak #5).

`benchmarks/reference_curves.py` extracts every data polyline from the 12
committed line-plot figures (`/root/reference/output/figures/**.pdf`) and
diffs them, in data coordinates, against this repo's curve arrays. The full
run re-solves every workload (u-sweep, social fixed point — minutes); the
artifact `benchmarks/CURVES_vs_reference.json` is committed, and this test
asserts its tolerances so a stale/regressed artifact fails the suite.

The tolerance ladder is set by the PDF's own precision, not by solver
accuracy: GKS writes device coordinates quantized to 0.01 pt on axes
spanning ~300-530 pt, a floor of ~2e-5..4e-4 data units per figure
(dominated by x-quantization x local slope on steep curves). Measured
2026-07-30: every series' max |dy| is within 3x that floor; the scalar
parity behind the curves is separately pinned at 1e-6 by
`tests/test_reference_parity.py`.

The parser itself is exercised on one figure (cheap, no solver work) so a
reference-tree or parser regression is caught even without re-running the
full artifact.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np
import pytest

BENCH_DIR = Path(__file__).resolve().parent.parent / "benchmarks"
ARTIFACT = BENCH_DIR / "CURVES_vs_reference.json"

sys.path.insert(0, str(BENCH_DIR))

# Per-figure max|dy| tolerance, ~3x the measured worst series (data units).
# panel_b's series live on a ~11-time-unit axis — the absolute numbers are
# bigger but the fraction of axis range (~1.5e-4) matches the others.
TOLERANCES = {
    "baseline/learning_dynamics": 4e-4,
    "baseline/hazard_rate": 2e-4,
    "baseline/equilibrium_dynamics_main": 1.5e-4,
    "baseline/equilibrium_dynamics_fast": 4e-4,
    "baseline/equilibrium_dynamics_low_u": 2e-4,
    "baseline/comp_stat_u_panel_a": 2e-4,
    "baseline/comp_stat_u_panel_b": 5e-3,
    "heterogeneity/aggregate_withdrawals_hetero": 6e-4,
    "interest_rates/value_function": 1.5e-4,
    "interest_rates/hazard_decomposition": 1.5e-4,
    "social_learning/baseline_equilibrium": 2e-4,
    "social_learning/social_learning_equilibrium": 2e-4,
}
MIN_SERIES = {  # every expected series must be present in the artifact
    "baseline/learning_dynamics": 3,
    "baseline/hazard_rate": 3,
    "baseline/equilibrium_dynamics_main": 3,
    "baseline/comp_stat_u_panel_b": 2,
    "heterogeneity/aggregate_withdrawals_hetero": 3,
    "interest_rates/hazard_decomposition": 4,
    "interest_rates/value_function": 1,
}


class TestCommittedArtifact:
    def test_artifact_exists_and_covers_all_figures(self):
        data = json.loads(ARTIFACT.read_text())
        assert set(data) == set(TOLERANCES), (
            f"figure coverage mismatch: {set(TOLERANCES) ^ set(data)}"
        )
        for fig, n in MIN_SERIES.items():
            assert len(data[fig]) >= n, f"{fig}: {len(data[fig])} series < {n}"

    def test_all_series_within_tolerance(self):
        data = json.loads(ARTIFACT.read_text())
        failures = []
        for fig, sers in data.items():
            tol = TOLERANCES[fig]
            for name, res in sers.items():
                if res["max_abs_dy"] > tol:
                    failures.append(f"{fig}:{name} max|dy|={res['max_abs_dy']:.2e} > {tol}")
                assert res["n_ref_points"] >= 50, f"{fig}:{name} too few points"
        assert not failures, failures


@pytest.mark.skipif(
    not Path("/root/reference/output/figures/baseline/learning_dynamics.pdf").exists(),
    reason="reference replication tree not present in this image (environment-bound)",
)
class TestParserLive:
    """The extraction pipeline against the reference tree, no solver work."""

    def test_learning_dynamics_closed_form(self):
        from reference_curves import (
            axis_auto,
            diff_series,
            figure_geometry,
            parse_strokes,
            series,
        )

        pdf = Path("/root/reference/output/figures/baseline/learning_dynamics.pdf")
        strokes = parse_strokes(pdf)
        geo = figure_geometry(strokes)
        ax_x = axis_auto(geo.xticks, geo.box[0], geo.box[1], 0.0, 20.0)
        ax_y = axis_auto(geo.yticks, geo.box[2], geo.box[3], 1e-4, 1.0)
        t = np.linspace(0.0, 20.0, 4001)
        x0 = 1e-4
        for color, beta in (("blue", 0.5), ("red", 1.0), ("green", 2.0)):
            dev = series(strokes, color, min_pts=100)
            xy = np.stack([ax_x.to_data(dev[:, 0]), ax_y.to_data(dev[:, 1])], axis=1)
            ours = x0 * np.exp(beta * t) / (1.0 - x0 + x0 * np.exp(beta * t))
            res = diff_series(xy, t, ours)
            assert res["n_ref_points"] == 1000
            assert res["max_abs_dy"] < 4e-4, (color, res)

    def test_wrong_tick_values_fail_loudly(self):
        from reference_curves import figure_geometry, parse_strokes, axis_from_ticks

        pdf = Path("/root/reference/output/figures/baseline/learning_dynamics.pdf")
        geo = figure_geometry(parse_strokes(pdf))
        with pytest.raises(AssertionError):
            # non-uniform values cannot fit the uniform tick geometry
            axis_from_ticks(geo.xticks, [0.0, 5.0, 10.0, 15.0, 21.0])
