"""Test configuration.

Forces an 8-device virtual CPU platform BEFORE jax backend init so
multi-chip sharding paths (mesh tests) execute without TPU hardware, and
enables x64 — the reference's correctness envelope is machine-eps float64
(`src/baseline/learning.jl:43,51`).

Note: this image's axon sitecustomize force-registers the TPU plugin and
overrides the JAX_PLATFORMS env var, so the platform must be pinned via
jax.config after import (verified: env alone is ignored).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
