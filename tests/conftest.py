"""Test configuration.

Forces an 8-device virtual CPU platform BEFORE jax backend init so
multi-chip sharding paths (mesh tests) execute without TPU hardware, and
enables x64 — the reference's correctness envelope is machine-eps float64
(`src/baseline/learning.jl:43,51`).

Note: this image's axon sitecustomize force-registers the TPU plugin and
overrides the JAX_PLATFORMS env var, so the platform must be pinned via
jax.config after import (verified: env alone is ignored).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Pin the suite to the bit-exact fixed-numerics path (ISSUE 9, same move as
# PR 7's `elastic=False` test pinning): the golden/parity/chaos suites are
# regression anchors for the PRE-adaptive solver semantics, and the fixed
# path reproduces them byte-for-byte at seed-suite cost — the adaptive
# kernels compile separate while_loop programs per config, which on the
# 2-core CI/tier-1 box pushes the ~400-test suite past its wall-clock
# budget if every default-config test pays both. Adaptive correctness is
# covered explicitly: tests/test_numerics.py (direct kernel contracts +
# adaptive-vs-fixed agreement across all four stacks, overriding this pin
# with `numerics="adaptive"`), the CI numerics-parity step, and bench.py's
# back-to-back adaptive/fixed grid measurement. Production defaults are
# untouched (SolverConfig resolves "auto" → adaptive when SBR_NUMERICS is
# unset — asserted by tests/test_numerics.py::TestNumericsConfig).
# Unconditional (not setdefault): an inherited SBR_NUMERICS=adaptive must
# not silently flip the anchor suites; tests that want adaptive pass
# numerics="adaptive" explicitly or monkeypatch the env.
os.environ["SBR_NUMERICS"] = "fixed"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
