"""Baseline pipeline gate tests (SURVEY §7.2 step 2).

The oracle in `oracle.py` is an independent scipy implementation accurate to
~1e-10; agreement to 1e-6 is the BASELINE.md Figure-3 CPU-match criterion.
Reference workload parameters come from `scripts/1_baseline.jl:34-44,106,118`.
"""

import jax
import jax.numpy as jnp
import numpy as np

from sbr_tpu import make_model_params, solve_learning, solve_equilibrium_baseline, with_overrides
from sbr_tpu.baseline.solver import solve_equilibrium_core
from sbr_tpu.models.params import SolverConfig
from sbr_tpu.models.results import Status

from oracle import solve_oracle

TOL = 1e-6


def _solve_jax(m, config=SolverConfig()):
    ls = solve_learning(m.learning, config)
    return solve_equilibrium_baseline(ls, m.economic, config)


def _assert_matches_oracle(m, config=SolverConfig()):
    res = _solve_jax(m, config)
    orc = solve_oracle(
        beta=m.learning.beta,
        x0=m.learning.x0,
        u=m.economic.u,
        p=m.economic.p,
        kappa=m.economic.kappa,
        lam=m.economic.lam,
        eta=m.economic.eta,
        tspan_end=m.learning.tspan[1],
    )
    assert bool(res.bankrun) == orc.bankrun
    if orc.bankrun:
        assert abs(float(res.xi) - orc.xi) < TOL, (float(res.xi), orc.xi)
        assert abs(float(res.tau_bar_in_unc) - orc.tau_bar_in) < TOL
        assert abs(float(res.tau_bar_out_unc) - orc.tau_bar_out) < TOL
        assert abs(float(res.aw_max) - orc.aw_max) < 1e-5
    else:
        assert np.isnan(float(res.xi))
    return res, orc


def test_figure3_main_equilibrium():
    """Gate: β=1, η_bar=15, u=0.1, p=0.5, κ=0.6, λ=0.01 (`1_baseline.jl:34-44`)."""
    m = make_model_params()
    res, orc = _assert_matches_oracle(m)
    assert bool(res.converged)
    assert int(res.status) == Status.RUN
    # derived normal-time quantities (`solver.jl:82-83`)
    assert abs(float(res.tau_in) - max(orc.xi - orc.tau_bar_in, 0.0)) < TOL
    assert abs(float(res.tau_out) - max(orc.xi - orc.tau_bar_out, 0.0)) < TOL


def test_figure3bis_fast_communication():
    """β=3 via copy-with-overrides — η stays pinned at 15 (`1_baseline.jl:106`)."""
    base = make_model_params()
    m = with_overrides(base, beta=3.0)
    assert m.economic.eta == 15.0  # the copy-ctor quirk (model.jl:189-211)
    _assert_matches_oracle(m)


def test_figure3ter_low_u():
    m = with_overrides(make_model_params(), u=0.01)
    _assert_matches_oracle(m)


def test_no_run_when_u_above_hazard_max():
    """u above max h ⇒ buffers coincide at tspan end ⇒ trivially no run
    (`solver.jl:221-223,429-433`)."""
    m = with_overrides(make_model_params(), u=5.0)
    res = _solve_jax(m)
    assert not bool(res.bankrun)
    assert int(res.status) == Status.NO_CROSSING
    assert bool(res.converged)  # trivial case counts as converged
    assert float(res.tolerance) == 0.0
    assert np.isnan(float(res.xi))
    assert np.isnan(float(res.aw_max))


def test_no_root_when_kappa_unreachable():
    """κ above the reachable AW range ⇒ bisection finds no root ⇒ NaN
    (`solver.jl:316-324` non-convergence path)."""
    m = with_overrides(make_model_params(), kappa=0.99, u=0.2)
    res = _solve_jax(m)
    orc = solve_oracle(u=0.2, kappa=0.99)
    assert not orc.bankrun
    assert not bool(res.bankrun)
    assert int(res.status) in (Status.NO_ROOT, Status.NO_CROSSING)
    assert not bool(res.converged) or int(res.status) == Status.NO_CROSSING


def test_vmap_over_u_matches_scalar():
    """The u-sweep unit: Stage 1 shared, Stages 2-3 vmapped (`1_baseline.jl:169`)."""
    m = make_model_params()
    config = SolverConfig()
    ls = solve_learning(m.learning, config)
    u_vals = jnp.asarray([0.01, 0.05, 0.1, 0.15, 0.5])
    e = m.economic

    batched = jax.vmap(
        lambda u: solve_equilibrium_core(
            ls, u, e.p, e.kappa, e.lam, e.eta, m.learning.tspan[1], config
        )
    )(u_vals)

    for i, u in enumerate(np.asarray(u_vals)):
        single = solve_equilibrium_core(
            ls, u, e.p, e.kappa, e.lam, e.eta, m.learning.tspan[1], config
        )
        np.testing.assert_allclose(
            np.asarray(batched.xi)[i], float(single.xi), rtol=0, atol=1e-12, equal_nan=True
        )
        assert int(np.asarray(batched.status)[i]) == int(single.status)


def test_jit_compiles_and_matches_eager():
    m = make_model_params()
    config = SolverConfig()
    ls = solve_learning(m.learning, config)
    e = m.economic

    fn = jax.jit(
        lambda u: solve_equilibrium_core(
            ls, u, e.p, e.kappa, e.lam, e.eta, m.learning.tspan[1], config
        ).xi
    )
    assert abs(float(fn(0.1)) - float(_solve_jax(m).xi)) < 1e-12


def test_f32_path_close_to_f64():
    """The sweep dtype ladder: f32 results within ~1e-3 of f64 (SURVEY §7.3)."""
    m = make_model_params()
    config = SolverConfig()
    ls64 = solve_learning(m.learning, config, dtype=jnp.float64)
    ls32 = solve_learning(m.learning, config, dtype=jnp.float32)
    r64 = solve_equilibrium_baseline(ls64, m.economic, config)
    r32 = solve_equilibrium_baseline(ls32, m.economic, config)
    assert bool(r32.bankrun) == bool(r64.bankrun)
    assert abs(float(r32.xi) - float(r64.xi)) < 5e-3


def test_aw_at_xi_equals_kappa():
    """Equilibrium condition AW(ξ)=κ holds on the returned curve."""
    m = make_model_params()
    res = _solve_jax(m)
    ls = solve_learning(m.learning)
    aw_at_xi = float(ls.cdf_at(res.xi) - ls.cdf_at(jnp.minimum(res.tau_bar_in_unc, res.xi)))
    assert abs(aw_at_xi - m.economic.kappa) < 1e-9


def test_repr_and_solve_time():
    """Results print one readable line and carry wall-clock solve_time
    (reference `Base.show` + `SolvedModel.solve_time`, `solver.jl:116-129,414`)."""
    m = make_model_params()
    res = _solve_jax(m)
    r = repr(res)
    assert "\n" not in r and "EquilibriumResult(" in r and "bankrun=True" in r
    assert res.solve_time > 0
    # vmapped (batched) results must not blow up the repr either
    ls = solve_learning(m.learning)
    import jax

    from sbr_tpu.baseline.solver import solve_equilibrium_core

    e = m.economic
    batched = jax.vmap(
        lambda u: solve_equilibrium_core(
            ls, u, e.p, e.kappa, e.lam, e.eta, ls.grid[-1], SolverConfig()
        )
    )(jnp.linspace(0.05, 0.15, 3))
    rb = repr(batched)
    assert "\n" not in rb and "(3,)" in rb
