"""Tests for the numerical-health diagnostics layer (`sbr_tpu.diag`,
ISSUE 2 tentpole).

Covers the acceptance criteria: degenerate rootfind inputs (non-bracketing
bisection intervals, all-above/all-below crossing fallbacks, NaN-poisoned
curves) surface `Health` flags instead of silently returning defaults;
health riding the solver stacks changes no output value and causes no
retrace when telemetry toggles; `report health` renders a run and exits
nonzero on a deliberately NaN-poisoned sweep; `report gc` retention; the
bench probe cache.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu import diag, obs
from sbr_tpu.core.rootfind import bisect, first_upcrossing, last_downcrossing
from sbr_tpu.diag import (
    DIVERGENT_MASK,
    FALLBACK_IN_DEFAULT,
    FALLBACK_IN_KNOT,
    FALLBACK_OUT_DEFAULT,
    FALLBACK_OUT_KNOT,
    FP_NOT_CONVERGED,
    NAN_INPUT,
    NAN_OUTPUT,
    NO_BRACKET,
    NONFINITE_RESIDUAL,
    Health,
)
from sbr_tpu.obs import report


@pytest.fixture(autouse=True)
def _no_active_run():
    assert obs.current_run() is None
    was_on = obs.metrics().enabled
    yield
    while obs.end_run() is not None:
        pass
    (obs.metrics().enable if was_on else obs.metrics().disable)()


# -- core primitives: degenerate inputs --------------------------------------


def test_bisect_health_clean_root():
    f = lambda x: x**3 - 2.0
    x_plain = bisect(f, jnp.asarray(0.0), jnp.asarray(2.0), num_iters=90)
    x, h = bisect(f, jnp.asarray(0.0), jnp.asarray(2.0), num_iters=90, with_health=True)
    assert float(x) == float(x_plain)  # health must not perturb the iterate
    assert float(h.residual) < 1e-13
    assert float(h.bracket_width) < 1e-13
    assert int(h.iterations) == 90
    assert int(h.flags) == 0


def test_bisect_health_non_bracketing_interval():
    # f > 0 on the whole interval: no sign change, the returned "root" is
    # the bracket collapse point — NO_BRACKET must say so.
    f = lambda x: x**2 + 1.0
    x, h = bisect(f, jnp.asarray(1.0), jnp.asarray(2.0), num_iters=60, with_health=True)
    assert int(h.flags) & NO_BRACKET
    assert not (int(h.flags) & DIVERGENT_MASK)  # informational, not divergence
    assert np.isfinite(float(x))


def test_bisect_health_nan_poisoned():
    f = lambda x: x - jnp.nan
    x, h = bisect(f, jnp.asarray(0.0), jnp.asarray(1.0), num_iters=30, with_health=True)
    flags = int(h.flags)
    assert flags & NONFINITE_RESIDUAL
    assert flags & DIVERGENT_MASK
    x2, h2 = bisect(
        lambda t: t - 0.5, jnp.asarray(jnp.nan), jnp.asarray(1.0), num_iters=30, with_health=True
    )
    assert int(h2.flags) & NAN_INPUT


def test_crossing_health_fallback_ladder():
    x = jnp.linspace(0.0, 1.0, 64)
    # all below the level -> default rung on both crossings
    t, h = first_upcrossing(x, jnp.zeros(64), 0.5, 42.0, with_health=True)
    assert float(t) == 42.0
    assert int(h.flags) & FALLBACK_IN_DEFAULT
    t, h = last_downcrossing(x, jnp.zeros(64), 0.5, 42.0, with_health=True)
    assert float(t) == 42.0
    assert int(diag.as_out_crossing(h).flags) & FALLBACK_OUT_DEFAULT
    # all above the level -> first/last-knot rung
    t, h = first_upcrossing(x, jnp.ones(64), 0.5, 42.0, with_health=True)
    assert float(t) == 0.0
    assert int(h.flags) & FALLBACK_IN_KNOT
    t, h = last_downcrossing(x, jnp.ones(64), 0.5, 42.0, with_health=True)
    assert float(t) == 1.0
    assert int(diag.as_out_crossing(h).flags) & FALLBACK_OUT_KNOT
    # genuine crossing -> no flags
    y = 1.0 - (np.asarray(x) - 0.5) ** 2 * 8.0
    t, has, h = first_upcrossing(x, jnp.asarray(y), 0.5, 42.0, return_flag=True, with_health=True)
    assert bool(has) and int(h.flags) == 0


def test_crossing_health_nan_poisoned_curve():
    """A fully-NaN curve silently takes the `default` rung; the flags must
    report the poison instead of letting it pass as a no-crossing."""
    x = jnp.linspace(0.0, 1.0, 32)
    t, h = first_upcrossing(x, jnp.full(32, jnp.nan), 0.5, 7.0, with_health=True)
    assert float(t) == 7.0  # value semantics unchanged (reference fallback)
    assert int(h.flags) & NAN_INPUT
    assert int(h.flags) & DIVERGENT_MASK
    # NaN level, clean curve
    t, h = first_upcrossing(x, jnp.ones(32), jnp.nan, 7.0, with_health=True)
    assert int(h.flags) & NAN_INPUT


def test_rk4_and_quadrature_health():
    from sbr_tpu.core.integrate import cumtrapz, cumulative_gauss_legendre
    from sbr_tpu.core.ode import rk4

    ts = jnp.linspace(0.0, 1.0, 11)
    ys, h = rk4(lambda t, y, a: -y, jnp.asarray(1.0), ts, substeps=2, with_health=True)
    assert int(h.flags) == 0 and int(h.iterations) == 20
    ys, h = rk4(lambda t, y, a: -y, jnp.asarray(jnp.nan), ts, with_health=True)
    assert int(h.flags) & NAN_INPUT and int(h.flags) & NAN_OUTPUT

    out, h = cumtrapz(jnp.ones(16), dx=0.1, with_health=True)
    assert int(h.flags) == 0 and int(h.iterations) == 15
    out, h = cumtrapz(jnp.full(16, jnp.nan), dx=0.1, with_health=True)
    assert int(h.flags) & NAN_INPUT

    grid = jnp.linspace(0.0, 1.0, 9)
    out, h = cumulative_gauss_legendre(lambda t: jnp.exp(t), grid, with_health=True)
    assert int(h.flags) == 0
    out, h = cumulative_gauss_legendre(
        lambda t: jnp.full_like(t, jnp.nan), grid, with_health=True
    )
    assert int(h.flags) & NAN_INPUT


def test_or_reduce_flags_matches_elementwise_or():
    flags = jnp.asarray([FALLBACK_IN_KNOT, NO_BRACKET, 0, NAN_INPUT | NO_BRACKET], jnp.int32)
    got = int(diag.or_reduce_flags(flags))
    assert got == FALLBACK_IN_KNOT | NO_BRACKET | NAN_INPUT


def test_health_merge():
    a = Health.empty(jnp.float64).replace(
        residual=jnp.asarray(1e-9), flags=jnp.int32(FALLBACK_IN_KNOT)
    )
    b = Health.empty(jnp.float64).replace(
        residual=jnp.asarray(1e-3),
        iterations=jnp.int32(90),
        flags=jnp.int32(NO_BRACKET),
    )
    m = a.merge(b)
    assert float(m.residual) == 1e-3  # fmax ignores the NaN slots
    assert int(m.iterations) == 90
    assert int(m.flags) == FALLBACK_IN_KNOT | NO_BRACKET


# -- solver stacks -----------------------------------------------------------


def _solve_config():
    from sbr_tpu.models.params import SolverConfig

    return SolverConfig(n_grid=128, bisect_iters=40)


def test_baseline_result_carries_health():
    from sbr_tpu import make_model_params, solve_learning
    from sbr_tpu.baseline.solver import solve_equilibrium_baseline

    m = make_model_params()
    cfg = _solve_config()
    ls = solve_learning(m.learning, cfg)
    res = solve_equilibrium_baseline(ls, m.economic, config=cfg)
    assert res.health is not None
    assert bool(res.bankrun)
    assert not (int(res.health.flags) & DIVERGENT_MASK)
    # achieved residual must agree with the reported tolerance field
    assert float(res.health.residual) == pytest.approx(float(res.tolerance), abs=1e-12)


def test_diagnostics_no_value_change_no_retrace(tmp_path):
    """The acceptance criterion: health is always part of the traced
    program, so toggling telemetry on/off neither changes any solver
    output nor invalidates a traced jit cache (obs.metrics discipline)."""
    from sbr_tpu import make_model_params, solve_learning
    from sbr_tpu.baseline.solver import solve_equilibrium_core

    m = make_model_params()
    cfg = _solve_config()
    ls = solve_learning(m.learning, cfg)
    traces = []

    @jax.jit
    def solve(u):
        traces.append(1)  # runs only at trace time
        return solve_equilibrium_core(
            ls, u, m.economic.p, m.economic.kappa, m.economic.lam,
            m.economic.eta, ls.grid[-1], cfg,
        )

    u = jnp.asarray(m.economic.u)
    res_off = solve(u)
    assert len(traces) == 1
    with obs.run_context(run_dir=str(tmp_path / "r")):
        res_on = solve(u)
        obs.log_health("toggle", res_on.health, res_on.status)
    res_off2 = solve(u)
    assert len(traces) == 1, "telemetry toggle retraced the solver"
    for a, b, c in zip(
        jax.tree_util.tree_leaves(res_off),
        jax.tree_util.tree_leaves(res_on),
        jax.tree_util.tree_leaves(res_off2),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_sweep_health_grid_shapes_and_census(tmp_path):
    import numpy as np

    from sbr_tpu import make_model_params
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    m = make_model_params()
    cfg = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        grid = beta_u_grid(np.array([0.5, 1.0]), np.array([0.05, 0.1, 0.5]), m, config=cfg)
    assert grid.health.residual.shape == (2, 3)
    assert grid.health.flags.shape == (2, 3)
    # run cells must be clean of divergent flags, and the census must agree
    flags = np.asarray(grid.health.flags)
    assert not np.any(flags & DIVERGENT_MASK)
    events = [
        json.loads(line)
        for line in (run.run_dir / "events.jsonl").read_text().splitlines()
    ]
    (health_ev,) = [e for e in events if e["kind"] == "health"]
    assert health_ev["stage"] == "sweeps.beta_u_grid"
    assert health_ev["cells"] == 6
    assert health_ev["divergent"] == 0
    assert "residual_hist" in health_ev
    manifest = json.loads((run.run_dir / "manifest.json").read_text())
    assert manifest["health"]["sweeps.beta_u_grid"]["cells"] == 6
    assert manifest["health"]["sweeps.beta_u_grid"]["divergent"] == 0


def test_social_fixed_point_health_flags_non_convergence():
    from sbr_tpu import make_model_params
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.social.solver import solve_equilibrium_social

    m = make_model_params()
    cfg = SolverConfig(n_grid=96, bisect_iters=30)
    # starved iteration budget -> FP_NOT_CONVERGED must be flagged
    res = solve_equilibrium_social(m, cfg, max_iter=3)
    assert not bool(res.converged)
    assert int(res.health.flags) & FP_NOT_CONVERGED
    assert int(res.health.iterations) >= 3
    # the default calibration's ξ search walks past η -> FP_ABORTED
    res = solve_equilibrium_social(m, cfg, max_iter=250)
    assert bool(res.aborted)
    assert int(res.health.flags) & diag.FP_ABORTED
    # converging calibration (test_social's Figure-12 config) -> clean flags
    m_run = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
    res = solve_equilibrium_social(m_run, SolverConfig(n_grid=512), tol=1e-4, max_iter=500)
    assert bool(res.converged)
    assert not (int(res.health.flags) & (FP_NOT_CONVERGED | DIVERGENT_MASK))
    assert float(res.health.residual) == pytest.approx(float(res.error))


def test_hetero_health_clean_and_poisoned():
    from sbr_tpu.hetero.learning import solve_learning_hetero
    from sbr_tpu.hetero.solver import solve_equilibrium_hetero
    from sbr_tpu.models.params import make_hetero_params

    cfg = _solve_config()
    hp = make_hetero_params(betas=(0.5, 1.0, 2.0), dist=(0.3, 0.4, 0.3))
    lsh = solve_learning_hetero(hp.learning, cfg)
    res = solve_equilibrium_hetero(lsh, hp.economic, cfg)
    assert not (int(res.health.flags) & DIVERGENT_MASK)
    assert float(res.health.residual) == pytest.approx(float(res.tolerance), abs=1e-12)
    # poison one group's curves: the per-group crossing census must flag it
    lsh_bad = lsh.replace(cdfs=lsh.cdfs.at[1].set(jnp.nan), pdfs=lsh.pdfs.at[1].set(jnp.nan))
    res_bad = solve_equilibrium_hetero(lsh_bad, hp.economic, cfg)
    assert int(res_bad.health.flags) & NAN_INPUT


# -- summarize + report health CLI -------------------------------------------


def test_summarize_worst_cells_and_divergence():
    h = Health(
        residual=jnp.asarray([1e-8, jnp.nan, 0.3]),
        bracket_width=jnp.asarray([1e-12, jnp.nan, 1.0]),
        iterations=jnp.asarray([90, 0, 90], jnp.int32),
        flags=jnp.asarray([0, NAN_INPUT, NO_BRACKET], jnp.int32),
    )
    s = diag.summarize(h, status=jnp.asarray([0, 1, 2], jnp.int32))
    assert s["cells"] == 3
    assert s["divergent"] == 1
    assert s["flag_counts"] == {"no_bracket": 1, "nan_input": 1}
    # the NO_ROOT cell's 0.3 is an expected-degenerate residual and must
    # NOT pollute max_residual; only the RUN cell's counts
    assert s["max_residual"] == pytest.approx(1e-8)
    # the divergent cell ranks first even with a NaN residual
    assert s["worst_cells"][0]["index"] == [1]
    assert s["worst_cells"][0]["flags"] == ["nan_input"]
    assert s["worst_cells"][0]["status"] == "NO_CROSSING"
    # the degenerate cell still appears (it carries a flag) but residual-less
    no_root = [c for c in s["worst_cells"] if c["index"] == [2]]
    assert no_root and no_root[0]["residual"] is None


def test_report_health_poisoned_run_exits_nonzero(tmp_path, capsys):
    """ISSUE 2 acceptance: a deliberately NaN-poisoned sweep must flag and
    `report health` must exit nonzero so CI can gate on it."""
    import numpy as np

    from sbr_tpu import make_model_params
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    m = make_model_params()
    cfg = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)
    with obs.run_context(run_dir=str(tmp_path / "bad")) as run:
        beta_u_grid(np.array([0.5, np.nan]), np.array([0.05, 0.1]), m, config=cfg)
    rc = report.main(["health", str(run.run_dir)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DIVERGENCE DETECTED" in out
    assert "nan_input" in out
    assert "NaN CENSUS" in out


def test_report_health_clean_run_exits_zero(tmp_path, capsys):
    import numpy as np

    from sbr_tpu import make_model_params
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    m = make_model_params()
    cfg = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)
    with obs.run_context(run_dir=str(tmp_path / "ok")) as run:
        beta_u_grid(np.array([0.5, 1.0]), np.array([0.05, 0.1]), m, config=cfg)
    rc = report.main(["health", str(run.run_dir)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "OK" in out
    assert "RESIDUAL HISTOGRAM" in out


def test_report_health_without_health_events_exits_3(tmp_path, capsys):
    with obs.run_context(run_dir=str(tmp_path / "empty")) as run:
        obs.event("custom")
    assert report.main(["health", str(run.run_dir)]) == 3


def test_legacy_report_still_renders_health_block(tmp_path, capsys):
    import numpy as np

    from sbr_tpu import make_model_params
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    m = make_model_params()
    cfg = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        beta_u_grid(np.array([1.0]), np.array([0.1]), m, config=cfg)
    assert report.main([str(run.run_dir)]) == 0
    assert "HEALTH" in capsys.readouterr().out


# -- retention (report gc + auto-prune) --------------------------------------


def _mk_runs(root, n):
    dirs = []
    for i in range(n):
        with obs.run_context(label=f"r{i}", root=str(root)) as run:
            pass
        (run.run_dir / "touch").write_text(str(i))
        import os
        import time

        # distinct mtimes without sleeping a full second; gc recency reads
        # the log files, not just the directory, so age those too
        t = time.time() - (n - i) * 10
        for p in (run.run_dir, run.run_dir / "events.jsonl", run.run_dir / "manifest.json"):
            os.utime(p, (t, t))
        dirs.append(run.run_dir)
    return dirs


def test_report_gc_keeps_most_recent(tmp_path, capsys):
    dirs = _mk_runs(tmp_path, 4)
    assert report.main(["gc", str(tmp_path), "--keep", "2"]) == 0
    remaining = sorted(d.name for d in tmp_path.iterdir() if d.is_dir())
    assert [d.name for d in dirs[2:]] == remaining
    assert "removed 2 run dir(s)" in capsys.readouterr().out


def test_gc_runs_skips_active_and_foreign_dirs(tmp_path):
    from sbr_tpu.obs.runlog import gc_runs

    _mk_runs(tmp_path, 2)
    (tmp_path / "not_a_run").mkdir()  # no manifest.json: not ours to delete
    active = obs.start_run(root=str(tmp_path), label="active")
    removed = gc_runs(tmp_path, keep=0)
    obs.end_run()
    assert active.run_dir.exists()
    assert (tmp_path / "not_a_run").exists()
    assert len(removed) == 2


def test_gc_runs_protects_other_process_live_run(tmp_path):
    """A manifest still in status "running" with recent activity belongs to
    ANOTHER process's open run (this process's stack can't vouch for it) —
    gc must leave it alone; once stale past the grace window it is a
    crashed run's leftovers and is collectable (code-review finding)."""
    import os
    import time

    from sbr_tpu.obs.runlog import gc_runs

    live = tmp_path / "live_run"
    live.mkdir()
    (live / "manifest.json").write_text(json.dumps({"status": "running"}))
    (live / "events.jsonl").write_text("{}\n")
    assert gc_runs(tmp_path, keep=0) == []
    assert live.exists()
    # stale: no activity for longer than the grace window -> collectable
    t = time.time() - 10_000.0
    for p in (live, live / "manifest.json", live / "events.jsonl"):
        os.utime(p, (t, t))
    removed = gc_runs(tmp_path, keep=0, running_grace_s=3600.0)
    assert removed and not live.exists()


def test_auto_prune_on_finalize(tmp_path):
    _mk_runs(tmp_path, 3)
    run = obs.start_run(root=str(tmp_path), label="pruner", auto_prune_keep=1)
    obs.end_run()
    dirs = [d for d in tmp_path.iterdir() if d.is_dir()]
    # the pruning run itself + 1 kept survivor
    assert len(dirs) == 2
    assert run.run_dir.exists()


# -- bench probe cache -------------------------------------------------------


def test_probe_cache_skips_ladder(tmp_path, monkeypatch):
    import bench

    monkeypatch.setenv("SBR_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("SBR_BENCH_PROBE_CACHE_TTL_S", "900")

    def boom(timeout):
        raise AssertionError("probe ladder must not run on a cache hit")

    bench._write_probe_cache("cpu", [{"attempt": 1, "outcome": "ok"}])
    monkeypatch.setattr(bench, "_probe_accelerator", boom)
    platform, history = bench._probe_loop()
    assert platform == "cpu"
    assert history[0]["cached"] is True

    # expired cache -> the ladder runs again
    stale = json.loads(bench._probe_cache_path().read_text())
    stale["ts"] -= 10_000
    bench._probe_cache_path().write_text(json.dumps(stale))
    monkeypatch.setattr(bench, "_probe_accelerator", lambda t: ("tpu", "ok", 0.1))
    platform, history = bench._probe_loop()
    assert platform == "tpu"
    assert history[0].get("cached") is None
    # and the fresh outcome was re-cached
    assert json.loads(bench._probe_cache_path().read_text())["platform"] == "tpu"


def test_probe_cache_disabled_by_zero_ttl(tmp_path, monkeypatch):
    import bench

    monkeypatch.setenv("SBR_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("SBR_BENCH_PROBE_CACHE_TTL_S", "0")
    bench._write_probe_cache("cpu", [])
    assert not bench._probe_cache_path().exists()
    monkeypatch.setattr(bench, "_probe_accelerator", lambda t: ("tpu", "ok", 0.1))
    platform, _ = bench._probe_loop()
    assert platform == "tpu"
