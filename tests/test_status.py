"""Unit tests for utils.status accounting (obs satellite, PR 1).

`status_counts`/`status_summary` feed the obs subsystem's structured
`status` events and run manifests, so their key ORDER must be deterministic
(enum declaration order, UNKNOWN last) and their counts must always sum to
the grid size — including the all-no-run edge case and out-of-enum codes
(the tiled checkpoint driver's -1 "never computed" fill).
"""

import numpy as np

from sbr_tpu.models.results import Status
from sbr_tpu.utils.status import UNKNOWN_KEY, status_counts, status_summary


def test_status_counts_mixed_grid():
    grid = np.array(
        [
            [Status.RUN, Status.RUN, Status.NO_CROSSING],
            [Status.NO_ROOT, Status.FALSE_EQ, Status.RUN],
        ],
        dtype=np.int32,
    )
    counts = status_counts(grid)
    assert counts == {"RUN": 3, "NO_CROSSING": 1, "NO_ROOT": 1, "FALSE_EQ": 1}
    assert sum(counts.values()) == grid.size


def test_status_counts_key_order_deterministic():
    grid = np.array([Status.FALSE_EQ, Status.RUN, -1, Status.NO_ROOT], dtype=np.int32)
    counts = status_counts(grid)
    # Enum declaration order first, UNKNOWN (out-of-enum codes) last —
    # independent of the order codes appear in the data.
    assert list(counts) == [s.name for s in Status] + [UNKNOWN_KEY]
    assert counts[UNKNOWN_KEY] == 1
    assert sum(counts.values()) == grid.size


def test_status_counts_all_no_run_grid():
    # Edge case: a grid where NO cell found a bank-run equilibrium.
    grid = np.full((4, 5), int(Status.NO_CROSSING), dtype=np.int32)
    counts = status_counts(grid)
    assert counts["RUN"] == 0
    assert counts["NO_CROSSING"] == 20
    assert sum(counts.values()) == 20
    assert UNKNOWN_KEY not in counts

    summary = status_summary(grid)
    assert summary.startswith("0/20 run")
    assert "20 no_crossing" in summary


def test_status_summary_mixed():
    grid = np.array([Status.RUN, Status.RUN, Status.NO_ROOT], dtype=np.int32)
    s = status_summary(grid)
    assert s.startswith("2/3 run")
    assert "1 no_root" in s
    # zero-count categories are omitted
    assert "false_eq" not in s


def test_status_counts_accepts_jax_arrays():
    import jax.numpy as jnp

    grid = jnp.zeros((3,), dtype=jnp.int32)
    assert status_counts(grid) == {"RUN": 3, "NO_CROSSING": 0, "NO_ROOT": 0, "FALSE_EQ": 0}
