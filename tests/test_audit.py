"""Numerics audit observatory tests (ISSUE 17): classification tiers,
golden-registry round-trips + LOUD version refusal, canary fault
injection, scheduler drift latching, torn audit event lines, `report
audit` exit codes, artifact GC retention, and the SBR_AUDIT=0
structural-no-op witnesses (no scheduler, no module import, no
`sbr_audit` metric lines, zero new XLA traces).

Synthetic probes return their fingerprint/values dicts directly and the
battery runs with an explicit environment key, so the registry tests
never touch jax; only the engine/scheduler witnesses solve anything."""

import json
import math
import os
from pathlib import Path

import pytest

from sbr_tpu.obs import audit
from sbr_tpu.obs.report import audit_doc
from sbr_tpu.resilience import faults

# Explicit environment key: registry tests stay jax-free.
KEY = {"platform": "test", "x64": False, "jax": "0.0",
       "grid_program": 0, "scenario_program": 0}


def const_probe(name="synth.const", tier="bitwise", fingerprint="f" * 64,
                values=None, ok=None, **kw):
    """A synthetic probe returning a fixed result (no solve, no jax)."""
    def fn():
        out = {"fingerprint": fingerprint,
               "values": dict(values or {"v": 1.5}), "meta": {}}
        if ok is not None:
            out["ok"] = ok
        return out
    return audit.Probe(name=name, tier=tier, fn=fn, **kw)


def run(probe, reg_dir, update=False, **kw):
    return audit.run_battery(probe_names=[probe], reg_dir=reg_dir,
                             update=update, key=KEY, emit=False, **kw)


# ---------------------------------------------------------------------------
# Classification tiers
# ---------------------------------------------------------------------------


class TestClassify:
    def test_no_golden(self):
        p = const_probe()
        verdict, _ = audit.classify(p, {"fingerprint": "a", "values": {}}, None)
        assert verdict == "no_golden"

    def test_bitwise_pass_and_drift(self):
        p = const_probe(tier="bitwise")
        g = {"fingerprint": "abc", "values": {"v": 1.0}}
        assert audit.classify(p, {"fingerprint": "abc", "values": {}}, g)[0] == "pass"
        verdict, detail = audit.classify(p, {"fingerprint": "xyz", "values": {}}, g)
        assert verdict == "drift" and "fingerprint" in detail

    def test_ulp_tolerates_last_ulp(self):
        import numpy as np

        v = 0.37
        bumped = float(np.nextafter(np.float64(v), np.float64(1.0)))
        p = const_probe(tier="ulp", max_ulps=2)
        g = {"fingerprint": "g", "values": {"xi": v}}
        r = {"fingerprint": "other", "values": {"xi": bumped}}
        assert audit.classify(p, r, g)[0] == "pass"

    def test_ulp_drift_beyond_budget(self):
        p = const_probe(tier="ulp", max_ulps=2)
        g = {"fingerprint": "g", "values": {"xi": 0.37}}
        r = {"fingerprint": "x", "values": {"xi": 0.37 + 1e-6}}
        assert audit.classify(p, r, g)[0] == "drift"

    def test_ulp_key_set_change_is_drift(self):
        p = const_probe(tier="ulp")
        g = {"fingerprint": "g", "values": {"xi": 0.37}}
        r = {"fingerprint": "g", "values": {"xi": 0.37, "extra": 1.0}}
        assert audit.classify(p, r, g)[0] == "drift"

    def test_tolerance_pass_drift_and_selfcheck(self):
        p = const_probe(tier="tolerance", tol=1e-5)
        g = {"fingerprint": "g", "values": {"rel": 1.0}}
        ok = {"fingerprint": "x", "values": {"rel": 1.0 + 1e-7}}
        bad = {"fingerprint": "x", "values": {"rel": 1.1}}
        assert audit.classify(p, ok, g)[0] == "pass"
        assert audit.classify(p, bad, g)[0] == "drift"
        failed = {"fingerprint": "x", "values": {"rel": 1.0}, "ok": False}
        verdict, detail = audit.classify(p, failed, g)
        assert verdict == "drift" and "self-check" in detail

    def test_tolerance_nan_is_drift(self):
        p = const_probe(tier="tolerance")
        g = {"fingerprint": "g", "values": {"rel": 1.0}}
        r = {"fingerprint": "x", "values": {"rel": float("nan")}}
        assert audit.classify(p, r, g)[0] == "drift"

    def test_bad_tier_rejected(self):
        with pytest.raises(ValueError):
            const_probe(tier="vibes")


class TestUlpDiff:
    def test_identical_and_adjacent(self):
        import numpy as np

        assert audit.ulp_diff(0.5, 0.5) == 0.0
        nxt = float(np.nextafter(np.float64(0.5), np.float64(1.0)))
        assert audit.ulp_diff(0.5, nxt) == 1.0

    def test_nan_semantics(self):
        # Both NaN: a legitimately-NaN ξ must equal its golden.
        assert audit.ulp_diff(float("nan"), float("nan")) == 0.0
        assert math.isinf(audit.ulp_diff(float("nan"), 0.5))

    def test_sign_straddle_is_finite(self):
        assert audit.ulp_diff(-1e-300, 1e-300) > 0


# ---------------------------------------------------------------------------
# Golden registry: round-trip, archiving, LOUD version refusal
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_update_then_pass(self, tmp_path):
        p = const_probe()
        rep = run(p, tmp_path, update=True)
        assert rep["updated"] and Path(rep["golden_path"]).is_file()
        rep2 = run(p, tmp_path)
        assert rep2["ok"] and rep2["probes"][p.name]["verdict"] == "pass"

    def test_no_goldens_reports_missing(self, tmp_path):
        rep = run(const_probe(), tmp_path)
        assert not rep["ok"] and rep["missing"] == ["synth.const"]

    def test_changed_fingerprint_is_drift(self, tmp_path):
        run(const_probe(fingerprint="a" * 64), tmp_path, update=True)
        rep = run(const_probe(fingerprint="b" * 64), tmp_path)
        assert rep["drift"] == ["synth.const"]

    def test_rewrite_archives_previous_golden(self, tmp_path):
        p = const_probe()
        run(p, tmp_path, update=True)
        run(p, tmp_path, update=True)
        archives = list(tmp_path.glob("goldens_*.0*.json"))
        assert len(archives) == 1
        # The archive glob can never match an active golden (two dots).
        active = audit.golden_path(tmp_path, KEY)
        assert active.is_file() and active not in archives

    def test_version_mismatch_refused_loudly(self, tmp_path):
        p = const_probe()
        run(p, tmp_path, update=True)
        path = audit.golden_path(tmp_path, KEY)
        doc = json.loads(path.read_text())
        doc["registry_version"] = audit.AUDIT_REGISTRY_VERSION + 1
        path.write_text(json.dumps(doc))
        with pytest.raises(audit.AuditRegistryVersionError) as err:
            run(p, tmp_path)
        # The refusal must carry the regeneration hint, not just fail.
        assert "--update-goldens" in str(err.value)

    def test_skipped_probe_never_becomes_golden(self, tmp_path, monkeypatch):
        monkeypatch.setattr(audit, "_x64_enabled", lambda: False)
        skip = const_probe(name="synth.x64only", requires_x64=True)
        keep = const_probe(name="synth.keep")
        audit.run_battery(probe_names=[skip, keep], reg_dir=tmp_path,
                          update=True, key=KEY, emit=False)
        doc = json.loads(audit.golden_path(tmp_path, KEY).read_text())
        assert "synth.keep" in doc["probes"]
        assert "synth.x64only" not in doc["probes"]

    def test_probe_exception_is_error_verdict(self, tmp_path):
        def boom():
            raise RuntimeError("solver exploded")
        p = audit.Probe(name="synth.boom", tier="bitwise", fn=boom)
        rep = run(p, tmp_path)
        entry = rep["probes"]["synth.boom"]
        assert entry["verdict"] == "error" and "exploded" in entry["detail"]
        assert rep["drift"] == ["synth.boom"]

    def test_unknown_probe_name_raises(self, tmp_path):
        with pytest.raises(KeyError):
            audit.run_battery(probe_names=["no.such.probe"], reg_dir=tmp_path,
                              key=KEY, emit=False)


# ---------------------------------------------------------------------------
# Canary fault injection (the chaos-testable detection path)
# ---------------------------------------------------------------------------


class TestCanaryFaults:
    def teardown_method(self):
        faults.reset()

    def test_corrupt_rule_flags_drift(self, tmp_path):
        p = const_probe()
        run(p, tmp_path, update=True)
        faults.install(faults.FaultPlan({
            "seed": 7,
            "rules": [{"point": "audit.canary", "kind": "corrupt",
                       "match": "synth.const"}],
        }))
        rep = run(p, tmp_path)
        assert rep["drift"] == ["synth.const"]
        assert rep["probes"][p.name]["meta"]["injected_fault"] == "corrupt"

    def test_nan_rule_flags_drift(self, tmp_path):
        p = const_probe()
        run(p, tmp_path, update=True)
        faults.install(faults.FaultPlan({
            "seed": 7,
            "rules": [{"point": "audit.canary", "kind": "nan"}],
        }))
        rep = run(p, tmp_path)
        assert rep["drift"] == ["synth.const"]

    def test_match_restricts_to_one_probe(self, tmp_path):
        a = const_probe(name="synth.a", fingerprint="a" * 64)
        b = const_probe(name="synth.b", fingerprint="b" * 64)
        audit.run_battery(probe_names=[a, b], reg_dir=tmp_path, update=True,
                          key=KEY, emit=False)
        faults.install(faults.FaultPlan({
            "seed": 7,
            "rules": [{"point": "audit.canary", "kind": "corrupt",
                       "match": "synth.b"}],
        }))
        rep = audit.run_battery(probe_names=[a, b], reg_dir=tmp_path,
                                key=KEY, emit=False)
        assert rep["drift"] == ["synth.b"]
        assert rep["probes"]["synth.a"]["verdict"] == "pass"


# ---------------------------------------------------------------------------
# Audit events, torn lines, `report audit` gating
# ---------------------------------------------------------------------------


class TestReportAudit:
    def _audited_run(self, tmp_path, probes_and_kwargs):
        from sbr_tpu import obs

        run_dir = tmp_path / "run"
        r = obs.start_run(label="audit_test", run_dir=str(run_dir))
        try:
            for probe, kw in probes_and_kwargs:
                audit.run_battery(probe_names=[probe], reg_dir=tmp_path / "reg",
                                  key=KEY, **kw)
        finally:
            obs.end_run()
        return r.run_dir

    def test_clean_run_exit0(self, tmp_path):
        p = const_probe()
        audit.run_battery(probe_names=[p], reg_dir=tmp_path / "reg",
                          update=True, key=KEY, emit=False)
        run_dir = self._audited_run(tmp_path, [(p, {"cycle": 1})])
        doc, code = audit_doc(run_dir)
        assert code == 0 and not doc["breaches"]
        assert doc["probes"]["synth.const"]["verdict"] == "pass"
        assert doc["last_verdict"] == "pass"

    def test_drifted_run_exit1(self, tmp_path):
        audit.run_battery(probe_names=[const_probe(fingerprint="a" * 64)],
                          reg_dir=tmp_path / "reg", update=True, key=KEY,
                          emit=False)
        run_dir = self._audited_run(
            tmp_path, [(const_probe(fingerprint="b" * 64), {"cycle": 1})])
        doc, code = audit_doc(run_dir)
        assert code == 1
        assert "synth.const" in doc["drifted_probes"]

    def test_battery_artifact_written(self, tmp_path):
        p = const_probe()
        audit.run_battery(probe_names=[p], reg_dir=tmp_path / "reg",
                          update=True, key=KEY, emit=False)
        run_dir = self._audited_run(tmp_path, [(p, {"cycle": 1})])
        arts = list((Path(run_dir) / "audit").glob("battery_*.json"))
        assert len(arts) == 1
        assert json.loads(arts[0].read_text())["probes"]["synth.const"]

    def test_torn_audit_lines_tolerated(self, tmp_path):
        p = const_probe()
        audit.run_battery(probe_names=[p], reg_dir=tmp_path / "reg",
                          update=True, key=KEY, emit=False)
        run_dir = Path(self._audited_run(tmp_path, [(p, {"cycle": 1})]))
        # A torn (truncated mid-write) trailing line must not take down
        # the fold — counters still reflect every intact line.
        with open(run_dir / "events.jsonl", "a") as fh:
            fh.write('{"kind": "audit", "action": "probe", "pro')
        doc, code = audit_doc(run_dir)
        assert code == 0
        assert doc["probes"]["synth.const"]["events"] == 1

    def test_not_a_dir_exit2(self, tmp_path):
        doc, code = audit_doc(tmp_path / "nope")
        assert code == 2 and doc["error"] == "not a directory"

    def test_unaudited_run_exit3(self, tmp_path):
        from sbr_tpu import obs

        run_dir = tmp_path / "run"
        obs.start_run(label="plain", run_dir=str(run_dir))
        obs.end_run()
        doc, code = audit_doc(run_dir)
        assert code == 3

    def test_manifest_rollup_lands(self, tmp_path):
        p = const_probe()
        audit.run_battery(probe_names=[p], reg_dir=tmp_path / "reg",
                          update=True, key=KEY, emit=False)
        run_dir = Path(self._audited_run(tmp_path, [(p, {"cycle": 1})]))
        manifest = json.loads((run_dir / "manifest.json").read_text())
        blk = manifest["audit"]
        assert blk["passed"] >= 1 and blk["last_verdict"] == "pass"


# ---------------------------------------------------------------------------
# Artifact GC (`report gc --audit-keep`)
# ---------------------------------------------------------------------------


class TestGcAuditFiles:
    def test_battery_artifact_retention(self, tmp_path):
        adir = tmp_path / "runs" / "run_a" / "audit"
        adir.mkdir(parents=True)
        for i in range(6):
            (adir / f"battery_{i:04d}.json").write_text("{}")
        removed = audit.gc_audit_files(tmp_path / "runs", keep=2,
                                       reg_dir=tmp_path / "noreg")
        assert len(removed) == 4
        left = sorted(p.name for p in adir.glob("battery_*.json"))
        assert left == ["battery_0004.json", "battery_0005.json"]

    def test_live_run_untouched(self, tmp_path):
        d = tmp_path / "runs" / "run_live"
        (d / "audit").mkdir(parents=True)
        for i in range(6):
            (d / "audit" / f"battery_{i:04d}.json").write_text("{}")
        (d / "manifest.json").write_text(json.dumps({"status": "running"}))
        removed = audit.gc_audit_files(tmp_path / "runs", keep=2,
                                       reg_dir=tmp_path / "noreg")
        assert removed == []

    def test_archived_goldens_pruned_active_kept(self, tmp_path):
        reg = tmp_path / "reg"
        reg.mkdir()
        (reg / "goldens_abc.json").write_text("{}")
        for i in range(5):
            (reg / f"goldens_abc.{i:03d}.json").write_text("{}")
        removed = audit.gc_audit_files(tmp_path / "noruns", keep=2, reg_dir=reg)
        assert len(removed) == 3
        assert (reg / "goldens_abc.json").is_file()
        assert sorted(p.name for p in reg.glob("goldens_abc.*.json")) == [
            "goldens_abc.003.json", "goldens_abc.004.json"]


# ---------------------------------------------------------------------------
# Env semantics + scheduler
# ---------------------------------------------------------------------------


class TestEnvKnobs:
    def test_enabled_default_off(self, monkeypatch):
        monkeypatch.delenv("SBR_AUDIT", raising=False)
        assert audit.enabled() is False
        monkeypatch.setenv("SBR_AUDIT", "0")
        assert audit.enabled() is False
        monkeypatch.setenv("SBR_AUDIT", "1")
        assert audit.enabled() is True

    def test_interval_and_probe_filter(self, monkeypatch):
        monkeypatch.setenv("SBR_AUDIT_INTERVAL_S", "2.5")
        assert audit.interval_s() == 2.5
        monkeypatch.setenv("SBR_AUDIT_INTERVAL_S", "garbage")
        assert audit.interval_s() == audit.DEFAULT_INTERVAL_S
        monkeypatch.setenv("SBR_AUDIT_PROBES", "a, b,")
        assert audit.probe_filter() == ("a", "b")
        monkeypatch.setenv("SBR_AUDIT_PROBES", "")
        assert audit.probe_filter() is None


class TestScheduler:
    def _goldens(self, reg, probe):
        audit.run_battery(probe_names=[probe], reg_dir=reg, update=True,
                          emit=False)

    def test_cycle_pass_then_drift_latches(self, tmp_path):
        p = const_probe()
        self._goldens(tmp_path, p)
        s = audit.AuditScheduler(engine=None, reg_dir=tmp_path,
                                 interval=3600.0, probe_names=[p])
        s.run_cycle()
        assert s.status == "pass" and s.status_gauge() == 1
        assert s.heartbeat_block()["cycles"] == 1
        faults.install(faults.FaultPlan({
            "seed": 1,
            "rules": [{"point": "audit.canary", "kind": "corrupt"}],
        }))
        try:
            s.run_cycle()
        finally:
            faults.reset()
        assert s.status == "drift" and s.drift_probes == [p.name]
        # Drift LATCHES: a clean cycle after the corruption does not
        # un-flag the worker — restart is the only way back.
        s.run_cycle()
        assert s.status == "drift" and s.status_gauge() == -1

    def test_prometheus_lines(self, tmp_path):
        p = const_probe()
        self._goldens(tmp_path, p)
        s = audit.AuditScheduler(engine=None, reg_dir=tmp_path,
                                 interval=3600.0, probe_names=[p])
        s.run_cycle()
        text = "\n".join(s.prometheus_lines())
        assert "sbr_audit_status 1" in text
        assert "sbr_audit_probe_ms" in text

    def test_cycle_error_recorded_not_raised(self, tmp_path):
        def boom():
            raise RuntimeError("registry on fire")
        # A version-mismatched golden file makes run_battery RAISE (not
        # classify) — the scheduler must swallow it into last_error.
        p = const_probe()
        self._goldens(tmp_path, p)
        path = next(tmp_path.glob("goldens_*.json"))
        doc = json.loads(path.read_text())
        doc["registry_version"] = audit.AUDIT_REGISTRY_VERSION + 1
        path.write_text(json.dumps(doc))
        s = audit.AuditScheduler(engine=None, reg_dir=tmp_path,
                                 interval=3600.0, probe_names=[p])
        assert s.run_cycle() is None
        assert s.status == "pending"
        assert "AuditRegistryVersionError" in (s.snapshot()["last_error"] or "")


# ---------------------------------------------------------------------------
# SBR_AUDIT=0 structural no-op + engine wiring witnesses
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def _engine(self):
        from sbr_tpu.models.params import SolverConfig
        from sbr_tpu.serve.engine import Engine

        return Engine(config=SolverConfig(n_grid=64, bisect_iters=20,
                                          refine_crossings=False))

    def test_off_is_structural_noop(self, monkeypatch):
        import sys

        from sbr_tpu.obs import prof

        monkeypatch.delenv("SBR_AUDIT", raising=False)
        sys.modules.pop("sbr_tpu.obs.audit", None)
        traces_before = sum(prof.trace_counts().values())
        eng = self._engine()
        try:
            eng.start()
            assert eng.audit is None
            # The audit module must not even be imported...
            assert "sbr_tpu.obs.audit" not in sys.modules
            # ...the exposition must be byte-free of audit metrics...
            assert "sbr_audit" not in eng.prometheus()
        finally:
            eng.close()
        # ...and zero new XLA programs traced by constructing the engine.
        assert sum(prof.trace_counts().values()) == traces_before

    def test_on_attaches_scheduler(self, tmp_path, monkeypatch):
        p = const_probe()
        audit.run_battery(probe_names=[p], reg_dir=tmp_path, update=True,
                          emit=False)
        monkeypatch.setenv("SBR_AUDIT", "1")
        monkeypatch.setenv("SBR_AUDIT_REGISTRY_DIR", str(tmp_path))
        monkeypatch.setenv("SBR_AUDIT_INTERVAL_S", "3600")
        monkeypatch.setenv("SBR_AUDIT_PROBES", "graphgen.layout")
        eng = self._engine()
        try:
            eng.start()
            assert eng.audit is not None
            assert "sbr_audit_status" in eng.prometheus()
            # Drift flips /healthz degraded with the audit_drift reason.
            eng.audit.status = "drift"
            eng.audit.drift_probes = ["graphgen.layout"]
            hz = eng.healthz()
            assert hz["status"] == "degraded"
            assert any("audit_drift" in r for r in hz["reasons"])
        finally:
            eng.close()


class TestRouterQuarantine:
    def _beat(self, ann, status):
        ann.beat(audit={
            "status": status, "cycles": 3,
            "drift_probes": ["graphgen.layout"] if status == "drift" else [],
        })

    def test_drifted_heartbeat_quarantines_and_clears(self, tmp_path):
        from sbr_tpu.serve.fleet import WorkerAnnouncer
        from sbr_tpu.serve.router import Router

        ann = WorkerAnnouncer(tmp_path, "http://127.0.0.1:1", host="w0")
        self._beat(ann, "drift")
        router = Router(tmp_path, poll_s=0.01)
        router.refresh_workers(force=True)
        w = router._workers["w0"]
        assert w.quarantined
        assert router._candidates() == []
        # A clean heartbeat (worker restarted) re-admits it.
        self._beat(ann, "pass")
        router.refresh_workers(force=True)
        assert not w.quarantined
        assert len(router._candidates()) == 1

    def test_healthz_reports_quarantine(self, tmp_path):
        from sbr_tpu.serve.fleet import WorkerAnnouncer
        from sbr_tpu.serve.router import Router

        bad = WorkerAnnouncer(tmp_path, "http://127.0.0.1:1", host="w0")
        good = WorkerAnnouncer(tmp_path, "http://127.0.0.1:2", host="w1")
        self._beat(bad, "drift")
        self._beat(good, "pass")
        router = Router(tmp_path, poll_s=0.0)
        doc = router.healthz()
        assert doc["status"] == "degraded"
        assert doc["quarantined"] == 1 and doc["routable"] == 1
        assert any("quarantine" in r for r in doc.get("reasons", []))


# ---------------------------------------------------------------------------
# History schema 11
# ---------------------------------------------------------------------------


class TestHistorySchema11:
    def test_audit_metrics_whitelisted(self):
        from sbr_tpu.obs import history

        assert history.SCHEMA >= 11  # ISSUE 18 bumped to 12 (demand workload)
        out = history.bench_metrics({
            "value": 10.0,
            "extra": {"audit_probes_per_sec": 2.5,
                      "audit_overhead_ratio": 1.02},
        })
        assert out["audit_probes_per_sec"] == 2.5
        assert out["audit_overhead_ratio"] == 1.02

    def test_overhead_polarity_lower_better(self):
        from sbr_tpu.obs import history

        assert history.polarity("audit_overhead_ratio") == -1
        assert history.polarity("audit_probes_per_sec") == 1

    def test_old_schema_lines_still_load(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "perf_history.jsonl"
        rows = [
            {"ts": 1.0, "value": 10.0, "metrics": {"x": 1.0}},  # schema-less
            {"ts": 2.0, "schema": 10, "value": 11.0,
             "metrics": {"infomodel_belief_updates_per_sec": 5.0}},
        ]
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        loaded = history.load(path)
        assert len(loaded) == 2
        assert loaded[0]["schema"] == 1
        assert loaded[1]["schema"] == 10
