"""On-device graph generation + fused infection step (ISSUE 10).

Contracts under test:

- device canonical layout == host canonicalization of the same raw stream
  (bitwise: src, row_ptr, indeg, and the incremental orientation arrays);
- dst-sorted invariants (row_ptr monotone from 0 to E, diffs == indeg,
  sources in range);
- seeded determinism, in-process and CROSS-PROCESS (the stream is keyed by
  numpy SeedSequence words, never by jax PRNG state);
- chunk-plan invariance (the capacity plan affects peak memory and speed,
  never bytes);
- degree-distribution statistics per spec (ER mean degree, scale-free
  heavy tails on BOTH endpoints, SBM within-block fraction);
- sharded generation assembles the same graph as single-device generation
  byte-for-byte (and equals the sharded host prepare of the raw stream);
- fused step == unfused step bitwise, on the CPU lax fallback AND in
  Pallas interpret mode, for both engines and both dtypes — and the
  foldin stream always resolves to the unfused path (no fused lowering
  implements the fold_in draw chain);
- history schema 6 (agents_graph_build_s / agents_graph_gen_edges_per_sec /
  agents_graph_gen_speedup): bench_metrics pickup, polarity, and
  back-compat gating against committed schema 1-5 lines.
"""

import dataclasses
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

from sbr_tpu.social import (
    AgentSimConfig,
    ErdosRenyiSpec,
    ScaleFreeSpec,
    StochasticBlockSpec,
    erdos_renyi_edges,
    prepare_agent_graph,
    prepare_generated_graph,
    simulate_agents,
)
from sbr_tpu.social import agents as A
from sbr_tpu.social import fused, graphgen

REPO = Path(__file__).resolve().parents[1]

SPECS = [
    ErdosRenyiSpec(n=500, avg_degree=6.0),
    ScaleFreeSpec(n=500, avg_degree=6.0, gamma=2.5),
    StochasticBlockSpec(n=500, avg_degree=6.0, n_blocks=4, p_in=0.8),
]


# ---------------------------------------------------------------------------
# Spec validation
# ---------------------------------------------------------------------------


class TestSpecs:
    def test_validation(self):
        with pytest.raises(ValueError, match="n >= 2"):
            ErdosRenyiSpec(n=1, avg_degree=2.0)
        with pytest.raises(ValueError, match="avg_degree"):
            ErdosRenyiSpec(n=10, avg_degree=0.0)
        with pytest.raises(ValueError, match="gamma"):
            ScaleFreeSpec(n=10, avg_degree=2.0, gamma=1.0)
        with pytest.raises(ValueError, match="n_blocks"):
            StochasticBlockSpec(n=10, avg_degree=2.0, n_blocks=1)
        with pytest.raises(ValueError, match="p_in"):
            StochasticBlockSpec(n=10, avg_degree=2.0, p_in=1.5)
        with pytest.raises(ValueError, match="2\\*n_blocks"):
            StochasticBlockSpec(n=4, avg_degree=2.0, n_blocks=3)
        with pytest.raises(ValueError, match="int32"):
            ErdosRenyiSpec(n=2**20, avg_degree=3000.0)

    def test_specs_are_hashable_jit_keys(self):
        assert hash(ErdosRenyiSpec(n=10, avg_degree=2.0)) == hash(
            ErdosRenyiSpec(n=10, avg_degree=2.0)
        )

    def test_edge_count_deterministic(self):
        spec = ErdosRenyiSpec(n=1000, avg_degree=8.0)
        assert spec.edge_count(7) == spec.edge_count(7)
        # the ER count is the host sampler's binomial law, not a constant
        assert spec.edge_count(7) != spec.edge_count(8)


# ---------------------------------------------------------------------------
# Canonical-layout parity vs the host pipeline + dst-sorted invariants
# ---------------------------------------------------------------------------


class TestCanonicalParity:
    @pytest.mark.parametrize("spec", SPECS, ids=lambda s: type(s).__name__)
    def test_device_layout_equals_host_canonicalization(self, spec):
        """The device build's (src, row_ptr, indeg) must be BITWISE the
        host `_canonicalize_graph` of the same raw stream."""
        src, dst = graphgen.generate_edges(spec, seed=3)
        _, src_h, _, indeg_h, row_ptr_h = A._canonicalize_graph(
            1.0, src, dst, spec.n, np.float32
        )
        built = graphgen._SingleBuild(spec, 3, None)
        np.testing.assert_array_equal(np.asarray(built.src_sorted()), src_h)
        np.testing.assert_array_equal(
            np.asarray(built.row_ptr), row_ptr_h.astype(np.int32)
        )
        np.testing.assert_array_equal(
            np.asarray(built.indeg), indeg_h.astype(np.int32)
        )

    @pytest.mark.parametrize("spec", SPECS, ids=lambda s: type(s).__name__)
    def test_dst_sorted_invariants(self, spec):
        built = graphgen._SingleBuild(spec, 5, None)
        row_ptr = np.asarray(built.row_ptr)
        indeg = np.asarray(built.indeg)
        src = np.asarray(built.src_sorted())
        assert row_ptr[0] == 0 and row_ptr[-1] == built.e == len(src)
        assert np.all(np.diff(row_ptr) >= 0)  # monotone
        np.testing.assert_array_equal(np.diff(row_ptr), indeg)
        assert int(indeg.sum()) == built.e
        assert src.min() >= 0 and src.max() < spec.n
        # out-degree census is consistent with the source stream
        np.testing.assert_array_equal(
            np.asarray(built.outdeg), np.bincount(src, minlength=spec.n)
        )

    def test_incremental_orientation_equals_host_prepare(self):
        spec = ScaleFreeSpec(n=400, avg_degree=5.0, gamma=2.3)
        src, dst = graphgen.generate_edges(spec, seed=11)
        pg_d = prepare_generated_graph(spec, seed=11, engine="incremental")
        pg_h = prepare_agent_graph(1.0, src, dst, spec.n, engine="incremental")
        assert pg_d.engine == pg_h.engine == "incremental"
        for a, b in zip(pg_d.inc, pg_h.inc):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(pg_d.src), np.asarray(pg_h.src))

    def test_empty_graph_prepares_as_gather(self):
        spec = ErdosRenyiSpec(n=64, avg_degree=1e-9)
        assert spec.edge_count(0) == 0
        pg = prepare_generated_graph(spec, seed=0, engine="incremental")
        assert pg.engine == "gather" and pg.n_edges == 0

    def test_engine_measure_rejected(self):
        with pytest.raises(ValueError, match="engine"):
            prepare_generated_graph(SPECS[0], seed=0, engine="measure")

    def test_vector_betas_land_in_prepared(self):
        spec = ErdosRenyiSpec(n=100, avg_degree=4.0)
        betas = np.linspace(0.5, 2.0, 100, dtype=np.float32)
        pg = prepare_generated_graph(spec, seed=0, betas=betas)
        np.testing.assert_allclose(np.asarray(pg.betas), betas)


# ---------------------------------------------------------------------------
# Determinism
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_same_seed_bitwise_same_different_seed_differs(self):
        spec = ErdosRenyiSpec(n=600, avg_degree=7.0)
        a = graphgen._SingleBuild(spec, 9, None)
        b = graphgen._SingleBuild(spec, 9, None)
        c = graphgen._SingleBuild(spec, 10, None)
        np.testing.assert_array_equal(
            np.asarray(a.src_sorted()), np.asarray(b.src_sorted())
        )
        assert not np.array_equal(
            np.asarray(a.src_sorted())[: min(a.e, c.e)],
            np.asarray(c.src_sorted())[: min(a.e, c.e)],
        )

    def test_cross_process_bitwise(self):
        """The stream is keyed by numpy SeedSequence words — bit-identical
        across processes regardless of jax PRNG configuration."""
        import hashlib

        spec = ScaleFreeSpec(n=300, avg_degree=5.0, gamma=2.5)
        built = graphgen._SingleBuild(spec, 21, None)
        digest = hashlib.sha256(
            np.asarray(built.src_sorted()).tobytes()
            + np.asarray(built.row_ptr).tobytes()
        ).hexdigest()
        code = (
            "import hashlib, numpy as np\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "from sbr_tpu.social import graphgen\n"
            "spec = graphgen.ScaleFreeSpec(n=300, avg_degree=5.0, gamma=2.5)\n"
            "b = graphgen._SingleBuild(spec, 21, None)\n"
            "print(hashlib.sha256(np.asarray(b.src_sorted()).tobytes()"
            " + np.asarray(b.row_ptr).tobytes()).hexdigest())"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=240,
            env={**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"},
            cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr[-800:]
        assert out.stdout.strip() == digest

    def test_chunk_plan_never_changes_bytes(self):
        spec = StochasticBlockSpec(n=500, avg_degree=6.0, n_blocks=5, p_in=0.7)
        base = graphgen._SingleBuild(spec, 4, None)
        for chunk in (64, 97, 4096):
            other = graphgen._SingleBuild(spec, 4, chunk)
            np.testing.assert_array_equal(
                np.asarray(base.src_sorted()), np.asarray(other.src_sorted())
            )
            np.testing.assert_array_equal(
                np.asarray(base.inc_arrays()[0]), np.asarray(other.inc_arrays()[0])
            )


# ---------------------------------------------------------------------------
# Capacity plan
# ---------------------------------------------------------------------------


class TestChunkPlan:
    def test_deterministic_power_of_two_with_floor_and_cap(self):
        c = graphgen.plan_chunk_edges(10**8, 10**7, budget_bytes=1 << 30)
        assert c == graphgen.plan_chunk_edges(10**8, 10**7, budget_bytes=1 << 30)
        assert c & (c - 1) == 0  # power of two
        # starving the budget floors at 2^14, never below
        assert graphgen.plan_chunk_edges(10**8, 10**7, budget_bytes=1) == 1 << 14
        # a tiny graph caps at E
        assert graphgen.plan_chunk_edges(100, 50, budget_bytes=1 << 30) == 100

    def test_budget_monotone(self):
        small = graphgen.plan_chunk_edges(10**8, 10**6, budget_bytes=1 << 28)
        large = graphgen.plan_chunk_edges(10**8, 10**6, budget_bytes=1 << 32)
        assert large >= small

    def test_env_budget_respected(self, monkeypatch):
        monkeypatch.setenv("SBR_GRAPHGEN_BUDGET_BYTES", str(1 << 24))
        assert graphgen.plan_chunk_edges(10**8, 10**6) == graphgen.plan_chunk_edges(
            10**8, 10**6, budget_bytes=1 << 24
        )


# ---------------------------------------------------------------------------
# Degree statistics per generative model
# ---------------------------------------------------------------------------


class TestDegreeStats:
    def test_er_mean_degree(self):
        spec = ErdosRenyiSpec(n=20_000, avg_degree=8.0)
        src, dst = graphgen.generate_edges(spec, seed=1)
        indeg = np.bincount(dst, minlength=spec.n)
        outdeg = np.bincount(src, minlength=spec.n)
        assert abs(indeg.mean() - 8.0) < 0.4
        assert abs(outdeg.mean() - 8.0) < 0.4
        # Poisson-like spread, not degenerate: var ≈ mean for ER
        assert 0.5 * 8.0 < indeg.var() < 2.0 * 8.0

    def test_scale_free_heavy_tails_both_endpoints(self):
        spec = ScaleFreeSpec(n=20_000, avg_degree=8.0, gamma=2.2)
        src, dst = graphgen.generate_edges(spec, seed=1)
        indeg = np.bincount(dst, minlength=spec.n)
        outdeg = np.bincount(src, minlength=spec.n)
        # hubs: the max degree dwarfs the mean on BOTH orientations
        # (in-degree drives the learning dynamics — it must be heavy)
        assert indeg.max() > 20 * indeg.mean()
        assert outdeg.max() > 20 * outdeg.mean()
        # weights are (i+1)^{-1/(gamma-1)}: node 0 is the heaviest hub
        assert indeg[0] > 100
        er = np.bincount(
            graphgen.generate_edges(ErdosRenyiSpec(n=20_000, avg_degree=8.0), 1)[1],
            minlength=20_000,
        )
        # top-1% mass far exceeds ER's at the same mean degree
        k = 200
        sf_top = np.sort(indeg)[-k:].sum() / indeg.sum()
        er_top = np.sort(er)[-k:].sum() / er.sum()
        assert sf_top > 3 * er_top

    def test_sbm_within_block_fraction(self):
        spec = StochasticBlockSpec(
            n=20_000, avg_degree=8.0, n_blocks=4, p_in=0.8
        )
        src, dst = graphgen.generate_edges(spec, seed=1)
        block = np.minimum(src * spec.n_blocks // spec.n, spec.n_blocks - 1)
        block_d = np.minimum(dst * spec.n_blocks // spec.n, spec.n_blocks - 1)
        within = float(np.mean(block == block_d))
        assert abs(within - 0.8) < 0.02
        assert not np.any(src == dst)  # SBM rewires in-block self-loops


# ---------------------------------------------------------------------------
# Sharded generation
# ---------------------------------------------------------------------------


class TestShardedGeneration:
    def test_sharded_equals_single_device_and_host(self):
        """Each device generates only its position range; the assembled
        graph is byte-identical to the single-device build (positions are
        pure functions of (seed, edge id)) and to the sharded host prepare
        of the same raw stream."""
        mesh = jax.make_mesh((8,), ("agents",))
        spec = ErdosRenyiSpec(n=640, avg_degree=6.0)
        built = graphgen._SingleBuild(spec, 3, None)
        src, dst = graphgen.generate_edges(spec, seed=3)
        for eng in ("gather", "incremental"):
            pg_d = prepare_generated_graph(spec, seed=3, mesh=mesh, engine=eng)
            pg_h = prepare_agent_graph(1.0, src, dst, spec.n, mesh=mesh, engine=eng)
            np.testing.assert_array_equal(
                np.asarray(pg_d.src), np.asarray(pg_h.src), err_msg=eng
            )
            np.testing.assert_array_equal(
                np.asarray(pg_d.row_ptr), np.asarray(pg_h.row_ptr), err_msg=eng
            )
            np.testing.assert_array_equal(
                np.asarray(pg_d.indeg), np.asarray(pg_h.indeg), err_msg=eng
            )
            # the global concatenation's valid prefix IS the single-device
            # canonical stream
            np.testing.assert_array_equal(
                np.asarray(pg_d.src).ravel()[: built.e],
                np.asarray(built.src_sorted()),
                err_msg=eng,
            )
            if eng == "incremental":
                for a, b in zip(pg_d.inc, pg_h.inc):
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sharded_simulation_matches_single_device(self):
        """The full chain: generated sharded graph + fused sharded step ==
        generated single-device graph + fused single-device step, bitwise
        (the global-agent-id RNG invariance carries through graphgen)."""
        mesh = jax.make_mesh((8,), ("agents",))
        spec = ErdosRenyiSpec(n=640, avg_degree=6.0)
        cfg = AgentSimConfig(n_steps=30, dt=0.1)
        pg1 = prepare_generated_graph(spec, seed=3, engine="gather", config=cfg)
        pg8 = prepare_generated_graph(
            spec, seed=3, mesh=mesh, engine="gather", config=cfg
        )
        r1 = simulate_agents(prepared=pg1, x0=0.02, config=cfg, seed=5)
        r8 = simulate_agents(prepared=pg8, x0=0.02, config=cfg, seed=5)
        np.testing.assert_array_equal(
            np.asarray(r1.informed), np.asarray(r8.informed)[: spec.n]
        )


# ---------------------------------------------------------------------------
# Fused infection step
# ---------------------------------------------------------------------------


class TestFusedStep:
    def _graph(self, n=800, seed=2):
        return erdos_renyi_edges(n, 6.0, seed=seed)

    @pytest.mark.parametrize("engine", ["gather", "incremental"])
    @pytest.mark.parametrize("mode", ["lax", "interpret"])
    def test_bitwise_parity_vs_unfused(self, engine, mode):
        n = 800
        src, dst = self._graph(n)
        base_cfg = AgentSimConfig(n_steps=40, dt=0.1, fused="unfused")
        want = simulate_agents(
            1.2, src, dst, n, x0=0.02, config=base_cfg, seed=7, engine=engine
        )
        got = simulate_agents(
            1.2, src, dst, n, x0=0.02,
            config=dataclasses.replace(base_cfg, fused=mode), seed=7,
            engine=engine,
        )
        np.testing.assert_array_equal(
            np.asarray(want.informed), np.asarray(got.informed)
        )
        np.testing.assert_array_equal(np.asarray(want.t_inf), np.asarray(got.t_inf))
        np.testing.assert_array_equal(
            np.asarray(want.informed_frac), np.asarray(got.informed_frac)
        )

    def test_bitwise_parity_f64_lax_and_interpret(self):
        n = 400
        src, dst = self._graph(n)
        res = {}
        for mode in ("unfused", "lax", "interpret"):
            cfg = AgentSimConfig(n_steps=25, dt=0.1, fused=mode)
            res[mode] = simulate_agents(
                1.0, src, dst, n, x0=0.02, config=cfg, seed=3, dtype=np.float64
            )
        for mode in ("lax", "interpret"):
            np.testing.assert_array_equal(
                np.asarray(res["unfused"].informed), np.asarray(res[mode].informed),
                err_msg=mode,
            )
            np.testing.assert_array_equal(
                np.asarray(res["unfused"].t_inf), np.asarray(res[mode].t_inf),
                err_msg=mode,
            )

    def test_foldin_stream_is_untouched_by_fusion(self):
        """Every fused lowering computes the counter draw; the foldin
        stream must resolve to unfused under ANY requested mode (the 0.8.0
        regression guard: a fused-lax foldin run must not silently become
        the counter stream)."""
        n = 400
        src, dst = self._graph(n)
        want = simulate_agents(
            1.0, src, dst, n, x0=0.02,
            config=AgentSimConfig(n_steps=25, dt=0.1, rng_stream="foldin",
                                  fused="unfused"),
            seed=3,
        )
        for mode in ("auto", "lax", "interpret"):
            got = simulate_agents(
                1.0, src, dst, n, x0=0.02,
                config=AgentSimConfig(n_steps=25, dt=0.1, rng_stream="foldin",
                                      fused=mode),
                seed=3,
            )
            np.testing.assert_array_equal(
                np.asarray(want.informed), np.asarray(got.informed), err_msg=mode
            )
            np.testing.assert_array_equal(
                np.asarray(want.t_inf), np.asarray(got.t_inf), err_msg=mode
            )

    def test_resolve_mode_contract(self, monkeypatch):
        monkeypatch.delenv("SBR_FUSED", raising=False)
        # CPU backend: auto → lax (tier-1 semantics unchanged by construction)
        assert fused.resolve_mode("auto", np.float32, "counter") == "lax"
        # no fused lowering implements the foldin draw chain
        for mode in ("auto", "lax", "pallas", "interpret"):
            assert fused.resolve_mode(mode, np.float32, "foldin") == "unfused"
        # compiled TPU Pallas lacks uint64 words; the interpreter keeps f64
        assert fused.resolve_mode("pallas", np.float64, "counter") == "lax"
        assert fused.resolve_mode("interpret", np.float64, "counter") == "interpret"
        assert fused.resolve_mode("unfused", np.float32, "counter") == "unfused"
        with pytest.raises(ValueError, match="fused"):
            fused.resolve_mode("vectorized", np.float32, "counter")
        monkeypatch.setenv("SBR_FUSED", "unfused")
        assert fused.resolve_mode("auto", np.float32, "counter") == "unfused"
        # a typo'd override must raise, not fall through to the default
        monkeypatch.setenv("SBR_FUSED", "palas")
        with pytest.raises(ValueError, match="SBR_FUSED"):
            fused.resolve_mode("auto", np.float32, "counter")

    def test_config_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="fused"):
            AgentSimConfig(fused="simd")


# ---------------------------------------------------------------------------
# Closure-loop integration (graph= spec path)
# ---------------------------------------------------------------------------


class TestCloseLoopGraph:
    def test_spec_mismatch_raises(self):
        from sbr_tpu.social import close_loop

        with pytest.raises(ValueError, match="n_agents"):
            close_loop(
                n_agents=1000, graph=ErdosRenyiSpec(n=999, avg_degree=15.0),
                t_max=4.0,
            )

    @pytest.mark.slow
    def test_generated_graph_closes_loop(self):
        """A device-generated ER graph closes the Stage 1-3 loop within
        the same tolerance envelope as the host-sampled path (different,
        equally valid realization of the same model)."""
        from sbr_tpu.social import close_loop

        host = close_loop(n_agents=20_000, avg_degree=15.0, dt=0.05, t_max=16.0)
        dev = close_loop(
            n_agents=20_000, avg_degree=15.0, dt=0.05, t_max=16.0,
            graph=ErdosRenyiSpec(n=20_000, avg_degree=15.0),
        )
        assert np.isfinite(dev.err_aw_sup)
        # same MC scale as the host-sampled realization at this shape...
        assert dev.err_aw_rms < 2.0 * host.err_aw_rms + 0.01
        assert dev.err_g_rms < 2.0 * host.err_g_rms + 0.01
        # ...and absolutely small against the mean-field curves
        assert dev.err_aw_sup < 0.1


# ---------------------------------------------------------------------------
# History schema 6
# ---------------------------------------------------------------------------


class TestHistorySchema6:
    def test_bench_metrics_pick_up_graphgen_columns(self):
        from sbr_tpu.obs import history

        m = history.bench_metrics(
            {
                "metric": "eq_per_sec",
                "value": 1.0,
                "extra": {
                    "agents_graph_build_s": 4.2,
                    "agents_graph_gen_edges_per_sec": 2.4e7,
                    "agents_graph_gen_speedup": 6.5,
                },
            }
        )
        assert m["agents_graph_build_s"] == 4.2
        assert m["agents_graph_gen_edges_per_sec"] == 2.4e7
        assert m["agents_graph_gen_speedup"] == 6.5

    def test_polarity(self):
        from sbr_tpu.obs import history

        assert history.polarity("agents_graph_build_s") == -1
        assert history.polarity("agents_graph_gen_edges_per_sec") == 1
        assert history.polarity("agents_graph_gen_speedup") == 1

    def test_schema6_gates_against_schema1_to_5(self, tmp_path):
        """Committed schema 1-5 lines still load, and a schema-6 append
        gates its shared metrics against them (the CI trend gate
        contract)."""
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        rows = [
            {"ts": "t0", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1000.0}},  # schema-less → 1
            {"schema": 2, "ts": "t1", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1010.0, "mem_peak_bytes": 5000}},
            {"schema": 3, "ts": "t2", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1005.0, "serve_p99_ms": 4.0}},
            {"schema": 4, "ts": "t3", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1002.0, "sweep_warm_hit_rate": 1.0}},
            {"schema": 5, "ts": "t4", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1004.0, "grid_adaptive_speedup": 2.2}},
        ]
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        history.append(
            {"eq_per_sec": 1008.0, "agents_graph_build_s": 4.0,
             "agents_graph_gen_edges_per_sec": 2.0e7,
             "agents_graph_gen_speedup": 6.0},
            platform="cpu", path=path,
        )
        records = history.load(path)
        assert [r["schema"] for r in records] == [1, 2, 3, 4, 5, history.SCHEMA]
        verdicts, status = history.check(records, min_points=3)
        assert status == "ok"
        assert verdicts["eq_per_sec"]["n"] == 6
        # new columns are short, never a false gate
        assert verdicts["agents_graph_gen_edges_per_sec"]["status"] == "short"

    def test_generation_regression_gates(self, tmp_path):
        from sbr_tpu.obs import history

        rows = [
            {"schema": 6, "ts": f"t{i}", "label": "bench", "platform": "cpu",
             "metrics": {"agents_graph_gen_edges_per_sec": 2.0e7}}
            for i in range(3)
        ] + [
            {"schema": 6, "ts": "t9", "label": "bench", "platform": "cpu",
             "metrics": {"agents_graph_gen_edges_per_sec": 1.0e7}}
        ]
        path = tmp_path / "hist.jsonl"
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        verdicts, status = history.check(history.load(path), min_points=3)
        assert status == "regression"
        assert verdicts["agents_graph_gen_edges_per_sec"]["status"] == "regression"
