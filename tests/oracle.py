"""Independent high-precision CPU oracle for the baseline pipeline.

A deliberately boring scipy implementation of the same mathematics the
reference solves (closed-form logistic Stage 1, adaptive quadrature for the
hazard normalization `src/baseline/solver.jl:172-182`, brentq root-finding for
buffers `solver.jl:211-264` and for ξ `solver.jl:308-376`). Accuracy ~1e-10,
so agreement of the TPU framework with this oracle to 1e-6 is the BASELINE.md
CPU-match criterion without needing a Julia runtime in the image.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.integrate import quad
from scipy.optimize import brentq


def G(t, beta, x0):
    return x0 / (x0 + (1.0 - x0) * np.exp(-beta * np.asarray(t, dtype=float)))


def g(t, beta, x0):
    Gt = G(t, beta, x0)
    return beta * Gt * (1.0 - Gt)


@dataclasses.dataclass
class OracleSolution:
    xi: float
    tau_bar_in: float
    tau_bar_out: float
    bankrun: bool
    aw_max: float
    hr_max: float


def hazard_fn(p, lam, beta, x0, eta):
    """Returns h(τ̄) as a callable using adaptive quadrature."""

    def eg(s):
        return np.exp(lam * s) * g(s, beta, x0)

    int_eta = quad(eg, 0.0, eta, limit=200)[0]

    def h(tau):
        i = quad(eg, 0.0, tau, limit=200)[0]
        return (p * np.exp(lam * tau) * g(tau, beta, x0)) / (p * i + (1.0 - p) * int_eta)

    return h


def solve_oracle(beta=1.0, x0=1e-4, u=0.1, p=0.5, kappa=0.6, lam=0.01, eta=15.0, tspan_end=None, n_scan=4000):
    """Full baseline solve: hazard crossings -> buffers -> ξ -> AW_max."""
    if tspan_end is None:
        tspan_end = 2.0 * eta
    h = hazard_fn(p, lam, beta, x0, eta)

    taus = np.linspace(0.0, eta, n_scan)
    hvals = np.array([h(t) for t in taus])
    above = hvals > u

    if not above.any():
        return OracleSolution(np.nan, tspan_end, tspan_end, False, np.nan, hvals.max())

    # first up-crossing
    up = np.where(~above[:-1] & above[1:])[0]
    if len(up):
        i = up[0]
        tau_in = brentq(lambda t: h(t) - u, taus[i], taus[i + 1], xtol=1e-13)
    else:
        tau_in = taus[np.argmax(above)]
    # last down-crossing
    dn = np.where(above[:-1] & ~above[1:])[0]
    if len(dn):
        i = dn[-1]
        tau_out = brentq(lambda t: h(t) - u, taus[i], taus[i + 1], xtol=1e-13)
    else:
        tau_out = taus[len(above) - 1 - np.argmax(above[::-1])]

    if tau_in == tau_out:
        return OracleSolution(np.nan, tau_in, tau_out, False, np.nan, hvals.max())

    def aw(xi):
        return G(min(xi, tau_out), beta, x0) - G(min(xi, tau_in), beta, x0) - kappa

    if aw(tau_in) * aw(tau_out) > 0:
        return OracleSolution(np.nan, tau_in, tau_out, False, np.nan, hvals.max())

    xi = brentq(aw, tau_in, tau_out, xtol=1e-14)

    # first-crossing (slope) validation: withdrawal-path slope at ξ
    slope = g(min(xi, tau_out), beta, x0) - g(min(xi, tau_in), beta, x0)
    if slope < 0:
        return OracleSolution(np.nan, tau_in, tau_out, False, np.nan, hvals.max())

    # AW_max over the [0, eta] grid (reference evaluates on the HR grid,
    # `solver.jl:495-532`)
    tgrid = np.linspace(0.0, eta, 20001)
    t_in_con = min(tau_in, xi)
    t_out_con = min(tau_out, xi)
    s_in = tgrid - xi + t_in_con
    aw_in = np.where(s_in >= 0, G(np.maximum(s_in, 0.0), beta, x0), 0.0)
    s_out = tgrid - xi + t_out_con
    aw_out = np.where(s_out >= 0, G(np.maximum(s_out, 0.0), beta, x0), 0.0)
    aw_cum = aw_out - aw_in + G(0.0, beta, x0)
    return OracleSolution(xi, tau_in, tau_out, True, aw_cum.max(), hvals.max())


# ---------------------------------------------------------------------------
# Heterogeneity oracle (reference `src/extensions/heterogeneity/`)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OracleHeteroSolution:
    xi: float
    tau_bar_ins: np.ndarray
    tau_bar_outs: np.ndarray
    bankrun: bool
    cdfs: object  # callable t -> (K,)


def solve_hetero_learning_oracle(betas, dist, x0, tspan):
    """Coupled SI ODE dG_k = (1-G_k)·β_k·(dist·G) via scipy with dense output
    (`heterogeneity_learning.jl:49-94`)."""
    from scipy.integrate import solve_ivp

    betas = np.asarray(betas, dtype=float)
    dist = np.asarray(dist, dtype=float)

    def rhs(t, G):
        omega = dist @ G
        return (1.0 - G) * betas * omega

    sol = solve_ivp(
        rhs,
        tspan,
        np.full(len(betas), x0),
        method="LSODA",
        rtol=1e-12,
        atol=1e-14,
        dense_output=True,
    )
    cdfs = sol.sol

    def pdfs(t):
        Gt = np.clip(cdfs(t), 0.0, 1.0)
        return (1.0 - Gt) * betas * (dist @ Gt)

    return cdfs, pdfs


def solve_hetero_oracle(betas, dist, x0=1e-4, u=0.1, p=0.9, kappa=0.3, lam=0.1, eta_bar=30.0, n_scan=4000):
    """Full heterogeneity pipeline: per-group hazard/buffers, weighted-AW root
    at the FIRST up-crossing (`heterogeneity_solver.jl:48-210`)."""
    betas = np.asarray(betas, dtype=float)
    dist = np.asarray(dist, dtype=float)
    K = len(betas)
    beta_ave = float(betas @ dist)
    eta = eta_bar / beta_ave
    tspan = (0.0, 2.0 * eta)
    cdfs, pdfs = solve_hetero_learning_oracle(betas, dist, x0, tspan)

    taus = np.linspace(0.0, eta, n_scan)
    tau_ins = np.full(K, tspan[1])
    tau_outs = np.full(K, tspan[1])
    for k in range(K):
        def eg(s, k=k):
            return np.exp(lam * s) * pdfs(s)[k]

        int_eta = quad(eg, 0.0, eta, limit=400)[0]

        def h(tau, k=k, int_eta=int_eta, eg=eg):
            i = quad(eg, 0.0, tau, limit=400)[0]
            return (p * np.exp(lam * tau) * pdfs(tau)[k]) / (p * i + (1.0 - p) * int_eta)

        hvals = np.array([h(t) for t in taus])
        above = hvals > u
        if not above.any():
            continue
        up = np.where(~above[:-1] & above[1:])[0]
        if len(up):
            i = up[0]
            tau_ins[k] = brentq(lambda t: h(t) - u, taus[i], taus[i + 1], xtol=1e-13)
        else:
            tau_ins[k] = taus[np.argmax(above)]
        dn = np.where(above[:-1] & ~above[1:])[0]
        if len(dn):
            i = dn[-1]
            tau_outs[k] = brentq(lambda t: h(t) - u, taus[i], taus[i + 1], xtol=1e-13)
        else:
            tau_outs[k] = taus[len(above) - 1 - np.argmax(above[::-1])]

    if np.all(tau_ins == tau_outs):
        return OracleHeteroSolution(np.nan, tau_ins, tau_outs, False, cdfs)

    def aw(xi):
        t_out = np.minimum(tau_outs, xi)
        t_in = np.minimum(tau_ins, xi)
        per = np.array([cdfs(t_out[k])[k] - cdfs(t_in[k])[k] for k in range(K)])
        return float(dist @ per) - kappa

    # First up-crossing of AW(ξ)=κ in [0, 2·max τ̄_OUT] — the root the
    # reference's first-crossing validation accepts.
    xis = np.linspace(0.0, 2.0 * tau_outs.max(), 8000)
    vals = np.array([aw(x) for x in xis])
    up = np.where((vals[:-1] < 0) & (vals[1:] >= 0))[0]
    if len(up) == 0:
        return OracleHeteroSolution(np.nan, tau_ins, tau_outs, False, cdfs)
    i = up[0]
    xi = brentq(aw, xis[i], xis[i + 1], xtol=1e-13)
    return OracleHeteroSolution(xi, tau_ins, tau_outs, True, cdfs)


# ---------------------------------------------------------------------------
# Interest-rate oracle (reference `src/extensions/interest_rates/`)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OracleInterestSolution:
    xi: float
    tau_bar_in: float
    tau_bar_out: float
    bankrun: bool
    v_at: object  # callable τ̄ -> V


def solve_interest_oracle(
    beta=1.0, x0=1e-4, u=0.0, p=0.5, kappa=0.6, lam=0.01, eta=15.0, r=0.06, delta=0.1, n_scan=4000
):
    """HJB value function + effective-hazard pipeline
    (`value_function_solver.jl:66-112`, `interest_rate_solver.jl:51-150`)."""
    from scipy.integrate import solve_ivp

    tspan_end = 2.0 * eta
    h = hazard_fn(p, lam, beta, x0, eta)

    def hjb(tau, V):
        ht = h(tau)
        return (ht + delta) * (1.0 - V[0]) + max(u + r * V[0] - ht, 0.0)

    v0 = (u + delta) / (r + delta)
    sol = solve_ivp(
        hjb, (0.0, eta), [v0], method="LSODA", rtol=1e-11, atol=1e-13, dense_output=True
    )
    v_at = lambda t: float(sol.sol(t)[0])

    def h_eff(tau):
        return h(tau) - r * v_at(tau)

    taus = np.linspace(0.0, eta, n_scan)
    hvals = np.array([h_eff(t) for t in taus])
    above = hvals > u
    if not above.any():
        return OracleInterestSolution(np.nan, tspan_end, tspan_end, False, v_at)

    up = np.where(~above[:-1] & above[1:])[0]
    if len(up):
        i = up[0]
        tau_in = brentq(lambda t: h_eff(t) - u, taus[i], taus[i + 1], xtol=1e-13)
    else:
        tau_in = taus[np.argmax(above)]
    dn = np.where(above[:-1] & ~above[1:])[0]
    if len(dn):
        i = dn[-1]
        tau_out = brentq(lambda t: h_eff(t) - u, taus[i], taus[i + 1], xtol=1e-13)
    else:
        tau_out = taus[len(above) - 1 - np.argmax(above[::-1])]

    if tau_in == tau_out:
        return OracleInterestSolution(np.nan, tau_in, tau_out, False, v_at)

    def aw(xi):
        return G(min(xi, tau_out), beta, x0) - G(min(xi, tau_in), beta, x0) - kappa

    if aw(tau_in) * aw(tau_out) > 0:
        return OracleInterestSolution(np.nan, tau_in, tau_out, False, v_at)
    xi = brentq(aw, tau_in, tau_out, xtol=1e-14)
    return OracleInterestSolution(xi, tau_in, tau_out, True, v_at)


# ---------------------------------------------------------------------------
# Social-learning fixed-point oracle (reference
# `src/extensions/social_learning/social_learning_solver.jl:63-263`)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OracleSocialSolution:
    xi: float
    bankrun: bool
    converged: bool
    iterations: int
    aw: np.ndarray  # final AW samples on grid
    grid: np.ndarray
    aw_max: float


def _np_cumtrapz(y, dx):
    from scipy.integrate import cumulative_trapezoid

    return cumulative_trapezoid(y, dx=dx, initial=0.0)


def solve_social_oracle(
    beta=0.9, x0=1e-4, u=0.5, p=0.99, kappa=0.25, lam=0.25, eta=30.0 / 0.9,
    tol=1e-4, max_iter=500, n=16384,
):
    """Independent numpy mirror of the damped fixed point: forced learning in
    closed form, trapezoid hazard, brentq for buffers and xi, the no-run
    xi + eta/500 fallback, sup-norm convergence on the undamped candidate,
    alpha = 0.5 damping."""
    t = np.linspace(0.0, eta, n)
    dx = t[1] - t[0]
    aw = G(t, beta, x0)  # word-of-mouth init
    xi = 0.0
    converged = False
    bankrun = False
    it = 0
    for it in range(1, max_iter + 1):
        aw_old = aw.copy()
        big_a = _np_cumtrapz(aw_old, dx)
        cdf = 1.0 - (1.0 - x0) * np.exp(-beta * big_a)
        pdf = (1.0 - cdf) * beta * aw_old

        eg = np.exp(lam * t) * pdf
        integ = _np_cumtrapz(eg, dx)
        hr = (p * eg) / (p * integ + (1.0 - p) * integ[-1])

        def h_of(tau):
            return np.interp(tau, t, hr)

        above = hr > u
        bankrun = False
        tau_in = tau_out = eta
        if above.any():
            up = np.where(~above[:-1] & above[1:])[0]
            if len(up):
                i = up[0]
                tau_in = brentq(lambda s: h_of(s) - u, t[i], t[i + 1], xtol=1e-13)
            else:
                tau_in = t[np.argmax(above)]
            dn = np.where(above[:-1] & ~above[1:])[0]
            if len(dn):
                i = dn[-1]
                tau_out = brentq(lambda s: h_of(s) - u, t[i], t[i + 1], xtol=1e-13)
            else:
                tau_out = t[len(above) - 1 - np.argmax(above[::-1])]

        def G_of(s):
            return np.interp(s, t, cdf)

        if tau_in != tau_out:
            def aw_err(x):
                return G_of(min(x, tau_out)) - G_of(min(x, tau_in)) - kappa

            if aw_err(tau_in) * aw_err(tau_out) <= 0:
                xi_c = brentq(aw_err, tau_in, tau_out, xtol=1e-14)
                eps = dx
                a0 = G_of(min(xi_c, tau_out)) - G_of(min(xi_c, tau_in))
                a1 = G_of(min(xi_c, tau_out) + eps) - G_of(min(xi_c, tau_in) + eps)
                if a1 >= a0:
                    bankrun = True
                    xi = xi_c

        if not bankrun:
            xi = xi + eta / 500.0
            if xi > eta:
                break

        t_in_con = min(tau_in, xi)
        t_out_con = min(tau_out, xi)
        s_in = t - xi + t_in_con
        aw_in = np.where(s_in >= 0, G_of(np.maximum(s_in, 0.0)), 0.0)
        s_out = t - xi + t_out_con
        aw_out = np.where(s_out >= 0, G_of(np.maximum(s_out, 0.0)), 0.0)
        aw_new = aw_out - aw_in + G_of(0.0)

        err = np.max(np.abs(aw_new - aw_old))
        if err < tol:
            aw = aw_new
            converged = True
            break
        aw = 0.5 * aw_old + 0.5 * aw_new

    return OracleSocialSolution(
        xi=xi, bankrun=bankrun, converged=converged, iterations=it,
        aw=aw, grid=t, aw_max=float(np.nanmax(aw)),
    )
