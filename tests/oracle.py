"""Independent high-precision CPU oracle for the baseline pipeline.

A deliberately boring scipy implementation of the same mathematics the
reference solves (closed-form logistic Stage 1, adaptive quadrature for the
hazard normalization `src/baseline/solver.jl:172-182`, brentq root-finding for
buffers `solver.jl:211-264` and for ξ `solver.jl:308-376`). Accuracy ~1e-10,
so agreement of the TPU framework with this oracle to 1e-6 is the BASELINE.md
CPU-match criterion without needing a Julia runtime in the image.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.integrate import quad
from scipy.optimize import brentq


def G(t, beta, x0):
    return x0 / (x0 + (1.0 - x0) * np.exp(-beta * np.asarray(t, dtype=float)))


def g(t, beta, x0):
    Gt = G(t, beta, x0)
    return beta * Gt * (1.0 - Gt)


@dataclasses.dataclass
class OracleSolution:
    xi: float
    tau_bar_in: float
    tau_bar_out: float
    bankrun: bool
    aw_max: float
    hr_max: float


def hazard_fn(p, lam, beta, x0, eta):
    """Returns h(τ̄) as a callable using adaptive quadrature."""

    def eg(s):
        return np.exp(lam * s) * g(s, beta, x0)

    int_eta = quad(eg, 0.0, eta, limit=200)[0]

    def h(tau):
        i = quad(eg, 0.0, tau, limit=200)[0]
        return (p * np.exp(lam * tau) * g(tau, beta, x0)) / (p * i + (1.0 - p) * int_eta)

    return h


def solve_oracle(beta=1.0, x0=1e-4, u=0.1, p=0.5, kappa=0.6, lam=0.01, eta=15.0, tspan_end=None, n_scan=4000):
    """Full baseline solve: hazard crossings -> buffers -> ξ -> AW_max."""
    if tspan_end is None:
        tspan_end = 2.0 * eta
    h = hazard_fn(p, lam, beta, x0, eta)

    taus = np.linspace(0.0, eta, n_scan)
    hvals = np.array([h(t) for t in taus])
    above = hvals > u

    if not above.any():
        return OracleSolution(np.nan, tspan_end, tspan_end, False, np.nan, hvals.max())

    # first up-crossing
    up = np.where(~above[:-1] & above[1:])[0]
    if len(up):
        i = up[0]
        tau_in = brentq(lambda t: h(t) - u, taus[i], taus[i + 1], xtol=1e-13)
    else:
        tau_in = taus[np.argmax(above)]
    # last down-crossing
    dn = np.where(above[:-1] & ~above[1:])[0]
    if len(dn):
        i = dn[-1]
        tau_out = brentq(lambda t: h(t) - u, taus[i], taus[i + 1], xtol=1e-13)
    else:
        tau_out = taus[len(above) - 1 - np.argmax(above[::-1])]

    if tau_in == tau_out:
        return OracleSolution(np.nan, tau_in, tau_out, False, np.nan, hvals.max())

    def aw(xi):
        return G(min(xi, tau_out), beta, x0) - G(min(xi, tau_in), beta, x0) - kappa

    if aw(tau_in) * aw(tau_out) > 0:
        return OracleSolution(np.nan, tau_in, tau_out, False, np.nan, hvals.max())

    xi = brentq(aw, tau_in, tau_out, xtol=1e-14)

    # first-crossing (slope) validation: withdrawal-path slope at ξ
    slope = g(min(xi, tau_out), beta, x0) - g(min(xi, tau_in), beta, x0)
    if slope < 0:
        return OracleSolution(np.nan, tau_in, tau_out, False, np.nan, hvals.max())

    # AW_max over the [0, eta] grid (reference evaluates on the HR grid,
    # `solver.jl:495-532`)
    tgrid = np.linspace(0.0, eta, 20001)
    t_in_con = min(tau_in, xi)
    t_out_con = min(tau_out, xi)
    s_in = tgrid - xi + t_in_con
    aw_in = np.where(s_in >= 0, G(np.maximum(s_in, 0.0), beta, x0), 0.0)
    s_out = tgrid - xi + t_out_con
    aw_out = np.where(s_out >= 0, G(np.maximum(s_out, 0.0), beta, x0), 0.0)
    aw_cum = aw_out - aw_in + G(0.0, beta, x0)
    return OracleSolution(xi, tau_in, tau_out, True, aw_cum.max(), hvals.max())
