"""Tests for the elastic sweep scheduler (`sbr_tpu.resilience.elastic`):
heartbeat membership, the deterministic throughput-weighted claim plan,
the cross-run global tile cache, the elastic multihost driver, the
`report elastic` gate, and the gc satellites (ISSUE 8)."""

import json
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.resilience import elastic, shutdown
from sbr_tpu.utils import run_tiled_grid

CFG = SolverConfig(n_grid=96, bisect_iters=40)
BETAS = np.linspace(0.5, 2.0, 4)
US = np.linspace(0.05, 0.5, 4)


# ---------------------------------------------------------------------------
# Membership: heartbeats
# ---------------------------------------------------------------------------


class TestHeartbeat:
    def test_announce_live_withdraw(self, tmp_path):
        hb = elastic.Heartbeat(tmp_path, host="h1", ttl_s=60.0)
        hb.beat(tiles_done=3, cells_per_sec=12.5)
        hosts = elastic.live_hosts(tmp_path)
        assert hosts["h1"]["tiles_done"] == 3
        assert hosts["h1"]["cells_per_sec"] == 12.5
        hb.withdraw()
        assert elastic.live_hosts(tmp_path) == {}
        # withdraw also unregisters from the shutdown release registry
        assert str(hb.path) not in shutdown._RELEASE_REGISTRY

    def test_ttl_expiry_and_torn_write(self, tmp_path):
        hb = elastic.Heartbeat(tmp_path, host="h1", ttl_s=10.0)
        hb.beat()
        # Exactly at TTL: dead (>=, matching the lease boundary semantics).
        rec = json.loads(hb.path.read_text())
        assert elastic.live_hosts(tmp_path, now=rec["ts"] + 10.0) == {}
        assert "h1" in elastic.live_hosts(tmp_path, now=rec["ts"] + 9.999)
        # A torn heartbeat counts as dead, not a crash.
        hb.path.write_text("{torn")
        assert elastic.live_hosts(tmp_path) == {}
        hb.withdraw()

    def test_heartbeat_released_on_graceful_shutdown(self, tmp_path):
        """SIGTERM inside the shutdown envelope must remove registered
        coordination files (heartbeat/lease) so peers reclaim immediately."""
        hb = elastic.Heartbeat(tmp_path, host="h1", ttl_s=600.0)
        hb.beat()
        lease = tmp_path / "tile_b00000_u00000.lease"
        lease.write_text("{}")
        shutdown.release_on_exit(lease)
        assert hb.path.exists() and lease.exists()
        with pytest.raises(SystemExit) as exc:
            with shutdown.graceful_shutdown(label="t"):
                raise shutdown.Interrupted(signal.SIGTERM)
        assert exc.value.code == 128 + signal.SIGTERM
        assert not hb.path.exists() and not lease.exists()


# ---------------------------------------------------------------------------
# Cost model / rebalancing plan
# ---------------------------------------------------------------------------


class TestPlanClaims:
    TILES = [((b, u), 16.0) for b in (0, 4, 8, 12) for u in (0, 4)]

    def test_deterministic_and_exact_partition(self):
        rates = {"b": 1.0, "a": 1.0, "c": 1.0}
        p1 = elastic.plan_claims(self.TILES, rates)
        p2 = elastic.plan_claims(list(reversed(self.TILES)), dict(rates))
        assert p1 == p2  # same inputs (any order) -> same plan on every host
        assigned = [t for tiles in p1.values() for t in tiles]
        assert sorted(assigned) == sorted(t for t, _ in self.TILES)

    def test_throughput_proportional_shares(self):
        plan = elastic.plan_claims(self.TILES, {"fast": 3.0, "slow": 1.0})
        assert len(plan["fast"]) == 6 and len(plan["slow"]) == 2

    def test_lpt_orders_large_tiles_first(self):
        tiles = [((0, 0), 4.0), ((0, 2), 16.0), ((2, 0), 16.0)]
        plan = elastic.plan_claims(tiles, {"only": 1.0})
        assert plan["only"][0] in ((0, 2), (2, 0))  # big tiles claimed first
        assert plan["only"][-1] == (0, 0)

    def test_degenerate_inputs(self):
        assert elastic.plan_claims([], {"a": 1.0}) == {"a": []}
        assert elastic.plan_claims(self.TILES, {}) == {}
        # Non-positive published rates fall back to 1.0, not a crash.
        plan = elastic.plan_claims(self.TILES, {"a": 0.0, "b": -3.0})
        assert len(plan["a"]) + len(plan["b"]) == len(self.TILES)

    def test_tracker_ewma_and_history_seed(self, tmp_path, monkeypatch):
        tr = elastic.ThroughputTracker()
        tr.update(100, 2.0)
        assert tr.rate == 50.0
        tr.update(100, 1.0)
        assert 50.0 < tr.rate < 100.0
        # Seed from the SIDECAR elastic history (kept beside, not inside,
        # the trend-gated file — see _rate_history_path).
        monkeypatch.setenv("SBR_OBS_HISTORY", str(tmp_path / "h.jsonl"))
        from sbr_tpu.obs import history

        sidecar = elastic._rate_history_path()
        assert str(sidecar).endswith("h.jsonl.elastic.jsonl")
        for v in (10.0, 30.0, 20.0):
            history.append({"elastic_cells_per_sec": v}, label="elastic_sweep",
                           path=sidecar)
        assert elastic.seed_rate_from_history() == 20.0
        # The gated main history stays untouched by elastic appends.
        elastic._append_rate_history(42.0, tiles_computed=3)
        assert not (tmp_path / "h.jsonl").exists()
        assert len(history.load(sidecar)) == 4


# ---------------------------------------------------------------------------
# Cross-run global tile cache
# ---------------------------------------------------------------------------


def _arrays(seed=0.0):
    return {
        "max_aw": np.full((2, 2), 1.5 + seed),
        "xi": np.full((2, 2), 2.5 + seed),
        "status": np.zeros((2, 2), np.int32),
    }


class TestTileCache:
    def test_roundtrip_byte_identical(self, tmp_path):
        cache = elastic.TileCache(tmp_path / "cache")
        base = make_model_params()
        key = cache.key(base, CFG, None, BETAS[:2], US[:2])
        arrays = _arrays()
        assert cache.load(key) is None  # cold
        cache.store(key, arrays)
        got = cache.load(key)
        assert all(got[f].tobytes() == arrays[f].tobytes() for f in arrays)

    def test_key_distinguishes_sweeps(self):
        cache = elastic.TileCache("/nonexistent")
        base = make_model_params()
        k = cache.key(base, CFG, None, BETAS[:2], US[:2])
        assert k != cache.key(base, CFG, None, BETAS[:2], US[2:])  # values
        assert k != cache.key(base, SolverConfig(n_grid=128), None, BETAS[:2], US[:2])
        assert k != cache.key(base, CFG, "float32", BETAS[:2], US[:2])
        # Same inputs reproduce the key (process-stable content address).
        assert k == cache.key(base, CFG, None, BETAS[:2], US[:2])

    def test_corrupt_entry_quarantined_not_served(self, tmp_path):
        from sbr_tpu.resilience import faults

        cache = elastic.TileCache(tmp_path / "cache")
        key = cache.key(make_model_params(), CFG, None, BETAS[:2], US[:2])
        cache.store(key, _arrays())
        faults.corrupt_file(cache.path(key))
        assert cache.load(key) is None
        assert not cache.path(key).exists()  # slot freed for recompute
        assert list((cache.path(key).parent / "quarantine").glob("*.npz"))

    def test_gc_prunes_cold_keeps_warm(self, tmp_path):
        import os

        cache = elastic.TileCache(tmp_path / "cache")
        base = make_model_params()
        k_cold = cache.key(base, CFG, None, BETAS[:2], US[:2])
        k_warm = cache.key(base, CFG, None, BETAS[2:], US[2:])
        cache.store(k_cold, _arrays())
        cache.store(k_warm, _arrays(1.0))
        old = time.time() - 40 * 86400
        os.utime(cache.path(k_cold), (old, old))
        # A hard-killed writer's orphaned store tmp is debris past an hour.
        orphan = cache.path(k_warm).parent / "tmpdead.tmp"
        orphan.write_bytes(b"partial")
        os.utime(orphan, (time.time() - 7200, time.time() - 7200))
        removed = elastic.gc_tile_cache(tmp_path / "cache", keep_days=30.0)
        assert cache.path(k_cold) in removed
        assert not cache.path(k_cold).exists()
        assert orphan in removed and not orphan.exists()
        assert cache.load(k_warm) is not None  # warm entry survived

    def test_recorded_tile_shape_adopted_by_auto_joiner(self, tmp_path):
        """The creating host's resolved shape lands in the checkpoint
        manifest; a late joiner with tile_shape='auto' adopts it instead of
        re-planning from its own capacity (heterogeneous-fleet join)."""
        base = make_model_params()
        ck = tmp_path / "ck"
        run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2),
                       checkpoint_dir=ck, tile_owner=lambda b, u: False)
        assert elastic.recorded_tile_shape(ck) == (2, 2)
        assert elastic.recorded_tile_shape(tmp_path / "nope") is None

    def test_heartbeat_survives_transient_write_failure(self, tmp_path, monkeypatch):
        hb = elastic.Heartbeat(tmp_path, host="h1", ttl_s=60.0)
        import os as _os

        real_replace = _os.replace
        monkeypatch.setattr(
            elastic.os, "replace",
            lambda *a: (_ for _ in ()).throw(OSError("ESTALE")),
        )
        hb.beat()  # must not raise: liveness telemetry is best-effort
        monkeypatch.setattr(elastic.os, "replace", real_replace)
        hb.beat(tiles_done=1)
        assert elastic.live_hosts(tmp_path)["h1"]["tiles_done"] == 1
        hb.withdraw()


# ---------------------------------------------------------------------------
# The elastic driver end-to-end (single process playing several roles)
# ---------------------------------------------------------------------------


class TestElasticSweep:
    def test_single_host_matches_direct_run(self, tmp_path):
        from sbr_tpu.parallel import run_tiled_grid_multihost

        base = make_model_params()
        full = run_tiled_grid_multihost(
            BETAS, US, base, str(tmp_path / "ck"), config=CFG, tile_shape=(2, 2),
            poll_s=0.05, timeout_s=60.0, elastic=True,
        )
        direct = run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2))
        for f in ("max_aw", "xi", "status"):
            assert np.asarray(getattr(full, f)).tobytes() == np.asarray(
                getattr(direct, f)
            ).tobytes()
        # Scaffolding cleaned: no leases, no heartbeats left behind.
        assert not list((tmp_path / "ck").glob("*.lease"))
        assert not list((tmp_path / "ck").glob("host_*.hb"))

    def test_joiner_adopts_mid_sweep_remainder(self, tmp_path):
        """A 'late joiner' against a checkpoint dir where another host
        already landed half the tiles computes only the remainder —
        launch-time ownership does not exist."""
        from sbr_tpu.parallel import run_tiled_grid_multihost

        base = make_model_params()
        ck = tmp_path / "ck"
        # Half the sweep already on disk (the departed host's work).
        run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=ck,
            tile_owner=lambda b, u: b == 0,
        )
        assert len(list(ck.glob("tile_*.npz"))) == 2
        from sbr_tpu import obs

        with obs.run_context(label="join", run_dir=tmp_path / "run"):
            run_tiled_grid_multihost(
                BETAS, US, base, str(ck), config=CFG, tile_shape=(2, 2),
                poll_s=0.05, timeout_s=60.0, elastic=True,
            )
        manifest = json.loads((tmp_path / "run" / "manifest.json").read_text())
        blk = manifest["elastic"]
        assert blk["tiles"].get("computed") == 2  # only the remainder
        assert blk["scheduler"]["join"] == 1 and blk["scheduler"]["leave"] == 1

    def test_live_peer_lease_respected_then_reclaimed_after_ttl(self, tmp_path):
        """A tile leased by a live peer is not touched; once the lease TTL
        lapses the claim loop takes it over (the silent-death path)."""
        from sbr_tpu.parallel import run_tiled_grid_multihost
        from sbr_tpu.parallel.distributed import _try_lease

        base = make_model_params()
        ck = tmp_path / "ck"
        ck.mkdir()
        assert _try_lease(ck, 0, 0, ttl_s=2.0)  # a "peer" holds tile (0,0)
        t0 = time.monotonic()
        from sbr_tpu import obs

        with obs.run_context(label="reclaim", run_dir=tmp_path / "run"):
            full = run_tiled_grid_multihost(
                BETAS, US, base, str(ck), config=CFG, tile_shape=(2, 2),
                poll_s=0.1, timeout_s=60.0, elastic=True,
            )
        assert time.monotonic() - t0 >= 1.0  # actually waited out the TTL
        direct = run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2))
        assert np.asarray(full.xi).tobytes() == np.asarray(direct.xi).tobytes()
        manifest = json.loads((tmp_path / "run" / "manifest.json").read_text())
        assert manifest["elastic"]["scheduler"].get("reclaim", 0) >= 1

    def test_warm_global_cache_computes_zero_tiles(self, tmp_path, monkeypatch):
        from sbr_tpu.parallel import run_tiled_grid_multihost

        monkeypatch.setenv("SBR_TILE_CACHE_DIR", str(tmp_path / "cache"))
        base = make_model_params()
        kwargs = dict(config=CFG, tile_shape=(2, 2), poll_s=0.05,
                      timeout_s=60.0, elastic=True)
        cold = run_tiled_grid_multihost(BETAS, US, base, str(tmp_path / "ck1"), **kwargs)
        from sbr_tpu import obs

        with obs.run_context(label="warm", run_dir=tmp_path / "run"):
            warm = run_tiled_grid_multihost(
                BETAS, US, base, str(tmp_path / "ck2"), **kwargs
            )
        assert np.asarray(warm.xi).tobytes() == np.asarray(cold.xi).tobytes()
        manifest = json.loads((tmp_path / "run" / "manifest.json").read_text())
        blk = manifest["elastic"]
        assert blk["tiles"].get("computed") is None or blk["tiles"].get("computed", 0) == 0
        assert blk["tiles"].get("cache") == 4
        assert blk["cache"].get("hit") == 4

    def test_wait_false_returns_none_after_claiming(self, tmp_path):
        from sbr_tpu.parallel import run_tiled_grid_multihost

        base = make_model_params()
        out = run_tiled_grid_multihost(
            BETAS, US, base, str(tmp_path / "ck"), config=CFG, tile_shape=(2, 2),
            wait=False, elastic=True,
        )
        assert out is None
        # Sole host + work-conserving queue: it computed everything.
        assert len(list((tmp_path / "ck").glob("tile_*.npz"))) == 4


# ---------------------------------------------------------------------------
# report elastic + gc satellites
# ---------------------------------------------------------------------------


class TestReportElastic:
    def _report(self, run_dir, *extra):
        return subprocess.run(
            [sys.executable, "-m", "sbr_tpu.obs.report", "elastic", str(run_dir), *extra],
            capture_output=True, text=True, timeout=120.0,
        )

    def test_no_elastic_data_exits_three(self, tmp_path):
        from sbr_tpu import obs

        with obs.run_context(label="plain", run_dir=tmp_path / "run"):
            pass
        proc = self._report(tmp_path / "run")
        assert proc.returncode == 3
        assert "no scheduler events" in proc.stdout

    def test_scheduler_story_rendered_and_json(self, tmp_path):
        from sbr_tpu import obs

        with obs.run_context(label="el", run_dir=tmp_path / "run") as run:
            run.log_scheduler("join", host="h1", tiles=4)
            run.log_scheduler("claim", host="h1", tile="tile_b00000_u00000")
            run.log_scheduler("done", host="h1", tile="tile_b00000_u00000",
                              source="computed", dur_s=2.0, cells=4)
            run.log_scheduler("done", host="h1", tile="tile_b00000_u00002",
                              source="cache", dur_s=0.01, cells=4)
            run.log_cache("hit", tile="tile_b00000_u00002")
            run.log_scheduler("leave", host="h1", tiles_done=2)
        proc = self._report(tmp_path / "run", "--json")
        assert proc.returncode == 0
        doc = json.loads(proc.stdout)
        assert doc["tiles_computed"] == 1 and doc["tiles_from_cache"] == 1
        assert doc["hosts"]["h1"]["tiles_done"] == 2
        assert doc["hosts"]["h1"]["cells_per_sec"] == 2.0
        assert doc["cache"] == {"hit": 1}
        human = self._report(tmp_path / "run")
        assert human.returncode == 0
        assert "HOSTS" in human.stdout and "GLOBAL TILE CACHE" in human.stdout

    def test_gc_prunes_stale_heartbeats_keeps_live(self, tmp_path):
        from sbr_tpu.obs import mem

        live = elastic.Heartbeat(tmp_path, host="live", ttl_s=600.0)
        live.beat()
        dead = elastic.Heartbeat(tmp_path, host="dead", ttl_s=1.0)
        dead.beat()
        rec = json.loads(dead.path.read_text())
        rec["ts"] -= 60.0
        dead.path.write_text(json.dumps(rec))
        removed = mem.gc_debris(tmp_path)
        assert dead.path in removed and not dead.path.exists()
        assert live.path.exists()
        live.withdraw()

    def test_report_gc_tile_cache_cli(self, tmp_path):
        import os

        cache = elastic.TileCache(tmp_path / "cache")
        key = cache.key(make_model_params(), CFG, None, BETAS[:2], US[:2])
        cache.store(key, _arrays())
        old = time.time() - 40 * 86400
        os.utime(cache.path(key), (old, old))
        proc = subprocess.run(
            [sys.executable, "-m", "sbr_tpu.obs.report", "gc", str(tmp_path / "runs"),
             "--keep", "4", "--tile-cache", str(tmp_path / "cache"),
             "--keep-days", "30"],
            capture_output=True, text=True, timeout=120.0,
        )
        assert proc.returncode == 0, proc.stderr
        assert "cold tile-cache" in proc.stdout
        assert not cache.path(key).exists()
