"""Master CLI tests for the --paper tiled-heatmap path (VERDICT r1 missing-#2):
the paper-resolution artifact is produced through the checkpoint/resume
machinery, and an interrupted run resumes from finished tiles instead of
restarting (the reference's 5000×5000 grid restarts from zero,
`scripts/1_baseline.jl:209-210`)."""

from pathlib import Path


def _run_paper(out: Path, ckpt: Path, res: int = 24, tile: int = 8) -> int:
    from sbr_tpu.figures import master

    return master.main(
        [
            "--output",
            str(out),
            "--sections",
            "",
            "--paper",
            "--paper-res",
            str(res),
            "--paper-tile",
            str(tile),
            "--checkpoint-dir",
            str(ckpt),
        ]
    )


def test_paper_heatmap_generates_and_resumes(tmp_path, capsys):
    out, ckpt = tmp_path / "out", tmp_path / "ckpt"
    pdf = out / "figures" / "baseline/comp_stat_cross_heatmap_AW_large.pdf"

    assert _run_paper(out, ckpt) == 0
    assert pdf.exists()
    tiles = sorted(ckpt.glob("tile_*.npz"))
    assert len(tiles) == 9  # 24/8 × 24/8
    capsys.readouterr()

    # Simulated interrupt: artifact gone, some tiles lost — the rerun must
    # recompute only the missing tiles and regenerate the artifact.
    pdf.unlink()
    tiles[0].unlink()
    tiles[4].unlink()
    assert _run_paper(out, ckpt) == 0
    assert pdf.exists()
    assert "resumed 7 tiles" in capsys.readouterr().out

    # The tex document picks the paper heatmap up once it exists on disk.
    tex = (out / "replication_figures.tex").read_text()
    assert "comp_stat_cross_heatmap_AW_large.pdf" in tex


def test_graft_entry_compiles_and_runs():
    """The driver compile-checks entry() single-chip at round end; guard it
    in-suite so a refactor cannot silently break the hook."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import __graft_entry__ as ge

    fn, example_args = ge.entry()
    import jax
    import numpy as np

    out = jax.jit(fn)(*example_args)
    xi, aw_max, status = out
    assert xi.shape == example_args[0].shape
    st = np.asarray(status)
    assert ((st >= 0) & (st <= 3)).all()
    run = st == 0
    assert run.any()
    assert np.isfinite(np.asarray(xi)[run]).all()
