"""Differential tests against the REFERENCE's own numerics (VERDICT r3 #4).

`tests/test_baseline.py` bounds sbr_tpu against ideal math (the scipy
oracle); this module bounds it against a faithful Python emulation of the
reference's actual algorithm (`tests/ref_emulator.py`: adaptive Stage-1
grid inherited by every stage, sequential trapezoid hazard, grid-linear
crossing interpolation, tolerance-exit bisection with the local-grid slope
check — `/root/reference/src/baseline/learning.jl:41-54`,
`solver.jl:153-376,495-532`). If the reference's discretization deviates
from ideal math anywhere, these tests catch the figure-parity gap the
oracle tests would miss.

Measured while building (grid-density study in `ref_emulator.py`): at the
reference's eps-tolerance grid density the reference algorithm itself sits
within ~1e-6 of ideal math at the script calibrations, so TPU-vs-reference
≤ 1e-6 here plus oracle agreement elsewhere close the loop.

The committed-figure frontier comparison (the 5000×5000 heatmap raster
embedded in the reference's own PDF vs this repo's checkpointed status
tiles) lives in `benchmarks/reference_frontier.py` — it needs the ~287 MB
tile store and is an analysis artifact, not a unit test; its result is
recorded in PARITY.md.
"""

from __future__ import annotations

import numpy as np
import pytest

from ref_emulator import solve_reference_baseline

from sbr_tpu import (
    make_model_params,
    solve_learning,
    solve_equilibrium_baseline,
    with_overrides,
)
from sbr_tpu.models.params import SolverConfig

# The four script calibrations that produce reference scalars
# (`scripts/1_baseline.jl:34-44,106-126`, `scripts/4_social_learning.jl:36-43`;
# the copy-ctor keeps η pinned, `src/baseline/model.jl:189-211`).
CALIBRATIONS = {
    "main": {},
    "fast": {"beta": 3.0},
    "low_u": {"u": 0.01},
    "social_wom": {
        "beta": 0.9,
        "u": 0.5,
        "p": 0.99,
        "kappa": 0.25,
        "lam": 0.25,
        "eta_bar": 30.0,
    },
}


def _solve_sbr(name):
    kw = dict(CALIBRATIONS[name])
    if name == "social_wom":
        m = make_model_params(**kw)
    else:
        m = with_overrides(make_model_params(), **kw)
    config = SolverConfig()
    res = solve_equilibrium_baseline(solve_learning(m.learning, config), m.economic, config)
    return m, res


def _solve_ref(name):
    kw = dict(CALIBRATIONS[name])
    if name == "social_wom":
        eta = kw.pop("eta_bar") / kw["beta"]
        return solve_reference_baseline(eta=eta, tspan_end=2 * eta, **kw)
    # with_overrides pins η=15 and tspan=(0,30) from the base model
    return solve_reference_baseline(eta=15.0, tspan_end=30.0, **kw)


class TestScriptCalibrations:
    """TPU-vs-reference ≤ 1e-6 on every scalar the scripts print."""

    @pytest.mark.parametrize("name", list(CALIBRATIONS))
    def test_equilibrium_scalars(self, name):
        _, res = _solve_sbr(name)
        ref = _solve_ref(name)
        assert bool(res.bankrun) == ref.bankrun
        assert float(res.xi) == pytest.approx(ref.xi, abs=1e-6)
        assert float(res.tau_bar_in_unc) == pytest.approx(ref.tau_in_unc, abs=1e-6)
        assert float(res.tau_bar_out_unc) == pytest.approx(ref.tau_out_unc, abs=1e-6)

    @pytest.mark.parametrize("name", list(CALIBRATIONS))
    def test_aw_max(self, name):
        """AW_max drives the Figure 4/5 values; the reference takes the max
        over ITS grid's knots (`solver.jl:566`) — a grid-sampling max, so
        the bound is interpolation-limited rather than 1e-6-exact."""
        from sbr_tpu.baseline.solver import get_aw

        m, res = _solve_sbr(name)
        ref = _solve_ref(name)
        config = SolverConfig()
        ls = solve_learning(m.learning, config)
        aw_cum, _, _ = get_aw(
            res.xi, res.tau_bar_in_unc, res.tau_bar_out_unc, res.tau_grid, ls
        )
        assert float(np.max(np.asarray(aw_cum))) == pytest.approx(ref.aw_max, abs=2e-6)


class TestNoRunFrontier:
    """The Figure-4/5 no-run boundary: the u at which equilibria disappear
    must agree between sbr_tpu and the reference algorithm — the frontier
    is figure content (the shaded regions of Fig 4 and the NaN mask of
    Fig 5), and it is exactly where adaptive-grid numerics could drift."""

    @pytest.mark.parametrize("beta,u_lo,u_hi", [(1.0, 0.10, 0.12), (3.0, 0.31, 0.34)])
    def test_frontier_location(self, beta, u_lo, u_hi):
        """Bisect OUR frontier (cheap, jit-cached solves), then check the
        emulator flips run→no-run inside ±2e-6 of it — equivalent to
        |u*_sbr − u*_ref| ≤ 2e-6 at two emulator solves instead of
        a full second bisection (each emulator solve is a ~2 s RK45 run)."""
        config = SolverConfig()
        base = with_overrides(make_model_params(), beta=beta)
        ls = solve_learning(base.learning, config)

        def sbr_runs(u):
            m = with_overrides(base, u=u)
            return bool(
                solve_equilibrium_baseline(ls, m.economic, config).bankrun
            )

        lo, hi = u_lo, u_hi
        assert sbr_runs(lo) and not sbr_runs(hi), "band must straddle the frontier"
        for _ in range(18):
            mid = 0.5 * (lo + hi)
            lo, hi = (mid, hi) if sbr_runs(mid) else (lo, mid)
        u_star = 0.5 * (lo + hi)

        # Figure-4 resolution is 5000 points over [0.001, 1] → du ≈ 2e-4;
        # require agreement two orders tighter than a figure pixel
        tol = 2e-6
        assert solve_reference_baseline(beta=beta, u=u_star - tol, tspan_end=30.0).bankrun
        assert not solve_reference_baseline(beta=beta, u=u_star + tol, tspan_end=30.0).bankrun

    @pytest.mark.slow
    def test_band_statuses_agree(self):
        """Across a band straddling the β=1 frontier, run/no-run decisions
        agree point for point except within a hair of the boundary."""
        config = SolverConfig()
        base = make_model_params()
        ls = solve_learning(base.learning, config)
        us = np.linspace(0.105, 0.115, 15)
        disagreements = []
        for u in us:
            m = with_overrides(base, u=float(u))
            s = bool(solve_equilibrium_baseline(ls, m.economic, config).bankrun)
            r = solve_reference_baseline(u=float(u)).bankrun
            if s != r:
                disagreements.append(float(u))
        # any residual disagreement must hug the frontier (≈ 0.1091953)
        assert all(abs(u - 0.1091953) < 5e-6 for u in disagreements), disagreements


class TestExtensionParity:
    """The hetero and interest extensions against emulations of the
    reference's own extension algorithms (`ref_emulator.solve_reference_hetero`
    / `solve_reference_interest`) at the script calibrations
    (`scripts/2_heterogeneity.jl:38-49`, `scripts/3_interest_rates.jl:37-46`).
    Tolerances are looser than baseline because the hetero path is
    grid-backed (no closed form) on BOTH sides."""

    def test_hetero_script_calibration(self):
        from ref_emulator import solve_reference_hetero

        from sbr_tpu.hetero import solve_equilibrium_hetero, solve_learning_hetero
        from sbr_tpu.models.params import make_hetero_params

        ref = solve_reference_hetero((0.125, 12.5), (0.9, 0.1))
        m = make_hetero_params(
            betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0,
            u=0.1, p=0.9, kappa=0.3, lam=0.1,
        )
        config = SolverConfig()
        res = solve_equilibrium_hetero(
            solve_learning_hetero(m.learning, config), m.economic, config
        )
        assert bool(res.bankrun) == ref.bankrun
        assert float(res.xi) == pytest.approx(ref.xi, abs=1e-5)
        np.testing.assert_allclose(
            np.asarray(res.tau_bar_in_uncs), ref.tau_in_uncs, atol=5e-5
        )
        np.testing.assert_allclose(
            np.asarray(res.tau_bar_out_uncs), ref.tau_out_uncs, atol=5e-5
        )

    def test_hetero_extreme_beta_ratio(self):
        """VERDICT r4 task 4: the hetero grid at extreme β_k separation.

        Under the η = η̄/⟨β⟩ convention the uniform shared grid partially
        self-regularizes (a fast group's transition width scales with the
        same ⟨β⟩ that sets η), so the exposed regime needs a LARGE η̄ with
        widely separated βs: here β = (1, 300), dist = (0.99, 0.01),
        η̄ = 3000 → η ≈ 752, uniform spacing 0.18 vs a fast-group hazard
        spike the uniform grid samples wrong (measured: uniform-grid
        τ̄_OUT for the fast group is 2.036 vs the reference's 1.958 — a 4%
        error; ξ off by 3.3e-3). The exact Ω-reduction path
        (`hetero/learning.py::solve_learning_hetero_exact`, default via
        grid_warp > 0) matches the emulator to ≤1e-5. Oracle: the
        reference-numerics emulator, whose adaptive grid resolves any β
        (`heterogeneity_learning.jl:73-74`)."""
        from ref_emulator import solve_reference_hetero

        from sbr_tpu.hetero import solve_equilibrium_hetero, solve_learning_hetero
        from sbr_tpu.models.params import make_hetero_params

        ref = solve_reference_hetero(
            (1.0, 300.0), (0.99, 0.01), u=0.1, p=0.9, kappa=0.3, lam=0.01, eta_bar=3000.0
        )
        m = make_hetero_params(
            betas=[1.0, 300.0], dist=[0.99, 0.01], eta_bar=3000.0,
            u=0.1, p=0.9, kappa=0.3, lam=0.01,
        )
        config = SolverConfig()  # grid_warp 0.5 → exact Ω path
        res = solve_equilibrium_hetero(
            solve_learning_hetero(m.learning, config), m.economic, config
        )
        assert bool(res.bankrun) == ref.bankrun
        assert float(res.xi) == pytest.approx(ref.xi, abs=2e-5)
        np.testing.assert_allclose(
            np.asarray(res.tau_bar_out_uncs), ref.tau_out_uncs, atol=1e-4
        )

    def test_interest_script_calibration(self):
        from ref_emulator import solve_reference_interest

        from sbr_tpu.interest import solve_equilibrium_interest
        from sbr_tpu.models.params import make_interest_params

        ref = solve_reference_interest()
        m = make_interest_params(u=0.0, r=0.06, delta=0.1)
        config = SolverConfig()
        res = solve_equilibrium_interest(
            solve_learning(m.learning, config), m.economic, config
        )
        assert bool(res.base.bankrun) == ref.bankrun
        assert float(res.base.xi) == pytest.approx(ref.xi, abs=1e-6)
        assert float(res.base.tau_bar_in_unc) == pytest.approx(ref.tau_in_unc, abs=1e-6)
        assert float(res.base.tau_bar_out_unc) == pytest.approx(ref.tau_out_unc, abs=1e-6)
        assert float(res.v[0]) == pytest.approx(ref.v0, abs=1e-9)

    def test_interest_extreme_beta(self):
        """VERDICT r4 task 3: the interest path at β ≫ n_grid/η — the regime
        where a uniform grid swallows the 1/β-wide logistic transition. The
        solver no longer pins grid_warp=0 (round-4's silent config rewrite):
        the HJB integrates over the warped grid (non-uniform RK4 intervals +
        searchsorted hazard interp) and V's crossing interp follows the grid.
        Oracle: the reference-numerics emulator (adaptive grid, like
        `learning.jl:51` resolves any β). η is pinned at 15 (the heatmap's
        copy-ctor convention) so the transition width 1/β ≈ 7.5e-4 is ~5x
        under the uniform spacing η/n_grid."""
        from ref_emulator import solve_reference_interest

        from sbr_tpu.interest import solve_equilibrium_interest
        from sbr_tpu.models.params import make_interest_params

        beta = 2000.0
        m = make_interest_params(
            beta=beta, eta=15.0, u=0.1, r=0.06, delta=0.1, tspan=(0.0, 30.0)
        )
        config = SolverConfig()  # grid_warp 0.5 default, now honored
        assert config.grid_warp > 0.0
        ls = solve_learning(m.learning, config)
        res = solve_equilibrium_interest(ls, m.economic, config)
        ref = solve_reference_interest(
            beta=beta, eta=15.0, u=0.1, r=0.06, delta=0.1, tspan_end=30.0
        )
        assert bool(res.base.bankrun) == ref.bankrun
        assert float(res.base.xi) == pytest.approx(ref.xi, abs=1e-6)
        assert float(res.base.tau_bar_out_unc) == pytest.approx(ref.tau_out_unc, abs=1e-6)


class TestSocialParity:
    def test_social_script_calibration(self):
        """The social fixed point against the reference's own damped
        iteration (`ref_emulator.solve_reference_social`) at the Figure-12
        calibration, both sides at the script's sup-norm tolerance (1e-4).

        Bound justified by measurement (VERDICT r4 task 5, run 2026-07-30):
        the theoretical stopping-width bound is |Δξ| ≲ tol/g(ξ) ≈ 1e-3,
        but the two loops track each other ITERATION FOR ITERATION (50=50
        at tol=1e-4, 56=56 at 1e-5) so the stopping error largely cancels:
        measured |Δξ| = 4.4e-5 at tol=1e-4, 2.6e-5 at 1e-5. The residual
        floor is each side's own discretization (~5e-5: the emulator moves
        5.0e-5 between rtol 1e-10 and 3e-14; sbr moves 5.8e-5 between
        n_grid 4096 and 8192). 2e-4 is ~4x the measured gap and ~4x that
        floor — tight enough to catch a real regression, loose enough for
        the documented numerics."""
        from ref_emulator import solve_reference_social

        from sbr_tpu.social.solver import solve_equilibrium_social

        ref = solve_reference_social()
        m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
        # numerics="fixed": the lockstep iteration-count assertion below is a
        # statement about the reference's PLAIN DAMPED loop, which is exactly
        # the fixed path's contract (ISSUE 9); the adaptive path's Anderson
        # tail converges in fewer iterations by design and has its own
        # adaptive-vs-fixed agreement test in tests/test_numerics.py.
        res = solve_equilibrium_social(
            m, SolverConfig(n_grid=4096, numerics="fixed"), tol=1e-4, max_iter=500
        )
        assert ref.converged and bool(res.converged)
        assert bool(res.equilibrium.bankrun) == ref.bankrun
        # near-lockstep iteration counts (measured exactly equal; ±1 allows
        # a benign scipy/JAX step-selection change without a false alarm)
        assert abs(int(res.iterations) - ref.iterations) <= 1
        assert float(res.xi) == pytest.approx(ref.xi, abs=2e-4)
