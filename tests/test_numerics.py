"""Adaptive batched numerics (ISSUE 9).

Four contracts under test:

1. `core.rootfind.chandrupatla` — convergence-masked bracketing — agrees
   with the 90-iteration `bisect` to ≤1e-10 on oracle-checked root
   batteries and on the β×u grid, in a fraction of the iterations, and
   flags degenerate brackets (no sign change, NaN endpoints, root at an
   endpoint) the way its Health contract promises.
2. `core.rootfind.threshold_crossings_masked` — the O(√n) blocked crossing
   search — is BIT-identical to the `first_upcrossing`/`last_downcrossing`
   scan pair (values, fallback ladder, and health flags) across adversarial
   curves; these are the index-identity proofs the module docstring cites.
3. `core.ode.bs32` — the Bogacki–Shampine 3(2) embedded pair — meets its
   tolerance on smooth problems in ~1 attempt per save interval and raises
   `ODE_BUDGET` when an interval exhausts its step cap.
4. `numerics="fixed"` is the bit-exact escape hatch: outputs are BITWISE
   identical to the pre-PR solver (golden arrays captured from the parent
   commit in tests/data/golden_fixed_numerics.npz), while the default
   adaptive mode matches fixed status grids exactly and ξ to 1e-10.

Plus the history side: schema-5 records (grid_adaptive_speedup,
grid_mean_effective_iters) gate against schema 1-4 lines in `report trend`.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu.core.ode import bs32, rk4
from sbr_tpu.core.rootfind import (
    bisect,
    chandrupatla,
    first_upcrossing,
    last_downcrossing,
    threshold_crossings_masked,
)
from sbr_tpu.diag.health import (
    FALLBACK_IN_DEFAULT,
    FALLBACK_IN_KNOT,
    NAN_INPUT,
    NO_BRACKET,
    ODE_BUDGET,
)
from sbr_tpu.models.params import SolverConfig, make_model_params

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "golden_fixed_numerics.npz")


# -- chandrupatla vs bisect ---------------------------------------------------


class TestChandrupatla:
    def test_agrees_with_bisect_on_root_battery(self):
        """Cube roots, transcendental roots, and scaled logistics — the
        ≤1e-10 oracle-grid agreement criterion on a vmapped lane battery."""
        cs = jnp.linspace(0.5, 8.0, 64)

        # increasing f per `bisect`'s reference update-rule convention
        # (positive error contracts the upper bound) — every solver call
        # site is oriented this way
        for f, lo, hi in [
            (lambda x: x**3 - cs, jnp.zeros_like(cs), jnp.full_like(cs, 2.5)),
            (lambda x: 0.1 * cs * x - jnp.cos(x), jnp.zeros_like(cs), jnp.full_like(cs, 4.0)),
            (lambda x: 1.0 / (1.0 + jnp.exp(-cs * x)) - 0.7, jnp.zeros_like(cs), jnp.full_like(cs, 9.0)),
        ]:
            x_b = bisect(f, lo, hi, num_iters=90)
            x_c = chandrupatla(f, lo, hi, budget=90)
            np.testing.assert_allclose(np.asarray(x_c), np.asarray(x_b), rtol=0, atol=1e-10)

    def test_converges_far_under_budget(self):
        """The whole point: actual per-lane iterations ≪ the fixed budget,
        and the Health records them (the fixed path can only report 90)."""
        cs = jnp.linspace(0.5, 8.0, 64)
        f = lambda x: x**3 - cs
        x, h = chandrupatla(f, jnp.zeros_like(cs), jnp.full_like(cs, 2.5), budget=90, with_health=True)
        iters = np.asarray(h.iterations)
        assert iters.shape == (64,)
        assert iters.max() < 40 and iters.mean() < 25
        _, h_b = bisect(f, jnp.zeros_like(cs), jnp.full_like(cs, 2.5), num_iters=90, with_health=True)
        assert np.asarray(h_b.iterations).min() == 90  # budget, not actual
        assert np.all(np.asarray(h.residual) <= np.asarray(h_b.residual) + 1e-12)

    def test_x0_seed_agrees(self):
        c = jnp.asarray(2.0)
        f = lambda x: x**2 - c
        x = chandrupatla(f, jnp.asarray(0.0), jnp.asarray(2.0), x0=jnp.asarray(1.5))
        assert float(x) == pytest.approx(np.sqrt(2.0), abs=1e-12)

    def test_root_at_endpoint(self):
        f = lambda x: x  # root exactly at lo
        x, h = chandrupatla(f, jnp.asarray(0.0), jnp.asarray(2.0), with_health=True)
        assert abs(float(x)) < 1e-12
        assert int(h.flags) & NO_BRACKET == 0 or abs(float(x)) < 1e-12

    def test_no_sign_change_flagged(self):
        """Non-bracketing input: like `bisect`, no convergence promise — the
        call terminates, returns a candidate inside the interval, and the
        Health carries NO_BRACKET so the caller can classify."""
        f = lambda x: x**2 + 1.0
        x, h = chandrupatla(f, jnp.asarray(-2.0), jnp.asarray(2.0), budget=50, with_health=True)
        assert int(h.flags) & NO_BRACKET
        assert -2.0 <= float(x) <= 2.0

    def test_nan_endpoint_flagged(self):
        f = lambda x: x - 0.5
        x, h = chandrupatla(f, jnp.asarray(jnp.nan), jnp.asarray(2.0), budget=20, with_health=True)
        assert int(h.flags) & NAN_INPUT

    def test_mixed_batch_early_exit(self):
        """Easy lanes freeze while a hard lane keeps iterating: per-lane
        counts differ inside one while_loop."""
        cs = jnp.asarray([1.0, 1.0 + 1e-14])  # second root sits ~eps from lo
        f = lambda x: x - cs
        _, h = chandrupatla(f, jnp.zeros(2), jnp.full((2,), 100.0), budget=90, with_health=True)
        iters = np.asarray(h.iterations)
        assert iters[0] <= iters[1] <= 90


# -- blocked crossings: bit-identity vs the scan pair -------------------------


def _scan_pair(x, y, level, default):
    t_in, has_up, h_in = first_upcrossing(x, y, level, default, return_flag=True, with_health=True)
    t_out, has_dn, h_out = last_downcrossing(x, y, level, default, return_flag=True, with_health=True)
    return t_in, has_up, t_out, has_dn, h_in, h_out


def _assert_crossings_identical(x, y, level, default):
    ref = _scan_pair(x, y, level, default)
    got = threshold_crossings_masked(x, y, level, default, with_health=True)
    for name, r, g in zip(("t_in", "has_up", "t_out", "has_dn"), ref[:4], got[:4]):
        r, g = np.asarray(r), np.asarray(g)
        assert r.tobytes() == g.tobytes(), f"{name}: scan={r} blocked={g}"
    for name, r, g in zip(("h_in", "h_out"), ref[4:], got[4:]):
        assert np.asarray(r.flags).tobytes() == np.asarray(g.flags).tobytes(), name


class TestMaskedCrossings:
    @pytest.mark.parametrize("n", [17, 100, 256, 257, 1000])
    def test_random_curves_bit_identical(self, n):
        rng = np.random.default_rng(n)
        x = jnp.asarray(np.linspace(0.0, 10.0, n))
        for trial in range(5):
            y = jnp.asarray(np.cumsum(rng.normal(size=n)))
            level = float(np.quantile(np.asarray(y), rng.uniform(0.05, 0.95)))
            _assert_crossings_identical(x, y, level, 10.0)

    def test_hazard_shaped_curve(self):
        """The actual workload shape: unimodal hazard, level sweeping from
        below the min to above the max (the no-crossing fallback rungs)."""
        x = jnp.asarray(np.linspace(0.0, 15.0, 512))
        y = jnp.asarray(np.exp(-0.5 * (np.asarray(x) - 6.0) ** 2) * 0.8)
        for level in [-0.1, 0.0, 0.2, 0.5, 0.79999, 0.8, 0.9]:
            _assert_crossings_identical(x, y, level, 15.0)

    def test_fallback_rungs_and_flags(self):
        x = jnp.asarray(np.linspace(0.0, 1.0, 64))
        always_above = jnp.ones(64) * 2.0
        ref = _scan_pair(x, always_above, 1.0, 9.0)
        got = threshold_crossings_masked(x, always_above, 1.0, 9.0, with_health=True)
        # always above: no transition, first/last-knot fallback
        assert float(got[0]) == float(ref[0]) == 0.0
        assert float(got[2]) == float(ref[2]) == 1.0
        assert int(got[4].flags) & FALLBACK_IN_KNOT
        never_above = jnp.zeros(64)
        got2 = threshold_crossings_masked(x, never_above, 1.0, 9.0, with_health=True)
        assert float(got2[0]) == float(got2[2]) == 9.0
        assert int(got2[4].flags) & FALLBACK_IN_DEFAULT
        _assert_crossings_identical(x, never_above, 1.0, 9.0)

    def test_nan_poison_bit_identical(self):
        x = jnp.asarray(np.linspace(0.0, 1.0, 128))
        y = np.sin(np.asarray(x) * 7.0)
        for poison in [slice(0, 5), slice(60, 70), slice(120, 128)]:
            yp = y.copy()
            yp[poison] = np.nan
            _assert_crossings_identical(x, jnp.asarray(yp), 0.3, 2.0)
        _assert_crossings_identical(x, jnp.full(128, jnp.nan), 0.3, 2.0)  # all NaN
        # NaN level disables every crossing on both paths
        _assert_crossings_identical(x, jnp.asarray(y), jnp.nan, 2.0)
        got = threshold_crossings_masked(x, jnp.full(128, jnp.nan), 0.3, 2.0, with_health=True)
        assert int(got[4].flags) & NAN_INPUT

    def test_exact_knot_touch(self):
        """y == level at a knot: `>` strictness must match the scan exactly."""
        x = jnp.asarray(np.linspace(0.0, 1.0, 33))
        y = np.zeros(33)
        y[10:20] = 1.0
        y[15] = 0.5  # dip exactly to the level
        _assert_crossings_identical(x, jnp.asarray(y), 0.5, 3.0)
        _assert_crossings_identical(x, jnp.asarray(y), 1.0, 3.0)

    def test_under_vmap(self):
        """Batched curves (the sweep layout) stay bit-identical lane-wise."""
        rng = np.random.default_rng(7)
        x = jnp.asarray(np.linspace(0.0, 5.0, 200))
        ys = jnp.asarray(np.cumsum(rng.normal(size=(8, 200)), axis=-1))
        levels = jnp.asarray(rng.normal(size=8))
        blocked = jax.vmap(lambda y, l: threshold_crossings_masked(x, y, l, 5.0))(ys, levels)
        for k in range(8):
            ref = _scan_pair(x, ys[k], levels[k], 5.0)
            for r, g in zip(ref[:4], [b[k] for b in blocked]):
                assert np.asarray(r).tobytes() == np.asarray(g).tobytes()


# -- adaptive ODE -------------------------------------------------------------


class TestBS32:
    def test_exponential_decay_accuracy(self):
        ts = jnp.linspace(0.0, 2.0, 41)
        ys = bs32(lambda t, y, _: -1.5 * y, jnp.asarray(1.0), ts, rtol=1e-8, atol=1e-12)
        assert ys.shape == (41,)
        assert float(ys[0]) == 1.0
        np.testing.assert_allclose(np.asarray(ys), np.exp(-1.5 * np.asarray(ts)), rtol=1e-6)

    def test_matches_dense_rk4_on_logistic(self):
        """The hetero RHS shape: logistic growth, vector state."""
        f = lambda t, y, _: y * (1.0 - y)
        y0 = jnp.asarray([1e-4, 1e-2, 0.3])
        ts = jnp.linspace(0.0, 12.0, 257)
        adaptive = bs32(f, y0, ts, rtol=1e-9, atol=1e-12)
        fixed = rk4(f, y0, ts, substeps=8)
        assert adaptive.shape == fixed.shape == (257, 3)
        np.testing.assert_allclose(np.asarray(adaptive), np.asarray(fixed), rtol=0, atol=1e-8)

    def test_cheap_on_smooth_dense_grid(self):
        """A dense save grid on smooth dynamics costs ~1 attempt per
        interval — the speedup the fixed worst-case substeps left behind."""
        ts = jnp.linspace(0.0, 1.0, 513)
        _, h = bs32(lambda t, y, _: -y, jnp.asarray(1.0), ts, with_health=True)
        assert int(h.iterations) < 2 * 512
        assert int(h.flags) & ODE_BUDGET == 0

    def test_budget_exhaustion_flagged(self):
        """Fast dynamics under an artificially tiny per-interval cap: the
        bridge fires and Health carries ODE_BUDGET."""
        ts = jnp.linspace(0.0, 1.0, 3)
        out, h = bs32(
            lambda t, y, _: -800.0 * y, jnp.asarray(1.0), ts,
            rtol=1e-10, atol=1e-12, max_steps_per_interval=2, with_health=True,
        )
        assert int(h.flags) & ODE_BUDGET
        assert np.all(np.isfinite(np.asarray(out)))


# -- numerics="fixed": bitwise regression vs the pre-PR solver ---------------


@pytest.fixture(scope="module")
def golden():
    return np.load(GOLDEN)


class TestFixedBitwise:
    """Golden arrays in tests/data/golden_fixed_numerics.npz were captured
    from the PARENT commit (pre-adaptive solver, f64, CPU). The fixed path
    must reproduce them byte-for-byte — the escape-hatch contract that keeps
    the chaos/golden/parity suites and tile-cache keys stable."""

    def test_grid_bitwise_identical(self, golden):
        from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

        cfg = SolverConfig(n_grid=512, bisect_iters=60, refine_crossings=False, numerics="fixed")
        g = beta_u_grid(golden["betas"], golden["us"], make_model_params(), config=cfg, dtype=jnp.float64)
        for name, got in [("grid_xi", g.xi), ("grid_aw", g.max_aw), ("grid_status", g.status)]:
            got = np.asarray(got)
            assert got.dtype == golden[name].dtype
            assert got.tobytes() == golden[name].tobytes(), name

    def test_baseline_scalar_bitwise(self, golden):
        from sbr_tpu import solve_equilibrium_baseline, solve_learning

        cfg = SolverConfig(numerics="fixed")
        base = make_model_params()
        ls = solve_learning(base.learning, cfg)
        res = solve_equilibrium_baseline(ls, base.economic, cfg)
        assert float(res.xi) == float(golden["scalar_xi"])
        assert float(res.aw_max) == float(golden["scalar_aw"])

    def test_hetero_scalar_bitwise(self, golden):
        from sbr_tpu.hetero.learning import solve_learning_hetero
        from sbr_tpu.hetero.solver import get_aw_hetero, solve_equilibrium_hetero
        from sbr_tpu.models.params import make_hetero_params

        cfg = SolverConfig(numerics="fixed")
        m = make_hetero_params(
            betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
        )
        lsh = solve_learning_hetero(m.learning, cfg)
        res = solve_equilibrium_hetero(lsh, m.economic, cfg)
        assert float(res.xi) == float(golden["hetero_xi"])
        assert float(get_aw_hetero(res, lsh).aw_max) == float(golden["hetero_aw"])

    @pytest.mark.slow
    def test_social_fixed_point_bitwise(self, golden):
        from sbr_tpu.social.solver import solve_equilibrium_social

        m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
        res = solve_equilibrium_social(
            m, SolverConfig(n_grid=1024, numerics="fixed"), tol=1e-4, max_iter=200
        )
        assert bool(res.converged) == bool(golden["social_converged"])
        assert int(res.iterations) == int(golden["social_iters"])
        assert float(res.equilibrium.xi) == float(golden["social_xi"])


# -- adaptive vs fixed across the solver stacks ------------------------------


class TestAdaptiveVsFixed:
    def test_grid_status_exact_xi_close(self, golden):
        """The acceptance-criteria parity shape in miniature: status grids
        match EXACTLY, ξ to 1e-10, and adaptive's Health carries real
        per-cell iteration counts far under the fixed budget. Reuses the
        golden 12×12 shape so the fixed-mode program shares its compile
        with TestFixedBitwise."""
        from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

        base = make_model_params()
        betas, us = golden["betas"], golden["us"]
        kw = dict(n_grid=512, bisect_iters=60, refine_crossings=False)
        g_a = beta_u_grid(betas, us, base, config=SolverConfig(numerics="adaptive", **kw), dtype=jnp.float64)
        g_f = beta_u_grid(betas, us, base, config=SolverConfig(numerics="fixed", **kw), dtype=jnp.float64)
        assert np.array_equal(np.asarray(g_a.status), np.asarray(g_f.status))
        xi_a, xi_f = np.asarray(g_a.xi), np.asarray(g_f.xi)
        both = np.isfinite(xi_a) & np.isfinite(xi_f)
        assert np.array_equal(np.isfinite(xi_a), np.isfinite(xi_f))
        np.testing.assert_allclose(xi_a[both], xi_f[both], rtol=0, atol=1e-10)
        it_a = np.asarray(g_a.health.iterations)
        it_f = np.asarray(g_f.health.iterations)
        assert it_a.mean() < 0.5 * it_f.mean()  # typically ~7-25 vs 60

    def test_interest_agreement(self):
        from sbr_tpu import solve_learning
        from sbr_tpu.interest import solve_equilibrium_interest
        from sbr_tpu.models.params import make_interest_params

        m = make_interest_params(beta=1.0, eta_bar=15.0, u=0.0, p=0.5, kappa=0.6, lam=0.01, r=0.06, delta=0.1)
        out = {}
        for mode in ("adaptive", "fixed"):
            cfg = SolverConfig(n_grid=1024, numerics=mode)
            ls = solve_learning(m.learning, cfg)
            out[mode] = solve_equilibrium_interest(ls, m.economic, cfg)
        assert bool(out["adaptive"].base.bankrun) == bool(out["fixed"].base.bankrun)
        assert float(out["adaptive"].base.xi) == pytest.approx(float(out["fixed"].base.xi), abs=1e-6)

    def test_hetero_agreement(self):
        """Covers both hetero-only adaptive kernels: bs32 on the coupled-K
        ODE (whole-vector error norm) and chandrupatla in compute_xi_hetero.
        Same params as TestFixedBitwise so the fixed program shares its
        compile."""
        from sbr_tpu.hetero.learning import solve_learning_hetero
        from sbr_tpu.hetero.solver import get_aw_hetero, solve_equilibrium_hetero
        from sbr_tpu.models.params import make_hetero_params

        m = make_hetero_params(
            betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
        )
        out = {}
        for mode in ("adaptive", "fixed"):
            cfg = SolverConfig(numerics=mode)
            lsh = solve_learning_hetero(m.learning, cfg)
            out[mode] = (solve_equilibrium_hetero(lsh, m.economic, cfg), lsh)
        r_a, lsh_a = out["adaptive"]
        r_f, lsh_f = out["fixed"]
        assert int(r_a.status) == int(r_f.status)
        assert float(r_a.xi) == pytest.approx(float(r_f.xi), abs=1e-6)
        assert float(get_aw_hetero(r_a, lsh_a).aw_max) == pytest.approx(
            float(get_aw_hetero(r_f, lsh_f).aw_max), abs=1e-8
        )

    def test_hetero_sharded_agreement(self):
        """compute_xi_hetero's comment claims the convergence-masked
        while_loop is shard-safe (every f-eval psum-completed, so all
        shards see identical iterates and termination). Exercise it on the
        8-virtual-device mesh: a jax upgrade that tightens shard_map's
        replication checking must fail HERE, not in production under the
        adaptive default."""
        from sbr_tpu.hetero import solve_hetero_sharded
        from sbr_tpu.models.params import make_hetero_params

        rng = np.random.default_rng(3)
        k = 16  # 2 groups/device on the 8-device mesh
        betas = np.exp(rng.uniform(np.log(0.3), np.log(3.0), k))
        dist = rng.dirichlet(np.ones(k))
        m = make_hetero_params(
            betas=betas, dist=dist / dist.sum(), eta_bar=15.0, u=0.1, p=0.5,
            kappa=0.6, lam=0.01,
        )
        mesh = jax.make_mesh((8,), ("k",))
        out = {}
        for mode in ("adaptive", "fixed"):
            cfg = SolverConfig(n_grid=512, bisect_iters=60, numerics=mode)
            _, res, aw = solve_hetero_sharded(m, mesh, cfg)
            out[mode] = (res, aw)
        r_a, aw_a = out["adaptive"]
        r_f, aw_f = out["fixed"]
        assert int(r_a.status) == int(r_f.status)
        # Sharded learning keeps fixed RK4 under both modes (bit-exact
        # sharding equivalence), so only the ξ bisection differs: both
        # bracketers converge the bracket below 1e-9 here.
        np.testing.assert_allclose(float(r_a.xi), float(r_f.xi), atol=1e-9)
        np.testing.assert_allclose(float(aw_a.aw_max), float(aw_f.aw_max), atol=1e-9)

    @pytest.mark.slow
    def test_social_agreement(self):
        """The Anderson-accelerated tail lands within the fixed point's own
        tolerance envelope of the plain damped loop (tests/test_reference_parity
        pins the damped iteration count; this pins cross-mode agreement)."""
        from sbr_tpu.social.solver import solve_equilibrium_social

        m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
        out = {}
        for mode in ("adaptive", "fixed"):
            out[mode] = solve_equilibrium_social(
                m, SolverConfig(n_grid=1024, numerics=mode), tol=1e-4, max_iter=200
            )
        assert bool(out["adaptive"].converged) and bool(out["fixed"].converged)
        # ξ amplifies the 1e-4 AW tolerance through the crossing geometry;
        # 5e-3 is the measured cross-trajectory envelope at these params.
        assert float(out["adaptive"].equilibrium.xi) == pytest.approx(
            float(out["fixed"].equilibrium.xi), abs=5e-3
        )
        assert int(out["adaptive"].iterations) <= int(out["fixed"].iterations) + 5


# -- SolverConfig numerics resolution ----------------------------------------


class TestNumericsConfig:
    def test_auto_resolves_adaptive_by_default(self, monkeypatch):
        monkeypatch.delenv("SBR_NUMERICS", raising=False)
        cfg = SolverConfig()
        assert cfg.numerics == "adaptive" and cfg.adaptive

    def test_env_var_pins_fixed(self, monkeypatch):
        monkeypatch.setenv("SBR_NUMERICS", "fixed")
        cfg = SolverConfig()
        assert cfg.numerics == "fixed" and not cfg.adaptive
        # explicit beats env
        assert SolverConfig(numerics="adaptive").adaptive

    def test_invalid_mode_rejected(self):
        with pytest.raises(Exception):
            SolverConfig(numerics="turbo")

    def test_fingerprints_distinguish_modes(self):
        """Adaptive and fixed tiles must never share cache entries: the
        resolved mode is concrete in the config, so fingerprints differ —
        and GRID_PROGRAM_VERSION bumped for the cross-run tile cache."""
        from sbr_tpu.sweeps.baseline_sweeps import GRID_PROGRAM_VERSION
        from sbr_tpu.utils.checkpoint import params_fingerprint

        assert GRID_PROGRAM_VERSION >= 2
        fa = params_fingerprint(SolverConfig(numerics="adaptive"))
        ff = params_fingerprint(SolverConfig(numerics="fixed"))
        assert fa != ff


# -- history schema 5 ---------------------------------------------------------


class TestHistorySchema5:
    def test_bench_metrics_pick_up_numerics_columns(self):
        from sbr_tpu.obs import history

        m = history.bench_metrics(
            {
                "metric": "eq_per_sec",
                "value": 1.0,
                "extra": {"grid_adaptive_speedup": 2.4, "grid_mean_effective_iters": 9.1},
            }
        )
        assert m["grid_adaptive_speedup"] == 2.4
        assert m["grid_mean_effective_iters"] == 9.1

    def test_polarity(self):
        from sbr_tpu.obs import history

        assert history.polarity("grid_adaptive_speedup") == 1
        assert history.polarity("grid_mean_effective_iters") == -1

    def test_schema5_gates_against_schema1_to_4(self, tmp_path):
        """Committed schema 1-4 lines still load, and a schema-5 append
        gates its shared metrics against them (the CI trend gate contract)."""
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        rows = [
            {"ts": "t0", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1000.0}},  # schema-less → 1
            {"schema": 2, "ts": "t1", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1010.0, "mem_peak_bytes": 5000}},
            {"schema": 3, "ts": "t2", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1005.0, "serve_p99_ms": 4.0}},
            {"schema": 4, "ts": "t3", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1002.0, "sweep_warm_hit_rate": 1.0}},
        ]
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        history.append(
            {"eq_per_sec": 1008.0, "grid_adaptive_speedup": 2.2, "grid_mean_effective_iters": 9.0},
            platform="cpu", path=path,
        )
        records = history.load(path)
        assert [r["schema"] for r in records] == [1, 2, 3, 4, history.SCHEMA]
        verdicts, status = history.check(records, min_points=3)
        assert status == "ok"
        assert verdicts["eq_per_sec"]["n"] == 5
        # new columns are short, never a false gate
        assert verdicts["grid_adaptive_speedup"]["status"] == "short"

    def test_speedup_regression_gates(self, tmp_path):
        from sbr_tpu.obs import history

        rows = [
            {"schema": 5, "ts": f"t{i}", "label": "bench", "platform": "cpu",
             "metrics": {"grid_adaptive_speedup": 2.0}}
            for i in range(3)
        ] + [
            {"schema": 5, "ts": "t9", "label": "bench", "platform": "cpu",
             "metrics": {"grid_adaptive_speedup": 1.0}}
        ]
        path = tmp_path / "hist.jsonl"
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        verdicts, status = history.check(history.load(path), min_points=3)
        assert status == "regression"
        assert verdicts["grid_adaptive_speedup"]["status"] == "regression"
