"""Tests for the performance observatory (`sbr_tpu.obs.prof`, ISSUE 3
tentpole): the retrace detector (a jitted function called with churning
shapes must produce `retrace` events with the correct counts), XLA compile
attribution via the jax.monitoring listeners, opt-in profiler capture with
the size bound, and the acceptance contract that enabling SBR_OBS_PROFILE
and the listeners changes no solver output and causes zero additional
retraces (the `tests/test_diag.py` no-retrace/no-value-change discipline).
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu import obs
from sbr_tpu.obs import prof


@pytest.fixture(autouse=True)
def _no_active_run():
    assert obs.current_run() is None
    was_on = obs.metrics().enabled
    yield
    while obs.end_run() is not None:
        pass
    (obs.metrics().enable if was_on else obs.metrics().disable)()


def _events(run_dir):
    return [
        json.loads(line)
        for line in (Path(run_dir) / "events.jsonl").read_text().splitlines()
    ]


# -- retrace detector --------------------------------------------------------


def test_retrace_detector_counts_shape_churn(tmp_path):
    """A jitted function fed churning shapes retraces per call; once the
    within-run count passes its budget, each further trace lands a
    `retrace` event with the correct running count."""

    @jax.jit
    def f(x):
        prof.note_trace("test_prof.churn", budget=2)
        return (x * 2.0).sum()

    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        vals = [float(f(jnp.arange(float(n)))) for n in (2, 3, 4, 5)]
    # instrumentation changes no values
    assert vals == [float(sum(2.0 * i for i in range(n))) for n in (2, 3, 4, 5)]

    retraces = [e for e in _events(run.run_dir) if e["kind"] == "retrace"]
    assert [e["count"] for e in retraces] == [3, 4]
    assert all(e["name"] == "test_prof.churn" and e["budget"] == 2 for e in retraces)

    manifest = json.loads((run.run_dir / "manifest.json").read_text())
    entry = manifest["retraces"]["test_prof.churn"]
    assert entry == {"traces": 4, "budget": 2, "over_budget": True}


def test_retrace_detector_quiet_on_stable_shapes(tmp_path):
    @jax.jit
    def f(x):
        prof.note_trace("test_prof.stable", budget=1)
        return x + 1.0

    x = jnp.arange(4.0)
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        for _ in range(5):
            f(x)
    assert not [e for e in _events(run.run_dir) if e["kind"] == "retrace"]
    manifest = json.loads((run.run_dir / "manifest.json").read_text())
    assert manifest["retraces"]["test_prof.stable"]["over_budget"] is False


def test_note_trace_counts_without_run():
    """The registry counts process-wide even with telemetry off — a later
    run reports only its own delta."""
    before = prof.trace_counts().get("test_prof.bare", 0)

    @jax.jit
    def f(x):
        prof.note_trace("test_prof.bare")
        return x * 2

    f(jnp.arange(3.0))
    assert prof.trace_counts()["test_prof.bare"] == before + 1


# -- compile attribution (jax.monitoring) ------------------------------------


def test_compile_attribution_to_active_span(tmp_path):
    if not prof.install():
        pytest.skip("jax.monitoring unavailable on this jax build")
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        with obs.span("compile_here"):
            # a fresh lambda can never hit an existing jit cache
            float(jax.jit(lambda x: (x * 1.5).sum())(jnp.arange(6.0)))
    manifest = json.loads((run.run_dir / "manifest.json").read_text())
    xla = manifest["xla"]
    assert xla["monitoring"] is True
    assert xla["compiles"] >= 1
    assert xla["backend_compile_s"] > 0.0
    assert "compile_here" in xla["by_span"]
    assert xla["by_span"]["compile_here"]["compiles"] >= 1
    compile_events = [e for e in _events(run.run_dir) if e["kind"] == "xla_compile"]
    assert any(e["span"] == "compile_here" for e in compile_events)
    assert any(e["phase"] == "backend_compile" for e in compile_events)


# -- profiler capture --------------------------------------------------------


def test_profile_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("SBR_OBS_PROFILE", raising=False)
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        with obs.profile("nope") as trace_dir:
            assert trace_dir is None
    assert not [e for e in _events(run.run_dir) if e["kind"] == "profile"]
    manifest = json.loads((run.run_dir / "manifest.json").read_text())
    assert manifest["profiles"] is None


def test_profile_capture_records_summary(tmp_path, monkeypatch):
    monkeypatch.setenv("SBR_OBS_PROFILE", "1")
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        with obs.profile("cap") as trace_dir:
            assert trace_dir is not None
            float(jax.jit(lambda x: (x * 2.0).sum())(jnp.arange(32.0)))
    (ev,) = [e for e in _events(run.run_dir) if e["kind"] == "profile"]
    assert ev["label"] == "cap"
    assert ev["files"] > 0 and ev["bytes"] > 0 and ev["pruned"] is False
    assert ev["window_s"] > 0.0
    # the capture lives INSIDE the run dir, so run retention prunes it too
    assert str(run.run_dir) in ev["trace_dir"]
    assert Path(ev["trace_dir"]).is_dir()
    manifest = json.loads((run.run_dir / "manifest.json").read_text())
    assert manifest["profiles"][0]["label"] == "cap"


def test_profile_size_bound_prunes_capture(tmp_path, monkeypatch):
    monkeypatch.setenv("SBR_OBS_PROFILE", "1")
    monkeypatch.setenv("SBR_OBS_PROFILE_MAX_MB", "0.0001")  # ~100 bytes
    with obs.run_context(run_dir=str(tmp_path / "r")) as run:
        with obs.profile("big") as trace_dir:
            float(jax.jit(lambda x: (x * 2.0).sum())(jnp.arange(32.0)))
    (ev,) = [e for e in _events(run.run_dir) if e["kind"] == "profile"]
    assert ev["pruned"] is True
    assert not Path(ev["trace_dir"]).exists()


# -- acceptance: observatory toggles perturb nothing -------------------------


def test_profiling_env_and_listeners_cause_no_retrace_no_value_change(tmp_path, monkeypatch):
    """ISSUE 3 acceptance: with the monitoring listeners installed and
    SBR_OBS_PROFILE=1 (annotations active on every span), a traced library
    program is neither invalidated nor retraced and its outputs are
    unchanged."""
    prof.install()
    traces = []

    @jax.jit
    def g(x):
        traces.append(1)  # runs only at trace time
        prof.note_trace("test_prof.accept")
        with obs.span("inner"):  # trace guard → no-op under tracing
            return (x * 3.0).sum()

    x = jnp.arange(8.0)
    y_off = float(g(x))
    assert len(traces) == 1
    monkeypatch.setenv("SBR_OBS_PROFILE", "1")
    with obs.run_context(run_dir=str(tmp_path / "r")):
        with obs.span("outer"), obs.step_annotation(0, "rep"):
            y_on = float(g(x))
    monkeypatch.delenv("SBR_OBS_PROFILE")
    y_off2 = float(g(x))
    assert len(traces) == 1, "observatory toggle retraced the program"
    assert y_on == y_off == y_off2


def test_solver_outputs_identical_under_profiling_env(tmp_path, monkeypatch):
    """The sweep stack solved with SBR_OBS_PROFILE=1 (span annotations on)
    must be bit-identical to the plain path."""
    from sbr_tpu import make_model_params
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    m = make_model_params()
    cfg = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)
    betas, us = np.array([0.5, 1.0]), np.array([0.05, 0.5])
    plain = beta_u_grid(betas, us, m, config=cfg)
    monkeypatch.setenv("SBR_OBS_PROFILE", "1")
    with obs.run_context(run_dir=str(tmp_path / "r")):
        profiled = beta_u_grid(betas, us, m, config=cfg)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain), jax.tree_util.tree_leaves(profiled)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
