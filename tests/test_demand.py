"""Workload-demand observatory tests (ISSUE 18): Misra-Gries sketch
guarantees (merge commutativity item-for-item, associativity under
capacity, deterministic top-k), fixed-grid binning, the streaming
`DemandTracker` (window expiry on an injected clock, answer-source
labels, compact heartbeat blocks), fleet merge through the router, the
prefetch advisor (pure + byte-stable plans, cross-PROCESS determinism via
the replay CLI), `report demand` gating, `report gc --demand-keep`
retention, loadgen trace-row replay (backfill tolerance), the
SBR_DEMAND=0 structural no-op witness (module never imported, /metrics
byte-free, zero new XLA traces, bit-identical answers), history schema
12, and the advisor-closes-the-loop e2e gate (plan tiles swept into the
tile cache turn a red coverage gate green on a real engine replay).
"""

import hashlib
import json
import os
import random
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.obs import demand as dm

REPO = Path(__file__).resolve().parent.parent

CFG = SolverConfig(n_grid=64, bisect_iters=20, refine_crossings=False)

PAYLOAD = {"beta": 1.0, "u": 0.1, "scenario": "mix", "kind": "plain"}


def _feq(a, b) -> bool:
    """Bitwise float equality (NaN-safe): the byte-identity contract."""
    return np.float64(a).tobytes() == np.float64(b).tobytes()


# ---------------------------------------------------------------------------
# Misra-Gries sketch guarantees
# ---------------------------------------------------------------------------


class TestMisraGries:
    def test_heavy_hitter_guarantee(self):
        # Any item with frequency > N/(k+1) must be tracked, with count
        # undershooting by at most N/(k+1).
        sk = dm.MisraGries(2)
        stream = ["hot"] * 60 + ["a", "b", "c", "d"] * 10  # N=100, k=2
        random.Random(0).shuffle(stream)
        for item in stream:
            sk.update(item, PAYLOAD)
        assert "hot" in sk.counters
        assert 60 - 100 / 3 <= sk.counters["hot"] <= 60

    def test_merge_commutative_item_for_item(self):
        a, b = dm.MisraGries(3), dm.MisraGries(3)
        for item, n in [("x", 9), ("y", 4), ("z", 2)]:
            a.update(item, PAYLOAD, n)
        for item, n in [("x", 1), ("q", 7), ("r", 3), ("y", 2)]:
            b.update(item, PAYLOAD, n)
        ab, ba = a.merge(b), b.merge(a)
        # The satellite contract: merge(a, b) == merge(b, a) ITEM FOR ITEM
        # (same keys, same counts), not merely same top-k ordering.
        assert ab.counters == ba.counters
        assert ab.top() == ba.top()

    def test_merge_associative_under_capacity(self):
        # With capacity for the union (no decrement applied), merged counts
        # are exact itemwise sums — fully associative.
        sketches = []
        for seed in range(3):
            sk = dm.MisraGries(16)
            rng = random.Random(seed)
            for _ in range(50):
                sk.update(f"item{rng.randrange(6)}", PAYLOAD)
            sketches.append(sk)
        a, b, c = sketches
        left = a.merge(b).merge(c)
        right = a.merge(b.merge(c))
        assert left.counters == right.counters

    def test_deterministic_topk_under_seeded_stream(self):
        def run():
            sk = dm.MisraGries(8)
            rng = random.Random(1234)
            for _ in range(5000):
                # Zipf-ish skew so there ARE heavy hitters to rank.
                item = f"q{min(rng.randrange(1, 40), rng.randrange(1, 40))}"
                sk.update(item, PAYLOAD)
            return sk.top()

        assert run() == run()

    def test_top_ties_break_by_item_key(self):
        sk = dm.MisraGries(8)
        for item in ("bb", "aa", "cc"):
            sk.update(item, PAYLOAD, 5)
        assert [i for i, _, _ in sk.top()] == ["aa", "bb", "cc"]

    def test_doc_roundtrip(self):
        sk = dm.MisraGries(4)
        for item, n in [("x", 3), ("y", 1)]:
            sk.update(item, {**PAYLOAD, "beta": float(n)}, n)
        back = dm.MisraGries.from_doc(sk.to_doc())
        assert back.counters == sk.counters
        assert back.payloads == sk.payloads
        # Torn docs degrade to empty, never raise.
        assert dm.MisraGries.from_doc({"items": [["x"], None, 3]}).counters == {}


# ---------------------------------------------------------------------------
# Binning + fingerprints
# ---------------------------------------------------------------------------


class TestBinning:
    def test_grid_aligned_to_sweep_ranges(self):
        nb = 16
        assert dm.bin_of(dm.BETA_RANGE[0], dm.U_RANGE[0], nb) == (0, 0)
        # Upper edges (and anything beyond) clamp into the last bin.
        assert dm.bin_of(dm.BETA_RANGE[1], dm.U_RANGE[1], nb) == (nb - 1, nb - 1)
        assert dm.bin_of(99.0, -5.0, nb) == (nb - 1, 0)
        b = dm.bin_bounds(0, 0, nb)
        assert b["beta_lo"] == dm.BETA_RANGE[0] and b["u_lo"] == dm.U_RANGE[0]

    def test_fingerprint_is_stable_hash_of_exact_coords(self):
        fp = dm.query_fingerprint(1.25, 0.3, "mix", "plain")
        expected = hashlib.sha256(
            f"{1.25!r}|{0.3!r}|mix|plain".encode()
        ).hexdigest()[:16]
        assert fp == expected
        # kind and scenario are part of the identity
        assert fp != dm.query_fingerprint(1.25, 0.3, "mix", "grads")
        assert fp != dm.query_fingerprint(1.25, 0.3, "other", "plain")


# ---------------------------------------------------------------------------
# DemandTracker (streaming, windowed)
# ---------------------------------------------------------------------------


class TestDemandTracker:
    def _tracker(self, clock, window_s=12.0):
        return dm.DemandTracker(window_s=window_s, bins=8, topk_n=8,
                                time_fn=lambda: clock[0])

    def test_sources_split_warm_and_cold(self):
        clock = [100.0]
        tr = self._tracker(clock)
        for k in range(40):
            tr.record(1.0, 0.1, source="lru" if k % 2 else "computed")
        hot = tr.snapshot()["hot_bins"]
        assert len(hot) == 1
        assert hot[0]["count"] == 40 and hot[0]["warm"] == 20
        assert hot[0]["warm_coverage"] == 0.5

    def test_window_expires_but_totals_persist(self):
        clock = [100.0]
        tr = self._tracker(clock, window_s=12.0)
        tr.record(1.0, 0.1)
        tr.record(2.0, 0.5)
        assert tr.window_surface()["queries"] == 2
        clock[0] += 13.0  # one full window later: all slots stale
        assert tr.window_surface()["queries"] == 0
        assert tr.totals_surface()["queries"] == 2
        assert tr.queries_total == 2

    def test_record_never_raises(self):
        clock = [0.0]
        tr = self._tracker(clock)
        tr.record("junk", None, scenario=object())  # type: ignore[arg-type]
        tr.record_params(object())  # no .learning/.economic
        assert tr.queries_total == 0

    def test_heartbeat_block_caps_cells(self):
        clock = [50.0]
        tr = dm.DemandTracker(window_s=1000.0, bins=16, topk_n=4,
                              time_fn=lambda: clock[0])
        # Spread queries over >64 distinct bins of the 16x16 grid.
        for i in range(16):
            for j in range(6):
                tr.record(0.51 + i * 0.218, 0.03 + j * 0.14)
        hb = tr.heartbeat_block()
        assert len(hb["cells"]) == 64
        assert len(hb["sketch"]["items"]) <= 4
        # The full window surface is uncapped (some pairs share a bin, so
        # compare against the observed distinct-cell count, not 16*6).
        assert len(tr.window_surface()["cells"]) > 64

    def test_prometheus_lines(self):
        clock = [5.0]
        tr = self._tracker(clock)
        tr.record(1.0, 0.1, source="lru")
        text = "\n".join(tr.prometheus_lines())
        assert "sbr_demand_queries_total 1" in text
        assert "sbr_demand_window_queries 1" in text
        assert "sbr_demand_hot_warm_coverage 1" in text


# ---------------------------------------------------------------------------
# Surface merge + fleet (router) merge
# ---------------------------------------------------------------------------


def _surface_from(counts_sources, bins=8, k=8):
    """Tiny surface builder: {(beta, u, source): n} -> surface doc."""
    tr = dm.DemandTracker(window_s=1000.0, bins=bins, topk_n=k,
                          time_fn=lambda: 1.0)
    for (beta, u, source), n in counts_sources.items():
        for _ in range(n):
            tr.record(beta, u, source=source)
    return tr.heartbeat_block()


class TestMergeSurfaces:
    def test_merge_sums_cells_sources_and_sketch(self):
        a = _surface_from({(1.0, 0.1, "computed"): 3, (2.0, 0.5, "lru"): 1})
        b = _surface_from({(1.0, 0.1, "lru"): 2})
        m = dm.merge_surfaces([a, b])
        assert m["queries"] == 6
        hot = dm.hot_bins(m)
        assert hot[0]["count"] == 5 and hot[0]["warm"] == 2

    def test_mismatched_binning_skipped_not_smeared(self):
        a = _surface_from({(1.0, 0.1, "computed"): 2}, bins=8)
        b = _surface_from({(1.0, 0.1, "computed"): 2}, bins=16)
        m = dm.merge_surfaces([a, b])
        assert m["queries"] == 2
        assert m["skipped_surfaces"] == 1

    def test_router_merges_heartbeat_blocks(self, tmp_path):
        from sbr_tpu.serve.fleet import WorkerAnnouncer
        from sbr_tpu.serve.router import Router

        w0 = WorkerAnnouncer(tmp_path, "http://127.0.0.1:1", host="w0")
        w1 = WorkerAnnouncer(tmp_path, "http://127.0.0.1:2", host="w1")
        w0.beat(demand=_surface_from({(1.0, 0.1, "computed"): 3}))
        w1.beat(demand=_surface_from({(1.0, 0.1, "lru"): 2,
                                      (3.0, 0.8, "computed"): 1}))
        router = Router(tmp_path, poll_s=0.01)
        router.refresh_workers(force=True)
        merged = router.fleet_demand()
        assert merged is not None
        assert merged["queries"] == 6
        assert merged["workers"] == ["w0", "w1"]
        assert router.statz()["demand"]["queries"] == 6
        text = router.prometheus()
        assert "sbr_demand_fleet_window_queries 6" in text
        assert "sbr_demand_fleet_workers 2" in text

    def test_router_without_demand_blocks_stays_byte_free(self, tmp_path):
        from sbr_tpu.serve.fleet import WorkerAnnouncer
        from sbr_tpu.serve.router import Router

        WorkerAnnouncer(tmp_path, "http://127.0.0.1:1", host="w0").beat(qps=1.0)
        router = Router(tmp_path, poll_s=0.01)
        router.refresh_workers(force=True)
        assert router.fleet_demand() is None
        assert "demand" not in router.statz()
        assert "sbr_demand" not in router.prometheus()


# ---------------------------------------------------------------------------
# Prefetch advisor
# ---------------------------------------------------------------------------


class TestAdvisorPlan:
    def test_plan_is_pure_and_byte_stable(self):
        s = _surface_from({(1.0, 0.1, "computed"): 5, (2.0, 0.5, "computed"): 3})
        p1 = dm.advisor_plan(s, None, floor=0.5)
        p2 = dm.advisor_plan(s, None, floor=0.5)
        assert dm.plan_bytes(p1) == dm.plan_bytes(p2)
        assert p1["plan_fingerprint"] == p2["plan_fingerprint"]
        assert p1["tiles"][0]["rank"] == 1
        # The top tile names the exact hot coordinates to sweep.
        assert p1["tiles"][0]["betas"] == [1.0] and p1["tiles"][0]["us"] == [0.1]

    def test_covered_demand_scores_zero(self):
        s = _surface_from({(1.0, 0.1, "computed"): 5})
        cov = {"entries": 1, "pairs": [[1.0, 0.1]]}
        plan = dm.advisor_plan(s, cov)
        assert plan["tiles"][0]["tile_coverage"] == 1.0
        assert plan["tiles"][0]["score"] == 0.0
        # Uncovered: full demand weight.
        assert dm.advisor_plan(s, None)["tiles"][0]["score"] == 5.0

    def test_coverage_from_cache_dir_reads_meta_sidecars(self, tmp_path):
        (tmp_path / "a.meta.json").write_text(json.dumps(
            {"key": "k", "cell_tag": "t", "betas": [1.0, 2.0], "us": [0.1]}
        ))
        (tmp_path / "torn.meta.json").write_text("{nope")
        cov = dm.coverage_from_cache_dir(tmp_path)
        assert cov["entries"] == 1
        assert cov["pairs"] == [[1.0, 0.1], [2.0, 0.1]]
        # Missing root: None (no cache configured != empty cache).
        assert dm.coverage_from_cache_dir(tmp_path / "nope") is None


# ---------------------------------------------------------------------------
# Offline replay (loadgen --trace-out rows) + the cross-process witness
# ---------------------------------------------------------------------------


def _trace_rows(n=30):
    """A deterministic hot-stream trace: two hot cells + a cold tail."""
    rows = []
    for k in range(n):
        if k % 3 == 0:
            beta, u = 1.25, 0.3
        elif k % 3 == 1:
            beta, u = 1.25, 0.31
        else:
            beta, u = 0.6 + (k % 7) * 0.41, 0.8
        rows.append({"query": k, "beta": beta, "u": u, "scenario": "mix",
                     "kind": "plain", "source": "computed", "status": 200})
    return rows


class TestReplay:
    def test_backfill_tolerant_reader(self):
        rows = _trace_rows(12) + [
            {"query": 99, "status": 200},            # pre-ISSUE-18 row
            {"query": 98, "beta": float("nan"), "u": 0.1},
            "not a dict",
        ]
        surface, stats = dm.replay_rows(rows)
        assert stats == {"rows": 15, "replayed": 12, "legacy_rows": 2,
                         "bad_rows": 1}
        assert surface["queries"] == 12
        # Sourceless rows would land under "unknown" (cold) — these carry it.
        assert dm.hot_bins(surface)[0]["warm_coverage"] == 0.0

    def test_replay_cli_cross_process_byte_identical_plans(self, tmp_path):
        # THE determinism witness: two independent processes replaying the
        # same trace write byte-identical advisor_plan.json.
        trace = tmp_path / "trace.jsonl"
        trace.write_text("".join(json.dumps(r) + "\n" for r in _trace_rows()))
        plans = []
        for name in ("a", "b"):
            out = tmp_path / f"plan_{name}.json"
            proc = subprocess.run(
                [sys.executable, "-m", "sbr_tpu.obs.demand", "replay",
                 str(trace), "--plan-out", str(out), "--json"],
                capture_output=True, text=True, cwd=REPO,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            assert proc.returncode == 0, proc.stderr
            doc = json.loads(proc.stdout)
            assert doc["planned_tiles"] >= 1
            plans.append(out.read_bytes())
        assert plans[0] == plans[1]
        plan = json.loads(plans[0])
        assert plan["schema"] == dm.PLAN_SCHEMA
        assert plan["plan_fingerprint"]

    def test_replay_cli_exit_codes(self, tmp_path):
        def replay(*argv):
            return subprocess.run(
                [sys.executable, "-m", "sbr_tpu.obs.demand", "replay", *argv],
                capture_output=True, text=True, cwd=REPO,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            ).returncode

        assert replay(str(tmp_path / "missing.jsonl")) == 2
        legacy = tmp_path / "legacy.jsonl"
        legacy.write_text('{"query": 0, "status": 200}\n')
        assert replay(str(legacy)) == 3
        trace = tmp_path / "t.jsonl"
        trace.write_text("".join(json.dumps(r) + "\n" for r in _trace_rows()))
        assert replay(str(trace)) == 0
        # All-cold stream under a coverage floor: gate breach.
        assert replay(str(trace), "--floor", "0.5") == 1


# ---------------------------------------------------------------------------
# Engine wiring: SBR_DEMAND=0 structural no-op + on-path recording
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def _engine(self, **kw):
        from sbr_tpu.serve.engine import Engine

        return Engine(config=CFG, **kw)

    def test_off_is_structural_noop_with_bit_identical_answers(self, monkeypatch):
        from sbr_tpu.obs import prof

        pool = [make_model_params(beta=1.2, u=0.25),
                make_model_params(beta=2.1, u=0.6)]
        monkeypatch.setenv("SBR_DEMAND", "1")
        eng = self._engine()
        try:
            eng.start()
            on_xi = [r.xi for r in eng.query_many(pool, scenario="mix")]
            assert eng.demand is not None
        finally:
            eng.close()

        monkeypatch.delenv("SBR_DEMAND", raising=False)
        sys.modules.pop("sbr_tpu.obs.demand", None)
        traces_before = sum(prof.trace_counts().values())
        eng = self._engine()
        try:
            eng.start()
            off_xi = [r.xi for r in eng.query_many(pool, scenario="mix")]
            assert eng.demand is None
            # The demand module must not even be imported...
            assert "sbr_tpu.obs.demand" not in sys.modules
            # ...the exposition must be byte-free of demand metrics...
            assert "sbr_demand" not in eng.prometheus()
            assert "demand" not in eng.statz()
        finally:
            eng.close()
        # ...zero new XLA programs traced by running demand-off...
        assert sum(prof.trace_counts().values()) == traces_before
        # ...and answers bit-identical to the demand-on run.
        assert all(_feq(a, b) for a, b in zip(on_xi, off_xi))
        # (re-import for the rest of the module: `dm` stays bound)
        import sbr_tpu.obs.demand  # noqa: F401

    def test_on_records_and_lands_artifacts(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SBR_DEMAND", "1")
        run_dir = tmp_path / "run"
        eng = self._engine(run_dir=str(run_dir))
        try:
            eng.start()
            pool = [make_model_params(beta=1.2, u=0.25),
                    make_model_params(beta=2.1, u=0.6)]
            eng.query_many(pool, scenario="mix")
            eng.query_many(pool, scenario="mix")  # -> lru warm hits
            snap = eng.demand.snapshot()
            assert snap["queries_total"] == 4
            assert "sbr_demand_queries_total 4" in eng.prometheus()
            assert eng.statz()["demand"]["queries_total"] == 4
        finally:
            eng.close()
        doc = json.loads((run_dir / "demand.json").read_text())
        assert doc["totals"]["queries"] == 4
        srcs = {}
        for cell in doc["totals"]["cells"].values():
            for s, v in cell["sources"].items():
                srcs[s] = srcs.get(s, 0) + v
        assert srcs == {"computed": 2, "lru": 2}
        plan = json.loads((run_dir / "advisor_plan.json").read_text())
        assert plan["schema"] == dm.PLAN_SCHEMA and plan["tiles"]
        manifest = json.loads((run_dir / "manifest.json").read_text())
        assert manifest["demand"]["plan"] == 1
        assert manifest["demand"]["last_plan"] == plan["plan_fingerprint"]

    def test_worker_stats_carry_demand_block_only_when_on(self, monkeypatch):
        from sbr_tpu.serve.fleet import _worker_stats

        monkeypatch.setenv("SBR_DEMAND", "1")
        eng = self._engine()
        try:
            eng.start()
            eng.query_many([make_model_params(beta=1.2, u=0.25)])
            stats = _worker_stats(eng)
            assert stats["demand"]["queries"] == 1
        finally:
            eng.close()
        monkeypatch.delenv("SBR_DEMAND", raising=False)
        eng = self._engine()
        try:
            eng.start()
            assert "demand" not in _worker_stats(eng)
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# report demand (gate) + report gc --demand-keep (retention)
# ---------------------------------------------------------------------------


def _write_demand_run(tmp_path, name, counts_sources):
    d = tmp_path / name
    d.mkdir(parents=True, exist_ok=True)
    (d / "demand.json").write_text(json.dumps({
        "schema": dm.LIVE_SCHEMA,
        "totals": _surface_from(counts_sources),
    }))
    return d


class TestReportDemand:
    def test_exit_2_bad_dir(self, tmp_path):
        from sbr_tpu.obs.report import demand_doc

        doc, code = demand_doc([tmp_path / "nope"])
        assert code == 2 and doc["exit"] == 2

    def test_exit_3_no_data(self, tmp_path):
        from sbr_tpu.obs.report import demand_doc

        empty = tmp_path / "empty"
        empty.mkdir()
        doc, code = demand_doc([empty])
        assert code == 3 and "no demand data" in doc["error"]

    def test_gate_and_merge_across_runs(self, tmp_path):
        from sbr_tpu.obs.report import demand_doc, render_demand

        a = _write_demand_run(tmp_path, "a", {(1.0, 0.1, "computed"): 6})
        b = _write_demand_run(tmp_path, "b", {(1.0, 0.1, "lru"): 4})
        doc, code = demand_doc([a, b], floor=0.5)
        assert code == 1  # warm coverage 0.4 under the 0.5 floor
        assert doc["queries"] == 10
        assert doc["hot_warm_coverage"] == 0.4
        assert "COLD HOT-REGION" in render_demand(doc)
        doc, code = demand_doc([a, b], floor=0.3)
        assert code == 0
        assert "GATE: ok" in render_demand(doc)
        # No floor anywhere: the gate is disarmed.
        doc, code = demand_doc([a, b])
        assert code == 0 and doc["floor"] is None

    def test_floor_env_default(self, tmp_path, monkeypatch):
        from sbr_tpu.obs.report import demand_doc

        a = _write_demand_run(tmp_path, "a", {(1.0, 0.1, "computed"): 6})
        monkeypatch.setenv("SBR_DEMAND_COVERAGE_FLOOR", "0.9")
        doc, code = demand_doc([a])
        assert code == 1 and doc["floor"] == 0.9

    def test_cli_json_contract(self, tmp_path):
        from sbr_tpu.obs import report

        a = _write_demand_run(tmp_path, "a", {(1.0, 0.1, "lru"): 5})
        code = report.main(["demand", str(a), "--floor", "0.5", "--json"])
        assert code == 0


class TestGcDemandKeep:
    def _run_dir(self, root, name, status="done", rotated=3):
        d = root / name
        d.mkdir(parents=True)
        (d / "manifest.json").write_text(json.dumps({"status": status}))
        (d / "demand.json").write_text("{}")
        (d / "advisor_plan.json").write_text("{}")
        for i in range(rotated):
            (d / f"demand.{i:03d}.json").write_text("{}")
            (d / f"advisor_plan.{i:03d}.json").write_text("{}")
        return d

    def test_prunes_rotated_keeps_active_and_live_runs(self, tmp_path):
        done = self._run_dir(tmp_path, "run_done")
        live = self._run_dir(tmp_path, "run_live", status="running")
        removed = dm.gc_demand_files(tmp_path, keep=1)
        # done run: 2 of 3 rotated pruned per kind; active files untouched.
        assert len(removed) == 4
        assert (done / "demand.json").exists()
        assert (done / "advisor_plan.json").exists()
        assert not (done / "demand.000.json").exists()
        assert (done / "demand.002.json").exists()
        # live run (manifest "running", fresh mtime): never touched.
        assert len(list(live.glob("demand.*.json"))) == 3

    def test_report_gc_flag(self, tmp_path):
        from sbr_tpu.obs import report

        self._run_dir(tmp_path, "run_a")
        code = report.main(["gc", str(tmp_path), "--keep", "99",
                            "--demand-keep", "0"])
        assert code == 0
        assert not list((tmp_path / "run_a").glob("demand.0*.json"))
        assert (tmp_path / "run_a" / "demand.json").exists()

    def test_rotation_archives_snapshots(self, tmp_path, monkeypatch):
        from sbr_tpu.obs import runlog

        monkeypatch.setenv("SBR_DEMAND_ROTATE_S", "5")
        clock = [0.0]
        run = runlog.RunContext(root=tmp_path, label="rot")
        tr = dm.DemandTracker(window_s=60.0, bins=8, topk_n=4,
                              time_fn=lambda: clock[0], run=run)
        tr.record(1.0, 0.1)
        assert tr.maybe_write(run, force=True)
        clock[0] += 6.0
        tr.record(2.0, 0.5)
        assert tr.maybe_write(run, force=True)
        run.finalize()
        assert (Path(run.run_dir) / "demand.000.json").exists()
        assert (Path(run.run_dir) / "demand.json").exists()


# ---------------------------------------------------------------------------
# History schema 12
# ---------------------------------------------------------------------------


class TestHistorySchema12:
    def test_demand_metrics_whitelisted(self):
        from sbr_tpu.obs import history

        assert history.SCHEMA >= 12  # ISSUE 19 bumped to 13 (prewarm workload)
        out = history.bench_metrics({
            "value": 10.0,
            "extra": {"demand_updates_per_sec": 5e5, "demand_merge_ms": 0.8},
        })
        assert out["demand_updates_per_sec"] == 5e5
        assert out["demand_merge_ms"] == 0.8

    def test_polarity(self):
        from sbr_tpu.obs import history

        assert history.polarity("demand_updates_per_sec") == 1
        assert history.polarity("demand_merge_ms") == -1

    def test_schema_1_to_11_lines_still_load_and_gate(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        rows = [{"ts": 1.0, "metrics": {"eq_per_sec": 10.0}}]  # schema-less
        rows += [{"schema": s, "metrics": {"eq_per_sec": 10.0 + s / 10}}
                 for s in range(2, 12)]
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        history.append({"eq_per_sec": 10.6}, path=path)
        records = history.load(path)
        assert ([r["schema"] for r in records]
                == list(range(1, 12)) + [history.SCHEMA])
        verdicts, status = history.check(records, tolerance=0.15)
        assert status == "ok"


# ---------------------------------------------------------------------------
# The advisor closes the loop (acceptance gate): plan -> sweep -> warm
# ---------------------------------------------------------------------------


class TestAdvisorClosesLoop:
    def test_plan_tiles_turn_red_coverage_gate_green(self, tmp_path, monkeypatch):
        from sbr_tpu.obs.report import demand_doc
        from sbr_tpu.resilience.elastic import TileCache, tile_meta
        from sbr_tpu.serve.engine import Engine, ServeConfig

        FLOOR = 0.6
        base = make_model_params()
        hot_cells = [(1.25, 0.3), (1.25, 0.31), (2.5, 0.55)]
        stream = [hot_cells[k % 3] for k in range(18)]

        # Phase 1 — the COLD run: every hot query computed, nothing warm.
        # `report demand` must flag the hot region red under the floor.
        cold = _write_demand_run(
            tmp_path, "cold",
            {(b, u, "computed"): sum(1 for c in stream if c == (b, u))
             for b, u in hot_cells},
        )
        doc, code = demand_doc([cold], floor=FLOOR)
        assert code == 1, "cold hot region must flag red"
        plan = doc["advisor"]
        assert plan["tiles"], "advisor must rank tiles for the hot region"

        # Phase 2 — sweep the plan's top-ranked tiles into the tile cache:
        # each tile's exact beta/u axes become one stored tile + cell-index
        # sidecar (what a background elastic sweep would land).
        cache = TileCache(tmp_path / "tile_cache")
        for t in plan["tiles"]:
            betas, us = t["betas"], t["us"]
            assert betas and us, t
            key = cache.key(base, CFG, "float64", betas, us)
            shape = (len(betas), len(us))
            arrays = {
                "xi": np.full(shape, 0.25),
                "max_aw": np.full(shape, 0.5),
                "status": np.zeros(shape),
            }
            cache.store(key, arrays,
                        meta=tile_meta(base, CFG, "float64", betas, us, key))

        # Phase 3 — replay the hot stream (the stream cells the plan swept;
        # the cold tail stays cold and unqueried) against a real engine
        # whose only answer path is the tile cache (breaker forced open):
        # the bridge's exact-membership lookup must serve every planned
        # cell warm.
        planned = {(b, u) for t in plan["tiles"]
                   for b in t["betas"] for u in t["us"]}
        hot_stream = [c for c in stream if c in planned]
        assert hot_stream, (planned, stream)
        monkeypatch.setenv("SBR_DEMAND", "1")
        monkeypatch.setenv("SBR_TILE_CACHE_DIR", str(cache.root))
        warm_dir = tmp_path / "warm"
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)),
                     run_dir=str(warm_dir))
        try:
            eng.start()
            for _ in range(eng.breaker.threshold):
                eng.breaker.record_failure()  # solver path DOWN
            for b, u in hot_stream:
                q = make_model_params(
                    beta=b, u=u, eta=base.economic.eta,
                    tspan=base.learning.tspan, x0=base.learning.x0,
                )
                res = eng.query_many([q])[0]
                assert res.source == "tilecache", (b, u, res.source)
        finally:
            eng.close()

        # The measured warm-hit rate on the hot region clears the floor
        # the cold run flagged red — the loop is closed.
        doc, code = demand_doc([warm_dir], floor=FLOOR)
        assert code == 0, doc.get("breaches")
        assert doc["hot_warm_coverage"] >= FLOOR
        assert doc["queries"] == len(hot_stream)
