"""Tests for the memory observatory (`sbr_tpu.obs.mem`, ISSUE 5).

Covers the acceptance criteria: the ``mem`` event schema and manifest
``memory`` roll-up (per-span/per-tile attribution), OOM-preflight graceful
skip on CPU (``memory_stats()`` returning None) and fail-closed behavior
with a synthetic capacity, capacity-planner determinism (same capacity ⇒
same tile shape), the schema-1→2 ``bench_history.jsonl`` back-compat read,
the ``report memory`` exit-code contract (0 within budget / 1 over the
headroom threshold / 3 missing data), and the ``report gc`` checkpoint-
debris satellite (quarantine/ + stale tile_*.lease pruning).
"""

import json
import os
import time

import numpy as np
import pytest

from sbr_tpu import obs
from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.obs import mem, report


@pytest.fixture(autouse=True)
def _no_active_run():
    """Telemetry must never leak between tests (mirrors test_obs.py)."""
    assert obs.current_run() is None
    was_on = obs.metrics().enabled
    yield
    while obs.end_run() is not None:
        pass
    (obs.metrics().enable if was_on else obs.metrics().disable)()


_TINY = SolverConfig(n_grid=64, bisect_iters=20, refine_crossings=False)


# -- snapshots & the SBR_OBS_MEM_LIVE gate -----------------------------------


def test_snapshot_on_cpu_carries_live_bytes_only():
    import jax.numpy as jnp

    keep = jnp.arange(1024.0)  # ensure at least one live buffer
    snap = mem.snapshot()
    assert snap.get("live_buffer_bytes", 0) >= keep.nbytes
    # CPU backends expose no allocator stats — the keys must be absent,
    # not zero (consumers treat every field as optional).
    assert "bytes_in_use" not in snap
    assert "bytes_limit" not in snap


def test_live_gate_env_and_context(monkeypatch):
    assert mem.live_enabled()
    monkeypatch.setenv("SBR_OBS_MEM_LIVE", "0")
    assert not mem.live_enabled()
    assert mem.live_bytes() is None
    monkeypatch.setenv("SBR_OBS_MEM_LIVE", "1")
    with mem.live_disabled():
        assert not mem.live_enabled()
        assert mem.snapshot() == {}  # nothing observable on CPU with the gate off
    assert mem.live_enabled()  # restored


def test_headroom_env(monkeypatch):
    assert mem.headroom() == pytest.approx(0.8)
    monkeypatch.setenv("SBR_MEM_HEADROOM", "0.5")
    assert mem.headroom() == pytest.approx(0.5)
    monkeypatch.setenv("SBR_MEM_HEADROOM", "nonsense")
    assert mem.headroom() == pytest.approx(0.8)  # garbage falls back, never raises


# -- mem event schema --------------------------------------------------------


def test_mem_event_schema_and_manifest_rollup(tmp_path):
    import jax
    import jax.numpy as jnp

    run_dir = tmp_path / "run"
    fn = jax.jit(lambda x: (x * 2.0).sum())
    with obs.run_context(run_dir=str(run_dir)):
        with obs.span("stage_m") as sp:
            y = obs.jit_call("prog_m", fn, jnp.arange(256.0))
            sp.sync(y)
        obs.log_tile_mem("tile_b00000_u00000")

    events = [
        json.loads(line) for line in (run_dir / "events.jsonl").read_text().splitlines()
    ]
    mem_events = [e for e in events if e["kind"] == "mem"]
    assert mem_events, "span end + jit call must land mem events"
    for ev in mem_events:
        assert "where" in ev and "span" in ev
        assert isinstance(ev.get("live_buffer_bytes"), int)
    tile_events = [e for e in mem_events if e.get("tile")]
    assert tile_events and tile_events[0]["where"] == "tile"

    manifest = json.loads((run_dir / "manifest.json").read_text())
    block = manifest["memory"]
    assert block["peak_bytes"] == block["peak_live_buffer_bytes"] > 0
    assert block["peak_span"] is not None
    assert "tile_b00000_u00000" in (block["tiles"] or {})
    top = block["top_programs"]
    assert top and top[0]["name"] == "prog_m"
    assert {"arg_bytes", "out_bytes", "temp_bytes"} <= set(top[0])


# -- analytical footprints & preflight ---------------------------------------


def test_grid_tile_footprint_scales_with_cells():
    from sbr_tpu.sweeps.baseline_sweeps import grid_tile_footprint

    fp8 = grid_tile_footprint(8, 8, _TINY)
    fp16 = grid_tile_footprint(16, 16, _TINY)
    assert fp8["total_bytes"] > 0
    assert fp16["total_bytes"] > fp8["total_bytes"]
    assert fp16["out_bytes"] > fp8["out_bytes"]


def test_policy_tile_footprint():
    from sbr_tpu.sweeps.policy_sweeps import policy_tile_footprint

    fp = policy_tile_footprint(2, 2, 2, _TINY)
    assert fp["total_bytes"] > 0


def test_preflight_graceful_skip_on_cpu(tmp_path):
    """CPU: memory_stats() is None ⇒ no capacity ⇒ verdict "skipped" — and
    check_preflight passes it through (never fail-closed without evidence)."""
    run_dir = tmp_path / "run"
    with obs.run_context(run_dir=str(run_dir)):
        rec = mem.preflight("tile[8x8]", {"total_bytes": 10**18})
        assert rec["verdict"] == "skipped"
        assert rec["reason"] == "no-capacity"
        mem.check_preflight(rec)  # must not raise
    events = [
        json.loads(line) for line in (run_dir / "events.jsonl").read_text().splitlines()
    ]
    (pf,) = [e for e in events if e["kind"] == "preflight"]
    assert pf["verdict"] == "skipped"
    manifest = json.loads((run_dir / "manifest.json").read_text())
    assert manifest["memory"]["preflight"][0]["verdict"] == "skipped"


def test_preflight_fails_closed_on_exceeds():
    fp = {"total_bytes": 2 * 2**30, "arg_bytes": 0, "out_bytes": 0, "temp_bytes": 2 * 2**30}
    rec = mem.preflight("tile[big]", fp, capacity=2**30, headroom_frac=0.8)
    assert rec["verdict"] == "exceeds"
    with pytest.raises(mem.MemoryPreflightError, match="exceeds the memory budget"):
        mem.check_preflight(rec)
    ok = mem.preflight("tile[ok]", {"total_bytes": 100}, capacity=2**30)
    assert ok["verdict"] == "ok"
    mem.check_preflight(ok)


def test_policy_sweep_preflight_fails_closed_with_synthetic_capacity(monkeypatch):
    """The policy sweep has no tile loop in front of it — its direct
    preflight must refuse an analytically-oversized grid pre-dispatch."""
    from sbr_tpu.models.params import make_interest_params
    from sbr_tpu.sweeps.policy_sweeps import policy_sweep_interest

    monkeypatch.setattr(mem, "device_capacity", lambda stats=None: 4096)
    with pytest.raises(mem.MemoryPreflightError):
        policy_sweep_interest(
            np.array([0.5, 1.0]), np.array([0.05, 0.1]), np.array([0.0, 0.01]),
            make_interest_params(u=0.1, delta=0.1), config=_TINY,
        )


def test_auto_preflight_uses_planner_model_not_a_second_compile(monkeypatch, tmp_path):
    """On the tile_shape="auto" path the preflight verdict must come from
    the planner's fitted model (source "planner-model") — not a full-tile
    AOT compile whose executable would be discarded."""
    from sbr_tpu.utils.checkpoint import run_tiled_grid

    monkeypatch.setattr(mem, "allocator_stats", lambda: {"bytes_limit": 64 * 2**20})
    run_dir = tmp_path / "run"
    with obs.run_context(run_dir=str(run_dir)):
        run_tiled_grid(
            np.linspace(0.5, 1.0, 4), np.linspace(0.05, 0.5, 4),
            make_model_params(), config=_TINY, tile_shape="auto",
        )
    block = json.loads((run_dir / "manifest.json").read_text())["memory"]
    assert block["plan"]["verdict"] == "ok"
    (pf,) = block["preflight"]
    assert pf["verdict"] == "ok"
    assert pf["source"] == "planner-model"


def test_tiled_sweep_preflight_fails_closed_with_synthetic_capacity(monkeypatch):
    """With a (mocked) tiny device capacity, run_tiled_grid must refuse the
    dispatch BEFORE any device work — the anti-XLA-OOM contract."""
    from sbr_tpu.utils import checkpoint

    monkeypatch.setattr(mem, "device_capacity", lambda stats=None: 4096)
    with pytest.raises(mem.MemoryPreflightError):
        checkpoint.run_tiled_grid(
            np.linspace(0.5, 1.0, 4),
            np.linspace(0.05, 0.5, 4),
            make_model_params(),
            config=_TINY,
            tile_shape=(4, 4),
        )


# -- capacity planner --------------------------------------------------------


def test_fit_linear_model_two_points():
    fixed, per_cell = mem.fit_linear_model([(64, 10_000 + 64 * 100), (256, 10_000 + 256 * 100)])
    assert per_cell == pytest.approx(100.0)
    assert fixed == pytest.approx(10_000.0)


def test_planner_determinism_same_capacity_same_shape():
    model = (10_000.0, 400.0)
    shapes = {
        mem.plan_tile_shape(5000, 5000, model, capacity=16 * 2**30)[0] for _ in range(5)
    }
    assert len(shapes) == 1  # same capacity ⇒ same tile shape, every time


def test_planner_picks_largest_power_of_two_within_budget():
    model = (0.0, 1024.0)  # 1 KiB per cell
    # budget = 0.8 * 128 MiB = 102.4 MiB → 256² cells = 64 MiB fits,
    # 512² = 256 MiB does not.
    (tb, tu), rec = mem.plan_tile_shape(5000, 5000, model, capacity=128 * 2**20)
    assert (tb, tu) == (256, 256)
    assert rec["verdict"] == "ok"
    assert rec["modeled_bytes"] <= rec["budget_bytes"]
    # More capacity ⇒ a no-smaller tile (monotone in capacity).
    (tb2, _), _ = mem.plan_tile_shape(5000, 5000, model, capacity=512 * 2**20)
    assert tb2 >= tb


def test_planner_no_capacity_falls_back():
    shape, rec = mem.plan_tile_shape(5000, 5000, (0.0, 0.0), capacity=None)
    assert shape == (256, 256)
    assert rec["verdict"] == "skipped" and rec["reason"] == "no-capacity"
    # Small grids clamp the fallback to the covering power of two.
    shape_small, _ = mem.plan_tile_shape(100, 100, (0.0, 0.0), capacity=None)
    assert shape_small == (128, 128)


def test_planner_raises_when_nothing_fits():
    with pytest.raises(mem.MemoryPreflightError, match="no power-of-two tile"):
        mem.plan_tile_shape(100, 100, (10**12, 10**9), capacity=2**20)


def test_planner_respects_mesh_divisibility():
    model = (0.0, 1024.0)
    (tb, tu), _ = mem.plan_tile_shape(
        5000, 5000, model, capacity=128 * 2**20, multiple_of=(4, 4)
    )
    assert tb % 4 == 0 and tu % 4 == 0


def test_planner_per_device_divisor_scales_sharded_tiles():
    """A tile sharded over N devices puts ~1/N of its cells on each: the
    planner must budget per device, not undersize by the device count."""
    model = (0.0, 1024.0)
    (t1, _), _ = mem.plan_tile_shape(5000, 5000, model, capacity=128 * 2**20)
    (t4, _), rec = mem.plan_tile_shape(
        5000, 5000, model, capacity=128 * 2**20, per_device_divisor=4
    )
    assert t1 == 256 and t4 == 512  # 4× the cells fit when split over 4 devices
    assert rec["per_device_divisor"] == 4


def test_auto_tile_shape_records_plan_and_preflight_in_manifest(tmp_path):
    """Acceptance: a sweep launched with tile_shape="auto" records its
    planned shape + preflight verdict in manifest.json (CPU: both land as
    graceful skips with the fallback shape)."""
    from sbr_tpu.utils.checkpoint import run_tiled_grid

    run_dir = tmp_path / "run"
    with obs.run_context(run_dir=str(run_dir)):
        grid = run_tiled_grid(
            np.linspace(0.5, 1.0, 4),
            np.linspace(0.05, 0.5, 4),
            make_model_params(),
            config=_TINY,
            tile_shape="auto",
        )
    assert grid.xi.shape == (4, 4)
    block = json.loads((run_dir / "manifest.json").read_text())["memory"]
    assert block["plan"]["requested"] == "auto"
    assert tuple(block["plan"]["tile_shape"]) == (4, 4)  # pow2 cover of the grid
    assert block["plan"]["verdict"] == "skipped"  # no capacity on CPU
    assert block["preflight"][0]["verdict"] == "skipped"
    assert block["tiles"]  # per-tile peaks attributed


def test_resolve_tile_shape_passthrough_and_determinism():
    from sbr_tpu.utils.checkpoint import resolve_tile_shape

    shape, rec = resolve_tile_shape(100, 100, (32, 16), _TINY, None)
    assert shape == (32, 16) and rec is None
    a, _ = resolve_tile_shape(100, 100, "auto", _TINY, None)
    b, _ = resolve_tile_shape(100, 100, "auto", _TINY, None)
    assert a == b  # deterministic — multihost peers must agree


# -- bench history: schema 1 → 2 back-compat ---------------------------------


def test_history_schema2_appends_and_reads_schema1(tmp_path):
    from sbr_tpu.obs import history

    path = tmp_path / "hist.jsonl"
    # A committed schema-1 line (pre-memory) and a legacy schema-less line.
    with open(path, "w") as fh:
        fh.write(json.dumps({"schema": 1, "ts": "2026-01-01T00:00:00", "label": "bench",
                             "platform": "tpu", "metrics": {"eq_per_sec": 1000.0}}) + "\n")
        fh.write(json.dumps({"ts": "2026-01-02T00:00:00", "label": "bench",
                             "platform": "tpu", "metrics": {"eq_per_sec": 1010.0}}) + "\n")
    history.append({"eq_per_sec": 990.0, "mem_peak_bytes": 2**30},
                   platform="tpu", path=path)
    records = history.load(path)
    assert [r["schema"] for r in records] == [1, 1, history.SCHEMA]
    # The current-schema record gates against the schema-1 baseline (same metric).
    verdicts, status = history.check(records, tolerance=0.15, min_points=3)
    assert status == "ok"
    assert verdicts["eq_per_sec"]["n"] == 3
    # The new memory metric is present but still short — never a false gate.
    assert verdicts["mem_peak_bytes"]["status"] == "short"


def test_bench_metrics_schema2_memory_keys():
    from sbr_tpu.obs import history

    result = {
        "metric": "eq_per_sec",
        "value": 5.0,
        "extra": {
            "grid_mem_peak_bytes": 123456,
            "agents_mem_peak_bytes": 0,  # zero = no allocator stats: dropped
            "obs": {"memory_peak_bytes": 777},
        },
    }
    m = history.bench_metrics(result)
    assert m["grid_mem_peak_bytes"] == 123456
    assert m["mem_peak_bytes"] == 777
    assert "agents_mem_peak_bytes" not in m
    assert history.polarity("grid_mem_peak_bytes") == -1  # lower is better


# -- report memory -----------------------------------------------------------


def _write_run(tmp_path, manifest_memory=None, events=()):
    run_dir = tmp_path / "synth_run"
    run_dir.mkdir()
    manifest = {"schema": "sbr-obs/1", "label": "t", "status": "complete"}
    if manifest_memory is not None:
        manifest["memory"] = manifest_memory
    (run_dir / "manifest.json").write_text(json.dumps(manifest))
    with open(run_dir / "events.jsonl", "w") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    return run_dir


def test_report_memory_exit_0_within_budget(tmp_path, capsys):
    run_dir = _write_run(
        tmp_path,
        manifest_memory={
            "peak_live_buffer_bytes": 100,
            "peak_device_bytes": 1000,
            "peak_span": "sweeps.beta_u_grid",
            "capacity_bytes": 10_000,
            "headroom": 0.8,
            "tiles": {"tile_b00000_u00000": 1000},
        },
    )
    code = report.main(["memory", str(run_dir)])
    out = capsys.readouterr().out
    assert code == 0
    assert "tile_b00000_u00000" in out and "OVER" not in out


def test_report_memory_exit_1_on_tile_over_threshold(tmp_path, capsys):
    run_dir = _write_run(
        tmp_path,
        manifest_memory={
            "peak_device_bytes": 9_500,
            "capacity_bytes": 10_000,
            "headroom": 0.8,
            "tiles": {"tile_b00000_u00000": 9_500, "tile_b00000_u00004": 100},
        },
    )
    code = report.main(["memory", str(run_dir)])
    out = capsys.readouterr().out
    assert code == 1
    assert "OVER THRESHOLD" in out
    # A looser --headroom clears the flag: the threshold is configurable.
    assert report.main(["memory", str(run_dir), "--headroom", "0.99"]) == 0


def test_report_memory_exit_1_from_events_only(tmp_path):
    """The event log is authoritative when the manifest roll-up never
    landed (kill -9 mid-run)."""
    run_dir = _write_run(
        tmp_path,
        events=[
            {"mono": 0.1, "ts": 1.0, "kind": "mem", "where": "tile",
             "tile": "tile_b00000_u00000", "peak_bytes_in_use": 9_900,
             "bytes_limit": 10_000},
        ],
    )
    doc, code = report.memory_doc(report.load_run(run_dir))
    assert code == 1
    assert doc["over_tiles"] == ["tile_b00000_u00000"]


def test_report_memory_exit_3_on_missing_data(tmp_path, capsys):
    run_dir = _write_run(tmp_path)
    assert report.main(["memory", str(run_dir)]) == 3
    assert "no memory data" in capsys.readouterr().out
    run_dir2 = tmp_path / "synth_run"
    (run_dir2 / "manifest.json").unlink()
    assert report.main(["memory", str(run_dir2)]) == 2  # not a run dir


def test_report_memory_json_contract(tmp_path, capsys):
    run_dir = _write_run(
        tmp_path,
        manifest_memory={
            "peak_device_bytes": 500,
            "capacity_bytes": 10_000,
            "headroom": 0.8,
            "tiles": {"tile_b00000_u00000": 500},
            "plan": {"requested": "auto", "tile_shape": [256, 256], "verdict": "ok"},
            "preflight": [{"label": "tile[256x256]", "verdict": "ok"}],
        },
    )
    code = report.main(["memory", str(run_dir), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert code == 0 and doc["exit"] == 0
    assert doc["memory"]["plan"]["verdict"] == "ok"
    assert doc["tiles"] == {"tile_b00000_u00000": 500}
    assert doc["threshold_bytes"] == 8_000


def test_report_memory_exit_1_on_preflight_exceeds(tmp_path):
    run_dir = _write_run(
        tmp_path,
        manifest_memory={
            "peak_device_bytes": 1,
            "preflight": [{"label": "tile[512x512]", "verdict": "exceeds"}],
        },
    )
    assert report.main(["memory", str(run_dir)]) == 1


# -- report gc: checkpoint debris (satellite) --------------------------------


def test_gc_debris_prunes_quarantine_and_stale_leases(tmp_path):
    ckpt = tmp_path / "ckpt"
    (ckpt / "quarantine").mkdir(parents=True)
    (ckpt / "quarantine" / "tile_b00000_u00000.npz").write_bytes(b"corrupt")
    # Completed steal: tile exists → lease is scaffolding.
    (ckpt / "tile_b00000_u00000.npz").write_bytes(b"x")
    (ckpt / "tile_b00000_u00000.lease").write_text(
        json.dumps({"pid": 1, "ts": time.time(), "ttl_s": 900})
    )
    # Expired lease (dead holder), torn lease, and a LIVE lease.
    (ckpt / "tile_b00000_u00004.lease").write_text(
        json.dumps({"pid": 2, "ts": time.time() - 10_000, "ttl_s": 900})
    )
    (ckpt / "tile_b00004_u00000.lease").write_text("{torn")
    live = ckpt / "tile_b00004_u00004.lease"
    live.write_text(json.dumps({"pid": 3, "ts": time.time(), "ttl_s": 900}))
    # A stealer that died between writing its takeover temp file and the
    # os.replace (parallel.distributed._try_lease) — always debris.
    (ckpt / "tile_b00008_u00000.lease.4242.tmp").write_text("{half")

    removed = mem.gc_debris(tmp_path)
    names = {p.name for p in removed}
    assert "quarantine" in names
    assert "tile_b00000_u00000.lease" in names
    assert "tile_b00000_u00004.lease" in names
    assert "tile_b00004_u00000.lease" in names
    assert "tile_b00008_u00000.lease.4242.tmp" in names
    assert live.exists(), "a live lease must never be yanked from its holder"
    assert not (ckpt / "quarantine").exists()
    assert (ckpt / "tile_b00000_u00000.npz").exists()  # results are never touched


def test_report_gc_cli_sweeps_debris(tmp_path, capsys):
    root = tmp_path / "obs_root"
    root.mkdir()
    ckpt = tmp_path / "ckpt"
    (ckpt / "quarantine").mkdir(parents=True)
    (ckpt / "tile_b00000_u00000.lease").write_text("{torn")
    code = report.main(
        ["gc", str(root), "--keep", "4", "--checkpoints", str(ckpt)]
    )
    out = capsys.readouterr().out
    assert code == 0
    assert "2 checkpoint-debris path(s)" in out
    assert not (ckpt / "quarantine").exists()
    assert not (ckpt / "tile_b00000_u00000.lease").exists()
