"""Interest-rate extension tests.

Oracles (SURVEY §4): the r=0 ⇒ baseline degeneracy — the reference's own
implicit regression oracle (`interest_rate_solver.jl:89-101`) — plus an
independent scipy HJB + effective-hazard pipeline at the reference Figure
configuration (`scripts/3_interest_rates.jl:37-46`: r=0.06, δ=0.1, u=0).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu.baseline.learning import solve_learning
from sbr_tpu.baseline.solver import solve_equilibrium_baseline
from sbr_tpu.interest import solve_equilibrium_interest
from sbr_tpu.models.params import SolverConfig, make_interest_params

from oracle import solve_interest_oracle

CONFIG = SolverConfig(n_grid=4096)


@pytest.fixture(scope="module")
def ref_solution():
    """Reference interest configuration (`3_interest_rates.jl:37-46`)."""
    m = make_interest_params(beta=1.0, eta_bar=15.0, u=0.0, p=0.5, kappa=0.6, lam=0.01, r=0.06, delta=0.1)
    ls = solve_learning(m.learning, CONFIG)
    res = solve_equilibrium_interest(ls, m.economic, CONFIG)
    return m, ls, res


class TestValueFunction:
    def test_boundary_condition(self, ref_solution):
        m, _, res = ref_solution
        econ = m.economic
        expected = (econ.u + econ.delta) / (econ.r + econ.delta)
        np.testing.assert_allclose(float(res.v[0]), expected, rtol=1e-12)

    def test_matches_scipy_hjb(self, ref_solution):
        m, _, res = ref_solution
        oracle = solve_interest_oracle()
        taus = np.asarray(res.base.tau_grid)
        v_ref = np.array([oracle.v_at(t) for t in taus])
        np.testing.assert_allclose(np.asarray(res.v), v_ref, atol=5e-7)

    def test_value_bounded(self, ref_solution):
        """With u=0 the HJB rest point (h→0, reentry active) is V*=δ/(δ−r);
        V stays within (0, V*]. (V is NOT monotone: where V>1 and h is large,
        the (h+δ)(1−V) term turns negative — observed dip ~2e-7 at the hazard
        peak.)"""
        m, _, res = ref_solution
        econ = m.economic
        v = np.asarray(res.v)
        v_star = econ.delta / (econ.delta - econ.r)
        assert (v > 0).all() and (v <= v_star + 1e-9).all()


class TestInterestEquilibrium:
    def test_r0_reduces_to_baseline(self):
        """r=0 ⇒ h−rV ≡ h ⇒ exact baseline result (`interest_rate_solver.jl:89-101`)."""
        m = make_interest_params(r=0.0, delta=0.1)  # baseline defaults otherwise
        ls = solve_learning(m.learning, CONFIG)
        res_i = solve_equilibrium_interest(ls, m.economic, CONFIG)
        res_b = solve_equilibrium_baseline(ls, m.economic, CONFIG)
        # Buffer detection runs on grid-sampled h here vs refined closed-form
        # hazard in the baseline path, hence 1e-6 not exact-equality.
        np.testing.assert_allclose(float(res_i.base.xi), float(res_b.xi), atol=1e-6)
        np.testing.assert_allclose(
            float(res_i.base.tau_bar_in_unc), float(res_b.tau_bar_in_unc), atol=1e-5
        )
        assert bool(res_i.base.bankrun) == bool(res_b.bankrun)

    def test_reference_config_matches_oracle(self, ref_solution):
        _, _, res = ref_solution
        oracle = solve_interest_oracle()
        assert bool(res.base.bankrun) == oracle.bankrun
        np.testing.assert_allclose(float(res.base.xi), oracle.xi, atol=1e-5)
        np.testing.assert_allclose(float(res.base.tau_bar_in_unc), oracle.tau_bar_in, atol=1e-4)
        np.testing.assert_allclose(float(res.base.tau_bar_out_unc), oracle.tau_bar_out, atol=1e-4)

    def test_effective_hazard_below_hazard(self, ref_solution):
        """h − rV < h strictly when r > 0 (V > 0)."""
        _, _, res = ref_solution
        assert (np.asarray(res.hr_effective) < np.asarray(res.base.hr)).all()

    def test_interest_delays_exit_vs_u0_baseline(self, ref_solution):
        """Positive r raises the option value of staying: the exit buffer
        τ̄_OUT under h−rV is smaller than the baseline u=0 exit buffer
        (agents exit later in normal time)."""
        m, ls, res = ref_solution
        base = solve_equilibrium_baseline(ls, m.economic, CONFIG)
        assert float(res.base.tau_bar_out_unc) < float(base.tau_bar_out_unc)

    def test_vmap_over_r(self):
        """r is a traced scalar: a policy sweep over r is one vmap."""
        import jax

        from sbr_tpu.interest.solver import solve_equilibrium_interest_core

        m = make_interest_params(u=0.0, r=0.06, delta=0.1)
        ls = solve_learning(m.learning, CONFIG)
        econ = m.economic
        rs = jnp.linspace(0.0, 0.09, 8)

        def cell(r):
            res = solve_equilibrium_interest_core(
                ls, econ.u, econ.p, econ.kappa, econ.lam, econ.eta, r, econ.delta,
                ls.grid[-1], CONFIG,
            )
            return res.base.xi, res.base.status

        xi, status = jax.jit(jax.vmap(cell))(rs)
        assert xi.shape == (8,)
        # r=0 lane equals the scalar baseline path
        res0 = solve_equilibrium_baseline(ls, econ, CONFIG)
        np.testing.assert_allclose(float(xi[0]), float(res0.xi), atol=1e-6)
