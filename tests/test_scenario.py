"""Composable scenario engine (ISSUE 14): golden legacy parity, the
composition matrix, policy-modifier semantics, multi-bank contagion, spec
fingerprints, serve integration, and history schema 9."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu import scenario
from sbr_tpu.baseline.learning import solve_learning
from sbr_tpu.baseline.solver import solve_equilibrium_baseline
from sbr_tpu.models.params import (
    EconomicParamsInterest,
    ModelParamsHetero,
    SolverConfig,
    make_hetero_params,
    make_interest_params,
    make_model_params,
    params_to_pytree,
    pytree_to_params,
    with_overrides,
)
from sbr_tpu.models.results import Status
from sbr_tpu.scenario import ScenarioSpec, spec_fingerprint

CFG_KW = dict(n_grid=96, bisect_iters=40)


def _cfg(numerics="fixed", **kw):
    merged = {**CFG_KW, **kw}
    return SolverConfig(numerics=numerics, **merged)


def _health_equal(a, b):
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        if not np.array_equal(np.asarray(leaf_a), np.asarray(leaf_b), equal_nan=True):
            return False
    return True


def _assert_bitwise(res, xi, status, health=None, health_ref=None):
    assert np.array_equal(np.asarray(res.xi), np.asarray(xi), equal_nan=True)
    assert np.array_equal(np.asarray(res.status), np.asarray(status))
    if health_ref is not None:
        assert _health_equal(health, health_ref)


# ---------------------------------------------------------------------------
# Golden parity: each legacy stack through its equivalent ScenarioSpec is
# bit-identical (ξ, status, Health) under both numerics modes.
# ---------------------------------------------------------------------------


class TestGoldenParity:
    @pytest.mark.parametrize("numerics", ["fixed", "adaptive"])
    def test_baseline_reduction_bit_identical(self, numerics):
        cfg = _cfg(numerics)
        base = make_model_params(beta=1.2, u=0.08)
        ls = solve_learning(base.learning, cfg)
        direct = solve_equilibrium_baseline(ls, base.economic, cfg)
        res = scenario.solve(ScenarioSpec(), base, config=cfg)
        _assert_bitwise(res, direct.xi, direct.status, res.health, direct.health)

    @pytest.mark.parametrize("numerics", ["fixed", "adaptive"])
    def test_interest_reduction_bit_identical(self, numerics):
        from sbr_tpu.interest.solver import solve_equilibrium_interest

        cfg = _cfg(numerics)
        params = make_interest_params(beta=1.0, u=0.05, r=0.02, delta=0.1)
        ls = solve_learning(params.learning, cfg)
        direct = solve_equilibrium_interest(ls, params.economic, cfg)
        res = scenario.solve(ScenarioSpec(modifiers=("interest",)), params, config=cfg)
        _assert_bitwise(
            res, direct.base.xi, direct.base.status, res.health, direct.base.health
        )

    @pytest.mark.parametrize("numerics", ["fixed", "adaptive"])
    def test_hetero_reduction_bit_identical(self, numerics):
        from sbr_tpu.hetero.learning import solve_learning_hetero
        from sbr_tpu.hetero.solver import solve_equilibrium_hetero

        cfg = _cfg(numerics)
        params = make_hetero_params(betas=(0.6, 1.4), dist=(0.4, 0.6), u=0.05)
        lsh = solve_learning_hetero(params.learning, cfg)
        direct = solve_equilibrium_hetero(lsh, params.economic, cfg)
        res = scenario.solve(ScenarioSpec(learning="hetero"), params, config=cfg)
        _assert_bitwise(res, direct.xi, direct.status, res.health, direct.health)

    @pytest.mark.parametrize("numerics", ["fixed", "adaptive"])
    def test_social_reduction_bit_identical(self, numerics):
        from sbr_tpu.social.solver import solve_equilibrium_social

        cfg = _cfg(numerics)
        base = make_model_params(beta=1.0, u=0.1)
        direct = solve_equilibrium_social(base, cfg, max_iter=120)
        res = scenario.solve(
            ScenarioSpec(learning="social", social_max_iter=120), base, config=cfg
        )
        _assert_bitwise(
            res, direct.equilibrium.xi, direct.equilibrium.status,
            res.health, direct.health,
        )
        assert np.asarray(res.detail.iterations) == np.asarray(direct.iterations)

    def test_hook_free_core_untouched_by_refactor(self):
        """The extracted classify_cell + hook plumbing must leave the
        hook-free call signature working exactly as before (positional)."""
        from sbr_tpu.baseline.solver import solve_equilibrium_core

        cfg = _cfg()
        base = make_model_params()
        ls = solve_learning(base.learning, cfg)
        e = base.economic
        res = solve_equilibrium_core(ls, e.u, e.p, e.kappa, e.lam, e.eta, ls.grid[-1], cfg)
        assert int(res.status) == Status.RUN


# ---------------------------------------------------------------------------
# Spec validation: the composition matrix rejects loudly.
# ---------------------------------------------------------------------------


class TestSpecValidation:
    def test_unknown_learning_and_modifier(self):
        with pytest.raises(ValueError, match="unknown learning"):
            ScenarioSpec(learning="bayesian")
        with pytest.raises(ValueError, match="unknown modifier"):
            ScenarioSpec(modifiers=("taxes",))
        with pytest.raises(ValueError, match="duplicate"):
            ScenarioSpec(modifiers=("lolr", "lolr"))

    def test_multibank_matrix_rejections(self):
        with pytest.raises(ValueError, match="baseline"):
            ScenarioSpec(learning="hetero", banks=3)
        with pytest.raises(ValueError, match="baseline"):
            ScenarioSpec(learning="social", banks=2)
        with pytest.raises(ValueError, match="banks >= 2"):
            ScenarioSpec(exposure=((0, 1, 0.5),))
        with pytest.raises(ValueError, match="out of range"):
            ScenarioSpec(banks=2, exposure=((0, 5, 0.5),))
        with pytest.raises(ValueError, match="self-exposure"):
            ScenarioSpec(banks=2, exposure=((1, 1, 0.5),))

    def test_params_compat_rejections(self):
        cfg = _cfg()
        with pytest.raises(ValueError, match="r/delta"):
            scenario.solve(
                ScenarioSpec(modifiers=("interest",)), make_model_params(), config=cfg
            )
        with pytest.raises(ValueError, match="ModelParamsHetero"):
            scenario.solve(
                ScenarioSpec(learning="hetero"), make_model_params(), config=cfg
            )

    def test_reductions(self):
        assert ScenarioSpec().reduces_to() == "baseline"
        assert ScenarioSpec(modifiers=("interest",)).reduces_to() == "interest"
        assert ScenarioSpec(learning="hetero").reduces_to() == "hetero"
        assert ScenarioSpec(learning="social").reduces_to() == "social"
        assert ScenarioSpec(modifiers=("lolr",)).reduces_to() is None
        assert ScenarioSpec(banks=2).reduces_to() is None
        assert ScenarioSpec(modifiers=("interest", "lolr")).reduces_to() is None

    def test_doc_round_trip(self):
        spec = ScenarioSpec(
            learning="baseline", modifiers=("insurance_cap", "lolr"),
            banks=3, exposure=((0, 1, 0.5), (1, 2, 0.25)), lgd=0.4,
        )
        assert ScenarioSpec.from_doc(spec.to_doc()) == spec
        with pytest.raises(ValueError, match="unknown scenario field"):
            ScenarioSpec.from_doc({"modfiers": ["lolr"]})


# ---------------------------------------------------------------------------
# Policy modifiers: economic semantics.
# ---------------------------------------------------------------------------


class TestPolicyModifiers:
    def test_policy_params_round_trip(self):
        p = make_model_params(insurance_cap=0.25, suspension_t=4.0, lolr_rate=0.3)
        tree = params_to_pytree(p)
        assert tree["insurance_cap"] == 0.25
        assert tree["suspension_t"] == 4.0
        assert tree["lolr_rate"] == 0.3
        assert pytree_to_params(tree) == p
        q = with_overrides(p, lolr_rate=0.5)
        assert q.economic.lolr_rate == 0.5
        assert q.economic.insurance_cap == 0.25  # carried, not reset

    def test_policy_param_validation(self):
        with pytest.raises(ValueError, match="insurance_cap"):
            make_model_params(insurance_cap=1.5)
        with pytest.raises(ValueError, match="suspension_t"):
            make_model_params(suspension_t=-1.0)
        with pytest.raises(ValueError, match="lolr_rate"):
            make_model_params(lolr_rate=-0.1)

    def test_policy_params_accept_traced_scalars(self):
        """The PR 12 traced-scalar deferral covers the policy fields."""

        def build(c):
            tree = params_to_pytree(make_model_params())
            tree["insurance_cap"] = c
            return pytree_to_params(tree).economic.insurance_cap * 2.0

        out = jax.jit(build)(0.25)
        assert float(out) == 0.5

    def test_inert_knobs_leave_solve_unchanged(self):
        """Default (inert) policy values + active modifiers ≈ no modifiers
        where the math degenerates: cap=0 scales by 1, lolr=0 keeps κ."""
        cfg = _cfg()
        base = make_model_params(u=0.08)
        plain = scenario.solve(ScenarioSpec(), base, config=cfg)
        inert = scenario.solve(
            ScenarioSpec(modifiers=("insurance_cap", "lolr")), base, config=cfg
        )
        assert int(inert.status) == int(plain.status)
        np.testing.assert_allclose(
            float(inert.xi), float(plain.xi), rtol=0, atol=1e-12
        )

    def test_insurance_cap_weakens_runs(self):
        cfg = _cfg()
        base = make_model_params(u=0.08)
        uncapped = scenario.solve(
            ScenarioSpec(modifiers=("insurance_cap",)), base, config=cfg
        )
        assert int(uncapped.status) == Status.RUN
        capped = scenario.solve(
            ScenarioSpec(modifiers=("insurance_cap",)),
            with_overrides(base, insurance_cap=0.9), config=cfg,
        )
        # With 90% of deposits insured the hazard collapses below u:
        # no crossing, no run.
        assert int(capped.status) != Status.RUN

    def test_suspension_blocks_late_runs(self):
        cfg = _cfg()
        base = make_model_params(u=0.08)
        free = scenario.solve(ScenarioSpec(modifiers=("suspension",)),
                              with_overrides(base, suspension_t=1e6), config=cfg)
        assert int(free.status) == Status.RUN
        frozen = scenario.solve(
            ScenarioSpec(modifiers=("suspension",)),
            with_overrides(base, suspension_t=1e-3), config=cfg,
        )
        # Convertibility suspended from t≈0: hazard identically 0, no run.
        assert int(frozen.status) == Status.NO_CROSSING

    def test_lolr_raises_threshold(self):
        cfg = _cfg()
        base = make_model_params(u=0.08)
        plain = scenario.solve(ScenarioSpec(), base, config=cfg)
        assert int(plain.status) == Status.RUN
        rescued = scenario.solve(
            ScenarioSpec(modifiers=("lolr",)),
            with_overrides(base, lolr_rate=5.0), config=cfg,
        )
        # κ_eff = 0.6·6 = 3.6 > max AW ≤ 1: no root — the injection
        # outruns any feasible withdrawal share.
        assert int(rescued.status) == Status.NO_ROOT


# ---------------------------------------------------------------------------
# Genuine compositions.
# ---------------------------------------------------------------------------


class TestCompositions:
    def test_hetero_interest_social_combined(self):
        """The scenario the paper never touched: all three extension axes
        in ONE composed pipeline, converged with Health clean."""
        cfg = _cfg()
        hp = make_hetero_params(betas=(0.8, 1.6), dist=(0.5, 0.5), u=0.05)
        econ = EconomicParamsInterest(
            u=hp.economic.u, p=hp.economic.p, kappa=hp.economic.kappa,
            lam=hp.economic.lam, eta_bar=hp.economic.eta_bar, eta=hp.economic.eta,
            r=0.01, delta=0.1, insurance_cap=0.1, lolr_rate=0.05,
        )
        params = ModelParamsHetero(learning=hp.learning, economic=econ)
        spec = ScenarioSpec(
            learning="social", modifiers=("interest", "insurance_cap", "lolr"),
            social_max_iter=150,
        )
        res = scenario.solve(spec, params, config=cfg)
        assert bool(np.asarray(res.detail["converged"]))
        assert int(res.status) == Status.RUN
        assert np.isfinite(float(res.xi))
        from sbr_tpu.diag.health import DIVERGENT_MASK

        assert int(np.asarray(res.health.flags)) & DIVERGENT_MASK == 0

    def test_hetero_x_interest(self):
        cfg = _cfg()
        hp = make_hetero_params(betas=(0.8, 1.6), dist=(0.5, 0.5), u=0.05)
        econ = EconomicParamsInterest(
            u=hp.economic.u, p=hp.economic.p, kappa=hp.economic.kappa,
            lam=hp.economic.lam, eta_bar=hp.economic.eta_bar, eta=hp.economic.eta,
            r=0.02, delta=0.1,
        )
        params = ModelParamsHetero(learning=hp.learning, economic=econ)
        res = scenario.solve(
            ScenarioSpec(learning="hetero", modifiers=("interest",)), params, config=cfg
        )
        # A positive rate lowers the effective hazard → the run regime
        # shrinks vs the pure hetero solve at the same params.
        pure = scenario.solve(ScenarioSpec(learning="hetero"), params, config=cfg)
        assert res.detail.xi.shape == pure.detail.xi.shape
        if int(pure.status) == Status.RUN and int(res.status) == Status.RUN:
            assert float(res.xi) >= float(pure.xi) - 1e-9

    def test_scenario_grid_matches_legacy_on_reduction(self):
        from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        base = make_model_params()
        betas = np.linspace(0.5, 2.0, 6)
        us = np.linspace(0.02, 0.5, 5)
        composed = scenario.scenario_grid(ScenarioSpec(), betas, us, base, config=cfg)
        legacy = beta_u_grid(betas, us, base, config=cfg)
        assert np.array_equal(np.asarray(composed.status), np.asarray(legacy.status))
        assert np.array_equal(
            np.asarray(composed.xi), np.asarray(legacy.xi), equal_nan=True
        )

    def test_policy_sweep_grid(self):
        """A policy-modifier sweep is just a grid sweep over the composed
        pipeline: higher insured fraction ⇒ no more runs than baseline."""
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        betas = np.linspace(0.5, 2.0, 5)
        us = np.linspace(0.02, 0.5, 5)
        spec = ScenarioSpec(modifiers=("insurance_cap",))
        g0 = scenario.scenario_grid(
            spec, betas, us, make_model_params(insurance_cap=0.0), config=cfg
        )
        g1 = scenario.scenario_grid(
            spec, betas, us, make_model_params(insurance_cap=0.6), config=cfg
        )
        runs0 = int((np.asarray(g0.status) == Status.RUN).sum())
        runs1 = int((np.asarray(g1.status) == Status.RUN).sum())
        assert runs1 <= runs0
        assert runs0 > 0


# ---------------------------------------------------------------------------
# Multi-bank contagion.
# ---------------------------------------------------------------------------


class TestMultiBank:
    def test_empty_network_equals_independent(self):
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        plist = [make_model_params(beta=1.0 + 0.3 * i, u=0.05 + 0.02 * i)
                 for i in range(3)]
        mb = scenario.solve_multibank(ScenarioSpec(banks=3), plist, config=cfg)
        assert mb.converged and mb.iterations == 1
        batch = scenario.engine.batch_fn(
            ScenarioSpec(), cfg,
            jnp.dtype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32).name,
        )
        cols = scenario.multibank._bank_columns(
            ScenarioSpec(banks=3), plist,
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32,
        )
        xi_i, _t, _a, st_i, _h = batch(*cols)
        assert np.array_equal(np.asarray(mb.status), np.asarray(st_i))
        assert np.array_equal(np.asarray(mb.xi), np.asarray(xi_i), equal_nan=True)
        assert np.array_equal(
            np.asarray(mb.kappa_eff),
            np.asarray(cols[scenario.SCENARIO_KEYS.index("kappa")]),
        )

    def test_contagion_flips_a_sound_bank(self):
        """A bank with no run equilibrium on its own (κ above its peak
        withdrawal share) becomes runnable once counterparty losses erode
        κ_eff — the contagion mechanism itself."""
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        fragile = make_model_params(beta=1.0, u=0.05)
        sound = make_model_params(beta=1.0, u=0.05, kappa=0.93)
        plist = [fragile, sound, sound]
        no_net = scenario.solve_multibank(ScenarioSpec(banks=3), plist, config=cfg)
        assert int(np.asarray(no_net.status)[0]) == Status.RUN
        assert int(np.asarray(no_net.status)[1]) != Status.RUN

        spec = ScenarioSpec(
            banks=3, exposure=((0, 1, 1.0), (0, 2, 1.0), (1, 2, 0.5)), lgd=0.9
        )
        mb = scenario.solve_multibank(spec, plist, config=cfg)
        st = np.asarray(mb.status)
        assert int(st[0]) == Status.RUN  # the fragile bank still runs
        assert int(st[1]) == Status.RUN  # ...and drags its counterparty down
        assert float(np.asarray(mb.kappa_eff)[1]) < 0.93
        assert float(np.asarray(mb.spillover)[1]) > 0

    def test_exactly_stable_network_converges_at_tol_zero(self):
        """A no-run network is a fixed point after round 1 (delta == 0.0
        exactly); `<=` must declare it converged even at contagion_tol=0
        instead of burning the whole iteration budget."""
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        calm = make_model_params(u=5.0)  # u above the hazard: no run anywhere
        spec = ScenarioSpec(banks=2, exposure=((0, 1, 0.5),), contagion_tol=0.0)
        mb = scenario.solve_multibank(spec, calm, config=cfg)
        assert mb.converged and mb.iterations == 1
        assert not bool(np.asarray(mb.bankrun).any())

    def test_solve_and_solve_multibank_agree_on_defaults(self):
        """The same multi-bank call through scenario.solve and
        solve_multibank must use the same default numerics — same
        fingerprint, same bytes."""
        plist = [make_model_params(u=0.05)] * 2
        spec = ScenarioSpec(banks=2, exposure=((0, 1, 0.5),))
        a = scenario.solve(spec, plist)
        b = scenario.solve_multibank(spec, plist)
        assert a.fingerprint == b.fingerprint
        assert np.array_equal(np.asarray(a.xi), np.asarray(b.xi), equal_nan=True)

    def test_multibank_per_bank_health_tagged(self, tmp_path):
        from sbr_tpu import obs

        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        plist = [make_model_params(u=0.05)] * 2
        spec = ScenarioSpec(banks=2, exposure=((0, 1, 0.5),))
        with obs.run_context(run_dir=str(tmp_path / "run")):
            scenario.solve_multibank(spec, plist, config=cfg)
        import json

        events = [
            json.loads(line)
            for line in (tmp_path / "run" / "events.jsonl").read_text().splitlines()
        ]
        health = [e for e in events if e.get("kind") == "health" and "bank" in e]
        assert {e["bank"] for e in health} == {0, 1}
        assert all("scenario" in e for e in health)
        # the fold key keeps banks separate in the per-stage census
        assert len({e["stage"] for e in health}) == 2


# ---------------------------------------------------------------------------
# Fingerprints & serving.
# ---------------------------------------------------------------------------


class TestFingerprintsAndServe:
    def test_fingerprint_sensitivity(self):
        base = make_model_params()
        cfg = _cfg()
        fp0 = spec_fingerprint(ScenarioSpec(), base, cfg, "float64")
        assert fp0 == spec_fingerprint(ScenarioSpec(), base, cfg, "float64")
        assert fp0 != spec_fingerprint(
            ScenarioSpec(modifiers=("lolr",)), base, cfg, "float64"
        )
        assert fp0 != spec_fingerprint(
            ScenarioSpec(), with_overrides(base, lolr_rate=0.1), cfg, "float64"
        )
        assert fp0 != spec_fingerprint(ScenarioSpec(), base, cfg, "float32")

    def test_served_scenario_query_cached_by_fingerprint(self):
        from sbr_tpu.serve.engine import Engine

        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        eng = Engine(config=cfg)
        try:
            spec = ScenarioSpec(modifiers=("insurance_cap", "lolr"))
            params = make_model_params(u=0.08, insurance_cap=0.2, lolr_rate=0.1)
            first = eng.query_scenario(params, spec)
            again = eng.query_scenario(params, spec)
            assert first["source"] == "computed"
            assert again["source"] == "lru"
            assert first["scenario_fingerprint"] == again["scenario_fingerprint"]
            assert first["xi"] == again["xi"]
            other = eng.query_scenario(
                with_overrides(params, lolr_rate=0.2), spec
            )
            assert other["scenario_fingerprint"] != first["scenario_fingerprint"]
        finally:
            eng.close()

    def test_program_cache_ignores_host_only_knobs(self):
        """Specs differing only in host-side knobs (lgd, contagion_tol,
        ...) must share one compiled cell program — a server accepting
        arbitrary scenario objects cannot compile one executable per
        wire-supplied float value."""
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        dtype_name = jnp.dtype(
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        ).name
        a = scenario.engine.batch_fn(
            ScenarioSpec(banks=2, exposure=((0, 1, 0.5),), lgd=0.5), cfg, dtype_name
        )
        b = scenario.engine.batch_fn(
            ScenarioSpec(banks=3, lgd=0.6, contagion_tol=1e-4), cfg, dtype_name
        )
        assert a is b  # same cell-program projection → same cached program
        assert ScenarioSpec(lgd=0.9).cell_program_spec() == ScenarioSpec()

    def test_multibank_fingerprint_normalizes_shared_params(self):
        """One shared struct vs an N-list of the same struct is the SAME
        solve — same fingerprint, same cache key."""
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        p = make_model_params(u=0.05)
        spec = ScenarioSpec(banks=3)
        shared = scenario.solve_multibank(spec, p, config=cfg)
        listed = scenario.solve_multibank(spec, [p, p, p], config=cfg)
        assert shared.fingerprint == listed.fingerprint
        with pytest.raises(ValueError, match="params structs"):
            scenario.solve_multibank(spec, [p, p], config=cfg)

    def test_served_multibank_query(self):
        from sbr_tpu.serve.engine import Engine

        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        eng = Engine(config=cfg)
        try:
            spec = ScenarioSpec(banks=3, exposure=((0, 1, 0.5), (0, 2, 0.5)))
            rec = eng.query_scenario(make_model_params(u=0.05), spec)
            assert rec["banks"] == 3
            assert len(rec["xi"]) == 3 and len(rec["status"]) == 3
            assert rec["converged"] in (True, False)
        finally:
            eng.close()

    def test_endpoint_policy_knobs_and_interest_over_http(self):
        """The wire surface: policy knobs are accepted /query parameters,
        an active modifier actually changes the served answer, r/δ route
        through interest-typed params for the 'interest' modifier, and an
        unservable spec × params combination is a 400 (client error), not
        a retryable 503."""
        import json
        import urllib.error
        import urllib.request

        from sbr_tpu.serve.endpoint import ServeEndpoint
        from sbr_tpu.serve.engine import Engine

        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        eng = Engine(config=cfg)

        def post(doc):
            body = json.dumps(doc).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ep.port}/query", data=body,
                headers={"Content-Type": "application/json"},
            )
            return json.loads(urllib.request.urlopen(req).read())

        try:
            with ServeEndpoint(eng) as ep:
                plain = post({"u": 0.08, "scenario": {"modifiers": ["insurance_cap"]}})
                capped = post({
                    "u": 0.08, "insurance_cap": 0.9,
                    "scenario": {"modifiers": ["insurance_cap"]},
                })
                assert plain["status"] == Status.RUN
                assert capped["status"] != Status.RUN  # the knob reached the solver
                assert capped["scenario_fingerprint"] != plain["scenario_fingerprint"]

                interest = post({
                    "u": 0.05, "r": 0.02, "delta": 0.1,
                    "scenario": {"modifiers": ["interest"]},
                })
                assert "scenario_fingerprint" in interest

                # unservable combination: interest modifier without r/delta
                with pytest.raises(urllib.error.HTTPError) as exc:
                    post({"u": 0.05, "scenario": {"modifiers": ["interest"]}})
                assert exc.value.code == 400
                # r/delta on a PLAIN query would be silently ignored: 400
                with pytest.raises(urllib.error.HTTPError) as exc:
                    post({"u": 0.05, "r": 0.02})
                assert exc.value.code == 400
                # ...and likewise on a scenario WITHOUT the interest
                # modifier (the composed pipeline would ignore r while
                # fingerprinting it)
                with pytest.raises(urllib.error.HTTPError) as exc:
                    post({"u": 0.05, "r": 0.02,
                          "scenario": {"modifiers": ["insurance_cap"]}})
                assert exc.value.code == 400
                # a policy knob without its modifier is equally inert —
                # equally loud
                with pytest.raises(urllib.error.HTTPError) as exc:
                    post({"u": 0.05, "insurance_cap": 0.5})
                assert exc.value.code == 400
                with pytest.raises(urllib.error.HTTPError) as exc:
                    post({"u": 0.05, "lolr_rate": 0.2,
                          "scenario": {"modifiers": ["suspension"]}})
                assert exc.value.code == 400
        finally:
            eng.close()

    def test_multibank_exhaustion_reports_solved_kappa(self):
        """converged=False must still pair kappa_eff with the xi/status it
        was solved under (re-dispatching at result.kappa_eff reproduces
        the reported grids)."""
        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        plist = [make_model_params(u=0.05), make_model_params(u=0.05, kappa=0.93)]
        spec = ScenarioSpec(
            banks=2, exposure=((0, 1, 1.0), (1, 0, 1.0)), lgd=0.9,
            contagion_max_iter=1,  # force exhaustion after one round
        )
        mb = scenario.solve_multibank(spec, plist, config=cfg)
        assert not mb.converged
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        batch = scenario.engine.batch_fn(ScenarioSpec(), SolverConfig(
            n_grid=96, bisect_iters=40, refine_crossings=False), jnp.dtype(dtype).name)
        cols = scenario.multibank._bank_columns(spec, plist, dtype)
        cols[scenario.SCENARIO_KEYS.index("kappa")] = mb.kappa_eff
        xi_re, _t, _a, st_re, _h = batch(*cols)
        assert np.array_equal(np.asarray(mb.status), np.asarray(st_re))
        assert np.array_equal(np.asarray(mb.xi), np.asarray(xi_re), equal_nan=True)

    def test_scenario_grad_coverage_matrix(self):
        from sbr_tpu.grad import scenario_xi_and_grad

        cfg = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)
        base = make_model_params(u=0.08)
        g = scenario_xi_and_grad(ScenarioSpec(), base, config=cfg)
        assert np.isfinite(float(g.grads["beta"]))
        with pytest.raises(NotImplementedError, match="gradient coverage"):
            scenario_xi_and_grad(ScenarioSpec(modifiers=("lolr",)), base, config=cfg)
        with pytest.raises(NotImplementedError, match="gradient coverage"):
            scenario_xi_and_grad(ScenarioSpec(banks=2), base, config=cfg)


# ---------------------------------------------------------------------------
# History schema 9.
# ---------------------------------------------------------------------------


class TestHistorySchema9:
    def test_schema_bumped_and_keys_harvested(self, tmp_path):
        from sbr_tpu.obs import history

        assert history.SCHEMA >= 9  # ISSUE 15 bumped to 10
        result = {
            "metric": "beta_u_grid_equilibria_per_sec", "value": 100.0,
            "extra": {
                "scenario_overhead_ratio": 1.02,
                "scenario_multibank_cells_per_sec": 512.5,
            },
        }
        m = history.bench_metrics(result)
        assert m["scenario_overhead_ratio"] == 1.02
        assert m["scenario_multibank_cells_per_sec"] == 512.5
        # polarity: overhead is lower-better, throughput higher-better
        assert history.polarity("scenario_overhead_ratio") == -1
        assert history.polarity("scenario_multibank_cells_per_sec") == 1

    def test_old_schemas_still_load_and_gate(self, tmp_path):
        import json

        from sbr_tpu.obs import history

        p = tmp_path / "hist.jsonl"
        lines = []
        # one line per historical schema, 1..8, plus a schema-less legacy line
        lines.append({"metrics": {"eq_per_sec": 10.0}})
        for s in range(1, 9):
            lines.append({"schema": s, "platform": "cpu",
                          "metrics": {"eq_per_sec": 10.0 + s}})
        p.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
        history.append({"eq_per_sec": 18.5, "scenario_overhead_ratio": 1.0},
                       platform="cpu", path=p)
        records = history.load(p)
        assert len(records) == 10
        assert records[0]["schema"] == 1  # schema-less stamped as 1
        assert records[-1]["schema"] == history.SCHEMA
        verdicts, status = history.check(records)
        assert status == "ok"
        assert verdicts["eq_per_sec"]["status"] == "ok"
