"""Social-learning extension tests.

Oracles (SURVEY §4): scipy integration of the forced ODE, an independent
numpy mirror of the reference's damped fixed point
(`social_learning_solver.jl:63-263`), and the dense-graph/immediate-exit
limit of the explicit-agent simulation, which must recover the baseline
logistic (AW = G ⇒ dG/dt = β·G·(1-G))."""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu.baseline.learning import logistic_cdf
from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.social import (
    AgentSimConfig,
    erdos_renyi_edges,
    prepare_agent_graph,
    scale_free_edges,
    simulate_agents,
    solve_equilibrium_social,
    solve_forced_learning,
)
from tests.oracle import solve_social_oracle


class TestForcedLearning:
    def test_constant_forcing_closed_form(self):
        """AW ≡ c ⇒ G(t) = 1 - (1-x0)·e^{-βct}."""
        beta, c, x0 = 0.7, 0.4, 1e-3
        grid = jnp.linspace(0.0, 10.0, 2001)
        ls = solve_forced_learning(beta, jnp.full_like(grid, c), grid, x0)
        expect = 1.0 - (1.0 - x0) * np.exp(-beta * c * np.asarray(grid))
        np.testing.assert_allclose(np.asarray(ls.cdf), expect, atol=1e-12)

    def test_vs_scipy_nontrivial_forcing(self):
        """Forced ODE against scipy on a logistic-CDF forcing curve."""
        from scipy.integrate import solve_ivp

        beta, x0 = 0.9, 1e-4
        grid = np.linspace(0.0, 30.0, 8193)
        aw = np.asarray(logistic_cdf(jnp.asarray(grid), 0.9, 1e-4))

        def rhs(t, y):
            return (1.0 - y[0]) * beta * np.interp(t, grid, aw)

        sol = solve_ivp(rhs, (0.0, 30.0), [x0], rtol=1e-12, atol=1e-14, dense_output=True)
        ls = solve_forced_learning(beta, jnp.asarray(aw), jnp.asarray(grid), x0)
        got = np.asarray(ls.cdf)[::512]
        want = sol.sol(grid[::512])[0]
        np.testing.assert_allclose(got, want, atol=1e-8)

    def test_pdf_is_ode_rhs(self):
        beta, x0 = 1.2, 1e-4
        grid = jnp.linspace(0.0, 5.0, 501)
        aw = jnp.linspace(0.0, 1.0, 501)
        ls = solve_forced_learning(beta, aw, grid, x0)
        np.testing.assert_allclose(
            np.asarray(ls.pdf), np.asarray((1.0 - ls.cdf) * beta * aw), atol=1e-14
        )


class TestSocialFixedPoint:
    @pytest.fixture(scope="class")
    def solved(self):
        """Figure-12/13 parameters (`scripts/4_social_learning.jl:36-43`)."""
        m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
        return m, solve_equilibrium_social(m, SolverConfig(n_grid=4096), tol=1e-4, max_iter=500)

    def test_converges(self, solved):
        _, res = solved
        assert bool(res.converged)
        assert not bool(res.aborted)
        assert 2 <= int(res.iterations) <= 500

    def test_history_telemetry(self, solved):
        """The error/ξ iteration ring (VERDICT r3 #7): filled for exactly the
        iterations that ran, errors broadly contracting (damped fixed point:
        monotone-ish, allow transient bumps), final entries consistent."""
        _, res = solved
        err, xi = res.history()
        n = min(int(res.iterations), res.history_err.shape[-1])
        assert len(err) == n == len(xi)
        assert np.isfinite(err[1:]).all() and np.isfinite(xi).all()
        # contraction: the last error is far below the first finite one, and
        # at least ~2/3 of consecutive steps decrease the error
        first = err[1] if not np.isfinite(err[0]) else err[0]
        assert err[-1] < first * 0.1
        dec_frac = np.mean(np.diff(err[1:]) < 0)
        assert dec_frac > 0.6, dec_frac
        assert err[-1] == pytest.approx(float(res.error))
        assert xi[-1] == pytest.approx(float(res.xi))
        # solve_time stamped by the host entry
        assert res.solve_time > 0

    def test_repr_one_line(self, solved):
        _, res = solved
        r = repr(res)
        assert "\n" not in r and "SocialFixedPointResult(" in r
        assert "iterations=" in r and "converged=True" in r

    def test_vs_oracle(self, solved):
        m, res = solved
        ora = solve_social_oracle(
            beta=0.9, x0=1e-4, u=0.5, p=0.99, kappa=0.25, lam=0.25,
            eta=m.economic.eta, tol=1e-4, max_iter=500,
        )
        assert ora.bankrun and ora.converged
        assert bool(res.equilibrium.bankrun)
        # fixed points agree to discretization + fixed-point tolerance
        assert abs(float(res.xi) - ora.xi) < 2e-3 * m.economic.eta
        got_aw = np.interp(ora.grid, np.asarray(res.grid), np.asarray(res.aw))
        assert np.max(np.abs(got_aw - ora.aw)) < 5e-3

    def test_fixed_point_property(self, solved):
        """One more application of the map moves AW by < tol (undamped)."""
        from sbr_tpu.baseline.solver import get_aw, solve_equilibrium_core

        m, res = solved
        ls = solve_forced_learning(
            jnp.asarray(0.9, res.aw.dtype), res.aw, res.grid, jnp.asarray(1e-4, res.aw.dtype)
        )
        eq = solve_equilibrium_core(
            ls, m.economic.u, m.economic.p, m.economic.kappa, m.economic.lam,
            m.economic.eta, m.economic.eta, SolverConfig(n_grid=4096),
        )
        assert bool(eq.bankrun)
        aw_next, _, _ = get_aw(eq.xi, eq.tau_bar_in_unc, eq.tau_bar_out_unc, res.grid, ls)
        # convergence was declared on the undamped candidate, so one more map
        # application stays within a small multiple of tol
        assert float(jnp.max(jnp.abs(aw_next - res.aw))) < 5e-4

    def test_word_of_mouth_comparison(self, solved):
        """Social-learning ξ differs from the word-of-mouth baseline on the
        same economics (`scripts/4_social_learning.jl:65-81` prints Δξ)."""
        from sbr_tpu.baseline.learning import solve_learning
        from sbr_tpu.baseline.solver import solve_equilibrium_baseline
        from sbr_tpu.models.params import LearningParams

        m, res = solved
        eta = m.economic.eta
        lp = LearningParams(beta=0.9, tspan=(0.0, eta), x0=1e-4)
        ls = solve_learning(lp, SolverConfig(n_grid=4096))
        base = solve_equilibrium_baseline(ls, m.economic, SolverConfig(n_grid=4096))
        assert bool(base.bankrun)
        # at the Figure-12 parameters the withdrawal-feedback loop ACCELERATES
        # the crash relative to word-of-mouth: ξ_social ≈ 8.926 < ξ_wom ≈ 9.190
        assert float(res.xi) < float(base.xi) - 0.1

    def test_no_run_converges_flat(self):
        """u above the hazard peak everywhere ⇒ the no-equilibrium branch
        iterates ξ+η/500 while AW damps to a flat curve and converges without
        a run (`social_learning_solver.jl:149-191` — convergence is checked in
        the no-equilibrium branch too)."""
        m = make_model_params(beta=0.9, eta_bar=30.0, u=50.0, p=0.99, kappa=0.25, lam=0.25)
        res = solve_equilibrium_social(m, SolverConfig(n_grid=1024), tol=1e-4, max_iter=600)
        assert bool(res.converged)
        assert not bool(res.equilibrium.bankrun)
        # ξ advanced by it·η/500 along the no-run path
        assert float(res.xi) == pytest.approx(
            int(res.iterations) * m.economic.eta / 500.0, rel=1e-9
        )
        # AW damped toward the flat no-withdrawal level G(0)=x0
        assert float(jnp.max(res.aw) - jnp.min(res.aw)) < 1e-3


class TestGraphGenerators:
    def test_erdos_renyi_degree(self):
        src, dst = erdos_renyi_edges(5000, 12.0, seed=1)
        assert len(src) == len(dst)
        deg = np.bincount(dst, minlength=5000)
        assert abs(deg.mean() - 12.0) < 0.5
        assert (src != dst).all()

    def test_scale_free_skew(self):
        src, dst = scale_free_edges(5000, 10.0, gamma=2.5, seed=2)
        outdeg = np.bincount(src, minlength=5000)
        # heavy tail: max out-degree far above the mean
        assert outdeg.max() > 10 * outdeg.mean()
        assert (src != dst).all()


class TestAgentSimulation:
    def test_dense_graph_recovers_logistic(self):
        """Immediate exit on a dense graph ⇒ AW=G ⇒ baseline logistic ODE
        (SURVEY §4(e): representative-agent limit).

        exact_seeds + x0=1e-2 (200 founding seeds) keep the early
        stochastic-growth drift small enough that the bound is
        seed-robust: measured max-rel 0.089 ± 0.020 over 12 seeds — ~8σ
        below 0.25. (At the old x0=1e-3 Bernoulli seeding the growth
        phase's lognormal drift made the same bound fail ~40% of seeds
        under EITHER rng stream; the original seed was just lucky.)"""
        n, beta, x0 = 20000, 1.0, 1e-2
        src, dst = erdos_renyi_edges(n, 120.0, seed=3)
        cfg = AgentSimConfig(n_steps=300, dt=0.05)
        res = simulate_agents(
            beta, src, dst, n, x0=x0, config=cfg, seed=0, exact_seeds=True
        )
        t = np.asarray(res.t_grid)
        got = np.asarray(res.informed_frac)
        # the logistic preserves initial perturbations (G ∝ x0·e^{βt} while
        # small), so compare against the REALIZED seed fraction
        x0_eff = got[0]
        want = np.asarray(logistic_cdf(jnp.asarray(t), beta, float(x0_eff)))
        active = want > 0.01
        rel = np.abs(got[active] - want[active]) / want[active]
        assert rel.max() < 0.25
        assert abs(got[-1] - want[-1]) < 0.02  # saturation level matches tightly

    def test_withdrawal_window(self):
        """exit_delay beyond the horizon ⇒ no withdrawals ⇒ no contagion."""
        n = 2000
        src, dst = erdos_renyi_edges(n, 20.0, seed=4)
        cfg = AgentSimConfig(n_steps=100, dt=0.1, exit_delay=1e9)
        res = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=0)
        assert float(res.withdrawn_frac.max()) == 0.0
        assert float(res.informed_frac[-1]) == pytest.approx(
            float(res.informed_frac[0]), abs=1e-12
        )

    def test_heterogeneous_betas(self):
        """Fast agents inform before slow ones (agent-level heterogeneity)."""
        n = 4000
        betas = np.where(np.arange(n) < n // 2, 5.0, 0.05).astype(np.float32)
        src, dst = erdos_renyi_edges(n, 30.0, seed=5)
        cfg = AgentSimConfig(n_steps=150, dt=0.05)
        res = simulate_agents(betas, src, dst, n, x0=0.01, config=cfg, seed=0)
        informed = np.asarray(res.informed)
        fast = informed[: n // 2].mean()
        slow = informed[n // 2 :].mean()
        assert fast > slow + 0.2

    def test_sharded_matches_physics(self):
        """8-way sharded run (edge-count sharding + psum) also recovers the
        logistic limit and returns exactly-shaped unpadded outputs."""
        n = 10001  # not divisible by 8 → exercises agent padding
        src, dst = erdos_renyi_edges(n, 100.0, seed=6)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=200, dt=0.05)
        res = simulate_agents(1.0, src, dst, n, x0=2e-3, config=cfg, seed=0, mesh=mesh)
        assert res.informed.shape == (n,)
        t = np.asarray(res.t_grid)
        got = np.asarray(res.informed_frac)
        # same realized-seed methodology as the dense test: the logistic
        # preserves the initial perturbation, so the oracle starts from the
        # REALIZED Bernoulli seed fraction, not the nominal x0
        x0_eff = float(got[0])
        want = np.asarray(logistic_cdf(jnp.asarray(t), 1.0, x0_eff))
        assert abs(got[-1] - want[-1]) < 0.03
        # monotone non-decreasing informed fraction
        assert (np.diff(got) >= -1e-7).all()

    def test_sharded_is_bit_exact_vs_single_device(self):
        """RNG keyed by global agent id ⇒ the 8-device run equals the
        single-device run EXACTLY (per-agent state and informed times),
        not merely statistically — the sharding layer is a pure refactor
        of the same computation."""
        n = 1024
        src, dst = scale_free_edges(n, 16.0, seed=7)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=50, dt=0.1)
        r1 = simulate_agents(1.0, src, dst, n, x0=0.02, config=cfg, seed=0)
        r8 = simulate_agents(1.0, src, dst, n, x0=0.02, config=cfg, seed=0, mesh=mesh)
        assert r1.informed_frac.shape == r8.informed_frac.shape
        np.testing.assert_array_equal(np.asarray(r1.informed), np.asarray(r8.informed))
        np.testing.assert_array_equal(np.asarray(r1.t_inf), np.asarray(r8.t_inf))
        # aggregates differ only by float reduction order (mean vs psum-of-sums)
        np.testing.assert_allclose(
            np.asarray(r1.informed_frac), np.asarray(r8.informed_frac), atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(r1.withdrawn_frac), np.asarray(r8.withdrawn_frac), atol=1e-6
        )

    def test_comm_strategies_bit_identical(self):
        """The bitpacked psum_scatter path and the naive all_gather+psum
        baseline compute the same counts — results must match exactly."""
        n = 4096
        src, dst = scale_free_edges(n, 12.0, seed=9)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=60, dt=0.1)
        ra = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=1, mesh=mesh)
        rb = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=1, mesh=mesh, comm="allgather_psum"
        )
        np.testing.assert_array_equal(np.asarray(ra.informed), np.asarray(rb.informed))
        np.testing.assert_array_equal(np.asarray(ra.t_inf), np.asarray(rb.t_inf))
        np.testing.assert_array_equal(
            np.asarray(ra.informed_frac), np.asarray(rb.informed_frac)
        )

    def test_sharded_bit_exact_with_padding(self):
        """Exact equivalence also holds when N is not divisible by the mesh
        (padded inert agents draw randomness but never activate)."""
        n = 1001
        src, dst = erdos_renyi_edges(n, 12.0, seed=8)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=40, dt=0.1)
        r1 = simulate_agents(1.0, src, dst, n, x0=0.02, config=cfg, seed=3)
        r8 = simulate_agents(1.0, src, dst, n, x0=0.02, config=cfg, seed=3, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(r1.informed), np.asarray(r8.informed))
        np.testing.assert_array_equal(np.asarray(r1.t_inf), np.asarray(r8.t_inf))


class TestClosure:
    """Equilibrium→agent loop (VERDICT r2 task 2): the solved fixed point's
    withdrawal window drives the explicit-agent simulation, whose aggregate
    trajectories must converge to the fixed point's AW/G curves in the
    dense-graph large-N limit."""

    def test_window_from_equilibrium(self):
        """At the Figure-12 calibration the strategy withdraws immediately
        (τ̄_OUT^UNC > ξ ⇒ exit_delay = 0) and re-enters ξ − τ̄_IN later."""
        from sbr_tpu.social import equilibrium_window

        m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
        fp = solve_equilibrium_social(m, tol=1e-4, max_iter=500)
        assert bool(fp.equilibrium.bankrun)
        exit_delay, reentry_delay = equilibrium_window(fp.equilibrium)
        xi = float(fp.equilibrium.xi)
        assert exit_delay == pytest.approx(0.0, abs=1e-9)  # τ̄_OUT^UNC > ξ here
        assert reentry_delay == pytest.approx(xi - float(fp.equilibrium.tau_bar_in_unc), rel=1e-9)
        assert 2.0 < reentry_delay < 4.0  # ≈ 2.95 at this calibration

    def test_window_requires_bankrun(self):
        from sbr_tpu.social import equilibrium_window

        # x0 = 0.01 kills the run at these parameters (fixed point converges
        # to no-equilibrium): the window is undefined.
        m = make_model_params(
            beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25, x0=0.01
        )
        fp = solve_equilibrium_social(m, tol=1e-4, max_iter=500)
        assert not bool(fp.equilibrium.bankrun)
        with pytest.raises(ValueError, match="no bank run"):
            equilibrium_window(fp.equilibrium)

    @pytest.mark.slow
    def test_agent_sim_converges_to_fixed_point(self):
        """withdrawn_frac → AW(t) and informed_frac → G(t) as (N, degree)
        grow toward the mean-field limit; absolute error at the large
        configuration is MC-small. Mid-trajectory start (g0 = 0.02) removes
        the founding-seed branching noise that decays only as 1/√(x0·N)
        (see closure.close_loop docstring)."""
        from sbr_tpu.social import close_loop

        small = close_loop(n_agents=20_000, avg_degree=15.0, dt=0.05, t_max=16.0)
        large = close_loop(n_agents=100_000, avg_degree=60.0, dt=0.05, t_max=16.0)
        # same window in both (the fixed point doesn't depend on the sim)
        assert small.exit_delay == large.exit_delay
        assert small.reentry_delay == large.reentry_delay
        # convergence toward the mean-field limit
        assert large.err_aw_rms < small.err_aw_rms
        assert large.err_g_rms < small.err_g_rms
        # absolute MC-scale agreement at the large configuration
        assert large.err_aw_rms < 0.03
        assert large.err_g_rms < 0.03
        assert large.err_aw_sup < 0.06


class TestStretchConfig:
    """Small-scale copy of the BASELINE.md stretch workload
    (benchmarks/stretch.py): heterogeneous lognormal β on a scale-free
    graph, with a withdrawal window active."""

    def test_hetero_beta_scale_free_window(self):
        n = 8000
        rng = np.random.default_rng(0)
        betas = rng.lognormal(0.0, 0.5, n).astype(np.float32)
        src, dst = scale_free_edges(n, avg_degree=10.0, gamma=2.5, seed=11)
        cfg = AgentSimConfig(n_steps=150, dt=0.1, exit_delay=0.0, reentry_delay=3.0)
        res = simulate_agents(betas, src, dst, n, x0=0.005, config=cfg, seed=0)
        g = np.asarray(res.informed_frac)
        aw = np.asarray(res.withdrawn_frac)
        assert np.isfinite(g).all() and np.isfinite(aw).all()
        assert (np.diff(g) >= -1e-7).all()  # informed fraction is monotone
        assert (aw <= g + 1e-7).all()  # withdrawn ⊆ informed
        assert g[-1] > g[0]  # contagion actually spread
        # faster learners (top β quartile) get informed more than slower
        # ones (bottom quartile), conditional on having in-neighbors
        informed = np.asarray(res.informed)
        indeg = np.bincount(np.asarray(dst), minlength=n)
        has_in = indeg > 0
        q1, q3 = np.quantile(betas, [0.25, 0.75])
        fast = informed[(betas >= q3) & has_in].mean()
        slow = informed[(betas <= q1) & has_in].mean()
        assert fast > slow


class TestIncrementalEngine:
    """engine="incremental" (event-driven ±1 count maintenance) must be
    BIT-IDENTICAL to the full-recount gather engine — including when its
    per-step budgets overflow and it falls back to the full recount."""

    def test_bit_identical_with_window(self):
        n = 6000
        src, dst = erdos_renyi_edges(n, 12.0, seed=21)
        cfg = AgentSimConfig(n_steps=120, dt=0.1, exit_delay=0.3, reentry_delay=2.0)
        a = simulate_agents(1.0, src, dst, n, x0=0.005, config=cfg, seed=3, engine="gather")
        b = simulate_agents(1.0, src, dst, n, x0=0.005, config=cfg, seed=3, engine="incremental")
        np.testing.assert_array_equal(np.asarray(a.informed), np.asarray(b.informed))
        np.testing.assert_array_equal(np.asarray(a.t_inf), np.asarray(b.t_inf))
        np.testing.assert_array_equal(
            np.asarray(a.withdrawn_frac), np.asarray(b.withdrawn_frac)
        )
        np.testing.assert_array_equal(
            np.asarray(a.informed_frac), np.asarray(b.informed_frac)
        )

    def test_bit_identical_through_fallback(self):
        """A hub above incremental_max_degree forces the full-recount branch
        on every step it changes status; tiny budgets force agent-count
        overflows too. Results must still match exactly."""
        n = 3000
        rng = np.random.default_rng(5)
        src, dst = erdos_renyi_edges(n, 8.0, seed=22)
        # add a hub: agent 0 feeds 500 random destinations (out-degree 500)
        hub_dst = rng.choice(np.arange(1, n), size=500, replace=False).astype(np.int32)
        src = np.concatenate([src, np.zeros(500, np.int32)])
        dst = np.concatenate([dst, hub_dst])
        cfg = AgentSimConfig(n_steps=100, dt=0.1, exit_delay=0.0, reentry_delay=1.5)
        a = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=4, engine="gather")
        b = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=4,
            engine="incremental", incremental_budget=64, incremental_max_degree=16,
        )
        np.testing.assert_array_equal(np.asarray(a.informed), np.asarray(b.informed))
        np.testing.assert_array_equal(np.asarray(a.t_inf), np.asarray(b.t_inf))
        np.testing.assert_array_equal(
            np.asarray(a.withdrawn_frac), np.asarray(b.withdrawn_frac)
        )

    def test_engine_validation(self):
        n = 100
        src, dst = erdos_renyi_edges(n, 4.0, seed=0)
        with pytest.raises(ValueError, match="Unknown engine"):
            simulate_agents(1.0, src, dst, n, engine="warp")

    def test_compact_impls_bit_identical(self):
        """The two `_compact_ids` lowerings (cumsum+scatter vs searchsorted)
        are the same function: ascending True indices, dump-padded, first
        `budget` kept on overflow — across densities incl. empty, full,
        exactly-at-budget, and a dump sentinel different from n."""
        from sbr_tpu.social.agents import _compact_ids

        rng = np.random.default_rng(11)
        for n, budget, dump, k in [
            (1000, 64, 1000, 0),
            (1000, 64, 1000, 1),
            (1000, 64, 1000, 63),
            (1000, 64, 1000, 64),
            (1000, 64, 1000, 65),
            (1000, 64, 1000, 1000),
            (1000, 64, 2**30, 170),
            (257, 300, 257, 40),  # budget > n
        ]:
            mask = np.zeros(n, bool)
            if k:
                mask[rng.choice(n, size=min(k, n), replace=False)] = True
            a = np.asarray(_compact_ids(jnp.asarray(mask), budget, dump, "scatter"))
            for impl in ("searchsorted", "searchsorted_blocked"):
                b = np.asarray(_compact_ids(jnp.asarray(mask), budget, dump, impl))
                np.testing.assert_array_equal(
                    a, b, err_msg=f"impl={impl} n={n} budget={budget} k={k}"
                )

    def test_compact_impl_config_bit_identical(self):
        """engine='incremental' under compact_impl='searchsorted' reproduces
        the default lowering's results exactly (through fallback steps too)."""
        n = 4000
        src, dst = erdos_renyi_edges(n, 10.0, seed=23)
        for extra in ({}, {"incremental_budget": 48}):
            base = AgentSimConfig(n_steps=80, dt=0.1, exit_delay=0.2, reentry_delay=1.8)
            a = simulate_agents(
                1.0, src, dst, n, x0=0.01, config=base, seed=6,
                engine="incremental", **extra,
            )
            for impl in ("scatter", "searchsorted", "searchsorted_blocked"):
                alt = replace(base, compact_impl=impl)
                b = simulate_agents(
                    1.0, src, dst, n, x0=0.01, config=alt, seed=6,
                    engine="incremental", **extra,
                )
                np.testing.assert_array_equal(
                    np.asarray(a.informed), np.asarray(b.informed)
                )
                np.testing.assert_array_equal(np.asarray(a.t_inf), np.asarray(b.t_inf))
                np.testing.assert_array_equal(
                    np.asarray(a.withdrawn_frac), np.asarray(b.withdrawn_frac)
                )

    def test_compact_impl_validation(self):
        with pytest.raises(ValueError, match="compact_impl"):
            AgentSimConfig(compact_impl="bogus")

    def test_full_recount_telemetry(self):
        """The per-step recount flag: all-True for the gather engine, only
        the overflow steps for the incremental one (forced here via a tiny
        budget), and its True steps still produce exact counts (the engines
        agree bit-for-bit regardless of the flag pattern)."""
        n = 3000
        src, dst = erdos_renyi_edges(n, 8.0, seed=14)
        cfg = AgentSimConfig(n_steps=60, dt=0.1)
        g = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=5, engine="gather")
        assert np.asarray(g.full_recount_steps).all()
        assert g.full_recount_steps.shape == (60,)
        inc_small = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=5,
            engine="incremental", incremental_budget=16,
        )
        recs = np.asarray(inc_small.full_recount_steps)
        assert 0 < recs.sum() < 60  # some overflow steps, not all
        inc_big = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=5,
            engine="incremental", incremental_budget=4096,
        )
        assert np.asarray(inc_big.full_recount_steps).sum() < recs.sum()
        np.testing.assert_array_equal(
            np.asarray(g.informed), np.asarray(inc_small.informed)
        )
        assert "recounts=" in repr(inc_small)

    def test_full_recount_telemetry_sharded(self):
        """The sharded incremental flag is the psum'd any-device overflow:
        replicated, (n_steps,), and present through the chunked path."""
        n = 2048
        src, dst = erdos_renyi_edges(n, 8.0, seed=15)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=40, dt=0.1)
        r = simulate_agents(
            1.0, src, dst, n, x0=0.02, config=cfg, seed=3, mesh=mesh,
            engine="incremental", incremental_budget=8,
        )
        recs = np.asarray(r.full_recount_steps)
        assert recs.shape == (40,) and recs.sum() > 0
        cfg_c = replace(cfg, max_steps_per_launch=17)
        rc = simulate_agents(
            1.0, src, dst, n, x0=0.02, config=cfg_c, seed=3, mesh=mesh,
            engine="incremental", incremental_budget=8,
        )
        assert np.asarray(rc.full_recount_steps).shape == (40,)
        np.testing.assert_array_equal(np.asarray(r.informed), np.asarray(rc.informed))

    def test_zero_edge_graph(self):
        """E = 0 routes to the gather kernel (the incremental dense grid
        cannot gather from an empty edge array): no crash, no contagion."""
        n = 50
        src = np.zeros(0, np.int32)
        dst = np.zeros(0, np.int32)
        cfg = AgentSimConfig(n_steps=20, dt=0.1)
        res = simulate_agents(1.0, src, dst, n, x0=0.1, config=cfg, seed=0)
        g = np.asarray(res.informed_frac)
        assert g[-1] == g[0]  # nothing spreads without edges

    def test_sharded_incremental_bit_exact(self):
        """8-device incremental (per-block event compaction + psum_scatter
        deltas) equals the single-device run exactly, windowed config,
        N not divisible by the mesh (exercises agent padding)."""
        n = 5003
        src, dst = erdos_renyi_edges(n, 10.0, seed=31)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=80, dt=0.1, exit_delay=0.2, reentry_delay=2.5)
        r1 = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=7)
        r8 = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=7, mesh=mesh, engine="incremental"
        )
        np.testing.assert_array_equal(np.asarray(r1.informed), np.asarray(r8.informed))
        np.testing.assert_array_equal(np.asarray(r1.t_inf), np.asarray(r8.t_inf))
        np.testing.assert_allclose(
            np.asarray(r1.withdrawn_frac), np.asarray(r8.withdrawn_frac), atol=1e-6
        )

    def test_sharded_incremental_bit_exact_on_skewed_graph(self):
        """Scale-free out-degree skew, default budgets: the edge-count
        sharded incremental engine (hub edges split across chunks) equals
        the single-device gather run exactly — the round-3 padding-skew
        objection to making incremental the sharded default."""
        n = 4001
        src, dst = scale_free_edges(n, 10.0, gamma=2.2, seed=41)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=70, dt=0.1, exit_delay=0.1, reentry_delay=2.0)
        r1 = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=11, engine="gather")
        r8 = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=11, mesh=mesh,
            engine="incremental",
        )
        np.testing.assert_array_equal(np.asarray(r1.informed), np.asarray(r8.informed))
        np.testing.assert_array_equal(np.asarray(r1.t_inf), np.asarray(r8.t_inf))
        np.testing.assert_array_equal(
            np.asarray(r1.informed_frac), np.asarray(r8.informed_frac)
        )

    def test_sharded_incremental_searchsorted_bit_exact(self):
        """The sharded incremental engine under compact_impl='searchsorted'
        (the per-device compaction of globally-visible changed agents)
        equals the single-device run exactly, including agent padding."""
        n = 5003
        src, dst = erdos_renyi_edges(n, 10.0, seed=31)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(
            n_steps=80, dt=0.1, exit_delay=0.2, reentry_delay=2.5,
            compact_impl="searchsorted",
        )
        r1 = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=7)
        r8 = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=7, mesh=mesh,
            engine="incremental",
        )
        np.testing.assert_array_equal(np.asarray(r1.informed), np.asarray(r8.informed))
        np.testing.assert_array_equal(np.asarray(r1.t_inf), np.asarray(r8.t_inf))
        np.testing.assert_allclose(
            np.asarray(r1.withdrawn_frac), np.asarray(r8.withdrawn_frac), atol=1e-6
        )

    def test_sharded_incremental_fallback_matches_gather(self):
        """Tiny budgets force the psum'd overflow path (bitpacked full
        recount) on most steps; must still equal the sharded gather engine
        exactly."""
        n = 2048
        src, dst = scale_free_edges(n, 8.0, seed=33)
        mesh = jax.make_mesh((8,), ("agents",))
        cfg = AgentSimConfig(n_steps=60, dt=0.1, exit_delay=0.0, reentry_delay=2.0)
        rg = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=9, mesh=mesh)
        ri = simulate_agents(
            1.0, src, dst, n, x0=0.01, config=cfg, seed=9, mesh=mesh,
            engine="incremental", incremental_budget=32, incremental_max_degree=8,
        )
        np.testing.assert_array_equal(np.asarray(rg.informed), np.asarray(ri.informed))
        np.testing.assert_array_equal(np.asarray(rg.t_inf), np.asarray(ri.t_inf))
        np.testing.assert_array_equal(
            np.asarray(rg.informed_frac), np.asarray(ri.informed_frac)
        )


class TestClosureSharded:
    def test_close_loop_accepts_mesh(self):
        """The closure driver composes with a device mesh (the sim runs the
        sharded gather engine; RNG keyed by global id keeps results equal to
        the single-device run, so the errors match exactly)."""
        from sbr_tpu.social import close_loop

        mesh = jax.make_mesh((8,), ("agents",))
        c1 = close_loop(n_agents=8000, avg_degree=15.0, dt=0.1, t_max=12.0)
        c8 = close_loop(n_agents=8000, avg_degree=15.0, dt=0.1, t_max=12.0, mesh=mesh)
        assert c8.err_aw_rms == pytest.approx(c1.err_aw_rms, abs=1e-6)
        assert c8.err_g_rms == pytest.approx(c1.err_g_rms, abs=1e-6)


class TestAutoEngine:
    def test_heuristic_prefers_incremental_for_light_tails(self):
        from sbr_tpu.social.agents import _auto_engine

        outdeg = np.full(10000, 10)
        assert _auto_engine(outdeg, 64, 200, 10000, 1.0, 0.05, 4096) == "incremental"
        # a couple of ER-tail hubs are fine (each costs ≤ 2 fallback steps)
        outdeg[:5] = 200
        assert _auto_engine(outdeg, 64, 200, 10000, 1.0, 0.05, 4096) == "incremental"

    def test_heuristic_absorbs_clustered_scale_free_tails(self):
        """Hub fallbacks cluster into the transition steps, so a scale-free
        tail with H ≫ 0 hubs does NOT force gather: the incremental engine
        measured 1.42x faster at the 10^6-agent scale-free stretch shape
        (ENGINE_COMPARE_sf_tpu_2026-07-31.json) that the round-4 2·H census
        misrouted. Saturation: only a census whose expected hub changes
        reach ~1 per step from the very first steps (p_hub ≈ 1 everywhere,
        fallback fraction ≳ 80%) should still pick gather."""
        from sbr_tpu.social.agents import _auto_engine

        rng = np.random.default_rng(0)
        n = 100_000
        w = (np.arange(1, n + 1)) ** (-1.0 / 1.5)
        src = rng.choice(n, size=10 * n, p=w / w.sum())
        outdeg = np.bincount(src, minlength=n)
        assert (outdeg > 64).sum() > 200  # heavy tail really present
        assert _auto_engine(outdeg, 64, 200, n, 1.0, 0.05, 4096) == "incremental"
        # a census with 10^6 hub agents saturates every step → gather
        many_hubs = np.full(2_000_000, 200)
        assert _auto_engine(many_hubs, 64, 200, 2_000_000, 1.0, 0.05, 1 << 30) == "gather"

    def test_heuristic_counts_mass_change_overflow(self):
        """ADVICE r3: a fast contagion overflows the change budget through
        the logistic bulk even with zero hubs — the heuristic must count
        those steps, not just hub fallbacks."""
        from sbr_tpu.social.agents import _auto_engine

        outdeg = np.full(1000, 10)  # no hubs at all
        # peak change rate 2·n·β·dt/4 = 5e5 ≫ budget 4096 → the bulk
        # overflows for ~25 steps of the 80; under the cost model (fallback
        # ≈ one recount + ε, incremental step ≈ 0.35 recounts)
        # 25·1.15 + 55·0.35 ≈ 48 < 80 recounts, so a burst this size is
        # still worth absorbing — but the count must be PRESENT: a run
        # whose window is wall-to-wall overflow (β=10, dt=0.3 from the
        # census x0=1e-4 → all 6 steps above budget) must route to gather
        assert _auto_engine(outdeg, 64, 80, 2_000_000, 5.0, 0.1, 4096) == "incremental"
        assert _auto_engine(outdeg, 64, 6, 2_000_000, 10.0, 0.3, 4096) == "gather"
        # budget 3e5 leaves only the steepest steps above budget
        assert _auto_engine(outdeg, 64, 80, 2_000_000, 5.0, 0.1, 300_000) == "incremental"

    def test_census_matches_measured_zero_at_bench_shape(self):
        """CENSUS_CALIBRATION_cpu_2026-08-01.json ground truth: the ER bench
        shape (10^6 agents, β=1, dt=0.05, default budget, no-exit window)
        measured ZERO recount steps; the window-aware census must predict
        none (the old hard-coded 2-wave factor predicted 44)."""
        from sbr_tpu.social.agents import _census_fallback_steps

        outdeg = np.full(1000, 10)  # no hubs; only the overflow term acts
        assert (
            _census_fallback_steps(outdeg, 64, 200, 1_000_000, 1.0, 0.05, 15625, 1.0)
            == 0.0
        )
        # a finite window doubles the change mass back above budget (over a
        # horizon that covers the stretched transition peak: t_mid ≈ 11.5
        # at β_eff = 1/1.25, beyond the 200-step bench window)
        assert (
            _census_fallback_steps(outdeg, 64, 280, 1_000_000, 1.0, 0.05, 15625, 2.0)
            > 0.0
        )

    def test_auto_waves_from_window_geometry(self):
        """prepare_agent_graph derives the census wave count from the
        window's overlap with the horizon: a finite reentry_delay beyond
        T behaves like the infinite window (one wave), and an empty or
        post-horizon window produces no changes at all (zero waves →
        incremental, trivially clean)."""
        n = 3000
        src, dst = erdos_renyi_edges(n, 8.0, seed=2)
        # β=3 pushes the one-wave change mass just under this small budget;
        # the doubled mass would overflow — the engine choice is the probe
        for reentry, want_engine in [
            (np.inf, "incremental"),  # no exits ever
            (1e6, "incremental"),  # exits exist but far beyond T=12
            (2.0, "incremental"),  # in-horizon exits: 2 waves, still cheap here
        ]:
            cfg = AgentSimConfig(n_steps=120, dt=0.1, reentry_delay=reentry)
            pg = prepare_agent_graph(3.0, src, dst, n, config=cfg)
            assert pg.engine == want_engine, (reentry, pg.engine)
        # empty window: no agent ever changes; incremental is trivially clean
        cfg = AgentSimConfig(n_steps=120, dt=0.1, exit_delay=5.0, reentry_delay=2.0)
        pg = prepare_agent_graph(3.0, src, dst, n, config=cfg)
        assert pg.engine == "incremental"
        res = simulate_agents(prepared=pg, x0=0.01, config=cfg, seed=0)
        assert np.asarray(res.full_recount_steps).sum() == 0
        assert float(res.withdrawn_frac.max()) == 0.0

    def test_census_routes_stretch_tail_to_incremental(self):
        """The stretch scale-free shape (H=12098 hubs, 10^6 agents,
        lognormal-β mean 1.1331) measured incremental 1.42x faster on TPU
        (ENGINE_COMPARE_sf_tpu_2026-07-31.json) but the round-4 census
        routed it to gather; the telemetry-recalibrated census routes it
        to the measured winner (prediction 147 of 200 recount steps vs
        144 measured — CENSUS_CALIBRATION_cpu_2026-08-01.json)."""
        from sbr_tpu.social.agents import _auto_engine

        outdeg = np.zeros(1_000_000, np.int64)
        outdeg[:12098] = 200  # the stretch census's hub count
        args = (outdeg, 64, 200, 1_000_000, 1.1331, 0.05, 15625)
        assert _auto_engine(*args, waves=1.0) == "incremental"

    def test_max_chunk_slice_splits_hubs(self):
        """Edge-count sharding: a hub whose out-edges span chunk boundaries
        is censused by its largest per-chunk slice, not its full degree."""
        from sbr_tpu.social.agents import _max_chunk_slice

        # agent 0: 100 edges, agents 1..10: 10 each → out_ptr
        degs = np.array([100] + [10] * 10)
        out_ptr = np.concatenate([[0], np.cumsum(degs)])
        # chunk size 40: hub splits into slices 40/40/20 → max 40
        slices = _max_chunk_slice(out_ptr, 40, 11)
        assert slices[0] == 40
        assert (slices[1:] <= 10).all()

    def test_auto_matches_explicit_engines(self):
        """Whatever auto picks, results equal both explicit engines."""
        n = 3000
        src, dst = erdos_renyi_edges(n, 10.0, seed=41)
        cfg = AgentSimConfig(n_steps=60, dt=0.1, exit_delay=0.0, reentry_delay=2.0)
        auto = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=1)
        for eng in ("gather", "incremental"):
            r = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=1, engine=eng)
            np.testing.assert_array_equal(np.asarray(auto.informed), np.asarray(r.informed))
            np.testing.assert_array_equal(np.asarray(auto.t_inf), np.asarray(r.t_inf))


def test_plot_agent_closure_builds_figure():
    """The closure figure builder renders from a LoopComparison (unit-level;
    the CLI path is exercised by master --fast section 4)."""
    import matplotlib

    matplotlib.use("Agg")
    from sbr_tpu.figures.plotting import plot_agent_closure
    from sbr_tpu.social import close_loop

    comp = close_loop(
        n_agents=2000, avg_degree=10.0, dt=0.2, t_max=12.0,
        config=SolverConfig(n_grid=1024), max_iter=300,
    )
    fig = plot_agent_closure(comp)
    assert len(fig.axes) >= 2
    import matplotlib.pyplot as plt

    plt.close(fig)


class TestVerboseFixedPoint:
    def test_verbose_streams_iterations(self, capfd):
        """The reference threads `verbose` through its solver and prints
        per-iteration error/ξ (`social_learning_solver.jl:124-241`); here
        the same telemetry streams from inside the device while_loop."""
        m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
        res = solve_equilibrium_social(m, SolverConfig(n_grid=512), verbose=True)
        jax.effects_barrier()
        out = capfd.readouterr().out
        assert "[social fp] iter 1:" in out
        assert f"iter {int(res.iterations)}" in out


class TestPreparedGraph:
    def test_prepared_path_bit_identical(self):
        """prepare_agent_graph + prepared= must reproduce the one-shot call
        exactly (the rng stream is independent of graph prep), single-device
        and sharded, both engines."""
        from sbr_tpu.social import prepare_agent_graph

        n = 3001
        src, dst = erdos_renyi_edges(n, 9.0, seed=51)
        cfg = AgentSimConfig(n_steps=50, dt=0.1, exit_delay=0.1, reentry_delay=2.0)
        for mesh in (None, jax.make_mesh((8,), ("agents",))):
            for eng in ("gather", "incremental"):
                a = simulate_agents(1.1, src, dst, n, x0=0.01, config=cfg, seed=6,
                                    mesh=mesh, engine=eng)
                pg = prepare_agent_graph(1.1, src, dst, n, config=cfg, mesh=mesh, engine=eng)
                assert pg.engine == eng
                b = simulate_agents(prepared=pg, x0=0.01, config=cfg, seed=6)
                np.testing.assert_array_equal(np.asarray(a.informed), np.asarray(b.informed))
                np.testing.assert_array_equal(np.asarray(a.t_inf), np.asarray(b.t_inf))
                np.testing.assert_array_equal(
                    np.asarray(a.informed_frac), np.asarray(b.informed_frac)
                )
                # a second seed through the same prepared graph differs (the
                # prep cache must not freeze the seed stream)
                c = simulate_agents(prepared=pg, x0=0.01, config=cfg, seed=7)
                assert not np.array_equal(np.asarray(b.informed), np.asarray(c.informed))

    def test_missing_args_raise(self):
        with pytest.raises(ValueError, match="prepared="):
            simulate_agents(1.0, None, None, None)


class TestLaunchChunking:
    """config.max_steps_per_launch: host-level launch splitting must be
    BIT-IDENTICAL to the unchunked run for every engine/sharding combination
    — the step index is global (times + RNG stream unchanged) and the
    neighbor counts are integers that rebuild exactly at chunk starts."""

    def _graph(self, n=3000, seed=11):
        return erdos_renyi_edges(n, 12.0, seed=seed)

    def _assert_same(self, a, b):
        np.testing.assert_array_equal(np.asarray(a.t_grid), np.asarray(b.t_grid))
        np.testing.assert_array_equal(
            np.asarray(a.informed_frac), np.asarray(b.informed_frac)
        )
        np.testing.assert_array_equal(
            np.asarray(a.withdrawn_frac), np.asarray(b.withdrawn_frac)
        )
        np.testing.assert_array_equal(np.asarray(a.informed), np.asarray(b.informed))
        np.testing.assert_array_equal(np.asarray(a.t_inf), np.asarray(b.t_inf))
        assert a.agent_steps == b.agent_steps

    @pytest.mark.parametrize("engine", ["gather", "incremental"])
    def test_single_device_bit_identical(self, engine):
        n = 3000
        src, dst = self._graph(n)
        # finite reentry window: chunk starts must rebuild counts for agents
        # that are mid-window AND have already reentered (the wd_prev=False
        # rebuild path), and ragged 40/7 chunking exercises two chunk sizes
        base = dict(n_steps=40, dt=0.08, exit_delay=0.2, reentry_delay=1.6)
        one = simulate_agents(
            1.5, src, dst, n, x0=0.02, seed=9,
            config=AgentSimConfig(**base), engine=engine,
        )
        chunked = simulate_agents(
            1.5, src, dst, n, x0=0.02, seed=9,
            config=AgentSimConfig(**base, max_steps_per_launch=7), engine=engine,
        )
        self._assert_same(one, chunked)

    @pytest.mark.parametrize("engine", ["gather", "incremental"])
    def test_sharded_bit_identical(self, engine):
        n = 3001  # not divisible by 8 → padding carried across chunks
        src, dst = self._graph(n, seed=12)
        mesh = jax.make_mesh((8,), ("agents",))
        base = dict(n_steps=24, dt=0.08, exit_delay=0.2, reentry_delay=1.6)
        one = simulate_agents(
            1.5, src, dst, n, x0=0.02, seed=9, mesh=mesh,
            config=AgentSimConfig(**base), engine=engine,
        )
        chunked = simulate_agents(
            1.5, src, dst, n, x0=0.02, seed=9, mesh=mesh,
            config=AgentSimConfig(**base, max_steps_per_launch=9), engine=engine,
        )
        self._assert_same(one, chunked)

    def test_step_offset_resume_equals_full_run(self):
        """Two manual calls stitched with step_offset reproduce one run —
        the resume surface underneath the chunking loop."""
        n = 2000
        src, dst = self._graph(n, seed=13)
        cfg = AgentSimConfig(n_steps=30, dt=0.1, exit_delay=0.3, reentry_delay=2.0)
        full = simulate_agents(2.0, src, dst, n, x0=0.02, seed=4, config=cfg)
        cfg_a = AgentSimConfig(n_steps=18, dt=0.1, exit_delay=0.3, reentry_delay=2.0)
        cfg_b = AgentSimConfig(n_steps=12, dt=0.1, exit_delay=0.3, reentry_delay=2.0)
        a = simulate_agents(2.0, src, dst, n, x0=0.02, seed=4, config=cfg_a)
        b = simulate_agents(
            2.0, src, dst, n, x0=0.02, seed=4, config=cfg_b,
            informed0=np.asarray(a.informed), t_inf0=np.asarray(a.t_inf),
            step_offset=18,
        )
        np.testing.assert_array_equal(
            np.asarray(full.informed_frac),
            np.concatenate([np.asarray(a.informed_frac), np.asarray(b.informed_frac)]),
        )
        np.testing.assert_array_equal(np.asarray(full.informed), np.asarray(b.informed))
        np.testing.assert_array_equal(np.asarray(full.t_inf), np.asarray(b.t_inf))


class TestCounterRng:
    def test_threefry_block_matches_jax_internal(self):
        """The hand-rolled Threefry-2x32 must be the real algorithm —
        cross-checked bit-for-bit against JAX's own implementation."""
        jprng = pytest.importorskip("jax._src.prng")
        from sbr_tpu.social.agents import _threefry2x32

        k = jnp.array([0x12345678, 0x9ABCDEF0], dtype=jnp.uint32)
        counts = jnp.arange(128, dtype=jnp.uint32)
        ref = jprng.threefry_2x32(k, counts)
        x0, x1 = _threefry2x32(k[0], k[1], counts[:64], counts[64:])
        np.testing.assert_array_equal(np.asarray(ref[:64]), np.asarray(x0))
        np.testing.assert_array_equal(np.asarray(ref[64:]), np.asarray(x1))

    def test_counter_uniform_statistics(self):
        from sbr_tpu.social.agents import _agent_uniforms

        n = 200_000
        ids = jnp.arange(n, dtype=jnp.uint32)
        key = jax.random.PRNGKey(3)
        u = np.asarray(
            _agent_uniforms(key, jnp.int32(7), ids, jnp.float32, "counter")
        )
        assert u.dtype == np.float32
        assert 0.0 <= u.min() and u.max() < 1.0
        assert abs(u.mean() - 0.5) < 2e-3  # ~3 sigma of sqrt(1/12)/sqrt(n)
        assert abs(u.var() - 1.0 / 12.0) < 2e-3
        # adjacent-id independence (lag-1 correlation)
        r = np.corrcoef(u[:-1], u[1:])[0, 1]
        assert abs(r) < 0.01
        # different steps decorrelate
        u2 = np.asarray(
            _agent_uniforms(key, jnp.int32(8), ids, jnp.float32, "counter")
        )
        assert abs(np.corrcoef(u, u2)[0, 1]) < 0.01

    def test_counter_f64_uniforms(self):
        from sbr_tpu.social.agents import _agent_uniforms

        ids = jnp.arange(50_000, dtype=jnp.uint32)
        u = np.asarray(
            _agent_uniforms(jax.random.PRNGKey(1), jnp.int32(2), ids, jnp.float64,
                            "counter")
        )
        assert u.dtype == np.float64
        assert 0.0 <= u.min() and u.max() < 1.0
        assert abs(u.mean() - 0.5) < 4e-3
        # f64 draws carry sub-f32 resolution (52-bit mantissa path)
        assert (np.abs(u - u.astype(np.float32)) > 0).any()

    def test_counter_stream_engine_and_sharding_invariance(self):
        """Under rng_stream='counter' every equivalence the default stream
        guarantees must still hold: gather == incremental == 8-device
        sharded, bit for bit."""
        n = 5003
        src, dst = erdos_renyi_edges(n, 10.0, seed=17)
        cfg = AgentSimConfig(
            n_steps=60, dt=0.1, exit_delay=0.2, reentry_delay=2.0,
            rng_stream="counter",
        )
        mesh = jax.make_mesh((8,), ("agents",))
        base = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=5,
                               engine="gather")
        for kwargs in (
            dict(engine="incremental"),
            dict(engine="gather", mesh=mesh),
            dict(engine="incremental", mesh=mesh),
        ):
            other = simulate_agents(
                1.0, src, dst, n, x0=0.01, config=cfg, seed=5, **kwargs
            )
            np.testing.assert_array_equal(
                np.asarray(base.informed), np.asarray(other.informed), err_msg=str(kwargs)
            )
            np.testing.assert_array_equal(
                np.asarray(base.t_inf), np.asarray(other.t_inf), err_msg=str(kwargs)
            )

    def test_streams_are_different_realizations_of_same_dynamics(self):
        """foldin vs counter: same physics, different draws — final G
        differs but only within statistical scatter."""
        n = 4000
        src, dst = erdos_renyi_edges(n, 12.0, seed=19)
        a = simulate_agents(
            1.0, src, dst, n, x0=0.01,
            config=AgentSimConfig(n_steps=50, dt=0.1, rng_stream="foldin"), seed=5,
        )
        b = simulate_agents(
            1.0, src, dst, n, x0=0.01,
            config=AgentSimConfig(n_steps=50, dt=0.1, rng_stream="counter"), seed=5,
        )
        ga, gb = float(a.informed_frac[-1]), float(b.informed_frac[-1])
        assert ga != gb  # different realization...
        assert abs(ga - gb) < 0.1  # ...of the same dynamics

    def test_rng_stream_validation(self):
        with pytest.raises(ValueError, match="rng_stream"):
            AgentSimConfig(rng_stream="xor")


class TestMeasuredEngine:
    @pytest.mark.slow
    def test_measure_tries_wider_cap_on_heavy_tails(self):
        """When the census predicts a recount-heavy run and max_degree was
        not pinned, engine='measure' adds an 8x-wider cap candidate; the
        winner's results stay bit-identical to the explicit engines (the
        cap is perf-only). Pinning max_degree suppresses the candidate."""
        n = 4000
        src, dst = scale_free_edges(n, 10.0, gamma=2.2, seed=9)
        cfg = AgentSimConfig(n_steps=100, dt=0.1)
        pg = prepare_agent_graph(3.0, src, dst, n, config=cfg, engine="measure")
        labels = [lbl for lbl, _ in pg.measured_steps_per_sec]
        assert "incremental(max_degree=512)" in labels, labels
        assert len(labels) == 3
        got = simulate_agents(prepared=pg, x0=0.01, config=cfg, seed=2)
        want = simulate_agents(
            3.0, src, dst, n, x0=0.01, config=cfg, seed=2, engine="gather"
        )
        np.testing.assert_array_equal(np.asarray(got.informed), np.asarray(want.informed))
        np.testing.assert_array_equal(np.asarray(got.t_inf), np.asarray(want.t_inf))
        pinned = prepare_agent_graph(
            3.0, src, dst, n, config=cfg, engine="measure",
            incremental_max_degree=64,
        )
        assert len(pinned.measured_steps_per_sec) == 2

    def test_measure_picks_a_winner_and_matches_both(self):
        """engine="measure" must return one of the two engines with rates
        recorded for both, and simulating with the winner must match both
        explicit engines bit for bit (outputs are engine-invariant)."""
        from sbr_tpu.social import prepare_agent_graph

        n = 2000
        src, dst = erdos_renyi_edges(n, 10.0, seed=21)
        cfg = AgentSimConfig(n_steps=30, dt=0.1, exit_delay=0.1, reentry_delay=1.5)
        pg = prepare_agent_graph(1.0, src, dst, n, config=cfg, engine="measure")
        assert pg.engine in ("gather", "incremental")
        assert pg.measured_steps_per_sec is not None
        names = [e for e, _ in pg.measured_steps_per_sec]
        assert sorted(names) == ["gather", "incremental"]
        assert all(rate > 0 for _, rate in pg.measured_steps_per_sec)
        got = simulate_agents(prepared=pg, x0=0.01, config=cfg, seed=5)
        for eng in ("gather", "incremental"):
            want = simulate_agents(
                1.0, src, dst, n, x0=0.01, config=cfg, seed=5, engine=eng
            )
            np.testing.assert_array_equal(
                np.asarray(got.informed), np.asarray(want.informed)
            )
            np.testing.assert_array_equal(
                np.asarray(got.withdrawn_frac), np.asarray(want.withdrawn_frac)
            )

    def test_measure_rejected_alongside_prepared(self):
        """The prepared= conflict guard still fires for engine='measure'."""
        from sbr_tpu.social import prepare_agent_graph

        n = 500
        src, dst = erdos_renyi_edges(n, 5.0, seed=22)
        cfg = AgentSimConfig(n_steps=5, dt=0.1)
        pg = prepare_agent_graph(1.0, src, dst, n, config=cfg)
        with pytest.raises(ValueError, match="conflict with prepared"):
            simulate_agents(prepared=pg, config=cfg, engine="measure")
        # ANY explicit incremental_max_degree alongside prepared= is a
        # conflict since the None-default change — including the old
        # default value 64, which used to slip through unchecked
        with pytest.raises(ValueError, match="conflict with prepared"):
            simulate_agents(prepared=pg, config=cfg, incremental_max_degree=64)

    def test_measure_rejected_on_direct_simulate_call(self):
        """engine='measure' hides ~5x wall-clock in a one-shot call and
        discards the rates — only the prepare path accepts it."""
        n = 500
        src, dst = erdos_renyi_edges(n, 5.0, seed=23)
        with pytest.raises(ValueError, match="prepare_agent_graph feature"):
            simulate_agents(
                1.0, src, dst, n, config=AgentSimConfig(n_steps=5, dt=0.1),
                engine="measure",
            )

    def test_measure_probe_passthrough_and_validation(self):
        """measure_probe shapes the timed trajectory; unknown keys fail."""
        from sbr_tpu.social import prepare_agent_graph

        n = 1000
        src, dst = erdos_renyi_edges(n, 8.0, seed=24)
        cfg = AgentSimConfig(n_steps=10, dt=0.1)
        pg = prepare_agent_graph(
            1.0, src, dst, n, config=cfg, engine="measure",
            measure_probe={"x0": 0.3, "seed": 7},
        )
        assert pg.engine in ("gather", "incremental")
        with pytest.raises(ValueError, match="unknown keys"):
            prepare_agent_graph(
                1.0, src, dst, n, config=cfg, engine="measure",
                measure_probe={"not_a_key": 1},
            )

    def test_measure_empty_graph_short_circuits(self):
        """No edges: both candidates coerce to gather, so measure returns
        the gather prep without fake 'incremental' rates."""
        from sbr_tpu.social import prepare_agent_graph

        e = np.zeros(0, np.int32)
        pg = prepare_agent_graph(
            1.0, e, e, 100, config=AgentSimConfig(n_steps=3, dt=0.1),
            engine="measure",
        )
        assert pg.engine == "gather"
        assert pg.measured_steps_per_sec is None


class TestAgentStateCheckpoint:
    def test_disk_resume_bit_identical(self, tmp_path):
        """save → load → resume reproduces the uninterrupted run exactly
        (the disk form of the step_offset resume surface)."""
        from sbr_tpu.social import load_agent_state, save_agent_state

        n = 2000
        src, dst = erdos_renyi_edges(n, 12.0, seed=31)
        mk = lambda steps: AgentSimConfig(
            n_steps=steps, dt=0.1, exit_delay=0.3, reentry_delay=2.0
        )
        full = simulate_agents(2.0, src, dst, n, x0=0.02, seed=6, config=mk(30))
        a = simulate_agents(2.0, src, dst, n, x0=0.02, seed=6, config=mk(18))
        ckpt = tmp_path / "agents.npz"
        save_agent_state(ckpt, a, seed=6, dt=0.1)
        resume = load_agent_state(ckpt, dt=0.1)
        assert resume["step_offset"] == 18 and resume["seed"] == 6
        b = simulate_agents(2.0, src, dst, n, x0=0.02, config=mk(12), **resume)
        np.testing.assert_array_equal(
            np.asarray(full.informed_frac),
            np.concatenate([np.asarray(a.informed_frac), np.asarray(b.informed_frac)]),
        )
        np.testing.assert_array_equal(np.asarray(full.informed), np.asarray(b.informed))
        np.testing.assert_array_equal(np.asarray(full.t_inf), np.asarray(b.t_inf))

    def test_dt_mismatch_rejected(self, tmp_path):
        from sbr_tpu.social import load_agent_state, save_agent_state

        n = 300
        src, dst = erdos_renyi_edges(n, 5.0, seed=32)
        r = simulate_agents(1.0, src, dst, n, x0=0.02, seed=0,
                            config=AgentSimConfig(n_steps=4, dt=0.1))
        ckpt = tmp_path / "s.npz"
        save_agent_state(ckpt, r, seed=0, dt=0.1)
        with pytest.raises(ValueError, match="dt"):
            load_agent_state(ckpt, dt=0.05)

    def test_probe_without_measure_engine_rejected(self):
        from sbr_tpu.social import prepare_agent_graph

        n = 300
        src, dst = erdos_renyi_edges(n, 5.0, seed=33)
        with pytest.raises(ValueError, match="only applies to engine='measure'"):
            prepare_agent_graph(
                1.0, src, dst, n, config=AgentSimConfig(n_steps=3, dt=0.1),
                measure_probe={"x0": 0.1},
            )
