"""Tests for the (β, u, r) interest-rate policy sweep."""

import numpy as np
import pytest

from sbr_tpu import make_model_params, solve_learning, solve_equilibrium_baseline
from sbr_tpu.interest.solver import solve_equilibrium_interest
from sbr_tpu.models.params import SolverConfig, make_interest_params
from sbr_tpu.models.results import Status
from sbr_tpu.sweeps import policy_sweep_interest

CFG = SolverConfig(n_grid=1024, bisect_iters=60)


def test_policy_sweep_matches_scalar_solves():
    base = make_interest_params(u=0.0, delta=0.1)
    betas = np.asarray([0.8, 1.0, 1.5])
    us = np.asarray([0.0, 0.05])
    rs = np.asarray([0.0, 0.03, 0.06])
    sweep = policy_sweep_interest(betas, us, rs, base, CFG)
    assert sweep.xi.shape == (3, 2, 3)

    for bi, ui, ri in [(0, 0, 0), (1, 0, 2), (2, 1, 1)]:
        m = make_interest_params(
            beta=float(betas[bi]),
            # η/tspan pinned at base resolved values, like the sweep.
            eta=base.economic.eta,
            tspan=base.learning.tspan,
            u=float(us[ui]),
            r=float(rs[ri]),
            delta=0.1,
        )
        ls = solve_learning(m.learning, CFG)
        single = solve_equilibrium_interest(ls, m.economic, CFG)
        np.testing.assert_allclose(
            float(sweep.xi[bi, ui, ri]), float(single.base.xi), rtol=1e-10, equal_nan=True
        )
        assert int(sweep.status[bi, ui, ri]) == int(single.base.status)


def test_r_zero_plane_matches_baseline_sweep():
    """The r=0 plane must reproduce the baseline solver exactly — the
    reference's r=0 fallback oracle (`interest_rate_solver.jl:89-101`)."""
    base = make_interest_params(u=0.1, delta=0.1)
    betas = np.asarray([1.0, 2.0])
    us = np.asarray([0.05, 0.1, 0.3])
    sweep = policy_sweep_interest(betas, us, np.asarray([0.0]), base, CFG)

    for bi, beta in enumerate(betas):
        m = make_model_params(beta=float(beta), eta=base.economic.eta, tspan=base.learning.tspan)
        ls = solve_learning(m.learning, CFG)
        for ui, u in enumerate(us):
            from sbr_tpu.models.params import EconomicParams

            econ = EconomicParams(
                u=float(u),
                p=m.economic.p,
                kappa=m.economic.kappa,
                lam=m.economic.lam,
                eta_bar=m.economic.eta_bar,
                eta=m.economic.eta,
            )
            single = solve_equilibrium_baseline(ls, econ, CFG)
            np.testing.assert_allclose(
                float(sweep.xi[bi, ui, 0]), float(single.xi), rtol=1e-10, equal_nan=True
            )


def test_r_raises_collapse_threshold_monotonicity():
    """Higher r raises the continuation value, delaying/removing runs: the
    run region can only shrink as r grows (economic sanity check)."""
    base = make_interest_params(u=0.0, delta=0.1)
    rs = np.linspace(0.0, 0.09, 4)
    sweep = policy_sweep_interest(
        np.asarray([1.0]), np.linspace(0.0, 0.4, 24), rs, base, CFG
    )
    run = np.asarray(sweep.status) == int(Status.RUN)
    counts = run.sum(axis=(0, 1))  # per-r run counts
    assert (np.diff(counts) <= 0).all()
    assert counts[0] > 0


def test_r_above_delta_rejected():
    base = make_interest_params(delta=0.1)
    with pytest.raises(ValueError, match="must be < delta"):
        policy_sweep_interest([1.0], [0.1], [0.2], base, CFG)


def test_policy_sweep_at_stretch_scale():
    """10×10×10 = the BASELINE.md stretch-row grid (f32 sweep path, as run
    by benchmarks/stretch.py). Checks structural invariants at scale and a
    scalar spot-check; the exact-parity coverage lives in the small-grid
    tests above."""
    import jax.numpy as jnp

    base = make_interest_params(u=0.0, delta=0.1)
    betas = np.linspace(0.5, 3.0, 10)
    us = np.linspace(0.0, 0.45, 10)
    rs = np.linspace(0.0, 0.09, 10)
    sweep = policy_sweep_interest(betas, us, rs, base, dtype=jnp.float32)
    assert sweep.xi.shape == (10, 10, 10)

    status = np.asarray(sweep.status)
    xi = np.asarray(sweep.xi)
    run = status == int(Status.RUN)
    assert run.any() and (~run).any()  # both regimes present on this grid
    # xi finite exactly on run cells; NaN elsewhere
    assert np.isfinite(xi[run]).all()
    assert np.isnan(xi[~run]).all()
    # the run region shrinks as r grows (continuation value rises)
    counts = run.sum(axis=(0, 1))
    assert (np.diff(counts) <= 0).all()

    # spot-check one run cell against the scalar solver at f32 tolerance
    bi, ui, ri = map(int, np.argwhere(run)[0])
    m = make_interest_params(
        beta=float(betas[bi]), eta=base.economic.eta, tspan=base.learning.tspan,
        u=float(us[ui]), r=float(rs[ri]), delta=0.1,
    )
    cfg = SolverConfig(refine_crossings=False)  # the sweep-path default
    ls = solve_learning(m.learning, cfg, dtype=jnp.float32)
    single = solve_equilibrium_interest(ls, m.economic, cfg)
    np.testing.assert_allclose(
        float(sweep.xi[bi, ui, ri]), float(single.base.xi), rtol=2e-5
    )


def test_policy_sweep_sharded_matches_unsharded():
    """(B, U) mesh-sharded policy sweep equals the single-device program
    exactly (cells are independent; no collectives)."""
    import jax
    import jax.numpy as jnp

    base = make_interest_params(u=0.0, delta=0.1)
    betas = np.linspace(0.5, 3.0, 4)   # divides the 2-axis of the (2,4) mesh
    us = np.linspace(0.0, 0.4, 8)      # divides the 4-axis
    rs = np.linspace(0.0, 0.09, 3)
    mesh = jax.make_mesh((2, 4), ("b", "u"))
    sharded = policy_sweep_interest(betas, us, rs, base, CFG, mesh=mesh)
    single = policy_sweep_interest(betas, us, rs, base, CFG)
    np.testing.assert_array_equal(np.asarray(sharded.status), np.asarray(single.status))
    np.testing.assert_allclose(
        np.asarray(sharded.xi), np.asarray(single.xi), atol=1e-12, equal_nan=True
    )
    np.testing.assert_allclose(
        np.asarray(sharded.aw_max), np.asarray(single.aw_max), atol=1e-12, equal_nan=True
    )
