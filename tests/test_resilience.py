"""Tests for the resilience layer (`sbr_tpu.resilience`): deterministic
fault injection, the unified retry engine, self-healing tile execution
(sidecars / quarantine / degrade ladder), work stealing, graceful
shutdown, and the `report resilience` gate."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.resilience import (
    FaultPlan,
    InjectedFault,
    RetryBudget,
    RetryError,
    RetryPolicy,
    faults,
    heal,
    retry,
)
from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid
from sbr_tpu.utils import run_tiled_grid

CFG = SolverConfig(n_grid=96, bisect_iters=40)
BETAS = np.linspace(0.5, 2.0, 4)
US = np.linspace(0.05, 0.5, 4)
REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Every test starts and ends without an installed fault plan, and with
    fast retry backoffs (real sleeps belong in production, not the suite)."""
    monkeypatch.setenv("SBR_RETRY_BASE_DELAY_S", "0.01")
    faults.install(None)
    yield
    faults.install(None)


def _mono():
    return beta_u_grid(BETAS, US, make_model_params(), config=CFG)


# ---------------------------------------------------------------------------
# Fault plans
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_same_seed_same_fault_sequence(self):
        """Determinism: replaying the same invocation sequence against two
        plans with one seed fires identical faults; a different seed (with
        probabilistic rules) diverges."""
        spec = {
            "seed": 7,
            "rules": [
                {"point": "a", "kind": "nan", "p": 0.5},
                {"point": "b", "kind": "corrupt", "p": 0.3, "max_fires": 4},
            ],
        }

        def replay(plan):
            for i in range(40):
                plan.fire("a", target=f"t{i}")
                plan.fire("b", target=f"t{i}")
            return [(f["point"], f["kind"], f["target"], f["hit"]) for f in plan.firings]

        a, b = replay(FaultPlan(spec)), replay(FaultPlan(spec))
        assert a == b and len(a) > 0
        other = replay(FaultPlan({**spec, "seed": 8}))
        assert other != a

    def test_at_hits_match_and_max_fires(self):
        plan = FaultPlan(
            {
                "seed": 0,
                "rules": [
                    {"point": "p", "kind": "nan", "at_hits": [2], "match": "yes"},
                ],
            }
        )
        assert plan.fire("p", "yes-1") is None  # hit 1
        assert plan.fire("p", "no") is None  # no match: not even a hit
        rule = plan.fire("p", "yes-2")  # hit 2 -> fires
        assert rule is not None and rule.kind == "nan"
        assert plan.fire("p", "yes-3") is None

    def test_alignment_does_not_spend_other_rules_budget(self):
        """When one rule claims an invocation, the other matching rules'
        streams advance WITHOUT charging their max_fires budget — a planned
        fault must still happen on its own turn (code-review regression)."""
        plan = FaultPlan(
            {
                "seed": 0,
                "rules": [
                    {"point": "p", "kind": "nan", "at_hits": [1]},
                    {"point": "p", "kind": "corrupt", "p": 1.0, "max_fires": 1},
                ],
            }
        )
        assert plan.fire("p").kind == "nan"  # rule 0 claims hit 1
        assert plan.rules[1].fires == 0  # rule 1 aligned, budget untouched
        assert plan.fire("p").kind == "corrupt"  # rule 1 still fires

    def test_transient_raises_injected_fault(self):
        plan = FaultPlan(
            {"seed": 0, "rules": [{"point": "p", "kind": "transient"}]}
        )
        with pytest.raises(InjectedFault):
            plan.fire("p")
        assert plan.firings[0]["kind"] == "transient"

    def test_env_plan_parsing(self, monkeypatch, tmp_path):
        monkeypatch.setenv(
            "SBR_FAULT_PLAN",
            json.dumps({"seed": 3, "rules": [{"point": "x", "kind": "nan"}]}),
        )
        faults.reset()
        assert faults.plan().seed == 3
        # File-path form.
        f = tmp_path / "plan.json"
        f.write_text(json.dumps({"seed": 9, "rules": []}))
        monkeypatch.setenv("SBR_FAULT_PLAN", str(f))
        faults.reset()
        assert faults.plan().seed == 9

    def test_sweep_dispatch_fault_point_reaches_real_sweeps(self):
        faults.install(
            FaultPlan(
                {"seed": 0, "rules": [{"point": "sweep.dispatch", "kind": "transient", "max_fires": 1}]}
            )
        )
        with pytest.raises(InjectedFault):
            _mono()
        # max_fires exhausted: the very next sweep runs clean.
        assert _mono().max_aw.shape == (4, 4)


# ---------------------------------------------------------------------------
# Retry engine
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_transient_retried_then_recovers(self):
        calls = {"n": 0}
        outcomes = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return "ok"

        policy = RetryPolicy(max_attempts=4, base_delay_s=0.0)
        out = policy.call(
            flaky, scope="s", observer=lambda **r: outcomes.append(r["outcome"])
        )
        assert out == "ok" and calls["n"] == 3
        assert outcomes == ["retrying", "retrying", "recovered"]

    def test_deterministic_errors_fail_fast(self):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise ValueError("shape bug")

        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=5, base_delay_s=0.0).call(
                broken, scope="s", observer=lambda **r: None
            )
        assert calls["n"] == 1

    def test_gave_up_raises_retry_error(self):
        def always():
            raise RuntimeError("down")

        with pytest.raises(RetryError, match="failed after 2 attempts"):
            RetryPolicy(max_attempts=2, base_delay_s=0.0).call(
                always, scope="probe", observer=lambda **r: None
            )

    def test_budget_shared_across_scopes(self):
        budget = RetryBudget(1)
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.0)

        def always():
            raise RuntimeError("down")

        # First scope consumes the single shared retry, then exhausts it.
        with pytest.raises(RetryError, match="retry budget exhausted"):
            policy.call(always, scope="a", budget=budget, observer=lambda **r: None)
        assert budget.remaining == 0
        # Second scope gets no retries at all.
        outcomes = []
        with pytest.raises(RetryError):
            policy.call(
                always, scope="b", budget=budget,
                observer=lambda **r: outcomes.append(r["outcome"]),
            )
        assert outcomes == ["budget_exhausted"]

    def test_backoff_schedule_and_env(self, monkeypatch):
        policy = RetryPolicy(base_delay_s=10.0, multiplier=2.0, max_delay_s=25.0)
        assert [policy.delay_s(k) for k in (1, 2, 3)] == [10.0, 20.0, 25.0]
        monkeypatch.setenv("SBR_X_ATTEMPTS", "7")  # historical alias
        monkeypatch.setenv("SBR_X_BASE_DELAY_S", "0.5")
        p = retry.policy_from_env("SBR_X", max_attempts=3, base_delay_s=10.0)
        assert p.max_attempts == 7 and p.base_delay_s == 0.5

    def test_budget_time_based_refill(self):
        """Direct RetryBudget refill coverage (ISSUE 8 satellite — the
        serve engine only exercised it indirectly): the pool refreshes
        lazily against an injectable clock, and a read EXACTLY at the
        refill boundary (>=) refills."""
        clock = {"t": 0.0}
        budget = RetryBudget(2, refill_s=10.0, clock=lambda: clock["t"])
        assert budget.take() and budget.take() and not budget.take()
        assert budget.remaining == 0
        clock["t"] = 9.999  # strictly inside the window: still dry
        assert budget.remaining == 0 and not budget.take()
        clock["t"] = 10.0  # exactly at the boundary: refilled
        assert budget.remaining == 2
        assert budget.take()
        # The epoch reset at the refill: the NEXT window starts at t=10.
        clock["t"] = 19.999
        assert budget.remaining == 1
        clock["t"] = 20.0
        assert budget.remaining == 2

    def test_budget_without_refill_keeps_one_shot_semantics(self):
        clock = {"t": 0.0}
        budget = RetryBudget(1, clock=lambda: clock["t"])
        assert budget.take() and not budget.take()
        clock["t"] = 1e9
        assert budget.remaining == 0  # sweeps rely on a non-refilling pool


# ---------------------------------------------------------------------------
# Self-healing tile execution
# ---------------------------------------------------------------------------


class TestCorruptTileQuarantine:
    def test_corrupt_tile_quarantined_and_recomputed(self, tmp_path):
        base = make_model_params()
        mono = _mono()
        run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path)
        tiles = sorted(tmp_path.glob("tile_*.npz"))
        assert heal.verify_file(tiles[0]) == "ok"
        faults.corrupt_file(tiles[0])  # torn write: truncate to half
        assert heal.verify_file(tiles[0]) == "mismatch"

        second = run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path
        )
        # The quarantine holds the evidence; the slot was recomputed clean.
        assert list((tmp_path / "quarantine").glob("tile_*.npz"))
        assert heal.verify_file(tiles[0]) == "ok"
        np.testing.assert_array_equal(np.asarray(second.status), np.asarray(mono.status))
        np.testing.assert_allclose(
            np.asarray(second.xi), np.asarray(mono.xi), rtol=0, equal_nan=True
        )

    def test_non_owner_leaves_foreign_corrupt_tile_in_place(self, tmp_path):
        """A multihost non-owner pass must not quarantine a peer's corrupt
        tile — it would move the file away and then NOT recompute the slot,
        orphaning it (code-review regression). The owner's own pass (or the
        assembly pass) quarantines and recomputes."""
        base = make_model_params()
        run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path)
        tile = sorted(tmp_path.glob("tile_*.npz"))[0]
        faults.corrupt_file(tile)
        run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path,
            tile_owner=lambda b, u: False,  # none of the tiles are ours
        )
        assert tile.exists()  # evidence left for the owner
        assert not (tmp_path / "quarantine").exists()
        assert heal.verify_file(tile) == "mismatch"

    def test_legacy_tile_without_sidecar_is_trusted(self, tmp_path):
        base = make_model_params()
        run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path)
        tile = sorted(tmp_path.glob("tile_*.npz"))[0]
        # Rewrite the tile with a marker and DROP the sidecar: a pre-sidecar
        # build's checkpoint must keep resuming (served from disk as-is).
        data = np.load(tile)
        arrays = {k: data[k].copy() for k in data.files}
        arrays["xi"] = np.full_like(arrays["xi"], 321.0)
        with open(tile, "wb") as f:
            np.savez(f, **arrays)
        heal.sidecar_path(tile).unlink()
        assert heal.verify_file(tile) == "legacy"
        out = run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path
        )
        assert np.all(np.asarray(out.xi)[:2, :2] == 321.0)


class TestDegradeLadder:
    def test_nan_poisoned_cell_repaired(self, tmp_path):
        """A nan fault poisons one cell's results+flags; the degrade ladder
        re-runs it per-cell and restores the exact fault-free values."""
        base = make_model_params()
        mono = _mono()
        faults.install(
            FaultPlan(
                {"seed": 0, "rules": [
                    {"point": "tile.result", "kind": "nan", "cells": 1, "max_fires": 1},
                ]}
            )
        )
        healed = run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=tmp_path
        )
        np.testing.assert_array_equal(np.asarray(healed.xi), np.asarray(mono.xi))
        np.testing.assert_array_equal(np.asarray(healed.max_aw), np.asarray(mono.max_aw))
        # The repair is recorded in the checkpoint manifest.
        repairs = json.loads((tmp_path / "manifest.json").read_text())["repairs"]
        assert repairs and repairs[0]["repaired"] and repairs[0]["rung"] == 0

    def test_heal_disabled_leaves_poison(self):
        base = make_model_params()
        mono = _mono()
        faults.install(
            FaultPlan(
                {"seed": 0, "rules": [
                    {"point": "tile.result", "kind": "nan", "cells": 1, "max_fires": 1},
                ]}
            )
        )
        poisoned = run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), heal_divergent=False
        )
        # Cell (0,0) of the first tile was NaN-poisoned and stays poisoned —
        # the control proving the ladder (not luck) repaired it above.
        assert np.isnan(np.asarray(poisoned.xi)[0, 0])
        assert not np.isnan(np.asarray(mono.xi)[0, 0]) or True  # mono may be NaN-free here
        rest = np.asarray(poisoned.xi).copy()
        rest[0, 0] = np.asarray(mono.xi)[0, 0]
        np.testing.assert_array_equal(rest, np.asarray(mono.xi))


class TestTileRetry:
    def test_injected_transient_recovered_via_real_sweep(self, tmp_path):
        """A transient fault inside beta_u_grid (sweep.dispatch) is absorbed
        by the tile loop's retry policy — the real path, no monkeypatching."""
        base = make_model_params()
        mono = _mono()
        faults.install(
            FaultPlan(
                {"seed": 0, "rules": [
                    {"point": "sweep.dispatch", "kind": "transient", "at_hits": [1]},
                ]}
            )
        )
        out = run_tiled_grid(BETAS, US, base, config=CFG, tile_shape=(2, 2))
        np.testing.assert_array_equal(np.asarray(out.xi), np.asarray(mono.xi))


# ---------------------------------------------------------------------------
# kill -9 mid-tile -> resume
# ---------------------------------------------------------------------------


WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_enable_x64", True)  # match the suite's precision
import numpy as np
from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.utils import run_tiled_grid
from sbr_tpu.resilience import faults, FaultPlan

faults.install(FaultPlan({"seed": 0, "rules": [
    {"point": "tile.compute", "kind": "hang", "at_hits": [3], "duration_s": 120.0}]}))
run_tiled_grid(
    np.linspace(0.5, 2.0, 4), np.linspace(0.05, 0.5, 4), make_model_params(),
    config=SolverConfig(n_grid=96, bisect_iters=40),
    tile_shape=(2, 2), checkpoint_dir=sys.argv[1])
print("UNREACHABLE")
"""


class TestKillNineResume:
    def test_resume_after_sigkill_mid_tile(self, tmp_path):
        """kill -9 a sweep while a tile hangs (an injected 120 s stall);
        the resumed run serves finished tiles from disk and recomputes the
        rest — final grid identical to an uninterrupted one."""
        ckpt = tmp_path / "ckpt"
        script = tmp_path / "worker.py"
        script.write_text(WORKER)
        env = {**os.environ, "PYTHONPATH": str(REPO)}
        proc = subprocess.Popen(
            [sys.executable, str(script), str(ckpt)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        try:
            deadline = time.monotonic() + 300.0
            while len(list(ckpt.glob("tile_*.npz"))) < 2:
                assert proc.poll() is None, f"worker died early:\n{proc.stdout.read()}"
                assert time.monotonic() < deadline, "worker never produced 2 tiles"
                time.sleep(0.2)
            os.kill(proc.pid, signal.SIGKILL)  # no grace, no handlers: kill -9
            proc.wait(timeout=30.0)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == -signal.SIGKILL
        n_before = len(list(ckpt.glob("tile_*.npz")))
        assert 2 <= n_before < 4

        base = make_model_params()
        resumed = run_tiled_grid(
            BETAS, US, base, config=CFG, tile_shape=(2, 2), checkpoint_dir=ckpt
        )
        mono = _mono()
        np.testing.assert_allclose(
            np.asarray(resumed.xi), np.asarray(mono.xi), rtol=0, equal_nan=True
        )
        np.testing.assert_array_equal(np.asarray(resumed.status), np.asarray(mono.status))


# ---------------------------------------------------------------------------
# Work stealing
# ---------------------------------------------------------------------------


class TestWorkStealing:
    def test_survivor_adopts_orphaned_tiles(self, tmp_path):
        """Process 0 of 2 waits on a peer that never existed; after the
        grace period it leases and computes the orphan's tiles instead of
        timing out."""
        from sbr_tpu.parallel import run_tiled_grid_multihost

        base = make_model_params()
        betas = np.linspace(0.5, 3.0, 6)
        us = np.linspace(0.02, 0.3, 8)
        full = run_tiled_grid_multihost(
            betas, us, base, str(tmp_path), config=CFG, tile_shape=(3, 4),
            process_id=0, num_processes=2, poll_s=0.05, timeout_s=120.0,
            steal_grace_s=0.2, lease_ttl_s=5.0, elastic=False,
        )
        assert len(list(tmp_path.glob("tile_*.npz"))) == 4
        assert not list(tmp_path.glob("tile_*.lease"))  # scaffolding cleaned
        direct = run_tiled_grid(betas, us, base, config=CFG, tile_shape=(3, 4))
        np.testing.assert_allclose(
            np.asarray(full.xi), np.asarray(direct.xi), atol=0, equal_nan=True
        )

    def test_live_lease_blocks_steal_expired_lease_taken(self, tmp_path):
        from sbr_tpu.parallel.distributed import _try_lease

        assert _try_lease(tmp_path, 0, 0, ttl_s=60.0) is True
        # Second claimant: the live lease wins.
        assert _try_lease(tmp_path, 0, 0, ttl_s=60.0) is False
        # Backdate the lease past its TTL: takeover allowed.
        lease = tmp_path / "tile_b00000_u00000.lease"
        rec = json.loads(lease.read_text())
        rec["ts"] -= 120.0
        lease.write_text(json.dumps(rec))
        assert _try_lease(tmp_path, 0, 0, ttl_s=60.0) is True

    def test_lease_takeover_exactly_at_ttl_boundary(self, tmp_path, monkeypatch):
        """age == ttl is EXPIRED (strict `<` keeps a lease alive only
        strictly inside its window) — ISSUE 8 satellite, pinned with a
        frozen clock so the boundary is exact."""
        from sbr_tpu.parallel import distributed

        assert distributed._try_lease(tmp_path, 0, 0, ttl_s=60.0) is True
        lease = tmp_path / "tile_b00000_u00000.lease"
        ts = json.loads(lease.read_text())["ts"]
        monkeypatch.setattr(distributed.time, "time", lambda: ts + 60.0)
        assert distributed._try_lease(tmp_path, 0, 0, ttl_s=60.0) is True
        # One tick inside the window: the holder keeps it.
        fresh_ts = json.loads(lease.read_text())["ts"]
        monkeypatch.setattr(distributed.time, "time", lambda: fresh_ts + 59.999)
        assert distributed._try_lease(tmp_path, 0, 0, ttl_s=60.0) is False

    def test_expired_lease_race_loser_backs_off(self, tmp_path, monkeypatch):
        """Double-steal window fix (ISSUE 8 satellite): when a racer's
        record lands AFTER ours during an expired-lease takeover, the
        nonce re-read must tell us we LOST and _try_lease returns False."""
        import os as _os

        from sbr_tpu.parallel import distributed

        assert distributed._try_lease(tmp_path, 0, 0, ttl_s=60.0) is True
        lease = tmp_path / "tile_b00000_u00000.lease"
        rec = json.loads(lease.read_text())
        rec["ts"] -= 120.0  # expired: both survivors go for the takeover
        lease.write_text(json.dumps(rec))

        real_replace = _os.replace

        def racing_replace(src, dst):
            real_replace(src, dst)
            if str(dst) == str(lease):  # the racer replaces right after us
                rival = dict(json.loads(lease.read_text()))
                rival["nonce"] = "rival-nonce"
                lease.write_text(json.dumps(rival))

        monkeypatch.setattr(distributed.os, "replace", racing_replace)
        assert distributed._try_lease(tmp_path, 0, 0, ttl_s=60.0) is False


# ---------------------------------------------------------------------------
# Graceful shutdown + report resilience
# ---------------------------------------------------------------------------


PREEMPT_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_enable_x64", True)  # match the suite's precision
import numpy as np
from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.utils import run_tiled_grid
from sbr_tpu.resilience import faults, FaultPlan
from sbr_tpu import obs

faults.install(FaultPlan({"seed": 0, "rules": [
    {"point": "tile.compute", "kind": "preempt", "at_hits": [2]}]}))
obs.start_run(label="preempt", root=sys.argv[2])
run_tiled_grid(
    np.linspace(0.5, 2.0, 4), np.linspace(0.05, 0.5, 4), make_model_params(),
    config=SolverConfig(n_grid=96, bisect_iters=40),
    tile_shape=(2, 2), checkpoint_dir=sys.argv[1])
print("UNREACHABLE")
"""


class TestGracefulShutdown:
    def test_sigterm_finalizes_interrupted_manifest(self, tmp_path):
        """An injected preemption (SIGTERM to self mid-sweep) exits 143 with
        the obs manifest finalized as "interrupted" and no partial tile
        temp files left behind."""
        script = tmp_path / "worker.py"
        script.write_text(PREEMPT_WORKER)
        env = {**os.environ, "PYTHONPATH": str(REPO)}
        proc = subprocess.run(
            [sys.executable, str(script), str(tmp_path / "ckpt"), str(tmp_path / "obs")],
            capture_output=True, text=True, env=env, timeout=300.0,
        )
        assert proc.returncode == 143, proc.stdout + proc.stderr
        assert "UNREACHABLE" not in proc.stdout
        run_dir = next((tmp_path / "obs").iterdir())
        manifest = json.loads((run_dir / "manifest.json").read_text())
        assert manifest["status"] == "interrupted"
        assert manifest["resilience"]["faults"] == {"tile.compute:preempt": 1}
        assert not list((tmp_path / "ckpt").glob("*.tmp"))
        # The first tile landed before the preemption and survives for resume.
        assert len(list((tmp_path / "ckpt").glob("tile_*.npz"))) == 1


class TestReportResilience:
    def _run_with_events(self, tmp_path, emit):
        from sbr_tpu import obs

        with obs.run_context(label="r", run_dir=tmp_path / "run") as run:
            emit(run)
        return tmp_path / "run"

    def _report(self, run_dir, *extra):
        return subprocess.run(
            [sys.executable, "-m", "sbr_tpu.obs.report", "resilience", str(run_dir), *extra],
            capture_output=True, text=True, timeout=120.0,
        )

    def test_clean_run_exits_zero(self, tmp_path):
        run_dir = self._run_with_events(tmp_path, lambda run: None)
        proc = self._report(run_dir)
        assert proc.returncode == 0
        assert "clean" in proc.stdout

    def test_recovered_run_exits_zero_and_renders(self, tmp_path):
        def emit(run):
            run.log_fault("tile.compute", "transient")
            run.log_retry("Tile (0,0)", "retrying", attempt=1, backoff_s=0.1)
            run.log_retry("Tile (0,0)", "recovered", attempt=2)
            run.log_repair("quarantine", "tile_b00000_u00000.npz")

        run_dir = self._run_with_events(tmp_path, emit)
        proc = self._report(run_dir)
        assert proc.returncode == 0
        assert "INJECTED FAULTS" in proc.stdout and "REPAIRS" in proc.stdout

    def test_gave_up_gates_exit_one_and_json(self, tmp_path):
        def emit(run):
            run.log_retry("Tile (2,0)", "gave_up", attempt=3, error="dead backend")
            run.log_repair("degrade_ladder", "tile[0,1]", ok=False)

        run_dir = self._run_with_events(tmp_path, emit)
        proc = self._report(run_dir, "--json")
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["unrecovered"] == 2 and doc["exit"] == 1
        # Manifest roll-up carries the same story for humans.
        manifest = json.loads((run_dir / "manifest.json").read_text())
        assert manifest["resilience"]["retries"]["Tile (2,0)"]["gave_up"] == 1
