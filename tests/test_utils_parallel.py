"""Tests for the auxiliary subsystems (utils/) and mesh helpers (parallel/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.models.results import Status
from sbr_tpu.parallel import balanced_2d, make_agent_mesh, make_grid_mesh
from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid
from sbr_tpu.utils import StageTimer, run_tiled_grid, status_counts, status_summary
from sbr_tpu.utils.timing import fence

CFG = SolverConfig(n_grid=512, bisect_iters=60)


class TestMesh:
    def test_balanced_2d(self):
        assert balanced_2d(8) == (2, 4)
        assert balanced_2d(16) == (4, 4)
        assert balanced_2d(7) == (1, 7)
        assert balanced_2d(1) == (1, 1)
        for n in (2, 6, 12, 24):
            a, b = balanced_2d(n)
            assert a * b == n and a <= b

    def test_make_grid_mesh(self):
        mesh = make_grid_mesh()
        assert set(mesh.axis_names) == {"b", "u"}
        assert mesh.devices.size == len(jax.devices())

    def test_make_grid_mesh_bad_shape(self):
        with pytest.raises(ValueError):
            make_grid_mesh(shape=(3, 5))  # 15 != 8 devices

    def test_make_agent_mesh(self):
        mesh = make_agent_mesh()
        assert mesh.axis_names == ("agents",)

    def test_grid_sweep_on_helper_mesh(self):
        """The helper-built mesh drives a sharded sweep end to end."""
        mesh = make_grid_mesh()
        base = make_model_params()
        a, b = mesh.devices.shape
        grid = beta_u_grid(
            np.linspace(0.5, 2.0, 2 * a), np.linspace(0.05, 0.5, 2 * b), base, config=CFG, mesh=mesh
        )
        assert grid.max_aw.shape == (2 * a, 2 * b)
        assert int((np.asarray(grid.status) == int(Status.RUN)).sum()) > 0


class TestStatus:
    def test_counts_and_summary(self):
        status = jnp.asarray([0, 0, 1, 2, 3, 0], dtype=jnp.int32)
        counts = status_counts(status)
        assert counts["RUN"] == 3
        assert counts["NO_CROSSING"] == 1
        assert counts["NO_ROOT"] == 1
        assert counts["FALSE_EQ"] == 1
        s = status_summary(status)
        assert "3/6 run" in s

    def test_summary_matches_sweep(self):
        base = make_model_params()
        grid = beta_u_grid(np.linspace(0.5, 2.0, 4), np.linspace(0.05, 2.0, 8), base, config=CFG)
        counts = status_counts(grid.status)
        assert sum(counts.values()) == 32
        # High u region must contain no-run cells, low u must run.
        assert counts["RUN"] > 0
        assert counts["RUN"] < 32


class TestTiming:
    def test_stage_timer(self):
        timer = StageTimer()
        with timer.stage("a"):
            x = jnp.ones((64,)) * 2.0
            timer.sync(x)
        with timer.stage("b"):
            pass
        assert timer.times["a"] >= 0.0
        assert set(timer.times) == {"a", "b"}
        rep = timer.report()
        assert "a" in rep and "total" in rep

    def test_fence_handles_nan_and_ints(self):
        fence(jnp.asarray([1.0, jnp.nan]), jnp.asarray([1, 2], dtype=jnp.int32), jnp.asarray([True]))


class TestTiledCheckpoint:
    def _grids(self):
        return np.linspace(0.5, 2.0, 6), np.linspace(0.02, 1.0, 8)

    def test_matches_monolithic(self):
        betas, us = self._grids()
        base = make_model_params()
        mono = beta_u_grid(betas, us, base, config=CFG)
        tiled = run_tiled_grid(betas, us, base, config=CFG, tile_shape=(4, 3))
        np.testing.assert_allclose(
            np.asarray(tiled.max_aw), np.asarray(mono.max_aw), rtol=1e-12, equal_nan=True
        )
        np.testing.assert_array_equal(np.asarray(tiled.status), np.asarray(mono.status))

    def test_resume_from_disk(self, tmp_path):
        betas, us = self._grids()
        base = make_model_params()
        first = run_tiled_grid(betas, us, base, config=CFG, tile_shape=(3, 4), checkpoint_dir=tmp_path)
        tiles = sorted(tmp_path.glob("tile_*.npz"))
        assert len(tiles) == 2 * 2

        # Resume semantics: alter one stored tile (refreshing its sha256
        # sidecar so integrity verification still passes — a MISMATCHING
        # sidecar would rightly trigger quarantine+recompute, covered by
        # tests/test_resilience.py), delete another; the altered one must
        # be served from disk (proving no recompute), the deleted one
        # recomputed.
        from sbr_tpu.resilience import heal

        poisoned = np.load(tiles[0])
        arrays = {k: poisoned[k].copy() for k in poisoned.files}
        arrays["xi"] = np.full_like(arrays["xi"], 123.0)
        with open(tiles[0], "wb") as f:
            np.savez(f, **arrays)
        heal.write_sidecar(tiles[0])
        tiles[1].unlink()

        second = run_tiled_grid(betas, us, base, config=CFG, tile_shape=(3, 4), checkpoint_dir=tmp_path)
        assert np.all(np.asarray(second.xi)[:3, :4] == 123.0)
        # The rest of the grid still matches the first run.
        np.testing.assert_allclose(
            np.asarray(second.max_aw)[3:, :], np.asarray(first.max_aw)[3:, :],
            rtol=1e-12, equal_nan=True,
        )

    def test_retry_then_raise(self, monkeypatch):
        betas, us = self._grids()
        base = make_model_params()
        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            raise RuntimeError("injected")

        import sbr_tpu.utils.checkpoint as ckpt

        monkeypatch.setattr(ckpt, "beta_u_grid", boom)
        with pytest.raises(RuntimeError, match="failed after 3 attempts"):
            run_tiled_grid(betas, us, base, config=CFG, tile_shape=(6, 8), max_retries=2)
        assert calls["n"] == 3


class TestMultiHostFarming:
    """DCN sweep-farming layer (`parallel.distributed`): filesystem-
    coordinated tile split across processes, simulated here by running
    each process role sequentially in one process."""

    def test_tile_assignment_partitions_exactly(self):
        from sbr_tpu.parallel import tile_assignment

        for n_tiles in (1, 7, 8, 23):
            for n_proc in (1, 2, 3, 8):
                seen = []
                for p in range(n_proc):
                    seen.extend(tile_assignment(n_tiles, n_proc, p))
                assert sorted(seen) == list(range(n_tiles))
                sizes = [len(tile_assignment(n_tiles, n_proc, p)) for p in range(n_proc)]
                assert max(sizes) - min(sizes) <= 1

    def test_two_process_farm_assembles_full_grid(self, tmp_path):
        """Legacy static split (elastic=False): ownership is the launch-time
        tile_assignment share — elastic claim-queue semantics are covered by
        tests/test_elastic.py."""
        from sbr_tpu.parallel import run_tiled_grid_multihost

        base = make_model_params()
        betas = np.linspace(0.5, 3.0, 6)
        us = np.linspace(0.02, 0.3, 8)

        # worker 0: computes its share, returns immediately (wait=False)
        out0 = run_tiled_grid_multihost(
            betas, us, base, str(tmp_path), config=CFG, tile_shape=(3, 4),
            process_id=0, num_processes=2, wait=False, elastic=False,
        )
        assert out0 is None
        n_after_0 = len(list(tmp_path.glob("tile_*.npz")))
        assert 0 < n_after_0 < 4  # owns a strict subset of the 4 tiles

        # worker 1: computes the rest, waits (all present), assembles
        full = run_tiled_grid_multihost(
            betas, us, base, str(tmp_path), config=CFG, tile_shape=(3, 4),
            process_id=1, num_processes=2, poll_s=0.1, timeout_s=10.0,
            elastic=False,
        )
        assert len(list(tmp_path.glob("tile_*.npz"))) == 4
        direct = run_tiled_grid(betas, us, base, config=CFG, tile_shape=(3, 4))
        np.testing.assert_allclose(
            np.asarray(full.xi), np.asarray(direct.xi), atol=1e-12, equal_nan=True
        )
        np.testing.assert_array_equal(np.asarray(full.status), np.asarray(direct.status))

    def test_wait_times_out_on_missing_peer(self, tmp_path):
        from sbr_tpu.parallel import run_tiled_grid_multihost

        base = make_model_params()
        betas = np.linspace(0.5, 3.0, 6)
        us = np.linspace(0.02, 0.3, 8)
        with pytest.raises(TimeoutError, match="peer process likely died"):
            run_tiled_grid_multihost(
                betas, us, base, str(tmp_path), config=CFG, tile_shape=(3, 4),
                process_id=0, num_processes=2, poll_s=0.05, timeout_s=0.3,
                elastic=False, work_steal=False,
            )

    def test_initialize_distributed_single_process_noop(self, monkeypatch):
        from sbr_tpu.parallel import initialize_distributed

        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
        assert initialize_distributed() is False


class TestRealTwoProcessFarm:
    """Genuine process concurrency (VERDICT r2 task 7): process 0's share
    runs in a spawned subprocess while process 1 runs in-test against the
    SAME checkpoint dir, so manifest creation and tile writes
    (`utils/checkpoint.py`) race for real instead of being sequenced."""

    def test_concurrent_worker_subprocess(self, tmp_path):
        import subprocess
        import sys
        from pathlib import Path

        from sbr_tpu.parallel import run_tiled_grid_multihost

        repo = Path(__file__).resolve().parent.parent
        worker = tmp_path / "worker0.py"
        worker.write_text(
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "import numpy as np\n"
            "from sbr_tpu.models.params import SolverConfig, make_model_params\n"
            "from sbr_tpu.parallel import run_tiled_grid_multihost\n"
            # interpolate the module CFG so both processes share one sweep
            # fingerprint even if CFG changes
            f"cfg = SolverConfig(n_grid={CFG.n_grid}, bisect_iters={CFG.bisect_iters})\n"
            "base = make_model_params()\n"
            "betas = np.linspace(0.5, 3.0, 6)\n"
            "us = np.linspace(0.02, 0.3, 8)\n"
            f"run_tiled_grid_multihost(betas, us, base, {str(tmp_path / 'ckpt')!r},\n"
            "    config=cfg, tile_shape=(3, 4), process_id=0, num_processes=2,\n"
            "    wait=False)\n"
            "print('WORKER0 DONE', flush=True)\n"
        )
        import os

        env = {**os.environ, "PYTHONPATH": str(repo)}
        proc = subprocess.Popen(
            [sys.executable, str(worker)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(tmp_path),
        )
        try:
            base = make_model_params()
            betas = np.linspace(0.5, 3.0, 6)
            us = np.linspace(0.02, 0.3, 8)
            # process 1 starts immediately: both processes hit the shared
            # checkpoint dir (manifest fingerprint + tile writes) while the
            # other is live, and the wait-loop exercises the real barrier.
            full = run_tiled_grid_multihost(
                betas, us, base, str(tmp_path / "ckpt"), config=CFG,
                tile_shape=(3, 4), process_id=1, num_processes=2,
                poll_s=0.2, timeout_s=180.0,
            )
            out, _ = proc.communicate(timeout=180)
            assert proc.returncode == 0, f"worker failed:\n{out}"
            assert "WORKER0 DONE" in out
        finally:
            if proc.poll() is None:
                proc.kill()

        assert len(list((tmp_path / "ckpt").glob("tile_*.npz"))) == 4
        from sbr_tpu.utils import run_tiled_grid

        direct = run_tiled_grid(betas, us, base, config=CFG, tile_shape=(3, 4))
        np.testing.assert_allclose(
            np.asarray(full.xi), np.asarray(direct.xi), atol=1e-12, equal_nan=True
        )
        np.testing.assert_array_equal(np.asarray(full.status), np.asarray(direct.status))


def test_profiler_trace_writes_capture(tmp_path):
    """`utils.timing.trace` (the bench harness's profiler hook) captures an
    XLA trace into the given directory."""
    from sbr_tpu.utils.timing import trace

    with trace(str(tmp_path)):
        fence(jnp.arange(128.0) * 2.0)
    files = [p for p in tmp_path.rglob("*") if p.is_file()]
    assert files, "profiler trace produced no files"


class TestTwoProcessSharedMesh:
    """Regime 1 of `parallel/distributed.py`: one sharded program spanning
    processes (VERDICT r3 task 6 — the only §5.8 path that had never run
    with >1 process). Two SUBPROCESSES each bring up 4 virtual CPU devices,
    `initialize_distributed()` into one 2-process cluster, build an
    8-device global mesh, and run (a) the sharded agent sim and (b) the
    K-sharded hetero pipeline across it; the test compares both processes'
    replicated outputs against the same programs on this process's own
    single-process 8-device mesh."""

    WORKER = r"""
import os, sys, json
import numpy as np

pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]

import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from sbr_tpu.parallel import initialize_distributed
assert initialize_distributed(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert jax.local_device_count() == 4

from sbr_tpu.models.params import SolverConfig, make_hetero_params
from sbr_tpu.social import AgentSimConfig, erdos_renyi_edges, simulate_agents
from sbr_tpu.hetero import solve_hetero_sharded

mesh = jax.make_mesh((8,), ("agents",))
n = 4003
src, dst = erdos_renyi_edges(n, 8.0, seed=13)
cfg = AgentSimConfig(n_steps=30, dt=0.1, exit_delay=0.1, reentry_delay=2.0)
sim = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=5, mesh=mesh)
g = np.asarray(jax.device_get(sim.informed_frac))
aw = np.asarray(jax.device_get(sim.withdrawn_frac))

k = 16
rng = np.random.default_rng(0)
dist = rng.dirichlet(np.ones(k)); dist = dist / dist.sum()
m_het = make_hetero_params(betas=np.linspace(0.5, 2.0, k), dist=dist, eta_bar=15.0)
cfg_h = SolverConfig(n_grid=128, bisect_iters=40)
mesh_k = jax.make_mesh((8,), ("k",))
import jax.numpy as jnp
_, res_het, _ = solve_hetero_sharded(m_het, mesh_k, cfg_h, dtype=jnp.float32)
xi = float(res_het.xi)

np.savez(os.path.join(outdir, f"proc{pid}.npz"), g=g, aw=aw, xi=xi)
print(f"WORKER{pid} DONE", flush=True)
"""

    def test_shared_mesh_two_processes(self, tmp_path):
        import os
        import socket
        import subprocess
        import sys
        from pathlib import Path

        import jax

        repo = Path(__file__).resolve().parent.parent
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        worker = tmp_path / "mesh_worker.py"
        worker.write_text(self.WORKER)
        env = {
            **os.environ,
            "PYTHONPATH": str(repo),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        }
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(pid), str(port), str(tmp_path)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
                cwd=str(tmp_path),
            )
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=600)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        if any("Multiprocess computations aren't implemented" in out for out in outs):
            # jax's CPU backend gained multiprocess collectives only in newer
            # releases; on older jax the two-process mesh cannot exist at all
            # (environment-bound — the path is exercised for real on TPU pods).
            pytest.skip("this jax's CPU backend does not implement multiprocess computations")
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
            assert f"WORKER{pid} DONE" in out

        # single-process oracle on this process's own 8-device mesh
        from sbr_tpu.models.params import SolverConfig, make_hetero_params
        from sbr_tpu.social import AgentSimConfig, erdos_renyi_edges, simulate_agents
        from sbr_tpu.hetero import solve_hetero_sharded
        import jax.numpy as jnp

        mesh = jax.make_mesh((8,), ("agents",))
        n = 4003
        src, dst = erdos_renyi_edges(n, 8.0, seed=13)
        cfg = AgentSimConfig(n_steps=30, dt=0.1, exit_delay=0.1, reentry_delay=2.0)
        sim = simulate_agents(1.0, src, dst, n, x0=0.01, config=cfg, seed=5, mesh=mesh)

        k = 16
        rng = np.random.default_rng(0)
        dist = rng.dirichlet(np.ones(k))
        dist = dist / dist.sum()
        m_het = make_hetero_params(betas=np.linspace(0.5, 2.0, k), dist=dist, eta_bar=15.0)
        mesh_k = jax.make_mesh((8,), ("k",))
        _, res_het, _ = solve_hetero_sharded(
            m_het, mesh_k, SolverConfig(n_grid=128, bisect_iters=40), dtype=jnp.float32
        )

        for pid in (0, 1):
            got = np.load(tmp_path / f"proc{pid}.npz")
            np.testing.assert_allclose(
                got["g"], np.asarray(sim.informed_frac), atol=1e-6
            )
            np.testing.assert_allclose(
                got["aw"], np.asarray(sim.withdrawn_frac), atol=1e-6
            )
            assert got["xi"] == pytest.approx(float(res_het.xi), abs=1e-5)
