"""Core numerics substrate tests."""

import numpy as np
import jax
import jax.numpy as jnp
from scipy.integrate import cumulative_trapezoid

from sbr_tpu.core import (
    bisect,
    cumtrapz,
    cumulative_gauss_legendre,
    first_upcrossing,
    interp,
    interp_uniform,
    last_downcrossing,
    rk4,
    threshold_crossings,
)


def test_interp_matches_numpy():
    xp = np.linspace(0.0, 3.0, 57)
    fp = np.sin(xp) + 0.3 * xp
    x = np.linspace(-0.5, 3.5, 201)  # includes out-of-range (clamped)
    got = np.asarray(interp(x, xp, fp))
    want = np.interp(x, xp, fp)
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_interp_uniform_matches_general():
    t0, t1, n = 0.0, 30.0, 512
    xp = np.linspace(t0, t1, n)
    fp = np.cos(xp)
    x = np.linspace(-1.0, 31.0, 777)
    got = np.asarray(interp_uniform(x, t0, xp[1] - xp[0], jnp.asarray(fp)))
    want = np.interp(x, xp, fp)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


def test_cumtrapz_matches_scipy():
    x = np.sort(np.random.default_rng(0).uniform(0, 10, 300))
    y = np.exp(-0.3 * x) * np.sin(x)
    got = np.asarray(cumtrapz(jnp.asarray(y), x=jnp.asarray(x)))
    want = cumulative_trapezoid(y, x, initial=0.0)
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)


def test_cumulative_gauss_legendre_exact():
    grid = jnp.linspace(0.0, 5.0, 64)
    got = np.asarray(cumulative_gauss_legendre(lambda t: jnp.exp(t), grid, order=8))
    want = np.exp(np.asarray(grid)) - 1.0
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_threshold_crossings_interior():
    # hump crossing level 0.5 at exactly t=1 and t=3 for y = 1-(t-2)^2/... pick
    x = np.linspace(0.0, 4.0, 4001)
    y = 1.0 - (x - 2.0) ** 2 / 2.0  # crosses 0.5 at 1 and 3
    t_in, t_out = threshold_crossings(jnp.asarray(x), jnp.asarray(y), 0.5, 99.0)
    assert abs(float(t_in) - 1.0) < 1e-5
    assert abs(float(t_out) - 3.0) < 1e-5


def test_threshold_crossings_boundaries():
    x = jnp.linspace(0.0, 1.0, 100)
    y_low = jnp.zeros(100)
    t_in, t_out = threshold_crossings(x, y_low, 0.5, 42.0)
    assert float(t_in) == 42.0 and float(t_out) == 42.0
    y_high = jnp.ones(100)
    t_in, t_out = threshold_crossings(x, y_high, 0.5, 42.0)
    assert float(t_in) == 0.0 and float(t_out) == 1.0


def test_crossing_fallbacks_partial():
    # starts above, single down-crossing: first_up falls back to first above knot
    x = np.linspace(0.0, 1.0, 101)
    y = 1.0 - x  # crosses 0.5 at exactly 0.5, starts above
    t_in = float(first_upcrossing(jnp.asarray(x), jnp.asarray(y), 0.5, 9.0))
    t_out = float(last_downcrossing(jnp.asarray(x), jnp.asarray(y), 0.5, 9.0))
    assert t_in == 0.0
    assert abs(t_out - 0.5) < 1e-12


def test_bisect_root():
    f = lambda x: x**3 - 2.0
    got = float(bisect(f, jnp.asarray(0.0), jnp.asarray(2.0), num_iters=90))
    assert abs(got - 2.0 ** (1.0 / 3.0)) < 1e-14


def test_bisect_vmappable():
    targets = jnp.linspace(1.0, 8.0, 16)
    roots = jax.vmap(lambda c: bisect(lambda x: x**2 - c, 0.0, 10.0, num_iters=80))(targets)
    np.testing.assert_allclose(np.asarray(roots), np.sqrt(np.asarray(targets)), rtol=1e-12)


def test_rk4_logistic_vs_closed_form():
    beta, x0 = 1.3, 1e-4
    ts = jnp.linspace(0.0, 20.0, 2001)
    ys = rk4(lambda t, y, a: a * y * (1 - y), jnp.asarray(x0), ts, args=beta, substeps=2)
    want = x0 / (x0 + (1 - x0) * np.exp(-beta * np.asarray(ts)))
    np.testing.assert_allclose(np.asarray(ys), want, atol=1e-10)


def test_interp_guided_warped_grid_matches_searchsorted():
    """`warped_grid_index` + `interp_guided` must reproduce jnp.interp on the
    transition-warped hazard grid exactly — the analytic rank map replaces
    searchsorted inside the HJB scan (the warp-honoring interest path's
    measured 3.7x policy-sweep cost), so it must bracket identically at any
    β, at knots, between knots, and out of range."""
    from sbr_tpu.baseline.solver import _warped_grid, warped_grid_index
    from sbr_tpu.core import interp_guided

    rng = np.random.default_rng(3)
    x0 = 1e-4
    for beta in (1.0, 37.0, 1e3, 1e4):
        eta = 15.0 / beta
        n, warp = 257, 0.5
        grid = np.asarray(_warped_grid(eta, beta, x0, n, warp, jnp.float64))
        assert (np.diff(grid) >= 0).all()
        fp = np.sin(grid * beta) + grid * beta  # pointwise function of knots
        x = np.concatenate(
            [rng.uniform(-0.1 * eta, 1.1 * eta, 501), grid, 0.5 * (grid[:-1] + grid[1:])]
        )
        guess = warped_grid_index(x, eta, beta, x0, n, warp)
        got = np.asarray(
            interp_guided(x, jnp.asarray(grid), jnp.asarray(fp), guess)
        )
        want = np.interp(x, grid, fp)
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12, err_msg=f"beta={beta}")
