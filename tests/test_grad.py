"""Differentiable equilibria (ISSUE 13): IFT gradient correctness against
finite-difference oracles, primal bit-identity with the forward solvers,
grad-trust flags, Health tangent isolation, traced parameter construction,
calibration recovery, stress search, the `report grad` gate, served
sensitivities, and history schema 8.

Structural notes the assertions lean on:

- Reverse-mode THROUGH bisection iterations returns an exact 0 (the
  iterates are piecewise constant in θ), so an FD match ≤ 1e-5 proves the
  IFT custom rules carry the derivative — a leak cannot pass.
- Under adaptive numerics the root-finder is a `lax.while_loop`, which
  jax cannot reverse-differentiate AT ALL: `jax.grad` succeeding there is
  structural proof that no backprop touches the solver iterations.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu.diag.health import (
    GRAD_AT_NONEQUILIBRIUM,
    GRAD_ILL_CONDITIONED,
    GRAD_NONFINITE,
    flag_names,
)
from sbr_tpu.grad import api, calibrate, stress
from sbr_tpu.grad.cell import BASE_KEYS, aprime_tol, baseline_cell, interest_cell
from sbr_tpu.grad.ift import implicit_root
from sbr_tpu.models.params import (
    PARAMS_LEAF_NAMES,
    ModelParams,
    SolverConfig,
    make_interest_params,
    make_model_params,
    params_to_pytree,
    pytree_to_params,
    with_overrides,
)

F64 = jnp.float64
CFG = SolverConfig(n_grid=256, bisect_iters=90, refine_crossings=False)
CFG_REFINE = SolverConfig(n_grid=256, bisect_iters=90, refine_crossings=True)


def _theta(params, dtype=F64, **extra):
    th = {k: jnp.asarray(v, dtype) for k, v in params_to_pytree(params).items()
          if k != "eta_bar"}
    th.update({k: jnp.asarray(v, dtype) for k, v in extra.items()})
    return th


def _fd(fn, th, k, h_rel=1e-6):
    h = h_rel * max(1.0, abs(float(th[k])))
    up = dict(th)
    up[k] = th[k] + h
    dn = dict(th)
    dn[k] = th[k] - h
    return (float(fn(up)) - float(fn(dn))) / (2 * h)


# ---------------------------------------------------------------------------
# implicit_root
# ---------------------------------------------------------------------------


class TestImplicitRoot:
    def test_grad_matches_fd_and_iteration_backprop_is_zero(self):
        from sbr_tpu.core.rootfind import bisect

        def resid(x, th):
            return 1.0 / (1.0 + jnp.exp(-th["a"] * (x - 2.0))) - th["k"]

        def solve(th):
            return bisect(lambda x: resid(x, th), 0.0, 10.0, num_iters=70)

        th = {"a": jnp.asarray(1.3, F64), "k": jnp.asarray(0.4, F64)}
        x = implicit_root(resid, solve, th)
        g = jax.grad(lambda t: implicit_root(resid, solve, t))(th)
        for k in th:
            h = 1e-6
            up, dn = dict(th), dict(th)
            up[k] = th[k] + h
            dn[k] = th[k] - h
            fd = (implicit_root(resid, solve, up) - implicit_root(resid, solve, dn)) / (2 * h)
            assert abs(float(g[k]) - float(fd)) / abs(float(fd)) < 1e-6

        # The anti-oracle: differentiating THROUGH the iterations yields an
        # exact 0 — the structural reason the IFT rules exist.
        g_naive = jax.grad(lambda t: solve(t))(th)
        assert float(g_naive["a"]) == 0.0 and float(g_naive["k"]) == 0.0
        assert np.isfinite(float(x))

    def test_vmap_composes(self):
        from sbr_tpu.core.rootfind import bisect

        def resid(x, th):
            return x * x - th["k"]

        def solve(th):
            return bisect(lambda x: resid(x, th), 0.0, 4.0, num_iters=70)

        ks = jnp.linspace(1.0, 4.0, 5)
        grads = jax.vmap(lambda k: jax.grad(
            lambda t: implicit_root(resid, solve, t))({"k": k})["k"])(ks)
        # d sqrt(k)/dk = 1/(2 sqrt(k))
        np.testing.assert_allclose(
            np.asarray(grads), 1.0 / (2.0 * np.sqrt(np.asarray(ks))), rtol=1e-8
        )


# ---------------------------------------------------------------------------
# The FD oracle battery (acceptance: <= 1e-5 relative, f64)
# ---------------------------------------------------------------------------


class TestOracleBattery:
    def test_battery_fixed_refined(self):
        from sbr_tpu.grad.parity import run_battery

        rep = run_battery(n=4, seed=0, tol=1e-5, config=CFG_REFINE)
        assert rep["n_checked"] >= 2, rep
        assert rep["ok"], rep
        assert rep["worst_rel"] <= 1e-5

    def test_adaptive_numerics_grad_succeeds_and_matches(self):
        """Chandrupatla is a while_loop — reverse-mode through it raises;
        jax.grad succeeding here proves zero backprop through iterations,
        and the value matches the fixed path's gradient."""
        cfg_a = SolverConfig(n_grid=256, bisect_iters=60,
                             refine_crossings=False, numerics="adaptive")
        cfg_f = SolverConfig(n_grid=256, bisect_iters=60,
                             refine_crossings=False, numerics="fixed")
        params = make_model_params(beta=1.5, u=0.1, kappa=0.6)
        th = _theta(params)
        grads = {}
        for name, cfg in (("adaptive", cfg_a), ("fixed", cfg_f)):
            wrt = {k: th[k] for k in ("beta", "u", "kappa")}
            rest = {k: v for k, v in th.items() if k not in wrt}
            g = jax.grad(
                lambda wv: baseline_cell({**rest, **wv}, cfg, F64)["xi_candidate"]
            )(wrt)
            grads[name] = {k: float(v) for k, v in g.items()}
        for k in ("beta", "u", "kappa"):
            assert grads["adaptive"][k] == pytest.approx(grads["fixed"][k], rel=1e-6)

    def test_interest_grads_match_fd(self):
        params = make_interest_params(beta=1.5, u=0.1, kappa=0.6, r=0.005, delta=0.1)
        th = _theta(ModelParams(params.learning, params.economic),
                    r=0.005, delta=0.1)

        def xi_of(t):
            return interest_cell(t, CFG, F64)["xi_candidate"]

        wrt = ("beta", "u", "kappa", "r")
        g = jax.grad(lambda wv: xi_of({**th, **wv}))({k: th[k] for k in wrt})
        for k in wrt:
            fd = _fd(xi_of, th, k)
            assert abs(float(g[k]) - fd) / max(abs(fd), 1e-9) < 1e-5, k


# ---------------------------------------------------------------------------
# Primal bit-identity with the forward solvers
# ---------------------------------------------------------------------------


class TestPrimalEquality:
    def test_baseline_cell_bitwise_vs_solve_param_cell(self):
        from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

        params = make_model_params(beta=1.5, u=0.1, kappa=0.6)
        th = _theta(params)
        out = baseline_cell(th, CFG, F64)
        xi_f, tau_in_f, _, status_f, _ = solve_param_cell(
            *(th[k] for k in BASE_KEYS), CFG, F64
        )
        assert float(out["xi"]) == float(xi_f)
        assert float(out["tau_in"]) == float(tau_in_f)
        assert int(out["status"]) == int(status_f)

    def test_interest_cell_bitwise_vs_interest_solver(self):
        from sbr_tpu.baseline.learning import solve_learning
        from sbr_tpu.interest.solver import solve_equilibrium_interest

        for r in (0.0, 0.01):
            ip = make_interest_params(beta=1.5, u=0.1, kappa=0.6, r=r, delta=0.1)
            ls = solve_learning(ip.learning, CFG, dtype=F64)
            res = solve_equilibrium_interest(ls, ip.economic, CFG)
            th = _theta(ModelParams(ip.learning, ip.economic), r=r, delta=0.1)
            out = interest_cell(th, CFG, F64)
            assert int(out["status"]) == int(res.base.status)
            a, b = float(out["xi"]), float(res.base.xi)
            assert (a == b) or (np.isnan(a) and np.isnan(b))

    def test_nonrun_xi_masked_nan_with_zero_tangent(self):
        params = make_model_params(beta=1.5, u=0.5, kappa=0.6)  # no crossing
        th = _theta(params)
        out = baseline_cell(th, CFG, F64)
        assert np.isnan(float(out["xi"]))
        g = jax.grad(lambda wv: baseline_cell({**th, **wv}, CFG, F64)["xi"])(
            {"kappa": th["kappa"]}
        )
        assert float(g["kappa"]) == 0.0  # the NaN mask is a constant branch


# ---------------------------------------------------------------------------
# Grad-trust flags
# ---------------------------------------------------------------------------


class TestGradFlags:
    def test_nonequilibrium_flag(self):
        res = api.xi_and_grad(
            make_model_params(beta=1.5, u=0.5, kappa=0.6), config=CFG
        )
        assert int(res.flags) & GRAD_AT_NONEQUILIBRIUM
        assert not bool(res.trusted)
        assert "grad_at_nonequilibrium" in flag_names(int(res.flags))

    def test_ill_conditioned_flag_near_aw_plateau(self):
        """AW'(ξ) = g(ξ) on the interior branch: κ just under the
        reachable mass at SMALL u pushes ξ toward τ̄_OUT deep in the
        saturated tail where g ≈ 0 — the IFT denominator degenerates."""
        from sbr_tpu.baseline.learning import logistic_cdf

        params = make_model_params(beta=1.5, u=0.005, kappa=0.6)
        th = _theta(params)
        out = baseline_cell(th, CFG, F64)
        reach = float(
            logistic_cdf(out["tau_out"], th["beta"], th["x0"])
            - logistic_cdf(out["tau_in"], th["beta"], th["x0"])
        )
        th2 = dict(th)
        th2["kappa"] = jnp.asarray(reach * (1.0 - 1e-6), F64)
        out2 = baseline_cell(th2, CFG, F64, aprime_tol_=1e-2)
        assert int(out2["status"]) == 0, "must still be a RUN root"
        assert int(out2["flags"]) & GRAD_ILL_CONDITIONED
        # the healthy cell at the same tolerance carries no flag
        out_ok = baseline_cell(th, CFG, F64, aprime_tol_=1e-3)
        assert not (int(out_ok["flags"]) & GRAD_ILL_CONDITIONED)

    def test_aprime_tol_resolution(self, monkeypatch):
        assert aprime_tol(jnp.float64) == pytest.approx(float(jnp.finfo(jnp.float64).eps) ** 0.5)
        monkeypatch.setenv("SBR_GRAD_APRIME_TOL", "0.25")
        assert aprime_tol(jnp.float64) == 0.25
        assert aprime_tol(jnp.float64, 0.5) == 0.5  # explicit wins

    def test_flag_census_counts(self):
        surf = api.sensitivity_surface(
            np.linspace(0.8, 2.0, 3), np.array([0.08, 0.5]),
            make_model_params(), config=CFG,
        )
        census = api.flag_census(surf.status, surf.flags)
        assert census["cells"] == 6
        assert census["run_cells"] + census["at_nonequilibrium"] == 6
        assert census["nonfinite_run"] == 0  # NaN grads only on no-run lanes


# ---------------------------------------------------------------------------
# Health tangent isolation (satellite: stop_gradient at construction)
# ---------------------------------------------------------------------------


class TestHealthStopGradient:
    def test_threaded_health_gradient_equals_health_free_bitwise(self):
        from sbr_tpu.core.rootfind import bisect

        def with_health(k):
            x, h = bisect(lambda x: x * x - k, 0.0, 3.0, num_iters=40,
                          with_health=True)
            # A caller accidentally folding health leaves into a loss must
            # get the health-free gradient: the leaves carry no tangents.
            return x + h.residual + h.bracket_width

        def health_free(k):
            return bisect(lambda x: x * x - k, 0.0, 3.0, num_iters=40)

        g1 = jax.grad(with_health)(2.0)
        g0 = jax.grad(health_free)(2.0)
        assert float(g1) == float(g0)

    def test_full_solve_health_threading_leaks_nothing(self):
        th = _theta(make_model_params(beta=1.5, u=0.1, kappa=0.6))

        def loss_with_health(wv):
            from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

            xi, tau_in, aw_max, status, health = solve_param_cell(
                *( {**th, **wv}[k] for k in BASE_KEYS), CFG, F64
            )
            # residual depends on θ; stop_gradient must zero its tangent
            return jnp.nansum(aw_max) + health.residual

        def loss_plain(wv):
            from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

            xi, tau_in, aw_max, status, health = solve_param_cell(
                *( {**th, **wv}[k] for k in BASE_KEYS), CFG, F64
            )
            return jnp.nansum(aw_max)

        wv = {"u": th["u"]}
        g1 = jax.grad(loss_with_health)(wv)
        g0 = jax.grad(loss_plain)(wv)
        assert float(g1["u"]) == float(g0["u"])


# ---------------------------------------------------------------------------
# Traced params + pytree round-trip (satellite)
# ---------------------------------------------------------------------------


class TestParamsPytree:
    def test_make_model_params_accepts_traced_scalars(self):
        def f(beta):
            p = make_model_params(beta=beta)
            return p.economic.eta + p.learning.tspan[1]

        v = jax.jit(f)(jnp.asarray(2.0, F64))
        assert float(v) == pytest.approx(15.0 / 2.0 + 2 * 15.0 / 2.0)
        # and it differentiates — no silent float() coercion anywhere
        g = jax.grad(f)(jnp.asarray(2.0, F64))
        assert float(g) == pytest.approx(-3 * 15.0 / 4.0)

    def test_concrete_validation_still_raises(self):
        with pytest.raises(ValueError):
            make_model_params(beta=-1.0)
        with pytest.raises(ValueError):
            make_model_params(kappa=1.5)

    def test_round_trip_exact(self):
        p = make_model_params(beta=1.7, u=0.2, kappa=0.45, eta=3.3,
                              tspan=(0.0, 9.9), x0=2e-4)
        tree = params_to_pytree(p)
        assert set(tree) == set(PARAMS_LEAF_NAMES)
        q = pytree_to_params(tree)
        assert q == p

    def test_round_trip_rejects_bad_leaves(self):
        tree = params_to_pytree(make_model_params())
        tree["bogus"] = 1.0
        with pytest.raises(ValueError):
            pytree_to_params(tree)
        tree.pop("bogus")
        tree.pop("beta")
        with pytest.raises(ValueError):
            pytree_to_params(tree)

    def test_pytree_to_params_accepts_traced_leaves(self):
        def f(beta):
            tree = params_to_pytree(make_model_params())
            tree["beta"] = beta
            return pytree_to_params(tree).learning.beta * 2.0

        assert float(jax.jit(f)(jnp.asarray(3.0, F64))) == 6.0


# ---------------------------------------------------------------------------
# Trace accounting: differentiating adds zero solver traces
# ---------------------------------------------------------------------------


class TestTraceCounts:
    def test_grad_program_traces_root_solver_once(self):
        from sbr_tpu.obs import prof

        cfg = SolverConfig(n_grid=224, bisect_iters=90, refine_crossings=False)
        th = _theta(make_model_params(beta=1.5, u=0.1, kappa=0.6))

        def count():
            return prof.trace_counts().get("grad.root_solve", 0)

        before = count()
        jax.jit(lambda t: baseline_cell(t, cfg, F64)["xi_candidate"])(th)
        value_traces = count() - before

        before = count()
        jax.jit(jax.grad(
            lambda wv: baseline_cell({**th, **wv}, cfg, F64)["xi_candidate"]
        ))({"kappa": th["kappa"]})
        grad_traces = count() - before

        # refine off => exactly the ξ solve, and the BACKWARD pass adds no
        # additional solver program: one trace each.
        assert value_traces == 1
        assert grad_traces == 1


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------


class TestCalibration:
    def test_recovers_planted_parameters(self):
        truth = make_model_params(beta=1.4, u=0.12, kappa=0.55)
        t_obs, aw_obs, xi_obs = calibrate.synth_withdrawals(
            truth, n_obs=48, config=CFG
        )
        init = with_overrides(truth, beta=1.1, u=0.16, kappa=0.62)
        fit = calibrate.fit_withdrawals(
            t_obs, aw_obs, init, xi_obs=xi_obs, steps=400, config=CFG
        )
        assert fit.converged, (fit.loss, fit.steps)
        planted = {"beta": 1.4, "u": 0.12, "kappa": 0.55}
        for k, v in planted.items():
            assert abs(fit.params[k] - v) / v < 1e-3, (k, fit.params)

    def test_dead_start_reports_unconverged(self):
        truth = make_model_params(beta=1.4, u=0.12, kappa=0.55)
        t_obs, aw_obs, xi_obs = calibrate.synth_withdrawals(
            truth, n_obs=32, config=CFG
        )
        # u above the hazard peak: no crossing, flat curve, dead gradient
        bad = with_overrides(truth, u=0.6)
        fit = calibrate.fit_withdrawals(
            t_obs, aw_obs, bad, xi_obs=xi_obs, steps=80, config=CFG
        )
        assert not fit.converged

    def test_emits_obs_events(self, tmp_path):
        from sbr_tpu import obs

        truth = make_model_params(beta=1.4, u=0.12, kappa=0.55)
        t_obs, aw_obs, xi_obs = calibrate.synth_withdrawals(
            truth, n_obs=24, config=CFG
        )
        init = with_overrides(truth, beta=1.2, u=0.14, kappa=0.6)
        run_dir = tmp_path / "run"
        with obs.run_context(label="grad", run_dir=str(run_dir)):
            calibrate.fit_withdrawals(
                t_obs, aw_obs, init, xi_obs=xi_obs, steps=40, config=CFG
            )
        events = [
            json.loads(line)
            for line in (run_dir / "events.jsonl").read_text().splitlines()
        ]
        actions = [e.get("action") for e in events if e.get("kind") == "grad"]
        assert "calib_start" in actions and "calib_done" in actions


# ---------------------------------------------------------------------------
# Stress search
# ---------------------------------------------------------------------------


class TestStress:
    def test_flips_no_run_cell_and_matches_solver_boundary(self):
        from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

        p0 = make_model_params(beta=1.5, u=0.1, kappa=0.97)  # NO_ROOT: κ too high
        res = stress.stress_search(p0, wrt=("kappa",), steps=200, lr=0.02,
                                   config=CFG)
        assert res.flipped and res.validated
        assert res.margin0 > 0 and res.margin_final < 0
        kappa_star = res.params_flipped["kappa"]

        # Direct solver bisection on κ for the true run boundary.
        th = _theta(p0)

        def status_at(kappa):
            out = solve_param_cell(
                *((jnp.asarray(kappa, F64) if k == "kappa" else th[k])
                  for k in BASE_KEYS), CFG, F64,
            )
            return int(out[3])

        lo, hi = 0.5, 0.97  # run at lo, no-run at hi
        assert status_at(lo) == 0 and status_at(hi) != 0
        for _ in range(40):
            mid = 0.5 * (lo + hi)
            if status_at(mid) == 0:
                lo = mid
            else:
                hi = mid
        assert abs(kappa_star - lo) < 2e-3, (kappa_star, lo)

    def test_already_running_cell_is_zero_shock(self):
        res = stress.stress_search(
            make_model_params(beta=1.5, u=0.1, kappa=0.6),
            wrt=("kappa",), config=CFG,
        )
        assert res.flipped and res.margin0 < 0
        assert res.shock_norm == 0.0

    def test_margin_sign_agrees_with_solver(self):
        from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

        for kappa, u in ((0.6, 0.1), (0.97, 0.1), (0.6, 0.5)):
            th = _theta(make_model_params(beta=1.5, u=u, kappa=kappa))
            m = float(stress.run_margin(th, CFG, F64))
            status = int(solve_param_cell(*(th[k] for k in BASE_KEYS), CFG, F64)[3])
            assert (m < 0) == (status == 0), (kappa, u, m, status)


# ---------------------------------------------------------------------------
# report grad
# ---------------------------------------------------------------------------


class TestReportGrad:
    def _run_with_events(self, tmp_path, events):
        from sbr_tpu import obs

        run_dir = tmp_path / "run"
        with obs.run_context(label="grad", run_dir=str(run_dir)):
            for kw in events:
                obs.event("grad", **kw)
        return str(run_dir)

    def test_exit0_on_healthy_run(self, tmp_path, capsys):
        from sbr_tpu.obs.report import main

        d = self._run_with_events(tmp_path, [
            dict(action="calib_start", wrt=["beta"], steps=10, n_obs=8, lr=0.05),
            dict(action="calib_step", step=0, loss=0.1),
            dict(action="calib_done", steps=10, loss=1e-9, converged=True,
                 fit_beta=1.4),
            dict(action="flags", stage="s", cells=4, run_cells=2,
                 at_nonequilibrium=2, ill_conditioned=0, nonfinite=2,
                 nonfinite_run=0, untrusted=2),
        ])
        assert main(["grad", d]) == 0
        out = capsys.readouterr().out
        assert "CALIBRATIONS" in out and "GRADIENT FLAG CENSUS" in out

    def test_exit1_on_unconverged_calibration(self, tmp_path, capsys):
        from sbr_tpu.obs.report import main

        d = self._run_with_events(tmp_path, [
            dict(action="calib_done", steps=10, loss=0.5, converged=False),
        ])
        assert main(["grad", d, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["exit"] == 1 and doc["calibrations"][0]["converged"] is False

    def test_running_calibration_does_not_gate(self, tmp_path):
        """calib_start with no calib_done yet = a LIVE fit: reading the
        run dir mid-calibration must not produce a false-red exit 1."""
        from sbr_tpu.obs.report import main

        d = self._run_with_events(tmp_path, [
            dict(action="calib_start", wrt=["beta"], steps=100, n_obs=8, lr=0.05),
            dict(action="calib_step", step=0, loss=0.1),
        ])
        assert main(["grad", d]) == 0

    def test_exit1_on_nonfinite_run_gradients(self, tmp_path):
        from sbr_tpu.obs.report import main

        d = self._run_with_events(tmp_path, [
            dict(action="flags", stage="s", cells=4, run_cells=4,
                 at_nonequilibrium=0, ill_conditioned=0, nonfinite=1,
                 nonfinite_run=1, untrusted=1),
        ])
        assert main(["grad", d]) == 1

    def test_exit3_without_grad_data_and_2_on_bad_dir(self, tmp_path):
        from sbr_tpu import obs
        from sbr_tpu.obs.report import main

        run_dir = tmp_path / "empty"
        with obs.run_context(label="none", run_dir=str(run_dir)):
            pass
        assert main(["grad", str(run_dir)]) == 3
        assert main(["grad", str(tmp_path / "missing")]) == 2

    def test_real_surface_census_exits_zero(self, tmp_path, capsys):
        from sbr_tpu import obs
        from sbr_tpu.obs.report import main

        run_dir = tmp_path / "surf"
        with obs.run_context(label="grad", run_dir=str(run_dir)):
            api.sensitivity_surface(
                np.linspace(0.8, 2.0, 3), np.array([0.08, 0.5]),
                make_model_params(), config=CFG,
            )
        assert main(["grad", str(run_dir), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["censuses"][0]["stage"] == "grad.sensitivity_surface"


# ---------------------------------------------------------------------------
# Serving: grads=true queries
# ---------------------------------------------------------------------------


class TestServeGrads:
    def _engine(self, tmp_path=None):
        from sbr_tpu.serve.engine import Engine, ServeConfig

        cfg = SolverConfig(n_grid=128, bisect_iters=60, refine_crossings=False)
        serve = ServeConfig(
            buckets=(1, 4),
            cache_dir=str(tmp_path / "cache") if tmp_path is not None else None,
        )
        return Engine(config=cfg, serve=serve)

    def test_grads_query_matches_api_and_caches(self):
        eng = self._engine()
        p = make_model_params(beta=1.5, u=0.1, kappa=0.6)
        plain = eng.query(p)
        res = eng.query(p, grads=True)
        assert plain.grads is None and res.grads is not None
        assert res.xi == plain.xi  # the grad program serves the SAME ξ
        gres = api.xi_and_grad(
            p, config=eng.config, dtype=eng.dtype
        )
        for k in ("beta", "u", "kappa"):
            assert res.grads[k] == pytest.approx(float(gres.grads[k]), rel=1e-9)
        assert res.grad_flags == int(gres.flags)
        # separate cache identities, both hit on repeat
        assert eng.query(p, grads=True).source == "lru"
        assert eng.query(p).source == "lru"
        eng.close()

    def test_grads_survive_disk_restart(self, tmp_path):
        p = make_model_params(beta=1.5, u=0.1, kappa=0.6)
        eng = self._engine(tmp_path)
        first = eng.query(p, grads=True)
        eng.close()
        eng2 = self._engine(tmp_path)
        res = eng2.query(p, grads=True)
        assert res.source == "disk"
        assert res.grads == first.grads and res.grad_flags == first.grad_flags
        eng2.close()

    def test_endpoint_grads_field(self):
        import urllib.request

        from sbr_tpu.serve.endpoint import ServeEndpoint

        eng = self._engine()
        with ServeEndpoint(eng) as ep:
            body = json.dumps(
                {"beta": 1.5, "u": 0.1, "kappa": 0.6, "grads": True}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ep.port}/query", data=body,
                headers={"Content-Type": "application/json"},
            )
            doc = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert set(doc["grads"]) == {"beta", "u", "kappa"}
            assert "grad_flags" in doc
            # plain queries stay grad-free on the wire
            req2 = urllib.request.Request(
                f"http://127.0.0.1:{ep.port}/query",
                data=json.dumps({"beta": 1.5, "u": 0.1, "kappa": 0.6}).encode(),
                headers={"Content-Type": "application/json"},
            )
            doc2 = json.loads(urllib.request.urlopen(req2, timeout=30).read())
            assert "grads" not in doc2
        eng.close()


# ---------------------------------------------------------------------------
# History schema 8
# ---------------------------------------------------------------------------


class TestHistorySchema8:
    def test_polarity(self):
        from sbr_tpu.obs.history import polarity

        assert polarity("grads_per_sec") == 1
        assert polarity("calib_steps_per_sec") == 1

    def test_bench_metrics_picks_grad_keys(self):
        from sbr_tpu.obs.history import bench_metrics

        result = {
            "metric": "beta_u_grid_equilibria_per_sec", "value": 1000.0,
            "extra": {"grads_per_sec": 5000.0, "calib_steps_per_sec": 40.0},
        }
        m = bench_metrics(result)
        assert m["grads_per_sec"] == 5000.0
        assert m["calib_steps_per_sec"] == 40.0

    def test_schema8_gates_against_schema1_to_7(self, tmp_path):
        """Committed schema 1-7 lines still load, and a schema-8 append
        gates its new keys once enough points exist."""
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        lines = [
            {"ts": "t0", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1000.0}},  # schema-less → 1
        ] + [
            {"schema": s, "ts": f"t{s}", "label": "bench", "platform": "cpu",
             "metrics": {"eq_per_sec": 1000.0}}
            for s in range(2, 8)
        ]
        with open(path, "w") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        history.append(
            {"eq_per_sec": 990.0, "grads_per_sec": 5000.0}, platform="cpu",
            path=path,
        )
        records = history.load(path)
        assert [r["schema"] for r in records] == [1, 2, 3, 4, 5, 6, 7, history.SCHEMA]
        verdicts, status = history.check(records, tolerance=0.15)
        assert status == "ok"
        assert verdicts["eq_per_sec"]["status"] == "ok"
        # new key: too few points to gate yet — short, never a false alarm
        assert verdicts["grads_per_sec"]["status"] == "short"

    def test_schema8_regression_detected(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        for i in range(4):
            history.append({"grads_per_sec": 5000.0}, platform="cpu", path=path)
        history.append({"grads_per_sec": 2000.0}, platform="cpu", path=path)
        verdicts, status = history.check(history.load(path), tolerance=0.15)
        assert status == "regression"
        assert verdicts["grads_per_sec"]["status"] == "regression"
