"""Serving observatory tests (ISSUE 7): micro-batcher determinism, cache
warm-path compile accounting, AOT executable reload, live metrics windows,
the HTTP exposition endpoints, `report serve` gating, and the
params-fingerprint satellite.

The engine solves tiny SolverConfig programs so each bucket compiles in a
couple of seconds on CPU; everything here is tier-1."""

import json
import os
import subprocess
import sys
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.obs import prof
from sbr_tpu.obs.metrics import LogHistogram, log_bounds
from sbr_tpu.serve.engine import Engine, ServeConfig
from sbr_tpu.serve.live import LiveMetrics
from sbr_tpu.serve.loadgen import build_pool, query_mix
from sbr_tpu.utils.checkpoint import canonicalize, params_fingerprint

REPO = Path(__file__).resolve().parent.parent

# Small program: compiles fast, still exercises the full three-stage solve.
CFG = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)


def _bits(results):
    """Bitwise signature of per-query float outputs (NaN-safe)."""
    return [
        (
            np.float64(r.xi).tobytes(),
            np.float64(r.tau_bar_in).tobytes(),
            np.float64(r.aw_max).tobytes(),
            r.status,
            r.flags,
        )
        for r in results
    ]


# ---------------------------------------------------------------------------
# Satellite: public params fingerprint
# ---------------------------------------------------------------------------


class TestParamsFingerprint:
    def test_same_params_same_hex(self):
        a = make_model_params(beta=1.5, u=0.2)
        b = make_model_params(beta=1.5, u=0.2)
        assert params_fingerprint(a) == params_fingerprint(b)

    def test_dict_ordering_invariant(self):
        a = {"beta": 1.5, "u": 0.2, "nested": {"x": 1, "y": 2}}
        b = {"nested": {"y": 2, "x": 1}, "u": 0.2, "beta": 1.5}
        assert params_fingerprint(a) == params_fingerprint(b)

    def test_distinguishes_params(self):
        a = make_model_params(beta=1.5, u=0.2)
        b = make_model_params(beta=1.5, u=0.2000001)
        assert params_fingerprint(a) != params_fingerprint(b)

    def test_type_name_enters_hash(self):
        # Same numbers under a different dataclass type must not collide.
        assert "ModelParams(" in canonicalize(make_model_params())

    def test_unknown_type_fails_loudly(self):
        class Opaque:
            pass

        with pytest.raises(TypeError):
            params_fingerprint(Opaque())

    def test_stable_across_processes(self):
        params = make_model_params(beta=2.5, u=0.33)
        expected = params_fingerprint(params)
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from sbr_tpu.models.params import make_model_params\n"
            "from sbr_tpu.utils.checkpoint import params_fingerprint\n"
            "print(params_fingerprint(make_model_params(beta=2.5, u=0.33)))"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=240,
            env={**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"},
            cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr[-800:]
        assert out.stdout.strip() == expected


# ---------------------------------------------------------------------------
# Tentpole: micro-batcher determinism + cache/compile accounting
# ---------------------------------------------------------------------------


class TestMicroBatcher:
    def test_determinism_across_bucket_sizes(self):
        """The same seeded query stream through batch buckets 1, 8, and 64
        yields bitwise-identical per-query results (padded vmap lanes are
        independent)."""
        pool = build_pool(3, 10)
        stream = [pool[i] for i in query_mix(3, len(pool), 24)]
        signatures = []
        for bucket in (1, 8, 64):
            eng = Engine(config=CFG, serve=ServeConfig(buckets=(bucket,)))
            try:
                results = eng.query_many(stream)
            finally:
                eng.close()
            signatures.append(_bits(results))
        assert signatures[0] == signatures[1] == signatures[2]

    def test_cache_warm_replay_zero_compiles(self):
        """A cache-warm replay of the same stream issues ZERO new traces and
        zero new XLA compiles (asserted via the prof registries, which is
        what /metrics exposes) and serves everything from the LRU."""
        pool = build_pool(4, 8)
        stream = [pool[i] for i in query_mix(4, len(pool), 32)]
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(8,)))
        try:
            first = eng.query_many(stream)
            traces_before = dict(prof.trace_counts())
            compiles_before = prof.compile_totals()["compiles"]
            replay = eng.query_many(stream)
            assert prof.trace_counts() == traces_before
            assert prof.compile_totals()["compiles"] == compiles_before
        finally:
            eng.close()
        assert all(r.source == "lru" for r in replay)
        assert _bits(first) == _bits(replay)
        # repeated-mix stream over an 8-point pool: hit rate well over 0.5
        totals = eng.live.snapshot()["totals"]
        assert totals["queries"] == 64
        assert totals["cache_hits"] / totals["queries"] >= 0.5

    def test_threaded_path_matches_direct(self):
        pool = build_pool(5, 6)
        direct = Engine(config=CFG, serve=ServeConfig(buckets=(8,)))
        try:
            want = direct.query_many(pool)
        finally:
            direct.close()
        threaded = Engine(config=CFG, serve=ServeConfig(buckets=(8,)))
        threaded.start()
        try:
            got = threaded.query_many(pool, timeout=120)
        finally:
            threaded.close()
        assert _bits(want) == _bits(got)

    def test_scalar_query_and_scenario_accounting(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        try:
            r = eng.query(make_model_params(beta=1.0, u=0.1), scenario="fig4")
            assert r.source == "computed" and r.scenario == "fig4"
            r2 = eng.query(make_model_params(beta=1.0, u=0.1), scenario="fig4")
            assert r2.source == "lru"
            assert _bits([r]) == _bits([r2])
            assert eng.live.scenarios == {"fig4": 2}
        finally:
            eng.close()


class TestCaches:
    def test_disk_result_cache_survives_restart(self, tmp_path):
        pool = build_pool(6, 4)
        cfg = ServeConfig(buckets=(8,), cache_dir=str(tmp_path))
        a = Engine(config=CFG, serve=cfg)
        try:
            want = a.query_many(pool)
        finally:
            a.close()
        assert list((tmp_path / "results").rglob("*.json"))
        b = Engine(config=CFG, serve=cfg)
        try:
            got = b.query_many(pool)
        finally:
            b.close()
        assert all(r.source == "disk" for r in got)
        assert _bits(want) == _bits(got)

    def test_aot_executable_reload_skips_compile(self, tmp_path):
        """A restarted engine with the same cache dir reloads the serialized
        bucket executable: fresh params compute WITHOUT a new serve.batch
        trace or XLA compile (the ~2 s first-call compile is skipped)."""
        cfg = ServeConfig(buckets=(8,), cache_dir=str(tmp_path))
        a = Engine(config=CFG, serve=cfg)
        try:
            a.query_many(build_pool(7, 4))
        finally:
            a.close()
        if a._exec_meta["serialized"] == 0:
            pytest.skip(f"backend cannot serialize executables: {a._exec_meta['aot']}")
        assert list((tmp_path / "execs").glob("*.pkl"))

        b = Engine(config=CFG, serve=cfg)
        traces_before = dict(prof.trace_counts())
        compiles_before = prof.compile_totals()["compiles"]
        try:
            fresh = build_pool(8, 4)  # different params: result cache misses
            got = b.query_many(fresh)
        finally:
            b.close()
        assert all(r.source == "computed" for r in got)
        assert b._exec_meta["loaded"] == 1 and b._exec_meta["compiled"] == 0
        assert prof.trace_counts() == traces_before
        assert prof.compile_totals()["compiles"] == compiles_before

    def test_non_dict_disk_entry_recomputes(self, tmp_path):
        """A torn disk-cache write can leave valid NON-DICT JSON; the lookup
        must treat it as a miss (recompute), not kill the batcher thread."""
        cfg = ServeConfig(buckets=(8,), cache_dir=str(tmp_path))
        a = Engine(config=CFG, serve=cfg)
        try:
            want = a.query_many(build_pool(13, 2))
        finally:
            a.close()
        for f in (tmp_path / "results").rglob("*.json"):
            f.write_text("[1, 2, 3]")
        b = Engine(config=CFG, serve=cfg)
        b.start()
        try:
            got = b.query_many(build_pool(13, 2), timeout=120)
        finally:
            b.close()
        assert all(r.source == "computed" for r in got)
        assert _bits(want) == _bits(got)

    def test_submit_after_close_raises(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        eng.start()
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit(make_model_params())
        with pytest.raises(RuntimeError, match="closed"):
            eng.query_many([make_model_params()])

    def test_divergent_results_served_but_never_cached(self, tmp_path, monkeypatch):
        """A DIVERGENT_MASK result reaches the caller (flags visible) but
        must not enter the LRU or disk cache — a cached hit would replay
        the poison forever while /healthz recovered."""
        from sbr_tpu.diag.health import NAN_OUTPUT

        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,), cache_dir=str(tmp_path)))
        rec = {"xi": float("nan"), "tau_bar_in": 0.0, "aw_max": float("nan"),
               "status": 0, "flags": int(NAN_OUTPUT), "residual": float("nan")}
        monkeypatch.setattr(eng, "_dispatch", lambda params: [dict(rec) for _ in params])
        try:
            r1 = eng.query(make_model_params())
            assert r1.divergent and r1.source == "computed"
            r2 = eng.query(make_model_params())
            assert r2.source == "computed"  # recomputed, not a cache hit
            assert len(eng._lru) == 0
            assert not list((tmp_path / "results").rglob("*.json"))
            assert eng.live.totals["divergent_cells"] == 2  # stays visible
        finally:
            eng.close()

    def test_serveconfig_normalizes_buckets(self):
        assert ServeConfig(buckets=(64, 8, 1)).buckets == (1, 8, 64)
        with pytest.raises(ValueError):
            ServeConfig(buckets=(0, 8))

    def test_lru_eviction_bounded(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(8,), lru_max=3))
        try:
            eng.query_many(build_pool(9, 6))
            assert len(eng._lru) == 3
        finally:
            eng.close()

    def test_disk_cache_prune_bounded(self, tmp_path):
        cfg = ServeConfig(buckets=(8,), cache_dir=str(tmp_path), disk_cap=3)
        eng = Engine(config=CFG, serve=cfg)
        try:
            eng.query_many(build_pool(14, 6))
            eng._prune_disk_cache()  # cadence in prod is every 512 writes
            left = list((tmp_path / "results").rglob("*.json"))
            assert len(left) == 3
        finally:
            eng.close()

    def test_retry_budget_refills_over_time(self, monkeypatch):
        """A long-lived server must not latch unhealthy forever after the
        budget drains: the pool refreshes every SBR_SERVE_RETRY_REFILL_S."""
        import time as _time

        monkeypatch.setenv("SBR_SERVE_RETRY_REFILL_S", "0.05")
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        try:
            while eng.retry_budget.take():
                pass
            assert eng.healthz()["status"] == "unhealthy"
            _time.sleep(0.08)
            assert eng.healthz()["status"] == "ready"
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Live metrics: windowing, histograms, prometheus rendering
# ---------------------------------------------------------------------------


class TestLiveMetrics:
    def test_log_histogram_quantiles(self):
        h = LogHistogram(log_bounds(0.1, 1000.0, per_decade=4))
        for v in (1.0,) * 50 + (10.0,) * 45 + (500.0,) * 5:
            h.record(v)
        assert h.count == 100
        assert h.quantile(0.5) <= 2.0
        assert 5.0 <= h.quantile(0.95) <= 20.0
        assert h.quantile(0.99) >= 100.0
        s = h.summary()
        assert s["count"] == 100 and s["max"] == 500.0

    def test_histogram_delta_isolates_phase(self):
        h = LogHistogram(log_bounds(0.1, 1000.0, per_decade=4))
        for v in (500.0,) * 10:  # "warmup": slow samples
            h.record(v)
        before = h.copy()
        for v in (1.0,) * 30:  # "measured": fast samples
            h.record(v)
        d = h.delta(before)
        assert d.count == 30
        assert d.quantile(0.99) <= 2.0  # warmup's 500 ms never leaks in
        with pytest.raises(ValueError):
            h.delta(LogHistogram((1.0, 2.0)))

    def test_histogram_overflow_bucket(self):
        h = LogHistogram((1.0, 10.0))
        h.record(99999.0)
        assert h.counts[-1] == 1
        assert h.quantile(0.99) == 99999.0

    def test_window_expiry(self):
        clock = [0.0]
        live = LiveMetrics(window_s=12.0, time_fn=lambda: clock[0])
        live.record_query(0.001, "computed")
        assert live.window()["queries"] == 1
        clock[0] += 100.0  # all slots age out; lifetime totals stay
        assert live.window()["queries"] == 0
        assert live.totals["queries"] == 1

    def test_scenario_table_bounded(self):
        live = LiveMetrics(window_s=60.0)
        for i in range(200):
            live.record_query(0.001, "computed", scenario=f"tag{i}")
        assert len(live.scenarios) <= LiveMetrics._MAX_SCENARIOS + 1
        assert live.scenarios["_other"] == 200 - LiveMetrics._MAX_SCENARIOS

    def test_prometheus_exposition_shape(self):
        live = LiveMetrics(window_s=60.0)
        live.record_query(0.002, "computed")
        live.record_query(0.001, "lru")
        live.record_batch(3, 8)
        text = live.to_prometheus()
        assert "# TYPE sbr_serve_queries_total counter" in text
        assert "sbr_serve_queries_total 2" in text
        assert "sbr_serve_cache_hits_total 1" in text
        assert 'le="+Inf"' in text and "sbr_serve_latency_ms_count 2" in text
        assert "sbr_serve_xla_compiles_total" in text


# ---------------------------------------------------------------------------
# Endpoint + healthz + report serve gate
# ---------------------------------------------------------------------------


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:  # 404/503 still carry a body
        return err.code, err.read().decode()


class TestEndpointAndGate:
    def test_endpoint_routes_and_report_gate(self, tmp_path, monkeypatch):
        from sbr_tpu.obs.report import main as report_main
        from sbr_tpu.serve.endpoint import ServeEndpoint

        run_dir = tmp_path / "run"
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(8,)), run_dir=str(run_dir))
        eng.start()
        ep = ServeEndpoint(eng).start()
        try:
            pool = build_pool(11, 6)
            eng.query_many(pool + pool, timeout=180)  # repeats ⇒ cache hits
            code, metrics_text = _get(ep.port, "/metrics")
            assert code == 200 and "sbr_serve_queries_total 12" in metrics_text
            code, health = _get(ep.port, "/healthz")
            assert code == 200 and json.loads(health)["status"] == "ready"
            code, statz = _get(ep.port, "/statz")
            doc = json.loads(statz)
            assert doc["totals"]["queries"] == 12
            assert doc["window"]["hit_rate"] >= 0.5
            code, _ = _get(ep.port, "/nope")
            assert code == 404
        finally:
            ep.close()
            eng.close()

        # live.json landed in the run dir; gate passes with no SLO...
        assert (run_dir / "live.json").exists()
        for var in ("SBR_SERVE_SLO_MS", "SBR_SERVE_CACHE_FLOOR", "SBR_SERVE_WARMUP"):
            monkeypatch.delenv(var, raising=False)
        assert report_main(["serve", str(run_dir)]) == 0
        assert report_main(["serve", str(run_dir), "--json"]) == 0
        # ...exits 1 when the SLO is artificially low...
        monkeypatch.setenv("SBR_SERVE_SLO_MS", "0.000001")
        assert report_main(["serve", str(run_dir)]) == 1
        monkeypatch.delenv("SBR_SERVE_SLO_MS")
        # ...and 1 again when the cache floor is unreachable after warmup.
        assert report_main(
            ["serve", str(run_dir), "--cache-floor", "1.1", "--warmup", "1"]
        ) == 1
        # missing data → 3; missing dir → 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert report_main(["serve", str(empty)]) == 3
        assert report_main(["serve", str(tmp_path / "nothing")]) == 2

    def test_endpoint_close_without_start_returns(self):
        """socketserver's shutdown() deadlocks when serve_forever never ran;
        close() must special-case the never-started endpoint."""
        from sbr_tpu.serve.endpoint import ServeEndpoint

        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        try:
            ep = ServeEndpoint(eng)  # constructed, never started
            ep.close()  # must return, not deadlock
        finally:
            eng.close()

    def test_cache_floor_gate_scopes_consistently(self, tmp_path):
        """A quiet window holding two fresh queries on a long-warm server
        must NOT trip the floor gate: the rate and the arming count come
        from the same scope."""
        from sbr_tpu.obs.report import serve_doc

        run_dir = tmp_path / "run"
        run_dir.mkdir()
        live = {
            "schema": "sbr-serve-live/1", "ts": 0, "uptime_s": 9999,
            "totals": {"queries": 10000, "cache_hits": 9500, "hit_rate": 0.95,
                       "latency_ms": {"p99": 1.0}},
            "window": {"queries": 2, "cache_hits": 0, "hit_rate": 0.0,
                       "latency_ms": {"p99": 1.0}},
        }
        (run_dir / "live.json").write_text(json.dumps(live))
        doc, code = serve_doc(run_dir, cache_floor=0.5, warmup=50)
        assert code == 0, doc["breaches"]
        # but a genuinely cold warmed-up window still breaches
        live["window"] = {"queries": 200, "cache_hits": 10, "hit_rate": 0.05,
                         "latency_ms": {"p99": 1.0}}
        (run_dir / "live.json").write_text(json.dumps(live))
        doc, code = serve_doc(run_dir, cache_floor=0.5, warmup=50)
        assert code == 1 and "hit rate" in doc["breaches"][0]

    def test_loadgen_rejects_bad_buckets(self, capsys):
        # a bad token is a setup error (exit 2, stderr message), never a
        # traceback; empty tokens from trailing commas are filtered
        from sbr_tpu.serve.loadgen import main as loadgen_main

        assert loadgen_main(["--buckets", "-4"]) == 2
        assert loadgen_main(["--buckets", "x"]) == 2
        assert loadgen_main(["--buckets", ",,"]) == 2
        capsys.readouterr()

    def test_healthz_degraded_and_unhealthy(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        try:
            assert eng.healthz()["status"] == "ready"
            eng.live.record_query(0.001, "computed", divergent=True)
            assert eng.healthz()["status"] == "degraded"
            while eng.retry_budget.take():
                pass
            doc = eng.healthz()
            assert doc["status"] == "unhealthy"
            assert any("budget" in r for r in doc["reasons"])
        finally:
            eng.close()

    def test_dispatch_failure_marks_tickets_and_errors(self, monkeypatch):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        try:
            monkeypatch.setattr(
                eng, "_dispatch",
                lambda params: (_ for _ in ()).throw(RuntimeError("boom")),
            )
            with pytest.raises(RuntimeError, match="boom"):
                eng.query(make_model_params())
            assert eng.live.totals["errors"] == 1
            assert eng.healthz()["status"] == "degraded"
        finally:
            eng.close()


class TestLoadgen:
    def test_loadgen_assert_warm_via_metrics_scrape(self, tmp_path, capsys):
        """The acceptance contract end to end: after warmup, the seeded
        repeated-mix stream shows cache hit rate >= 0.5 and ZERO
        post-warmup XLA compiles — verified from the scraped /metrics
        counters (--assert-warm), not logs — and `report serve --json`
        exits 0 on the run dir the engine wrote."""
        from sbr_tpu.obs.report import main as report_main
        from sbr_tpu.serve.loadgen import main as loadgen_main

        run_dir = tmp_path / "run"
        rc = loadgen_main([
            "--queries", "60", "--pool", "8", "--seed", "0",
            "--n-grid", "96", "--bisect-iters", "30", "--buckets", "1,8",
            "--run-dir", str(run_dir), "--assert-warm",
        ])
        out = capsys.readouterr().out
        summary = json.loads(out.strip().splitlines()[-1])
        assert rc == 0, summary
        assert summary["cache_hit_rate"] >= 0.5
        assert summary["post_warmup_xla_compiles"] == 0
        assert summary["healthz"]["status"] == "ready"
        assert report_main(["serve", str(run_dir), "--json"]) == 0


# ---------------------------------------------------------------------------
# Satellite: torn events.jsonl tolerance in obs.report
# ---------------------------------------------------------------------------


class TestTornEventLog:
    def _run_dir(self, tmp_path) -> Path:
        d = tmp_path / "run"
        d.mkdir()
        (d / "manifest.json").write_text(json.dumps(
            {"schema": "sbr-obs/1", "label": "t", "status": "running",
             "n_events": 3, "stages": {}, "jit": {}}
        ))
        good = [
            {"mono": 0.1, "ts": 1.0, "kind": "stage_start", "stage": "s"},
            {"mono": 0.2, "ts": 1.1, "kind": "health", "stage": "s",
             "cells": 4, "divergent": 0},
        ]
        lines = [json.dumps(ev).encode() for ev in good]
        # Torn final line from a killed process: cut mid-record, mid-UTF-8
        # multibyte sequence (b"\xe2\x82" is a truncated €).
        lines.append(b'{"mono": 0.3, "ts": 1.2, "kind": "mem", "note": "\xe2\x82')
        (d / "events.jsonl").write_bytes(b"\n".join(lines))
        return d

    def test_load_run_tolerates_and_counts(self, tmp_path):
        from sbr_tpu.obs.report import load_run

        run = load_run(self._run_dir(tmp_path))
        assert run["bad_event_lines"] == 1
        assert [ev["kind"] for ev in run["events"]] == ["stage_start", "health"]

    def test_non_dict_line_counts_as_bad(self, tmp_path):
        from sbr_tpu.obs.report import load_run

        d = self._run_dir(tmp_path)
        with open(d / "events.jsonl", "ab") as fh:
            fh.write(b"\n42\n")
        assert load_run(d)["bad_event_lines"] == 2

    def test_report_subcommands_survive_torn_line(self, tmp_path, capsys):
        from sbr_tpu.obs.report import main as report_main

        d = str(self._run_dir(tmp_path))
        assert report_main([d]) == 0
        out = capsys.readouterr().out
        assert "1 unparseable event line(s) skipped" in out
        assert report_main(["health", d]) == 0  # intact health events gate
        assert report_main(["resilience", d]) == 0
        assert report_main([d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["bad_event_lines"] == 1


# ---------------------------------------------------------------------------
# Satellite: bench serve workload + schema-3 history
# ---------------------------------------------------------------------------


class TestBenchServe:
    def test_bench_serve_tiny(self, monkeypatch):
        monkeypatch.setenv("SBR_BENCH_SIZES", "tiny")
        sys.path.insert(0, str(REPO))
        try:
            import bench
        finally:
            sys.path.pop(0)
        out = bench.bench_serve("cpu")
        assert out["serve_queries"] == 48
        assert out["serve_p50_ms"] > 0
        assert out["serve_p99_ms"] >= out["serve_p50_ms"]
        assert out["serve_cache_hit_rate"] >= 0.5

    def test_history_schema3_backcompat(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        rows = [
            # schema-less (=1), schema 2, then schema-3 lines with serve metrics
            {"ts": "t0", "label": "bench", "platform": "cpu",
             "metrics": {"beta_u_grid_equilibria_per_sec": 1000.0}},
            {"schema": 2, "ts": "t1", "label": "bench", "platform": "cpu",
             "metrics": {"beta_u_grid_equilibria_per_sec": 1010.0,
                         "mem_peak_bytes": 5000}},
            {"schema": 3, "ts": "t2", "label": "bench", "platform": "cpu",
             "metrics": {"beta_u_grid_equilibria_per_sec": 1005.0,
                         "serve_p99_ms": 4.0, "serve_cache_hit_rate": 0.9}},
            {"schema": 3, "ts": "t3", "label": "bench", "platform": "cpu",
             "metrics": {"beta_u_grid_equilibria_per_sec": 1002.0,
                         "serve_p99_ms": 4.1, "serve_cache_hit_rate": 0.88}},
        ]
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        records = history.load(path)
        assert [r["schema"] for r in records] == [1, 2, 3, 3]
        verdicts, status = history.check(records, min_points=3)
        assert status == "ok"
        assert verdicts["beta_u_grid_equilibria_per_sec"]["status"] == "ok"

    def test_serve_latency_regression_gates(self, tmp_path):
        from sbr_tpu.obs import history

        assert history.polarity("serve_p99_ms") == -1
        assert history.polarity("serve_cache_hit_rate") == 1
        rows = [
            {"schema": 3, "ts": f"t{i}", "label": "bench", "platform": "cpu",
             "metrics": {"serve_p99_ms": 4.0}}
            for i in range(3)
        ] + [
            {"schema": 3, "ts": "t9", "label": "bench", "platform": "cpu",
             "metrics": {"serve_p99_ms": 40.0}}
        ]
        path = tmp_path / "hist.jsonl"
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        verdicts, status = history.check(history.load(path), min_points=3)
        assert status == "regression"
        assert verdicts["serve_p99_ms"]["status"] == "regression"

    def test_bench_metrics_picks_up_serve_keys(self):
        from sbr_tpu.obs.history import bench_metrics

        result = {
            "metric": "beta_u_grid_equilibria_per_sec", "value": 1.0,
            "extra": {"serve_p50_ms": 0.4, "serve_p99_ms": 4.0,
                      "serve_cache_hit_rate": 0.9},
        }
        got = bench_metrics(result)
        assert got["serve_p50_ms"] == 0.4
        assert got["serve_p99_ms"] == 4.0
        assert got["serve_cache_hit_rate"] == 0.9


# ---------------------------------------------------------------------------
# Checkpoint fingerprint integration (the extraction must keep protecting
# the tile checkpoints)
# ---------------------------------------------------------------------------


class TestSweepFingerprintIntegration:
    def test_sweep_fingerprint_uses_canonical_form(self):
        from sbr_tpu.utils.checkpoint import _sweep_fingerprint

        base = make_model_params()
        cfg = SolverConfig(refine_crossings=False)
        a = _sweep_fingerprint([0.5, 1.0], [0.1, 0.2], base, cfg, (2, 2), "float32")
        b = _sweep_fingerprint([0.5, 1.0], [0.1, 0.2], base, cfg, (2, 2), "float32")
        assert a == b
        c = _sweep_fingerprint([0.5, 1.0], [0.1, 0.2], base, cfg, (2, 2), "float64")
        assert a != c
