"""Information-model engine (ISSUE 15): spec algebra, the gossip bitwise
reduction, the fused belief kernel, panic rewiring determinism, mean-field
fixed points, the close-the-loop contract, seeds-axis population sweeps,
population serving, tiled scenario grids, report infomodel gating, and
history schema 10."""

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu import obs
from sbr_tpu.infomodels import (
    InfoModelSpec,
    crossing_times,
    default_spec,
    infomodel_fingerprint,
    info_learning_curve,
    observed_fraction,
    parse_population_doc,
    population_fingerprint,
    population_query,
    simulate_info,
    solve_fixed_point_info,
)
from sbr_tpu.models.params import SolverConfig, make_hetero_params, make_model_params
from sbr_tpu.social.agents import AgentSimConfig, simulate_agents
from sbr_tpu.social.closure import close_loop
from sbr_tpu.social.graphgen import (
    ErdosRenyiSpec,
    ScaleFreeSpec,
    StochasticBlockSpec,
    prepare_generated_graph,
)

REPO = Path(__file__).resolve().parent.parent

MODEL = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)


@pytest.fixture(scope="module")
def bayes_fp():
    """The default bayes fixed point at the Figure-12 economics, shared by
    every closure/population test in the module (the solve is the
    expensive step)."""
    return solve_fixed_point_info(
        InfoModelSpec(channel="bayes"), MODEL, config=SolverConfig(n_grid=512)
    )


# ---------------------------------------------------------------------------
# Spec algebra
# ---------------------------------------------------------------------------


class TestInfoModelSpec:
    def test_validation_rejects_bad_fields(self):
        with pytest.raises(ValueError, match="channel"):
            InfoModelSpec(channel="telepathy")
        with pytest.raises(ValueError, match="dynamics"):
            InfoModelSpec(dynamics="wormhole")
        with pytest.raises(ValueError, match="q_calm"):
            InfoModelSpec(q_calm=0.5, q_run=0.1)
        with pytest.raises(ValueError, match="threshold_scale"):
            InfoModelSpec(threshold_scale=0.0)
        with pytest.raises(ValueError, match="sum to 1"):
            InfoModelSpec(groups=((0.5, 3.0, 1.0), (0.6, 3.0, 1.0)))
        with pytest.raises(ValueError, match="K >= 2"):
            InfoModelSpec(groups=((1.0, 3.0, 1.0),))
        with pytest.raises(ValueError, match="epoch_steps"):
            InfoModelSpec(epoch_steps=0)

    def test_llr_signs(self):
        llr0, llr1 = InfoModelSpec(channel="bayes").llr
        assert llr0 < 0 < llr1

    def test_doc_round_trip(self):
        spec = InfoModelSpec(
            channel="bayes", dynamics="rewire", epoch_steps=7,
            groups=((0.25, 2.0, 1.0), (0.75, 4.0, 3.0)),
        )
        assert InfoModelSpec.from_doc(spec.to_doc()) == spec
        assert InfoModelSpec.from_doc({}) == InfoModelSpec()

    def test_doc_unknown_key_is_loud(self):
        with pytest.raises(ValueError, match="chanel"):
            InfoModelSpec.from_doc({"chanel": "bayes"})

    def test_reduces_to_gossip(self):
        assert InfoModelSpec().reduces_to_gossip()
        assert not InfoModelSpec(channel="bayes").reduces_to_gossip()
        assert not InfoModelSpec(dynamics="rewire").reduces_to_gossip()
        assert not InfoModelSpec(
            groups=((0.5, 3.0, 1.0), (0.5, 3.0, 2.0))
        ).reduces_to_gossip()

    def test_fingerprint_distinct_and_stable(self):
        a = infomodel_fingerprint(InfoModelSpec(), MODEL)
        b = infomodel_fingerprint(InfoModelSpec(channel="bayes"), MODEL)
        assert a != b
        assert a == infomodel_fingerprint(InfoModelSpec(), MODEL)
        assert a != infomodel_fingerprint(InfoModelSpec(), MODEL, extra=(1,))

    def test_from_hetero_params(self):
        hp = make_hetero_params(betas=(0.5, 1.5), dist=(0.4, 0.6))
        spec = InfoModelSpec.from_hetero_params(hp, channel="bayes")
        w, t, a = spec.group_table()
        assert w == (0.4, 0.6)
        # awareness = beta_k / <beta>, dist-weighted mean 1
        assert abs(sum(wi * ai for wi, ai in zip(w, a)) - 1.0) < 1e-12

    def test_default_spec_env(self, monkeypatch):
        monkeypatch.setenv("SBR_INFOMODEL", "bayes")
        monkeypatch.setenv("SBR_INFOMODEL_DYNAMICS", "rewire")
        monkeypatch.setenv("SBR_INFOMODEL_EPOCH_STEPS", "9")
        spec = default_spec()
        assert (spec.channel, spec.dynamics, spec.epoch_steps) == ("bayes", "rewire", 9)
        monkeypatch.setenv("SBR_INFOMODEL", "psychic")
        with pytest.raises(ValueError, match="SBR_INFOMODEL"):
            default_spec()


# ---------------------------------------------------------------------------
# Gossip bitwise reduction (ISSUE 15 satellite 3)
# ---------------------------------------------------------------------------


class TestGossipReduction:
    @pytest.mark.parametrize("engine", ["gather", "incremental"])
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("fused", ["lax", "interpret"])
    def test_bitwise_equal_to_legacy(self, engine, dtype, fused):
        graph = ErdosRenyiSpec(n=400, avg_degree=8.0)
        cfg = AgentSimConfig(n_steps=20, dt=0.1, fused=fused)
        r_info = simulate_info(
            InfoModelSpec(), graph, beta=1.2, x0=0.02, config=cfg, seed=5,
            dtype=dtype, engine=engine,
        )
        pg = prepare_generated_graph(
            graph, seed=5, betas=1.2, config=cfg, dtype=dtype, engine=engine
        )
        r_leg = simulate_agents(prepared=pg, x0=0.02, config=cfg, seed=5)
        for f in ("informed", "t_inf", "informed_frac", "withdrawn_frac", "t_grid"):
            np.testing.assert_array_equal(
                np.asarray(getattr(r_info, f)), np.asarray(getattr(r_leg, f))
            )
        assert r_info.belief is None and r_info.epochs == 1

    def test_group_heterogeneity_changes_trajectory(self):
        graph = ErdosRenyiSpec(n=2000, avg_degree=10.0)
        cfg = AgentSimConfig(n_steps=30, dt=0.1)
        homog = simulate_info(
            InfoModelSpec(), graph, beta=1.0, x0=0.02, config=cfg, seed=2
        )
        hetero = simulate_info(
            InfoModelSpec(groups=((0.5, 3.0, 0.2), (0.5, 3.0, 1.8))),
            graph, beta=1.0, x0=0.02, config=cfg, seed=2,
        )
        assert not np.array_equal(
            np.asarray(homog.informed_frac), np.asarray(hetero.informed_frac)
        )


# ---------------------------------------------------------------------------
# The fused belief kernel
# ---------------------------------------------------------------------------


class TestBeliefKernel:
    def _args(self, n, dtype):
        rng = np.random.default_rng(0)
        informed = jnp.asarray(rng.random(n) < 0.1)
        t_inf = jnp.where(informed, 0.0, 0.0).astype(dtype)
        belief = jnp.asarray(rng.normal(0, 1, n), dtype)
        counts = jnp.asarray(rng.integers(0, 12, n), jnp.int32)
        awareness = jnp.full(n, 2.0, dtype)
        deg = jnp.full(n, 12.0, dtype)
        thr = jnp.asarray(rng.normal(3.0, 1.5, n), dtype)
        return informed, t_inf, belief, counts, awareness, deg, thr

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
    def test_interpret_matches_lax(self, dtype):
        """Decisions (informed', t_inf') equal; beliefs ulp-close — the
        float accumulator may fuse differently per lowering (FMA), unlike
        the integer-Threefry infection kernel (see `belief_update`)."""
        from sbr_tpu.social.fused import belief_update

        n = 1500  # exercises the pad path (not a multiple of the block)
        args = self._args(n, dtype)
        llr0, llr1 = InfoModelSpec(channel="bayes").llr
        out_lax = belief_update(*args, 0.3, 0.1, llr0, llr1, "lax")
        out_int = belief_update(*args, 0.3, 0.1, llr0, llr1, "interpret")
        np.testing.assert_array_equal(np.asarray(out_lax[0]), np.asarray(out_int[0]))
        np.testing.assert_array_equal(np.asarray(out_lax[1]), np.asarray(out_int[1]))
        # a few ulp at the ACCUMULATOR's magnitude (the increment is a
        # same-order add, so relative error vs the small post-sum value
        # can read as tens of eps — measured 6e-6 f32 / 5e-15 f64)
        tol = 1e-4 if dtype == jnp.float32 else 1e-12
        np.testing.assert_allclose(
            np.asarray(out_lax[2]), np.asarray(out_int[2]), rtol=tol, atol=tol
        )

    def test_crossing_is_absorbing_and_stamps_t_inf(self):
        from sbr_tpu.social.fused import belief_update

        informed = jnp.zeros(4, bool)
        t_inf = jnp.zeros(4, jnp.float32)
        belief = jnp.asarray([0.0, 2.9, -5.0, 10.0], jnp.float32)
        counts = jnp.asarray([10, 10, 0, 0], jnp.int32)
        awareness = jnp.ones(4, jnp.float32)
        deg = jnp.full(4, 10.0, jnp.float32)
        thr = jnp.asarray([100.0, 3.0, 0.0, 3.0], jnp.float32)
        llr0, llr1 = InfoModelSpec(channel="bayes").llr
        inf2, t2, bel2 = belief_update(
            informed, t_inf, belief, counts, awareness, deg, thr,
            1.0, 0.1, llr0, llr1, "lax",
        )
        inf2, t2 = np.asarray(inf2), np.asarray(t2)
        assert not inf2[0]  # threshold out of reach
        assert inf2[1] and t2[1] == pytest.approx(1.1)  # crossed this step
        assert not inf2[2]  # negative evidence, threshold 0 not crossed
        assert inf2[3] and t2[3] == pytest.approx(1.1)  # already above

    def test_unfused_resolves_to_lax(self):
        from sbr_tpu.social.fused import resolve_belief_mode

        assert resolve_belief_mode("unfused", np.float32) == "lax"
        assert resolve_belief_mode("pallas", np.float64) == "lax"
        with pytest.raises(ValueError, match="belief mode"):
            resolve_belief_mode("warp", np.float32)


# ---------------------------------------------------------------------------
# Panic rewiring
# ---------------------------------------------------------------------------


class TestRewire:
    GRAPH = ErdosRenyiSpec(n=800, avg_degree=8.0)
    CFG = AgentSimConfig(n_steps=24, dt=0.1)

    def test_epoch_count_and_divergence_from_static(self):
        spec = InfoModelSpec(dynamics="rewire", epoch_steps=8, rewire_bias=2.0)
        r = simulate_info(spec, self.GRAPH, beta=1.2, x0=0.02, config=self.CFG, seed=3)
        assert r.epochs == 3
        r_static = simulate_info(
            InfoModelSpec(), self.GRAPH, beta=1.2, x0=0.02, config=self.CFG, seed=3
        )
        assert not np.array_equal(
            np.asarray(r.informed_frac), np.asarray(r_static.informed_frac)
        )

    def test_in_process_determinism(self):
        spec = InfoModelSpec(
            channel="bayes", dynamics="rewire", epoch_steps=8, rewire_bias=2.0
        )
        r1 = simulate_info(spec, self.GRAPH, x0=0.02, config=self.CFG, seed=4)
        r2 = simulate_info(spec, self.GRAPH, x0=0.02, config=self.CFG, seed=4)
        for f in ("informed", "t_inf", "belief", "informed_frac", "withdrawn_frac"):
            np.testing.assert_array_equal(
                np.asarray(getattr(r1, f)), np.asarray(getattr(r2, f))
            )

    def test_cross_process_determinism(self):
        spec = InfoModelSpec(dynamics="rewire", epoch_steps=8, rewire_bias=2.0)
        r = simulate_info(spec, self.GRAPH, beta=1.2, x0=0.02, config=self.CFG, seed=6)
        digest = hashlib.sha256(
            np.asarray(r.informed).tobytes() + np.asarray(r.t_inf).tobytes()
        ).hexdigest()
        code = (
            "import hashlib, numpy as np\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "from sbr_tpu.infomodels import InfoModelSpec, simulate_info\n"
            "from sbr_tpu.social.graphgen import ErdosRenyiSpec\n"
            "from sbr_tpu.social.agents import AgentSimConfig\n"
            "spec = InfoModelSpec(dynamics='rewire', epoch_steps=8, rewire_bias=2.0)\n"
            "g = ErdosRenyiSpec(n=800, avg_degree=8.0)\n"
            "r = simulate_info(spec, g, beta=1.2, x0=0.02,"
            " config=AgentSimConfig(n_steps=24, dt=0.1), seed=6)\n"
            "print(hashlib.sha256(np.asarray(r.informed).tobytes()"
            " + np.asarray(r.t_inf).tobytes()).hexdigest())"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"},
            cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr[-800:]
        assert out.stdout.strip() == digest

    def test_tilt_table_shape_and_monotone(self):
        from sbr_tpu.social.graphgen import tilt_threshold_table

        wd = jnp.zeros(100, bool).at[10].set(True)
        thr = np.asarray(tilt_threshold_table(jnp.ones(100), wd, 4.0))
        assert thr.dtype == np.uint32
        assert (np.diff(thr.astype(np.int64)) >= 0).all()
        assert thr[-1] == 4294967295
        # the withdrawing slot's probability mass is (1+bias)x a calm slot's
        gap = thr.astype(np.int64)[10] - thr.astype(np.int64)[9]
        calm = thr.astype(np.int64)[9] - thr.astype(np.int64)[8]
        assert gap == pytest.approx(5 * calm, rel=0.01)

    def test_sbm_base_rejected(self):
        spec = InfoModelSpec(dynamics="rewire")
        sbm = StochasticBlockSpec(n=100, avg_degree=5.0)
        with pytest.raises(ValueError, match="rewire"):
            simulate_info(spec, sbm, config=self.CFG)

    def test_prepared_conflicts_with_rewire(self):
        pg = prepare_generated_graph(self.GRAPH, seed=0, betas=1.0, config=self.CFG)
        with pytest.raises(ValueError, match="prepared"):
            simulate_info(
                InfoModelSpec(dynamics="rewire"), self.GRAPH, config=self.CFG,
                prepared=pg,
            )

    def test_bias_zero_rewire_matches_static_physics(self):
        """The scalar awareness (a bayes knob, default 3.0) must CANCEL in
        the gossip channel: a bias-0 rewire of the default spec is the
        same model as static up to graph realizations, so the trajectories
        agree in distribution — a hidden β×awareness multiplier on one
        path (the review finding) would triple the cascade speed."""
        g = ErdosRenyiSpec(n=4000, avg_degree=12.0)
        cfg = AgentSimConfig(n_steps=60, dt=0.1)
        r_st = simulate_info(InfoModelSpec(), g, beta=1.0, x0=0.02, config=cfg, seed=3)
        r_rw = simulate_info(
            InfoModelSpec(dynamics="rewire", rewire_bias=0.0, epoch_steps=10),
            g, beta=1.0, x0=0.02, config=cfg, seed=3,
        )
        g_st = float(np.asarray(r_st.informed_frac)[-1])
        g_rw = float(np.asarray(r_rw.informed_frac)[-1])
        assert abs(g_st - g_rw) < 0.1, (g_st, g_rw)

    def test_scale_free_base_runs(self):
        spec = InfoModelSpec(dynamics="rewire", epoch_steps=12, rewire_bias=1.0)
        sf = ScaleFreeSpec(n=500, avg_degree=6.0, gamma=2.5)
        r = simulate_info(spec, sf, beta=1.0, x0=0.05, config=self.CFG, seed=1)
        assert r.epochs == 2
        assert np.isfinite(np.asarray(r.informed_frac)).all()


# ---------------------------------------------------------------------------
# Mean-field fixed points
# ---------------------------------------------------------------------------


class TestMeanField:
    def test_observed_fraction_tilt(self):
        spec = InfoModelSpec(dynamics="rewire", rewire_bias=4.0)
        aw = np.asarray([0.0, 0.1, 1.0])
        w = np.asarray(observed_fraction(jnp.asarray(aw), spec))
        np.testing.assert_allclose(w, aw * 5.0 / (1.0 + 4.0 * aw), rtol=1e-6)
        static = InfoModelSpec()
        assert observed_fraction(jnp.asarray(aw), static) is not None
        np.testing.assert_array_equal(
            np.asarray(observed_fraction(jnp.asarray(aw), static)), aw
        )

    def test_bayes_learning_curve_shape(self):
        spec = InfoModelSpec(channel="bayes")
        grid = jnp.linspace(0.0, 10.0, 200)
        aw = jnp.full(200, 0.3)
        ls = info_learning_curve(spec, 0.9, aw, grid, 1e-4)
        cdf = np.asarray(ls.cdf)
        assert (np.diff(cdf) >= -1e-12).all()  # monotone
        assert cdf[0] > 0.05  # the panic-prone instant cohort
        assert (np.asarray(ls.pdf) >= 0).all()

    def test_bayes_fixed_point_runs_and_converges(self, bayes_fp):
        assert bool(bayes_fp.converged)
        assert bool(bayes_fp.equilibrium.bankrun)
        assert 0.0 < float(bayes_fp.xi) < float(MODEL.economic.eta)

    def test_gossip_reducible_delegates_to_legacy(self):
        from sbr_tpu.social.solver import solve_equilibrium_social

        cfg = SolverConfig(n_grid=256)
        fp_info = solve_fixed_point_info(InfoModelSpec(), MODEL, config=cfg)
        fp_leg = solve_equilibrium_social(MODEL, config=cfg)
        assert np.array_equal(np.asarray(fp_info.aw), np.asarray(fp_leg.aw))
        assert float(fp_info.xi) == float(fp_leg.xi)

    def test_gossip_rewire_fixed_point_has_run(self):
        spec = InfoModelSpec(dynamics="rewire", rewire_bias=1.0, epoch_steps=5)
        fp = solve_fixed_point_info(spec, MODEL, config=SolverConfig(n_grid=512))
        assert bool(fp.converged) and bool(fp.equilibrium.bankrun)


# ---------------------------------------------------------------------------
# Close-the-loop contract + the seeds axis
# ---------------------------------------------------------------------------


class TestCloseLoop:
    def test_bayes_closes_against_mean_field(self, bayes_fp):
        comp = close_loop(
            model=MODEL, infomodel=InfoModelSpec(channel="bayes"),
            n_agents=4000, avg_degree=15.0, dt=0.05, g0=0.2, t_max=8.0,
            n_reps=2, fp=bayes_fp, tolerance=0.25,
        )
        assert comp.err_aw_sup < 0.25
        assert comp.err_g_rms < 0.06
        assert comp.infomodel is not None

    def test_gossip_rewire_closes_against_tilted_curve(self):
        spec = InfoModelSpec(dynamics="rewire", epoch_steps=2, rewire_bias=1.0)
        comp = close_loop(
            model=MODEL, infomodel=spec, n_agents=6000, avg_degree=15.0,
            dt=0.1, g0=0.02, t_max=14.0, config=SolverConfig(n_grid=512),
        )
        assert comp.err_aw_sup < 0.3
        assert comp.err_g_rms < 0.08

    def test_bayes_rewire_closes_at_fine_epochs(self):
        # The rewire curve is the epoch→0 limit and the bayes run window
        # is short (ξ≈0.4): epoch_steps·dt must sit well under it
        # (meanfield module docstring) — at 0.04 the loop closes.
        spec = InfoModelSpec(
            channel="bayes", dynamics="rewire", epoch_steps=2, rewire_bias=1.0
        )
        comp = close_loop(
            model=MODEL, infomodel=spec, n_agents=6000, avg_degree=20.0,
            dt=0.02, g0=None, t_max=6.0, config=SolverConfig(n_grid=512),
        )
        assert bool(comp.fp.equilibrium.bankrun)
        assert comp.aw_sim.max() > float(MODEL.economic.kappa)  # cascade ran
        assert comp.err_g_rms < 0.06

    def test_seeds_axis_prepares_graph_once(self, bayes_fp, monkeypatch):
        import sbr_tpu.social.closure as closure_mod
        from sbr_tpu.social import graphgen

        calls = []
        real = graphgen.prepare_generated_graph

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(graphgen, "prepare_generated_graph", counting)
        comp = close_loop(
            model=MODEL, infomodel=InfoModelSpec(channel="bayes"),
            n_agents=2000, avg_degree=10.0, dt=0.1, g0=None, t_max=6.0,
            seeds=[11, 22, 33], fp=bayes_fp,
        )
        assert len(calls) == 1  # ONE prepare for three members
        assert comp.n_reps == 3
        assert comp.aw_seeds is not None and comp.aw_seeds.shape[0] == 3
        # members differ (per-member thresholds/seeds vary)
        assert not np.array_equal(comp.aw_seeds[0], comp.aw_seeds[1])

    def test_seeds_axis_legacy_graph_spec(self):
        comp = close_loop(
            n_agents=3000, avg_degree=12.0, dt=0.1, t_max=10.0,
            graph=ErdosRenyiSpec(n=3000, avg_degree=12.0),
            seeds=[1, 2], config=SolverConfig(n_grid=256),
        )
        assert comp.aw_seeds is not None and comp.aw_seeds.shape[0] == 2

    def test_infomodel_rejects_mesh(self):
        from sbr_tpu.parallel import make_agent_mesh

        with pytest.raises(ValueError, match="single-device"):
            close_loop(
                model=MODEL, infomodel=InfoModelSpec(channel="bayes"),
                n_agents=1000, mesh=make_agent_mesh(),
            )

    def test_empty_seeds_rejected(self, bayes_fp):
        with pytest.raises(ValueError, match="non-empty"):
            close_loop(
                model=MODEL, infomodel=InfoModelSpec(channel="bayes"),
                n_agents=1000, seeds=[], fp=bayes_fp,
            )


# ---------------------------------------------------------------------------
# Population queries
# ---------------------------------------------------------------------------


class TestPopulation:
    def test_crossing_times_unit(self):
        t = np.asarray([0.0, 1.0, 2.0, 3.0])
        rows = np.asarray([
            [0.0, 0.1, 0.3, 0.5],   # crosses 0.25 between t=1 and t=2
            [0.0, 0.05, 0.1, 0.2],  # never crosses
            [0.5, 0.6, 0.7, 0.8],   # already above at t=0
        ])
        out = crossing_times(rows, t, 0.25)
        assert out[0] == pytest.approx(1.75)
        assert np.isnan(out[1])
        assert out[2] == 0.0

    def test_population_query_record(self, bayes_fp):
        rec = population_query(
            InfoModelSpec(channel="bayes"), ErdosRenyiSpec(n=1000, avg_degree=10.0),
            MODEL, seeds=3, vary="sim", g0=None,
            config=SolverConfig(n_grid=512), fp=bayes_fp,
        )
        assert rec["kind"] == "population"
        assert rec["seeds"] == 3 and len(rec["crossing_times"]) == 3
        assert 0.0 <= rec["run_probability"] <= 1.0
        q = rec["crossing_quantiles"]
        if rec["run_probability"] == 1.0:
            assert q["p10"] <= q["p50"] <= q["p90"]

    def test_population_query_vary_graph(self, bayes_fp):
        rec = population_query(
            InfoModelSpec(channel="bayes"), ErdosRenyiSpec(n=800, avg_degree=8.0),
            MODEL, seeds=2, vary="graph", g0=None,
            config=SolverConfig(n_grid=512), fp=bayes_fp,
        )
        assert rec["vary"] == "graph" and len(rec["crossing_times"]) == 2
        # per-realization comparisons, max-reduced (the review finding)
        assert rec["err_aw_sup"] > 0

    def test_parse_population_doc_errors(self):
        with pytest.raises(ValueError, match="graph"):
            parse_population_doc({})
        with pytest.raises(ValueError, match="unknown population"):
            parse_population_doc({"graph": {"n": 10, "avg_degree": 2}, "sedes": 3})
        with pytest.raises(ValueError, match="seeds"):
            parse_population_doc(
                {"graph": {"n": 10, "avg_degree": 2}, "seeds": 100000}
            )
        with pytest.raises(ValueError, match="vary"):
            parse_population_doc(
                {"graph": {"n": 10, "avg_degree": 2}, "vary": "chaos"}
            )
        kw = parse_population_doc(
            {"graph": {"model": "scale_free", "n": 50, "avg_degree": 3, "gamma": 2.2},
             "infomodel": {"channel": "bayes"}, "seeds": 2}
        )
        assert isinstance(kw["graph"], ScaleFreeSpec)
        assert kw["spec"].channel == "bayes"

    def test_population_fingerprint_distinctions(self):
        base = {"spec": InfoModelSpec(channel="bayes"),
                "graph": ErdosRenyiSpec(n=100, avg_degree=5.0),
                "seeds": 4, "vary": "sim", "seed": 0, "dt": 0.1}
        cfg = SolverConfig(n_grid=128)
        f = population_fingerprint(base, MODEL, cfg, "float64")
        assert f == population_fingerprint(dict(base), MODEL, cfg, "float64")
        assert f != population_fingerprint({**base, "vary": "graph"}, MODEL, cfg, "float64")
        assert f != population_fingerprint(
            {**base, "graph": ErdosRenyiSpec(n=101, avg_degree=5.0)}, MODEL, cfg,
            "float64",
        )


# ---------------------------------------------------------------------------
# Serving: Engine.query_population + the endpoint route
# ---------------------------------------------------------------------------


class TestServePopulation:
    POP = {
        "graph": {"model": "erdos_renyi", "n": 800, "avg_degree": 8},
        "infomodel": {"channel": "bayes"},
        "seeds": 2, "vary": "sim", "g0": None,
    }
    PARAMS_DOC = {
        "beta": 0.9, "eta_bar": 30.0, "u": 0.5, "p": 0.99,
        "kappa": 0.25, "lam": 0.25,
    }

    def _engine(self, tmp_path, monkeypatch):
        from sbr_tpu.serve.engine import Engine

        monkeypatch.setenv("SBR_SERVE_CACHE_DIR", str(tmp_path / "cache"))
        from sbr_tpu.serve.engine import ServeConfig

        return Engine(config=SolverConfig(n_grid=256), serve=ServeConfig.from_env())

    def test_cache_layers_and_restart(self, tmp_path, monkeypatch):
        eng = self._engine(tmp_path, monkeypatch)
        rec1 = eng.query_population(MODEL, self.POP)
        assert rec1["source"] == "computed"
        rec2 = eng.query_population(MODEL, self.POP)
        assert rec2["source"] == "lru"
        assert rec2["population_fingerprint"] == rec1["population_fingerprint"]
        eng.close()
        eng2 = self._engine(tmp_path, monkeypatch)
        rec3 = eng2.query_population(MODEL, self.POP)
        assert rec3["source"] == "disk"  # restart restores from the disk layer
        assert rec3["run_probability"] == rec1["run_probability"]
        eng2.close()

    def test_endpoint_route(self, tmp_path, monkeypatch):
        from sbr_tpu.serve.endpoint import ServeEndpoint

        eng = self._engine(tmp_path, monkeypatch)
        with ServeEndpoint(eng) as ep:
            url = f"http://127.0.0.1:{ep.port}/query"
            body = json.dumps({**self.PARAMS_DOC, "population": self.POP}).encode()
            r = urllib.request.urlopen(urllib.request.Request(url, data=body))
            doc = json.loads(r.read())
            assert r.status == 200
            assert doc["kind"] == "population" and "run_probability" in doc
            # malformed population -> 400
            bad = json.dumps(
                {**self.PARAMS_DOC, "population": {"graph": {"model": "nope"}}}
            ).encode()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(url, data=bad))
            assert exc.value.code == 400
            # population + scenario is a contradiction -> 400
            both = json.dumps(
                {**self.PARAMS_DOC, "population": self.POP,
                 "scenario": {"learning": "baseline"}}
            ).encode()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(url, data=both))
            assert exc.value.code == 400
            # population + grads -> 400
            wg = json.dumps(
                {**self.PARAMS_DOC, "population": self.POP, "grads": True}
            ).encode()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(url, data=wg))
            assert exc.value.code == 400
        eng.close()


# ---------------------------------------------------------------------------
# Tiled scenario grids (ISSUE 15 satellite 1 — the PR 13 remainder)
# ---------------------------------------------------------------------------


class TestTiledScenarioGrid:
    BETAS = np.linspace(0.5, 2.0, 10)
    US = np.linspace(0.1, 0.9, 8)
    CFG = SolverConfig(n_grid=96, bisect_iters=40, refine_crossings=False)

    def test_tiled_equals_plain_and_warm_cache(self, tmp_path, monkeypatch):
        from sbr_tpu import scenario
        from sbr_tpu.resilience.elastic import TileCache

        base = make_model_params(insurance_cap=0.3)
        spec = scenario.ScenarioSpec(modifiers=("insurance_cap",))
        plain = scenario.scenario_grid(
            spec, self.BETAS, self.US, base, config=self.CFG
        )
        cache = TileCache(str(tmp_path / "tc"))
        tiled = scenario.run_tiled_scenario_grid(
            spec, self.BETAS, self.US, base,
            checkpoint_dir=str(tmp_path / "ck1"), config=self.CFG,
            tile_shape=(5, 4), tile_cache=cache,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.status), np.asarray(tiled.status)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.xi), np.asarray(tiled.xi)
        )
        # warm re-sweep on a FRESH checkpoint: every tile answers from the
        # cross-run cache — scenario_grid must never run again
        import sbr_tpu.scenario.engine as eng_mod

        def boom(*a, **kw):
            raise AssertionError("warm re-sweep recomputed a tile")

        monkeypatch.setattr(eng_mod, "scenario_grid", boom)
        monkeypatch.setattr(scenario, "scenario_grid", boom)
        warm = scenario.run_tiled_scenario_grid(
            spec, self.BETAS, self.US, base,
            checkpoint_dir=str(tmp_path / "ck2"), config=self.CFG,
            tile_shape=(5, 4), tile_cache=cache,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.status), np.asarray(warm.status)
        )

    def test_spec_joins_fingerprint_and_cache_key(self, tmp_path):
        from sbr_tpu import scenario
        from sbr_tpu.resilience.elastic import TileCache
        from sbr_tpu.utils.checkpoint import tile_runner

        base = make_model_params(insurance_cap=0.3)
        cache = TileCache(str(tmp_path / "tc"))
        spec = scenario.ScenarioSpec(modifiers=("insurance_cap",))
        r_plain = tile_runner(
            self.BETAS, self.US, base, None, config=self.CFG,
            tile_shape=(5, 4), tile_cache=cache,
        )
        r_spec = tile_runner(
            self.BETAS, self.US, base, None, config=self.CFG,
            tile_shape=(5, 4), tile_cache=cache, scenario_spec=spec,
        )
        assert r_plain.cache_key(0, 0) != r_spec.cache_key(0, 0)
        # and the checkpoint fingerprints differ too: the same dir must
        # reject the other kind loudly
        ck = str(tmp_path / "ck")
        tile_runner(
            self.BETAS, self.US, base, ck, config=self.CFG, tile_shape=(5, 4),
        )
        with pytest.raises(ValueError, match="[Ff]ingerprint"):
            tile_runner(
                self.BETAS, self.US, base, ck, config=self.CFG,
                tile_shape=(5, 4), scenario_spec=spec,
            )

    def test_baseline_reduction_shares_plain_keying(self, tmp_path):
        from sbr_tpu import scenario
        from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

        base = make_model_params()
        tiled = scenario.run_tiled_scenario_grid(
            scenario.ScenarioSpec(), self.BETAS, self.US, base,
            checkpoint_dir=str(tmp_path / "ck"), config=self.CFG,
            tile_shape=(5, 4),
        )
        legacy = beta_u_grid(self.BETAS, self.US, base, config=self.CFG)
        np.testing.assert_array_equal(
            np.asarray(tiled.status), np.asarray(legacy.status)
        )

    def test_spec_constraints(self, tmp_path):
        from sbr_tpu import scenario

        base = make_model_params()
        with pytest.raises(ValueError, match="single-bank"):
            scenario.run_tiled_scenario_grid(
                scenario.ScenarioSpec(banks=2, exposure=((0, 1, 0.5),)),
                self.BETAS, self.US, [base, base],
            )
        with pytest.raises(ValueError, match="mesh"):
            from sbr_tpu.utils.checkpoint import tile_runner

            from sbr_tpu.parallel import make_agent_mesh

            tile_runner(
                self.BETAS, self.US, base, None, config=self.CFG,
                tile_shape=(5, 4), mesh=make_agent_mesh(),
                scenario_spec=scenario.ScenarioSpec(modifiers=("lolr",)),
            )


# ---------------------------------------------------------------------------
# Obs: log_infomodel roll-up + report infomodel gating
# ---------------------------------------------------------------------------


class TestReportInfomodel:
    def _report(self, run_dir, *args):
        r = subprocess.run(
            [sys.executable, "-m", "sbr_tpu.obs.report", "infomodel",
             str(run_dir), *args],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": str(REPO)}, cwd=str(REPO),
        )
        return r

    def test_manifest_rollup_and_exit_zero(self, tmp_path):
        run = obs.start_run(label="im", run_dir=str(tmp_path / "r"))
        obs.log_infomodel("fixed_point", channel="bayes", dynamics="static",
                          converged=True, iterations=20, xi=0.9, bankrun=True)
        obs.log_infomodel("closure", channel="bayes", dynamics="static",
                          n_agents=100, n_reps=1, err_aw_sup=0.1,
                          err_g_rms=0.02, tolerance=0.25)
        obs.log_infomodel("rewire_epoch", epoch=0, channel="bayes", steps=5,
                          edges=10, withdrawing=0)
        obs.end_run()
        manifest = json.loads((tmp_path / "r" / "manifest.json").read_text())
        blk = manifest["infomodel"]
        assert blk["fixed_point"] == 1 and blk["closure"] == 1
        assert "nonconverged" not in blk and "breaches" not in blk
        r = self._report(tmp_path / "r", "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert doc["counts"]["rewire_epoch"] == 1

    def test_breach_and_nonconverged_exit_one(self, tmp_path):
        run = obs.start_run(label="im", run_dir=str(tmp_path / "r"))
        obs.log_infomodel("fixed_point", channel="bayes", dynamics="static",
                          converged=False, iterations=250, xi=0.0, bankrun=False)
        obs.log_infomodel("closure", channel="gossip", dynamics="rewire",
                          n_agents=100, n_reps=1, err_aw_sup=0.9,
                          err_g_rms=0.5, tolerance=0.25)
        obs.end_run()
        manifest = json.loads((tmp_path / "r" / "manifest.json").read_text())
        assert manifest["infomodel"]["nonconverged"] == 1
        assert manifest["infomodel"]["breaches"] == 1
        r = self._report(tmp_path / "r", "--json")
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["nonconverged"] == 1 and doc["breaches_count"] == 1

    def test_no_data_exit_three(self, tmp_path):
        run = obs.start_run(label="plain", run_dir=str(tmp_path / "r"))
        obs.event("status", stage="x")
        obs.end_run()
        assert self._report(tmp_path / "r").returncode == 3

    def test_legacy_close_loop_emits_no_infomodel_events(self, tmp_path):
        """A run dir produced by the LEGACY gossip closure must keep
        reading exit 3 — emitting closure events there would defeat the
        no-data guard (the review finding)."""
        run = obs.start_run(label="legacy", run_dir=str(tmp_path / "r"))
        close_loop(
            n_agents=1500, avg_degree=10.0, dt=0.1, t_max=8.0,
            config=SolverConfig(n_grid=256),
        )
        obs.end_run()
        assert self._report(tmp_path / "r").returncode == 3

    def test_bad_dir_exit_two(self, tmp_path):
        assert self._report(tmp_path / "missing").returncode == 2


# ---------------------------------------------------------------------------
# History schema 10
# ---------------------------------------------------------------------------


class TestHistorySchema10:
    def test_append_and_gate_pick_up_schema10_keys(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        result = {
            "metric": "beta_u_grid_equilibria_per_sec", "value": 1000.0,
            "extra": {
                "infomodel_belief_updates_per_sec": 3.0e6,
                "infomodel_population_queries_per_sec": 2.5,
            },
        }
        metrics = history.bench_metrics(result)
        assert metrics["infomodel_belief_updates_per_sec"] == 3.0e6
        assert metrics["infomodel_population_queries_per_sec"] == 2.5
        history.append(metrics, path=path)
        recs = history.load(path)
        assert recs[-1]["schema"] == history.SCHEMA
        assert recs[-1]["metrics"]["infomodel_population_queries_per_sec"] == 2.5

    def test_polarity_higher_better(self):
        from sbr_tpu.obs.history import polarity

        assert polarity("infomodel_belief_updates_per_sec") == 1
        assert polarity("infomodel_population_queries_per_sec") == 1

    def test_old_schema_lines_still_load(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        lines = [
            {"label": "bench", "metrics": {"agent_steps_per_sec": 1.0}},  # schema-less
            {"schema": 9, "label": "bench",
             "metrics": {"scenario_overhead_ratio": 1.0}},
        ]
        with open(path, "w") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        history.append({"infomodel_belief_updates_per_sec": 5.0}, path=path)
        recs = history.load(path)
        assert [r["schema"] for r in recs] == [1, 9, history.SCHEMA]
