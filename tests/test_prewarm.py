"""Self-healing prefetch controller tests (ISSUE 19): plan-driven sweeps
through the elastic lease substrate, the per-β tile expansion that makes
prefetched cells tag-match live pool queries, epoch staleness, work
budgets, fail-closed program versioning, `report prewarm` gating, prewarm
state gc, the TileCacheBridge incremental sidecar index, the scenario
(non-baseline) sidecar refusal, and the SBR_PREWARM=0 structural no-op.

The expensive part is the one real sweep in the module-scoped `drained`
fixture (one (1, 2)-tile compile, reused by the re-drain / adoption /
bridge tests via the shared global tile cache — re-sweeps are "cache"
hits, not compiles).
"""

import json
import os
import shutil
import sys
import time
from pathlib import Path

import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.obs.report import prewarm_doc
from sbr_tpu.resilience import faults
from sbr_tpu.scenario.spec import ScenarioSpec
from sbr_tpu.serve import prewarm
from sbr_tpu.serve.fleet import TileCacheBridge
from sbr_tpu.serve.prewarm import (
    PLAN_SCHEMA,
    PrewarmController,
    _plan_tiles,
    gc_prewarm_files,
    load_plan,
)

CFG = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)

BETAS = (0.8, 1.6)
US = (0.1, 0.3)
FP = "feedbeefcafe0119"


def _plan(tiles, fp=FP, **extra) -> dict:
    return {"schema": PLAN_SCHEMA, "plan_fingerprint": fp,
            "tiles": tiles, **extra}


def _hot_tile(betas=BETAS, us=US, rank=1) -> dict:
    return {"bin": "3,1", "betas": list(betas), "us": list(us), "rank": rank}


def _write_plan(path: Path, plan: dict) -> Path:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(plan))
    return path


def _controller(plan_path, cache_dir, **kw) -> PrewarmController:
    kw.setdefault("config", CFG)
    kw.setdefault("ttl_s", 60)
    return PrewarmController(plan_file=plan_path, cache_dir=str(cache_dir), **kw)


@pytest.fixture(scope="module")
def drained(tmp_path_factory):
    """One drained two-tile plan (per-β expansion of a single hot bin)
    and the global tile cache it prefetched into."""
    tmp = tmp_path_factory.mktemp("prewarm")
    cache_dir = tmp / "tile_cache"
    plan_path = _write_plan(tmp / "advisor_plan.json", _plan([_hot_tile()]))
    ctl = _controller(plan_path, cache_dir)
    snap = ctl.drain(timeout_s=600)
    ctl.close()
    return tmp, cache_dir, plan_path, snap


# ---------------------------------------------------------------------------
# Plan loading + per-β expansion
# ---------------------------------------------------------------------------


class TestPlanLoading:
    def test_load_plan_validates(self, tmp_path):
        ok = _write_plan(tmp_path / "ok.json", _plan([_hot_tile()]))
        assert load_plan(ok)["plan_fingerprint"] == FP
        assert load_plan(tmp_path / "missing.json") is None
        torn = tmp_path / "torn.json"
        torn.write_text('{"schema": "sbr-demand-adv')
        assert load_plan(torn) is None
        alien = _write_plan(tmp_path / "alien.json",
                            {"schema": "other/9", "plan_fingerprint": "x",
                             "tiles": []})
        assert load_plan(alien) is None

    def test_plan_load_fault_point_returns_none(self, tmp_path):
        ok = _write_plan(tmp_path / "ok.json", _plan([_hot_tile()]))
        faults.install(faults.FaultPlan(
            {"rules": [{"point": "prewarm.plan_load", "kind": "transient"}]}
        ))
        try:
            assert load_plan(ok) is None
        finally:
            faults.reset()

    def test_per_beta_expansion_and_lease_order(self):
        # One hot bin with two βs MUST become two executable tiles — the
        # cell tag embeds the β-derived η/tspan, so a single-base sweep
        # could only ever match one β's queries.
        tiles = _plan_tiles(_plan([
            {"bin": "3,1", "betas": [1.6, 0.8], "us": [0.3, 0.1], "rank": 2},
            {"bin": "0,0", "betas": [2.4], "us": [0.5], "rank": 1},
            {"bin": "junk"},  # malformed: skipped, never fatal
        ]))
        assert [t["id"] for t in tiles] == [
            "t00000_00000", "t00001_00000", "t00002_00000"
        ]
        assert [t["lease"] for t in tiles] == [(0, 0), (1, 0), (2, 0)]
        # rank order first, then sorted β within a bin.
        assert tiles[0]["betas"] == [2.4]
        assert tiles[1]["betas"] == [0.8] and tiles[2]["betas"] == [1.6]
        assert tiles[1]["us"] == [0.1, 0.3]  # axes sorted per tile


# ---------------------------------------------------------------------------
# Drain → warm bridge (the tentpole end-to-end)
# ---------------------------------------------------------------------------


class TestDrainAndBridge:
    def test_drain_completes_warm(self, drained):
        _, _, _, snap = drained
        assert snap["status"] == "done"
        assert snap["tiles_total"] == 2  # per-β expansion of one hot bin
        assert snap["tiles_done"] == 2
        assert snap["warm"] == 2
        assert snap["counts"]["failed"] == 0

    def test_bridge_serves_pool_style_queries(self, drained):
        # THE coverage contract: a loadgen pool point is
        # make_model_params(beta=β, u=u) — β-derived η/tspan, NOT a pinned
        # base — and every plan cell must tag-match such a query.
        _, cache_dir, _, _ = drained
        bridge = TileCacheBridge(cache_dir)
        for b in BETAS:
            for u in US:
                rec = bridge.lookup(make_model_params(beta=b, u=u), CFG,
                                    "float64")
                assert rec is not None, f"cold cell ({b}, {u})"
        # Off-plan β: no tile covers it, the bridge must refuse.
        assert bridge.lookup(make_model_params(beta=3.3, u=US[0]), CFG,
                             "float64") is None

    def test_done_markers_and_no_leases_left(self, drained):
        _, cache_dir, _, _ = drained
        plan_dir = cache_dir / "_prewarm" / f"plan_{FP}"
        done = sorted(p.name for p in plan_dir.glob("done_*.json"))
        assert done == ["done_t00000_00000.json", "done_t00001_00000.json"]
        doc = json.loads((plan_dir / done[0]).read_text())
        assert doc["plan"] == FP and "program_version" in doc
        assert not list(plan_dir.glob("*.lease"))

    def test_second_sweeper_skips_done_tiles(self, drained):
        # Same rendezvous dir: done markers make a re-drain a no-op sweep.
        tmp, cache_dir, plan_path, _ = drained
        ctl = _controller(plan_path, cache_dir)
        snap = ctl.drain(timeout_s=60)
        ctl.close()
        assert snap["status"] == "done"
        assert snap["tiles_done"] == 0  # nothing re-run
        assert snap["warm"] == 2  # warm verdict re-verified from the cache

    def test_expired_lease_is_adopted(self, drained, tmp_path):
        # Fresh rendezvous dir + a stale lease from a "dead" sweeper on
        # tile 0: the drain must adopt it (takeover, counted) and finish;
        # both tiles come back as free cache hits — no recompute.
        _, cache_dir, plan_path, _ = drained
        state_root = tmp_path / "state"
        plan_dir = state_root / f"plan_{FP}"
        plan_dir.mkdir(parents=True)
        (plan_dir / "tile_b00000_u00000.lease").write_text(json.dumps({
            "pid": 0, "host": "dead-host", "nonce": "stale",
            "ts": time.time() - 9999.0, "ttl_s": 5.0,
        }))
        ctl = _controller(plan_path, cache_dir, state_root=state_root)
        snap = ctl.drain(timeout_s=120)
        ctl.close()
        assert snap["status"] == "done"
        assert snap["counts"]["adopted"] == 1
        assert snap["counts"]["cache"] == 2  # global tile cache, not solver
        assert snap["counts"]["computed"] == 0


# ---------------------------------------------------------------------------
# Epochs, budgets, fail-closed versioning
# ---------------------------------------------------------------------------


class TestEpochsAndBudgets:
    def test_new_fingerprint_abandons_stale_epoch(self, tmp_path):
        plan_path = _write_plan(tmp_path / "plan.json", _plan([_hot_tile()]))
        ctl = _controller(plan_path, tmp_path / "cache")
        assert ctl.poll_plan() and ctl.status == "sweeping"
        assert len(ctl._tiles) == 2
        _write_plan(plan_path, _plan([_hot_tile(betas=(2.4,), us=(0.5,))],
                                     fp="aa" * 8))
        os.utime(plan_path, (time.time() + 5, time.time() + 5))
        ctl.poll_plan()
        ctl.close()
        assert ctl.counts["abandoned_stale"] == 2
        assert ctl.counts["plans"] == 2
        assert ctl._plan_fp == "aa" * 8 and len(ctl._tiles) == 1

    def test_torn_rewrite_keeps_current_epoch(self, tmp_path):
        plan_path = _write_plan(tmp_path / "plan.json", _plan([_hot_tile()]))
        ctl = _controller(plan_path, tmp_path / "cache")
        assert ctl.poll_plan()
        plan_path.write_text('{"schema": "sbr-d')  # torn mid-rewrite
        os.utime(plan_path, (time.time() + 5, time.time() + 5))
        assert ctl.poll_plan()  # still active on the old epoch
        ctl.close()
        assert ctl.counts["plan_errors"] == 1
        assert ctl._plan_fp == FP and ctl.status == "sweeping"

    def test_budget_exhaustion_gates_report_exit1(self, tmp_path):
        from sbr_tpu import obs

        run_dir = tmp_path / "run"
        run = obs.start_run(label="prewarm_budget", run_dir=str(run_dir))
        try:
            plan_path = _write_plan(tmp_path / "plan.json",
                                    _plan([_hot_tile()]))
            ctl = _controller(plan_path, tmp_path / "cache",
                              max_seconds=0.001)
            assert ctl.poll_plan()
            time.sleep(0.01)
            assert ctl.step() is None  # budget closed the plan
            ctl.close()
            assert ctl.status == "budget_exhausted"
            assert ctl.counts["abandoned_budget"] == 2
            assert ctl.status_gauge() == -1
        finally:
            obs.end_run()
        doc, code = prewarm_doc(run.run_dir)
        assert code == 1
        assert any("budget" in b for b in doc["breaches"])

    def test_program_version_mismatch_fails_closed(self, tmp_path):
        plan_path = _write_plan(
            tmp_path / "plan.json",
            _plan([_hot_tile()], program_version=999999),
        )
        ctl = _controller(plan_path, tmp_path / "cache")
        ctl.poll_plan()
        ctl.close()
        assert ctl.status == "rejected"
        assert ctl.counts["plans_rejected"] == 1
        assert ctl._tiles == [] and ctl.step() is None
        assert ctl.status_gauge() == -1

    def test_stale_program_version_done_marker_reruns(self, drained, tmp_path):
        # A done marker from another solver generation describes cache
        # entries this generation can't serve: the tile must NOT count as
        # done.
        _, cache_dir, plan_path, _ = drained
        state_root = tmp_path / "state"
        plan_dir = state_root / f"plan_{FP}"
        plan_dir.mkdir(parents=True)
        (plan_dir / "done_t00000_00000.json").write_text(json.dumps(
            {"tile": "t00000_00000", "program_version": -1}
        ))
        ctl = _controller(plan_path, cache_dir, state_root=state_root)
        assert ctl.poll_plan()
        assert not ctl._tile_done(ctl._tiles[0])


# ---------------------------------------------------------------------------
# report prewarm exit contract
# ---------------------------------------------------------------------------


class TestReportPrewarm:
    def _run(self, tmp_path, emits):
        from sbr_tpu import obs

        run = obs.start_run(label="prewarm_report",
                            run_dir=str(tmp_path / "run"))
        try:
            for action, kw in emits:
                obs.log_prewarm(action, **kw)
        finally:
            obs.end_run()
        return run.run_dir

    def test_healthy_run_exit0(self, tmp_path):
        run_dir = self._run(tmp_path, [
            ("plan", {"fingerprint": "f1", "tiles": 2}),
            ("tile", {"tile": "t00000_00000", "source": "computed",
                      "fingerprint": "f1"}),
            ("adopt", {"tile": "t00001_00000", "fingerprint": "f1"}),
            ("tile", {"tile": "t00001_00000", "source": "cache",
                      "fingerprint": "f1"}),
            ("plan_done", {"fingerprint": "f1", "tiles": 2, "warm": 2}),
        ])
        doc, code = prewarm_doc(run_dir)
        assert code == 0 and not doc["breaches"]
        p = doc["plans"]["f1"]
        assert p["done"] and p["tiles_done"] == 2 and p["adopted"] == 1
        assert doc["sources"] == {"cache": 1, "computed": 1}

    def test_cold_completion_exit1(self, tmp_path):
        run_dir = self._run(tmp_path, [
            ("plan", {"fingerprint": "f1", "tiles": 2}),
            ("plan_done", {"fingerprint": "f1", "tiles": 2, "warm": 1}),
        ])
        doc, code = prewarm_doc(run_dir)
        assert code == 1
        assert any("cold" in b for b in doc["breaches"])

    def test_no_data_exit3_and_not_a_dir_exit2(self, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert prewarm_doc(empty)[1] == 3
        assert prewarm_doc(tmp_path / "missing")[1] == 2


# ---------------------------------------------------------------------------
# Retention: report gc --prewarm-keep (satellite)
# ---------------------------------------------------------------------------


class TestGcRetention:
    def test_keeps_recent_live_and_active_epochs(self, tmp_path):
        root = tmp_path / "_prewarm"
        now = time.time()
        for i in range(5):
            (root / f"plan_{i:02d}").mkdir(parents=True)
        # Oldest epoch has a LIVE lease: a sweeper still drains there.
        live = root / "plan_00" / "tile_b00000_u00000.lease"
        live.write_text(json.dumps({"ts": now, "ttl_s": 600.0, "nonce": "n"}))
        # The newest epoch carries lease debris for a tile already done.
        debris = root / "plan_04" / "tile_b00001_u00000.lease"
        debris.write_text(json.dumps({"ts": now - 9999, "ttl_s": 1.0}))
        (root / "plan_04" / "done_t00001_00000.json").write_text("{}")
        for i in range(5):  # stagger AFTER the writes that bump dir mtimes
            t = now - 1000 + i
            os.utime(root / f"plan_{i:02d}", (t, t))

        removed = gc_prewarm_files(state_root=root, keep=2, ttl_s=60)
        kept = sorted(p.name for p in root.iterdir())
        # plan_00 survives (live lease), 01/02 pruned, 03/04 kept (keep=2).
        assert kept == ["plan_00", "plan_03", "plan_04"]
        assert live.exists()
        assert not debris.exists() and str(debris) in removed

    def test_no_state_root_is_noop(self, tmp_path, monkeypatch):
        monkeypatch.delenv("SBR_PREWARM_STATE_DIR", raising=False)
        monkeypatch.delenv("SBR_TILE_CACHE_DIR", raising=False)
        assert gc_prewarm_files(state_root=tmp_path / "nope") == []
        assert gc_prewarm_files() == []


# ---------------------------------------------------------------------------
# TileCacheBridge incremental sidecar index (satellite)
# ---------------------------------------------------------------------------


class TestBridgeIncrementalIndex:
    def _lookup_until(self, bridge, params, want_hit, timeout_s=10.0):
        """Poll across the bridge's mtime slack window for the index to
        converge on the expected verdict."""
        deadline = time.monotonic() + timeout_s
        while True:
            rec = bridge.lookup(params, CFG, "float64")
            if (rec is not None) == want_hit or time.monotonic() >= deadline:
                return rec

    def test_index_tracks_stores_torn_sidecars_and_deletions(self, drained,
                                                             tmp_path):
        _, cache_dir, _, _ = drained
        cache = tmp_path / "cache_copy"
        shutil.copytree(cache_dir, cache)
        bridge = TileCacheBridge(cache)
        hot = make_model_params(beta=BETAS[0], u=US[0])
        assert bridge.lookup(hot, CFG, "float64") is not None

        # A torn sidecar appearing later must be skipped, not fatal.
        shard = next(p for p in cache.rglob("*.meta.json")).parent
        (shard / "torn.meta.json").write_text('{"key": "x", "cell_t')
        assert self._lookup_until(bridge, hot, want_hit=True) is not None

        # A NEW store after the first lookup (another sweeper prefetching
        # into the shared cache) must become visible without a new bridge.
        new_q = make_model_params(beta=2.4, u=US[0])
        assert bridge.lookup(new_q, CFG, "float64") is None
        plan_path = _write_plan(
            tmp_path / "plan_b.json",
            _plan([_hot_tile(betas=(2.4,))], fp="bb" * 8),
        )
        ctl = _controller(plan_path, cache)
        snap = ctl.drain(timeout_s=600)
        ctl.close()
        assert snap["status"] == "done" and snap["warm"] == 1
        assert self._lookup_until(bridge, new_q, want_hit=True) is not None

        # Deleting a cell's tile + sidecar must evict it from the index.
        meta = next(
            m for m in cache.rglob("*.meta.json")
            if m.name != "torn.meta.json"
            and json.loads(m.read_text())["betas"] == [2.4]
        )
        npz = meta.with_name(meta.name[: -len(".meta.json")] + ".npz")
        meta.unlink()
        if npz.exists():
            npz.unlink()
        assert self._lookup_until(bridge, new_q, want_hit=False) is None
        # ...while untouched cells keep serving.
        assert bridge.lookup(hot, CFG, "float64") is not None


# ---------------------------------------------------------------------------
# Scenario tiles: no sidecars, bridge refuses composed cells (satellite)
# ---------------------------------------------------------------------------


class TestScenarioSidecarRefusal:
    def test_scenario_sweep_writes_no_meta_and_bridge_refuses(self, tmp_path):
        # A composed-scenario surface is NOT the baseline answer for its
        # (β, u): prewarming it must never leave a sidecar the bridge
        # could mistake for a servable baseline cell.
        cache = tmp_path / "cache"
        plan_path = _write_plan(
            tmp_path / "plan.json",
            _plan([_hot_tile(betas=(1.0,), us=(0.1,))]),
        )
        spec = ScenarioSpec(modifiers=("insurance_cap",))
        ctl = _controller(plan_path, cache, scenario_spec=spec)
        snap = ctl.drain(timeout_s=600)
        ctl.close()
        assert snap["tiles_done"] == 1 and snap["counts"]["failed"] == 0
        assert cache.is_dir()  # the scenario tile DID land in the cache...
        assert not list(cache.rglob("*.meta.json"))  # ...without a sidecar
        bridge = TileCacheBridge(cache)
        assert bridge.lookup(make_model_params(beta=1.0, u=0.1), CFG,
                             "float64") is None


# ---------------------------------------------------------------------------
# Engine wiring: SBR_PREWARM=0 structural no-op (the control surface)
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def _engine(self):
        from sbr_tpu.serve.engine import Engine

        return Engine(config=SolverConfig(n_grid=64, bisect_iters=20,
                                          refine_crossings=False))

    def test_off_is_structural_noop(self, monkeypatch):
        from sbr_tpu.obs import prof

        monkeypatch.delenv("SBR_PREWARM", raising=False)
        sys.modules.pop("sbr_tpu.serve.prewarm", None)
        traces_before = sum(prof.trace_counts().values())
        eng = self._engine()
        try:
            assert eng.prewarm is None
            # The module must not even be imported...
            assert "sbr_tpu.serve.prewarm" not in sys.modules
            # ...the exposition must be byte-free of prewarm metrics...
            assert "sbr_prewarm" not in eng.prometheus()
            assert "prewarm" not in eng.statz()
        finally:
            eng.close()
        # ...and zero new XLA programs traced by wiring the engine.
        assert sum(prof.trace_counts().values()) == traces_before

    def test_on_attaches_controller(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SBR_PREWARM", "1")
        monkeypatch.setenv("SBR_TILE_CACHE_DIR", str(tmp_path / "cache"))
        monkeypatch.setenv("SBR_PREWARM_PLAN", str(tmp_path / "nope.json"))
        eng = self._engine()
        try:
            assert eng.prewarm is not None
            assert "sbr_prewarm_status" in eng.prometheus()
            hb = eng.prewarm.heartbeat_block()
            assert set(hb) == {"status", "plan", "tiles_done", "tiles_total",
                               "abandoned"}
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# History schema 13
# ---------------------------------------------------------------------------


class TestHistorySchema13:
    def test_prewarm_metrics_whitelisted(self):
        from sbr_tpu.obs import history

        assert history.SCHEMA >= 13  # ISSUE 20 bumped to 14 (flight workload)
        out = history.bench_metrics({
            "value": 10.0,
            "extra": {"prewarm_warm_hit_rate": 1.0,
                      "prewarm_outage_p99_ms": 64.1,
                      "prewarm_tiles_per_sec": 5.4},
        })
        assert out["prewarm_warm_hit_rate"] == 1.0
        assert out["prewarm_outage_p99_ms"] == 64.1
        assert out["prewarm_tiles_per_sec"] == 5.4

    def test_polarity(self):
        from sbr_tpu.obs import history

        assert history.polarity("prewarm_warm_hit_rate") == 1
        assert history.polarity("prewarm_tiles_per_sec") == 1
        assert history.polarity("prewarm_outage_p99_ms") == -1

    def test_schema_1_to_12_lines_still_load_and_gate(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        rows = [{"ts": 1.0, "metrics": {"eq_per_sec": 10.0}}]  # schema-less
        rows += [{"schema": s, "metrics": {"eq_per_sec": 10.0 + s / 10}}
                 for s in range(2, 13)]
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        history.append({"eq_per_sec": 10.7}, path=path)
        records = history.load(path)
        assert ([r["schema"] for r in records]
                == list(range(1, 13)) + [history.SCHEMA])
        verdicts, status = history.check(records, tolerance=0.15)
        assert status == "ok"
