"""Dispatch-pipeline flight recorder tests (ISSUE 20): lock-free ring
contract (concurrent writers bounded loss, no torn records under active
snapshots, per-stream seq uniqueness/monotonicity, dropped-records
accounting), the pure `derive_utilization` fold on hand-built synthetic
records (busy fraction, gap attribution by cause, queue percentiles,
occupancy, sweep bubbles, collectives), engine wiring (SBR_FLIGHT=0
structural no-op witness with bit-identical answers; on-path artifacts:
flight.json, manifest roll-up, /metrics, /statz, worker stats), the
synthetically starved pipeline acceptance gate (injected batch-formation
sleep -> attribution shifts and the floor gate trips), `report util`
exits, the `report summary` meta-gate, `report gc --flight-keep`
retention + rotation, and history schema 14.
"""

import json
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.obs import flight as fl

REPO = Path(__file__).resolve().parent.parent

CFG = SolverConfig(n_grid=64, bisect_iters=20, refine_crossings=False)


def _feq(a, b) -> bool:
    """Bitwise float equality (NaN-safe): the byte-identity contract."""
    return np.float64(a).tobytes() == np.float64(b).tobytes()


def _rec(t_s, stream, kind, seq, phase, tag="", val=None):
    """One serialized ring record, as flight.json carries them."""
    return [int(t_s * 1e9), stream, kind, tag, seq, phase, val]


def _span(t0, t1, stream, kind, seq, tag=""):
    """A closed begin/end pair sharing a seq."""
    return [_rec(t0, stream, kind, seq, "b", tag),
            _rec(t1, stream, kind, seq, "e", tag)]


def _snap(records, dropped=0):
    return {"schema": fl.LIVE_SCHEMA, "cap": 4096,
            "writes_total": len(records) + dropped,
            "dropped_records": dropped, "records": records}


# ---------------------------------------------------------------------------
# Ring contract
# ---------------------------------------------------------------------------


class TestRingContract:
    def test_overflow_overwrites_oldest_and_counts_drops(self):
        rec = fl.FlightRecorder(cap=64)
        for k in range(100):
            rec.point("engine", "queue_depth", val=k)
        snap = rec.snapshot()
        assert len(snap["records"]) == 64
        assert snap["writes_total"] == 100
        assert snap["dropped_records"] == 36
        # The retained window is the NEWEST 64 (overwrite-oldest).
        assert sorted(r[6] for r in snap["records"]) == list(range(36, 100))

    def test_concurrent_writers_lose_at_most_overflow(self):
        # 8 threads x 250 points = 2000 writes into a 512-slot ring: after
        # the writers quiesce, exactly cap records are retained and the
        # dropped counter accounts for the rest — no record vanishes
        # unaccounted, none tears.
        rec = fl.FlightRecorder(cap=512)

        def writer(tid):
            for k in range(250):
                rec.point("engine", "queue_depth", tag=f"w{tid}", val=k)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = rec.snapshot()
        assert len(snap["records"]) == 512
        assert snap["writes_total"] == 2000
        assert snap["dropped_records"] == 2000 - 512
        for r in snap["records"]:
            assert len(r) == 7  # whole tuples only — no partial writes

    def test_seq_unique_per_stream_under_concurrency(self):
        # Pair identity rests on per-stream seqs: 8 threads marking the
        # same stream must never mint a duplicate (itertools.count.next is
        # GIL-atomic), and every begin must carry its matching end.
        rec = fl.FlightRecorder(cap=8192)

        def writer(tid):
            for k in range(100):
                rec.mark("engine", "dispatch", k * 1e-3, k * 1e-3 + 5e-4,
                         tag=f"w{tid}")

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = rec.snapshot()
        begins = [r[4] for r in snap["records"] if r[5] == "b"]
        assert len(begins) == 800
        assert len(set(begins)) == 800
        util = fl.derive_utilization(snap)
        assert util["unpaired"] == 0
        assert util["dispatches"] == 800

    def test_seq_monotone_in_record_order_single_writer(self):
        clock = [0.0]
        rec = fl.FlightRecorder(cap=256, time_fn=lambda: clock[0])
        for k in range(20):
            clock[0] += 0.01
            rec.mark("engine", "dispatch", clock[0], clock[0] + 0.001)
            rec.point("sweeps", "tick")
        snap = rec.snapshot()
        for stream in ("engine", "sweeps"):
            seqs = [r[4] for r in snap["records"]
                    if r[1] == stream and r[5] in ("b", "p")]
            assert seqs == sorted(seqs)

    def test_snapshot_under_active_writes_never_tears(self):
        # Snapshots race live writers: every retained record must still be
        # a complete 7-tuple and derive_utilization must fold it without
        # raising — torn PAIRS are allowed (counted as unpaired), torn
        # RECORDS are not.
        rec = fl.FlightRecorder(cap=128)
        stop = threading.Event()

        def writer():
            k = 0
            while not stop.is_set():
                rec.mark("engine", "dispatch", k * 1e-4, k * 1e-4 + 5e-5)
                k += 1

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(50):
                snap = rec.snapshot()
                for r in snap["records"]:
                    assert len(r) == 7
                    assert r[5] in ("b", "e", "p")
                util = fl.derive_utilization(snap)
                assert util["records"] == len(snap["records"])
        finally:
            stop.set()
            for t in threads:
                t.join()

    def test_reset_drops_everything(self):
        rec = fl.FlightRecorder(cap=64)
        rec.mark("engine", "dispatch", 0.0, 1.0)
        rec.reset()
        snap = rec.snapshot()
        assert snap["records"] == [] and snap["dropped_records"] == 0

    def test_record_paths_never_raise(self):
        rec = fl.FlightRecorder(cap=64)
        rec.mark("nonexistent-stream", "x", 0.0, 1.0)  # bad stream: dropped
        rec.point("also-bad", "y")
        assert rec.snapshot()["records"] == []


# ---------------------------------------------------------------------------
# derive_utilization (pure fold on synthetic records)
# ---------------------------------------------------------------------------


class TestDeriveUtilization:
    def test_busy_fraction_is_dispatch_union_over_window(self):
        # Window 0..2 s (admission opens it, unpack closes it); one 1 s
        # dispatch => busy exactly 0.5.
        records = (_span(0.0, 0.05, "engine", "admission", 1)
                   + _span(0.5, 1.5, "engine", "dispatch", 2, tag="b1")
                   + _span(1.9, 2.0, "engine", "unpack", 3, tag="b1"))
        util = fl.derive_utilization(_snap(records))
        assert util["dispatches"] == 1
        assert util["window_s"] == 2.0
        assert util["device_busy_frac"] == 0.5
        assert util["host_gap_frac"] == 0.5

    def test_overlapping_dispatches_union_not_sum(self):
        records = (_span(0.0, 1.0, "engine", "dispatch", 1)
                   + _span(0.5, 1.5, "engine", "dispatch", 2)
                   + _span(1.5, 2.0, "engine", "unpack", 3))
        util = fl.derive_utilization(_snap(records))
        # Two overlapping 1 s dispatches cover 1.5 s of a 2 s window.
        assert util["device_busy_frac"] == 0.75

    def test_gap_attribution_priority_batch_then_cache_then_rest(self):
        # Gap 0..1 s before a 1 s dispatch: 0.4 s batch formation, 0.3 s
        # cache I/O, 0.3 s unexplained (no shed point -> queue starvation).
        records = (_span(0.0, 0.4, "engine", "batch", 1, tag="b1")
                   + _span(0.4, 0.7, "engine", "cache", 2)
                   + _span(1.0, 2.0, "engine", "dispatch", 3, tag="b1"))
        util = fl.derive_utilization(_snap(records))
        causes = util["gap_causes"]
        assert causes["batch_formation"]["s"] == pytest.approx(0.4)
        assert causes["cache_io"]["s"] == pytest.approx(0.3)
        assert causes["queue_starvation"]["s"] == pytest.approx(0.3)
        assert causes["batch_formation"]["frac"] == pytest.approx(0.4)
        assert "admission_shed" not in causes

    def test_shed_point_in_gap_attributes_admission_shed(self):
        records = (_span(1.0, 2.0, "engine", "dispatch", 1)
                   + [_rec(0.5, "engine", "shed", 2, "p", "expired")])
        util = fl.derive_utilization(_snap(records))
        causes = util["gap_causes"]
        assert set(causes) == {"admission_shed"}
        assert causes["admission_shed"]["frac"] == 1.0
        assert util["sheds"] == {"expired": 1}

    def test_unpaired_ends_and_begins_counted_not_crashed(self):
        records = ([_rec(1.0, "engine", "dispatch", 9, "e")]     # lost begin
                   + [_rec(2.0, "engine", "batch", 10, "b")]     # lost end
                   + _span(3.0, 4.0, "engine", "dispatch", 11))
        util = fl.derive_utilization(_snap(records))
        assert util["unpaired"] == 2
        assert util["dispatches"] == 1

    def test_queue_depth_percentiles_and_occupancy(self):
        records = [_rec(0.1 * k, "engine", "queue_depth", k + 1, "p",
                        val=float(k + 1)) for k in range(10)]
        records += [_rec(1.1, "engine", "occupancy", 20, "p", "b8", 0.5),
                    _rec(1.2, "engine", "occupancy", 21, "p", "b8", 1.0)]
        util = fl.derive_utilization(_snap(records))
        qd = util["queue_depth"]
        assert qd["samples"] == 10 and qd["max"] == 10.0
        assert qd["p50"] == 6.0
        occ = util["occupancy"]
        assert occ["mean"] == 0.75
        assert occ["by_bucket"] == {"b8": 0.75}

    def test_sweeps_bubbles_between_tiles(self):
        records = (_span(0.0, 1.0, "sweeps", "compute", 1, tag="t0")
                   + _span(1.5, 2.0, "sweeps", "compute", 2, tag="t1")
                   + _span(2.0, 2.1, "sweeps", "ckpt_save", 3, tag="t1"))
        util = fl.derive_utilization(_snap(records))
        sw = util["sweeps"]
        assert sw["tiles"] == 2
        assert sw["by_kind_ms"]["compute"] == pytest.approx(1500.0)
        assert sw["by_kind_ms"]["ckpt_save"] == pytest.approx(100.0)
        assert sw["bubbles_ms"] == [pytest.approx(500.0)]
        assert sw["bubble_total_ms"] == pytest.approx(500.0)

    def test_collectives_fold_spans_and_points(self):
        records = (_span(0.0, 0.1, "collectives", "barrier_poll", 1)
                   + _span(0.2, 0.3, "collectives", "barrier_poll", 2)
                   + [_rec(0.4, "collectives", "psum", 3, "p", "inc")])
        util = fl.derive_utilization(_snap(records))
        col = util["collectives"]
        assert col["barrier_poll"]["count"] == 2
        assert col["barrier_poll"]["total_ms"] == pytest.approx(200.0)
        assert col["psum"]["count"] == 1

    def test_malformed_rows_skipped(self):
        records = [["junk"], None, 42] + _span(0.0, 1.0, "engine",
                                               "dispatch", 1)
        records += _span(1.0, 2.0, "engine", "unpack", 2)
        util = fl.derive_utilization(_snap(records))
        assert util["records"] == 4  # only the well-formed rows counted
        assert util["dispatches"] == 1

    def test_empty_snapshot_yields_none_fractions(self):
        util = fl.derive_utilization(_snap([]))
        assert util["device_busy_frac"] is None
        assert util["host_gap_frac"] is None
        assert util["dispatches"] == 0


# ---------------------------------------------------------------------------
# Recorder surfaces (heartbeat block, /metrics lines)
# ---------------------------------------------------------------------------


class TestRecorderSurfaces:
    def test_heartbeat_block_is_compact(self):
        rec = fl.FlightRecorder(cap=128)
        rec.mark("engine", "admission", 0.0, 0.05)
        rec.mark("engine", "dispatch", 0.5, 1.5, tag="b1")
        rec.mark("engine", "unpack", 1.9, 2.0, tag="b1")
        rec.point("engine", "queue_depth", val=3.0)
        hb = rec.heartbeat_block()
        assert hb["dispatches"] == 1
        assert hb["device_busy_frac"] is not None
        assert hb["dropped_records"] == 0
        assert hb["queue_p99"] == 3.0

    def test_prometheus_lines_expose_flight_gauges(self):
        rec = fl.FlightRecorder(cap=128)
        rec.mark("engine", "dispatch", 0.5, 1.5, tag="b1")
        rec.mark("engine", "unpack", 1.5, 2.0, tag="b1")
        text = "\n".join(rec.prometheus_lines())
        assert "sbr_flight_dispatches 1" in text
        assert "sbr_flight_device_busy_frac" in text
        assert "sbr_flight_dropped_records 0" in text
        assert "sbr_flight_engine_ms" in text


# ---------------------------------------------------------------------------
# Engine wiring: SBR_FLIGHT=0 structural no-op + on-path recording
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def _engine(self, **kw):
        from sbr_tpu.serve.engine import Engine

        return Engine(config=CFG, **kw)

    def test_off_is_structural_noop_with_bit_identical_answers(self, monkeypatch):
        from sbr_tpu.obs import prof

        pool = [make_model_params(beta=1.2, u=0.25),
                make_model_params(beta=2.1, u=0.6)]
        monkeypatch.setenv("SBR_FLIGHT", "1")
        eng = self._engine()
        try:
            eng.start()
            on_xi = [r.xi for r in eng.query_many(pool, scenario="mix")]
            assert eng.flight is not None
        finally:
            eng.close()

        monkeypatch.delenv("SBR_FLIGHT", raising=False)
        sys.modules.pop("sbr_tpu.obs.flight", None)
        traces_before = sum(prof.trace_counts().values())
        eng = self._engine()
        try:
            eng.start()
            off_xi = [r.xi for r in eng.query_many(pool, scenario="mix")]
            assert eng.flight is None
            # The flight module must not even be imported...
            assert "sbr_tpu.obs.flight" not in sys.modules
            # ...the exposition must be byte-free of flight metrics...
            assert "sbr_flight" not in eng.prometheus()
            assert "flight" not in eng.statz()
        finally:
            eng.close()
        # ...zero new XLA programs traced by running flight-off...
        assert sum(prof.trace_counts().values()) == traces_before
        # ...and answers bit-identical to the flight-on run.
        assert all(_feq(a, b) for a, b in zip(on_xi, off_xi))
        # (re-import for the rest of the module: `fl` stays bound)
        import sbr_tpu.obs.flight  # noqa: F401

    def test_on_records_and_lands_artifacts(self, tmp_path, monkeypatch):
        from sbr_tpu.obs import flight as flight_mod

        flight_mod.reset_shared()
        monkeypatch.setenv("SBR_FLIGHT", "1")
        run_dir = tmp_path / "run"
        eng = self._engine(run_dir=str(run_dir))
        try:
            eng.start()
            pool = [make_model_params(beta=1.2, u=0.25),
                    make_model_params(beta=2.1, u=0.6)]
            eng.query_many(pool, scenario="mix")
            eng.query_many(pool, scenario="mix")  # -> lru warm hits
            snap = eng.flight.snapshot()
            assert snap["records"]
            assert "sbr_flight_records" in eng.prometheus()
            statz = eng.statz()
            assert statz["flight"]["records"] > 0
            assert statz["flight"]["dispatches"] >= 1
        finally:
            eng.close()
        doc = json.loads((run_dir / "flight.json").read_text())
        assert doc["schema"] == fl.LIVE_SCHEMA
        assert doc["records"]
        assert doc["util"]["schema"] == fl.UTIL_SCHEMA
        assert doc["util"]["dispatches"] >= 1
        manifest = json.loads((run_dir / "manifest.json").read_text())
        assert manifest["flight"]["final"] == 1
        assert manifest["flight"]["last_records"] > 0
        assert manifest["flight"]["last_dispatches"] >= 1

    def test_worker_stats_carry_flight_block_only_when_on(self, monkeypatch):
        from sbr_tpu.obs import flight as flight_mod
        from sbr_tpu.serve.fleet import _worker_stats

        flight_mod.reset_shared()
        monkeypatch.setenv("SBR_FLIGHT", "1")
        eng = self._engine()
        try:
            eng.start()
            eng.query_many([make_model_params(beta=1.2, u=0.25)])
            stats = _worker_stats(eng)
            assert stats["flight"]["dispatches"] >= 1
            assert "device_busy_frac" in stats["flight"]
        finally:
            eng.close()
        monkeypatch.delenv("SBR_FLIGHT", raising=False)
        eng = self._engine()
        try:
            eng.start()
            assert "flight" not in _worker_stats(eng)
        finally:
            eng.close()

    def test_router_rolls_up_fleet_flight(self, tmp_path):
        from sbr_tpu.serve.fleet import WorkerAnnouncer
        from sbr_tpu.serve.router import Router

        blk = {"device_busy_frac": 0.4, "host_gap_frac": 0.6,
               "dispatches": 10, "queue_p99": 2.0,
               "dropped_records": 1, "records": 50}
        blk2 = {"device_busy_frac": 0.8, "host_gap_frac": 0.2,
                "dispatches": 30, "queue_p99": 4.0,
                "dropped_records": 0, "records": 70}
        w0 = WorkerAnnouncer(tmp_path, "http://127.0.0.1:1", host="w0")
        w1 = WorkerAnnouncer(tmp_path, "http://127.0.0.1:2", host="w1")
        w0.beat(flight=blk)
        w1.beat(flight=blk2)
        router = Router(tmp_path, poll_s=0.01)
        router.refresh_workers(force=True)
        merged = router.fleet_flight()
        assert merged is not None
        assert merged["workers"] == ["w0", "w1"]
        assert merged["dispatches"] == 40
        assert merged["dropped_records"] == 1
        # Dispatch-weighted mean: (0.4*10 + 0.8*30) / 40 = 0.7.
        assert merged["device_busy_frac"] == pytest.approx(0.7)
        assert router.statz()["flight"]["dispatches"] == 40
        text = router.prometheus()
        assert "sbr_flight_fleet_workers 2" in text
        assert "sbr_flight_fleet_dispatches 40" in text

    def test_router_without_flight_blocks_stays_byte_free(self, tmp_path):
        from sbr_tpu.serve.fleet import WorkerAnnouncer
        from sbr_tpu.serve.router import Router

        WorkerAnnouncer(tmp_path, "http://127.0.0.1:1", host="w0").beat(qps=1.0)
        router = Router(tmp_path, poll_s=0.01)
        router.refresh_workers(force=True)
        assert router.fleet_flight() is None
        assert "flight" not in router.statz()
        assert "sbr_flight" not in router.prometheus()


# ---------------------------------------------------------------------------
# Sweep instrumentation (TileRunner.produce)
# ---------------------------------------------------------------------------


class TestSweepWiring:
    def test_produce_lands_sweep_spans_when_on(self, tmp_path, monkeypatch):
        from sbr_tpu.obs import flight as flight_mod
        from sbr_tpu.utils.checkpoint import tile_runner

        flight_mod.reset_shared()
        monkeypatch.setenv("SBR_FLIGHT", "1")
        base = make_model_params()
        runner = tile_runner([1.0, 1.5], [0.1, 0.2], base,
                             str(tmp_path / "ckpt"), config=CFG,
                             tile_shape=(2, 2))
        source, _ = runner.produce(0, 0)
        assert source == "computed"
        snap = flight_mod.shared().snapshot()
        kinds = {r[2] for r in snap["records"] if r[1] == "sweeps"}
        assert "compute" in kinds
        assert "ckpt_save" in kinds
        util = fl.derive_utilization(snap)
        assert util["sweeps"]["tiles"] >= 1

    def test_produce_records_nothing_when_off(self, tmp_path, monkeypatch):
        from sbr_tpu.utils.checkpoint import _flight_recorder

        monkeypatch.delenv("SBR_FLIGHT", raising=False)
        assert _flight_recorder() is None


# ---------------------------------------------------------------------------
# The starved-pipeline acceptance gate
# ---------------------------------------------------------------------------


class TestStarvedPipeline:
    def test_injected_batch_stall_shifts_attribution_and_trips_floor(
            self, tmp_path, monkeypatch):
        from sbr_tpu.obs import flight as flight_mod
        from sbr_tpu.obs import report
        from sbr_tpu.obs.report import util_doc
        from sbr_tpu.serve import engine as engine_mod

        flight_mod.reset_shared()
        monkeypatch.setenv("SBR_FLIGHT", "1")
        run_dir = tmp_path / "run"
        eng = engine_mod.Engine(config=CFG, run_dir=str(run_dir))
        orig = engine_mod.Engine._process_chunks

        def slow_chunks(self, unique, groups, max_bucket):
            # The synthetic stall: the host dawdles forming the batch while
            # the device sits idle. Lands between t_popped and the
            # batch-formation close, so the gap must attribute there.
            time.sleep(0.05)
            return orig(self, unique, groups, max_bucket)

        try:
            eng.start()
            eng.query_many([make_model_params(beta=1.2, u=0.25)])  # warm-up
            monkeypatch.setattr(engine_mod.Engine, "_process_chunks",
                                slow_chunks)
            eng.flight.reset()  # compile shadow out of the measured window
            for beta in (1.3, 1.4, 1.5, 1.6):
                eng.query_many([make_model_params(beta=beta, u=0.25)])
        finally:
            eng.close()

        doc, code = util_doc(run_dir, floor=0.8)
        assert code == 1, doc
        assert "under floor 0.8" in doc["breaches"][0]
        # The injected stall guarantees >=0.05s of batch-formation time
        # per measured dispatch — assert the ABSOLUTE attribution, not
        # which cause wins overall: on a loaded single-core runner the
        # blocking client's inter-query gaps stretch arbitrarily and are
        # (correctly) booked as queue starvation, so the dominant cause
        # is a race while the stall's own share is deterministic.
        causes = doc["util"]["gap_causes"]
        assert causes["batch_formation"]["s"] >= 0.15, causes
        assert (causes["batch_formation"]["s"]
                > causes.get("cache_io", {}).get("s", 0.0)), causes
        assert doc["util"]["dispatches"] >= 4
        # CLI contract: same breach through the subcommand.
        assert report.main(["util", str(run_dir), "--floor", "0.8",
                            "--json"]) == 1
        # The same window passes a floor it actually clears: the gate
        # judges utilization, not existence.
        doc, code = util_doc(run_dir, floor=1e-9)
        assert code == 0, doc


# ---------------------------------------------------------------------------
# report util (gate) exits
# ---------------------------------------------------------------------------


def _write_flight_run(tmp_path, name, records, dropped=0):
    d = tmp_path / name
    d.mkdir(parents=True, exist_ok=True)
    doc = _snap(records, dropped=dropped)
    doc["ts"] = 1.0
    (d / "flight.json").write_text(json.dumps(doc))
    return d


class TestReportUtil:
    def test_exit_2_bad_dir(self, tmp_path):
        from sbr_tpu.obs.report import util_doc

        doc, code = util_doc(tmp_path / "nope")
        assert code == 2 and doc["exit"] == 2

    def test_exit_3_no_data(self, tmp_path):
        from sbr_tpu.obs.report import util_doc

        empty = tmp_path / "empty"
        empty.mkdir()
        doc, code = util_doc(empty)
        assert code == 3 and "no flight data" in doc["error"]
        # A flight.json with no records is still "nothing to judge".
        (empty / "flight.json").write_text(json.dumps(_snap([])))
        doc, code = util_doc(empty)
        assert code == 3

    def test_floor_gate_and_disarm(self, tmp_path):
        from sbr_tpu.obs.report import render_util, util_doc

        # 3 dispatches covering 0.3 s of a 2 s window: busy 0.15.
        records = []
        for k in range(3):
            records += _span(0.5 * k, 0.5 * k + 0.1, "engine", "dispatch",
                             k + 1, tag="b1")
        records += _span(1.9, 2.0, "engine", "unpack", 9)
        d = _write_flight_run(tmp_path, "a", records)
        doc, code = util_doc(d, floor=0.5)
        assert code == 1
        assert "under floor 0.5" in doc["breaches"][0]
        assert "UTILIZATION DEGRADED" in render_util(doc)
        doc, code = util_doc(d, floor=0.1)
        assert code == 0
        assert "GATE: ok" in render_util(doc)
        # Below min dispatches the floor gate disarms with a note.
        doc, code = util_doc(d, floor=0.5, min_disp=5)
        assert code == 0
        assert any("disarmed" in n for n in doc["notes"])
        # No floor: never gates.
        doc, code = util_doc(d)
        assert code == 0 and doc["floor"] is None

    def test_floor_env_default(self, tmp_path, monkeypatch):
        from sbr_tpu.obs.report import util_doc

        records = (_span(0.0, 0.1, "engine", "dispatch", 1)
                   + _span(0.1, 0.2, "engine", "dispatch", 2)
                   + _span(0.2, 0.3, "engine", "dispatch", 3)
                   + _span(1.9, 2.0, "engine", "unpack", 4))
        d = _write_flight_run(tmp_path, "a", records)
        monkeypatch.setenv("SBR_FLIGHT_UTIL_FLOOR", "0.9")
        doc, code = util_doc(d)
        assert code == 1 and doc["floor"] == 0.9

    def test_dropped_records_surfaced_as_note(self, tmp_path):
        from sbr_tpu.obs.report import render_util, util_doc

        records = (_span(0.0, 1.0, "engine", "dispatch", 1)
                   + _span(1.0, 1.1, "engine", "unpack", 2))
        d = _write_flight_run(tmp_path, "a", records, dropped=7)
        doc, code = util_doc(d)
        assert code == 0
        assert any("7 record(s) overwritten" in n for n in doc["notes"])
        assert "SBR_FLIGHT_CAP" in render_util(doc)

    def test_cli_json_contract(self, tmp_path):
        from sbr_tpu.obs import report

        records = (_span(0.0, 1.0, "engine", "dispatch", 1)
                   + _span(1.0, 1.1, "engine", "unpack", 2))
        d = _write_flight_run(tmp_path, "a", records)
        assert report.main(["util", str(d), "--json"]) == 0
        assert report.main(["util", str(tmp_path / "gone"), "--json"]) == 2


# ---------------------------------------------------------------------------
# report summary (meta-gate)
# ---------------------------------------------------------------------------


class TestReportSummary:
    def test_exit_2_bad_dir(self, tmp_path):
        from sbr_tpu.obs.report import summary_doc

        doc, code = summary_doc(tmp_path / "nope")
        assert code == 2

    def test_merged_exit_is_max_of_subgates_on_real_run(self, tmp_path,
                                                        monkeypatch):
        from sbr_tpu.obs import flight as flight_mod
        from sbr_tpu.obs.report import render_summary, summary_doc
        from sbr_tpu.serve.engine import Engine

        flight_mod.reset_shared()
        monkeypatch.setenv("SBR_FLIGHT", "1")
        monkeypatch.setenv("SBR_DEMAND", "1")
        run_dir = tmp_path / "run"
        eng = Engine(config=CFG, run_dir=str(run_dir))
        try:
            eng.start()
            pool = [make_model_params(beta=1.2, u=0.25),
                    make_model_params(beta=2.1, u=0.6)]
            eng.query_many(pool, scenario="mix")
            eng.query_many(pool, scenario="mix")
        finally:
            eng.close()
        doc, code = summary_doc(run_dir)
        gates = doc["gates"]
        assert set(gates) == {"health", "serve", "fleet", "trace", "slo",
                              "audit", "demand", "prewarm", "util"}
        # The merged exit IS the max of the subgate exits.
        assert code == max(g["exit"] for g in gates.values())
        assert doc["exit"] == code
        # This run exercised >= 3 observatories end to end.
        passing = [n for n, g in gates.items() if g["exit"] == 0]
        assert {"serve", "demand", "util"} <= set(passing)
        for name in passing:
            assert gates[name]["reason"] == "ok"
        # Observatories that were not enabled surface their honest
        # no-data exits rather than silently passing.
        assert gates["audit"]["exit"] == 3
        assert code == 3
        text = render_summary(doc)
        assert "GATE: exit 3" in text and "audit" in text

    def test_crashing_subgate_reads_exit_2(self, tmp_path, monkeypatch):
        from sbr_tpu.obs import report

        d = tmp_path / "run"
        d.mkdir()

        def boom(run_dir):
            raise RuntimeError("gate exploded")

        monkeypatch.setattr(report, "_SUMMARY_GATES",
                            (("health", boom),) + report._SUMMARY_GATES[1:])
        doc, code = report.summary_doc(d)
        assert doc["gates"]["health"]["exit"] == 2
        assert "gate exploded" in doc["gates"]["health"]["reason"]
        assert code >= 2

    def test_cli_json_contract(self, tmp_path):
        from sbr_tpu.obs import report

        d = tmp_path / "run"
        d.mkdir()
        # An empty run dir: every gate reads its no-data exit; merged != 0.
        code = report.main(["summary", str(d), "--json"])
        assert code == 3


# ---------------------------------------------------------------------------
# report gc --flight-keep (retention) + rotation
# ---------------------------------------------------------------------------


class TestGcFlightKeep:
    def _run_dir(self, root, name, status="done", rotated=3):
        d = root / name
        d.mkdir(parents=True)
        (d / "manifest.json").write_text(json.dumps({"status": status}))
        (d / "flight.json").write_text("{}")
        for i in range(rotated):
            (d / f"flight.{i:03d}.json").write_text("{}")
        return d

    def test_prunes_rotated_keeps_active_and_live_runs(self, tmp_path):
        done = self._run_dir(tmp_path, "run_done")
        live = self._run_dir(tmp_path, "run_live", status="running")
        removed = fl.gc_flight_files(tmp_path, keep=1)
        assert len(removed) == 2
        assert (done / "flight.json").exists()
        assert not (done / "flight.000.json").exists()
        assert (done / "flight.002.json").exists()
        # live run (manifest "running", fresh mtime): never touched.
        assert len(list(live.glob("flight.*.json"))) == 3

    def test_report_gc_flag(self, tmp_path):
        from sbr_tpu.obs import report

        self._run_dir(tmp_path, "run_a")
        code = report.main(["gc", str(tmp_path), "--keep", "99",
                            "--flight-keep", "0"])
        assert code == 0
        assert not list((tmp_path / "run_a").glob("flight.0*.json"))
        assert (tmp_path / "run_a" / "flight.json").exists()

    def test_rotation_archives_snapshots(self, tmp_path, monkeypatch):
        from sbr_tpu.obs import runlog

        monkeypatch.setenv("SBR_FLIGHT_ROTATE_S", "5")
        clock = [0.0]
        run = runlog.RunContext(root=tmp_path, label="rot")
        rec = fl.FlightRecorder(cap=64, time_fn=lambda: clock[0])
        rec.mark("engine", "dispatch", 0.0, 0.5)
        assert rec.maybe_write(run, force=True)
        clock[0] += 6.0
        rec.mark("engine", "dispatch", 6.0, 6.5)
        assert rec.maybe_write(run, force=True)
        run.finalize()
        assert (Path(run.run_dir) / "flight.000.json").exists()
        assert (Path(run.run_dir) / "flight.json").exists()
        manifest = json.loads(
            (Path(run.run_dir) / "manifest.json").read_text())
        assert manifest["flight"]["rotate"] == 1


# ---------------------------------------------------------------------------
# History schema 14
# ---------------------------------------------------------------------------


class TestHistorySchema14:
    def test_flight_metrics_whitelisted(self):
        from sbr_tpu.obs import history

        assert history.SCHEMA >= 14
        out = history.bench_metrics({
            "value": 10.0,
            "extra": {"flight_overhead_ratio": 1.02,
                      "flight_device_busy_frac": 0.31,
                      "flight_host_gap_frac": 0.69},
        })
        assert out["flight_overhead_ratio"] == 1.02
        assert out["flight_device_busy_frac"] == 0.31
        assert out["flight_host_gap_frac"] == 0.69

    def test_polarity(self):
        from sbr_tpu.obs import history

        # busy higher-better; gap and the on/off overhead lower-better.
        assert history.polarity("flight_device_busy_frac") == 1
        assert history.polarity("flight_host_gap_frac") == -1
        assert history.polarity("flight_overhead_ratio") == -1

    def test_schema_1_to_13_lines_still_load_and_gate(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        rows = [{"ts": 1.0, "metrics": {"eq_per_sec": 10.0}}]  # schema-less
        rows += [{"schema": s, "metrics": {"eq_per_sec": 10.0 + s / 10}}
                 for s in range(2, 14)]
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        history.append({"eq_per_sec": 10.6}, path=path)
        records = history.load(path)
        assert ([r["schema"] for r in records]
                == list(range(1, 14)) + [history.SCHEMA])
        verdicts, status = history.check(records, tolerance=0.15)
        assert status == "ok"
