"""Heterogeneity extension tests.

Oracles (SURVEY §4): the K=1 degeneracy — one group with dist=[1.0] reduces
the coupled ODE to the baseline logistic (`heterogeneity_learning.jl:61-66`)
— and an independent scipy pipeline for the reference's two-group Figure
configuration (`scripts/2_heterogeneity.jl:38-49`).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from sbr_tpu.baseline.learning import logistic_cdf, solve_learning
from sbr_tpu.baseline.solver import solve_equilibrium_baseline
from sbr_tpu.hetero import get_aw_hetero, solve_equilibrium_hetero, solve_learning_hetero
from sbr_tpu.models.params import SolverConfig, make_hetero_params, make_model_params
from sbr_tpu.models.results import Status

from oracle import solve_hetero_oracle

CONFIG = SolverConfig(n_grid=4096)


@pytest.fixture(scope="module")
def ref_config_solution():
    """Two-group reference configuration (`2_heterogeneity.jl:38-49`)."""
    m = make_hetero_params(
        betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
    )
    lsh = solve_learning_hetero(m.learning, CONFIG)
    res = solve_equilibrium_hetero(lsh, m.economic, CONFIG)
    return m, lsh, res


class TestHeteroLearning:
    def test_k1_reduces_to_baseline_logistic(self):
        """dist=[1.0] ⇒ dG = (1-G)·β·G, the baseline SI ODE."""
        m = make_hetero_params(betas=[1.0], dist=[1.0], eta_bar=15.0)
        lsh = solve_learning_hetero(m.learning, CONFIG)
        exact = logistic_cdf(lsh.grid, 1.0, 1e-4)
        np.testing.assert_allclose(np.asarray(lsh.cdfs[0]), np.asarray(exact), atol=1e-9)

    def test_exact_omega_path_is_knot_exact(self):
        """The Ω-reduction path (grid_warp > 0) is EXACT at its knots, not
        just integrator-accurate: for K=1, Ω solves dΩ/dt = ω(Ω) whose
        solution makes G(Ω(t)) the logistic — so cdfs at the grid must
        match the closed form to quadrature precision (~1e-12), two to
        three orders beyond the RK4 oracle path's 1e-9. The only error is
        the Gauss-Legendre t(Ω) map; the G_k(Ω) expansion is algebraic."""
        m = make_hetero_params(betas=[1.0], dist=[1.0], eta_bar=15.0)
        assert CONFIG.grid_warp > 0.0  # exact path is the default
        lsh = solve_learning_hetero(m.learning, CONFIG)
        exact = logistic_cdf(lsh.grid, 1.0, 1e-4)
        np.testing.assert_allclose(np.asarray(lsh.cdfs[0]), np.asarray(exact), atol=2e-12)
        # and the grid is genuinely transition-warped (non-uniform)
        d = np.diff(np.asarray(lsh.grid))
        assert d.max() > 5.0 * np.median(d[d > 0])

    def test_two_group_cdfs_match_scipy(self):
        m = make_hetero_params(
            betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
        )
        lsh = solve_learning_hetero(m.learning, CONFIG)
        from oracle import solve_hetero_learning_oracle

        cdfs, _ = solve_hetero_learning_oracle([0.125, 12.5], [0.9, 0.1], 1e-4, m.learning.tspan)
        # Compare at grid knots where both are solver-exact (off-knot values
        # add O(h²·G'') linear-interp error ~2e-6 on both sides).
        knots = np.asarray(lsh.grid)
        ref = np.clip(cdfs(knots), 0.0, 1.0)
        np.testing.assert_allclose(np.asarray(lsh.cdfs), ref, atol=1e-9)

    def test_cdfs_monotone_and_bounded(self, ref_config_solution):
        _, lsh, _ = ref_config_solution
        cdfs = np.asarray(lsh.cdfs)
        assert (np.diff(cdfs, axis=1) >= -1e-12).all()
        assert (cdfs >= 0).all() and (cdfs <= 1).all()

    def test_fast_group_learns_first(self, ref_config_solution):
        _, lsh, _ = ref_config_solution
        mid = CONFIG.n_grid // 4
        assert float(lsh.cdfs[1, mid]) > float(lsh.cdfs[0, mid])


class TestHeteroEquilibrium:
    def test_k1_matches_baseline_solver(self):
        """One group ≡ baseline pipeline end to end."""
        mb = make_model_params()
        ls = solve_learning(mb.learning, CONFIG)
        base = solve_equilibrium_baseline(ls, mb.economic, CONFIG)

        mh = make_hetero_params(betas=[1.0], dist=[1.0], eta_bar=15.0)
        lsh = solve_learning_hetero(mh.learning, CONFIG)
        het = solve_equilibrium_hetero(lsh, mh.economic, CONFIG)

        assert bool(het.bankrun) == bool(base.bankrun)
        np.testing.assert_allclose(float(het.xi), float(base.xi), atol=2e-5)
        np.testing.assert_allclose(
            float(het.tau_bar_in_uncs[0]), float(base.tau_bar_in_unc), atol=2e-5
        )
        np.testing.assert_allclose(
            float(het.tau_bar_out_uncs[0]), float(base.tau_bar_out_unc), atol=2e-5
        )

    def test_two_group_matches_oracle(self, ref_config_solution):
        _, _, res = ref_config_solution
        oracle = solve_hetero_oracle([0.125, 12.5], [0.9, 0.1])
        assert bool(res.bankrun) == oracle.bankrun
        np.testing.assert_allclose(float(res.xi), oracle.xi, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(res.tau_bar_in_uncs), oracle.tau_bar_ins, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(res.tau_bar_out_uncs), oracle.tau_bar_outs, atol=1e-4
        )

    def test_aw_at_xi_equals_kappa(self, ref_config_solution):
        m, lsh, res = ref_config_solution
        xi = res.xi
        t_out = jnp.minimum(res.tau_bar_out_uncs, xi)
        t_in = jnp.minimum(res.tau_bar_in_uncs, xi)
        import jax

        per = jax.vmap(lambda row, t: jnp.interp(t, lsh.grid, row))(lsh.cdfs, t_out) - jax.vmap(
            lambda row, t: jnp.interp(t, lsh.grid, row)
        )(lsh.cdfs, t_in)
        aw = float(jnp.dot(lsh.dist, per))
        np.testing.assert_allclose(aw, m.economic.kappa, atol=1e-7)

    def test_no_run_when_u_above_hazard(self):
        """u above every group's hazard peak ⇒ NO_CROSSING, NaN ξ."""
        m = make_hetero_params(
            betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=50.0, p=0.9, kappa=0.3, lam=0.1
        )
        lsh = solve_learning_hetero(m.learning, CONFIG)
        res = solve_equilibrium_hetero(lsh, m.economic, CONFIG)
        assert not bool(res.bankrun)
        assert int(res.status) == Status.NO_CROSSING
        assert np.isnan(float(res.xi))
        # NaN propagates through the AW decomposition (reference returns
        # `nothing` for no-run, `heterogeneity_solver.jl:317-319`)
        aw = get_aw_hetero(res, lsh)
        assert np.isnan(float(aw.aw_max))
        assert np.isnan(np.asarray(aw.aw_cum)).all()

    def test_aw_decomposition(self, ref_config_solution):
        m, lsh, res = ref_config_solution
        aw = get_aw_hetero(res, lsh)
        # total is the dist-weighted sum of group curves
        recon = np.einsum("k,kn->n", np.asarray(lsh.dist), np.asarray(aw.aw_groups))
        np.testing.assert_allclose(np.asarray(aw.aw_cum), recon, atol=1e-12)
        # peak withdrawal reaches at least κ (a run happened)
        assert float(aw.aw_max) >= m.economic.kappa - 1e-6
        assert (np.asarray(aw.aw_groups) >= -1e-9).all()


class TestThousandGroups:
    """BASELINE.md parity config: K=1000 learning-speed groups."""

    def test_k1000_solves(self):
        cfg = SolverConfig(n_grid=1024, bisect_iters=60)
        k = 1000
        rng = np.random.default_rng(0)
        betas = np.exp(rng.uniform(np.log(0.2), np.log(5.0), k))
        dist = rng.dirichlet(np.ones(k))
        # exact simplex normalization for the 1e-10 constructor check
        dist = dist / dist.sum()
        m = make_hetero_params(
            betas=betas, dist=dist, eta_bar=15.0, u=0.1, p=0.5, kappa=0.6, lam=0.01
        )
        lsh = solve_learning_hetero(m.learning, cfg)
        assert lsh.cdfs.shape == (k, cfg.n_grid)
        res = solve_equilibrium_hetero(lsh, m.economic, cfg)
        assert bool(res.bankrun)
        assert res.hrs.shape == (k, cfg.n_grid)
        aw = get_aw_hetero(res, lsh)
        # equilibrium condition holds for the 1000-group weighted AW
        assert abs(float(aw.aw_max)) <= 1.0
        assert float(aw.aw_max) >= m.economic.kappa - 1e-6

    def test_k1000_uniform_groups_degenerate_to_baseline(self):
        """1000 identical groups must equal the single-group baseline —
        the K=1 degeneracy oracle at scale (SURVEY §4(b))."""
        from sbr_tpu import make_model_params, solve_learning, solve_equilibrium_baseline

        # full-resolution grid: the comparison measures RK4+interp error
        # against the closed form, which is O(h^2) in the grid spacing
        cfg = SolverConfig(n_grid=4096, bisect_iters=60)
        k = 1000
        dist = np.full(k, 1.0 / k)
        dist = dist / dist.sum()
        m = make_hetero_params(
            betas=np.full(k, 1.0), dist=dist, eta_bar=15.0, u=0.1, p=0.5, kappa=0.6, lam=0.01
        )
        lsh = solve_learning_hetero(m.learning, cfg)
        res = solve_equilibrium_hetero(lsh, m.economic, cfg)

        mb = make_model_params(beta=1.0, eta_bar=15.0, u=0.1, p=0.5, kappa=0.6, lam=0.01)
        ls = solve_learning(mb.learning, cfg)
        base = solve_equilibrium_baseline(ls, mb.economic, cfg)
        # RK4-sampled CDF vs closed form, then identical downstream machinery
        np.testing.assert_allclose(float(res.xi), float(base.xi), atol=1e-5)


class TestShardedGroupAxis:
    """K-axis sharding over the 8-virtual-device mesh (SURVEY §5.8): the
    only cross-shard couplings are ω (learning psum), the weighted AW
    (bisection psum), the bracket pmax, and the no-crossing count."""

    def test_k1000_sharded_matches_single_device(self):
        import jax

        from sbr_tpu.hetero import solve_hetero_sharded

        cfg = SolverConfig(n_grid=1024, bisect_iters=60)
        k = 1000  # 125 groups/device on the 8-device mesh
        rng = np.random.default_rng(0)
        betas = np.exp(rng.uniform(np.log(0.2), np.log(5.0), k))
        dist = rng.dirichlet(np.ones(k))
        dist = dist / dist.sum()
        m = make_hetero_params(
            betas=betas, dist=dist, eta_bar=15.0, u=0.1, p=0.5, kappa=0.6, lam=0.01
        )

        lsh1 = solve_learning_hetero(m.learning, cfg)
        res1 = solve_equilibrium_hetero(lsh1, m.economic, cfg)
        aw1 = get_aw_hetero(res1, lsh1)

        mesh = jax.make_mesh((8,), ("k",))
        lsh8, res8, aw8 = solve_hetero_sharded(m, mesh, cfg)

        # per-group stages are device-local → identical; psum-reduced
        # quantities differ only by float64 reduction order
        np.testing.assert_allclose(np.asarray(lsh8.cdfs), np.asarray(lsh1.cdfs), atol=1e-12)
        np.testing.assert_allclose(np.asarray(res8.hrs), np.asarray(res1.hrs), atol=1e-9)
        np.testing.assert_allclose(
            np.asarray(res8.tau_bar_in_uncs), np.asarray(res1.tau_bar_in_uncs), atol=1e-9
        )
        assert int(res8.status) == int(res1.status)
        np.testing.assert_allclose(float(res8.xi), float(res1.xi), atol=1e-9)
        np.testing.assert_allclose(float(aw8.aw_max), float(aw1.aw_max), atol=1e-9)
        np.testing.assert_allclose(np.asarray(aw8.aw_cum), np.asarray(aw1.aw_cum), atol=1e-9)

    def test_indivisible_k_raises(self):
        import jax

        from sbr_tpu.hetero import solve_hetero_sharded

        m = make_hetero_params(
            betas=[0.5, 1.0, 2.0], dist=[0.3, 0.3, 0.4], eta_bar=15.0
        )
        mesh = jax.make_mesh((8,), ("k",))
        with pytest.raises(ValueError, match="divide evenly"):
            solve_hetero_sharded(m, mesh, SolverConfig(n_grid=256))
