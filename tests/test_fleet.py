"""Serving-fleet tests (ISSUE 11): circuit breaker, fleet membership,
deadline/admission semantics, the degradation ladder's tile-cache bridge,
serve result-cache sidecars, scrape-coherent windows, router routing/
failover/hedging, `report fleet` gating, history schema 7, and the
graceful SIGTERM drain.

Router tests run against STUB workers (canned stdlib HTTP servers) so the
routing logic is exercised without paying a jax compile per worker; the
engine-level tests share one tiny SolverConfig like tests/test_serve.py.
"""

import dataclasses
import http.server
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.resilience import faults
from sbr_tpu.resilience.elastic import TileCache, cell_tag, gc_tile_cache, tile_meta
from sbr_tpu.serve.engine import (
    DeadlineExceeded,
    Engine,
    ServeConfig,
    SolverUnavailable,
)
from sbr_tpu.serve.fleet import (
    CircuitBreaker,
    TileCacheBridge,
    WorkerAnnouncer,
    live_workers,
)
from sbr_tpu.serve.live import LiveMetrics
from sbr_tpu.serve.router import Router

REPO = Path(__file__).resolve().parent.parent


def _feq(a, b) -> bool:
    """Bitwise float equality (NaN-safe): the byte-identity contract."""
    return np.float64(a).tobytes() == np.float64(b).tobytes()


CFG = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _clocked(self, **kw):
        now = [0.0]

        def clock():
            return now[0]

        return CircuitBreaker(clock=clock, **kw), now

    def test_opens_after_threshold_consecutive_failures(self):
        b, _ = self._clocked(threshold=3, cooldown_s=5.0)
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed" and b.allow()
        b.record_failure()
        assert b.state == "open" and not b.allow()

    def test_success_resets_consecutive_count(self):
        b, _ = self._clocked(threshold=2, cooldown_s=5.0)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == "closed"  # never two CONSECUTIVE failures

    def test_half_open_single_probe_then_close(self):
        b, now = self._clocked(threshold=1, cooldown_s=5.0)
        b.record_failure()
        assert b.state == "open" and not b.allow()
        now[0] = 5.0
        assert b.allow()  # the half-open probe
        assert b.state == "half_open"
        assert not b.allow()  # exactly ONE probe until its outcome lands
        b.record_success()
        assert b.state == "closed" and b.allow()

    def test_half_open_failure_reopens_and_restarts_cooldown(self):
        b, now = self._clocked(threshold=1, cooldown_s=5.0)
        b.record_failure()
        now[0] = 5.0
        assert b.allow()
        b.record_failure()
        assert b.state == "open"
        now[0] = 9.0  # cooldown restarted at t=5: not yet
        assert not b.allow()
        now[0] = 10.0
        assert b.allow()

    def test_admissible_is_side_effect_free(self):
        # Ranking candidates must not consume the half-open probe: a True
        # from admissible() leaves the state machine untouched; only
        # allow() (called at send time) grants the probe.
        b, now = self._clocked(threshold=1, cooldown_s=5.0)
        b.record_failure()
        now[0] = 5.0
        for _ in range(3):
            assert b.admissible()
        assert b.state == "open"  # no transition, no probe granted
        assert b.allow()  # the actual send takes the probe
        assert b.state == "half_open"
        assert not b.admissible()  # probe in flight: peers are not admitted
        b.record_success()
        assert b.admissible() and b.state == "closed"

    def test_transitions_observed_and_aged(self):
        seen = []
        now = [0.0]
        b = CircuitBreaker(threshold=1, cooldown_s=2.0, clock=lambda: now[0],
                           on_transition=lambda old, new: seen.append((old, new)))
        assert b.age_s() is None
        b.record_failure()
        now[0] = 1.5
        assert seen == [("closed", "open")]
        assert b.age_s() == pytest.approx(1.5)
        now[0] = 2.0
        b.allow()
        b.record_success()
        assert seen == [("closed", "open"), ("open", "half_open"),
                        ("half_open", "closed")]


# ---------------------------------------------------------------------------
# Fleet membership
# ---------------------------------------------------------------------------


class TestFleetMembership:
    def test_announce_and_filter_non_workers(self, tmp_path):
        ann = WorkerAnnouncer(tmp_path, "http://127.0.0.1:1234", host="w1")
        ann.beat(qps=2.5)
        # A sweep host sharing the dir (no url) must never route traffic.
        from sbr_tpu.resilience.elastic import Heartbeat

        Heartbeat(tmp_path, "sweep-host").beat(tiles_done=3)
        live = live_workers(tmp_path)
        assert list(live) == ["w1"]
        assert live["w1"]["url"] == "http://127.0.0.1:1234"
        assert live["w1"]["qps"] == 2.5
        ann.withdraw()
        assert live_workers(tmp_path) == {}

    def test_ttl_expiry(self, tmp_path):
        ann = WorkerAnnouncer(tmp_path, "http://x", ttl_s=0.05, host="w1")
        ann.beat()
        assert "w1" in live_workers(tmp_path)
        assert "w1" not in live_workers(tmp_path, now=time.time() + 1.0)

    def test_heartbeat_fault_point_silences_beat(self, tmp_path):
        plan = faults.FaultPlan(
            {"seed": 0, "rules": [
                {"point": "fleet.heartbeat", "kind": "transient", "at_hits": [1]},
            ]}
        )
        faults.install(plan)
        try:
            ann = WorkerAnnouncer(tmp_path, "http://x", host="w1")
            ann.beat()  # silenced: no heartbeat file lands
            assert live_workers(tmp_path) == {}
            ann.beat()  # next beat goes through
            assert "w1" in live_workers(tmp_path)
        finally:
            faults.install(None)
            faults.reset()


# ---------------------------------------------------------------------------
# Deadlines & admission (ISSUE 11 satellite: deadline semantics)
# ---------------------------------------------------------------------------


class TestDeadlines:
    def test_expired_deadline_sheds_with_zero_solver_work(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))

        def boom(*a, **k):  # the solver path must never be touched
            raise AssertionError("dispatch called for a shed query")

        eng._dispatch = boom
        with pytest.raises(DeadlineExceeded) as err:
            eng.query(make_model_params(beta=1.1, u=0.2), deadline_ms=0)
        assert err.value.retry_after_s > 0
        snap = eng.statz()
        assert snap["totals"]["shed"] == 1
        assert snap["totals"]["queries"] == 0
        eng.close()

    def test_unmeetable_deadline_sheds_from_service_estimate(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        eng._service_ewma_s = 10.0  # measured: the solver takes ~10 s
        eng._dispatch = lambda *a, **k: (_ for _ in ()).throw(AssertionError)
        with pytest.raises(DeadlineExceeded) as err:
            eng.query(make_model_params(beta=1.1, u=0.2), deadline_ms=100)
        assert err.value.retry_after_s == pytest.approx(10.0)
        # Plenty of deadline is admitted (and then fails on our stub,
        # proving admission — not the solver — was the gate above).
        with pytest.raises(AssertionError):
            eng.query(make_model_params(beta=1.1, u=0.2), deadline_ms=60_000)
        eng.close()

    def test_deadline_expiring_mid_batch_still_returns(self):
        # Admission and batch formation both pass (the 150 ms deadline is
        # comfortably alive when the synchronous _process starts); the
        # first-call compile + solve then takes far longer — the batch is
        # already paid for, so the caller still gets its full answer, and
        # nothing is shed or errored.
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        res = eng.query(make_model_params(beta=1.1, u=0.2), deadline_ms=150.0)
        assert res.status in (0, 1, 2, 3)
        snap = eng.statz()
        assert snap["totals"]["queries"] == 1
        assert snap["totals"]["shed"] == 0
        eng.close()

    def test_deadline_expired_while_queued_sheds_at_batch_formation(self):
        # A ticket that outlives its deadline in the QUEUE (admission could
        # not see queue wait) is shed at batch formation without burning a
        # dispatch; the waiter gets the explicit DeadlineExceeded.
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))

        def boom(*a, **k):
            raise AssertionError("dispatch burned on a queue-expired query")

        eng._dispatch = boom
        tk = eng.submit(make_model_params(beta=1.1, u=0.2), deadline_ms=30.0)
        time.sleep(0.06)  # the deadline lapses while "queued"
        eng._process([tk])
        with pytest.raises(DeadlineExceeded):
            tk.wait(timeout=1)
        assert eng.statz()["totals"]["shed"] == 1
        eng.close()

    def test_default_deadline_from_env(self, monkeypatch):
        monkeypatch.setenv("SBR_SERVE_DEADLINE_MS", "250")
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        assert eng.default_deadline_ms == 250.0
        eng._service_ewma_s = 5.0
        with pytest.raises(DeadlineExceeded):  # 250 ms < 5 s estimate
            eng.query(make_model_params(beta=1.1, u=0.2))
        eng.close()

    def test_endpoint_maps_shed_to_429_with_retry_after(self):
        from sbr_tpu.serve.endpoint import ServeEndpoint

        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        eng._service_ewma_s = 7.0
        with ServeEndpoint(eng) as ep:
            req = urllib.request.Request(
                f"http://127.0.0.1:{ep.port}/query",
                data=json.dumps({"beta": 1.1, "u": 0.2}).encode(),
                headers={"Content-Type": "application/json",
                         "X-SBR-Deadline-Ms": "50"},
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 429
            assert float(err.value.headers["Retry-After"]) == pytest.approx(7.0)
            body = json.loads(err.value.read())
            assert body["error"] == "deadline"
        eng.close()


# ---------------------------------------------------------------------------
# Engine breaker + degradation ladder
# ---------------------------------------------------------------------------


def _force_open(breaker: CircuitBreaker) -> None:
    for _ in range(breaker.threshold):
        breaker.record_failure()


@pytest.fixture(scope="module")
def swept_cache(tmp_path_factory):
    """One tiny tiled sweep whose tiles land in a global cache (shared by
    the ladder tests — the sweep compile is the expensive part)."""
    from sbr_tpu.utils.checkpoint import run_tiled_grid

    tmp_path = tmp_path_factory.mktemp("swept_cache")
    base = make_model_params()
    betas = np.linspace(0.5, 2.0, 4)
    us = np.linspace(0.05, 0.5, 4)
    cache_dir = tmp_path / "tile_cache"
    grid = run_tiled_grid(
        betas, us, base, config=CFG, tile_shape=(2, 2),
        checkpoint_dir=str(tmp_path / "ckpt"),
        tile_cache=TileCache(cache_dir),
    )
    return base, betas, us, cache_dir, grid


class TestEngineBreakerAndLadder:
    def test_open_breaker_short_circuits_and_degrades_healthz(self):
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        _force_open(eng.breaker)
        with pytest.raises(SolverUnavailable):
            eng.query_many([make_model_params(beta=1.1, u=0.2)])[0]
        health = eng.healthz()
        assert health["status"] == "degraded"
        assert any("breaker open" in r for r in health["reasons"])
        eng.close()

    @staticmethod
    def _cell_params(base, beta, u):
        """The ModelParams whose solve IS sweep cell (β, u): swept β/u with
        the base's pinned η/tspan/x0 economics."""
        return make_model_params(
            beta=float(beta), u=float(u), eta=base.economic.eta,
            tspan=base.learning.tspan, x0=base.learning.x0,
        )

    def test_store_writes_meta_and_bridge_finds_cell(self, swept_cache):
        base, betas, us, cache_dir, grid = swept_cache
        metas = list(cache_dir.rglob("*.meta.json"))
        assert len(metas) == 4  # one per stored tile
        doc = json.loads(metas[0].read_text())
        assert set(doc) == {"key", "cell_tag", "betas", "us"}

        bridge = TileCacheBridge(cache_dir)
        q = self._cell_params(base, betas[1], us[2])
        rec = bridge.lookup(q, CFG, "float64")
        assert rec is not None
        assert rec["xi"] == pytest.approx(
            float(np.asarray(grid.xi)[1, 2]), nan_ok=True, abs=0.0
        )
        assert rec["status"] == int(np.asarray(grid.status)[1, 2])
        # A different config must NOT match (tag includes the config).
        other = dataclasses.replace(CFG, bisect_iters=31)
        assert bridge.lookup(q, other, "float64") is None
        # A point off the swept axes must not match either.
        off = self._cell_params(base, 1.2345, us[2])
        assert bridge.lookup(off, CFG, "float64") is None

    def test_solver_outage_answered_from_tile_cache(self, tmp_path, monkeypatch,
                                                    swept_cache):
        base, betas, us, cache_dir, grid = swept_cache
        monkeypatch.setenv("SBR_TILE_CACHE_DIR", str(cache_dir))
        run_dir = tmp_path / "obs_run"
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)),
                     run_dir=str(run_dir))
        _force_open(eng.breaker)  # the solver path is DOWN
        q = self._cell_params(base, betas[0], us[1])
        res = eng.query_many([q])[0]
        assert res.degraded is True
        assert res.source == "tilecache"
        assert res.xi == pytest.approx(float(np.asarray(grid.xi)[0, 1]), nan_ok=True)
        assert np.isnan(res.tau_bar_in)  # tiles don't store it — labeled NaN
        # Observable end-to-end: /statz counters + healthz reason + the
        # obs manifest fleet block (the acceptance criterion).
        snap = eng.statz()
        assert snap["totals"]["degraded"] == 1
        assert snap["window"]["degraded"] == 1
        assert any("degraded-ladder" in r for r in snap["healthz"]["reasons"])
        eng.close()
        manifest = json.loads((run_dir / "manifest.json").read_text())
        assert manifest["fleet"]["degraded"] == 1

    def test_outage_without_matching_tile_errors_and_logs_exhaustion(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("SBR_TILE_CACHE_DIR", str(tmp_path / "empty_cache"))
        run_dir = tmp_path / "obs_run"
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)),
                     run_dir=str(run_dir))
        _force_open(eng.breaker)
        with pytest.raises(SolverUnavailable):
            eng.query_many([make_model_params(beta=1.27, u=0.33)])[0]
        eng.close()
        manifest = json.loads((run_dir / "manifest.json").read_text())
        assert manifest["fleet"]["ladder_exhausted"] == 1


# ---------------------------------------------------------------------------
# Serve result-cache sidecars (ISSUE 11 satellite: verify-on-read)
# ---------------------------------------------------------------------------


class TestServeCacheSidecars:
    def _warm_cache(self, tmp_path):
        serve = ServeConfig(buckets=(1,), cache_dir=str(tmp_path / "cache"))
        eng = Engine(config=CFG, serve=serve)
        p = make_model_params(beta=1.3, u=0.22)
        first = eng.query_many([p])[0]
        eng.close()
        files = list((tmp_path / "cache" / "results").rglob("*.json"))
        assert len(files) == 1
        return serve, p, first, files[0]

    def test_store_writes_sidecar_and_warm_hit_verifies(self, tmp_path):
        serve, p, first, entry = self._warm_cache(tmp_path)
        assert Path(str(entry) + ".sha256").exists()
        eng = Engine(config=CFG, serve=serve)  # fresh LRU: disk path
        res = eng.query_many([p])[0]
        assert res.source == "disk"
        assert _feq(res.xi, first.xi)
        eng.close()

    def test_corrupt_entry_quarantined_and_recomputed(self, tmp_path):
        serve, p, first, entry = self._warm_cache(tmp_path)
        good = entry.read_text()
        entry.write_text(good.replace('"xi":', '"xi_corrupted":', 1))
        eng = Engine(config=CFG, serve=serve)
        res = eng.query_many([p])[0]
        assert res.source == "computed"  # never trusted the corrupt bytes
        assert _feq(res.xi, first.xi)
        quarantined = list((entry.parent / "quarantine").glob("*.json"))
        assert len(quarantined) == 1  # evidence preserved, slot freed
        eng.close()

    def test_legacy_sidecarless_entry_still_trusted(self, tmp_path):
        serve, p, first, entry = self._warm_cache(tmp_path)
        Path(str(entry) + ".sha256").unlink()
        eng = Engine(config=CFG, serve=serve)
        res = eng.query_many([p])[0]
        assert res.source == "disk"  # pre-sidecar builds keep resuming
        eng.close()


# ---------------------------------------------------------------------------
# Scrape-coherent windows (ISSUE 11 satellite: /metrics vs rotation race)
# ---------------------------------------------------------------------------


class TestWindowCoherence:
    def test_statz_window_and_healthz_share_one_fold(self):
        # A clock that jumps half a window per read: any second fold inside
        # one statz() would see a DIFFERENT window than the first. The
        # divergent count in the healthz verdict and the window beside it
        # must still agree — one fold, passed down.
        now = [0.0]

        def stepping():
            now[0] += 30.0
            return now[0]

        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
        eng.live = LiveMetrics(window_s=60.0, time_fn=stepping)
        eng.live.record_query(0.001, "computed", divergent=True)
        doc = eng.statz()
        window_divergent = doc["window"]["divergent_cells"]
        health_mentions = any(
            "divergent" in r for r in doc["healthz"]["reasons"]
        )
        assert (window_divergent > 0) == health_mentions
        eng.close()

    def test_scrape_hammer_during_observe_stays_coherent(self):
        lm = LiveMetrics(window_s=0.25)  # slot every ~20 ms: rotations galore
        stop = threading.Event()

        def observe():
            while not stop.is_set():
                lm.record_query(0.0005, "computed")
                lm.record_query(0.0005, "lru")

        t = threading.Thread(target=observe, daemon=True)
        t.start()
        try:
            for _ in range(150):
                w = lm.window()
                # One fold: the quantile summary and the raw histogram
                # describe the SAME slots. The lock-free contract allows a
                # concurrent record to tear ONE in-flight count (count vs
                # counts updated non-atomically), never to mix windows —
                # so the two views may differ by at most the writer's two
                # in-flight samples, not by a whole rotated slot.
                assert abs(
                    w["latency_ms"]["count"] - sum(w["latency_hist_ms"]["counts"])
                ) <= 2
                prom = lm.to_prometheus()
                assert "sbr_serve_window_queries" in prom
        finally:
            stop.set()
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# Router: routing, failover, hedging, shedding (stub workers, no jax)
# ---------------------------------------------------------------------------


class _StubWorker:
    """A canned /query responder: fixed JSON body, optional delay/status."""

    def __init__(self, fleet_dir, host_id, xi=1.0, status_code=200,
                 delay_s=0.0, ttl_s=60.0):
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                stub.hits += 1
                stub.deadlines.append(self.headers.get("X-SBR-Deadline-Ms"))
                if stub.delay_s:
                    time.sleep(stub.delay_s)
                body = json.dumps(
                    {"xi": stub.xi, "tau_bar_in": 1.0, "aw_max": 2.0,
                     "status": 1, "flags": 0, "residual": 0.0,
                     "source": "computed", "degraded": False,
                     "scenario": "default", "latency_ms": 1.0}
                ).encode()
                code = stub.status_code
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if code == 429:
                    self.send_header("Retry-After", "2.5")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.xi = xi
        self.status_code = status_code
        self.delay_s = delay_s
        self.hits = 0
        self.deadlines = []
        self.thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self.thread.start()
        self.announcer = WorkerAnnouncer(
            fleet_dir, f"http://127.0.0.1:{self.port}", host=host_id, ttl_s=ttl_s
        )
        self.announcer.beat(healthz="ready")

    def close(self):
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass
        self.announcer.withdraw()


def _post(router, doc=None, deadline_ms=None, timeout=10):
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-SBR-Deadline-Ms"] = str(deadline_ms)
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/query",
        data=json.dumps(doc or {"beta": 1.0, "u": 0.1}).encode(),
        headers=headers, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class TestRouter:
    def test_failover_absorbs_a_dead_worker(self, tmp_path, monkeypatch):
        # Threshold 1: the dead worker's breaker opens on its FIRST failed
        # forward (the default 3 needs more traffic than this short mix —
        # the router's EWMA steers away from it after one failover).
        monkeypatch.setenv("SBR_BREAKER_THRESHOLD", "1")
        dead = _StubWorker(tmp_path, "w-dead", status_code=500)
        live = _StubWorker(tmp_path, "w-live", xi=42.0)
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            codes = [_post(router) for _ in range(4)]
            assert all(c == 200 for c, _ in codes)
            assert all(d["xi"] == 42.0 for _, d in codes)
            assert router.counters["failed"] == 0
            assert router.counters["failover"] >= 1
            # The dead worker's breaker opened after threshold failures and
            # /healthz says so.
            health = router.healthz()
            assert health["status"] == "degraded"
            assert "w-dead" in " ".join(health["reasons"])
        finally:
            router.close()
            dead.close()
            live.close()

    def test_all_workers_down_is_a_lost_query_503(self, tmp_path):
        dead = _StubWorker(tmp_path, "w-dead", status_code=500)
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(router)
            assert err.value.code == 503
            assert router.counters["failed"] == 1
        finally:
            router.close()
            dead.close()

    def test_worker_429_passes_through_as_shed_not_failover(self, tmp_path):
        shedder = _StubWorker(tmp_path, "w-shed", status_code=429)
        peer = _StubWorker(tmp_path, "w-peer", delay_s=0.2)
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            # Drive until the shedding worker is the one picked (scores tie
            # at the seed; host-id tie-break makes w-peer first, but its
            # 0.2 s delay raises its EWMA after one hit, so w-shed wins
            # from the second query on).
            saw_429 = False
            for _ in range(4):
                try:
                    _post(router)
                except urllib.error.HTTPError as err:
                    assert err.code == 429
                    assert float(err.headers["Retry-After"]) == 2.5
                    saw_429 = True
                    break
            assert saw_429
            assert router.counters["shed"] == 1
            assert router.counters["failover"] == 0  # shed is NOT failed over
            assert router.counters["failed"] == 0
        finally:
            router.close()
            shedder.close()
            peer.close()

    def test_expired_deadline_sheds_at_router_without_forwarding(self, tmp_path):
        w = _StubWorker(tmp_path, "w1")
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(router, deadline_ms=-1)
            assert err.value.code == 429
            assert w.hits == 0  # shed before any forward
            assert router.counters["shed"] == 1
        finally:
            router.close()
            w.close()

    def test_deadline_header_propagates_to_worker(self, tmp_path):
        w = _StubWorker(tmp_path, "w1")
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            _post(router, deadline_ms=5000)
            assert len(w.deadlines) == 1
            assert 0 < float(w.deadlines[0]) <= 5000
        finally:
            router.close()
            w.close()

    def test_hedge_win_recorded_once_in_latency_histogram(self, tmp_path):
        slow = _StubWorker(tmp_path, "a-slow", delay_s=0.8, xi=1.0)
        fast = _StubWorker(tmp_path, "b-fast", xi=2.0)
        # Force the primary pick onto the slow worker: host-id tie-break
        # ("a-slow" < "b-fast") at equal seed scores.
        router = Router(tmp_path, poll_s=0.01, hedge_ms=50.0).start()
        try:
            code, doc = _post(router)
            assert code == 200
            assert doc["xi"] == 2.0  # the hedge won
            assert router.counters["hedged"] == 1
            assert router.counters["hedge_wins"] == 1
            # Exactly ONE latency sample for the query — the hedged win is
            # never double-counted (deadline-semantics satellite).
            assert router.latency_hist.count == 1
            assert router.counters["completed"] == 1
        finally:
            router.close()
            slow.close()
            fast.close()

    def test_worker_4xx_passes_through_without_failover_or_loss(self, tmp_path):
        # A client error is the CLIENT's fault: re-sending the same bytes
        # to a peer would 4xx everywhere — so no failover, no breaker
        # charge, and above all no "lost" count tripping `report fleet`.
        bad = _StubWorker(tmp_path, "w-400", status_code=400)
        peer = _StubWorker(tmp_path, "w-peer", delay_s=0.2)
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            saw_400 = False
            for _ in range(4):
                try:
                    _post(router)
                except urllib.error.HTTPError as err:
                    assert err.code == 400
                    saw_400 = True
                    break
            assert saw_400
            assert router.counters["client_errors"] == 1
            assert router.counters["failed"] == 0
            assert router.counters["failover"] == 0
            router.refresh_workers(force=True)
            with router._workers_lock:
                assert all(
                    w.breaker.state == "closed"
                    for w in router._workers.values()
                )
        finally:
            router.close()
            bad.close()
            peer.close()

    def test_bad_deadline_header_is_client_error_not_loss(self, tmp_path):
        w = _StubWorker(tmp_path, "w1")
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/query",
                data=b"{}", method="POST",
                headers={"Content-Type": "application/json",
                         "X-SBR-Deadline-Ms": "abc"},
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 400
            assert router.counters["client_errors"] == 1
            assert router.counters["failed"] == 0
        finally:
            router.close()
            w.close()

    def test_expired_heartbeat_drops_worker(self, tmp_path):
        w = _StubWorker(tmp_path, "w1", ttl_s=0.2)
        router = Router(tmp_path, poll_s=0.01).start()
        try:
            router.refresh_workers(force=True)
            assert router.healthz()["routable"] == 1
            time.sleep(0.4)  # the TTL lapses with no further beats
            router.refresh_workers(force=True)
            assert router.healthz()["routable"] == 0
        finally:
            router.close()
            w.close()

    def test_injected_forward_fault_drives_failover(self, tmp_path):
        a = _StubWorker(tmp_path, "aa", xi=1.0)
        b = _StubWorker(tmp_path, "bb", xi=7.0)
        plan = faults.FaultPlan(
            {"seed": 0, "rules": [
                {"point": "router.forward", "kind": "transient",
                 "match": "aa", "max_fires": 1},
            ]}
        )
        faults.install(plan)
        try:
            router = Router(tmp_path, poll_s=0.01).start()
            code, doc = _post(router)
            assert code == 200 and doc["xi"] == 7.0
            assert router.counters["failover"] == 1
            router.close()
        finally:
            faults.install(None)
            faults.reset()
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# report fleet gating
# ---------------------------------------------------------------------------


class TestReportFleet:
    def _run_dir(self, tmp_path, counters=None, workers=None, events=()):
        from sbr_tpu import obs

        run_dir = tmp_path / "run"
        run = obs.RunContext(run_dir=str(run_dir), label="router")
        for action in events:
            run.log_fleet(action)
        run.live_snapshot(
            {"schema": "sbr-fleet/1", "counters": counters or {},
             "workers": workers or {}, "latency_ms": {}},
            name="fleet.json",
        )
        run.finalize()
        return run_dir

    def _report(self, run_dir, *extra):
        proc = subprocess.run(
            [sys.executable, "-m", "sbr_tpu.obs.report", "fleet",
             str(run_dir), "--json", *extra],
            capture_output=True, text=True, timeout=120,
        )
        return proc.returncode, json.loads(proc.stdout)

    def test_clean_run_exits_0(self, tmp_path):
        run_dir = self._run_dir(
            tmp_path,
            counters={"queries": 10, "completed": 10, "failed": 0, "failover": 1},
            workers={"w1": {"breaker": "closed", "breaker_age_s": None}},
            events=["failover", "worker_join"],
        )
        rc, doc = self._report(run_dir)
        assert rc == 0
        assert doc["failover_count"] == 1
        assert doc["events"]["failover"] == 1

    def test_lost_queries_exit_1(self, tmp_path):
        run_dir = self._run_dir(
            tmp_path, counters={"queries": 10, "completed": 9, "failed": 1},
            events=["lost"],
        )
        rc, doc = self._report(run_dir)
        assert rc == 1
        assert doc["lost"] == 1

    def test_lost_events_gate_even_without_snapshot_counters(self, tmp_path):
        # kill -9 fallback: the router died before its final snapshot —
        # the event fold alone must still gate.
        run_dir = self._run_dir(tmp_path, counters={"failed": 0}, events=["lost"])
        rc, doc = self._report(run_dir)
        assert rc == 1

    def test_breaker_stuck_open_exit_1_and_threshold(self, tmp_path):
        workers = {"w1": {"breaker": "open", "breaker_age_s": 120.0}}
        run_dir = self._run_dir(
            tmp_path, counters={"queries": 1, "completed": 1, "failed": 0},
            workers=workers, events=["breaker_open"],
        )
        rc, doc = self._report(run_dir, "--stuck-after-s", "60")
        assert rc == 1
        assert doc["stuck_breakers"] == ["w1"]
        # Default threshold (600 s) tolerates a recently opened breaker —
        # e.g. one parked over a freshly dead worker.
        rc, doc = self._report(run_dir)
        assert rc == 0

    def test_no_fleet_data_exit_3_and_bad_dir_exit_2(self, tmp_path):
        from sbr_tpu import obs

        empty = tmp_path / "empty_run"
        run = obs.RunContext(run_dir=str(empty), label="not-a-router")
        run.finalize()
        rc, _ = self._report(empty)
        assert rc == 3
        rc, _ = self._report(tmp_path / "nope")
        assert rc == 2


# ---------------------------------------------------------------------------
# History schema 7
# ---------------------------------------------------------------------------


class TestHistorySchema7:
    def test_schema_is_at_least_7_and_keys_picked_up(self):
        from sbr_tpu.obs import history

        assert history.SCHEMA >= 7  # 8 since ISSUE 13 (grad workload)
        metrics = history.bench_metrics(
            {"metric": "x", "value": 1.0,
             "extra": {"fleet_p99_ms": 12.5, "fleet_failover_count": 0,
                       "fleet_shed_rate": 0.0, "serve_p99_ms": 3.0}}
        )
        assert metrics["fleet_p99_ms"] == 12.5
        assert metrics["fleet_failover_count"] == 0
        assert metrics["fleet_shed_rate"] == 0.0

    def test_polarity_fleet_metrics_lower_better(self):
        from sbr_tpu.obs.history import polarity

        assert polarity("fleet_p99_ms") == -1
        assert polarity("fleet_failover_count") == -1
        assert polarity("fleet_shed_rate") == -1
        # The established polarities must not flip.
        assert polarity("serve_cache_hit_rate") == 1
        assert polarity("grid_adaptive_speedup") == 1
        assert polarity("sweep_warm_cells_per_sec") == 1

    def test_schemas_1_through_6_still_load_and_gate_schema_7(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        base = {"beta_u_grid_equilibria_per_sec": 100.0}
        lines = [
            {"metrics": base, "label": "bench", "platform": "cpu"},  # schema-less
            {"schema": 2, "metrics": {**base, "mem_peak_bytes": 10}, "platform": "cpu"},
            {"schema": 3, "metrics": {**base, "serve_p99_ms": 5.0}, "platform": "cpu"},
            {"schema": 4, "metrics": {**base, "sweep_warm_hit_rate": 1.0}, "platform": "cpu"},
            {"schema": 5, "metrics": {**base, "grid_adaptive_speedup": 2.0}, "platform": "cpu"},
            {"schema": 6, "metrics": {**base, "agents_graph_gen_speedup": 9.0}, "platform": "cpu"},
        ]
        with open(path, "w") as fh:
            for rec in lines:
                fh.write(json.dumps({"ts": "t", **rec}) + "\n")
        history.append(
            {**base, "fleet_p99_ms": 12.0, "fleet_failover_count": 0,
             "fleet_shed_rate": 0.0},
            platform="cpu", path=path,
        )
        records = history.load(path)
        assert len(records) == 7
        assert records[0]["schema"] == 1
        assert records[-1]["schema"] == history.SCHEMA  # 8 since ISSUE 13
        verdicts, status = history.check(records, tolerance=0.15)
        assert status == "ok"

    def test_fleet_p99_regression_gates(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        for v in (10.0, 10.5, 9.8):
            history.append({"fleet_p99_ms": v}, platform="cpu", path=path)
        history.append({"fleet_p99_ms": 30.0}, platform="cpu", path=path)
        verdicts, status = history.check(history.load(path), tolerance=0.15)
        assert status == "regression"
        assert verdicts["fleet_p99_ms"]["status"] == "regression"

    def test_failover_increase_from_zero_baseline_regresses(self, tmp_path):
        from sbr_tpu.obs import history

        path = tmp_path / "hist.jsonl"
        for _ in range(3):
            history.append({"fleet_failover_count": 0}, platform="cpu", path=path)
        history.append({"fleet_failover_count": 2}, platform="cpu", path=path)
        verdicts, status = history.check(history.load(path))
        # Lower-better with a zero baseline: ANY increase is a regression
        # (a clean fleet that starts failing over is a signal, not a %).
        assert status == "regression"


# ---------------------------------------------------------------------------
# Tile-cache meta gc
# ---------------------------------------------------------------------------


class TestTileMetaGc:
    def test_gc_removes_meta_with_entry_and_orphans(self, tmp_path):
        cache = TileCache(tmp_path / "cache")
        base = make_model_params()
        key = cache.key(base, CFG, "float64", [1.0], [0.1])
        arrays = {f: np.zeros((1, 1)) for f in ("max_aw", "xi", "status")}
        meta = tile_meta(base, CFG, "float64", [1.0], [0.1], key)
        cache.store(key, arrays, meta=meta)
        entry = cache.path(key)
        meta_path = Path(str(entry)[: -len(".npz")] + ".meta.json")
        assert meta_path.exists()
        # Cold entry: gc removes entry + sha256 + meta together.
        removed = gc_tile_cache(cache.root, keep_days=0.0,
                                now=time.time() + 86400.0)
        assert entry in removed and meta_path in removed
        # Orphan meta (entry pruned separately): swept after the grace hour.
        meta_path.write_text(json.dumps(meta))
        removed = gc_tile_cache(cache.root, keep_days=9999.0,
                                now=time.time() + 7200.0)
        assert meta_path in removed

    def test_cell_tag_distinguishes_economics_and_config(self):
        base = make_model_params()
        t1 = cell_tag(base, CFG, "float64")
        assert t1 == cell_tag(make_model_params(), CFG, "float64")
        assert t1 != cell_tag(make_model_params(kappa=0.61), CFG, "float64")
        assert t1 != cell_tag(base, dataclasses.replace(CFG, n_grid=128), "float64")
        assert t1 != cell_tag(base, CFG, "float32")


# ---------------------------------------------------------------------------
# Graceful drain (ISSUE 11 satellite) — one subprocess worker
# ---------------------------------------------------------------------------


class TestGracefulDrain:
    def test_sigterm_drains_heartbeat_and_finalizes_interrupted(self, tmp_path):
        from sbr_tpu.serve.loadgen import spawn_worker

        fleet_dir = tmp_path / "fleet"
        run_dir = tmp_path / "wrun"
        w = spawn_worker(
            str(fleet_dir), n_grid=96, bisect_iters=30, buckets="1",
            run_dir=str(run_dir), platform="cpu", heartbeat_ttl=60.0,
            timeout_s=180.0,
        )
        try:
            assert list(fleet_dir.glob("host_*.hb"))  # announced
            os.kill(w["pid"], signal.SIGTERM)
            rc = w["proc"].wait(timeout=60)
            assert rc == 143  # 128 + SIGTERM: the graceful-shutdown contract
            # The heartbeat was withdrawn at drain — router peers reclaim
            # instantly instead of waiting out the 60 s TTL.
            assert not list(fleet_dir.glob("host_*.hb"))
            manifest = json.loads((run_dir / "manifest.json").read_text())
            assert manifest["status"] == "interrupted"
        finally:
            if w["proc"].poll() is None:
                w["proc"].kill()
