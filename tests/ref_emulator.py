"""Faithful Python emulation of the REFERENCE's baseline numerics.

`tests/oracle.py` bounds sbr_tpu against ideal mathematics (adaptive
quadrature, brentq root-finding at tight tolerance). This module bounds it
against the reference's OWN algorithm (VERDICT r3 missing #1): the scipy
oracle answers "is the TPU build right?", this one answers "would the
reference's figures agree?" — which can differ wherever the reference's
adaptive-grid discretization deviates from ideal math (plausible near the
no-run frontier).

Every stage mirrors the reference implementation step for step:

- Stage 1 (`/root/reference/src/baseline/learning.jl:41-54`): the logistic
  ODE solved by an ADAPTIVE high-order RK pair at machine-level tolerance
  (AutoTsit5(Rosenbrock23) at reltol=abstol=eps() there; scipy's RK45 — the
  same Dormand-Prince family as Tsit5 — at its tightest accepted rtol
  here), with G and the symbolic pdf g=β·G·(1−G) (`learning.jl:161-173`)
  wrapped as LINEAR interpolants on the solver's own adaptive grid.
- Stage 2 hazard (`solver.jl:153-185`): the pdf's grid cut at η (η
  appended), the cumulative integral as a SEQUENTIAL trapezoid loop on that
  inherited grid, HR as a linear interpolant on it.
- Stage 2 buffers (`solver.jl:211-264`): boolean above-threshold scan on
  HR's grid, first ↑ / last ↓ crossing refined by linear interpolation,
  with the reference's exact boundary-case returns.
- Stage 3 (`solver.jl:308-376`): bisection from the midpoint guess with
  tolerance exit at 10·eps(κ), the finite-difference slope check using the
  LOCAL grid spacing at ξ as epsilon, the interval-collapse and
  max-iteration (iter == max_iters-1) aborts, and the 5-case status logic.
- AW curve (`solver.jl:495-532`): shifted-CDF evaluation on HR's grid with
  the t−ξ+τ̄_CON < 0 zeroing and the +G(0) founder offset; AW_max is the
  max over the grid knots (`solver.jl:566`).

The emulator is intentionally slow, host-side scipy/numpy — it exists only
as a differential-test oracle for `tests/test_reference_parity.py`.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np
from scipy.integrate import solve_ivp


@dataclasses.dataclass
class RefSolution:
    """Scalars the reference's `SolvedModel` + AW cache would carry."""

    xi: float
    tau_in_unc: float
    tau_out_unc: float
    bankrun: bool
    aw_max: float
    grid: np.ndarray  # the adaptive Stage-1 grid (the root of inheritance)
    hr_grid: np.ndarray
    hr_values: np.ndarray


def _linterp(grid, values):
    """Interpolations.jl LinearInterpolation: linear inside, throw outside
    (we clip instead of throwing; callers stay in range as the reference's
    do, so clipping never actually engages in the compared domain)."""
    return lambda t: np.interp(t, grid, values)


@functools.lru_cache(maxsize=256)
def solve_reference_baseline(
    beta: float = 1.0,
    x0: float = 1e-4,
    u: float = 0.1,
    p: float = 0.5,
    kappa: float = 0.6,
    lam: float = 0.01,
    eta: float = 15.0,
    tspan_end: float | None = None,
    rtol: float = 3e-14,
    max_step: float | None = None,
) -> RefSolution:
    tspan_end = 2.0 * eta if tspan_end is None else tspan_end

    # --- Stage 1: adaptive ODE, linear interpolants on ITS grid ----------
    # scipy clamps rtol at 100·eps; the reference's Tsit5 at reltol=eps
    # achieves h ≈ eps^(1/5) ≈ 7e-4 of the logistic's 1/β transition
    # timescale. max_step = 2e-3/β keeps the emulated grid ~3× COARSER than
    # the true reference grid: the emulator's discretization error then
    # UPPER-BOUNDS the reference's (~9× via the h² interp error), so an
    # sbr-vs-emulator agreement of 1e-6 implies sbr-vs-reference is at
    # least as tight — the conservative direction for a parity oracle.
    # Floored at tspan/20000: a GLOBAL cap of 2e-3/β at β ≫ 1 would force
    # ~β·10⁴ steps across the flat region, while rtol-adaptivity already
    # resolves the 1/β transition at h ≈ (100·eps)^(1/5)/β there.
    if max_step is None:
        max_step = max(2e-3 / beta, tspan_end / 20000.0)
    sol = solve_ivp(
        lambda t, y: beta * y * (1.0 - y),
        (0.0, tspan_end),
        [x0],
        method="RK45",
        rtol=rtol,
        atol=1e-16,
        max_step=max_step,
    )
    grid = sol.t
    g_vals = sol.y[0]
    cdf = _linterp(grid, g_vals)
    pdf_vals = beta * g_vals * (1.0 - g_vals)
    pdf = _linterp(grid, pdf_vals)

    # --- Stage 2: hazard on the inherited grid (solver.jl:153-185) -------
    tau_bar = grid[grid <= eta]
    if len(tau_bar) == 0 or tau_bar[-1] != eta:
        tau_bar = np.append(tau_bar, eta)

    def eg(t):
        return np.exp(lam * t) * pdf(t)

    # the reference's sequential trapezoid loop (solver.jl:172-175):
    # np.cumsum accumulates the same increments in the same order, so the
    # floating-point result is identical to the loop
    eg_vals = eg(tau_bar)
    increments = 0.5 * (eg_vals[:-1] + eg_vals[1:]) * np.diff(tau_bar)
    int_cum = np.concatenate([[0.0], np.cumsum(increments)])
    int_eta = int_cum[-1]
    hr_values = (p * np.exp(lam * tau_bar) * pdf(tau_bar)) / (
        p * int_cum + (1.0 - p) * int_eta
    )

    # --- Stage 2: optimal buffer (solver.jl:211-264) ---------------------
    above = hr_values > u
    if not above.any():
        tau_in_unc = tau_out_unc = tspan_end
    elif above.all():
        tau_in_unc, tau_out_unc = tau_bar[0], tau_bar[-1]
    else:
        tau_in_unc = tspan_end
        for i in range(len(tau_bar) - 1):
            if not above[i] and above[i + 1]:
                t1, t2 = tau_bar[i], tau_bar[i + 1]
                h1, h2 = hr_values[i], hr_values[i + 1]
                tau_in_unc = t1 + (u - h1) * (t2 - t1) / (h2 - h1)
                break
        tau_out_unc = tspan_end
        for i in range(len(tau_bar) - 2, -1, -1):
            if above[i] and not above[i + 1]:
                t1, t2 = tau_bar[i], tau_bar[i + 1]
                h1, h2 = hr_values[i], hr_values[i + 1]
                tau_out_unc = t1 + (u - h1) * (t2 - t1) / (h2 - h1)
                break
        if tau_in_unc == tspan_end and above.any():
            tau_in_unc = tau_bar[np.argmax(above)]
        if tau_out_unc == tspan_end and above.any():
            tau_out_unc = tau_bar[len(above) - 1 - np.argmax(above[::-1])]

    # --- Stage 3: bisection (solver.jl:308-376) --------------------------
    if tau_in_unc == tau_out_unc:  # u above max(HR): trivial no-run
        xi, bankrun = np.nan, False
    else:
        xi, bankrun = _compute_xi_reference(tau_in_unc, tau_out_unc, grid, cdf, kappa)

    # --- AW curve + max (solver.jl:495-532, 566) -------------------------
    aw_max = np.nan
    if bankrun:
        tin_con = min(tau_in_unc, xi)
        tout_con = min(tau_out_unc, xi)
        sh_in = tau_bar - xi + tin_con
        sh_out = tau_bar - xi + tout_con
        aw_in = np.where(sh_in >= 0, cdf(np.maximum(sh_in, 0.0)), 0.0)
        aw_out = np.where(sh_out >= 0, cdf(np.maximum(sh_out, 0.0)), 0.0)
        aw_cum = aw_out - aw_in + cdf(0.0)
        aw_max = float(np.max(aw_cum))

    return RefSolution(
        xi=float(xi),
        tau_in_unc=float(tau_in_unc),
        tau_out_unc=float(tau_out_unc),
        bankrun=bool(bankrun),
        aw_max=aw_max,
        grid=grid,
        hr_grid=tau_bar,
        hr_values=hr_values,
    )


def _compute_xi_reference(tau_in_unc, tau_out_unc, grid, cdf, kappa, max_iters=100):
    """solver.jl:308-376, line by line: midpoint start, tolerance exit at
    10·eps(κ), local-grid-spacing slope epsilon, 5-case logic."""
    xi_min, xi_max = tau_in_unc, tau_out_unc
    xi_new = 0.5 * (tau_in_unc + tau_out_unc)
    tolerance = 10.0 * np.spacing(kappa)
    for it in range(1, max_iters + 1):
        if abs(xi_min - xi_max) < 2.0 * np.spacing(abs(xi_min - xi_max)):
            return np.nan, False  # interval collapsed
        if it == max_iters - 1:
            return np.nan, False  # the reference's early max-iter abort
        xi_old = xi_new
        tin_con = min(tau_in_unc, xi_old)
        tout_con = min(tau_out_unc, xi_old)
        aw = cdf(tout_con) - cdf(tin_con)
        # slope check epsilon = LOCAL grid spacing at ξ (solver.jl:336-339)
        idx = np.searchsorted(grid, xi_old, side="right") - 1
        epsilon = grid[idx + 1] - grid[idx]
        aw_eps = cdf(tout_con + epsilon) - cdf(tin_con + epsilon)
        err = aw - kappa
        if abs(err) <= tolerance:
            if aw_eps >= aw:
                return xi_old, True  # Case 3a: first crossing
            return np.nan, False  # Case 3b: false equilibrium
        if err > 0:
            xi_max = xi_old
            xi_new = 0.5 * (xi_old + xi_min)
        else:
            xi_min = xi_old
            xi_new = 0.5 * (xi_old + xi_max)
    return np.nan, False
