"""Faithful Python emulation of the REFERENCE's baseline numerics.

`tests/oracle.py` bounds sbr_tpu against ideal mathematics (adaptive
quadrature, brentq root-finding at tight tolerance). This module bounds it
against the reference's OWN algorithm (VERDICT r3 missing #1): the scipy
oracle answers "is the TPU build right?", this one answers "would the
reference's figures agree?" — which can differ wherever the reference's
adaptive-grid discretization deviates from ideal math (plausible near the
no-run frontier).

Every stage mirrors the reference implementation step for step:

- Stage 1 (`/root/reference/src/baseline/learning.jl:41-54`): the logistic
  ODE solved by an ADAPTIVE high-order RK pair at machine-level tolerance
  (AutoTsit5(Rosenbrock23) at reltol=abstol=eps() there; scipy's RK45 — the
  same Dormand-Prince family as Tsit5 — at its tightest accepted rtol
  here), with G and the symbolic pdf g=β·G·(1−G) (`learning.jl:161-173`)
  wrapped as LINEAR interpolants on the solver's own adaptive grid.
- Stage 2 hazard (`solver.jl:153-185`): the pdf's grid cut at η (η
  appended), the cumulative integral as a SEQUENTIAL trapezoid loop on that
  inherited grid, HR as a linear interpolant on it.
- Stage 2 buffers (`solver.jl:211-264`): boolean above-threshold scan on
  HR's grid, first ↑ / last ↓ crossing refined by linear interpolation,
  with the reference's exact boundary-case returns.
- Stage 3 (`solver.jl:308-376`): bisection from the midpoint guess with
  tolerance exit at 10·eps(κ), the finite-difference slope check using the
  LOCAL grid spacing at ξ as epsilon, the interval-collapse and
  max-iteration (iter == max_iters-1) aborts, and the 5-case status logic.
- AW curve (`solver.jl:495-532`): shifted-CDF evaluation on HR's grid with
  the t−ξ+τ̄_CON < 0 zeroing and the +G(0) founder offset; AW_max is the
  max over the grid knots (`solver.jl:566`).

The emulator is intentionally slow, host-side scipy/numpy — it exists only
as a differential-test oracle for `tests/test_reference_parity.py`.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np
from scipy.integrate import solve_ivp


@dataclasses.dataclass
class RefSolution:
    """Scalars the reference's `SolvedModel` + AW cache would carry."""

    xi: float
    tau_in_unc: float
    tau_out_unc: float
    bankrun: bool
    aw_max: float
    grid: np.ndarray  # the adaptive Stage-1 grid (the root of inheritance)
    g_values: np.ndarray  # G on that grid (so callers reuse the same CDF)
    hr_grid: np.ndarray
    hr_values: np.ndarray


def _linterp(grid, values):
    """Interpolations.jl LinearInterpolation: linear inside, throw outside
    (we clip instead of throwing; callers stay in range as the reference's
    do, so clipping never actually engages in the compared domain)."""
    return lambda t: np.interp(t, grid, values)


def _hazard_reference(grid, pdf, p, lam, eta):
    """`hazard_rate` (`solver.jl:153-185`): the pdf's grid cut at η (η
    appended), sequential trapezoid of e^{λt}g(t) (np.cumsum accumulates the
    same increments in the same order as the reference's loop, so the
    floating-point result is identical), vectorized HR on the grid.
    Returns (tau_bar, hr_values)."""
    tau_bar = grid[grid <= eta]
    if len(tau_bar) == 0 or tau_bar[-1] != eta:
        tau_bar = np.append(tau_bar, eta)
    eg_vals = np.exp(lam * tau_bar) * pdf(tau_bar)
    increments = 0.5 * (eg_vals[:-1] + eg_vals[1:]) * np.diff(tau_bar)
    int_cum = np.concatenate([[0.0], np.cumsum(increments)])
    int_eta = int_cum[-1]
    hr_values = (p * np.exp(lam * tau_bar) * pdf(tau_bar)) / (
        p * int_cum + (1.0 - p) * int_eta
    )
    return tau_bar, hr_values


def _optimal_buffer_reference(u, tau_bar, hr_values, tspan_end):
    """`optimal_buffer` (`solver.jl:211-264`): boolean scan, first-↑/last-↓
    crossing by linear interpolation, with the exact boundary-case ladder."""
    above = hr_values > u
    if not above.any():
        return tspan_end, tspan_end
    if above.all():
        return tau_bar[0], tau_bar[-1]
    tau_in_unc = tspan_end
    for i in range(len(tau_bar) - 1):
        if not above[i] and above[i + 1]:
            t1, t2 = tau_bar[i], tau_bar[i + 1]
            h1, h2 = hr_values[i], hr_values[i + 1]
            tau_in_unc = t1 + (u - h1) * (t2 - t1) / (h2 - h1)
            break
    tau_out_unc = tspan_end
    for i in range(len(tau_bar) - 2, -1, -1):
        if above[i] and not above[i + 1]:
            t1, t2 = tau_bar[i], tau_bar[i + 1]
            h1, h2 = hr_values[i], hr_values[i + 1]
            tau_out_unc = t1 + (u - h1) * (t2 - t1) / (h2 - h1)
            break
    if tau_in_unc == tspan_end and above.any():
        tau_in_unc = tau_bar[np.argmax(above)]
    if tau_out_unc == tspan_end and above.any():
        tau_out_unc = tau_bar[len(above) - 1 - np.argmax(above[::-1])]
    return tau_in_unc, tau_out_unc


@functools.lru_cache(maxsize=256)
def solve_reference_baseline(
    beta: float = 1.0,
    x0: float = 1e-4,
    u: float = 0.1,
    p: float = 0.5,
    kappa: float = 0.6,
    lam: float = 0.01,
    eta: float = 15.0,
    tspan_end: float | None = None,
    rtol: float = 3e-14,
    max_step: float | None = None,
) -> RefSolution:
    tspan_end = 2.0 * eta if tspan_end is None else tspan_end

    # --- Stage 1: adaptive ODE, linear interpolants on ITS grid ----------
    # scipy clamps rtol at 100·eps; the reference's Tsit5 at reltol=eps
    # achieves h ≈ eps^(1/5) ≈ 7e-4 of the logistic's 1/β transition
    # timescale. max_step = 2e-3/β keeps the emulated grid ~3× COARSER than
    # the true reference grid: the emulator's discretization error then
    # UPPER-BOUNDS the reference's (~9× via the h² interp error), so an
    # sbr-vs-emulator agreement of 1e-6 implies sbr-vs-reference is at
    # least as tight — the conservative direction for a parity oracle.
    # Floored at tspan/20000: a GLOBAL cap of 2e-3/β at β ≫ 1 would force
    # ~β·10⁴ steps across the flat region, while rtol-adaptivity already
    # resolves the 1/β transition at h ≈ (100·eps)^(1/5)/β there.
    if max_step is None:
        max_step = max(2e-3 / beta, tspan_end / 20000.0)
    sol = solve_ivp(
        lambda t, y: beta * y * (1.0 - y),
        (0.0, tspan_end),
        [x0],
        method="RK45",
        rtol=rtol,
        atol=1e-16,
        max_step=max_step,
    )
    grid = sol.t
    g_vals = sol.y[0]
    cdf = _linterp(grid, g_vals)
    pdf_vals = beta * g_vals * (1.0 - g_vals)
    pdf = _linterp(grid, pdf_vals)

    # --- Stage 2: hazard on the inherited grid (solver.jl:153-185) -------
    tau_bar, hr_values = _hazard_reference(grid, pdf, p, lam, eta)

    # --- Stage 2: optimal buffer (solver.jl:211-264) ---------------------
    tau_in_unc, tau_out_unc = _optimal_buffer_reference(
        u, tau_bar, hr_values, tspan_end
    )

    # --- Stage 3: bisection (solver.jl:308-376) --------------------------
    if tau_in_unc == tau_out_unc:  # u above max(HR): trivial no-run
        xi, bankrun = np.nan, False
    else:
        xi, bankrun = _compute_xi_reference(tau_in_unc, tau_out_unc, grid, cdf, kappa)

    # --- AW curve + max (solver.jl:495-532, 566) -------------------------
    aw_max = np.nan
    if bankrun:
        tin_con = min(tau_in_unc, xi)
        tout_con = min(tau_out_unc, xi)
        sh_in = tau_bar - xi + tin_con
        sh_out = tau_bar - xi + tout_con
        aw_in = np.where(sh_in >= 0, cdf(np.maximum(sh_in, 0.0)), 0.0)
        aw_out = np.where(sh_out >= 0, cdf(np.maximum(sh_out, 0.0)), 0.0)
        aw_cum = aw_out - aw_in + cdf(0.0)
        aw_max = float(np.max(aw_cum))

    return RefSolution(
        xi=float(xi),
        tau_in_unc=float(tau_in_unc),
        tau_out_unc=float(tau_out_unc),
        bankrun=bool(bankrun),
        aw_max=aw_max,
        grid=grid,
        g_values=g_vals,
        hr_grid=tau_bar,
        hr_values=hr_values,
    )


def _compute_xi_reference(tau_in_unc, tau_out_unc, grid, cdf, kappa, max_iters=100):
    """solver.jl:308-376, line by line: midpoint start, tolerance exit at
    10·eps(κ), local-grid-spacing slope epsilon, 5-case logic."""
    xi_min, xi_max = tau_in_unc, tau_out_unc
    xi_new = 0.5 * (tau_in_unc + tau_out_unc)
    tolerance = 10.0 * np.spacing(kappa)
    for it in range(1, max_iters + 1):
        if abs(xi_min - xi_max) < 2.0 * np.spacing(abs(xi_min - xi_max)):
            return np.nan, False  # interval collapsed
        if it == max_iters - 1:
            return np.nan, False  # the reference's early max-iter abort
        xi_old = xi_new
        tin_con = min(tau_in_unc, xi_old)
        tout_con = min(tau_out_unc, xi_old)
        aw = cdf(tout_con) - cdf(tin_con)
        # slope check epsilon = LOCAL grid spacing at ξ (solver.jl:336-339)
        idx = np.searchsorted(grid, xi_old, side="right") - 1
        epsilon = grid[idx + 1] - grid[idx]
        aw_eps = cdf(tout_con + epsilon) - cdf(tin_con + epsilon)
        err = aw - kappa
        if abs(err) <= tolerance:
            if aw_eps >= aw:
                return xi_old, True  # Case 3a: first crossing
            return np.nan, False  # Case 3b: false equilibrium
        if err > 0:
            xi_max = xi_old
            xi_new = 0.5 * (xi_old + xi_min)
        else:
            xi_min = xi_old
            xi_new = 0.5 * (xi_old + xi_max)
    return np.nan, False


@dataclasses.dataclass
class RefHeteroSolution:
    """Scalars the reference's `SolvedModelHetero` would carry."""

    xi: float
    tau_in_uncs: np.ndarray  # (K,)
    tau_out_uncs: np.ndarray  # (K,)
    bankrun: bool
    grid: np.ndarray


@functools.lru_cache(maxsize=64)
def solve_reference_hetero(
    betas: tuple,
    dist: tuple,
    x0: float = 1e-4,
    u: float = 0.1,
    p: float = 0.9,
    kappa: float = 0.3,
    lam: float = 0.1,
    eta_bar: float = 30.0,
    rtol: float = 3e-14,
) -> RefHeteroSolution:
    """The reference's heterogeneity pipeline, step for step:

    - coupled K-ODE dG_k = (1-G_k)·β_k·ω, ω = Σ dist_j·G_j, adaptive grid
      (`heterogeneity_learning.jl:49-94`); pdfs symbolic from the rhs;
    - per-group hazard on the SHARED grid (`heterogeneity_solver.jl:255`,
      grid=lr.grid) and per-group buffers via the baseline scan;
    - `compute_ξ_hetero` (`heterogeneity_solver.jl:48-144`): weighted-AW
      bisection from the dist-weighted midpoint guess over [0, 2·max τ̄_OUT],
      ABSOLUTE tolerance 1e-12, max 500 iterations, shared-grid slope
      epsilon, plus `is_valid_equilibrium_hetero`'s backward first-crossing
      scan (`:175-210`) on convergence.
    """
    betas = np.asarray(betas, float)
    dist = np.asarray(dist, float)
    k = len(betas)
    beta_avg = float(np.sum(dist * betas))
    eta = eta_bar / beta_avg
    tspan_end = 2.0 * eta

    def rhs(t, g):
        omega = np.sum(dist * g)
        return (1.0 - g) * betas * omega

    max_step = max(2e-3 / beta_avg, tspan_end / 20000.0)
    sol = solve_ivp(
        rhs, (0.0, tspan_end), [x0] * k, method="RK45",
        rtol=rtol, atol=1e-16, max_step=max_step,
    )
    grid = sol.t
    cdf_vals = sol.y  # (K, n)
    omega_vals = dist @ cdf_vals
    pdf_vals = (1.0 - cdf_vals) * betas[:, None] * omega_vals[None, :]
    cdfs = [_linterp(grid, cdf_vals[j]) for j in range(k)]
    pdfs = [_linterp(grid, pdf_vals[j]) for j in range(k)]

    tau_in_uncs = np.zeros(k)
    tau_out_uncs = np.zeros(k)
    for j in range(k):
        tau_bar, hr_values = _hazard_reference(grid, pdfs[j], p, lam, eta)
        tau_in_uncs[j], tau_out_uncs[j] = _optimal_buffer_reference(
            u, tau_bar, hr_values, tspan_end
        )

    if np.all(tau_in_uncs == tau_out_uncs):
        return RefHeteroSolution(np.nan, tau_in_uncs, tau_out_uncs, False, grid)

    xi, ok = _compute_xi_hetero_reference(
        tau_in_uncs, tau_out_uncs, dist, cdfs, grid, kappa
    )
    return RefHeteroSolution(float(xi), tau_in_uncs, tau_out_uncs, bool(ok), grid)


def _compute_xi_hetero_reference(
    tau_in_uncs, tau_out_uncs, dist, cdfs, grid, kappa, max_iters=500, tol=1e-12
):
    """`compute_ξ_hetero` (`heterogeneity_solver.jl:48-144`) line by line."""
    k = len(dist)
    xi_new = float(np.sum(dist * (tau_in_uncs + tau_out_uncs) / 2.0))
    xi_min, xi_max = 0.0, float(np.max(tau_out_uncs)) * 2.0
    for it in range(1, max_iters + 1):
        if abs(xi_min - xi_max) < 2.0 * np.spacing(abs(xi_min - xi_max)):
            return np.nan, False
        if it == max_iters - 1:
            return np.nan, False
        xi_old = xi_new
        idx = np.searchsorted(grid, xi_old, side="right") - 1
        eps = grid[min(idx + 1, len(grid) - 1)] - grid[idx]
        aw = aw_eps = 0.0
        for j in range(k):
            tin = min(tau_in_uncs[j], xi_old)
            tout = min(tau_out_uncs[j], xi_old)
            aw += dist[j] * (cdfs[j](tout) - cdfs[j](tin))
            aw_eps += dist[j] * (cdfs[j](tout + eps) - cdfs[j](tin + eps))
        err = aw - kappa
        if abs(err) <= tol:
            if aw_eps >= aw:
                if not _is_valid_equilibrium_hetero_reference(
                    xi_old, tau_in_uncs, cdfs, grid, kappa, dist
                ):
                    return np.nan, False
                return xi_old, True
            return np.nan, False
        if err > 0:
            xi_max = xi_old
            xi_new = 0.5 * (xi_old + xi_min)
        else:
            xi_min = xi_old
            xi_new = 0.5 * (xi_old + xi_max)
    return np.nan, False


def _is_valid_equilibrium_hetero_reference(xi_star, tau_in_uncs, cdfs, grid, kappa, dist):
    """`is_valid_equilibrium_hetero` (`heterogeneity_solver.jl:175-210`):
    backward scan of AW(t; ξ*) for a ↓crossing of κ before ξ*."""
    g = grid[grid <= xi_star]
    if len(g) == 0:
        return True
    aw_path = np.zeros(len(g))
    for j in range(len(dist)):
        tau_i = max(0.0, xi_star - tau_in_uncs[j])
        aw_path += dist[j] * (cdfs[j](g) - cdfs[j](np.maximum(0.0, g - tau_i)))
    above = aw_path > kappa
    for i in range(len(g) - 2, -1, -1):
        if above[i] and not above[i + 1]:
            return False
    return True


@dataclasses.dataclass
class RefInterestSolution:
    """Scalars the reference's `SolvedModelInterest` would carry."""

    xi: float
    tau_in_unc: float
    tau_out_unc: float
    bankrun: bool
    v0: float  # V at τ̄=0 (the boundary value)


@functools.lru_cache(maxsize=64)
def solve_reference_interest(
    beta: float = 1.0,
    x0: float = 1e-4,
    u: float = 0.0,
    p: float = 0.5,
    kappa: float = 0.6,
    lam: float = 0.01,
    eta: float = 15.0,
    r: float = 0.06,
    delta: float = 0.1,
    tspan_end: float | None = None,
    rtol: float = 3e-14,
) -> RefInterestSolution:
    """The reference's interest-rate pipeline (`interest_rate_solver.jl:51-150`):
    baseline hazard, the HJB V′(τ̄)=(h+δ)(1−V)+max(u+rV−h,0) with boundary
    V(0)=(u+δ)/(r+δ) solved adaptively against the LINEAR-INTERPOLATED
    hazard and saved on HR's grid (`value_function_solver.jl:66-112`),
    effective hazard h−rV, then the baseline buffers/ξ machinery unchanged.
    """
    tspan_end = 2.0 * eta if tspan_end is None else tspan_end
    base = solve_reference_baseline(
        beta=beta, x0=x0, u=u, p=p, kappa=kappa, lam=lam, eta=eta,
        tspan_end=tspan_end, rtol=rtol,
    )
    tau_bar, hr_values = base.hr_grid, base.hr_values
    hr_interp = _linterp(tau_bar, hr_values)
    v0 = (u + delta) / (r + delta)

    def hjb(t, v):
        h = hr_interp(t)
        return (h + delta) * (1.0 - v) + np.maximum(u + r * v - h, 0.0)

    sol = solve_ivp(
        hjb, (0.0, tau_bar[-1]), [v0], method="RK45",
        rtol=rtol, atol=1e-16, t_eval=tau_bar,
        max_step=max(2e-3 / beta, tau_bar[-1] / 20000.0),
    )
    v_values = sol.y[0]
    h_eff = hr_values - r * v_values

    tau_in_unc, tau_out_unc = _optimal_buffer_reference(
        u, tau_bar, h_eff, tspan_end
    )
    if tau_in_unc == tau_out_unc:
        return RefInterestSolution(np.nan, tau_in_unc, tau_out_unc, False, v0)
    # baseline ξ machinery on the word-of-mouth CDF
    # (`interest_rate_solver.jl:122`), reusing the base solve's exact grid
    # and G values — the same inheritance the reference gets for free
    cdf = _linterp(base.grid, base.g_values)
    xi, bankrun = _compute_xi_reference(tau_in_unc, tau_out_unc, base.grid, cdf, kappa)
    return RefInterestSolution(
        float(xi), float(tau_in_unc), float(tau_out_unc), bool(bankrun), v0
    )


@dataclasses.dataclass
class RefSocialSolution:
    """What the reference's social fixed point returns (the last inner
    `SolvedModel`) plus the loop metadata it prints but drops."""

    xi: float
    bankrun: bool
    converged: bool
    iterations: int
    error: float


@functools.lru_cache(maxsize=16)
def solve_reference_social(
    beta: float = 0.9,
    x0: float = 1e-4,
    u: float = 0.5,
    p: float = 0.99,
    kappa: float = 0.25,
    lam: float = 0.25,
    eta_bar: float = 30.0,
    tol: float = 1e-4,
    max_iter: int = 500,
    # ~50 adaptive solves at rtol 3e-14 cost 140+ s for a fixed point whose
    # own stopping tolerance is 1e-4; 1e-10 keeps Stage-1 fidelity 4+
    # orders below the comparison tolerance at ~5x fewer RK steps
    rtol: float = 1e-10,
) -> RefSocialSolution:
    """The reference's social-learning fixed point
    (`social_learning_solver.jl:63-263`), iteration for iteration:

    - tspan overridden to (0, η); AW⁽⁰⁾ = the baseline word-of-mouth CDF;
    - per iteration: the forced ODE dG = (1−G)·β·AW⁽ⁿ⁻¹⁾(t) on an adaptive
      grid (`social_learning_dynamics.jl:58-78`), pdf symbolic from the
      rhs, then the FULL baseline Stage-2/3 on that grid;
    - inner no-run: ξ⁽ⁿ⁾ = ξ⁽ⁿ⁻¹⁾ + η/500, aborting past η;
    - convergence: sup-norm of the UNDAMPED candidate vs the previous AW on
      a fixed 1000-point comparison grid; else damp α = 0.5 ON THE CDF GRID.
    """
    eta = eta_bar / beta
    # much coarser grid floor than the scalar-parity emulators: the fixed
    # point is compared at its own 1e-4 stopping tolerance (ξ to ~1e-3);
    # grid interp error at h = η/2000 is ~1e-5, far below that, and this
    # loop pays ~50 adaptive solves (measured: the η/20000 floor cost 138 s
    # of test time for a ξ identical to 6 decimals)
    max_step = max(2e-3 / beta, eta / 2000.0)
    grid_comp = np.linspace(0.0, eta, 1000)

    # init: word-of-mouth baseline learning (`:90-94`)
    sol0 = solve_ivp(
        lambda t, y: beta * y * (1.0 - y), (0.0, eta), [x0],
        method="RK45", rtol=rtol, atol=1e-16, max_step=max_step,
    )
    aw_old = _linterp(sol0.t, sol0.y[0])

    xi_new = 0.0
    converged = False
    last = (np.nan, False)
    it = 0
    err = np.inf
    for it in range(1, max_iter + 1):
        xi_old = xi_new
        # (a) forced learning from withdrawals
        sol = solve_ivp(
            lambda t, y: (1.0 - y) * beta * aw_old(t), (0.0, eta), [x0],
            method="RK45", rtol=rtol, atol=1e-16, max_step=max_step,
        )
        cdf_grid = sol.t
        g_vals = sol.y[0]
        cdf = _linterp(cdf_grid, g_vals)
        pdf = _linterp(cdf_grid, (1.0 - g_vals) * beta * aw_old(cdf_grid))

        # (b) full baseline Stage 2/3 on the inherited grid
        tau_bar, hr_values = _hazard_reference(cdf_grid, pdf, p, lam, eta)
        tin, tout = _optimal_buffer_reference(u, tau_bar, hr_values, eta)
        if tin == tout:
            xi, bankrun = np.nan, False
        else:
            xi, bankrun = _compute_xi_reference(tin, tout, cdf_grid, cdf, kappa)
        last = (xi, bankrun)

        # (c) candidate AW via get_AW on HR's grid (`:164,198`)
        if not bankrun:
            xi_new = xi_old + eta / 500.0
            if xi_new > eta:
                break  # aborted (`:155-160`)
        else:
            xi_new = xi
        tin_con = min(tin, xi_new)
        tout_con = min(tout, xi_new)
        sh_in = tau_bar - xi_new + tin_con
        sh_out = tau_bar - xi_new + tout_con
        aw_in = np.where(sh_in >= 0, cdf(np.maximum(sh_in, 0.0)), 0.0)
        aw_out = np.where(sh_out >= 0, cdf(np.maximum(sh_out, 0.0)), 0.0)
        aw_new = _linterp(tau_bar, aw_out - aw_in + cdf(0.0))

        # (d) convergence on the UNDAMPED candidate (`:168-171,202-203`)
        err = float(np.max(np.abs(aw_new(grid_comp) - aw_old(grid_comp))))
        if err < tol:
            converged = True
            break
        # (e) damp on the CDF grid (`:183-187,222-227`)
        damped = 0.5 * aw_old(cdf_grid) + 0.5 * aw_new(cdf_grid)
        aw_old = _linterp(cdf_grid, damped)

    xi_final, bankrun_final = last
    return RefSocialSolution(
        xi=float(xi_final),
        bankrun=bool(bankrun_final),
        converged=bool(converged),
        iterations=it,
        error=err,
    )
