"""Distributed request tracing tests (ISSUE 16): sampling semantics,
writer atomicity + torn/interleaved-line tolerance, trace-file GC, the
engine/endpoint span pipeline, `report trace` join gating, `report slo`
breach gating, and the zero-XLA-trace + bit-identity acceptance witnesses.

The engine tests solve tiny SolverConfig programs (bucket (1,), n_grid 96)
so each compiles in seconds on CPU; everything here is tier-1."""

import json
import threading
import time
from pathlib import Path

import pytest

from sbr_tpu.models.params import SolverConfig, make_model_params
from sbr_tpu.obs import trace as qtrace
from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LabeledHistograms
from sbr_tpu.obs.report import slo_doc, trace_doc

CFG = SolverConfig(n_grid=96, bisect_iters=30, refine_crossings=False)


# ---------------------------------------------------------------------------
# Sampling semantics
# ---------------------------------------------------------------------------


class TestSampling:
    def test_default_off_mints_nothing(self, monkeypatch):
        monkeypatch.delenv("SBR_TRACE_SAMPLE", raising=False)
        assert qtrace.sample_rate() == 0.0
        assert qtrace.mint("worker") is None

    def test_rate_zero_hard_off(self, monkeypatch):
        monkeypatch.setenv("SBR_TRACE_SAMPLE", "0")
        assert qtrace.mint("router") is None

    def test_rate_one_always_keeps(self, monkeypatch):
        monkeypatch.setenv("SBR_TRACE_SAMPLE", "1")
        ctx = qtrace.mint("router")
        assert ctx is not None and ctx.keep

    def test_garbage_rate_is_off(self, monkeypatch):
        monkeypatch.setenv("SBR_TRACE_SAMPLE", "definitely")
        assert qtrace.sample_rate() == 0.0

    def test_keep_decision_deterministic(self):
        tid = qtrace.new_trace_id()
        votes = {qtrace.keep_decision(tid, 0.3) for _ in range(10)}
        assert len(votes) == 1  # router and workers agree without talking
        assert qtrace.keep_decision(tid, 1.0) is True
        assert qtrace.keep_decision(tid, 0.0) is False

    def test_keep_decision_tracks_rate(self):
        ids = [qtrace.new_trace_id() for _ in range(400)]
        kept = sum(qtrace.keep_decision(t, 0.5) for t in ids)
        assert 100 < kept < 300  # hash-uniform, loose bounds

    def test_header_presence_wins_over_local_rate(self, monkeypatch):
        monkeypatch.setenv("SBR_TRACE_SAMPLE", "0")
        ctx = qtrace.from_headers("abc123", "ff00ff00", service="worker")
        assert ctx is not None and ctx.keep
        assert ctx.trace_id == "abc123"
        assert ctx.remote_parent == "ff00ff00"

    def test_no_header_no_rate_no_context(self, monkeypatch):
        monkeypatch.delenv("SBR_TRACE_SAMPLE", raising=False)
        assert qtrace.from_headers(None, None) is None

    def test_add_drops_none_and_reserved_attrs(self):
        ctx = qtrace.TraceContext("t" * 16, service="x")
        sid = ctx.add("a.b", time.time(), 0.001, degraded=None, n=3,
                      trace="spoof")
        (rec,) = ctx.spans
        assert rec["span"] == sid
        assert rec["trace"] == "t" * 16  # reserved key not overridable
        assert "degraded" not in rec and rec["n"] == 3


# ---------------------------------------------------------------------------
# Writer: atomic append, exemplars, torn + interleaved lines, rotation, GC
# ---------------------------------------------------------------------------


def _commit_one(run_dir, tid="a1b2c3d4e5f60718", keep=True, exemplar=False,
                n_spans=2):
    ctx = qtrace.TraceContext(tid, keep=keep, service="test")
    t0 = time.time()
    for i in range(n_spans):
        ctx.add(f"layer.{i}", t0, 0.001 * (i + 1))
    w = qtrace.TraceWriter(run_dir)
    wrote = w.commit(ctx, exemplar=exemplar)
    w.close()
    return wrote


class TestWriter:
    def test_commit_and_load_roundtrip(self, tmp_path):
        assert _commit_one(tmp_path, n_spans=3)
        spans, bad = qtrace.load_spans(tmp_path)
        assert len(spans) == 3 and bad == 0
        assert all(s["trace"] == "a1b2c3d4e5f60718" for s in spans)

    def test_head_dropped_trace_not_written(self, tmp_path):
        assert not _commit_one(tmp_path, keep=False)
        assert not (tmp_path / qtrace.TRACE_FILE).exists()

    def test_exemplar_overrides_drop_and_marks(self, tmp_path):
        assert _commit_one(tmp_path, keep=False, exemplar=True)
        spans, _ = qtrace.load_spans(tmp_path)
        assert spans and all(s.get("exemplar") for s in spans)

    def test_kept_trace_not_marked_exemplar(self, tmp_path):
        assert _commit_one(tmp_path, keep=True, exemplar=True)
        spans, _ = qtrace.load_spans(tmp_path)
        assert spans and not any("exemplar" in s for s in spans)

    def test_torn_final_line_counted_not_fatal(self, tmp_path):
        _commit_one(tmp_path, n_spans=2)
        path = tmp_path / qtrace.TRACE_FILE
        raw = path.read_bytes()
        # kill -9 mid-append: final line cut inside the JSON (and inside a
        # UTF-8 continuation for good measure)
        path.write_bytes(raw + b'{"trace": "deadbeef", "sp\xc3')
        spans, bad = qtrace.load_spans(tmp_path)
        assert len(spans) == 2 and bad == 1

    def test_non_dict_and_missing_key_lines_counted(self, tmp_path):
        path = tmp_path / qtrace.TRACE_FILE
        path.write_text('[1, 2]\n{"trace": "x"}\n{"trace": "t", "span": "s"}\n')
        spans, bad = qtrace.load_spans(tmp_path)
        assert len(spans) == 1 and bad == 2

    def test_thread_interleaved_commits_all_parse(self, tmp_path):
        writer = qtrace.TraceWriter(tmp_path)
        n_threads, per_thread = 8, 25

        def work(k):
            for i in range(per_thread):
                ctx = qtrace.TraceContext(f"{k:08x}{i:08x}", service="test")
                t0 = time.time()
                ctx.add("alpha", t0, 0.001, k=k)
                ctx.add("beta", t0, 0.002, i=i)
                writer.commit(ctx)

        threads = [threading.Thread(target=work, args=(k,)) for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        writer.close()
        spans, bad = qtrace.load_spans(tmp_path)
        assert bad == 0  # whole-line atomic append: no torn interleavings
        assert len(spans) == n_threads * per_thread * 2

    def test_rotation_bounds_active_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SBR_TRACE_MAX_MB", "0.0000001")  # floor: 64 KiB
        writer = qtrace.TraceWriter(tmp_path)
        for i in range(60):
            ctx = qtrace.TraceContext(f"{i:016x}", service="test")
            t0 = time.time()
            for j in range(20):
                ctx.add(f"layer.{j}", t0, 0.001, filler="x" * 64)
            writer.commit(ctx)
        writer.close()
        rotated = list(tmp_path.glob("trace.*.jsonl"))
        assert rotated, "rotation never fired"
        assert (tmp_path / qtrace.TRACE_FILE).stat().st_size < 2 * (1 << 16)
        # Nothing lost across the rotation boundary
        spans, bad = qtrace.load_spans(tmp_path)
        assert bad == 0 and len(spans) == 60 * 20

    def test_writer_registry_singleton_and_summary(self, tmp_path):
        w1 = qtrace.writer_for(tmp_path)
        w2 = qtrace.writer_for(str(tmp_path))
        assert w1 is w2
        assert qtrace.writer_for(None) is None
        ctx = qtrace.TraceContext("f" * 16, service="test")
        ctx.add("x", time.time(), 0.001)
        w1.commit(ctx)
        assert qtrace.summary_for(tmp_path)["traces"] == 1
        counters = qtrace.close_for(tmp_path)
        assert counters["spans"] == 1
        assert qtrace.summary_for(tmp_path) is None  # forgotten after close


class TestTraceGC:
    def _mk_run(self, root, name, status="complete", rotated=3, mtime=None):
        d = root / name
        d.mkdir(parents=True)
        (d / "manifest.json").write_text(json.dumps({"status": status}))
        for i in range(rotated):
            p = d / f"trace.{i + 1:03d}.jsonl"
            p.write_text('{"trace": "t", "span": "s"}\n')
            t = (mtime or time.time()) + i
            import os

            os.utime(p, (t, t))
        (d / "trace.jsonl").write_text('{"trace": "t", "span": "s"}\n')
        return d

    def test_prunes_rotated_keeps_active_and_newest(self, tmp_path):
        d = self._mk_run(tmp_path, "run_a", rotated=3, mtime=time.time() - 60)
        removed = qtrace.gc_trace_files(tmp_path, keep_rotated=1)
        assert len(removed) == 2
        assert (d / "trace.jsonl").exists()
        assert (d / "trace.003.jsonl").exists()  # the newest rotated file
        assert not (d / "trace.001.jsonl").exists()

    def test_live_run_untouched(self, tmp_path):
        d = self._mk_run(tmp_path, "run_live", status="running", rotated=3)
        assert qtrace.gc_trace_files(tmp_path, keep_rotated=0) == []
        assert len(list(d.glob("trace.*.jsonl"))) == 3

    def test_report_gc_trace_keep_flag(self, tmp_path):
        import subprocess
        import sys

        self._mk_run(tmp_path, "run_b", rotated=2, mtime=time.time() - 60)
        proc = subprocess.run(
            [sys.executable, "-m", "sbr_tpu.obs.report", "gc", str(tmp_path),
             "--keep", "10", "--trace-keep", "0"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0
        assert "2 rotated trace span file(s)" in proc.stdout


# ---------------------------------------------------------------------------
# Per-layer histograms (the /metrics satellite)
# ---------------------------------------------------------------------------


class TestLayerHistograms:
    def test_labeled_histograms_record_and_export(self):
        h = LabeledHistograms(DEFAULT_LATENCY_BOUNDS_MS)
        h.record("engine.queue", 1.0)
        h.record("engine.queue", 2.0)
        h.record("engine.dispatch", 50.0)
        summ = h.summaries()
        assert summ["engine.queue"]["count"] == 2
        text = "\n".join(h.to_prometheus("sbr_trace_span_ms", label_key="layer"))
        assert 'layer="engine.queue"' in text
        assert text.count("# TYPE") == 1  # one header for the family

    def test_commit_folds_into_process_histograms(self, tmp_path):
        before = qtrace.layer_histograms().summaries().get(
            "test.fold", {}
        ).get("count", 0)
        ctx = qtrace.TraceContext("e" * 16, service="test")
        ctx.add("test.fold", time.time(), 0.005)
        w = qtrace.TraceWriter(tmp_path)
        w.commit(ctx)
        w.close()
        after = qtrace.layer_histograms().summaries()["test.fold"]["count"]
        assert after == before + 1


# ---------------------------------------------------------------------------
# report trace / report slo (synthetic spans — no engine)
# ---------------------------------------------------------------------------


def _write_spans(run_dir, spans):
    Path(run_dir).mkdir(parents=True, exist_ok=True)
    with open(Path(run_dir) / qtrace.TRACE_FILE, "a") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")


def _span(trace, span, parent, name, svc, ts, dur_ms, **attrs):
    return {"trace": trace, "span": span, "parent": parent, "name": name,
            "svc": svc, "ts": ts, "dur_ms": dur_ms, **attrs}


def _fleet_trace(router_dir, worker_dir, tid="11aa22bb33cc44dd", t0=1000.0,
                 forward_outcome="ok"):
    """One synthetic cross-process trace: router root + forward, worker
    request + engine child — the join the aggregator must reassemble."""
    _write_spans(router_dir, [
        _span(tid, "r0000001", None, "router.request", "router", t0, 100.0,
              status=200, outcome="completed"),
        _span(tid, "rf000001", "r0000001", "router.forward", "router",
              t0 + 1e-3, 98.0, worker="w1", outcome=forward_outcome),
    ])
    _write_spans(worker_dir, [
        _span(tid, "w0000001", "rf000001", "worker.request", "worker",
              t0 + 2e-3, 95.0, status=200),
        _span(tid, "e0000001", "w0000001", "engine.query", "worker",
              t0 + 3e-3, 90.0, source="computed"),
    ])


class TestReportTrace:
    def test_cross_dir_join_and_coverage(self, tmp_path):
        r, w = tmp_path / "router", tmp_path / "w0"
        _fleet_trace(r, w)
        doc, code = trace_doc([str(r), str(w)])
        assert code == 0
        assert doc["traces"] == 1 and doc["joined"] == 1
        assert doc["coverage_min"] > 0.9
        # With a single trace the duration-weighted figure equals it.
        assert doc["coverage_weighted"] == doc["coverage_min"]
        (wf,) = doc["waterfalls"]
        names = [row["name"] for row in wf["rows"]]
        assert names == ["router.request", "router.forward",
                         "worker.request", "engine.query"]

    def test_orphaned_sampled_trace_gates_exit_1(self, tmp_path):
        d = tmp_path / "router"
        _write_spans(d, [
            _span("ab" * 8, "r1", None, "router.request", "router", 1.0, 10.0),
            _span("ab" * 8, "x1", "missing0", "engine.query", "worker", 1.0, 5.0),
        ])
        doc, code = trace_doc([str(d)])
        assert code == 1
        assert doc["unjoined_traces"] == ["ab" * 8]

    def test_orphaned_exemplar_trace_tolerated(self, tmp_path):
        # A worker-side SLO-breach exemplar may legitimately miss its
        # router half (head-dropped there) — never a join failure.
        d = tmp_path / "w0"
        _write_spans(d, [
            _span("cd" * 8, "w1", "gone0001", "worker.request", "worker",
                  1.0, 10.0, exemplar=True),
        ])
        doc, code = trace_doc([str(d)])
        assert code == 0 and doc["exemplar_traces"] == 1

    def test_failover_and_hedge_counted(self, tmp_path):
        r, w = tmp_path / "router", tmp_path / "w0"
        _fleet_trace(r, w, tid="11" * 8, forward_outcome="error")
        _write_spans(r, [_span("11" * 8, "rf2", "r0000001", "router.forward",
                               "router", 1000.05, 40.0, worker="w2",
                               outcome="ok", role="hedge")])
        doc, code = trace_doc([str(r), str(w)])
        assert doc["failover_traces"] == 1
        assert doc["hedged_traces"] == 1

    def test_no_spans_exit_3_bad_dir_exit_2(self, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        _, code = trace_doc([str(empty)])
        assert code == 3
        _, code = trace_doc([str(tmp_path / "nope")])
        assert code == 2

    def test_torn_line_surfaced_as_bad_span_lines(self, tmp_path):
        d = tmp_path / "w0"
        _fleet_trace(d, d)
        with open(d / qtrace.TRACE_FILE, "ab") as fh:
            fh.write(b'{"trace": "torn')
        doc, code = trace_doc([str(d)])
        assert code == 0 and doc["bad_span_lines"] == 1


class TestReportSlo:
    def _live(self, d, slo_ms):
        Path(d).mkdir(parents=True, exist_ok=True)
        (Path(d) / "live.json").write_text(
            json.dumps({"slo": {"slo_ms": slo_ms}})
        )

    def test_breach_gates_exit_1_with_causality(self, tmp_path):
        r, w = tmp_path / "router", tmp_path / "w0"
        _fleet_trace(r, w, forward_outcome="error")  # e2e 100 ms
        self._live(r, 50.0)
        doc, code = slo_doc([str(r), str(w)])
        assert code == 1
        assert doc["breach_causality"]["breaches"] == 1
        assert doc["breach_causality"]["failover"] == 1
        (b,) = doc["breach_exemplars"]
        assert b["slo_ms"] == 50.0 and b["slowest_layer"] == "router.forward"

    def test_under_slo_exit_0_with_layer_table(self, tmp_path):
        r, w = tmp_path / "router", tmp_path / "w0"
        _fleet_trace(r, w)
        self._live(r, 5000.0)
        doc, code = slo_doc([str(r), str(w)])
        assert code == 0
        assert doc["layers"]["engine.query"]["count"] == 1
        assert doc["dirs"][0]["slo_ms"] == 5000.0

    def test_exemplar_mark_is_a_breach_verdict(self, tmp_path):
        d = tmp_path / "w0"
        _write_spans(d, [
            _span("ee" * 8, "w1", None, "worker.request", "worker", 1.0,
                  10.0, exemplar=True),
        ])
        doc, code = slo_doc([str(d)])
        assert code == 1 and doc["breach_exemplars"][0]["exemplar"]

    def test_nothing_to_judge_exit_3(self, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        _, code = slo_doc([str(empty)])
        assert code == 3


# ---------------------------------------------------------------------------
# Engine + endpoint integration (the expensive block: one shared engine)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_serve(tmp_path_factory):
    """One engine + HTTP endpoint with tracing at rate 1, run dir attached.
    Module-scoped: every integration test shares the compiled bucket."""
    import os

    from sbr_tpu import obs
    from sbr_tpu.serve.endpoint import ServeEndpoint
    from sbr_tpu.serve.engine import Engine, ServeConfig

    run_dir = tmp_path_factory.mktemp("trace_run")
    old = os.environ.get("SBR_TRACE_SAMPLE")
    os.environ["SBR_TRACE_SAMPLE"] = "1"
    run = obs.start_run(label="trace_it", run_dir=str(run_dir))
    eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)), run=run)
    eng.start()
    ep = ServeEndpoint(eng).start()
    try:
        yield eng, ep, run_dir
    finally:
        ep.close()
        eng.close()
        obs.end_run()
        if old is None:
            os.environ.pop("SBR_TRACE_SAMPLE", None)
        else:
            os.environ["SBR_TRACE_SAMPLE"] = old


def _post(port, doc, headers=None):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/query", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _spans_for(run_dir, tid, timeout_s=10.0):
    """Poll for a trace's spans: the endpoint commits in its handler's
    ``finally`` — AFTER the response bytes reach the client — so an
    immediate read races the writer."""
    deadline = time.monotonic() + timeout_s
    while True:
        spans, _ = qtrace.load_spans(run_dir)
        mine = [s for s in spans if s["trace"] == tid]
        if mine or time.monotonic() > deadline:
            return mine
        time.sleep(0.02)


class TestServeIntegration:
    def test_direct_hit_mints_and_joins(self, traced_serve):
        eng, ep, run_dir = traced_serve
        code, doc, hdrs = _post(ep.port, {"beta": 1.5, "u": 0.2})
        assert code == 200
        tid = doc["trace_id"]
        assert tid and hdrs[qtrace.TRACE_HEADER] == tid
        mine = _spans_for(run_dir, tid)
        names = {s["name"] for s in mine}
        assert {"worker.request", "engine.query", "engine.admission",
                "engine.queue", "engine.cache", "engine.dispatch"} <= names
        rdoc, rcode = trace_doc([str(run_dir)])
        assert rcode == 0
        mine_row = [e for e in rdoc["trace_table"] if e["trace"] == tid]
        # A warm in-process query finishes in single-digit ms, where the
        # endpoint's fixed parse/respond overhead is a visible slice; the
        # >= 0.95 acceptance floor is gated in the fleet chaos smoke
        # (realistic HTTP round trips), not on this micro request.
        assert mine_row and mine_row[0]["coverage"] >= 0.75

    def test_inbound_header_adopted_and_parented(self, traced_serve):
        eng, ep, run_dir = traced_serve
        tid, fid = "12" * 8, "34" * 4
        code, doc, _ = _post(
            ep.port, {"beta": 1.5, "u": 0.21},
            headers={qtrace.TRACE_HEADER: tid, qtrace.PARENT_HEADER: fid},
        )
        assert code == 200 and doc["trace_id"] == tid
        mine = _spans_for(run_dir, tid)
        root = [s for s in mine if s["name"] == "worker.request"]
        assert root and root[0]["parent"] == fid  # the cross-process edge

    def test_warm_traced_queries_add_zero_xla_traces(self, traced_serve):
        from sbr_tpu.obs import prof

        eng, ep, run_dir = traced_serve
        _post(ep.port, {"beta": 1.5, "u": 0.22})  # compile + fill cache
        before = dict(prof.trace_counts())
        for _ in range(3):
            code, doc, _ = _post(ep.port, {"beta": 1.5, "u": 0.22})
            assert code == 200 and doc["source"] in ("lru", "disk")
        assert dict(prof.trace_counts()) == before

    def test_cache_hit_span_says_lru(self, traced_serve):
        eng, ep, run_dir = traced_serve
        _post(ep.port, {"beta": 1.5, "u": 0.23})
        code, doc, _ = _post(ep.port, {"beta": 1.5, "u": 0.23})
        assert doc["source"] == "lru"
        mine = _spans_for(run_dir, doc["trace_id"])
        cache = [s for s in mine if s["name"] == "engine.cache"]
        assert cache and cache[0]["lru"] == "hit"
        # LRU hits never touch the batcher: no dispatch span, and the
        # queue/cache spans still cover the engine.query interval.
        assert not any(s["name"] == "engine.dispatch" for s in mine)


class TestBitIdentityWhenOff:
    def test_untraced_engine_answers_bit_identical(self, monkeypatch, tmp_path):
        """SBR_TRACE_SAMPLE=0 must be indistinguishable from a traced run
        in every served byte (the acceptance's bit-identity witness)."""
        from sbr_tpu.serve.engine import Engine, ServeConfig

        import numpy as np

        params = [make_model_params(beta=1.1 + 0.1 * i, u=0.2) for i in range(3)]

        def run_mix(rate):
            monkeypatch.setenv("SBR_TRACE_SAMPLE", rate)
            eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)))
            eng.start()
            try:
                out = eng.query_many(params, scenario="bitid")
            finally:
                eng.close()
            return [
                (np.float64(r.xi).tobytes(), np.float64(r.tau_bar_in).tobytes(),
                 np.float64(r.aw_max).tobytes(), r.status, r.flags, r.source)
                for r in out
            ]

        assert run_mix("0") == run_mix("1")

    def test_off_leaves_no_trace_artifacts(self, monkeypatch, tmp_path):
        import urllib.request

        from sbr_tpu import obs
        from sbr_tpu.serve.endpoint import ServeEndpoint
        from sbr_tpu.serve.engine import Engine, ServeConfig

        monkeypatch.setenv("SBR_TRACE_SAMPLE", "0")
        run = obs.start_run(label="untraced", run_dir=str(tmp_path / "run"))
        eng = Engine(config=CFG, serve=ServeConfig(buckets=(1,)), run=run)
        eng.start()
        ep = ServeEndpoint(eng).start()
        try:
            code, doc, hdrs = _post(ep.port, {"beta": 1.5, "u": 0.2})
        finally:
            ep.close()
            eng.close()
            obs.end_run()
        assert code == 200
        assert "trace_id" not in doc
        assert qtrace.TRACE_HEADER not in hdrs
        assert not (tmp_path / "run" / qtrace.TRACE_FILE).exists()
