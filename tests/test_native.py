"""Tests for the native (C++/ctypes) graph preprocessing layer."""

import numpy as np
import pytest

from sbr_tpu import native


def _numpy_reference(src, dst, n):
    order = np.argsort(dst, kind="stable")
    src_s, dst_s = src[order], dst[order]
    indeg = np.bincount(dst, minlength=n).astype(np.int32)
    row_ptr = np.zeros(n + 1, np.int64)
    np.cumsum(indeg, out=row_ptr[1:])
    return src_s, dst_s, indeg, row_ptr


def test_native_library_builds():
    """Where g++ exists the native path must come up; without a compiler the
    numpy fallback is the designed behavior, not a failure."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++ on this host — numpy fallback is expected")
    assert native.native_available()


def test_sbr_native_env_gate(monkeypatch):
    """SBR_NATIVE=0 disables the native library per CALL (the bench's
    host-numpy control measures the portable path alongside the native one
    in a single process), and unsetting it restores whatever the build
    produced."""
    monkeypatch.delenv("SBR_NATIVE", raising=False)  # baseline = build result
    before = native.get_lib()
    monkeypatch.setenv("SBR_NATIVE", "0")
    assert native.get_lib() is None
    assert not native.native_available()
    monkeypatch.delenv("SBR_NATIVE")
    assert native.get_lib() is before


def test_sort_matches_numpy_reference():
    rng = np.random.default_rng(0)
    n, e = 500, 20_000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    got = native.sort_edges_by_dst(src, dst, n)
    want = _numpy_reference(src, dst, n)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_sort_stability():
    """Equal-dst edges must keep source order (matches argsort stable)."""
    src = np.asarray([5, 4, 3, 2, 1, 0], np.int32)
    dst = np.asarray([1, 0, 1, 0, 1, 0], np.int32)
    src_s, dst_s, indeg, row_ptr = native.sort_edges_by_dst(src, dst, 2)
    np.testing.assert_array_equal(src_s, [4, 2, 0, 5, 3, 1])
    np.testing.assert_array_equal(dst_s, [0, 0, 0, 1, 1, 1])
    np.testing.assert_array_equal(indeg, [3, 3])
    np.testing.assert_array_equal(row_ptr, [0, 3, 6])


def test_sort_rejects_bad_ids():
    if not native.native_available():
        pytest.skip("native lib unavailable")
    with pytest.raises(ValueError, match="out of range"):
        native.sort_edges_by_dst(
            np.asarray([0], np.int32), np.asarray([7], np.int32), 4
        )


def test_er_edges_native_properties():
    out = native.er_edges_native(1000, 50_000, seed=7)
    if out is None:
        pytest.skip("native lib unavailable")
    src, dst = out
    assert src.shape == dst.shape == (50_000,)
    assert src.min() >= 0 and src.max() < 1000
    assert dst.min() >= 0 and dst.max() < 1000
    assert not (src == dst).any()  # self-loops re-drawn
    # deterministic in seed
    src2, dst2 = native.er_edges_native(1000, 50_000, seed=7)
    np.testing.assert_array_equal(src, src2)
    np.testing.assert_array_equal(dst, dst2)
    # roughly uniform endpoints
    counts = np.bincount(dst, minlength=1000)
    assert counts.std() / counts.mean() < 0.25


def test_prep_inputs_uses_sorted_edges():
    """The agent-sim host prep built on the native sort must produce the
    same simulation inputs as before the native layer existed."""
    from sbr_tpu.social.agents import _prep_inputs

    rng = np.random.default_rng(3)
    n, e = 200, 4_000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    betas, src_s, dst_s, indeg, row_ptr, informed0 = _prep_inputs(
        n, 1.0, 0.05, src, dst, 0, np.dtype(np.float32)
    )
    assert (np.diff(dst_s) >= 0).all()
    np.testing.assert_array_equal(
        row_ptr, np.searchsorted(dst_s, np.arange(n + 1), side="left")
    )
    np.testing.assert_allclose(indeg, np.bincount(dst, minlength=n))
