"""Golden pins for the scalars that feed the replication figures.

The reference validates its figures by printed-scalar eyeball checks
(`scripts/1_baseline.jl:83-87`, `2_heterogeneity.jl:70-75`,
`4_social_learning.jl:65-81`). These tests pin the same scalars so a figure
regression fails a test instead of an eyeball (VERDICT r1 weak-#7).

Values were captured from the f64 solve at SolverConfig defaults; the
baseline ones agree with the independent scipy oracle (tests/oracle.py) to
~1e-6, so they double as end-to-end regression anchors for the whole
pipeline. Tolerances: 1e-5 for deterministic f64 solves, 1e-3 for the
social fixed point (its own convergence tolerance is 1e-4).

CAVEAT (VERDICT r2 weak-7): the hetero/social pins below are OWN-OUTPUT
pins — regression anchors, not external truth. The baseline pins are
cross-checked against the scipy oracle, and the hetero/social CONFIGS have
separate oracle tests at looser tolerance (tests/test_hetero.py,
tests/test_social.py), but the pinned digits themselves (e.g.
ξ=16.875766906) encode this implementation's numerics: a change that
shifts both the implementation and these pins in tandem would pass here
and must be caught by the oracle tests instead.
"""

import pytest

from sbr_tpu import make_model_params, solve_learning, solve_equilibrium_baseline, with_overrides
from sbr_tpu.models.params import LearningParams, make_hetero_params


class TestBaselineFigureScalars:
    """Figures 2-3/3bis/3ter inputs (`1_baseline.jl:82-126`)."""

    @pytest.fixture(scope="class")
    def base(self):
        return make_model_params()  # β=1, η̄=15, u=0.1, p=0.5, κ=0.6, λ=0.01

    def test_main_equilibrium(self, base):
        ls = solve_learning(base.learning)
        res = solve_equilibrium_baseline(ls, base.economic)
        assert bool(res.bankrun)
        assert float(res.xi) == pytest.approx(10.215435605, abs=1e-5)
        assert float(res.aw_max) == pytest.approx(0.618230571, abs=1e-5)

    def test_fast_communication(self, base):
        m = with_overrides(base, beta=3.0)  # η stays pinned at 15
        ls = solve_learning(m.learning)
        res = solve_equilibrium_baseline(ls, m.economic)
        assert bool(res.bankrun)
        assert float(res.xi) == pytest.approx(3.256394431, abs=1e-5)
        assert float(res.aw_max) == pytest.approx(0.744437002, abs=1e-5)

    def test_low_deposit_utility(self, base):
        m = with_overrides(base, u=0.01)
        ls = solve_learning(m.learning)
        res = solve_equilibrium_baseline(ls, m.economic)
        assert bool(res.bankrun)
        assert float(res.xi) == pytest.approx(9.660277550, abs=1e-5)
        assert float(res.aw_max) == pytest.approx(0.847096205, abs=1e-5)


def test_hetero_figure_scalars():
    """Two-group figure inputs (`2_heterogeneity.jl:38-75`)."""
    from sbr_tpu.hetero.learning import solve_learning_hetero
    from sbr_tpu.hetero.solver import get_aw_hetero, solve_equilibrium_hetero

    m = make_hetero_params(
        betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
    )
    lsh = solve_learning_hetero(m.learning)
    res = solve_equilibrium_hetero(lsh, m.economic)
    assert bool(res.bankrun)
    assert float(res.xi) == pytest.approx(16.875766906, abs=1e-4)
    aw = get_aw_hetero(res, lsh)
    assert float(aw.aw_max) == pytest.approx(0.319828704, abs=1e-4)


def test_social_delta_xi_vs_word_of_mouth():
    """The Δξ comparison the reference prints (`4_social_learning.jl:65-81`):
    withdrawal feedback ACCELERATES the crash at the Figure-12 parameters."""
    from sbr_tpu.social.solver import solve_equilibrium_social

    m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
    social = solve_equilibrium_social(m, tol=1e-4, max_iter=500)
    assert bool(social.converged)

    lw = solve_learning(LearningParams(beta=0.9, tspan=(0.0, m.economic.eta), x0=1e-4))
    wom = solve_equilibrium_baseline(lw, m.economic)
    assert bool(wom.bankrun)

    assert float(social.xi) == pytest.approx(8.925581642, abs=1e-3)
    assert float(wom.xi) == pytest.approx(9.189793981, abs=1e-5)
    dxi = float(social.xi) - float(wom.xi)
    assert dxi == pytest.approx(-0.264212339, abs=2e-3)
