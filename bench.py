"""Benchmark: the two headline workloads from BASELINE.md.

1. equilibria/sec on the Figure-5 β×u comparative-statics grid — the
   reference solves the 500×500 grid sequentially in the bulk of its
   5-15 min replication run (`scripts/1_baseline.jl:209-285`) and reports
   ~0.5 s per single equilibrium solve (paper Appendix C.5.3), i.e. a
   baseline of 2 equilibria/sec. Here the whole grid is one jitted vmap²
   program on the accelerator; `vs_baseline` is (our equilibria/sec) / 2.
2. agent-steps/sec on the 10^6-agent explicit social-learning simulation
   (the north-star extension; the reference has no per-agent code, its
   representative-agent fixed point is ~20 s on CPU).

Prints exactly ONE JSON line on stdout (primary metric = equilibria/sec,
agent-steps/sec carried in "extra"); diagnostics go to stderr.

Defensive setup (round-1 postmortem, VERDICT §missing-1): the TPU backend
behind the axon tunnel can fail or hang on first contact, and the vmap²
program's cold compile is minutes. So: persistent XLA compile cache (same
dir the figures CLI uses), backend init retried with backoff, crossing
refinement OFF in the sweep path (SolverConfig.refine_crossings — the
grid is interpolation-bound anyway), and compile vs execute reported
separately on stderr.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _probe_accelerator(timeout_s: float) -> str:
    """Ask a SUBPROCESS what platform jax.devices() lands on.

    The axon TPU tunnel does not just fail — it can HANG jax.devices()
    indefinitely (observed in-session; round 1's capture died exactly here,
    BENCH_r01 rc=1). A hang inside this process would be unrecoverable
    (backend init is global and blocking), so the first contact happens in a
    child process that a hard timeout can kill. Returns the platform name,
    or "" when the probe failed or timed out.
    """
    import subprocess

    code = "import jax; print(jax.devices()[0].platform, flush=True)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        platform = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        if out.returncode == 0 and platform:
            return platform
        _log(f"probe rc={out.returncode}, stderr tail: {out.stderr.strip()[-200:]!r}")
        return ""
    except subprocess.TimeoutExpired:
        _log(f"probe timed out after {timeout_s:.0f}s (accelerator backend hung)")
        return ""


def _init_backend(retries: int = 2, backoff_s: float = 10.0, probe_timeout_s: float = 120.0):
    """Bring up a backend that is guaranteed not to hang this process.

    Strategy: probe the default (TPU) backend in a killable subprocess with
    retry/backoff; only if a probe succeeds is the in-process backend
    allowed to touch the accelerator. Otherwise pin the CPU platform — a
    degraded-but-real measurement beats the rc!=0 / no-output outcomes of
    round 1. ``SBR_BENCH_PLATFORM=cpu|tpu`` overrides the probe.
    """
    import os

    forced = os.environ.get("SBR_BENCH_PLATFORM", "").strip().lower()
    platform = forced
    if not forced:
        for attempt in range(1, retries + 1):
            platform = _probe_accelerator(probe_timeout_s)
            if platform:
                break
            if attempt < retries:
                _log(f"probe attempt {attempt}/{retries} failed; backing off {backoff_s:.0f}s")
                time.sleep(backoff_s)
    if not platform:
        platform = "cpu"
        _log("accelerator unreachable after all probes — falling back to CPU")

    import jax

    if platform == "cpu":
        # Must go through jax.config: this image's sitecustomize overrides
        # the JAX_PLATFORMS env var (see tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", str(Path.home() / ".cache/sbr_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    devices = jax.devices()
    _log(f"backend up: {len(devices)}x {devices[0].platform}")
    return jax, devices


def bench_grid(platform: str) -> dict:
    """Equilibria/sec on the β×u grid (f32 sweep path, refinement off)."""
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    if platform == "cpu":  # degraded fallback: still ≥ the 10^4-point north star
        n_beta, n_u = 128, 128
    else:
        n_beta, n_u = 640, 640  # 409.6k cells — 40× the north-star 10^4 points
    config = SolverConfig(n_grid=1024, bisect_iters=60, refine_crossings=False)
    base = make_model_params()  # Figure-5 base: β=1, η̄=15, κ=.6 (η pinned 15)

    # Reference grid domain (`scripts/1_baseline.jl:210-213`):
    # β = 1/ave_meeting_time, ave_meeting_time ∈ [1e-4, 1]; u ∈ [0.001, 1].
    amt = np.linspace(1e-4, 1.0, n_beta)
    betas = 1.0 / amt

    def run(rep: int):
        # Perturb u by 1e-6 per rep: physics-identical to the metric's
        # precision, but ensures each rep is a distinct computation. Fetch a
        # scalar reduction to host inside the timed region — on the axon TPU
        # tunnel `block_until_ready` returns before device work completes, so
        # a device→host read is the only honest fence.
        us = np.linspace(0.001, 1.0, n_u) + rep * 1e-6
        grid = beta_u_grid(betas, us, base, config=config, dtype=jnp.float32)
        fence = float(
            jnp.sum(grid.status) + jnp.nansum(grid.max_aw) + jnp.nansum(grid.xi)
        )
        return grid, fence

    t0 = time.perf_counter()
    grid, _ = run(0)  # includes compile (or a persistent-cache hit)
    first_s = time.perf_counter() - t0

    times = []
    for rep in range(1, 4):
        t0 = time.perf_counter()
        grid, _ = run(rep)
        times.append(time.perf_counter() - t0)
    elapsed = min(times)

    n_cells = n_beta * n_u
    n_run = int(np.sum(np.asarray(grid.status) == 0))
    _log(
        f"grid: {n_cells} cells in {elapsed:.3f}s steady-state "
        f"(first call {first_s:.1f}s = compile+execute, so compile ≈ "
        f"{first_s - elapsed:.1f}s); {n_run} run cells"
    )
    return {
        "eq_per_sec": n_cells / elapsed,
        "n_cells": n_cells,
        "first_call_s": first_s,
        "steady_s": elapsed,
    }


def bench_agents(platform: str) -> dict:
    """Agent-steps/sec: 10^6 agents, Erdős–Rényi deg 10, 200 steps, f32."""
    from sbr_tpu.social import AgentSimConfig, erdos_renyi_edges, simulate_agents

    if platform == "cpu":  # degraded fallback size
        n, n_steps = 100_000, 100
    else:
        n, n_steps = 1_000_000, 200
    t0 = time.perf_counter()
    src, dst = erdos_renyi_edges(n, 10.0, seed=0)
    _log(f"agents: graph built ({len(src)} edges) in {time.perf_counter() - t0:.1f}s")
    cfg = AgentSimConfig(n_steps=n_steps, dt=0.05)

    def run(seed: int):
        res = simulate_agents(1.0, src, dst, n, x0=1e-4, config=cfg, seed=seed)
        fence = float(res.informed_frac[-1])  # device→host read as the fence
        return res, fence

    t0 = time.perf_counter()
    _, frac0 = run(0)
    first_s = time.perf_counter() - t0
    times = []
    for seed in (1, 2):
        t0 = time.perf_counter()
        _, _ = run(seed)
        times.append(time.perf_counter() - t0)
    elapsed = min(times)

    steps = n * n_steps
    _log(
        f"agents: {steps} agent-steps in {elapsed:.3f}s steady-state "
        f"(first call {first_s:.1f}s incl. compile); final G = {frac0:.4f}"
    )
    return {
        "agent_steps_per_sec": steps / elapsed,
        "n_agents": n,
        "first_call_s": first_s,
        "steady_s": elapsed,
    }


def main() -> None:
    _, devices = _init_backend()
    platform = devices[0].platform

    grid = bench_grid(platform)
    try:
        agents = bench_agents(platform)
    except Exception as err:
        # The primary metric must still land even if the second workload
        # fails (graceful-degradation analogue of the sweeps' NaN cells).
        _log(f"agent bench failed: {err!r}")
        agents = None

    eq_per_sec = grid["eq_per_sec"]
    out = {
        "metric": "beta_u_grid_equilibria_per_sec",
        "value": round(eq_per_sec, 1),
        "unit": "equilibria/sec",
        "vs_baseline": round(eq_per_sec / 2.0, 1),
        "extra": {
            "platform": platform,
            "grid_cells": grid["n_cells"],
            "grid_first_call_s": round(grid["first_call_s"], 2),
            "grid_steady_s": round(grid["steady_s"], 3),
        },
    }
    if agents is not None:
        out["extra"]["agent_steps_per_sec"] = round(agents["agent_steps_per_sec"], 1)
        out["extra"]["n_agents"] = agents["n_agents"]
        out["extra"]["agents_first_call_s"] = round(agents["first_call_s"], 2)
        out["extra"]["agents_steady_s"] = round(agents["steady_s"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
