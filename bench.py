"""Benchmark: the two headline workloads from BASELINE.md.

1. equilibria/sec on the Figure-5 β×u comparative-statics grid — the
   reference solves the 500×500 grid sequentially in the bulk of its
   5-15 min replication run (`scripts/1_baseline.jl:209-285`) and reports
   ~0.5 s per single equilibrium solve (paper Appendix C.5.3), i.e. a
   baseline of 2 equilibria/sec. Here the whole grid is one jitted vmap²
   program on the accelerator; `vs_baseline` is (our equilibria/sec) / 2.
2. agent-steps/sec on the 10^6-agent explicit social-learning simulation
   (the north-star extension; the reference has no per-agent code, its
   representative-agent fixed point is ~20 s on CPU).

Prints exactly ONE JSON line on stdout (primary metric = equilibria/sec,
agent-steps/sec carried in "extra"); diagnostics go to stderr.

Defensive architecture (rounds 1-2 postmortem, VERDICT r2 §missing-1):
the TPU backend behind the axon tunnel can fail or HANG at any point —
round 1 died in `jax.devices()` (560 s+ hangs observed), round 2's probe
timed out twice at 120 s. So this script is split into a PARENT that never
touches an accelerator and a CHILD that does all device work:

- parent: probes the accelerator in a killable subprocess (real tiny jit
  computation, not just `jax.devices()` — a half-up backend must not
  pass), with >=3 attempts x 300 s and exponential backoff (budget sized
  to the observed 560 s hangs, per VERDICT r2 task 1);
- parent: runs the MEASUREMENT in a killable child too (`--measure`),
  eliminating the probe-then-attach TOCTOU (ADVICE r2: a tunnel that
  hangs between probe and attach must not take out the bench);
- parent: on child failure/timeout, re-runs the child pinned to CPU —
  a degraded-but-real measurement beats no output;
- the full probe/measure history (attempts, durations, outcomes) lands in
  the JSON `extra.probe_history`, so a CPU fallback is self-documenting.

Round-4 additions (VERDICT r3 task 1 + ADVICE r3):

- every accelerator-platform measurement is opportunistically PERSISTED as a
  timestamped driver-format JSON under `benchmarks/` (atomic tmp+rename), and
  every harness run appends one line to `benchmarks/CAPTURE_LOG.jsonl` — the
  evidence chain no longer depends on a human committing artifacts by hand;
- `python bench.py --watch N [interval_s]` probes every ~interval (default
  600 s) up to N times and runs+persists the full measurement on the first
  TPU success — an opportunistic capture daemon for the flaky tunnel;
- the whole probe→measure→CPU-retry envelope is capped by
  SBR_BENCH_BUDGET_S (default 3300 s): each phase's timeout shrinks to the
  remaining budget, so the worst case is ~55 min, not the former ~107 min.

Env overrides: SBR_BENCH_PLATFORM=cpu|tpu skips the probe;
SBR_BENCH_PROBE_ATTEMPTS / SBR_BENCH_PROBE_TIMEOUT_S /
SBR_BENCH_MEASURE_TIMEOUT_S / SBR_BENCH_BUDGET_S tune budgets;
SBR_BENCH_SIZES=tiny shrinks every workload to smoke-test scale (used by
tests/test_bench_harness.py); SBR_BENCH_PROBE_CACHE_TTL_S tunes the probe
outcome cache (`SBR_OBS_DIR/.probe_cache.json`, default 900 s, 0 disables)
that lets repeated runs against a hung backend skip the timeout ladder;
SBR_OBS_KEEP caps retained obs run dirs (bench default 16).

Run telemetry (PR 1): the measure child writes an `sbr_tpu.obs` run
directory (events.jsonl + manifest.json, dir from SBR_OBS_DIR, default
obs_runs/) and the JSON line's `extra.obs` block carries the
compile/execute split, device kind, and memory peak. Measurement loops run
with telemetry suspended, so metrics are unchanged by instrumentation.
`python bench.py --dry-run` smokes the whole pipeline on CPU at tiny sizes
in-process and renders with `python -m sbr_tpu.obs.report <run_dir>`.

Performance observatory (PR 3): every probe/measure history entry now has
ONE uniform, versioned shape (`"schema": 1` — phase, attempt, outcome,
platform, duration_s, timeout_s, backoff_s), mirrored into obs `probe`
events; each measure child appends its headline metrics (equilibria/sec,
agent-steps/sec, compile/dispatch splits, health divergent-count) to the
append-only perf history (`SBR_OBS_HISTORY`, default
benchmarks/bench_history.jsonl — tiny smoke runs skip unless the env var
is set), gated in CI by `python -m sbr_tpu.obs.report trend --check`;
and `SBR_OBS_PROFILE=1` captures a size-bounded `jax.profiler` trace of
one steady-state rep per workload into the run directory (summarized as a
`profile` event; the old always-on SBR_BENCH_TRACE_DIR capture is
superseded by this opt-in path).

Memory observatory (PR 5): each workload samples the allocator's
high-water mark after every steady-state rep (`sbr_tpu.obs.mem` — zero
reads on backends without `memory_stats()`), the JSON gains
`extra.grid_mem_peak_bytes` / `extra.agents_mem_peak_bytes`, and the perf
history records them (schema 2) so `report trend` gates memory regressions
alongside throughput. The O(live arrays) live-buffer sum is disabled
(`mem.live_disabled`, env `SBR_OBS_MEM_LIVE`) inside the timing loops on
top of the existing `obs.suspended()` envelope.

Serving observatory (ISSUE 7): a third workload drives the seeded loadgen
mix through an in-process `sbr_tpu.serve.Engine` (warmup over the
parameter pool, then the measured repeated mix) and reports
`extra.serve_p50_ms` / `extra.serve_p99_ms` / `extra.serve_cache_hit_rate`
(+ qps), appended to the perf history as schema 3 so `report trend
--check` catches serving-latency regressions; schema-1/2 lines still load
and gate.

Elastic sweeps (ISSUE 8): a fourth workload runs one cold elastic tiled
sweep (`parallel.run_tiled_grid_multihost` — heartbeats, claim plan,
leases) and a warm re-sweep against the cross-run global tile cache,
reporting `extra.sweep_cold_cells_per_sec` / `sweep_warm_cells_per_sec` /
`sweep_warm_hit_rate` (history schema 4) so `report trend` gates both the
scheduler's compute path and the cache's hit path.

Serving fleet (ISSUE 11): a fifth workload runs the MULTI-PROCESS fleet —
worker subprocesses behind an in-process `sbr_tpu.serve.router.Router` —
through the seeded loadgen mix over HTTP and reports the client-observed
`extra.fleet_p99_ms` plus `fleet_failover_count` / `fleet_shed_rate`
(history schema 7); any lost query fails the workload outright.

Differentiable equilibria (ISSUE 13): a sixth workload measures the
`sbr_tpu.grad` subsystem — IFT sensitivity-surface throughput
(`extra.grads_per_sec`: partial derivatives per second through the
vmapped value-and-grad grid program) and calibration speed
(`extra.calib_steps_per_sec`: jitted Adam steps over the IFT loss) —
appended to the perf history as schema 8 (schema-1..7 lines still load
and gate; both keys learn higher-better polarity from the per_sec rule).

Mega-scale agents (ISSUE 10): the agents workload now generates its graph
ON DEVICE (`sbr_tpu.social.graphgen` — the edge list never transits host
RAM) at 10^7 agents / 10^8 edges on every non-tiny platform, CPU
included, and reports generation separately from simulation:
`extra.agents_graph_build_s` / `agents_graph_gen_edges_per_sec` (steady
canonical-layout builds) and `agents_graph_gen_speedup` (device vs the
host-numpy pipeline at a 10^7-edge control shape), appended to the perf
history as schema 6 so `report trend` gates generation-path regressions;
schema-1..5 lines still load and gate.

Resilience (PR 4): the probe ladder's attempts/backoff now come from the
unified retry engine (`sbr_tpu.resilience.retry`, loaded standalone by
file path so the parent stays jax-free) — SBR_BENCH_PROBE_ATTEMPTS /
SBR_BENCH_PROBE_TIMEOUT_S keep working, joined by _BASE_DELAY_S /
_MULTIPLIER / _MAX_DELAY_S; a seeded SBR_FAULT_PLAN can inject probe
failures at the `bench.probe` fault point; and the measure child runs
under a graceful-shutdown envelope (SIGTERM finalizes the obs manifest
as "interrupted" instead of leaving a "running" corpse).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


_RESILIENCE_MODS: dict = {}


def _resilience_mod(name: str):
    """Load ``sbr_tpu/resilience/<name>.py`` STANDALONE by file path.

    The parent's contract is to never import the sbr_tpu package (and with
    it jax) — but the probe ladder's retry policy and the ``bench.probe``
    fault point live in `sbr_tpu.resilience`, whose `retry`/`faults`
    modules are deliberately stdlib-only. Loading them by path keeps the
    parent jax-free while sharing the exact engine the tile loop uses."""
    if name not in _RESILIENCE_MODS:
        import importlib.util

        path = Path(__file__).resolve().parent / "sbr_tpu" / "resilience" / f"{name}.py"
        spec = importlib.util.spec_from_file_location(f"_sbr_resilience_{name}", path)
        mod = importlib.util.module_from_spec(spec)
        # dataclasses (and friends) resolve a class's module through
        # sys.modules[__module__] — register before exec, like import does.
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _RESILIENCE_MODS[name] = mod
    return _RESILIENCE_MODS[name]


# ---------------------------------------------------------------------------
# Parent side: probe + orchestrate (never initializes a JAX backend)
# ---------------------------------------------------------------------------

_PROBE_CODE = """
import jax, jax.numpy as jnp
x = jnp.arange(64.0)
y = jax.jit(lambda v: (v * 2.0 + 1.0).sum())(x)
assert float(y) == 64.0 * 63.0 + 64.0, float(y)
print("PLATFORM=" + jax.devices()[0].platform, flush=True)
"""


def _run_killable(argv, timeout_s: float) -> tuple:
    """Run ``argv`` with stdout/stderr captured via TEMP FILES and the child
    in its OWN PROCESS GROUP, returning (rc_or_None, stdout, stderr, dur).

    Why not subprocess.run(capture_output=..., timeout=...): on timeout it
    kills the immediate child and then blocks in communicate() until the
    PIPE closes — and the TPU plugin spawns helper grandchildren that
    inherit the pipe and survive the kill, so the "timeout" never returns
    (observed: the watch daemon froze for 100 min inside probe #2 this
    way). Files cannot block, and killpg takes the helpers down too.
    """
    import signal
    import subprocess
    import tempfile

    t0 = time.perf_counter()
    with tempfile.TemporaryFile("w+") as fout, tempfile.TemporaryFile("w+") as ferr:
        proc = subprocess.Popen(
            argv,
            stdout=fout,
            stderr=ferr,
            stdin=subprocess.DEVNULL,
            text=True,
            start_new_session=True,  # own process group → killpg reaches helpers
        )
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()  # killpg can fail (pgid race); kill the child itself
            try:
                proc.wait(timeout=30.0)  # never wait unbounded — that IS the bug
            except subprocess.TimeoutExpired:
                _log("child unreapable after SIGKILL; abandoning (zombie)")
            rc = None
        dur = time.perf_counter() - t0
        fout.seek(0)
        ferr.seek(0)
        return rc, fout.read(), ferr.read(), dur


def _probe_accelerator(timeout_s: float) -> tuple:
    """Ask a SUBPROCESS to run a real tiny jit computation on the default
    (accelerator) backend and report its platform.

    The computation (compile + execute + device->host fetch + value check)
    is the point: round 2 showed `jax.devices()` alone can succeed while
    the first real dispatch hangs. A hang anywhere in the child (or its
    TPU-plugin helpers) is killed by the timeout via `_run_killable`.
    Returns (platform_or_empty, outcome_str, duration_s).
    """
    rc, stdout, stderr, dur = _run_killable(
        [sys.executable, "-c", _PROBE_CODE], timeout_s
    )
    if rc is None:
        _log(f"probe timed out after {timeout_s:.0f}s (accelerator backend hung)")
        return "", "timeout", dur
    platform = ""
    for line in stdout.strip().splitlines():
        if line.startswith("PLATFORM="):
            platform = line.split("=", 1)[1].strip()
    if rc == 0 and platform:
        return platform, "ok", dur
    _log(f"probe rc={rc}, stderr tail: {stderr.strip()[-200:]!r}")
    return "", f"rc={rc}", dur


def _obs_event(kind: str, **fields) -> None:
    """Emit an obs event from the PARENT process. Guarded on SBR_OBS so the
    default parent path never imports sbr_tpu (and with it the jax module) —
    the parent's contract is to stay off the accelerator stack entirely.
    RunContext construction is filesystem-only, so emission is safe when
    telemetry IS configured."""
    if os.environ.get("SBR_OBS", "").strip() in ("", "0"):
        return
    try:
        from sbr_tpu import obs

        obs.event(kind, **fields)
    except Exception as err:
        _log(f"obs event failed (non-fatal): {err!r}")


# Version of the probe/measure history record shape (ISSUE 3 satellite:
# probe and measure entries used to carry different key sets; now every
# entry has the same keys, and consumers can key on the schema number).
PROBE_HISTORY_SCHEMA = 1


def _history_entry(
    phase: str,
    outcome: str,
    platform: str = None,
    attempt: int = 0,
    duration_s: float = 0.0,
    timeout_s: float = 0.0,
    backoff_s: float = 0.0,
    **extra,
) -> dict:
    """One uniform probe/measure history record — identical key set for
    every phase (missing numerics are 0.0, missing platform None), plus
    phase-specific extras (cached/forced/watch_attempt) appended after."""
    entry = {
        "schema": PROBE_HISTORY_SCHEMA,
        "phase": phase,
        "attempt": int(attempt),
        "outcome": outcome,
        "platform": platform or None,
        "duration_s": round(float(duration_s), 1),
        "timeout_s": round(float(timeout_s), 1),
        "backoff_s": round(float(backoff_s), 1),
    }
    entry.update(extra)
    return entry


def _probe_cache_path() -> Path:
    return Path(os.environ.get("SBR_OBS_DIR", "obs_runs")) / ".probe_cache.json"


def _probe_cache_ttl_s() -> float:
    """Probe-outcome cache TTL. The point (ISSUE 2 satellite): a machine
    with a HUNG backend pays the full 3×300 s probe ladder on every harness
    run; caching the resolved platform — including the cpu fallback after a
    failed ladder — makes repeated runs within the TTL instant. 0 disables."""
    return float(os.environ.get("SBR_BENCH_PROBE_CACHE_TTL_S", "900"))


def _read_probe_cache() -> dict | None:
    ttl = _probe_cache_ttl_s()
    if ttl <= 0:
        return None
    try:
        entry = json.loads(_probe_cache_path().read_text())
        age = time.time() - float(entry["ts"])
        if 0 <= age <= ttl and entry.get("platform"):
            entry["age_s"] = round(age, 1)
            return entry
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        pass
    return None


def _write_probe_cache(platform: str, history: list) -> None:
    if _probe_cache_ttl_s() <= 0:
        return
    try:
        path = _probe_cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(
            json.dumps({"ts": time.time(), "platform": platform, "history": history})
        )
        os.replace(tmp, path)
    except OSError as err:
        _log(f"probe cache write failed (non-fatal): {err!r}")


class _Budget:
    """Wall-clock envelope for one harness run (ADVICE r3 #3: the former
    worst case of 3x300s probes + backoffs + 2x2700s measures was ~107 min,
    longer than a plausible driver round-end budget — so the bench could
    burn the whole window and still emit nothing). Every phase timeout is
    clamped to what remains of SBR_BENCH_BUDGET_S."""

    def __init__(self):
        self.total_s = float(os.environ.get("SBR_BENCH_BUDGET_S", "3300"))
        self.t0 = time.perf_counter()

    def remaining(self) -> float:
        return self.total_s - (time.perf_counter() - self.t0)

    def clamp(self, want_s: float, floor_s: float = 30.0) -> float:
        """Phase timeout: at most ``want_s``, at most the remaining budget,
        never below ``floor_s`` (a 5 s timeout would kill healthy children) —
        EXCEPT when the budget is already spent, where the phase gets 0 and
        the caller skips it (ADVICE r4: the floor used to let late phases
        overrun SBR_BENCH_BUDGET_S by minutes)."""
        if self.remaining() <= 0.0:
            return 0.0
        return max(floor_s, min(want_s, self.remaining()))


def _probe_loop(budget: "_Budget" = None) -> tuple:
    """Probe with retry/backoff; returns (platform, history list).

    Outcomes are cached (`SBR_OBS_DIR/.probe_cache.json`, TTL
    SBR_BENCH_PROBE_CACHE_TTL_S, default 900 s) so back-to-back harness
    runs against a hung tunnel skip the timeout ladder, and every attempt
    is ALSO recorded as an obs ``probe`` event when telemetry is on
    (SBR_OBS=1) — the run log carries the probe story, not just the JSON
    line's `extra.probe_history`."""
    cached = _read_probe_cache()
    if cached is not None:
        entry = _history_entry(
            "probe",
            "cached",
            platform=cached["platform"],
            cached=True,
            age_s=cached["age_s"],
            ttl_s=_probe_cache_ttl_s(),
        )
        _obs_event("probe", **entry)
        _log(
            f"probe cache hit ({cached['age_s']:.0f}s old): "
            f"platform={cached['platform']} — skipping probe ladder"
        )
        return cached["platform"], [entry]

    # Probe attempts/backoff ride the unified retry engine
    # (sbr_tpu.resilience.retry, loaded standalone — see _resilience_mod):
    # SBR_BENCH_PROBE_ATTEMPTS (alias of _MAX_ATTEMPTS), _BASE_DELAY_S,
    # _MULTIPLIER, _MAX_DELAY_S replace the former hardcoded 3×300 s ladder
    # (defaults keep its exact schedule: 3 attempts, 10 s·2^k backoff).
    policy = _resilience_mod("retry").policy_from_env(
        "SBR_BENCH_PROBE",
        max_attempts=3, base_delay_s=10.0, multiplier=2.0, max_delay_s=600.0,
    )
    attempts = policy.max_attempts
    timeout_s = float(os.environ.get("SBR_BENCH_PROBE_TIMEOUT_S", "300"))
    history = []
    platform = ""
    for attempt in range(1, attempts + 1):
        eff_timeout = budget.clamp(timeout_s) if budget else timeout_s
        if eff_timeout <= 0.0:  # clamp's 0-means-skip contract (ADVICE r4)
            _log("probe budget exhausted before attempt — skipping")
            break
        platform, outcome, dur = _probe_attempt(attempt, eff_timeout)
        # ADVICE r4: count the upcoming backoff sleep against the budget
        # check, so backoffs cannot push the run past SBR_BENCH_BUDGET_S.
        # The backoff decision is made BEFORE the entry is recorded so the
        # JSON history and the mirrored obs `probe` event carry the same
        # backoff_s (the event used to fire before the field was set).
        backoff = policy.delay_s(attempt)
        budget_left = budget is None or budget.remaining() >= 60.0 + backoff
        will_sleep = not platform and attempt < attempts and budget_left
        history.append(
            _history_entry(
                "probe",
                outcome,
                platform=platform,
                attempt=attempt,
                duration_s=dur,
                timeout_s=eff_timeout,
                backoff_s=backoff if will_sleep else 0.0,
            )
        )
        _obs_event("probe", **history[-1])
        if platform:
            break
        if not budget_left:
            _log("probe budget exhausted — skipping remaining attempts")
            break
        if will_sleep:
            _log(f"probe attempt {attempt}/{attempts} failed; backing off {backoff:.0f}s")
            _obs_event(
                "retry", scope="bench.probe", outcome="retrying",
                attempt=attempt, max_attempts=attempts, backoff_s=backoff,
            )
            time.sleep(backoff)
    if not platform:
        platform = "cpu"
        _log("accelerator unreachable after all probes — falling back to CPU")
        # "fell_back", NOT "gave_up": the CPU fallback is this harness's
        # DESIGNED degraded-success path (a measurement still lands), so it
        # must not trip `report resilience`'s unrecovered-failure gate.
        _obs_event(
            "retry", scope="bench.probe", outcome="fell_back",
            attempt=attempts, max_attempts=attempts, error="accelerator unreachable",
        )
    _write_probe_cache(platform, history)
    return platform, history


def _probe_attempt(attempt: int, timeout_s: float) -> tuple:
    """One probe attempt, preceded by the ``bench.probe`` fault point.

    The fault-plan check is env-guarded so the default path never loads
    the standalone faults module; an injected transient reads as a failed
    attempt (outcome ``"fault-injected"``) and flows through the ladder's
    normal backoff/fallback — chaos runs exercise the real recovery."""
    if os.environ.get("SBR_FAULT_PLAN", "").strip():
        mod = _resilience_mod("faults")
        try:
            mod.fire("bench.probe", target=f"attempt{attempt}")
        except mod.InjectedFault as err:
            _log(f"probe fault injected: {err}")
            return "", "fault-injected", 0.0
    return _probe_accelerator(timeout_s)


def _run_measurement(platform: str, timeout_s: float, script: str = None) -> tuple:
    """Run the measurement child pinned to ``platform``; returns
    (result_dict_or_None, outcome_str, duration_s). ``script`` defaults to
    this file; benchmarks/stretch.py reuses the harness by passing its own
    path (every device touch must live in a killable child — see module
    docstring). Uses `_run_killable` (file-backed IO + process-group kill)
    so a hung tunnel cannot freeze the parent past the timeout. A zero/
    negative ``timeout_s`` (exhausted budget) skips the phase outright."""
    if timeout_s <= 0.0:
        _log("measurement skipped — budget exhausted")
        return None, "skipped-budget", 0.0
    rc, stdout, stderr, dur = _run_killable(
        [sys.executable, script or os.path.abspath(__file__), "--measure", platform],
        timeout_s,
    )
    if stderr:
        sys.stderr.write(stderr)  # child diagnostics, forwarded
    if rc is None:
        _log(f"measure child timed out after {timeout_s:.0f}s on {platform}")
        return None, "timeout", dur
    if rc == 0 and stdout.strip():
        try:
            return json.loads(stdout.strip().splitlines()[-1]), "ok", dur
        except json.JSONDecodeError:
            _log(f"measure child printed non-JSON: {stdout[-200:]!r}")
            return None, "bad-json", dur
    _log(f"measure child rc={rc}")
    return None, f"rc={rc}", dur


def _benchmarks_dir() -> Path:
    return Path(__file__).resolve().parent / "benchmarks"


def _persist_capture(result: dict, script: str = None) -> None:
    """Opportunistically persist any ACCELERATOR-platform measurement as a
    timestamped driver-format JSON under benchmarks/ (VERDICT r3 weak #1:
    driver-captured beats builder-committed, but a builder-committed artifact
    written atomically the moment the chip answered beats losing the number
    to a later tunnel hang). No-op for CPU results."""
    platform = (result.get("extra") or {}).get("platform", "")
    if platform in ("", "cpu") or _tiny():
        return
    try:
        stamp = time.strftime("%Y-%m-%dT%H%M%S")
        name = Path(script).stem if script else "BENCH"
        name = "BENCH" if name == "bench" else name.upper()
        dest = _benchmarks_dir() / f"{name}_{platform}_auto_{stamp}.json"
        tmp = dest.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(result, indent=1) + "\n")
        os.replace(tmp, dest)
        _log(f"persisted {platform} capture -> {dest}")
    except OSError as err:
        _log(f"capture persist failed (non-fatal): {err!r}")


def _log_capture_attempt(entry: dict) -> None:
    """Append one line to benchmarks/CAPTURE_LOG.jsonl — the round's evidence
    that automatic capture was attempted even when the tunnel never answered.
    Tiny-size smoke runs (the test suite) are not capture attempts."""
    if _tiny():
        return
    try:
        entry = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"), **entry}
        with open(_benchmarks_dir() / "CAPTURE_LOG.jsonl", "a") as fh:
            fh.write(json.dumps(entry) + "\n")
    except OSError as err:
        _log(f"capture log append failed (non-fatal): {err!r}")


def run_harness(script: str = None, fallback: dict = None) -> None:
    """Parent orchestration shared by every benchmark script: probe (unless
    SBR_BENCH_PLATFORM forces a platform), run the `--measure` child of
    ``script``, re-run pinned to CPU on failure, and print ONE JSON line
    with the probe/measure history in `extra.probe_history`. ``fallback``
    is the result skeleton when every child fails. The whole run is capped
    by SBR_BENCH_BUDGET_S; accelerator results are persisted to
    benchmarks/ and every run is logged to CAPTURE_LOG.jsonl."""
    budget = _Budget()
    forced = os.environ.get("SBR_BENCH_PLATFORM", "").strip().lower()
    if forced:
        platform, history = forced, [
            _history_entry("probe", "forced", platform=forced, forced=True)
        ]
    else:
        platform, history = _probe_loop(budget)

    measure_timeout = float(os.environ.get("SBR_BENCH_MEASURE_TIMEOUT_S", "2700"))
    eff_timeout = budget.clamp(measure_timeout, floor_s=60.0)
    result, outcome, dur = _run_measurement(platform, eff_timeout, script)
    history.append(
        _history_entry(
            "measure", outcome, platform=platform, attempt=1,
            duration_s=dur, timeout_s=eff_timeout,
        )
    )
    _obs_event("probe", **history[-1])
    if result is None and platform != "cpu":
        _log("accelerator measurement failed — re-running pinned to CPU")
        eff_timeout = budget.clamp(measure_timeout, floor_s=60.0)
        result, outcome, dur = _run_measurement("cpu", eff_timeout, script)
        history.append(
            _history_entry(
                "measure", outcome, platform="cpu", attempt=2,
                duration_s=dur, timeout_s=eff_timeout,
            )
        )
        _obs_event("probe", **history[-1])
    if result is None:
        result = dict(fallback or {})
        result.setdefault("extra", {})["error"] = "all measurement children failed"
    result.setdefault("extra", {})["probe_history"] = history
    _persist_capture(result, script)
    _log_capture_attempt(
        {
            "script": Path(script).name if script else "bench.py",
            "platform": (result.get("extra") or {}).get("platform"),
            "outcome": outcome,
            "value": result.get("value"),
            "history": history,
        }
    )
    print(json.dumps(result))


def watch(max_attempts: int, interval_s: float) -> int:
    """Opportunistic capture daemon (VERDICT r3 task 1): probe with a short
    timeout every ``interval_s``; on the first accelerator hit, run the full
    measurement child and persist it. Exits 0 on a persisted accelerator
    capture, 1 if every probe failed. No CPU fallback — this mode exists
    only to catch the flaky tunnel in an up-phase; the round-end driver run
    still goes through run_harness."""
    probe_timeout = float(os.environ.get("SBR_BENCH_WATCH_PROBE_TIMEOUT_S", "120"))
    measure_timeout = float(os.environ.get("SBR_BENCH_MEASURE_TIMEOUT_S", "2700"))
    for attempt in range(1, max_attempts + 1):
        platform, outcome, dur = _probe_accelerator(probe_timeout)
        _log(f"watch probe {attempt}/{max_attempts}: {outcome} ({dur:.1f}s)")
        if platform and platform != "cpu":
            result, m_outcome, m_dur = _run_measurement(platform, measure_timeout)
            # The child re-derives its platform after backend init; a tunnel
            # that dropped between probe and attach silently falls back to
            # CPU in-child — that is NOT an accelerator capture, keep
            # watching (the probe-to-attach TOCTOU from the module docstring).
            measured = ((result or {}).get("extra") or {}).get("platform", "")
            entry = {
                "script": "bench.py --watch",
                "platform": measured or platform,
                "outcome": m_outcome,
                "probe_attempt": attempt,
            }
            if result is not None and measured not in ("", "cpu"):
                result.setdefault("extra", {})["probe_history"] = [
                    _history_entry(
                        "probe", outcome, platform=platform, attempt=attempt,
                        duration_s=dur, timeout_s=probe_timeout,
                        watch_attempt=attempt,
                    ),
                    _history_entry(
                        "measure", m_outcome, platform=measured, attempt=1,
                        duration_s=m_dur, timeout_s=measure_timeout,
                    ),
                ]
                entry["value"] = result.get("value")
                _persist_capture(result)
                _log_capture_attempt(entry)
                print(json.dumps(result))
                return 0
            if result is not None and measured == "cpu":
                entry["outcome"] = "cpu-fallback-in-child"
                _log("measure child fell back to CPU — not a capture; continuing watch")
            _log_capture_attempt(entry)
        else:
            _log_capture_attempt(
                {"script": "bench.py --watch", "platform": platform or None,
                 "outcome": outcome, "probe_attempt": attempt}
            )
        if attempt < max_attempts:
            time.sleep(interval_s)
    return 1


def main() -> None:
    run_harness(
        fallback={
            "metric": "beta_u_grid_equilibria_per_sec",
            "value": 0.0,
            "unit": "equilibria/sec",
            "vs_baseline": 0.0,
        }
    )


# ---------------------------------------------------------------------------
# Child side: the actual measurement (runs entirely in a killable process)
# ---------------------------------------------------------------------------


def _init_child_backend(platform: str):
    import jax

    if platform == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
    jax.config.update("jax_compilation_cache_dir", str(Path.home() / ".cache/sbr_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    devices = jax.devices()
    _log(f"backend up: {len(devices)}x {devices[0].platform}")
    return devices


def _append_history(result: dict, obs_run=None, label: str = "bench") -> None:
    """Append this measurement's headline metrics to the perf history
    (`sbr_tpu.obs.history`): equilibria/sec, agent-steps/sec, compile and
    dispatch splits, and the run's health divergent-count. Runs in the
    MEASURE CHILD (jax already up there; the parent stays off the sbr_tpu
    import path). Tiny smoke runs skip unless SBR_OBS_HISTORY is set — the
    test suite must not pollute the committed benchmarks history."""
    if _tiny() and not os.environ.get("SBR_OBS_HISTORY", "").strip():
        return
    try:
        from sbr_tpu.obs import history

        metrics = history.bench_metrics(result)
        if obs_run is not None:
            metrics["health_divergent"] = sum(
                int(v.get("divergent", 0)) for v in obs_run.health.values()
            )
        env_path = os.environ.get("SBR_OBS_HISTORY", "").strip()
        path = history.append(
            metrics,
            label=label,
            platform=(result.get("extra") or {}).get("platform"),
            path=env_path or _benchmarks_dir() / "bench_history.jsonl",
        )
        _log(f"perf history appended -> {path}")
    except Exception as err:  # the history must never sink the measurement
        _log(f"perf history append failed (non-fatal): {err!r}")


def _tiny() -> bool:
    """SBR_BENCH_SIZES=tiny shrinks every workload to smoke-test scale so the
    harness itself (probe → child → JSON) can be exercised in seconds — the
    driver depends on this script emitting valid JSON at round end, so the
    test suite runs the whole pipeline at tiny sizes."""
    return os.environ.get("SBR_BENCH_SIZES", "").strip().lower() == "tiny"


def _profile_rep(label: str, step: int, rep_fn) -> None:
    """Opt-in profiler capture (SBR_OBS_PROFILE=1) of ONE steady-state rep:
    the XLA-level breakdown lands in a size-bounded xplane trace inside the
    obs run directory (pruned with it by the gc machinery) with a compact
    `profile` summary event. The rep runs with telemetry suspended —
    jit_call's per-call fence must not reshape the profiled dispatch — and a
    StepTraceAnnotation frames it on the timeline. Profiling must never
    sink the measurement: any failure here is logged and swallowed (the
    metrics are already in hand when this runs)."""
    from sbr_tpu import obs

    try:
        with obs.profile(label) as trace_dir:
            if trace_dir is not None:
                with obs.suspended(), obs.step_annotation(step, f"{label}.rep"):
                    rep_fn()
                _log(f"profiler trace captured: {trace_dir}")
    except Exception as err:
        _log(f"profiler capture failed (non-fatal): {err!r}")


def _rep_peak_bytes(prev: int) -> int:
    """Fold the allocator's CURRENT usage (`bytes_in_use`) into a running
    per-rep peak (obs.mem). One `memory_stats()` read per rep, AFTER its
    timing window closed — zero reads (and always 0) on backends without
    the API, so CPU fallbacks simply omit the metric. Deliberately NOT
    `peak_bytes_in_use`: that high-water mark never resets, so the agents
    workload (which runs second) would just re-report the grid's peak and
    the per-workload trend series would attribute regressions to the wrong
    workload."""
    try:
        from sbr_tpu.obs import mem

        stats = mem.allocator_stats()
        if not stats:
            return prev
        return max(prev, int(stats.get("bytes_in_use", 0)))
    except Exception:
        return prev


def pipelined_time(dispatch, start_rep: int, n_pipe: int | None = None):
    """Sustained per-dispatch seconds: K dispatches in flight, ONE fence.

    A single fenced dispatch on this rig pays the tunnel's RPC round-trip
    (~0.1 s floor measured on the β×u grid: one 640-cell row costs 93% of
    the full 409.6k-cell grid, and n_grid 512→2048 moves nothing —
    ABLATE_GRID_tpu_2026-07-31), so per-rep fencing measures the tunnel,
    not the sweep. The framework's own workload shape is back-to-back
    dispatches (the 5000×5000 paper heatmap = 100 sequential tiles), hence
    the sustained protocol: launch K reps without an intervening fetch,
    then sum every rep's device-side reduction scalar ON DEVICE and read
    the one result back — a single D2H read that data-depends on every rep
    (stronger than stream ordering). `dispatch(rep)` must return
    `(_, device_scalar)` where the scalar reduces that rep's outputs.
    Returns (seconds_per_dispatch, n_pipe).
    """
    import numpy as np

    if n_pipe is None:
        n_pipe = 2 if _tiny() else 8
    fences = []
    t0 = time.perf_counter()
    for rep in range(start_rep, start_rep + n_pipe):
        _, fence = dispatch(rep)
        fences.append(fence)
    fence_total = float(sum(fences[1:], fences[0]))  # the one blocking read
    pipelined_s = (time.perf_counter() - t0) / n_pipe
    if not np.isfinite(fence_total):
        raise RuntimeError(f"pipelined fence reduced to {fence_total}")
    return pipelined_s, n_pipe


def bench_grid(platform: str) -> dict:
    """Equilibria/sec on the β×u grid (f32 sweep path, refinement off).

    Adaptive numerics (ISSUE 9): the headline runs the DEFAULT adaptive
    path (convergence-masked Chandrupatla + blocked crossings); a second,
    shorter pass times the bit-exact ``numerics="fixed"`` program
    back-to-back on the same shape so the artifact carries the measured
    ``adaptive_speedup`` — and the per-cell Health iteration grid yields
    ``mean_effective_iters``, the actual root-find work against the fixed
    path's constant ``bisect_iters`` budget (history schema 5).
    """
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    if _tiny():
        n_beta, n_u = 8, 8
    elif platform == "cpu":  # degraded fallback: still ≥ the 10^4-point north star
        n_beta, n_u = 128, 128
    else:
        n_beta, n_u = 640, 640  # 409.6k cells — 40× the north-star 10^4 points
    config = SolverConfig(
        n_grid=256 if _tiny() else 1024, bisect_iters=60, refine_crossings=False,
        numerics="adaptive",
    )
    config_fixed = SolverConfig(
        n_grid=256 if _tiny() else 1024, bisect_iters=60, refine_crossings=False,
        numerics="fixed",
    )
    base = make_model_params()  # Figure-5 base: β=1, η̄=15, κ=.6 (η pinned 15)

    # Reference grid domain (`scripts/1_baseline.jl:210-213`):
    # β = 1/ave_meeting_time, ave_meeting_time ∈ [1e-4, 1]; u ∈ [0.001, 1].
    amt = np.linspace(1e-4, 1.0, n_beta)
    betas = 1.0 / amt

    def make_dispatch(cfg):
        # One factory for both numerics modes so the adaptive headline and
        # the fixed control are guaranteed to time the SAME protocol —
        # identical u perturbation and fence — differing only in config.
        def dispatch(rep: int):
            # Perturb u by 1e-6 per rep: physics-identical to the metric's
            # precision, but ensures each rep is a distinct computation.
            # Returns the grid plus a DEVICE-side scalar reduction; fetching
            # that scalar to host is the fence — on the axon TPU tunnel
            # `block_until_ready` returns before device work completes, so a
            # device→host read is the only honest fence.
            us = np.linspace(0.001, 1.0, n_u) + rep * 1e-6
            grid = beta_u_grid(betas, us, base, config=cfg, dtype=jnp.float32)
            return grid, jnp.sum(grid.status) + jnp.nansum(grid.max_aw) + jnp.nansum(grid.xi)

        return dispatch

    dispatch = make_dispatch(config)

    def run(rep: int):
        grid, fence = dispatch(rep)
        return grid, float(fence)

    from sbr_tpu import obs

    t0 = time.perf_counter()
    grid, _ = run(0)  # includes compile (or a persistent-cache hit);
    # telemetry-on: routed through obs.jit_call → AOT compile/execute split
    first_s = time.perf_counter() - t0

    # Steady-state protocol runs with telemetry SUSPENDED: jit_call's
    # per-dispatch output fence would serialize the pipelined launches and
    # per-event file IO would pad dispatch_s, so the measured numbers must
    # be identical to a telemetry-off process.
    mem_peak = 0
    with obs.suspended(), obs.mem.live_disabled():
        # One untimed warm-up: rep 0 compiled via the AOT path, which does
        # not populate the plain jit cache — this retrace hits the
        # persistent compilation cache (a deserialize, not a recompile), so
        # the telemetry overhead is bounded to one dispatch and no timed
        # rep ever contains a compile. Tiny smoke runs (the test suite's
        # many harness children) skip it: there the numbers don't matter
        # and the retrace is pure suite wall-clock.
        if not _tiny():
            run(1)
        times = []
        for rep in range(2, 5):
            t0 = time.perf_counter()
            grid, _ = run(rep)
            times.append(time.perf_counter() - t0)
            mem_peak = _rep_peak_bytes(mem_peak)  # after the clock stopped
        dispatch_s = min(times)

        pipelined_s, n_pipe = pipelined_time(dispatch, start_rep=5)
        mem_peak = _rep_peak_bytes(mem_peak)

        # Fixed-numerics control pass (ISSUE 9): the bit-exact legacy
        # program on the same shape, timed with the same fenced protocol
        # (compile rep + 2 timed reps, min). Runs inside the suspended
        # envelope so neither program's timing carries telemetry overhead.
        # Tiny smoke runs skip it like the warm-up above — a second program
        # compile purely for a speedup number the suite never reads; the
        # zero default is falsy, so _measure_inner drops the schema-5 keys.
        fixed_s = 0.0
        if not _tiny():
            dispatch_fixed = make_dispatch(config_fixed)
            _, fence = dispatch_fixed(2)
            float(fence)  # compile + fence
            fixed_times = []
            for rep in range(3, 5):
                t0 = time.perf_counter()
                _, fence = dispatch_fixed(rep)
                float(fence)
                fixed_times.append(time.perf_counter() - t0)
            fixed_s = min(fixed_times)
    elapsed = min(dispatch_s, pipelined_s)
    # Speedup compares MATCHED protocols: single fenced dispatch vs single
    # fenced dispatch. The headline eq/sec may additionally benefit from
    # pipelining; crediting that to "adaptive" would inflate the gated
    # metric with launch-latency hiding unrelated to the numerics.
    speedup = fixed_s / dispatch_s if dispatch_s > 0 else 0.0
    # Zero in tiny mode like the other schema-5 keys: iteration statistics
    # at the reduced smoke shape must not enter a history that gates
    # lower-is-better _iters against real tier-1 baselines.
    mean_iters = (
        0.0
        if _tiny()
        else float(np.asarray(grid.health.iterations, dtype=np.float64).mean())
    )

    _profile_rep("bench.grid", 5, lambda: run(5))

    n_cells = n_beta * n_u
    n_run = int(np.sum(np.asarray(grid.status) == 0))
    control = (
        f"; fixed-numerics control {fixed_s:.3f}s (adaptive speedup "
        f"{speedup:.2f}x, mean effective iters {mean_iters:.1f} "
        f"vs budget {config.bisect_iters})"
        if fixed_s
        else ""
    )
    _log(
        f"grid: {n_cells} cells in {elapsed:.3f}s steady-state "
        f"({pipelined_s:.3f}s/dispatch pipelined ×{n_pipe}, {dispatch_s:.3f}s "
        f"single fenced dispatch; first call {first_s:.1f}s incl. compile); "
        f"{n_run} run cells{control}"
    )
    return {
        "eq_per_sec": n_cells / elapsed,
        "n_cells": n_cells,
        "first_call_s": first_s,
        "steady_s": elapsed,
        "dispatch_s": dispatch_s,
        "pipelined_s": pipelined_s,
        "n_pipe": n_pipe,
        "mem_peak_bytes": mem_peak,
        "fixed_steady_s": fixed_s,
        "adaptive_speedup": speedup,
        "mean_effective_iters": mean_iters,
    }


def bench_agents(platform: str) -> dict:
    """Agent-steps/sec + on-device graph generation (ISSUE 10): 10^7
    agents, Erdős–Rényi deg 10 → 10^8 edges, f32 — on every non-tiny
    platform, CPU included (the pre-0.8 host pipeline capped CPU at 10^5
    agents because the edge list transited host RAM; ~2.4 GB at this
    shape).

    Three stages, reported SEPARATELY (graph-gen throughput must not
    launder into step throughput or vice versa):

    - generation: `graphgen.prepare_generated_graph` builds the canonical
      dst-sorted layout on device, chunked and capacity-planned against
      the memory observatory (`plan_chunk_edges`). Steady-state rebuilds
      → `graph_build_s` / `graph_gen_edges_per_sec` (history schema 6).
    - host control at the 10^7-edge comparison shape (10^6 agents): the
      device generator vs the HOST NUMPY pipeline (`erdos_renyi_edges` +
      prepare under ``SBR_NATIVE=0`` — the portable baseline; the C
      counting sort is not numpy and not everywhere) → `graph_gen_speedup`.
      Skipped in tiny mode (sub-second shapes measure noise; the zero is
      dropped before history like the other reduced-shape stats).
    - simulation: unchanged steady-state protocol on the generated graph
      (engine pinned "incremental" at the mega shape — the census answer
      at this scale, pinned so the bench never times two engines across
      rounds; the out-edge orientation it needs is the counting-sort part
      of the build and lands in `prep_s`, not in the generation metric).
    """
    import numpy as np

    from sbr_tpu.social import AgentSimConfig, simulate_agents
    from sbr_tpu.social.graphgen import ErdosRenyiSpec, prepare_generated_graph

    tiny = _tiny()
    if tiny:
        n, n_steps, engine = 2_000, 20, "auto"
    elif platform == "cpu":
        n, n_steps, engine = 10_000_000, 50, "incremental"
    else:
        n, n_steps, engine = 10_000_000, 200, "incremental"
    spec = ErdosRenyiSpec(n=n, avg_degree=10.0)
    cfg = AgentSimConfig(n_steps=n_steps, dt=0.05)

    # --- generation stage: canonical-layout builds, cold then steady ---
    t0 = time.perf_counter()
    pg_g = prepare_generated_graph(spec, seed=0, engine="gather", config=cfg)
    pg_g.src.block_until_ready()
    build_first_s = time.perf_counter() - t0
    build_times = []
    for _ in range(2):
        t0 = time.perf_counter()
        pg_g = prepare_generated_graph(spec, seed=0, engine="gather", config=cfg)
        pg_g.src.block_until_ready()
        build_times.append(time.perf_counter() - t0)
    build_s = min(build_times)
    e = pg_g.n_edges
    gen_rate = e / build_s
    _log(
        f"agents: {e} edges generated on device in {build_s:.2f}s steady "
        f"({gen_rate / 1e6:.1f}M edges/s; first build {build_first_s:.2f}s "
        f"incl. compile)"
    )
    del pg_g

    # --- host control: device vs host-numpy at the 10^7-edge shape ---
    gen_speedup = host_rate = 0.0
    if not tiny:
        from sbr_tpu.social import erdos_renyi_edges, prepare_agent_graph

        spec_c = ErdosRenyiSpec(n=1_000_000, avg_degree=10.0)
        dev_t = []
        for _ in range(2):
            t0 = time.perf_counter()
            pg_c = prepare_generated_graph(spec_c, seed=0, engine="gather", config=cfg)
            pg_c.src.block_until_ready()
            dev_t.append(time.perf_counter() - t0)
        e_c = pg_c.n_edges
        del pg_c
        host_t = []
        prev_native = os.environ.get("SBR_NATIVE")
        os.environ["SBR_NATIVE"] = "0"
        try:
            for _ in range(2):
                t0 = time.perf_counter()
                src_h, dst_h = erdos_renyi_edges(spec_c.n, 10.0, seed=0)
                pg_h = prepare_agent_graph(
                    1.0, src_h, dst_h, spec_c.n, config=cfg, engine="gather"
                )
                pg_h.src.block_until_ready()
                host_t.append(time.perf_counter() - t0)
            e_h = len(src_h)
            del pg_h, src_h, dst_h
        finally:
            if prev_native is None:
                os.environ.pop("SBR_NATIVE", None)
            else:
                os.environ["SBR_NATIVE"] = prev_native
        host_rate = e_h / min(host_t)
        gen_speedup = (e_c / min(dev_t)) / host_rate
        _log(
            f"agents: device {e_c / min(dev_t) / 1e6:.1f}M vs host-numpy "
            f"{host_rate / 1e6:.1f}M edges/s at the 10^7-edge shape "
            f"({gen_speedup:.1f}x)"
        )

    # --- simulation stage: prepared once (engine-specific structures on
    # top of the canonical layout land here, not in the gen metric) ---
    t0 = time.perf_counter()
    pg = prepare_generated_graph(spec, seed=0, engine=engine, config=cfg)
    (pg.inc[0] if pg.inc is not None else pg.src).block_until_ready()
    prep_s = time.perf_counter() - t0
    _log(f"agents: graph prepared (engine={pg.engine}) in {prep_s:.1f}s")

    def run(seed: int):
        res = simulate_agents(prepared=pg, x0=1e-4, config=cfg, seed=seed)
        fence = float(res.informed_frac[-1])  # device→host read as the fence
        return res, fence

    t0 = time.perf_counter()
    res0, frac0 = run(0)
    first_s = time.perf_counter() - t0
    from sbr_tpu import obs

    mem_peak = 0
    times = []
    with obs.mem.live_disabled():  # O(live arrays) sum stays out of timed reps
        for seed in (1, 2):
            t0 = time.perf_counter()
            _, _ = run(seed)
            times.append(time.perf_counter() - t0)
            mem_peak = _rep_peak_bytes(mem_peak)
    elapsed = min(times)
    _profile_rep("bench.agents", 3, lambda: run(3))
    # engine observability in the artifact: which steps were full recounts
    # (telemetry is seed-stable at this shape in aggregate; seed 0's count
    # documents the capture's engine behavior)
    recounts = int(np.asarray(res0.full_recount_steps).sum())

    steps = n * n_steps
    _log(
        f"agents: {steps} agent-steps in {elapsed:.3f}s steady-state "
        f"(first call {first_s:.1f}s incl. compile, prep {prep_s:.1f}s); "
        f"final G = {frac0:.4f}; {recounts}/{n_steps} recount steps"
    )
    return {
        "agent_steps_per_sec": steps / elapsed,
        "n_agents": n,
        "n_steps": n_steps,
        "n_edges": e,
        "first_call_s": first_s,
        "steady_s": elapsed,
        "prep_s": prep_s,
        "engine": pg.engine,
        "recount_steps": recounts,
        "mem_peak_bytes": mem_peak,
        # Schema-6 generation metrics — zeroed in tiny mode (sub-second
        # builds measure dispatch noise; the zeros are dropped before
        # history like the other reduced-shape stats).
        "graph_build_first_s": build_first_s,
        "graph_build_s": 0.0 if tiny else build_s,
        "graph_gen_edges_per_sec": 0.0 if tiny else gen_rate,
        "graph_gen_speedup": gen_speedup,
        "host_gen_edges_per_sec": host_rate,
    }


def bench_serve(platform: str) -> dict:
    """Serving latency/cache workload (ISSUE 7): drive the seeded loadgen
    mix through an in-process `sbr_tpu.serve.Engine` — warmup pass over the
    parameter pool (compiles the bucket executables, fills the result
    cache), then the measured repeated-mix phase. Headline numbers are the
    measured-phase latency quantiles from the live log-bucket histogram and
    the cache hit rate; `report trend` gates them as schema-3 history
    metrics (serve_p50_ms / serve_p99_ms lower-better,
    serve_cache_hit_rate higher-better)."""
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.serve.engine import Engine, ServeConfig
    from sbr_tpu.serve.loadgen import build_pool, query_mix

    if _tiny():
        pool_n, n_queries, n_grid = 6, 48, 96
    elif platform == "cpu":
        pool_n, n_queries, n_grid = 32, 512, 512
    else:
        pool_n, n_queries, n_grid = 64, 2048, 1024
    config = SolverConfig(n_grid=n_grid, bisect_iters=60, refine_crossings=False)
    pool = build_pool(0, pool_n)
    mix = query_mix(0, pool_n, n_queries)

    engine = Engine(config=config, serve=ServeConfig(buckets=(1, 8, 64)))
    engine.start()
    try:
        t0 = time.perf_counter()
        for i in range(0, len(pool), 16):
            engine.query_many(pool[i : i + 16], scenario="warmup")
        warmup_s = time.perf_counter() - t0
        warm = engine.live.snapshot()
        # Measured-phase latency histogram = lifetime histogram delta across
        # the phase (LogHistogram.delta): the 60 s rolling window would fold
        # the warmup's compile-heavy latencies into the quantiles.
        hist_before = engine.live.total_hist.copy()

        t0 = time.perf_counter()
        for i in range(0, len(mix), 16):
            engine.query_many([pool[j] for j in mix[i : i + 16]], scenario="mix")
        measured_s = time.perf_counter() - t0

        snap = engine.live.snapshot()
        diff = engine.live.total_hist.delta(hist_before)
        totals, wt = snap["totals"], warm["totals"]
        measured_q = totals["queries"] - wt["queries"]
        measured_hits = totals["cache_hits"] - wt["cache_hits"]
    finally:
        engine.close()
    p50, p99 = diff.quantile(0.5), diff.quantile(0.99)
    hit_rate = measured_hits / measured_q if measured_q else 0.0
    _log(
        f"serve: {measured_q} queries in {measured_s:.3f}s "
        f"(warmup {len(pool)} in {warmup_s:.1f}s); p50 {p50} ms, "
        f"p99 {p99} ms, cache hit rate {hit_rate:.2f}"
    )
    return {
        "serve_queries": int(measured_q),
        "serve_pool": pool_n,
        "serve_p50_ms": p50,
        "serve_p99_ms": p99,
        "serve_cache_hit_rate": round(hit_rate, 4),
        "serve_qps": round(measured_q / measured_s, 1) if measured_s else 0.0,
        "serve_warmup_s": round(warmup_s, 3),
    }


def bench_fleet(platform: str) -> dict:
    """Serving-fleet SLO workload (ISSUE 11): the multi-process fleet —
    N worker subprocesses behind an in-process router — driven with the
    seeded loadgen mix over HTTP. Headline numbers are the client-observed
    measured-phase p99 through the router (fleet_p99_ms, lower-better),
    the failover count, and the admission shed rate; `report trend` gates
    them as schema-7 history metrics. Tiny shapes run the pipeline but
    zero the gated stats (reduced-shape numbers must not baseline the
    trend gate, the established dry-run rule)."""
    from types import SimpleNamespace

    from sbr_tpu.serve.loadgen import run_fleet

    tiny = _tiny()
    if tiny:
        n_workers, n_queries, pool_n, n_grid = 2, 16, 4, 96
    elif platform == "cpu":
        n_workers, n_queries, pool_n, n_grid = 3, 256, 16, 256
    else:
        n_workers, n_queries, pool_n, n_grid = 3, 1024, 32, 512
    args = SimpleNamespace(
        fleet=n_workers, queries=n_queries, pool=pool_n, group=8,
        n_grid=n_grid, bisect_iters=40 if tiny else 60, seed=0,
        buckets="1,8" if tiny else "1,8,64", run_dir=None, cache_dir=None,
        platform="cpu" if platform == "cpu" else None, fleet_dir=None,
        fleet_kill_after=None, answers_out=None, trace_out=None,
    )
    summary = run_fleet(args)
    if summary["failures"] or summary.get("fleet_lost", 0):
        raise RuntimeError(f"fleet bench lost queries: {summary['failures']}")
    _log(
        f"fleet: {summary['answered']}/{n_queries} queries over "
        f"{n_workers} worker(s); p50 {summary['fleet_p50_ms']} ms, "
        f"p99 {summary['fleet_p99_ms']} ms, "
        f"{summary['fleet_failover_count']} failover(s), "
        f"shed rate {summary['fleet_shed_rate']}, {summary['fleet_qps']} qps"
    )
    return {
        "fleet_workers": n_workers,
        "fleet_queries": int(summary["answered"]),
        "fleet_qps": summary["fleet_qps"],
        # Gated schema-7 stats: None (dropped by measure()) on tiny shapes
        # so a dry-run can never seed the regression baselines — None, not
        # 0, because 0 is a MEANINGFUL baseline for failovers/sheds (any
        # increase from a clean fleet regresses, the zero-baseline rule).
        "fleet_p99_ms": None if tiny else summary["fleet_p99_ms"],
        "fleet_failover_count": None if tiny else summary["fleet_failover_count"],
        "fleet_shed_rate": None if tiny else summary["fleet_shed_rate"],
    }


def bench_sweep(platform: str) -> dict:
    """Tiled-sweep workload (ISSUE 8): one cold elastic tiled sweep through
    `run_tiled_grid_multihost` (heartbeats, claim plan, leases), then a
    WARM re-sweep of the same grid into a fresh checkpoint dir with the
    cross-run global tile cache hot — the serving-fleet traffic shape where
    repeated sweeps re-request mostly-warm parameter regions. Headline
    numbers: cold compute throughput, warm cache-served throughput, and
    the warm hit rate (actual `cache` hit events over the tile count);
    `report trend` gates them as schema-4 history metrics (all
    higher-better)."""
    import shutil
    import tempfile

    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.parallel import run_tiled_grid_multihost

    import numpy as np

    if _tiny():
        n_beta, n_u, tile, n_grid = 8, 8, (4, 4), 96
    elif platform == "cpu":
        n_beta, n_u, tile, n_grid = 32, 32, (16, 16), 256
    else:
        n_beta, n_u, tile, n_grid = 128, 128, (64, 64), 1024
    config = SolverConfig(n_grid=n_grid, bisect_iters=60, refine_crossings=False)
    base = make_model_params()
    betas = np.linspace(0.5, 2.0, n_beta)
    us = np.linspace(0.02, 0.5, n_u)
    n_cells = n_beta * n_u

    from sbr_tpu import obs

    # Warm hits are counted from the obs `cache` event roll-up, NOT from a
    # cache-entry count delta: a warm recompute stores back under the
    # IDENTICAL deterministic key (os.replace), so the entry count cannot
    # distinguish "all hits" from "cache broken, all recomputed".
    run = obs.active_run()

    def _cache_counts() -> dict:
        return dict(run.elastic["cache"]) if run is not None else {}

    scratch = Path(tempfile.mkdtemp(prefix="sbr_bench_sweep_"))
    try:
        cache = scratch / "tile_cache"
        kwargs = dict(
            config=config, tile_shape=tile, poll_s=0.1, timeout_s=1800.0,
            elastic=True, tile_cache_dir=str(cache),
        )
        t0 = time.perf_counter()
        run_tiled_grid_multihost(betas, us, base, str(scratch / "ckpt_cold"), **kwargs)
        cold_s = time.perf_counter() - t0
        entries_cold = len(list(cache.rglob("*.npz")))

        before_warm = _cache_counts()
        t0 = time.perf_counter()
        run_tiled_grid_multihost(betas, us, base, str(scratch / "ckpt_warm"), **kwargs)
        warm_s = time.perf_counter() - t0
        after_warm = _cache_counts()
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    n_tiles = max(entries_cold, 1)
    warm_hits = after_warm.get("hit", 0) - before_warm.get("hit", 0)
    hit_rate = min(1.0, max(0.0, warm_hits / n_tiles)) if run is not None else 0.0
    _log(
        f"sweep: {n_cells} cells cold in {cold_s:.3f}s, warm in {warm_s:.3f}s "
        f"({entries_cold} tile(s) cached, {warm_hits} warm hit(s), "
        f"hit rate {hit_rate:.2f})"
    )
    return {
        "sweep_cells": n_cells,
        "sweep_tiles": entries_cold,
        "sweep_cold_s": round(cold_s, 3),
        "sweep_warm_s": round(warm_s, 3),
        "sweep_cold_cells_per_sec": round(n_cells / cold_s, 1) if cold_s else 0.0,
        "sweep_warm_cells_per_sec": round(n_cells / warm_s, 1) if warm_s else 0.0,
        "sweep_warm_hit_rate": round(hit_rate, 4),
    }


def bench_grad(platform: str) -> dict:
    """Differentiable-equilibria workload (ISSUE 13): IFT gradient
    throughput + calibration speed.

    Part 1 times `grad.api.sensitivity_surface` — the vmapped
    value-and-grad grid program — with the fenced single-dispatch
    protocol: `grads_per_sec` counts PARTIAL DERIVATIVES per second
    (cells × len(wrt)), the honest unit for a program whose cost scales
    with the wrt set. Part 2 times `grad.calibrate.fit_withdrawals` on the
    deterministic synthetic fixture: `calib_steps_per_sec` counts jitted
    Adam steps (compile excluded — one untimed step first). Tiny shapes
    zero the gated keys so reduced-shape stats never seed a baseline."""
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu.grad import api, calibrate
    from sbr_tpu.models.params import SolverConfig, make_model_params, with_overrides

    if _tiny():
        n_beta = n_u = 6
        n_grid = 128
        calib_steps = 8
    else:
        n_beta = n_u = 32 if platform == "cpu" else 96
        n_grid = 384 if platform == "cpu" else 1024
        calib_steps = 120
    config = SolverConfig(n_grid=n_grid, bisect_iters=60, refine_crossings=False)
    wrt = ("beta", "u", "kappa")
    base = make_model_params()
    betas = np.linspace(0.5, 2.5, n_beta)

    from sbr_tpu import obs

    def dispatch(rep: int):
        us = np.linspace(0.03, 0.3, n_u) + rep * 1e-7
        surf = api.sensitivity_surface(betas, us, base, wrt=wrt, config=config)
        fence = jnp.nansum(surf.xi) + sum(jnp.nansum(g) for g in surf.grads.values())
        return surf, fence

    t0 = time.perf_counter()
    _, fence = dispatch(0)
    float(fence)  # compile + fence
    first_s = time.perf_counter() - t0

    with obs.suspended(), obs.mem.live_disabled():
        times = []
        for rep in range(1, 4):
            t0 = time.perf_counter()
            _, fence = dispatch(rep)
            float(fence)
            times.append(time.perf_counter() - t0)
        surface_s = min(times)

        # Calibration: plant θ*, fit from a perturbed run-region init; one
        # untimed step burns the compile so the rate is steady-state.
        truth = make_model_params(beta=1.4, u=0.12, kappa=0.55)
        t_obs, aw_obs, xi_obs = calibrate.synth_withdrawals(
            truth, n_obs=48, config=config
        )
        init = with_overrides(truth, beta=1.1, u=0.15, kappa=0.62)
        calibrate.fit_withdrawals(
            t_obs, aw_obs, init, xi_obs=xi_obs, steps=1, config=config
        )
        t0 = time.perf_counter()
        fit = calibrate.fit_withdrawals(
            t_obs, aw_obs, init, xi_obs=xi_obs, steps=calib_steps,
            loss_tol=0.0, config=config,
        )
        calib_s = time.perf_counter() - t0

    n_cells = n_beta * n_u
    n_grads = n_cells * len(wrt)
    grads_per_sec = 0.0 if _tiny() else n_grads / surface_s
    calib_rate = 0.0 if _tiny() else fit.steps / calib_s
    _log(
        f"grad: {n_grads} partials over {n_cells} cells in {surface_s:.3f}s "
        f"steady ({first_s:.1f}s first incl. compile); calibration "
        f"{fit.steps} step(s) in {calib_s:.3f}s (converged={fit.converged}, "
        f"loss {fit.loss:.2e})"
    )
    return {
        "grad_cells": n_cells,
        "grad_surface_s": round(surface_s, 4),
        "grad_first_call_s": round(first_s, 2),
        "grads_per_sec": round(grads_per_sec, 1),
        "calib_steps_per_sec": round(calib_rate, 2),
        "calib_converged": bool(fit.converged),
        "calib_loss": fit.loss,
    }


def bench_scenario(platform: str) -> dict:
    """Composable-scenario workload (ISSUE 14): composition-layer overhead
    + multi-bank contagion throughput.

    Part 1 times the SAME β×u shape through `scenario.scenario_grid` with
    the baseline-reducible spec and through the legacy `beta_u_grid`
    program, back-to-back with the fenced protocol:
    ``scenario_overhead_ratio`` = composed steady / legacy steady — the
    composed cell IS `solve_param_cell`, so a ratio drifting above ~1
    means the composition layer grew a real cost (history schema 9,
    lower-better). Part 2 times an N-bank contagion solve on a ring
    exposure network: ``scenario_multibank_cells_per_sec`` counts
    bank-cells per second (contagion iterations × banks / wall). Tiny
    dry-run shapes zero the gated keys so reduced-shape stats never seed
    a baseline."""
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu import scenario
    from sbr_tpu.models.params import SolverConfig, make_model_params

    if _tiny():
        n_beta = n_u = 8
        n_grid = 128
        n_banks = 3
    elif platform == "cpu":
        n_beta = n_u = 96
        n_grid = 512
        n_banks = 16
    else:
        n_beta = n_u = 256
        n_grid = 1024
        n_banks = 64
    config = SolverConfig(n_grid=n_grid, bisect_iters=60, refine_crossings=False)
    base = make_model_params()
    betas = np.linspace(0.25, 3.0, n_beta)
    spec = scenario.ScenarioSpec()  # baseline-reducible: the overhead probe

    from sbr_tpu import obs
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    def composed(rep: int):
        us = np.linspace(0.01, 0.99, n_u) + rep * 1e-6
        g = scenario.scenario_grid(spec, betas, us, base, config=config, dtype=jnp.float32)
        return float(jnp.sum(g.status) + jnp.nansum(g.xi))

    def legacy(rep: int):
        us = np.linspace(0.01, 0.99, n_u) + rep * 1e-6
        g = beta_u_grid(betas, us, base, config=config, dtype=jnp.float32)
        return float(jnp.sum(g.status) + jnp.nansum(g.xi))

    t0 = time.perf_counter()
    composed(0)  # compile
    first_s = time.perf_counter() - t0
    legacy(0)

    with obs.suspended(), obs.mem.live_disabled():
        comp_s = min(
            _timed(lambda r=r: composed(r)) for r in (1, 2, 3)
        )
        leg_s = min(
            _timed(lambda r=r: legacy(r)) for r in (1, 2, 3)
        )

        # Multi-bank contagion: a directed ring of exposures, every bank
        # fragile enough that spillovers move κ and the loop iterates.
        ring = tuple(
            (i, (i + 1) % n_banks, 0.6) for i in range(n_banks)
        )
        # tol at f32 resolution: the bench child runs without x64, and a
        # tighter tol than the dtype can express just burns max_iter.
        mb_spec = scenario.ScenarioSpec(
            banks=n_banks, exposure=ring, contagion_max_iter=12, contagion_tol=1e-5
        )
        plist = [
            make_model_params(beta=1.0 + 0.5 * (i / max(n_banks - 1, 1)), u=0.05)
            for i in range(n_banks)
        ]
        scenario.solve_multibank(mb_spec, plist, config=config)  # compile
        t0 = time.perf_counter()
        mb = scenario.solve_multibank(mb_spec, plist, config=config)
        jnp.asarray(mb.status).block_until_ready()
        mb_s = time.perf_counter() - t0

    overhead = comp_s / leg_s if leg_s > 0 else 0.0
    mb_cells = mb.iterations * n_banks / mb_s if mb_s > 0 else 0.0
    _log(
        f"scenario: composed {comp_s:.3f}s vs legacy {leg_s:.3f}s "
        f"({overhead:.3f}x overhead, {first_s:.1f}s first incl. compile); "
        f"multibank {n_banks} banks x {mb.iterations} round(s) in {mb_s:.3f}s "
        f"({mb_cells:.1f} bank-cells/s, converged={mb.converged})"
    )
    return {
        "scenario_cells": n_beta * n_u,
        "scenario_composed_s": round(comp_s, 4),
        "scenario_legacy_s": round(leg_s, 4),
        "scenario_first_call_s": round(first_s, 2),
        "scenario_overhead_ratio": 0.0 if _tiny() else round(overhead, 4),
        "scenario_multibank_cells_per_sec": 0.0 if _tiny() else round(mb_cells, 1),
        "scenario_multibank_banks": n_banks,
        "scenario_multibank_iterations": mb.iterations,
        "scenario_multibank_converged": bool(mb.converged),
    }


def bench_infomodels(platform: str) -> dict:
    """Information-model workload (ISSUE 15): fused Bayesian belief-update
    throughput + population what-if query rate.

    Part 1 runs the bayes observer kernel (per-step `_seg_counts` recount
    + fused `belief_update`) on a device-generated ER graph and reports
    steady belief-updates/sec (= agent-steps/sec of the bayes channel).
    Part 2 times end-to-end population ξ-distribution queries (mean-field
    fixed point shared, S member sims + crossing reduction per query) at
    the serving query shape. History schema 10; tiny dry-run shapes zero
    the gated keys so reduced-shape stats never seed a baseline."""
    import numpy as np

    from sbr_tpu import obs
    from sbr_tpu.infomodels import InfoModelSpec, population_query
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.social.agents import AgentSimConfig
    from sbr_tpu.social.graphgen import ErdosRenyiSpec

    if _tiny():
        n_agents, deg, n_steps = 2_000, 8.0, 20
        pop_n, pop_seeds, pop_queries = 1_000, 2, 1
    elif platform == "cpu":
        n_agents, deg, n_steps = 200_000, 10.0, 60
        pop_n, pop_seeds, pop_queries = 5_000, 8, 3
    else:
        n_agents, deg, n_steps = 2_000_000, 10.0, 100
        pop_n, pop_seeds, pop_queries = 20_000, 16, 3

    from sbr_tpu.infomodels import simulate_info

    spec = InfoModelSpec(channel="bayes")
    graph = ErdosRenyiSpec(n=n_agents, avg_degree=deg)
    cfg = AgentSimConfig(n_steps=n_steps, dt=0.05, reentry_delay=3.0)

    def sim():
        r = simulate_info(spec, graph, x0=0.01, config=cfg, seed=1)
        float(np.asarray(r.informed_frac)[-1])  # device→host fence
        return r

    t0 = time.perf_counter()
    sim()  # compile + graph build
    first_s = time.perf_counter() - t0
    with obs.suspended(), obs.mem.live_disabled():
        steady_s = min(_timed(sim) for _ in range(2))
    updates_per_sec = n_agents * n_steps / steady_s if steady_s > 0 else 0.0

    # Population queries: distinct seeds so no layer can answer from a
    # warm record — this times the full solve+simulate+reduce path.
    model = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99,
                              kappa=0.25, lam=0.25)
    pop_graph = ErdosRenyiSpec(n=pop_n, avg_degree=10.0)
    pop_cfg = SolverConfig(n_grid=256)
    rec0 = population_query(  # warm-up: compiles + the shared fixed point
        spec, pop_graph, model, seeds=pop_seeds, vary="sim", g0=None,
        config=pop_cfg,
    )
    with obs.suspended(), obs.mem.live_disabled():
        t0 = time.perf_counter()
        for q in range(pop_queries):
            population_query(
                spec, pop_graph, model, seeds=pop_seeds, vary="sim",
                seed=10_000 + q, g0=None, config=pop_cfg,
            )
        pop_s = time.perf_counter() - t0
    queries_per_sec = pop_queries / pop_s if pop_s > 0 else 0.0

    _log(
        f"infomodels: {n_agents} agents x {n_steps} belief steps in "
        f"{steady_s:.3f}s steady ({updates_per_sec:.0f} updates/s, "
        f"{first_s:.1f}s first incl. compile); {pop_queries} population "
        f"quer(ies) x {pop_seeds} seeds @ {pop_n} agents in {pop_s:.3f}s "
        f"({queries_per_sec:.2f} q/s, run_p={rec0['run_probability']:.2f})"
    )
    return {
        "infomodel_agents": n_agents,
        "infomodel_steps": n_steps,
        "infomodel_first_call_s": round(first_s, 2),
        "infomodel_steady_s": round(steady_s, 4),
        "infomodel_belief_updates_per_sec": (
            0.0 if _tiny() else round(updates_per_sec, 1)
        ),
        "infomodel_population_queries_per_sec": (
            0.0 if _tiny() else round(queries_per_sec, 4)
        ),
        "infomodel_population_seeds": pop_seeds,
        "infomodel_population_run_probability": rec0["run_probability"],
    }


def bench_audit(platform: str) -> dict:
    """Numerics-audit workload (ISSUE 17): canary-battery probe throughput
    + serve-loop overhead of the idle-gated audit scheduler.

    Part 1 generates goldens for a cheap probe subset into a temp registry
    (compiles the probe solves), then times a steady battery pass →
    audit_probes_per_sec. Part 2 drives the same seeded query mix through
    an in-process Engine twice — audit scheduler OFF (control) then ON with
    a short interval so canaries really interleave with the idle gaps — and
    reports audit_overhead_ratio = on/off steady time (lower-better by the
    overhead rule; ~1.0 means canaries are invisible to the hot path).
    History schema 11; tiny dry-run shapes zero the gated keys so
    reduced-shape stats never seed a baseline."""
    import tempfile

    from sbr_tpu import obs
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.obs import audit
    from sbr_tpu.serve.engine import Engine, ServeConfig
    from sbr_tpu.serve.loadgen import build_pool, query_mix

    if _tiny():
        probe_names = ["graphgen.layout"]
        pool_n, n_queries, n_grid, rounds = 4, 24, 64, 1
    elif platform == "cpu":
        probe_names = ["graphgen.layout", "scenario.composed", "infomodel.gossip"]
        pool_n, n_queries, n_grid, rounds = 16, 192, 256, 2
    else:
        probe_names = ["graphgen.layout", "scenario.composed", "infomodel.gossip"]
        pool_n, n_queries, n_grid, rounds = 32, 512, 512, 2

    reg = tempfile.mkdtemp(prefix="sbr_audit_bench_")
    # Golden generation doubles as the compile warm-up: the scheduler in
    # part 2 runs in THIS process, so its canaries reuse these executables.
    audit.run_battery(update=True, probe_names=probe_names, reg_dir=reg,
                      emit=False)
    with obs.suspended(), obs.mem.live_disabled():
        battery_s = min(
            _timed(lambda: audit.run_battery(
                probe_names=probe_names, reg_dir=reg, emit=False))
            for _ in range(2)
        )
    probes_per_sec = len(probe_names) / battery_s if battery_s > 0 else 0.0

    if _tiny():
        # The overhead ratio is zeroed-and-dropped at tiny sizes anyway —
        # don't burn two engine warm-ups in the dry-run pipeline for it.
        _log(
            f"audit: {len(probe_names)} probe(s) battery in {battery_s:.3f}s "
            "steady (tiny: overhead phase skipped)"
        )
        return {
            "audit_probe_count": len(probe_names),
            "audit_battery_s": round(battery_s, 4),
            "audit_probes_per_sec": 0.0,
            "audit_overhead_ratio": 0.0,
            "audit_off_s": 0.0,
            "audit_on_s": 0.0,
            "audit_canary_cycles": 0,
        }

    config = SolverConfig(n_grid=n_grid, bisect_iters=40, refine_crossings=False)
    pool = build_pool(0, pool_n)
    mix = query_mix(0, pool_n, n_queries)
    audit_env = {
        "SBR_AUDIT_REGISTRY_DIR": reg,
        "SBR_AUDIT_INTERVAL_S": "0.5",
        "SBR_AUDIT_PROBES": ",".join(probe_names),
    }

    def drive(audit_on: bool):
        flip = {"SBR_AUDIT": "1" if audit_on else "0", **audit_env}
        old = {k: os.environ.get(k) for k in flip}
        os.environ.update(flip)
        try:
            engine = Engine(config=config, serve=ServeConfig(buckets=(1, 8)))
            engine.start()
            try:
                for i in range(0, len(pool), 8):
                    engine.query_many(pool[i : i + 8], scenario="warmup")
                with obs.suspended(), obs.mem.live_disabled():
                    t0 = time.perf_counter()
                    for _ in range(rounds):
                        for i in range(0, len(mix), 8):
                            engine.query_many(
                                [pool[j] for j in mix[i : i + 8]],
                                scenario="mix",
                            )
                    dt = time.perf_counter() - t0
                cycles = (
                    engine.audit.snapshot()["cycles"]
                    if engine.audit is not None else 0
                )
            finally:
                engine.close()
            return dt, cycles
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    off_s, _ = drive(False)
    on_s, cycles = drive(True)
    overhead = on_s / off_s if off_s > 0 else 0.0

    _log(
        f"audit: {len(probe_names)} probe(s) battery in {battery_s:.3f}s "
        f"steady ({probes_per_sec:.2f} probes/s); serve mix "
        f"{len(mix) * rounds} queries audit-off {off_s:.3f}s vs audit-on "
        f"{on_s:.3f}s (overhead x{overhead:.3f}, {cycles} canary cycle(s))"
    )
    return {
        "audit_probe_count": len(probe_names),
        "audit_battery_s": round(battery_s, 4),
        "audit_probes_per_sec": 0.0 if _tiny() else round(probes_per_sec, 3),
        "audit_overhead_ratio": 0.0 if _tiny() else round(overhead, 4),
        "audit_off_s": round(off_s, 3),
        "audit_on_s": round(on_s, 3),
        "audit_canary_cycles": int(cycles),
    }


def bench_demand(platform: str) -> dict:
    """Workload-demand observatory (ISSUE 18): streaming sketch/histogram
    update throughput + the router's fleet-merge cost.

    Part 1 streams a seeded loadgen-shaped query mix through one
    `DemandTracker.record` loop (per query: fixed-grid bin update +
    Misra-Gries sketch update + source label) → demand_updates_per_sec —
    the per-query cost the serving hot path pays at SBR_DEMAND=1. Part 2
    builds W workers' compact heartbeat surfaces from disjoint mix shards
    and times the router-side `merge_surfaces` fold → demand_merge_ms per
    fleet merge (what every /statz scrape and fleet.json write costs).
    Pure host bookkeeping — no engine, no device. History schema 12; tiny
    dry-run shapes zero the gated keys so reduced-shape stats never seed
    a baseline."""
    from sbr_tpu.obs import demand as dm
    from sbr_tpu.serve.loadgen import build_pool, query_mix

    if _tiny():
        pool_n, n_updates, workers, merges = 16, 2_000, 2, 5
    else:
        pool_n, n_updates, workers, merges = 256, 200_000, 8, 200

    pool = build_pool(0, pool_n)
    mix = query_mix(0, pool_n, n_updates)
    coords = [(p.learning.beta, p.economic.u) for p in pool]
    sources = ("computed", "lru", "disk", "tilecache")

    tracker = dm.DemandTracker(window_s=3600.0, bins=16, topk_n=32)
    t0 = time.perf_counter()
    for qi, idx in enumerate(mix):
        b, u = coords[idx]
        tracker.record(b, u, scenario="mix", source=sources[qi & 3])
    update_s = time.perf_counter() - t0
    updates_per_sec = n_updates / update_s if update_s > 0 else 0.0

    shard = max(len(mix) // workers, 1)
    blocks = []
    for w in range(workers):
        wt = dm.DemandTracker(window_s=3600.0, bins=16, topk_n=32)
        for qi, idx in enumerate(mix[w * shard : (w + 1) * shard]):
            b, u = coords[idx]
            wt.record(b, u, scenario="mix", source=sources[qi & 3])
        blocks.append(wt.heartbeat_block())
    t0 = time.perf_counter()
    for _ in range(merges):
        merged = dm.merge_surfaces(blocks)
    merge_ms = (time.perf_counter() - t0) / merges * 1e3

    _log(
        f"demand: {n_updates} updates in {update_s:.3f}s "
        f"({updates_per_sec:.0f}/s); {workers}-worker fleet merge "
        f"{merge_ms:.3f}ms ({merged['queries']} queries, "
        f"{len(merged['cells'])} cells)"
    )
    return {
        "demand_updates": n_updates,
        "demand_updates_per_sec": 0.0 if _tiny() else round(updates_per_sec, 1),
        "demand_merge_ms": 0.0 if _tiny() else round(merge_ms, 4),
        "demand_merge_workers": workers,
        "demand_sketch_items": len(merged["sketch"]["items"]),
        "demand_hot_cells": len(merged["cells"]),
    }


def bench_prewarm(platform: str) -> dict:
    """Self-healing prefetch workload (ISSUE 19): cold-outage vs
    prefetched-outage warm hit rate + degraded-answer p99 + controller
    sweep throughput.

    A permanent ``serve.dispatch`` transient (the breaker-open outage
    lever from the chaos drills) makes the solver path unavailable for
    the whole bench. Phase 1 queries the seeded pool through an engine
    bridged to an EMPTY tile cache — the cold outage, every hot query
    503s. Phase 2 drains a hand-ranked advisor plan covering the pool
    through a standalone `PrewarmController` (engine=None — always
    admissible) → prewarm_tiles_per_sec. Phase 3 re-runs the outage
    against the now-prefetched cache → prewarm_warm_hit_rate (fraction
    answered ``source="tilecache"``) and prewarm_outage_p99_ms (p99 of
    those degraded answers — the bridge's mtime-indexed sidecar lookup is
    the outage hot path this gates). History schema 13; tiny dry-run
    shapes zero the gated keys so reduced-shape stats never seed a
    baseline."""
    import hashlib
    import tempfile

    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.resilience import faults
    from sbr_tpu.serve.engine import Engine, ServeConfig
    from sbr_tpu.serve.loadgen import build_pool
    from sbr_tpu.serve.prewarm import PrewarmController

    if _tiny():
        pool_n, n_tiles, n_grid, n_rep = 4, 2, 64, 2
    else:
        pool_n, n_tiles, n_grid, n_rep = 12, 4, 128, 8
    config = SolverConfig(n_grid=n_grid, bisect_iters=40, refine_crossings=False)
    pool = build_pool(0, pool_n)

    # A plan tile per pool chunk: the chunk's β/u axes cross-cover its
    # points (what the demand advisor's bin tiles do at fleet scale).
    chunk = max(pool_n // n_tiles, 1)
    tiles = []
    for i in range(n_tiles):
        pts = pool[i * chunk : (i + 1) * chunk] or pool[-chunk:]
        tiles.append({
            "bin": f"{i},0",
            "betas": sorted({float(p.learning.beta) for p in pts}),
            "us": sorted({float(p.economic.u) for p in pts}),
            "rank": i + 1,
        })
    plan = {"schema": "sbr-demand-advisor/1", "tiles": tiles}
    plan["plan_fingerprint"] = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()
    ).hexdigest()[:16]

    saved_env = {
        k: os.environ.get(k)
        for k in ("SBR_TILE_CACHE_DIR", "SBR_RETRY_BASE_DELAY_S",
                  "SBR_RETRY_MAX_DELAY_S")
    }
    outage = {"rules": [{"point": "serve.dispatch", "kind": "transient", "p": 1.0}]}

    def _outage_pass(label):
        hits, lat_ms = 0, []
        engine = Engine(config=config, serve=ServeConfig(buckets=(1,)))
        try:
            for p in pool:
                try:
                    r = engine.query(p, scenario=label)
                except Exception:
                    continue  # ladder exhausted: the 503 path
                if r.source == "tilecache":
                    hits += 1
                    lat_ms.append(r.latency_s * 1e3)
        finally:
            engine.close()
        return hits, lat_ms

    with tempfile.TemporaryDirectory(prefix="sbr_bench_prewarm_") as tmp:
        cache_dir = os.path.join(tmp, "tilecache")
        plan_path = os.path.join(tmp, "advisor_plan.json")
        with open(plan_path, "w") as fh:
            json.dump(plan, fh)
        try:
            os.environ["SBR_TILE_CACHE_DIR"] = cache_dir
            # The outage pass burns dispatch retries until the breaker
            # opens; near-zero backoff keeps the bench honest about
            # ladder cost rather than sleep cost.
            os.environ["SBR_RETRY_BASE_DELAY_S"] = "0.01"
            os.environ["SBR_RETRY_MAX_DELAY_S"] = "0.05"

            faults.install(faults.FaultPlan(outage))
            try:
                cold_hits, _ = _outage_pass("prewarm-cold")
            finally:
                faults.reset()

            ctl = PrewarmController(
                engine=None, plan_file=plan_path,
                state_root=os.path.join(tmp, "_prewarm"),
                config=config, cache_dir=cache_dir,
            )
            t0 = time.perf_counter()
            snap = ctl.drain(timeout_s=600.0)
            drain_s = time.perf_counter() - t0
            tiles_done = snap["counts"]["tiles_done"]
            tiles_per_sec = tiles_done / drain_s if drain_s > 0 else 0.0

            faults.install(faults.FaultPlan(outage))
            try:
                warm_hits, warm_lat = [], []
                for _ in range(n_rep):
                    h, lat = _outage_pass("prewarm-warm")
                    warm_hits.append(h)
                    warm_lat.extend(lat)
            finally:
                faults.reset()
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    total = n_rep * pool_n
    hit_rate = sum(warm_hits) / total if total else 0.0
    warm_lat.sort()
    p99 = warm_lat[min(int(len(warm_lat) * 0.99), len(warm_lat) - 1)] if warm_lat else 0.0
    _log(
        f"prewarm: cold outage {cold_hits}/{pool_n} warm; drained "
        f"{tiles_done} tile(s) in {drain_s:.2f}s ({snap['status']}); "
        f"warm outage hit rate {hit_rate:.2f}, p99 {p99:.2f}ms"
    )
    return {
        "prewarm_pool": pool_n,
        "prewarm_tiles": tiles_done,
        "prewarm_cold_hits": int(cold_hits),
        "prewarm_plan_status": snap["status"],
        "prewarm_warm_hit_rate": 0.0 if _tiny() else round(hit_rate, 4),
        "prewarm_outage_p99_ms": 0.0 if _tiny() else round(p99, 3),
        "prewarm_tiles_per_sec": 0.0 if _tiny() else round(tiles_per_sec, 3),
    }


def bench_flight(platform: str) -> dict:
    """Flight-recorder workload (ISSUE 20): recorder-on vs recorder-off
    serve time at the standard serve shape → the overhead ratio the
    ≤1.05 acceptance gate judges, plus the recorder-on pass's measured
    device-busy / host-gap fractions — the baseline ruler the ROADMAP
    item-1 async-dispatch work must move (its acceptance criterion is
    "flight_host_gap_frac drops on the same bench").

    Both engines serve IDENTICAL per-rep query pools (unique params per
    rep, so every rep dispatches instead of replaying the LRU) after an
    untimed warm-up rep that absorbs compiles. The two engines stay warm
    side by side and each rep pool is timed back-to-back on both —
    off-first on even reps, on-first on odd — with the headline ratio
    the MEDIAN of per-pair on/off ratios. Pairing is what makes the
    gate resolvable: a rep pool serves in single-digit milliseconds, so
    sequential whole-pass timing lets any background stall (the obs
    writer thread, a GC pass, another tenant on a small box) land in
    one mode's window and read as 20% "overhead" where the true
    recording cost is microseconds; adjacent paired reps see the same
    machine state and the median ignores the odd poisoned pair.
    History schema 14; tiny dry-run shapes zero the gated keys so
    reduced-shape stats never seed a baseline."""
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.serve.engine import Engine, ServeConfig
    from sbr_tpu.serve.loadgen import build_pool

    if _tiny():
        pool_n, n_grid, n_rep = 4, 64, 2
    else:
        pool_n, n_grid, n_rep = 12, 128, 40
    config = SolverConfig(n_grid=n_grid, bisect_iters=40, refine_crossings=False)
    # Per-rep pools with distinct seeds: distinct params per rep, so each
    # timed rep pays a real dispatch; rep pools are shared between the on
    # and off engines so both serve byte-identical work.
    warm_pool = build_pool(999, pool_n)
    rep_pools = [build_pool(seed, pool_n) for seed in range(n_rep)]

    saved = os.environ.get("SBR_FLIGHT")

    def _make_engine(flight_on):
        if flight_on:
            os.environ["SBR_FLIGHT"] = "1"
        else:
            os.environ.pop("SBR_FLIGHT", None)
        engine = Engine(config=config, serve=ServeConfig(buckets=(1, 8)))
        engine.start()
        engine.query_many(warm_pool)  # compiles, untimed
        return engine

    def _timed_rep(engine, rep_pool):
        t0 = time.perf_counter()
        engine.query_many(rep_pool)
        return time.perf_counter() - t0

    eng_off = eng_on = None
    try:
        eng_off = _make_engine(False)
        eng_on = _make_engine(True)
        # The measured window starts clean: compile shadow must not
        # pollute the busy/gap fractions.
        eng_on.flight.reset()
        pair_ratios, off_times, on_times = [], [], []
        for i, rep_pool in enumerate(rep_pools):
            if i % 2 == 0:
                off_t = _timed_rep(eng_off, rep_pool)
                on_t = _timed_rep(eng_on, rep_pool)
            else:
                on_t = _timed_rep(eng_on, rep_pool)
                off_t = _timed_rep(eng_off, rep_pool)
            off_times.append(off_t)
            on_times.append(on_t)
            if off_t > 0:
                pair_ratios.append(on_t / off_t)
        from sbr_tpu.obs import flight as _flight

        util = _flight.derive_utilization(eng_on.flight.snapshot())
    finally:
        for engine in (eng_off, eng_on):
            if engine is not None:
                engine.close()
        if saved is None:
            os.environ.pop("SBR_FLIGHT", None)
        else:
            os.environ["SBR_FLIGHT"] = saved

    import statistics

    ratio = statistics.median(pair_ratios) if pair_ratios else 0.0
    off_s, on_s = min(off_times), min(on_times)
    busy = util.get("device_busy_frac") or 0.0
    gap = util.get("host_gap_frac") or 0.0
    _log(
        f"flight: off {off_s * 1e3:.1f}ms on {on_s * 1e3:.1f}ms "
        f"(median paired ratio {ratio:.3f} over {len(pair_ratios)} "
        f"rep pair(s)); busy {busy:.4f} gap {gap:.4f} over "
        f"{util.get('dispatches', 0)} dispatch(es)"
    )
    return {
        "flight_pool": pool_n,
        "flight_reps": n_rep,
        "flight_dispatches": int(util.get("dispatches") or 0),
        "flight_records": int(util.get("records") or 0),
        "flight_overhead_ratio": 0.0 if _tiny() else round(ratio, 4),
        "flight_device_busy_frac": 0.0 if _tiny() else round(busy, 4),
        "flight_host_gap_frac": 0.0 if _tiny() else round(gap, 4),
    }


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def measure(platform: str) -> None:
    """Measurement child entry: the real body runs inside a
    graceful-shutdown envelope so a preemption (SIGTERM) mid-bench still
    finalizes the obs manifest (status "interrupted") and removes partial
    temp files instead of leaving a "running" corpse."""
    from sbr_tpu.resilience.shutdown import graceful_shutdown

    with graceful_shutdown(label="bench.measure"):
        _measure_inner(platform)


def _measure_inner(platform: str) -> None:
    devices = _init_child_backend(platform)
    platform = devices[0].platform

    # Run telemetry (sbr_tpu.obs): every measure child writes a run
    # directory (events.jsonl + manifest.json) and the bench JSON gains an
    # `obs` block with the compile/execute split, device, and memory peak.
    # Measurement-critical loops inside the workloads suspend telemetry, so
    # the metrics are identical to a telemetry-off process.
    from sbr_tpu import obs

    # Retention (ISSUE 2 satellite): every measure child lands a run dir,
    # so repeated benches accumulate them; keep the most recent N
    # (SBR_OBS_KEEP overrides; empty means unset, matching obs.runlog)
    # and prune the rest at finalize.
    keep_env = os.environ.get("SBR_OBS_KEEP", "").strip()
    obs_run = obs.start_run(
        label="bench",
        auto_prune_keep=int(keep_env) if keep_env else 16,
    )
    with obs.span("bench.grid"):
        grid = bench_grid(platform)
    obs.event("bench_grid", **{k: round(v, 6) if isinstance(v, float) else v for k, v in grid.items()})
    try:
        with obs.span("bench.agents"):
            agents = bench_agents(platform)
    except Exception as err:
        # The primary metric must still land even if the second workload
        # fails (graceful-degradation analogue of the sweeps' NaN cells).
        _log(f"agent bench failed: {err!r}")
        agents = None
    if agents is not None:
        obs.event(
            "bench_agents",
            **{k: round(v, 6) if isinstance(v, float) else v for k, v in agents.items()},
        )
    try:
        with obs.span("bench.serve"):
            serve = bench_serve(platform)
    except Exception as err:
        # Same graceful degradation as the agents workload: the primary
        # metric must land even when the serving workload fails.
        _log(f"serve bench failed: {err!r}")
        serve = None
    if serve is not None:
        obs.event(
            "bench_serve",
            **{k: round(v, 6) if isinstance(v, float) else v for k, v in serve.items()},
        )
    try:
        with obs.span("bench.sweep"):
            sweep = bench_sweep(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the elastic-sweep workload fails.
        _log(f"sweep bench failed: {err!r}")
        sweep = None
    if sweep is not None:
        obs.event(
            "bench_sweep",
            **{k: round(v, 6) if isinstance(v, float) else v for k, v in sweep.items()},
        )
    try:
        with obs.span("bench.fleet"):
            fleet = bench_fleet(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the multi-process fleet workload fails.
        _log(f"fleet bench failed: {err!r}")
        fleet = None
    if fleet is not None:
        obs.event(
            "bench_fleet",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in fleet.items() if v is not None},
        )
    try:
        with obs.span("bench.grad"):
            grad = bench_grad(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the differentiable-equilibria workload fails.
        _log(f"grad bench failed: {err!r}")
        grad = None
    if grad is not None:
        obs.event(
            "bench_grad",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in grad.items() if v is not None},
        )
    try:
        with obs.span("bench.scenario"):
            scen = bench_scenario(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the composable-scenario workload fails.
        _log(f"scenario bench failed: {err!r}")
        scen = None
    if scen is not None:
        obs.event(
            "bench_scenario",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in scen.items() if v is not None},
        )
    try:
        with obs.span("bench.infomodels"):
            info = bench_infomodels(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the information-model workload fails.
        _log(f"infomodels bench failed: {err!r}")
        info = None
    if info is not None:
        obs.event(
            "bench_infomodels",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in info.items() if v is not None},
        )
    try:
        with obs.span("bench.audit"):
            aud = bench_audit(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the numerics-audit workload fails.
        _log(f"audit bench failed: {err!r}")
        aud = None
    if aud is not None:
        obs.event(
            "bench_audit",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in aud.items() if v is not None},
        )
    try:
        with obs.span("bench.demand"):
            dem = bench_demand(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the workload-demand bench fails.
        _log(f"demand bench failed: {err!r}")
        dem = None
    if dem is not None:
        obs.event(
            "bench_demand",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in dem.items() if v is not None},
        )
    try:
        with obs.span("bench.prewarm"):
            pw = bench_prewarm(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the self-healing prefetch workload fails.
        _log(f"prewarm bench failed: {err!r}")
        pw = None
    if pw is not None:
        obs.event(
            "bench_prewarm",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in pw.items() if v is not None},
        )
    try:
        with obs.span("bench.flight"):
            flt = bench_flight(platform)
    except Exception as err:
        # Same graceful degradation: the primary metric must land even
        # when the flight-recorder workload fails.
        _log(f"flight bench failed: {err!r}")
        flt = None
    if flt is not None:
        obs.event(
            "bench_flight",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in flt.items() if v is not None},
        )

    eq_per_sec = grid["eq_per_sec"]
    out = {
        "metric": "beta_u_grid_equilibria_per_sec",
        "value": round(eq_per_sec, 1),
        "unit": "equilibria/sec",
        "vs_baseline": round(eq_per_sec / 2.0, 1),
        "extra": {
            "platform": platform,
            "grid_cells": grid["n_cells"],
            "grid_first_call_s": round(grid["first_call_s"], 2),
            "grid_steady_s": round(grid["steady_s"], 3),
            "grid_dispatch_s": round(grid["dispatch_s"], 3),
            "grid_pipelined_s": round(grid["pipelined_s"], 3),
            "grid_pipeline_depth": grid["n_pipe"],
        },
    }
    # Schema-5 history metrics (ISSUE 9): the adaptive-vs-fixed control
    # split and the mean effective root-find iterations per cell.
    if grid.get("adaptive_speedup"):
        out["extra"]["grid_adaptive_speedup"] = round(grid["adaptive_speedup"], 3)
    if grid.get("mean_effective_iters"):
        out["extra"]["grid_mean_effective_iters"] = round(grid["mean_effective_iters"], 2)
    if grid.get("fixed_steady_s"):
        out["extra"]["grid_fixed_steady_s"] = round(grid["fixed_steady_s"], 3)
    if grid.get("mem_peak_bytes"):
        out["extra"]["grid_mem_peak_bytes"] = int(grid["mem_peak_bytes"])
    if agents is not None:
        out["extra"]["agent_steps_per_sec"] = round(agents["agent_steps_per_sec"], 1)
        out["extra"]["n_agents"] = agents["n_agents"]
        out["extra"]["agent_n_steps"] = agents["n_steps"]
        out["extra"]["agents_first_call_s"] = round(agents["first_call_s"], 2)
        out["extra"]["agents_steady_s"] = round(agents["steady_s"], 3)
        out["extra"]["agents_prep_s"] = round(agents["prep_s"], 2)
        out["extra"]["agents_engine"] = agents["engine"]
        out["extra"]["agents_recount_steps"] = agents["recount_steps"]
        if agents.get("mem_peak_bytes"):
            out["extra"]["agents_mem_peak_bytes"] = int(agents["mem_peak_bytes"])
        # Schema-6 history metrics (ISSUE 10): the on-device generation
        # split. Zero means "reduced shape / not measured" and is dropped
        # here so it never enters a gated history as a fake baseline.
        if agents.get("graph_build_s"):
            out["extra"]["agents_graph_build_s"] = round(agents["graph_build_s"], 3)
        if agents.get("graph_gen_edges_per_sec"):
            out["extra"]["agents_graph_gen_edges_per_sec"] = round(
                agents["graph_gen_edges_per_sec"], 1
            )
        if agents.get("graph_gen_speedup"):
            out["extra"]["agents_graph_gen_speedup"] = round(
                agents["graph_gen_speedup"], 2
            )
        if agents.get("n_edges"):
            out["extra"]["agents_n_edges"] = int(agents["n_edges"])
    if serve is not None:
        # Schema-3 history metrics: bench_metrics picks the serve_* keys up
        # so `report trend` gates serving-latency regressions.
        for k in (
            "serve_p50_ms",
            "serve_p99_ms",
            "serve_cache_hit_rate",
            "serve_qps",
            "serve_queries",
        ):
            if serve.get(k) is not None:
                out["extra"][k] = serve[k]
    if sweep is not None:
        # Schema-4 history metrics: cold/warm tiled-sweep throughput + the
        # warm cross-run-cache hit rate (`report trend` gates all three).
        for k in (
            "sweep_cold_cells_per_sec",
            "sweep_warm_cells_per_sec",
            "sweep_warm_hit_rate",
            "sweep_tiles",
        ):
            if sweep.get(k) is not None:
                out["extra"][k] = sweep[k]
    if fleet is not None:
        # Schema-7 history metrics (ISSUE 11): the multi-process fleet SLO
        # split. Tiny shapes return None for the gated three (never a fake
        # baseline); fleet_qps/workers always land for visibility.
        for k in (
            "fleet_p99_ms",
            "fleet_failover_count",
            "fleet_shed_rate",
            "fleet_qps",
            "fleet_workers",
        ):
            if fleet.get(k) is not None:
                out["extra"][k] = fleet[k]
    if grad is not None:
        # Schema-8 history metrics (ISSUE 13): IFT gradient throughput +
        # calibration step rate. Tiny shapes zero the gated keys (falsy →
        # dropped here) so reduced-shape stats never seed baselines.
        for k in ("grads_per_sec", "calib_steps_per_sec"):
            if grad.get(k):
                out["extra"][k] = grad[k]
        out["extra"]["grad_cells"] = grad["grad_cells"]
        out["extra"]["calib_converged"] = grad["calib_converged"]
    if scen is not None:
        # Schema-9 history metrics (ISSUE 14): composition-layer overhead
        # + multi-bank contagion throughput. Tiny shapes zero the gated
        # keys (falsy → dropped here) so reduced-shape stats never seed
        # baselines.
        for k in ("scenario_overhead_ratio", "scenario_multibank_cells_per_sec"):
            if scen.get(k):
                out["extra"][k] = scen[k]
        out["extra"]["scenario_multibank_banks"] = scen["scenario_multibank_banks"]
        out["extra"]["scenario_multibank_converged"] = scen[
            "scenario_multibank_converged"
        ]
    if info is not None:
        # Schema-10 history metrics (ISSUE 15): fused belief-update
        # throughput + population what-if query rate. Tiny shapes zero
        # the gated keys (falsy → dropped here) so reduced-shape stats
        # never seed baselines.
        for k in (
            "infomodel_belief_updates_per_sec",
            "infomodel_population_queries_per_sec",
        ):
            if info.get(k):
                out["extra"][k] = info[k]
        out["extra"]["infomodel_agents"] = info["infomodel_agents"]
        out["extra"]["infomodel_population_run_probability"] = info[
            "infomodel_population_run_probability"
        ]
    if aud is not None:
        # Schema-11 history metrics (ISSUE 17): canary-battery probe
        # throughput + idle-gated scheduler overhead ratio. Tiny shapes
        # zero the gated keys (falsy → dropped here) so reduced-shape
        # stats never seed baselines.
        for k in ("audit_probes_per_sec", "audit_overhead_ratio"):
            if aud.get(k):
                out["extra"][k] = aud[k]
        out["extra"]["audit_probe_count"] = aud["audit_probe_count"]
        out["extra"]["audit_canary_cycles"] = aud["audit_canary_cycles"]
    if dem is not None:
        # Schema-12 history metrics (ISSUE 18): streaming demand-update
        # throughput + router fleet-merge cost. Tiny shapes zero the
        # gated keys (falsy → dropped here) so reduced-shape stats never
        # seed baselines.
        for k in ("demand_updates_per_sec", "demand_merge_ms"):
            if dem.get(k):
                out["extra"][k] = dem[k]
        out["extra"]["demand_merge_workers"] = dem["demand_merge_workers"]
        out["extra"]["demand_sketch_items"] = dem["demand_sketch_items"]
    if pw is not None:
        # Schema-13 history metrics (ISSUE 19): outage warm hit rate from
        # prefetched tiles, degraded-answer p99, and controller sweep
        # throughput. Tiny shapes zero the gated keys (falsy → dropped
        # here) so reduced-shape stats never seed baselines.
        for k in ("prewarm_warm_hit_rate", "prewarm_outage_p99_ms",
                  "prewarm_tiles_per_sec"):
            if pw.get(k):
                out["extra"][k] = pw[k]
        out["extra"]["prewarm_tiles"] = pw["prewarm_tiles"]
        out["extra"]["prewarm_plan_status"] = pw["prewarm_plan_status"]
    if flt is not None:
        # Schema-14 history metrics (ISSUE 20): recorder-on/off serve
        # overhead ratio + the device-busy / host-gap baseline the
        # async-dispatch work will be gated against. Tiny shapes zero the
        # gated keys (falsy → dropped here) so reduced-shape stats never
        # seed baselines.
        for k in ("flight_overhead_ratio", "flight_device_busy_frac",
                  "flight_host_gap_frac"):
            if flt.get(k):
                out["extra"][k] = flt[k]
        out["extra"]["flight_dispatches"] = flt["flight_dispatches"]
        out["extra"]["flight_records"] = flt["flight_records"]
    obs.end_run()
    out["extra"]["obs"] = obs_run.summary()
    _log(f"obs run dir: {obs_run.run_dir}")
    # Perf history (ISSUE 3): this measurement's headline metrics become one
    # appended line the `report trend` gate can baseline future runs against.
    _append_history(out, obs_run)
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--measure":
        measure(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--dry-run":
        # Smoke the whole measurement pipeline in-process on CPU at tiny
        # sizes (seconds, no probe children): produces the obs run directory
        # and the one-line JSON with the `obs` block, for telemetry
        # validation (`python -m sbr_tpu.obs.report <run_dir>`).
        os.environ.setdefault("SBR_BENCH_SIZES", "tiny")
        measure("cpu")
    elif len(sys.argv) >= 2 and sys.argv[1] == "--watch":
        n = int(sys.argv[2]) if len(sys.argv) >= 3 else 6
        interval = float(sys.argv[3]) if len(sys.argv) >= 4 else 600.0
        sys.exit(watch(n, interval))
    else:
        main()
