"""Benchmark: equilibria/sec on the Figure-5 β×u comparative-statics grid.

The headline workload (SURVEY §6, BASELINE.md): the reference solves the
500×500 β×u grid sequentially in the bulk of its 5-15 min replication run
(`scripts/1_baseline.jl:209-285`) and reports ~0.5 s per single equilibrium
solve (paper Appendix C.5.3) — i.e. a baseline of 2 equilibria/sec. Here the
whole grid is one jitted vmap² program on the accelerator; `vs_baseline` is
(our equilibria/sec) / 2.

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    n_beta, n_u = 640, 640  # 409.6k cells — 40× the north-star 10^4 points
    config = SolverConfig(n_grid=1024, bisect_iters=60)
    base = make_model_params()  # Figure-5 base: β=1, η̄=15, κ=.6 (η pinned 15)

    # Reference grid domain (`scripts/1_baseline.jl:210-213`):
    # β = 1/ave_meeting_time, ave_meeting_time ∈ [1e-4, 1]; u ∈ [0.001, 1].
    amt = np.linspace(1e-4, 1.0, n_beta)
    betas = 1.0 / amt

    def run(rep: int):
        # Perturb u by 1e-6 per rep: physics-identical to the metric's
        # precision, but ensures each rep is a distinct computation. Fetch a
        # scalar reduction to host inside the timed region — on the axon TPU
        # tunnel `block_until_ready` returns before device work completes, so
        # a device→host read is the only honest fence.
        us = np.linspace(0.001, 1.0, n_u) + rep * 1e-6
        grid = beta_u_grid(betas, us, base, config=config, dtype=jnp.float32)
        fence = float(
            jnp.sum(grid.status) + jnp.nansum(grid.max_aw) + jnp.nansum(grid.xi)
        )
        return grid, fence

    t0 = time.perf_counter()
    grid, _ = run(0)  # includes compile
    compile_s = time.perf_counter() - t0

    times = []
    for rep in range(1, 4):
        t0 = time.perf_counter()
        grid, _ = run(rep)
        times.append(time.perf_counter() - t0)
    elapsed = min(times)

    n_cells = n_beta * n_u
    eq_per_sec = n_cells / elapsed
    n_run = int(np.sum(np.asarray(grid.status) == 0))
    print(
        f"[bench] {n_cells} cells in {elapsed:.3f}s (first call {compile_s:.1f}s "
        f"incl. compile) on {jax.devices()[0].platform}; {n_run} run cells",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "beta_u_grid_equilibria_per_sec",
                "value": round(eq_per_sec, 1),
                "unit": "equilibria/sec",
                "vs_baseline": round(eq_per_sec / 2.0, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
