"""The `Health` pytree: in-jit numerical-health diagnostics.

A `Health` is a tiny pytree of per-solve (or per-cell, when vmapped)
scalars that rides through jit/vmap/shard_map next to the numerical
result it describes:

- ``residual``       — final |f(x*)| of the defining equation (the xi
                       bisection's |AW(ξ*)−κ|, the social fixed point's
                       sup-norm error); NaN where not applicable.
- ``bracket_width``  — final bisection bracket width |hi−lo|; NaN where
                       not applicable.
- ``iterations``     — int32 iterations actually executed (bisection
                       halvings, fixed-point steps), summed under `merge`.
- ``flags``          — int32 bitmask of the `FALLBACK_*` / `NAN_*` /
                       `FP_*` bits below: which fallback path a crossing
                       detector took, NaN/Inf sentinels, bracket validity,
                       fixed-point convergence.

Everything is branchless array arithmetic, so carrying a `Health` through
a `lax.while_loop`/`fori_loop` costs a few scalar lanes; the core
primitives (`core.rootfind`, `core.ode`, `core.integrate`) only compute it
when a caller passes ``with_health=True``, so call sites that skip it pay
nothing — the loop carries and jaxprs are unchanged.

The split between ``flags`` and `models.results.Status` matters: status
codes classify *economic* outcomes (no-run cells are SUPPOSED to carry
NaN ξ), while health flags classify *numerical* trust. Only the
`DIVERGENT_MASK` bits — NaN poison, non-finite residuals, fixed-point
non-convergence — mean "do not trust this cell"; fallback-ladder and
no-bracket bits are informational corroboration of the status code.

Host-side, `summarize` reduces a (possibly million-cell) batched Health
to a JSON-ready census (flag counts, worst cells, residual histogram)
that `obs.log_health` emits as a ``health`` event and
`python -m sbr_tpu.obs.report health` renders and gates on.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

# ---------------------------------------------------------------------------
# Flag bits. Plain ints (not a jnp enum) so host code — the report CLI, flag
# name tables — can use them without importing JAX. Bits 0-1 are the
# "generic" crossing-fallback positions emitted by the core crossing
# primitives; `as_out_crossing` shifts them into the OUT positions (2-3) so
# a merged per-solve mask keeps the two crossings distinguishable.
# ---------------------------------------------------------------------------

FALLBACK_IN_KNOT = 1 << 0  # no up-crossing; fell back to first above-level knot
FALLBACK_IN_DEFAULT = 1 << 1  # nothing above the level; returned `default`
FALLBACK_OUT_KNOT = 1 << 2  # no down-crossing; fell back to last above-level knot
FALLBACK_OUT_DEFAULT = 1 << 3  # nothing above the level; returned `default`
NO_BRACKET = 1 << 4  # bisection endpoints do not bracket a sign change
NONFINITE_RESIDUAL = 1 << 5  # final residual is NaN/Inf
NAN_INPUT = 1 << 6  # NaN among the primitive's inputs (curve, level, bracket)
NAN_OUTPUT = 1 << 7  # non-finite values in a computed result (iterate, curve)
FP_NOT_CONVERGED = 1 << 8  # fixed point hit max_iter without converging
FP_ABORTED = 1 << 9  # fixed point's ξ search exceeded η and gave up
ODE_BUDGET = 1 << 10  # adaptive ODE interval exhausted its step budget
# Gradient-trust bits (sbr_tpu.grad, ISSUE 13): set on IFT sensitivity
# outputs, never by the forward solvers. They classify whether dξ/dθ can be
# trusted, the way DIVERGENT_MASK classifies whether ξ itself can.
GRAD_AT_NONEQUILIBRIUM = 1 << 11  # root candidate is not a RUN equilibrium
GRAD_ILL_CONDITIONED = 1 << 12  # |AW'(ξ)| near zero: dξ/dθ = -F_θ/F_ξ blows up
GRAD_NONFINITE = 1 << 13  # a computed gradient came back NaN/Inf

FLAG_NAMES = {
    FALLBACK_IN_KNOT: "fallback_in_knot",
    FALLBACK_IN_DEFAULT: "fallback_in_default",
    FALLBACK_OUT_KNOT: "fallback_out_knot",
    FALLBACK_OUT_DEFAULT: "fallback_out_default",
    NO_BRACKET: "no_bracket",
    NONFINITE_RESIDUAL: "nonfinite_residual",
    NAN_INPUT: "nan_input",
    NAN_OUTPUT: "nan_output",
    FP_NOT_CONVERGED: "fp_not_converged",
    FP_ABORTED: "fp_aborted",
    ODE_BUDGET: "ode_budget",
    GRAD_AT_NONEQUILIBRIUM: "grad_at_nonequilibrium",
    GRAD_ILL_CONDITIONED: "grad_ill_conditioned",
    GRAD_NONFINITE: "grad_nonfinite",
}
ALL_FLAGS = tuple(FLAG_NAMES)

# Bits that mean "this cell's numbers cannot be trusted" — `report health`
# exits nonzero when any cell carries one. Fallback/no-bracket bits are NOT
# here: they corroborate expected NO_CROSSING / NO_ROOT status outcomes.
DIVERGENT_MASK = (
    NONFINITE_RESIDUAL | NAN_INPUT | NAN_OUTPUT | FP_NOT_CONVERGED | FP_ABORTED
)

_IN_FALLBACK_MASK = FALLBACK_IN_KNOT | FALLBACK_IN_DEFAULT


def flag_names(mask: int) -> list:
    """Decode a host-side int bitmask into sorted flag-name strings."""
    mask = int(mask)
    return [name for bit, name in FLAG_NAMES.items() if mask & bit]


@struct.dataclass
class Health:
    """Per-solve numerical-health scalars (see module docstring).

    All leaves are arrays so a vmapped solve yields batched health — the
    per-cell health grids of the sweeps modules. 0-d per scalar solve.
    """

    residual: jnp.ndarray  # final |f(x*)|; NaN = not applicable
    bracket_width: jnp.ndarray  # final bisection bracket; NaN = n/a
    iterations: jnp.ndarray  # int32, summed by merge
    flags: jnp.ndarray  # int32 bitmask of the module-level bits

    def __post_init__(self):
        # Differentiability contract (ISSUE 13): health is TELEMETRY, never
        # part of the differentiated computation. Every leaf is cut from the
        # tangent/cotangent graph at construction, so a solve that threads
        # health through jax.grad/jvp has bitwise the same gradient as the
        # health-free solve — a caller folding health.residual into a loss
        # gets zero, not a spurious d|residual|/dθ term backpropagated
        # through the residual evaluation (regression: tests/test_grad.py).
        # Identity on values, so forward results and jaxpr shapes are
        # untouched; runs again on `replace`/tree_unflatten, idempotently.
        # Transform internals (vmap axis-tree building) unflatten structs
        # with non-array SENTINEL leaves — those pass through untouched.
        from jax import lax

        for field in ("residual", "bracket_width", "iterations", "flags"):
            try:
                object.__setattr__(self, field, lax.stop_gradient(getattr(self, field)))
            except TypeError:
                pass

    @classmethod
    def empty(cls, dtype=jnp.float32) -> "Health":
        """A neutral health: nothing measured, nothing flagged."""
        nan = jnp.asarray(jnp.nan, dtype)
        return cls(
            residual=nan,
            bracket_width=nan,
            iterations=jnp.zeros((), jnp.int32),
            flags=jnp.zeros((), jnp.int32),
        )

    @classmethod
    def of_flags(cls, flags, dtype=jnp.float32) -> "Health":
        """Health carrying only a flag mask (curve finiteness probes)."""
        nan = jnp.asarray(jnp.nan, dtype)
        return cls(
            residual=nan,
            bracket_width=nan,
            iterations=jnp.zeros((), jnp.int32),
            flags=jnp.asarray(flags, jnp.int32),
        )

    @classmethod
    def of_nan_probe(cls, nan_in, nonfinite_out, iterations, dtype=jnp.float32) -> "Health":
        """Health of a residual-free computation (ODE trajectory, cumulative
        quadrature): NaN-poisoned inputs and non-finite outputs are the only
        failure modes; ``iterations`` records the step/panel count."""
        dtype = dtype if jnp.issubdtype(jnp.dtype(dtype), jnp.floating) else jnp.float32
        nan = jnp.asarray(jnp.nan, dtype)
        return cls(
            residual=nan,
            bracket_width=nan,
            iterations=jnp.asarray(iterations, jnp.int32),
            flags=jnp.where(nan_in, jnp.int32(NAN_INPUT), jnp.int32(0))
            | jnp.where(nonfinite_out, jnp.int32(NAN_OUTPUT), jnp.int32(0)),
        )

    def merge(self, *others: "Health") -> "Health":
        """Combine healths of sequential stages into one per-solve health:
        worst (max) residual/bracket via NaN-ignoring `fmax`, summed
        iterations, OR'd flags. Broadcasts, so batched merges batched."""
        h = self
        for o in others:
            h = Health(
                residual=jnp.fmax(h.residual, o.residual),
                bracket_width=jnp.fmax(h.bracket_width, o.bracket_width),
                iterations=h.iterations + o.iterations,
                flags=h.flags | o.flags,
            )
        return h


def as_out_crossing(h: Health) -> Health:
    """Re-key a crossing primitive's health as the OUT (down-)crossing:
    shift the generic fallback bits (0-1) into the OUT positions (2-3) so
    merging IN and OUT crossing healths stays lossless."""
    fall = h.flags & _IN_FALLBACK_MASK
    return h.replace(flags=(h.flags & ~_IN_FALLBACK_MASK) | (fall << 2))


def or_reduce_flags(flags, reduce_scalar=None):
    """OR-reduce a flag-mask array to one scalar mask using only SUM-shaped
    reductions, so it works where OR has no collective: under a sharded
    axis, pass ``reduce_scalar=lambda s: lax.psum(s, axis_name)`` and each
    bit's presence count completes across shards; the local case is the
    identity. ~10 tiny scalar reductions — negligible in any program."""
    if reduce_scalar is None:
        reduce_scalar = lambda s: s
    out = jnp.zeros((), jnp.int32)
    for bit in ALL_FLAGS:
        present = reduce_scalar(jnp.sum((flags & bit) != 0)) > 0
        out = out | jnp.where(present, jnp.int32(bit), jnp.int32(0))
    return out


# ---------------------------------------------------------------------------
# Host-side reduction: Health (possibly batched) -> JSON-ready census.
# ---------------------------------------------------------------------------


def summarize(health: Health, status=None, worst_k: int = 5) -> dict:
    """Reduce a Health pytree to a JSON-ready dict at the host boundary.

    Forces a device→host fetch of the health leaves (callers gate on
    telemetry being enabled, same discipline as `obs.log_status`). With
    ``status`` (the matching Status grid) worst cells carry their status
    name, separating expected no-run NaN sentinels from genuine poison.

    Residual accounting is restricted to cells whose bisection MEANT
    something: NO_CROSSING / NO_ROOT cells run their fixed halvings on a
    degenerate or non-bracketing interval by design, and their large-but-
    expected |AW−κ| values would otherwise drown the genuinely converged
    cells out of ``max_residual``, the histogram, and the worst-cell
    ranking (code-review finding). With ``status`` given, RUN cells
    qualify; without it, cells free of NO_BRACKET / default-fallback flags
    do. Divergent-flag cells always rank first regardless.
    """
    import numpy as np

    res = np.atleast_1d(np.asarray(health.residual, dtype=np.float64))
    shape = res.shape
    res = res.ravel()
    flags = np.atleast_1d(np.asarray(health.flags, dtype=np.int64)).ravel()
    iters = np.atleast_1d(np.asarray(health.iterations, dtype=np.int64)).ravel()
    status_flat = (
        np.atleast_1d(np.asarray(status)).ravel() if status is not None else None
    )

    n = int(flags.size)
    flag_counts = {}
    for bit, name in FLAG_NAMES.items():
        c = int(((flags & bit) != 0).sum())
        if c:
            flag_counts[name] = c
    divergent = int(((flags & DIVERGENT_MASK) != 0).sum())

    out = {
        "cells": n,
        "divergent": divergent,
        "flag_counts": flag_counts,
        "iterations_total": int(iters.sum()),
        # Effective-iteration statistics (adaptive numerics, ISSUE 9): with
        # convergence-masked solvers `iterations` records the count each
        # cell ACTUALLY ran, so the mean/max expose how far typical cells
        # undershoot the worst-case budget (fixed mode reports the budget).
        "iterations_mean": round(float(iters.mean()), 2) if n else 0.0,
        "iterations_max": int(iters.max()) if n else 0,
    }

    finite = np.isfinite(res)
    if status_flat is not None:
        from sbr_tpu.models.results import Status  # lazy: results imports us

        meaningful = finite & (status_flat == int(Status.RUN))
    else:
        degenerate = NO_BRACKET | FALLBACK_IN_DEFAULT | FALLBACK_OUT_DEFAULT
        meaningful = finite & ((flags & degenerate) == 0)
    if meaningful.any():
        r = res[meaningful]
        out["max_residual"] = float(r.max())
        # log10 histogram with fixed integer-decade buckets (clamped to
        # [1e-18, 1e2]) so histograms diff cleanly across runs; zeros land
        # in the lowest bucket.
        exps = np.clip(
            np.floor(np.log10(np.clip(r, 1e-20, None))), -18.0, 2.0
        ).astype(int)
        hist = {}
        for e in np.sort(np.unique(exps)):
            hist[f"1e{int(e):+d}"] = int((exps == e).sum())
        out["residual_hist"] = hist

    # Worst cells: divergent cells first, then by meaningful residual —
    # the cells a human should look at. Unflagged cells whose residual is
    # NaN or expected-degenerate never qualify.
    score = np.where(
        (flags & DIVERGENT_MASK) != 0,
        np.inf,
        np.where(meaningful, res, -np.inf),
    )
    order = np.argsort(-score, kind="stable")
    worst = []
    for i in order[: max(worst_k, 0)]:
        i = int(i)
        if score[i] == -np.inf and flags[i] == 0:
            continue
        cell = {
            "index": [int(v) for v in np.unravel_index(i, shape)],
            "residual": float(res[i]) if meaningful[i] else None,
            "flags": flag_names(flags[i]),
        }
        if status_flat is not None:
            cell["status"] = _status_name(int(status_flat[i]))
        worst.append(cell)
    if worst:
        out["worst_cells"] = worst
    return out


def _status_name(code: int) -> str:
    # Lazy import: models.results imports this module for the Health type.
    from sbr_tpu.models.results import Status

    try:
        return Status(code).name
    except ValueError:
        return str(code)
