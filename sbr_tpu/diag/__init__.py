"""Numerical-health diagnostics: in-jit convergence evidence for every
silent numerical judgment in the pipeline.

The solver stacks hinge on judgments that used to leave no trace of *how
well* they went: the hazard-vs-utility crossing search with its fallback
ladder, the 90-halving blind bisection for the crash time ξ, the slope
check that rejects false equilibria, and the damped fixed point of the
social extension. PR 1's `sbr_tpu.obs` explains where wall-clock goes;
this layer (torchode's solver event/status introspection is the design
reference, PAPERS.md) explains whether the *numbers* can be trusted:

- `Health` — a small pytree (final residual, bracket width, iteration
  count, NaN/fallback flag bitmask) computed branchlessly INSIDE jit and
  returned next to results. Core primitives (`core.rootfind.bisect`,
  `first_upcrossing`/`last_downcrossing`, `core.ode.rk4`,
  `core.integrate`) produce it only when asked (``with_health=True``), so
  unconverted call sites pay nothing; the four solver stacks always
  thread it into their result pytrees, and the sweeps modules return
  per-cell health grids. Because health is always part of the traced
  program, turning diagnostics *reporting* on or off at the host boundary
  changes no solver output and causes no retrace (same discipline as
  `obs.metrics`; asserted by tests/test_diag.py).
- Host boundary — `obs.log_health(stage, health, status)` reduces a
  (possibly million-cell) health grid to a census (`summarize`: flag
  counts, divergent-cell count, worst cells, residual histogram), emits
  it as a ``health`` event, and folds a per-stage roll-up into the run
  manifest.
- Reporting — ``python -m sbr_tpu.obs.report health RUN_DIR`` renders
  worst-cell tables, the NaN census, and residual histograms, and exits
  nonzero when any cell carries a `DIVERGENT_MASK` flag — the CI gate.

Flag semantics: `Status` codes classify economic outcomes (a NO_ROOT cell
NaN-ing its ξ is the reference's intended semantics); health flags
classify numerical trust. Only NaN poison, non-finite residuals, and
fixed-point non-convergence count as divergence — fallback-ladder and
no-bracket bits are corroborating detail.
"""

from sbr_tpu.diag.health import (
    ALL_FLAGS,
    DIVERGENT_MASK,
    FALLBACK_IN_DEFAULT,
    FALLBACK_IN_KNOT,
    FALLBACK_OUT_DEFAULT,
    FALLBACK_OUT_KNOT,
    FLAG_NAMES,
    FP_ABORTED,
    FP_NOT_CONVERGED,
    NAN_INPUT,
    NAN_OUTPUT,
    NO_BRACKET,
    NONFINITE_RESIDUAL,
    Health,
    as_out_crossing,
    flag_names,
    or_reduce_flags,
    summarize,
)

__all__ = [
    "ALL_FLAGS",
    "DIVERGENT_MASK",
    "FALLBACK_IN_DEFAULT",
    "FALLBACK_IN_KNOT",
    "FALLBACK_OUT_DEFAULT",
    "FALLBACK_OUT_KNOT",
    "FLAG_NAMES",
    "FP_ABORTED",
    "FP_NOT_CONVERGED",
    "NAN_INPUT",
    "NAN_OUTPUT",
    "NO_BRACKET",
    "NONFINITE_RESIDUAL",
    "Health",
    "as_out_crossing",
    "flag_names",
    "or_reduce_flags",
    "summarize",
]
