"""Comparative-statics sweeps (reference `scripts/1_baseline.jl:137-285`)
and the (β, u, r) interest-rate policy grids (no reference counterpart)."""

from sbr_tpu.sweeps.baseline_sweeps import (
    GridSweepResult,
    USweepResult,
    beta_u_grid,
    u_sweep,
)
from sbr_tpu.sweeps.policy_sweeps import PolicySweepResult, policy_sweep_interest
