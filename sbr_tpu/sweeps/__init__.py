"""Comparative-statics sweeps (reference `scripts/1_baseline.jl:137-285`)."""

from sbr_tpu.sweeps.baseline_sweeps import (
    GridSweepResult,
    USweepResult,
    beta_u_grid,
    u_sweep,
)
