"""Policy sweeps over the interest-rate extension: vmapped (β, u, r) grids.

The reference has no policy-sweep machinery — its interest-rate script
solves a single calibration (`scripts/3_interest_rates.jl:37-64`). This
module provides the stretch-config workload from BASELINE.md: a 10^3-point
(β, u, r) grid of interest-rate equilibria as one jitted program, the
r-axis analogue of the baseline β×u sweep (`sweeps.baseline_sweeps`).

Structure exploited: Stage 1 depends only on β (closed form, free per
cell); the HJB value function and Stages 2-3 depend on (u, r, δ) and are
recomputed per cell — each cell is a `solve_equilibrium_interest_core`
call, so r = 0 cells degrade to exactly the baseline solver's answer
(`interest_rate_solver.jl:89-101` regression oracle).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from sbr_tpu.baseline.learning import solve_learning
from sbr_tpu.interest.solver import solve_equilibrium_interest_core
from sbr_tpu.models.params import ModelParamsInterest, SolverConfig
from sbr_tpu.sweeps.baseline_sweeps import _TracedLearning

# Version of the (β, u, r) policy-cell numerics — the policy analogue of
# `baseline_sweeps.GRID_PROGRAM_VERSION`, reserved for the same cross-run
# cache keying discipline when policy sweeps gain tiling: bump on any
# change that alters a cell's bytes.
POLICY_PROGRAM_VERSION = 1


@struct.dataclass
class PolicySweepResult:
    """(B, U, R) grids of equilibrium scalars."""

    beta_values: jnp.ndarray
    u_values: jnp.ndarray
    r_values: jnp.ndarray
    xi: jnp.ndarray  # (B, U, R)
    aw_max: jnp.ndarray  # (B, U, R)
    status: jnp.ndarray  # (B, U, R) int32
    health: object = None  # per-cell diag.Health grid (leaves (B, U, R))


@functools.lru_cache(maxsize=None)
def _policy_fn(config: SolverConfig, dtype_name: str, mesh=None, mesh_axes=None):
    """Jitted (β, u, r) program, cached by (config, dtype, mesh)."""
    dtype = jnp.dtype(dtype_name)

    def cell(beta, u, r, p, kappa, lam, eta, delta, t0, t1, x0):
        # Trace-time retrace accounting (obs.prof): vmap³ traces `cell`
        # once per program trace = one count per jit cache miss.
        from sbr_tpu.obs import prof

        prof.note_trace("sweeps.policy_interest")
        ls = solve_learning(_TracedLearning(beta=beta, tspan=(t0, t1), x0=x0), config, dtype=dtype)
        res = solve_equilibrium_interest_core(ls, u, p, kappa, lam, eta, r, delta, t1, config)
        return res.base.xi, res.base.aw_max, res.base.status, res.base.health

    bcast = (None,) * 8
    fn = jax.vmap(  # β axis
        jax.vmap(  # u axis
            jax.vmap(cell, in_axes=(None, None, 0) + bcast),  # r axis
            in_axes=(None, 0, None) + bcast,
        ),
        in_axes=(0, None, None) + bcast,
    )
    if mesh is not None:
        # (B, U) block-sharded via shard_map — each device runs the plain
        # vmap³ program on its local (B/n_b, U/n_u, R) block; cells are
        # independent, so there are no collectives and no sharded-indexing
        # propagation inside the traced cell (gather-heavy interp under 3
        # batched axes trips XLA's sharding-in-types inference otherwise).
        from jax.sharding import PartitionSpec as P

        from sbr_tpu.parallel.compat import pcast, shard_map

        b_ax, u_ax = mesh_axes

        def body(b, u, r, *scalars):
            # replicated inputs are device-invariant; mark every input
            # varying over both mesh axes (each only over the axes it does
            # not already vary on) so internal scan carries are consistent
            b = pcast(b, (u_ax,), to="varying")
            u = pcast(u, (b_ax,), to="varying")
            vary = lambda x: pcast(x, (b_ax, u_ax), to="varying")
            return fn(b, u, vary(r), *(vary(s) for s in scalars))

        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(b_ax), P(u_ax), P()) + (P(),) * 8,
            out_specs=P(b_ax, u_ax, None),
        )
        return jax.jit(sharded)
    return jax.jit(fn)


# AOT footprint cache, mirroring baseline_sweeps._FOOTPRINT_CACHE.
_FOOTPRINT_CACHE: dict = {}


def policy_tile_footprint(
    n_b: int,
    n_u: int,
    n_r: int,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> dict:
    """Analytical memory footprint of ONE (n_b × n_u × n_r) policy-grid
    dispatch — the (β, u, r) analogue of
    `baseline_sweeps.grid_tile_footprint`, feeding the pre-dispatch OOM
    preflight in `policy_sweep_interest` (`sbr_tpu.obs.mem`)."""
    from sbr_tpu.sweeps.baseline_sweeps import _sweep_footprint

    return _sweep_footprint(
        _FOOTPRINT_CACHE,
        (n_b, n_u, n_r),
        config,
        dtype,
        lambda cfg, dt: _policy_fn(cfg, dt, None, None),
        n_scalars=8,
    )


def policy_sweep_interest(
    beta_values,
    u_values,
    r_values,
    base: ModelParamsInterest,
    config: Optional[SolverConfig] = None,
    dtype=None,
    mesh: Optional[jax.sharding.Mesh] = None,
    mesh_axes: tuple = ("b", "u"),
) -> PolicySweepResult:
    """(β, u, r) policy grid of interest-rate equilibria.
    NOTE ``config=None`` ≠ ``config=SolverConfig()``: None selects the sweep
    default with crossing refinement OFF; an explicit SolverConfig() keeps
    the scalar parity path's refinement ON (slower compile, finer buffers).

    With ``mesh``, the (B, U) axes are sharded over its axes (r replicated);
    cells are independent so the program scales across chips with no
    collectives. Each mesh axis size must divide the matching value-array
    length (pad the arrays if needed).

    η/tspan/δ stay pinned at the base model's resolved values for every
    cell, matching the copy-constructor semantics of the baseline sweeps
    (`models.params.with_overrides` docstring). All r must satisfy r < δ.

    ``config`` defaults to crossing refinement OFF (see SolverConfig): grid
    outputs are interpolation-bound, and the per-cell refinement bisection
    dominates the vmap³ program's compile time.
    """
    if config is None:
        config = SolverConfig(refine_crossings=False)
    econ = base.economic
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))

    import numpy as np

    if float(np.max(np.asarray(r_values))) >= econ.delta:
        raise ValueError(f"All r values must be < delta = {econ.delta}")

    beta_values = jnp.asarray(beta_values, dtype=dtype)
    u_values = jnp.asarray(u_values, dtype=dtype)
    r_values = jnp.asarray(r_values, dtype=dtype)
    tspan = base.learning.tspan

    if mesh is not None:
        from sbr_tpu.parallel import shard_axis_values

        beta_values, u_values = shard_axis_values(mesh, mesh_axes, beta_values, u_values)

    scalars = tuple(
        jnp.asarray(v, dtype)
        for v in (
            econ.p,
            econ.kappa,
            econ.lam,
            econ.eta,
            econ.delta,
            tspan[0],
            tspan[1],
            base.learning.x0,
        )
    )
    from sbr_tpu import obs
    from sbr_tpu.obs.metrics import metrics

    fn = _policy_fn(
        config, dtype.name, mesh, tuple(mesh_axes) if mesh is not None else None
    )
    n_b, n_u, n_r = (int(v.shape[0]) for v in (beta_values, u_values, r_values))
    # OOM preflight (obs.mem): unlike the baseline grid, the policy sweep
    # has no tile loop in front of it, so this is its only pre-dispatch
    # memory check — fail closed on an analytically-oversized grid instead
    # of an XLA OOM. Graceful skip on CPU (no capacity: the footprint
    # compile is skipped too) and under a mesh (the unsharded lowering
    # would overestimate the per-device footprint).
    from sbr_tpu.obs import mem as obs_mem

    if obs_mem.preflight_enabled():
        label = f"policy[{n_b}x{n_u}x{n_r}]"
        if obs_mem.device_capacity() is None or mesh is not None:
            obs_mem.preflight(
                label, None, capacity=None,
                skip_reason="sharded" if mesh is not None else None,
            )
        else:
            obs_mem.check_preflight(
                obs_mem.preflight(
                    label, policy_tile_footprint(n_b, n_u, n_r, config, dtype)
                )
            )
    # Chaos fault point (resilience.faults), mirroring beta_u_grid's.
    from sbr_tpu.resilience import faults

    faults.fire("sweep.dispatch", target=f"policy_interest[{n_b}x{n_u}x{n_r}]")
    with obs.span(
        "sweeps.policy_interest",
        n_beta=n_b, n_u=n_u, n_r=n_r, dtype=dtype.name, sharded=mesh is not None,
    ) as sp:
        xi, aw_max, status, health = obs.jit_call(
            "sweeps.policy_interest", fn, beta_values, u_values, r_values, *scalars
        )
        sp.sync(status)
    metrics().inc("sweeps.policy_interest.cells", n_b * n_u * n_r)
    obs.log_status("sweeps.policy_interest", status)
    obs.log_health("sweeps.policy_interest", health, status)
    return PolicySweepResult(
        beta_values=beta_values,
        u_values=u_values,
        r_values=r_values,
        xi=xi,
        aw_max=aw_max,
        status=status,
        health=health,
    )
