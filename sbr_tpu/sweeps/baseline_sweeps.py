"""Comparative-statics sweeps as vmap / mesh-sharded programs.

Replaces the reference's sequential loops with early termination
(`scripts/1_baseline.jl:137-200` Figure-4 u-sweep, `:210-285` Figure-5 β×u
heatmap). On TPU, solving every cell densely and masking no-run cells with
NaN status codes is cheaper than serializing the no-run frontier search
(SURVEY §7.1.2); the early-termination accounting the reference prints is
recoverable from the returned status grid.

Algebraic structure exploited (the reference does this manually at
`1_baseline.jl:169`): Stage 1 depends only on learning parameters, so the
u-axis shares one learning solution; the β-axis re-derives Stage 1 in closed
form per cell, which is free.

Sharding: each cell is independent, so the β×u grid needs no collectives —
inputs/outputs are annotated with a `NamedSharding` over a 2-D mesh and XLA
partitions the whole program; tiles ride on separate chips and results gather
only at the host boundary.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from sbr_tpu.baseline.learning import solve_learning
from sbr_tpu.baseline.solver import solve_equilibrium_core
from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.models.results import LearningSolution
from sbr_tpu.obs import prof
from sbr_tpu.resilience import faults

# Version of the β×u grid-cell NUMERICS, folded into the cross-run global
# tile cache key (`resilience.elastic.TileCache.key`). The local checkpoint
# fingerprint protects one sweep dir, but the global cache outlives code
# versions: bump this whenever a change alters any cell's bytes (solver
# math, status semantics, health-driven healing inputs) so stale entries
# miss instead of silently serving old numerics.
# v2 (ISSUE 9): adaptive numerics — SolverConfig grew the `numerics` mode
# (also in the key via the config fingerprint, so adaptive and fixed tiles
# can never share entries) and adaptive cells carry convergence-masked
# Health iteration counts; pre-adaptive entries must miss.
GRID_PROGRAM_VERSION = 2


@struct.dataclass
class USweepResult:
    """Figure-4 outputs (`1_baseline.jl:139-142`): per-u scalars."""

    u_values: jnp.ndarray
    max_withdrawals: jnp.ndarray  # AW_max, NaN when no run
    collapse_times: jnp.ndarray  # ξ
    return_times: jnp.ndarray  # ξ - τ̄_IN (`1_baseline.jl:177`)
    status: jnp.ndarray  # int32 Status codes
    health: object = None  # per-cell diag.Health grid (leaves (n_u,))


@struct.dataclass
class GridSweepResult:
    """Figure-5 outputs: (B, U) grids (`1_baseline.jl:213` stores (U, B);
    transpose at the figure layer)."""

    beta_values: jnp.ndarray
    u_values: jnp.ndarray
    max_aw: jnp.ndarray  # (B, U)
    xi: jnp.ndarray  # (B, U)
    status: jnp.ndarray  # (B, U)
    # per-cell diag.Health (leaves (B, U)); None for results assembled from
    # tile checkpoints, whose on-disk format predates diagnostics
    health: object = None


def _lean_cell(ls: LearningSolution, u, p, kappa, lam, eta, tspan_end, config: SolverConfig):
    """One cell -> scalars only; XLA dead-code-eliminates the curve outputs
    (the health scalars ride along — a handful of flag/residual lanes)."""
    r = solve_equilibrium_core(ls, u, p, kappa, lam, eta, tspan_end, config)
    return r.xi, r.tau_bar_in_unc, r.aw_max, r.status, r.health


@functools.lru_cache(maxsize=None)
def _u_sweep_fn(config: SolverConfig, mesh=None, mesh_axis=None):
    """Jitted u-sweep, cached by (config, mesh) so repeated sweeps (and the
    bench harness) reuse one traced program instead of retracing per call.
    The learning solution and economics enter as traced arguments; jit
    dead-code-eliminates the discarded per-cell curves instead of
    materializing (n_u, n_grid) temporaries."""

    def fn(ls, u_values, p, kappa, lam, eta, tspan_end):
        # Trace-time retrace accounting (obs.prof): this body runs once per
        # jit cache miss, so the count is exactly the program's trace count.
        prof.note_trace("sweeps.u_sweep")

        def cell(u):
            return _lean_cell(ls, u, p, kappa, lam, eta, tspan_end, config)

        return jax.vmap(cell)(u_values)

    if mesh is not None:
        # u-axis block-sharded via shard_map — each device runs the plain
        # vmapped program on its local block (independent cells; sharded
        # gather indexing against the replicated learning solution trips
        # XLA's sharding-in-types inference otherwise, as in policy_sweeps).
        from jax.sharding import PartitionSpec as P

        from sbr_tpu.parallel.compat import pcast, shard_map

        def body(ls, u_values, *scalars):
            vary = lambda x: pcast(x, (mesh_axis,), to="varying")
            ls = jax.tree_util.tree_map(vary, ls)
            return fn(ls, u_values, *(vary(s) for s in scalars))

        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(mesh_axis)) + (P(),) * 5,
            out_specs=P(mesh_axis),
        )
        return jax.jit(sharded)

    return jax.jit(fn)


def u_sweep(
    ls: LearningSolution,
    u_values,
    econ,
    config: SolverConfig | None = None,
    tspan_end=None,
    mesh: Optional[jax.sharding.Mesh] = None,
    mesh_axis: str = "u",
) -> USweepResult:
    """Figure-4 u-sweep: one Stage-1 solution shared across all u
    (`1_baseline.jl:44,169`), Stages 2-3 vmapped.

    With ``mesh``, the u axis is sharded over ``mesh_axis`` (cells are
    independent; the shared learning solution replicates). The mesh axis
    size must divide len(u_values)."""
    if config is None:
        config = SolverConfig()
    from sbr_tpu import obs
    from sbr_tpu.obs.metrics import metrics

    if tspan_end is None:
        tspan_end = ls.grid[-1]
    dtype = ls.cdf.dtype
    u_values = jnp.asarray(u_values, dtype=dtype)
    if mesh is not None:
        from sbr_tpu.parallel import shard_axis_values

        (u_values,) = shard_axis_values(mesh, (mesh_axis,), u_values)

    fn = _u_sweep_fn(config, mesh, mesh_axis if mesh is not None else None)
    args = (
        ls,
        u_values,
        jnp.asarray(econ.p, dtype),
        jnp.asarray(econ.kappa, dtype),
        jnp.asarray(econ.lam, dtype),
        jnp.asarray(econ.eta, dtype),
        jnp.asarray(tspan_end, dtype),
    )
    n_u = int(u_values.shape[0])
    # Chaos fault point (resilience.faults): a transient rule here models a
    # device/tunnel failure at dispatch; one global None-check when unplanned.
    faults.fire("sweep.dispatch", target=f"u_sweep[{n_u}]")
    with obs.span("sweeps.u_sweep", n_u=n_u, sharded=mesh is not None) as sp:
        xi, tau_in, aw_max, status, health = obs.jit_call("sweeps.u_sweep", fn, *args)
        sp.sync(status)
    metrics().inc("sweeps.u_sweep.cells", n_u)
    obs.log_status("sweeps.u_sweep", status)
    obs.log_health("sweeps.u_sweep", health, status)
    return USweepResult(
        u_values=u_values,
        max_withdrawals=aw_max,
        collapse_times=xi,
        return_times=xi - tau_in,
        status=status,
        health=health,
    )


def beta_u_grid(
    beta_values,
    u_values,
    base: ModelParams,
    config: Optional[SolverConfig] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    mesh_axes: tuple = ("b", "u"),
    dtype=None,
) -> GridSweepResult:
    """Figure-5 β×u grid (`1_baseline.jl:224-267`) as one jitted program.
    NOTE ``config=None`` ≠ ``config=SolverConfig()``: None selects the sweep
    default with crossing refinement OFF; an explicit SolverConfig() keeps
    the scalar parity path's refinement ON (slower compile, finer buffers).

    Reproduces the copy-constructor semantics of the reference sweep: η and
    tspan stay pinned at the base model's resolved values for every β
    (`with_overrides`; see models.params docstring — `ModelParameters(m_base;
    β=β)` does NOT recompute η).

    With ``mesh``, the (B, U) grid is sharded over its axes; cells are
    independent so no collectives are required and the program scales across
    chips linearly. Each mesh axis size must divide the matching value-array
    length (pad the value arrays if needed).

    ``config`` defaults to crossing refinement OFF (see SolverConfig): grid
    outputs (AW_max, ξ, status) are interpolation-bound, and the per-cell
    refinement bisection dominates the vmap² program's compile time.
    """
    if config is None:
        config = SolverConfig(refine_crossings=False)
    # with_overrides pins eta/tspan to the base's resolved values for every
    # beta (see models.params), so the pinned economics are exactly base's.
    econ = base.economic
    tspan = base.learning.tspan
    x0 = base.learning.x0
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))

    beta_values = jnp.asarray(beta_values, dtype=dtype)
    u_values = jnp.asarray(u_values, dtype=dtype)

    if mesh is not None:
        from sbr_tpu.parallel import shard_axis_values

        beta_values, u_values = shard_axis_values(mesh, mesh_axes, beta_values, u_values)

    from sbr_tpu import obs
    from sbr_tpu.obs.metrics import metrics

    grid_fn = _grid_fn(config, dtype.name, mesh, tuple(mesh_axes) if mesh is not None else None)
    scalars = tuple(
        jnp.asarray(v, dtype) for v in (econ.p, econ.kappa, econ.lam, econ.eta, tspan[0], tspan[1], x0)
    )
    n_b, n_u = int(beta_values.shape[0]), int(u_values.shape[0])
    # Chaos fault point: the tile loop's retry policy (utils.checkpoint)
    # wraps this whole call, so a transient injected here exercises the
    # real recovery path, not a mock.
    faults.fire("sweep.dispatch", target=f"beta_u_grid[{n_b}x{n_u}]")
    with obs.span(
        "sweeps.beta_u_grid", n_beta=n_b, n_u=n_u, dtype=dtype.name, sharded=mesh is not None
    ) as sp:
        xi, tau_in, aw_max, status, health = obs.jit_call(
            "sweeps.beta_u_grid", grid_fn, beta_values, u_values, *scalars
        )
        sp.sync(status)
    metrics().inc("sweeps.beta_u_grid.cells", n_b * n_u)
    obs.log_status("sweeps.beta_u_grid", status)
    obs.log_health("sweeps.beta_u_grid", health, status)
    return GridSweepResult(
        beta_values=beta_values, u_values=u_values, max_aw=aw_max, xi=xi,
        status=status, health=health,
    )


class _TracedLearning:
    """Duck-typed LearningParams accepting traced beta (sweep-internal)."""

    def __init__(self, beta, tspan, x0):
        self.beta = beta
        self.tspan = tspan
        self.x0 = x0


def solve_param_cell(beta, u, p, kappa, lam, eta, t0, t1, x0, config: SolverConfig, dtype):
    """One fully-parameterized equilibrium cell from traced scalars:
    closed-form Stage 1 rebuilt per cell, then the lean Stage 2-3 solve.

    The shared unit under BOTH the β×u grid program (`_grid_fn` vmaps it
    over two axes with broadcast economics) and the serving engine's
    micro-batch program (`sbr_tpu.serve.engine` vmaps it over one axis
    with every parameter per-lane) — one definition means a served query
    and a sweep cell can never drift numerically."""
    ls = solve_learning(_TracedLearning(beta=beta, tspan=(t0, t1), x0=x0), config, dtype=dtype)
    return _lean_cell(ls, u, p, kappa, lam, eta, t1, config)


def _sweep_footprint(cache: dict, axes, config, dtype, build_fn, n_scalars) -> dict:
    """Shared footprint machinery for the sweep modules: normalize the
    (config, dtype) defaults exactly as the sweep entry points do, then AOT
    lower + compile the UNSHARDED program on abstract `jax.ShapeDtypeStruct`
    arguments (no data, no execution, no device buffers) and read XLA's
    ``memory_analysis()`` — cached per (axes, config, dtype), since the OOM
    preflight and the tile_shape="auto" planner hit the same shapes
    repeatedly. A mesh changes the per-device footprint and is handled by
    the callers' graceful-skip."""
    if config is None:
        config = SolverConfig(refine_crossings=False)
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    axes = tuple(int(n) for n in axes)
    key = (axes, config, dtype.name)
    fp = cache.get(key)
    if fp is None:
        from sbr_tpu.obs import mem

        scalar = jax.ShapeDtypeStruct((), dtype)
        args = tuple(jax.ShapeDtypeStruct((n,), dtype) for n in axes)
        args += (scalar,) * n_scalars
        fp = mem.aot_footprint(build_fn(config, dtype.name), *args)
        cache[key] = fp
    return dict(fp)


_FOOTPRINT_CACHE: dict = {}


def grid_tile_footprint(
    n_b: int,
    n_u: int,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> dict:
    """Analytical memory footprint of ONE (n_b × n_u) β×u grid dispatch
    (argument/output/temp bytes, summed as ``total_bytes``) — the model
    the OOM preflight compares against device capacity and the
    ``tile_shape="auto"`` planner probes (`sbr_tpu.obs.mem`).
    ``config=None`` selects the sweep default (refinement OFF), matching
    `beta_u_grid`. See `_sweep_footprint` for the AOT mechanics."""
    return _sweep_footprint(
        _FOOTPRINT_CACHE,
        (n_b, n_u),
        config,
        dtype,
        lambda cfg, dt: _grid_fn(cfg, dt, None, None),
        n_scalars=7,
    )


@functools.lru_cache(maxsize=None)
def _grid_fn(config: SolverConfig, dtype_name: str, mesh, mesh_axes):
    """Jitted β×u grid program, cached by (config, dtype, mesh) so repeated
    sweeps — tiled runs, the bench harness — reuse one traced program.
    Model parameters enter as traced scalars; Stage 1 is rebuilt per cell via
    the closed form, which is free."""
    dtype = jnp.dtype(dtype_name)

    def cell(beta, u, p, kappa, lam, eta, t0, t1, x0):
        # vmap² traces `cell` once per program trace — the retrace counter
        # (obs.prof) sees exactly the grid program's jit cache misses.
        prof.note_trace("sweeps.beta_u_grid")
        return solve_param_cell(beta, u, p, kappa, lam, eta, t0, t1, x0, config, dtype)

    bcast = (None,) * 7
    fn = jax.vmap(jax.vmap(cell, in_axes=(None, 0) + bcast), in_axes=(0, None) + bcast)

    if mesh is not None:
        # A single sharding is a pytree prefix: it applies to every output
        # leaf, including the per-cell Health scalars.
        out_sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*mesh_axes))
        return jax.jit(fn, out_shardings=out_sharding)
    return jax.jit(fn)
