"""Structured status accounting (SURVEY §5.5).

The reference reports sweep outcomes through prints: percent-progress
counters and early-termination totals (`scripts/1_baseline.jl:188-191,
261-271`). Under jit there are no prints; every sweep instead returns an
int32 status array (`models.results.Status`), and these helpers turn it
into the same accounting after the fact. The obs subsystem logs the same
accounting as structured `status` events (`obs.log_status`).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from sbr_tpu.models.results import Status

# Codes outside the Status enum (e.g. the tiled checkpoint driver's -1
# "never computed" fill) are accounted under this key so counts always sum
# to the grid size.
UNKNOWN_KEY = "UNKNOWN"


def status_counts(status) -> Dict[str, int]:
    """Histogram of `Status` codes in a sweep's status array.

    Key order is deterministic: `Status` enum declaration order, then
    ``UNKNOWN`` (out-of-enum codes) last — stable across runs and Python
    processes, so event logs and manifests diff cleanly.
    """
    status = np.asarray(status)
    counts = {s.name: int((status == int(s)).sum()) for s in Status}
    unknown = int(status.size) - sum(counts.values())
    if unknown:
        counts[UNKNOWN_KEY] = unknown
    return counts


def status_summary(status) -> str:
    """One-line summary matching the reference's accounting: run cells vs
    the no-run region it skips via early termination
    (`1_baseline.jl:269-271`). Deterministic part order (see
    `status_counts`); an all-no-run grid reads "0/N run, ..."."""
    counts = status_counts(status)
    total = int(np.asarray(status).size)
    run = counts.get("RUN", 0)
    parts = [f"{run}/{total} run"]
    parts += [f"{v} {k.lower()}" for k, v in counts.items() if k != "RUN" and v]
    return ", ".join(parts)
