"""Compatibility shim: the timing primitives moved to ``sbr_tpu.obs.timing``
as part of the run-telemetry subsystem (PR 1). Import from ``sbr_tpu.obs``
going forward; this module re-exports the full original surface so existing
call sites (`bench.py`, benchmarks/, tests) keep working unchanged."""

from sbr_tpu.obs.timing import StageTimer, fence, trace

__all__ = ["StageTimer", "fence", "trace"]
