"""Auxiliary subsystems: timing/profiling, status accounting, and sweep
checkpointing.

The reference's equivalents are hand-rolled `time()` deltas stored as
`solve_time` (`src/baseline/learning.jl:110,121`), `println` progress
accounting (`scripts/1_baseline.jl:188-191,261-271`), and no checkpointing
at all (every run recomputes everything — SURVEY §5.4). Here:

- ``timing``     — re-export shim for `sbr_tpu.obs.timing` (the wall-clock
                   stage timers and honest device fences moved into the
                   run-telemetry subsystem `sbr_tpu.obs`).
- ``status``     — structured per-cell status accounting (the jit-safe
                   replacement for the reference's early-termination prints).
- ``checkpoint`` — tiled sweep execution with on-disk resume and per-tile
                   retry, so paper-resolution grids survive interruption.
"""

from sbr_tpu.utils.checkpoint import run_tiled_grid
from sbr_tpu.utils.status import status_counts, status_summary
from sbr_tpu.obs.timing import StageTimer, trace

__all__ = ["StageTimer", "run_tiled_grid", "status_counts", "status_summary", "trace"]
