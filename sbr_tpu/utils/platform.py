"""Backend platform pinning.

This image's axon sitecustomize force-registers the TPU plugin and OVERRIDES
the `JAX_PLATFORMS` environment variable, so pinning a platform must go
through `jax.config` after importing jax (verified: env alone is ignored).
This is the home for that workaround — used by the bench harness child, the
figures CLI, and benchmarks/agent_comm.py. Two call sites intentionally keep
their own variants: tests/conftest.py (must set XLA_FLAGS before importing
jax, so it inlines the call) and __graft_entry__._ensure_devices (wraps it
in try/except RuntimeError because the driver may call it after a backend
already initialized, and follows with an explicit device-count check)."""

from __future__ import annotations


def pin_cpu_platform() -> None:
    """Pin the CPU backend, never touching a (possibly hung) accelerator.

    Must be called before any backend-initializing JAX operation; afterwards
    it either raises (backend already initialized) or is ignored by the live
    backend — callers that need certainty should check `jax.devices()`."""
    import jax

    jax.config.update("jax_platforms", "cpu")
