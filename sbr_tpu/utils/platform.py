"""Backend platform pinning.

This image's axon sitecustomize force-registers the TPU plugin and OVERRIDES
the `JAX_PLATFORMS` environment variable, so pinning a platform must go
through `jax.config` after importing jax (verified: env alone is ignored).
This is the single home for that workaround — used by the bench harness
child, the figures CLI, and mirrored by tests/conftest.py (which must also
set XLA_FLAGS before jax import, so it inlines the same call)."""

from __future__ import annotations


def pin_cpu_platform() -> None:
    """Pin the CPU backend, never touching a (possibly hung) accelerator.

    Must be called before any backend-initializing JAX operation; afterwards
    it either raises (backend already initialized) or is ignored by the live
    backend — callers that need certainty should check `jax.devices()`."""
    import jax

    jax.config.update("jax_platforms", "cpu")
