"""Tiled sweep execution with on-disk resume (SURVEY §5.4).

The reference recomputes everything on every run — its only reuse is
in-memory (`scripts/1_baseline.jl:44,169`). For paper-resolution grids
(5000×5000, "a couple hours" on the reference's CPU,
`1_baseline.jl:209-210`) the TPU build persists finished tiles so an
interrupted sweep resumes instead of restarting, and a failed tile is
retried rather than aborting the grid (the multi-host sweep-driver
failure-detection analogue, SURVEY §5.3).

Format: one ``.npz`` per tile (atomic rename) holding the four result
grids, keyed by tile indices; a resumed run recomputes nothing for tiles
already on disk. Tiles are plain numpy — checkpoints are device- and
dtype-portable.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import time
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.sweeps.baseline_sweeps import GridSweepResult, beta_u_grid

_FIELDS = ("max_aw", "xi", "status")


def _tile_path(ckpt_dir: Path, bi: int, ui: int) -> Path:
    return ckpt_dir / f"tile_b{bi:05d}_u{ui:05d}.npz"


def tile_origins(n_b: int, n_u: int, tile_shape: Tuple[int, int]) -> list:
    """Tile origins in `run_tiled_grid`'s iteration order — the single
    source of truth shared with the multi-host farm's ownership split and
    completion barrier (`parallel.distributed`)."""
    tb, tu = tile_shape
    return [(bi, ui) for bi in range(0, n_b, tb) for ui in range(0, n_u, tu)]


def _sweep_fingerprint(beta_values, u_values, base, config, tile_shape, dtype) -> str:
    """Hash of everything that determines tile contents, so a checkpoint dir
    can never silently serve results for different parameters."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(np.asarray(beta_values, dtype=np.float64)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(u_values, dtype=np.float64)).tobytes())
    h.update(repr((base, config, tuple(tile_shape), str(dtype))).encode())
    return h.hexdigest()


def _check_fingerprint(ckpt: Path, fingerprint: str) -> None:
    manifest = ckpt / "manifest.json"
    if manifest.exists():
        try:
            stored = json.loads(manifest.read_text()).get("fingerprint")
        except json.JSONDecodeError:
            # A peer process is mid-write on non-atomic shared storage;
            # with the atomic rename below this means corruption, not a
            # race — but give one short grace read before failing.
            time.sleep(0.2)
            try:
                stored = json.loads(manifest.read_text()).get("fingerprint")
            except json.JSONDecodeError as err:
                raise ValueError(
                    f"Checkpoint dir {ckpt} has an unreadable manifest.json "
                    f"({err}); it was likely written non-atomically by an "
                    "older build or truncated on disk. Delete the corrupt "
                    "manifest (or use a fresh checkpoint_dir) and rerun."
                ) from err
        if stored != fingerprint:
            raise ValueError(
                f"Checkpoint dir {ckpt} holds tiles for a different sweep "
                "(grid values, model, config, tile shape, or dtype changed). "
                "Use a fresh checkpoint_dir or delete the stale one."
            )
    elif any(ckpt.glob("tile_*.npz")):
        # Tiles without a manifest cannot be attributed to any sweep — fail
        # closed rather than silently adopting them.
        raise ValueError(
            f"Checkpoint dir {ckpt} contains tiles but no manifest.json; "
            "cannot confirm they belong to this sweep. Use a fresh "
            "checkpoint_dir or delete the unattributed tiles."
        )
    else:
        # Atomic write: multi-host farms start several processes against
        # one dir concurrently; a peer must never observe a partial file.
        # Losing the os.replace race to a peer writing the SAME sweep is
        # fine (identical content).
        fd, tmp = tempfile.mkstemp(dir=ckpt, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps({"fingerprint": fingerprint}))
        os.replace(tmp, manifest)


def _save_atomic(path: Path, arrays: dict) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        # Write via the open handle: np.savez appends ".npz" to bare paths,
        # which would break the atomic rename.
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def run_tiled_grid(
    beta_values,
    u_values,
    base: ModelParams,
    config: Optional[SolverConfig] = None,
    tile_shape: Tuple[int, int] = (256, 256),
    checkpoint_dir: Optional[str] = None,
    mesh=None,
    dtype=None,
    max_retries: int = 2,
    verbose: bool = False,
    tile_owner=None,
) -> GridSweepResult:
    """β×u grid in tiles with optional on-disk resume.
    NOTE ``config=None`` ≠ ``config=SolverConfig()``: None selects the sweep
    default (crossing refinement OFF, like `beta_u_grid`), and the config is
    part of the sweep fingerprint — switching between the two invalidates an
    existing checkpoint dir (by design: tile numerics would differ).

    Semantically identical to one `beta_u_grid` call over the full grid
    (cells are independent); tiling bounds device-memory footprint at
    paper resolution and gives the checkpoint/retry granularity.

    ``tile_owner(bi, ui) -> bool`` restricts computation to a subset of
    tiles (others stay at their NaN/-1 initial fill unless already on
    disk) — the hook the multi-host sweep farm uses to split a grid
    across processes (`parallel.distributed.run_tiled_grid_multihost`).
    """
    if config is None:  # sweep default: refinement off (see beta_u_grid)
        config = SolverConfig(refine_crossings=False)
    beta_values = np.asarray(beta_values)
    u_values = np.asarray(u_values)
    nb, nu = len(beta_values), len(u_values)
    tb, tu = tile_shape

    if mesh is not None:
        # Every tile (including ragged edge tiles) must satisfy
        # beta_u_grid's divisibility precondition; validate up front so a
        # deterministic sharding error is not retried.
        # beta_u_grid shards by the axes NAMED "b" and "u" (its default
        # mesh_axes), regardless of their order in the mesh.
        mb, mu = mesh.shape["b"], mesh.shape["u"]
        tile_dims = {min(tb, nb - bi) for bi in range(0, nb, tb)}, {
            min(tu, nu - ui) for ui in range(0, nu, tu)
        }
        if any(d % mb for d in tile_dims[0]) or any(d % mu for d in tile_dims[1]):
            raise ValueError(
                f"Tile sizes {sorted(tile_dims[0])}×{sorted(tile_dims[1])} must be "
                f"divisible by the mesh axes {mb}×{mu}; choose tile_shape/grid "
                "sizes that are multiples of the mesh shape."
            )

    ckpt = None
    if checkpoint_dir is not None:
        ckpt = Path(checkpoint_dir)
        ckpt.mkdir(parents=True, exist_ok=True)
        _check_fingerprint(
            ckpt, _sweep_fingerprint(beta_values, u_values, base, config, tile_shape, dtype)
        )

    # Keyed off _FIELDS so the accumulator, tile save, and cache load stay in
    # lockstep: adding a field without an init entry fails loudly here.
    field_init = {"max_aw": (np.nan, np.float64), "xi": (np.nan, np.float64), "status": (-1, np.int32)}
    out = {f: np.full((nb, nu), *field_init[f]) for f in _FIELDS}

    n_cached = 0
    for bi, ui in tile_origins(nb, nu, tile_shape):
            bs = slice(bi, min(bi + tb, nb))
            us = slice(ui, min(ui + tu, nu))
            path = _tile_path(ckpt, bi, ui) if ckpt is not None else None

            if path is not None and path.exists():
                data = np.load(path)
                for f in _FIELDS:
                    out[f][bs, us] = data[f]
                n_cached += 1
                continue

            if tile_owner is not None and not tile_owner(bi, ui):
                continue  # another process's tile; it lands on disk, not here

            last_err = None
            for attempt in range(max_retries + 1):
                try:
                    tile = beta_u_grid(
                        beta_values[bs], u_values[us], base, config=config, mesh=mesh, dtype=dtype
                    )
                    arrays = {f: np.asarray(getattr(tile, f)) for f in _FIELDS}
                    break
                except (ValueError, TypeError):
                    # Deterministic shape/param/dtype bugs: retrying the
                    # identical call just burns attempts — fail immediately.
                    raise
                except Exception as err:  # transient device/runtime failure
                    last_err = err
                    print(
                        f"  tile ({bi},{ui}) attempt {attempt + 1}/{max_retries + 1} "
                        f"failed: {err!r}",
                        file=sys.stderr,
                    )
                    if attempt < max_retries:
                        time.sleep(1.0 * (attempt + 1))  # brief backoff
            else:
                raise RuntimeError(
                    f"Tile ({bi},{ui}) failed after {max_retries + 1} attempts"
                ) from last_err

            for f in _FIELDS:
                out[f][bs, us] = arrays[f]
            if path is not None:
                _save_atomic(path, arrays)
            if verbose:
                done = (bi // tb) * ((nu + tu - 1) // tu) + ui // tu + 1
                total = ((nb + tb - 1) // tb) * ((nu + tu - 1) // tu)
                print(f"  tile {done}/{total} done")

    if verbose and n_cached:
        print(f"  resumed {n_cached} tiles from {ckpt}")

    import jax.numpy as jnp

    return GridSweepResult(
        beta_values=jnp.asarray(beta_values),
        u_values=jnp.asarray(u_values),
        max_aw=jnp.asarray(out["max_aw"]),
        xi=jnp.asarray(out["xi"]),
        status=jnp.asarray(out["status"]),
    )
