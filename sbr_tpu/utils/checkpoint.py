"""Tiled sweep execution with on-disk resume and self-healing (SURVEY §5.3-5.4).

The reference recomputes everything on every run — its only reuse is
in-memory (`scripts/1_baseline.jl:44,169`). For paper-resolution grids
(5000×5000, "a couple hours" on the reference's CPU,
`1_baseline.jl:209-210`) the TPU build persists finished tiles so an
interrupted sweep resumes instead of restarting, and a failed tile is
retried rather than aborting the grid (the multi-host sweep-driver
failure-detection analogue, SURVEY §5.3).

Format: one ``.npz`` per tile (atomic rename) holding the result grids,
keyed by tile indices, plus a ``.sha256`` integrity sidecar; a resumed run
recomputes nothing for tiles already on disk. Tiles are plain numpy —
checkpoints are device- and dtype-portable.

Resilience layer (`sbr_tpu.resilience`):

- tile failures go through the unified retry engine (`resilience.retry`,
  exponential backoff, deterministic-error fail-fast, a per-sweep shared
  retry budget ``SBR_RETRY_BUDGET``) instead of a bare loop;
- cached tiles are sha256-verified on load; a corrupt tile is quarantined
  (``quarantine/`` beside the checkpoint) and recomputed, never trusted;
- cells flagged divergent by the `sbr_tpu.diag` health bitmask are re-run
  per cell up the degrade ladder (same precision, then float64 with
  tightened tolerances — `resilience.heal`), and the checkpoint manifest
  gains a ``repairs`` block (disable with ``SBR_HEAL=0`` or ``heal=False``);
- SIGTERM/SIGINT inside the tile loop finalize obs manifests as
  ``"interrupted"`` and clean partial temp files (`resilience.shutdown`);
- named fault points (``tile.compute``, ``tile.result``,
  ``checkpoint.save``, ``checkpoint.load``) let a seeded ``SBR_FAULT_PLAN``
  inject transient errors, NaN-poisoned results, corrupted files, hangs,
  and preemptions deterministically (`resilience.faults`) — the chaos
  harness `python -m sbr_tpu.resilience.chaos` drives them in CI.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import time
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from sbr_tpu import obs
from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.resilience import faults, heal, retry, shutdown
from sbr_tpu.sweeps.baseline_sweeps import GridSweepResult, beta_u_grid

_FIELDS = ("max_aw", "xi", "status")


# ---------------------------------------------------------------------------
# Canonical parameter fingerprints (shared keying machinery)
# ---------------------------------------------------------------------------


def canonicalize(obj) -> str:
    """Deterministic textual form of a parameter pytree — the canonical
    input to `params_fingerprint` and `_sweep_fingerprint`.

    Stability contract: the same logical structure produces the same
    string across processes, interpreter restarts, and dict insertion
    orders. Dataclasses render as ``TypeName(field=..., ...)`` with fields
    sorted by name (so ModelParams vs ModelParamsInterest with identical
    numbers can never collide); dicts sort by key; floats use Python's
    shortest round-trip ``repr`` (exact for every binary64); numpy scalars
    and arrays hash dtype + raw bytes. Unknown object types raise
    ``TypeError`` — a silently unstable ``repr`` (memory addresses) must
    never leak into a cache key.
    """
    import dataclasses as _dc

    if _dc.is_dataclass(obj) and not isinstance(obj, type):
        inner = ",".join(
            f"{name}={canonicalize(getattr(obj, name))}"
            for name in sorted(f.name for f in _dc.fields(obj))
        )
        return f"{type(obj).__name__}({inner})"
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: canonicalize(kv[0]))
        return "{" + ",".join(f"{canonicalize(k)}:{canonicalize(v)}" for k, v in items) + "}"
    if isinstance(obj, (list, tuple)):
        return "[" + ",".join(canonicalize(v) for v in obj) + "]"
    if obj is None or isinstance(obj, (bool, int, str, bytes)):
        return repr(obj)
    if isinstance(obj, float):
        return repr(obj)
    if isinstance(obj, np.generic):
        return f"{obj.dtype.name}:{obj.item()!r}"
    if isinstance(obj, np.ndarray):
        return (
            f"ndarray{tuple(obj.shape)}:{obj.dtype.name}:"
            f"{np.ascontiguousarray(obj).tobytes().hex()}"
        )
    raise TypeError(
        f"canonicalize: unsupported type {type(obj).__name__} — extend the "
        "canonical form rather than falling back to repr (addresses would "
        "make fingerprints process-local)"
    )


def params_fingerprint(params) -> str:
    """Stable sha256 hex of a parameter pytree (ModelParams and friends,
    SolverConfig, or any nesting of dataclasses/dicts/sequences/scalars).

    The public keying helper extracted from the tile-checkpoint fingerprint
    (ISSUE 7 satellite): the same params pytree yields the same hex across
    processes and dict orderings, so the serving engine's result cache
    (`sbr_tpu.serve.engine`) and any future cross-run sweep cache can both
    key on it. See `canonicalize` for the stability contract.
    """
    return hashlib.sha256(canonicalize(params).encode()).hexdigest()


def resolve_tile_shape(
    nb: int,
    nu: int,
    tile_shape,
    config: Optional[SolverConfig] = None,
    dtype=None,
    mesh=None,
) -> Tuple[Tuple[int, int], Optional[dict]]:
    """Resolve ``tile_shape="auto"`` via the obs.mem capacity planner.

    An explicit ``(tb, tu)`` passes through untouched (plan record None).
    For ``"auto"``, the planner fits a linear footprint model from two
    small abstract AOT lowerings (`grid_tile_footprint`) and picks the
    largest power-of-two square tile fitting ``SBR_MEM_HEADROOM`` × device
    capacity; with no capacity (CPU / absent ``memory_stats``) it falls
    back to the historical (256, 256) default clamped to the grid, verdict
    ``"skipped"``. Deterministic: same capacity + same grid ⇒ same shape,
    so multihost peers planning independently agree on the tile grid (and
    the checkpoint fingerprint, which hashes the RESOLVED shape, fails
    loudly if they somehow don't). The decision is recorded as a ``plan``
    event + ``memory.plan`` manifest block when telemetry is on.
    """
    if tile_shape != "auto":
        tb, tu = tile_shape
        return (int(tb), int(tu)), None
    if config is None:  # the sweep default, matching run_tiled_grid
        config = SolverConfig(refine_crossings=False)
    from sbr_tpu.obs import mem as obs_mem
    from sbr_tpu.sweeps.baseline_sweeps import grid_tile_footprint

    multiple = (1, 1)
    if mesh is not None:
        multiple = (int(mesh.shape["b"]), int(mesh.shape["u"]))
    shape, rec = obs_mem.plan_from_probes(
        int(nb),
        int(nu),
        lambda tb, tu: grid_tile_footprint(tb, tu, config=config, dtype=dtype),
        multiple_of=multiple,
        # A sharded tile spreads its cells evenly over the mesh: per-device
        # footprint is ~cells/mesh-size, so budget the model per device or
        # the planner would undersize sharded tiles by the device count.
        per_device_divisor=multiple[0] * multiple[1],
    )
    try:
        from sbr_tpu import obs

        run = obs.current_run()
        if run is not None:
            run.log_plan(rec)
    except Exception:
        pass  # telemetry must never sink the planner
    return shape, rec


def _preflight_tile(nb, nu, tb, tu, config, dtype, mesh, plan=None) -> Optional[dict]:
    """OOM preflight for the tiled sweep: AOT-lower one worst-case (full)
    tile, read its analytical footprint, and fail CLOSED
    (`MemoryPreflightError`) when it exceeds headroom × capacity — a clear
    error before dispatch instead of an XLA OOM mid-sweep. Graceful skips
    (recorded, never fatal): ``SBR_MEM_PREFLIGHT=0``, no device capacity
    (CPU/absent API — the footprint compile is skipped too, so CPU runs
    pay nothing), or a mesh (the unsharded lowering would overestimate the
    per-device footprint by the device count; never fail a dispatch that
    actually fits). When the capacity planner already fitted this grid
    (``plan`` from tile_shape="auto"), the verdict comes from its model —
    the planner just proved the budget from two probe lowerings, and
    re-compiling the full tile only to discard the executable would double
    the first-dispatch XLA compile. An EXPLICIT tile_shape does pay that
    extra AOT compile, deliberately: the exact analytical footprint is the
    trustworthy fail-closed signal for a shape no model has vetted, the
    result is cached (`_FOOTPRINT_CACHE`), and rigs with a persistent XLA
    compile cache dedupe the dispatch-time recompile to a deserialize."""
    from sbr_tpu.obs import mem as obs_mem

    if not obs_mem.preflight_enabled():
        return None
    tb_eff, tu_eff = min(tb, nb), min(tu, nu)
    label = f"tile[{tb_eff}x{tu_eff}]"
    capacity = obs_mem.device_capacity()
    if capacity is None or mesh is not None:
        return obs_mem.preflight(
            label, None, capacity=None,
            skip_reason="sharded" if mesh is not None else None,
        )
    if plan is not None and plan.get("verdict") == "ok":
        fp = {
            "total_bytes": int(
                plan["model_fixed_bytes"]
                + plan["model_per_cell_bytes"] * (tb_eff * tu_eff)
            ),
            "source": "planner-model",
        }
    else:
        from sbr_tpu.sweeps.baseline_sweeps import grid_tile_footprint

        fp = grid_tile_footprint(tb_eff, tu_eff, config=config, dtype=dtype)
    return obs_mem.check_preflight(obs_mem.preflight(label, fp, capacity=capacity))


def _tile_path(ckpt_dir: Path, bi: int, ui: int) -> Path:
    return ckpt_dir / f"tile_b{bi:05d}_u{ui:05d}.npz"


def tile_origins(n_b: int, n_u: int, tile_shape: Tuple[int, int]) -> list:
    """Tile origins in `run_tiled_grid`'s iteration order — the single
    source of truth shared with the multi-host farm's ownership split and
    completion barrier (`parallel.distributed`)."""
    tb, tu = tile_shape
    return [(bi, ui) for bi in range(0, n_b, tb) for ui in range(0, n_u, tu)]


def _sweep_fingerprint(beta_values, u_values, base, config, tile_shape, dtype) -> str:
    """Hash of everything that determines tile contents, so a checkpoint dir
    can never silently serve results for different parameters. Built on the
    shared `canonicalize` form (not raw ``repr``, whose dataclass field
    ORDER — rather than name — used to define the hash); checkpoint dirs
    written by older builds therefore fail the fingerprint check loudly and
    must be recomputed, never silently adopted."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(np.asarray(beta_values, dtype=np.float64)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(u_values, dtype=np.float64)).tobytes())
    h.update(canonicalize((base, config, tuple(int(t) for t in tile_shape), str(dtype))).encode())
    return h.hexdigest()


def _check_fingerprint(ckpt: Path, fingerprint: str, tile_shape=None) -> None:
    """Create-or-verify the checkpoint manifest. The creating process also
    records its RESOLVED ``tile_shape`` so a late-joining elastic host can
    adopt the sweep's geometry instead of re-planning from its own device
    capacity (`resilience.elastic.recorded_tile_shape`) — without it, a
    heterogeneous joiner's "auto" resolution would fingerprint-mismatch."""
    manifest = ckpt / "manifest.json"
    if manifest.exists():
        try:
            stored = json.loads(manifest.read_text()).get("fingerprint")
        except json.JSONDecodeError:
            # A peer process is mid-write on non-atomic shared storage;
            # with the atomic rename below this means corruption, not a
            # race — but give one short grace read before failing.
            time.sleep(0.2)
            try:
                stored = json.loads(manifest.read_text()).get("fingerprint")
            except json.JSONDecodeError as err:
                raise ValueError(
                    f"Checkpoint dir {ckpt} has an unreadable manifest.json "
                    f"({err}); it was likely written non-atomically by an "
                    "older build or truncated on disk. Delete the corrupt "
                    "manifest (or use a fresh checkpoint_dir) and rerun."
                ) from err
        if stored != fingerprint:
            raise ValueError(
                f"Checkpoint dir {ckpt} holds tiles for a different sweep "
                "(grid values, model, config, tile shape, or dtype changed). "
                "Use a fresh checkpoint_dir or delete the stale one."
            )
    elif any(ckpt.glob("tile_*.npz")):
        # Tiles without a manifest cannot be attributed to any sweep — fail
        # closed rather than silently adopting them.
        raise ValueError(
            f"Checkpoint dir {ckpt} contains tiles but no manifest.json; "
            "cannot confirm they belong to this sweep. Use a fresh "
            "checkpoint_dir or delete the unattributed tiles."
        )
    else:
        # Atomic write: multi-host farms start several processes against
        # one dir concurrently; a peer must never observe a partial file.
        # Losing the os.replace race to a peer writing the SAME sweep is
        # fine (identical content).
        doc = {"fingerprint": fingerprint}
        if tile_shape is not None:
            doc["tile_shape"] = [int(t) for t in tile_shape]
        fd, tmp = tempfile.mkstemp(dir=ckpt, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(doc))
        os.replace(tmp, manifest)


def _save_atomic(path: Path, arrays: dict) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        # Write via the open handle: np.savez appends ".npz" to bare paths,
        # which would break the atomic rename. track_tmp registers the
        # partial file so a graceful shutdown sweeps it even if this
        # frame's own cleanup never runs (e.g. SIGTERM mid-interpreter).
        with shutdown.track_tmp(tmp):
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise
    # Integrity sidecar AFTER the rename: a crash between the two leaves a
    # tile with no sidecar, which verifies as "legacy" (trusted) — never a
    # tile whose sidecar describes different bytes.
    heal.write_sidecar(path)


def _load_tile_verified(path: Path, may_quarantine: bool = True) -> Optional[dict]:
    """Load a cached tile, sha256-verifying first. Returns the field dict,
    or None for a corrupt/unreadable tile — quarantined only when
    ``may_quarantine`` (the caller will recompute the slot). A multihost
    non-owner pass must NOT move a peer's corrupt tile away (it would skip
    the recompute, orphaning the slot and stalling the barrier); it leaves
    the evidence in place for the owner/stealer/assembly pass, all of which
    do recompute. The ``checkpoint.load`` fault point injects read failures."""
    tile_id = path.name
    try:
        faults.fire("checkpoint.load", target=tile_id)
        if heal.verify_file(path) == "mismatch":
            if may_quarantine:
                heal.quarantine(path, reason="sha256-mismatch")
            return None
        data = np.load(path)
        return {f: data[f] for f in _FIELDS}
    except Exception as err:
        # Unreadable beyond the hash check — torn zip (BadZipFile), rotted
        # magic bytes on a sidecar-less legacy tile (np.load raises
        # ValueError for those), missing fields (KeyError), or an injected
        # load fault: all are corruption from the sweep's point of view, and
        # quarantine+recompute is safe even for a genuine schema mismatch
        # (the recompute writes a current-schema tile).
        if may_quarantine and path.exists():
            heal.quarantine(path, reason=f"unreadable: {err!r}")
        return None


def _poison_tile(rule, arrays: dict, flags: np.ndarray, tile_id: str) -> None:
    """Apply a ``nan`` fault injection: poison the first ``rule.cells``
    cells of every float field and mark them NAN_OUTPUT-divergent — the
    simulated device-garbage the degrade ladder must catch and repair."""
    from sbr_tpu.diag.health import NAN_OUTPUT

    n = min(int(rule.cells), flags.size)
    for k in range(n):
        idx = np.unravel_index(k, flags.shape)
        for f in arrays:
            if np.issubdtype(arrays[f].dtype, np.floating):
                arrays[f][idx] = np.nan
        flags[idx] |= NAN_OUTPUT


def _record_repairs(ckpt: Path, repairs: list) -> None:
    """Fold this run's repairs into the checkpoint manifest's ``repairs``
    block (atomic rewrite). Concurrent peers can race the read-modify-write
    and drop each other's entries — tolerable: the obs event log is the
    authoritative record; this block is the human-facing summary."""
    manifest = ckpt / "manifest.json"
    try:
        doc = json.loads(manifest.read_text())
    except (OSError, json.JSONDecodeError):
        # Never rewrite the manifest from scratch: losing the stored
        # fingerprint would brick the checkpoint dir for future resumes.
        # The obs event log already carries every repair; skip the summary.
        return
    doc.setdefault("repairs", []).extend(repairs)
    fd, tmp = tempfile.mkstemp(dir=ckpt, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        f.write(json.dumps(doc))
    os.replace(tmp, manifest)


def _flight_recorder():
    """The process-wide flight recorder when ``SBR_FLIGHT`` is on, else
    None. The env check comes FIRST so the default path never imports
    `sbr_tpu.obs.flight` — the structural-no-op contract (ISSUE 20)."""
    if os.environ.get("SBR_FLIGHT", "").strip() in ("", "0"):
        return None
    try:
        from sbr_tpu.obs import flight

        return flight.shared()
    except Exception:
        return None


class TileRunner:
    """Per-tile production engine shared by `run_tiled_grid`'s loop and the
    elastic scheduler (`resilience.elastic`): produce ONE tile's arrays via
    local checkpoint -> cross-run global cache -> compute, with the full
    resilience stack (retry policy + shared budget, fault points, NaN
    poison hook, degrade-ladder healing, atomic save + sha256 sidecar)
    applied on the compute path.

    Factoring this out of the sweep loop is what makes elastic scheduling
    affordable: a host claiming one tile at a time calls `produce` per
    claim instead of re-running the whole `run_tiled_grid` scan (which
    loads every cached tile — O(tiles²) reads over a sweep).

    ``counts`` tallies tiles by source ("local" / "cache" / "computed") and
    ``repairs`` accumulates degrade-ladder reports for the checkpoint
    manifest. Construct via `tile_runner` (which resolves config/tile-shape
    defaults, checks the sweep fingerprint, and runs the OOM preflight) —
    the raw constructor assumes all of that already happened.
    """

    def __init__(
        self, beta_values, u_values, base, config, tile_shape, ckpt,
        mesh=None, dtype=None, policy=None, retry_budget=None,
        heal_divergent: bool = True, tile_cache=None, verbose: bool = False,
        scenario_spec=None,
    ) -> None:
        self.beta_values = np.asarray(beta_values)
        self.u_values = np.asarray(u_values)
        self.base = base
        self.config = config
        # Composed-scenario tiling (ISSUE 15 satellite, the PR 13
        # remainder): a non-None `scenario.ScenarioSpec` routes each
        # tile's compute through `scenario_grid` instead of `beta_u_grid`
        # and joins every fingerprint/cache key (see `_payload_base`), so
        # scenario sweeps ride the same leases / tile cache / retry stack.
        self.scenario_spec = scenario_spec
        self.tb, self.tu = (int(t) for t in tile_shape)
        self.nb, self.nu = len(self.beta_values), len(self.u_values)
        self.ckpt = Path(ckpt) if ckpt is not None else None
        self.mesh = mesh
        self.dtype = dtype
        self.policy = policy
        self.retry_budget = retry_budget
        self.heal_divergent = heal_divergent
        self.tile_cache = tile_cache
        self.verbose = verbose
        self.repairs: list = []
        self.counts = {"local": 0, "cache": 0, "computed": 0}

    # -- geometry ------------------------------------------------------------
    def slices(self, bi: int, ui: int) -> Tuple[slice, slice]:
        return (
            slice(bi, min(bi + self.tb, self.nb)),
            slice(ui, min(ui + self.tu, self.nu)),
        )

    def tile_id(self, bi: int, ui: int) -> str:
        return f"tile_b{bi:05d}_u{ui:05d}"

    def path(self, bi: int, ui: int) -> Optional[Path]:
        return _tile_path(self.ckpt, bi, ui) if self.ckpt is not None else None

    # -- production ----------------------------------------------------------
    def load_local(self, bi: int, ui: int, may_quarantine: bool = True):
        """Verified read of the local checkpoint slot (None on miss/corrupt;
        corrupt slots are quarantined only when ``may_quarantine``)."""
        path = self.path(bi, ui)
        if path is None or not path.exists():
            return None
        return _load_tile_verified(path, may_quarantine=may_quarantine)

    def _payload_base(self):
        """What the fingerprint/cache machinery hashes as "the model": the
        bare params for legacy sweeps (existing checkpoints and cache
        entries stay valid), the (params, spec) pair for scenario sweeps —
        `canonicalize` renders the tuple with the spec's dataclass name,
        so a composed tile can never collide with a plain one."""
        if self.scenario_spec is None:
            return self.base
        return (self.base, self.scenario_spec)

    def cache_key(self, bi: int, ui: int) -> Optional[str]:
        if self.tile_cache is None:
            return None
        bs, us = self.slices(bi, ui)
        return self.tile_cache.key(
            self._payload_base(), self.config, self.dtype,
            self.beta_values[bs], self.u_values[us],
        )

    def produce(self, bi: int, ui: int, skip_local: bool = False):
        """Make tile (bi, ui) exist locally; returns ``(source, arrays)``
        with source in {"local", "cache", "computed"}. ``skip_local`` skips
        the local read when the caller already checked (the sweep loop)."""
        path = self.path(bi, ui)
        fl = _flight_recorder()
        tid = self.tile_id(bi, ui)
        if not skip_local:
            t0 = time.monotonic()
            cached = self.load_local(bi, ui)
            if fl is not None:
                fl.mark("sweeps", "ckpt_load", t0, time.monotonic(), tag=tid)
            if cached is not None:
                self.counts["local"] += 1
                return "local", cached
        key = self.cache_key(bi, ui)
        if key is not None:
            t0 = time.monotonic()
            arrays = self.tile_cache.load(key, tile=tid)
            if fl is not None:
                fl.mark("sweeps", "cache_io", t0, time.monotonic(), tag=tid)
            if arrays is not None:
                self.counts["cache"] += 1
                if path is not None:
                    t0 = time.monotonic()
                    _save_atomic(path, arrays)
                    if fl is not None:
                        fl.mark("sweeps", "ckpt_save", t0, time.monotonic(),
                                tag=tid)
                return "cache", arrays
        t0 = time.monotonic()
        arrays = self._compute(bi, ui)
        if fl is not None:
            fl.mark("sweeps", "compute", t0, time.monotonic(), tag=tid)
        self.counts["computed"] += 1
        if path is not None:
            t0 = time.monotonic()
            _save_atomic(path, arrays)
            if fl is not None:
                fl.mark("sweeps", "ckpt_save", t0, time.monotonic(), tag=tid)
            # Chaos hook: a ``corrupt`` rule on checkpoint.save tears the
            # file AFTER the save (and its sidecar) landed — exactly the
            # torn-write mode verify-on-load must catch on the next read.
            inj = faults.fire("checkpoint.save", target=self.tile_id(bi, ui))
            if inj is not None and inj.kind == "corrupt":
                faults.corrupt_file(path)
        if key is not None:
            # Store AFTER the local save: the global entry is only ever
            # written from arrays that also landed (atomically) locally.
            # The meta sidecar makes the whole-tile entry per-cell
            # addressable for the serving fleet's degradation ladder
            # (resilience.elastic.tile_meta / serve.fleet.TileCacheBridge)
            # — PLAIN sweeps only: a scenario tile's cells answer a
            # different pipeline, and `cell_tag` hashes bare params, so a
            # sidecar here would let the ladder serve composed cells as
            # plain answers. Scenario entries stay whole-tile addressable.
            meta = None
            if self.scenario_spec is None:
                from sbr_tpu.resilience.elastic import tile_meta

                bs, us = self.slices(bi, ui)
                meta = tile_meta(
                    self.base, self.config, self.dtype,
                    self.beta_values[bs], self.u_values[us], key,
                )
            t0 = time.monotonic()
            self.tile_cache.store(
                key, arrays, tile=tid, meta=meta,
            )
            if fl is not None:
                fl.mark("sweeps", "cache_io", t0, time.monotonic(), tag=tid)
        return "computed", arrays

    def _compute(self, bi: int, ui: int) -> dict:
        """One tile's compute under the unified retry policy, with the
        fault-injection, poison, and degrade-ladder hooks of the sweep loop."""
        bs, us = self.slices(bi, ui)
        tile_id = self.tile_id(bi, ui)
        tile_snap: dict = {}

        def compute_tile():
            faults.fire("tile.compute", target=tile_id)
            if self.scenario_spec is not None:
                from sbr_tpu.scenario import scenario_grid

                tile = scenario_grid(
                    self.scenario_spec, self.beta_values[bs],
                    self.u_values[us], self.base, config=self.config,
                    dtype=self.dtype,
                )
            else:
                tile = beta_u_grid(
                    self.beta_values[bs], self.u_values[us], self.base,
                    config=self.config, mesh=self.mesh, dtype=self.dtype,
                )
            arrays = {f: np.asarray(getattr(tile, f)).copy() for f in _FIELDS}
            tile_flags = (
                np.asarray(tile.health.flags).copy()
                if tile.health is not None
                else np.zeros(arrays["status"].shape, np.int32)
            )
            if obs.current_run() is not None:
                # Snapshot while the tile's device buffers are still
                # live — after the host copies land, the live-buffer
                # sum would read an empty device.
                tile_snap.clear()
                tile_snap.update(obs.mem.snapshot())
            return arrays, tile_flags

        def observer(**rec):
            if rec.get("outcome") in ("retrying", "gave_up", "budget_exhausted"):
                print(
                    f"  tile ({bi},{ui}) attempt "
                    f"{rec.get('attempt')}/{rec.get('max_attempts')} "
                    f"{rec['outcome']}: {rec.get('error', '')}",
                    file=sys.stderr,
                )
            retry._default_observer(**rec)

        policy = self.policy if self.policy is not None else default_tile_policy()
        try:
            arrays, tile_flags = policy.call(
                compute_tile, scope=f"Tile ({bi},{ui})",
                budget=self.retry_budget, observer=observer,
            )
        except retry.RetryError as err:
            raise RuntimeError(str(err)) from err.__cause__

        # Chaos hook: a ``nan`` rule on tile.result poisons the computed
        # arrays + health flags, simulating device garbage downstream of
        # a successful dispatch; the degrade ladder below must repair it.
        inj = faults.fire("tile.result", target=tile_id)
        if inj is not None and inj.kind == "nan":
            _poison_tile(inj, arrays, tile_flags, tile_id)

        # The degrade ladder recomputes cells through the BASELINE path
        # (`heal.repair_divergent`): valid for plain sweeps and for
        # baseline-reducible specs (bit-identical cells by the scenario
        # parity contract), meaningless for genuine compositions — those
        # keep their original values, flags intact.
        heal_ok = self.scenario_spec is None or (
            self.scenario_spec.reduces_to() == "baseline"
        )
        if self.heal_divergent and heal_ok and (tile_flags != 0).any():
            tile_report = heal.repair_divergent(
                self.beta_values[bs], self.u_values[us], self.base,
                self.config, self.dtype, arrays, tile_flags, scope=tile_id,
            )
            if tile_report:
                self.repairs.extend({"tile": [bi, ui], **r} for r in tile_report)

        # Per-tile peak-memory attribution (obs.mem): one `mem` event
        # with a `tile` field, folded into the manifest's tile table —
        # `report memory` renders it and flags near-capacity tiles.
        obs.log_tile_mem(tile_id, **tile_snap)
        return arrays


def default_tile_policy(max_retries: int = 2) -> retry.RetryPolicy:
    """The tile loop's retry policy (``SBR_RETRY_*`` env overrides layered
    over ``max_retries`` extra attempts) — shared by `run_tiled_grid` and
    the elastic scheduler so both paths retry identically."""
    return retry.policy_from_env(
        "SBR_RETRY",
        max_attempts=max_retries + 1,
        base_delay_s=1.0,
        multiplier=2.0,
        max_delay_s=60.0,
    )


def default_retry_budget(n_tiles: int) -> retry.RetryBudget:
    """The per-sweep shared retry budget (``SBR_RETRY_BUDGET`` override)."""
    budget_env = os.environ.get("SBR_RETRY_BUDGET", "").strip()
    return retry.RetryBudget(int(budget_env) if budget_env else max(16, n_tiles))


def tile_runner(
    beta_values,
    u_values,
    base: ModelParams,
    checkpoint_dir,
    config: Optional[SolverConfig] = None,
    tile_shape=(256, 256),
    mesh=None,
    dtype=None,
    max_retries: int = 2,
    heal_divergent: Optional[bool] = None,
    retry_budget: Optional[retry.RetryBudget] = None,
    tile_cache=None,
    verbose: bool = False,
    scenario_spec=None,
) -> TileRunner:
    """Build a ready `TileRunner` for one sweep: resolves the config/tile-
    shape defaults exactly like `run_tiled_grid` (so fingerprints agree),
    creates+checks the checkpoint dir, and runs the OOM preflight once.
    ``tile_shape`` must already be resolved when it was "auto" upstream —
    pass the resolved pair (the elastic scheduler resolves before the
    claim loop, like the multihost ownership split always has).

    ``scenario_spec`` (ISSUE 15 satellite): a single-bank baseline-family
    `scenario.ScenarioSpec` routes tile compute through `scenario_grid`;
    the spec joins the sweep fingerprint and every tile-cache key, so
    composed sweeps and plain sweeps can never share bytes. Use
    `scenario.run_tiled_scenario_grid` rather than passing it here
    directly (it runs the spec×params validation)."""
    if config is None:  # sweep default: refinement off (see beta_u_grid)
        config = SolverConfig(refine_crossings=False)
    if scenario_spec is not None and mesh is not None:
        raise ValueError(
            "scenario_spec tiles compute through scenario_grid, which is "
            "single-device — mesh= is not supported on scenario sweeps"
        )
    beta_values = np.asarray(beta_values)
    u_values = np.asarray(u_values)
    nb, nu = len(beta_values), len(u_values)
    tile_shape, _plan = resolve_tile_shape(nb, nu, tile_shape, config, dtype, mesh)
    if mesh is not None:
        # Every tile (including ragged edge tiles) must satisfy
        # beta_u_grid's divisibility precondition; validate BEFORE the
        # manifest write below — a deterministic sharding error must not
        # leave a fingerprint for a tile shape the corrected retry will
        # then mismatch against. beta_u_grid shards by the axes NAMED
        # "b" and "u" (its default mesh_axes), regardless of mesh order.
        tb, tu = tile_shape
        mb, mu = mesh.shape["b"], mesh.shape["u"]
        tile_dims = {min(tb, nb - bi) for bi in range(0, nb, tb)}, {
            min(tu, nu - ui) for ui in range(0, nu, tu)
        }
        if any(d % mb for d in tile_dims[0]) or any(d % mu for d in tile_dims[1]):
            raise ValueError(
                f"Tile sizes {sorted(tile_dims[0])}×{sorted(tile_dims[1])} must be "
                f"divisible by the mesh axes {mb}×{mu}; choose tile_shape/grid "
                "sizes that are multiples of the mesh shape."
            )
    if heal_divergent is None:
        heal_divergent = os.environ.get("SBR_HEAL", "").strip() != "0"
    ckpt = None
    fp_base = base if scenario_spec is None else (base, scenario_spec)
    if checkpoint_dir is not None:
        ckpt = Path(checkpoint_dir)
        ckpt.mkdir(parents=True, exist_ok=True)
        _check_fingerprint(
            ckpt,
            _sweep_fingerprint(beta_values, u_values, fp_base, config, tile_shape, dtype),
            tile_shape=tile_shape,
        )
    _preflight_tile(nb, nu, tile_shape[0], tile_shape[1], config, dtype, mesh, plan=_plan)
    if retry_budget is None:
        retry_budget = default_retry_budget(len(tile_origins(nb, nu, tile_shape)))
    return TileRunner(
        beta_values, u_values, base, config, tile_shape, ckpt,
        mesh=mesh, dtype=dtype, policy=default_tile_policy(max_retries),
        retry_budget=retry_budget, heal_divergent=heal_divergent,
        tile_cache=tile_cache, verbose=verbose, scenario_spec=scenario_spec,
    )


def run_tiled_grid(
    beta_values,
    u_values,
    base: ModelParams,
    config: Optional[SolverConfig] = None,
    tile_shape=(256, 256),
    checkpoint_dir: Optional[str] = None,
    mesh=None,
    dtype=None,
    max_retries: int = 2,
    verbose: bool = False,
    tile_owner=None,
    heal_divergent: Optional[bool] = None,
    retry_budget: Optional[retry.RetryBudget] = None,
    tile_cache=None,
    scenario_spec=None,
) -> GridSweepResult:
    """β×u grid in tiles with optional on-disk resume.
    NOTE ``config=None`` ≠ ``config=SolverConfig()``: None selects the sweep
    default (crossing refinement OFF, like `beta_u_grid`), and the config is
    part of the sweep fingerprint — switching between the two invalidates an
    existing checkpoint dir (by design: tile numerics would differ).

    ``tile_shape`` may be ``"auto"``: the obs.mem capacity planner picks the
    largest power-of-two square tile whose modeled footprint (linear fit of
    two abstract AOT probe lowerings) fits ``SBR_MEM_HEADROOM`` (default
    0.8) × device capacity; on CPU (no ``memory_stats``) it falls back to
    (256, 256) clamped to the grid. The resolved shape enters the sweep
    fingerprint, and the decision is recorded in the obs manifest's
    ``memory.plan`` block. Before the tile loop dispatches, an OOM
    preflight AOT-lowers one worst-case tile and FAILS CLOSED
    (`obs.mem.MemoryPreflightError`) when its analytical footprint exceeds
    the headroom budget — disable with ``SBR_MEM_PREFLIGHT=0``. Each
    computed tile's peak memory lands as a ``mem`` event
    (``report memory RUN_DIR`` renders the per-tile table).

    Semantically identical to one `beta_u_grid` call over the full grid
    (cells are independent); tiling bounds device-memory footprint at
    paper resolution and gives the checkpoint/retry granularity.

    ``tile_owner(bi, ui) -> bool`` restricts computation to a subset of
    tiles (others stay at their NaN/-1 initial fill unless already on
    disk) — the hook the multi-host sweep farm uses to split a grid
    across processes (`parallel.distributed.run_tiled_grid_multihost`).

    Failure handling: each tile runs under the unified retry policy
    (``SBR_RETRY_*`` env overrides; ``max_retries`` keeps its historical
    meaning of extra attempts, so attempts = ``max_retries + 1``), all
    tiles share one retry budget (``SBR_RETRY_BUDGET``, default
    ``max(16, n_tiles)``; or pass ``retry_budget`` to share across sweeps),
    corrupt cached tiles are quarantined and recomputed, and divergent
    cells are repaired up the degrade ladder unless ``heal_divergent``
    (env ``SBR_HEAL``) disables it. A repaired-but-still-divergent cell
    keeps its original values — the ladder only ever upgrades trust.

    Cross-run global cache (ISSUE 8): with ``tile_cache`` (a
    `resilience.elastic.TileCache`, default from ``SBR_TILE_CACHE_DIR``),
    a tile missing locally is first looked up in the content-addressed
    cross-run store — keyed by params/config/dtype fingerprint × the
    tile's actual β/u values — and every computed tile is stored back, so
    repeated or overlapping sweeps recompute only cold tiles. Entries are
    sha256-verified on read (mismatch → quarantine + recompute, never
    trusted), and hits/misses/stores land as obs ``cache`` events.
    """
    # The cross-run global tile cache (resilience.elastic): None resolves
    # from SBR_TILE_CACHE_DIR (unset = disabled, the historical behavior).
    if tile_cache is None:
        from sbr_tpu.resilience.elastic import default_tile_cache

        tile_cache = default_tile_cache()

    runner = tile_runner(
        beta_values, u_values, base, checkpoint_dir, config=config,
        tile_shape=tile_shape, mesh=mesh, dtype=dtype, max_retries=max_retries,
        heal_divergent=heal_divergent, retry_budget=retry_budget,
        tile_cache=tile_cache, verbose=verbose, scenario_spec=scenario_spec,
    )
    beta_values, u_values = runner.beta_values, runner.u_values
    nb, nu, tb, tu = runner.nb, runner.nu, runner.tb, runner.tu
    ckpt = runner.ckpt
    origins = tile_origins(nb, nu, (tb, tu))

    # Keyed off _FIELDS so the accumulator, tile save, and cache load stay in
    # lockstep: adding a field without an init entry fails loudly here.
    field_init = {"max_aw": (np.nan, np.float64), "xi": (np.nan, np.float64), "status": (-1, np.int32)}
    out = {f: np.full((nb, nu), *field_init[f]) for f in _FIELDS}

    with shutdown.graceful_shutdown(label="tiled_grid"):
        for bi, ui in origins:
            bs, us = runner.slices(bi, ui)
            owned = tile_owner is None or tile_owner(bi, ui)
            cached = runner.load_local(bi, ui, may_quarantine=owned)
            if cached is not None:
                for f in _FIELDS:
                    out[f][bs, us] = cached[f]
                # Count through the runner so its per-source tally stays
                # authoritative for every caller (the elastic driver reads
                # counts["computed"] to gate its throughput-history append).
                runner.counts["local"] += 1
                continue
            # corrupt tile: quarantined above (if owned) — recompute

            if not owned:
                continue  # another process's tile; it lands on disk, not here

            _, arrays = runner.produce(bi, ui, skip_local=True)
            for f in _FIELDS:
                out[f][bs, us] = arrays[f]
            if verbose:
                done = (bi // tb) * ((nu + tu - 1) // tu) + ui // tu + 1
                total = ((nb + tb - 1) // tb) * ((nu + tu - 1) // tu)
                print(f"  tile {done}/{total} done")

    if verbose and runner.counts["local"]:
        print(f"  resumed {runner.counts['local']} tiles from {ckpt}")
    if ckpt is not None and runner.repairs:
        _record_repairs(ckpt, runner.repairs)

    import jax.numpy as jnp

    return GridSweepResult(
        beta_values=jnp.asarray(beta_values),
        u_values=jnp.asarray(u_values),
        max_aw=jnp.asarray(out["max_aw"]),
        xi=jnp.asarray(out["xi"]),
        status=jnp.asarray(out["status"]),
    )
