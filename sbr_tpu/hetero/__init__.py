"""Heterogeneous learning-speed extension (reference
`src/extensions/heterogeneity/`).

The group axis is a leading array dimension instead of a Julia vector-of-
interpolants: the coupled K-ODE is one `lax.scan` over a (K,) state, Stage 2
is `vmap` over group rows, and the weighted aggregate-withdrawal reduction in
Stage 3 is a dot product that becomes a `psum` when the group axis is sharded
over the mesh (SURVEY §5.8).
"""

from sbr_tpu.hetero.learning import solve_learning_hetero
from sbr_tpu.hetero.sharded import solve_hetero_sharded
from sbr_tpu.hetero.solver import (
    compute_xi_hetero,
    get_aw_hetero,
    solve_equilibrium_hetero,
)

__all__ = [
    "solve_learning_hetero",
    "solve_equilibrium_hetero",
    "compute_xi_hetero",
    "solve_hetero_sharded",
    "get_aw_hetero",
]
