"""Stage 1 for K heterogeneous groups — coupled SI network ODE.

Reference: `solve_SInetwork_hetero` (`src/extensions/heterogeneity/
heterogeneity_learning.jl:49-94`):

    dG_k/dt = (1 - G_k) · β_k · ω(t),   ω(t) = Σ_j dist_j · G_j(t)

The reference integrates with an adaptive solver and wraps each group in its
own interpolation object; here the state is a (K,) array advanced by RK4 on a
static grid (`core.ode.rk4`), so the whole family is one `lax.scan` and the
ω reduction is a dot product — a `psum` when the group axis is sharded.
PDFs come from the symbolic rhs g_k = (1-G_k)·β_k·ω exactly like
`compute_pdf_hetero` (`heterogeneity_learning.jl:114-134`), with no O(K²·n)
double loop: all groups evaluate in one broadcast.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from sbr_tpu.core.ode import rk4
from sbr_tpu.models.params import LearningParamsHetero, SolverConfig
from sbr_tpu.models.results import LearningSolutionHetero


def hetero_rhs(t, G, args):
    """Coupled SI rhs (`heterogeneity_learning.jl:57-67`). G: (K,).

    With ``axis_name`` set (group axis sharded under shard_map), the ω
    reduction completes across shards with a psum — the only collective the
    coupled system needs (SURVEY §5.8(a))."""
    del t
    betas, dist, axis_name = args
    omega = jnp.dot(dist, G)
    if axis_name is not None:
        omega = lax.psum(omega, axis_name)
    return (1.0 - G) * betas * omega


def hetero_substeps(params: LearningParamsHetero, config: SolverConfig) -> int:
    """RK4 substeps keeping β_max · h ≲ 0.015 per microstep: global error
    ~(βh)^4 then sits near 1e-8, inside the 1e-6 CPU-match envelope even for
    the fast-group configs (reference example β_max=12.5,
    `scripts/2_heterogeneity.jl:38`)."""
    t0, t1 = params.tspan
    h0 = (t1 - t0) / (config.n_grid - 1)
    beta_max = float(max(params.betas))
    return max(config.ode_substeps, int(jnp.ceil(beta_max * h0 / 0.015)))


def solve_learning_hetero_arrays(
    betas: jnp.ndarray,
    dist: jnp.ndarray,
    x0: float,
    grid: jnp.ndarray,
    substeps: int,
    axis_name=None,
) -> LearningSolutionHetero:
    """Array-level coupled solve — the shard_map-compatible core.

    ``betas``/``dist`` are the (local slice of the) group axis; with
    ``axis_name`` the ω reductions psum across the sharded axis, so every
    shard integrates its groups against the GLOBAL mixing field.
    """
    dtype = betas.dtype
    g0 = jnp.full(betas.shape, x0, dtype=dtype)
    if axis_name is not None:
        # The scan carry becomes device-varying (it mixes in the sharded
        # betas); mark the constant-filled initial state as varying too so
        # shard_map's manual-axes check accepts the loop.
        g0 = lax.pcast(g0, (axis_name,), to="varying")
    cdfs = rk4(hetero_rhs, g0, grid, args=(betas, dist, axis_name), substeps=substeps)  # (n, K)
    cdfs = jnp.clip(cdfs.T, 0.0, 1.0)  # (K, n)

    omega = jnp.einsum("k,kn->n", dist, cdfs)
    if axis_name is not None:
        omega = lax.psum(omega, axis_name)
    pdfs = (1.0 - cdfs) * betas[:, None] * omega[None, :]

    return LearningSolutionHetero(
        grid=grid,
        cdfs=cdfs,
        pdfs=pdfs,
        t0=grid[0],
        dt=grid[1] - grid[0],
        betas=betas,
        dist=dist,
    )


def solve_learning_hetero(
    params: LearningParamsHetero,
    config: SolverConfig = SolverConfig(),
    dtype=jnp.float64,
) -> LearningSolutionHetero:
    """Solve the coupled K-group system on a static uniform grid."""
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    t0, t1 = params.tspan
    grid = jnp.linspace(t0, t1, config.n_grid, dtype=dtype)
    betas = jnp.asarray(params.betas, dtype=dtype)
    dist = jnp.asarray(params.dist, dtype=dtype)
    return solve_learning_hetero_arrays(
        betas, dist, params.x0, grid, hetero_substeps(params, config)
    )
